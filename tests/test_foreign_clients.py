"""Foreign-language client runtimes (clients/go, clients/node).

Always verified here, toolchain or not:
- the checked-in frame fixtures (clients/fixtures/frames.json) match
  the server's own wire encoder byte-for-byte — the Go and TS clients
  assert their encoders against the same fixtures;
- the generated type files (types.go / types.ts) are in sync with
  tigerbeetle_tpu/bindings.py.

With a toolchain on PATH, the real client runs end-to-end against a
spawned server (the reference's per-language CI pattern —
src/scripts/ci.zig): `go test ./...` and the Node e2e script.
"""

import json
import os
import shutil
import subprocess
import threading

import numpy as np
import pytest

from tigerbeetle_tpu import bindings
from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.vsr import wire

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLIENTS = os.path.join(REPO, "clients")
CLUSTER = 3


def golden_frames():
    """The fixture cases, rebuilt from the server's encoder."""

    def frame(cluster, client, request, operation, body):
        h = wire.make_header(
            command=wire.Command.request, cluster=cluster, client=client,
            request=request, operation=operation,
        )
        wire.finalize_header(h, body)
        return h.tobytes() + body

    cases = []
    cases.append(("register", 0, 2, b""))

    a = np.zeros(1, types.ACCOUNT_DTYPE)
    a["id_lo"] = 9001
    a["ledger"] = 1
    a["code"] = 1
    a["user_data_64"] = 0x1122334455667788
    cases.append(("create_accounts", 1, 129, a.tobytes()))

    t = np.zeros(1, types.TRANSFER_DTYPE)
    t["id_lo"] = 77
    t["id_hi"] = 1
    t["debit_account_id_lo"] = 9001
    t["credit_account_id_lo"] = 9002
    t["amount_lo"] = 250
    t["ledger"] = 1
    t["code"] = 1
    t["flags"] = types.TransferFlags.pending
    t["timeout"] = 5
    cases.append(("create_transfers", 2, 130, t.tobytes()))

    ids = np.zeros(2, types.U128_PAIR_DTYPE)
    ids[0]["lo"] = 9001
    ids[1]["lo"] = 9002
    ids[1]["hi"] = 7
    cases.append(("lookup_accounts", 3, 131, ids.tobytes()))

    f = np.zeros(1, types.ACCOUNT_FILTER_DTYPE)
    f["account_id_lo"] = 9001
    f["timestamp_max"] = (1 << 63) - 1
    f["limit"] = 10
    f["flags"] = 3
    cases.append(("get_account_transfers", 4, 133, f.tobytes()))

    out = []
    for name, request, op, body in cases:
        out.append({
            "name": name, "cluster": CLUSTER,
            "client_lo": 0xC0FFEE, "client_hi": 0,
            "request": request, "operation": op,
            "body_hex": body.hex(),
            "frame_hex": frame(
                CLUSTER, 0xC0FFEE, request, op, body
            ).hex(),
        })
    return out


def test_frame_fixtures_match_server_encoder():
    with open(os.path.join(CLIENTS, "fixtures", "frames.json")) as fp:
        checked_in = json.load(fp)
    assert checked_in == golden_frames(), (
        "clients/fixtures/frames.json is stale — regenerate it from "
        "golden_frames() after any wire-protocol change"
    )


def test_generated_types_in_sync():
    with open(os.path.join(CLIENTS, "go", "types.go")) as fp:
        assert fp.read() == bindings.emit_go(), "clients/go/types.go stale"
    with open(os.path.join(CLIENTS, "node", "src", "types.ts")) as fp:
        assert fp.read() == bindings.emit_typescript(), (
            "clients/node/src/types.ts stale"
        )
    java_path = os.path.join(
        CLIENTS, "java", "src", "main", "java", "com", "tigerbeetle",
        "Types.java",
    )
    with open(java_path) as fp:
        assert fp.read() == bindings.emit_java(), (
            "clients/java Types.java stale"
        )
    with open(os.path.join(CLIENTS, "dotnet", "Types.cs")) as fp:
        assert fp.read() == bindings.emit_csharp(), (
            "clients/dotnet/Types.cs stale"
        )


# ---------------------------------------------------------------------------
# Live end-to-end runs, gated on toolchains.


class ServerFixture:
    def __init__(self, tmp_path):
        from tigerbeetle_tpu.runtime.native import native_available

        if not native_available():
            pytest.skip("native runtime not built")
        from tigerbeetle_tpu.runtime.server import (
            ReplicaServer,
            format_data_file,
        )

        config = cfg.TEST_MIN
        path = str(tmp_path / "data.tigerbeetle")
        format_data_file(path, cluster=CLUSTER, config=config)
        self.server = ReplicaServer(
            path, cluster=CLUSTER, addresses=["127.0.0.1:0"],
            replica_index=0,
            state_machine_factory=lambda: CpuStateMachine(config),
            config=config,
        )
        self.port = self.server.port
        self._stop = False
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self):
        while not self._stop:
            self.server.poll_once(timeout_ms=1)

    def close(self):
        self._stop = True
        self.thread.join(timeout=5)
        self.server.close()


@pytest.fixture
def server(tmp_path):
    f = ServerFixture(tmp_path)
    yield f
    f.close()


def test_go_client_end_to_end(server):
    go = shutil.which("go")
    if go is None:
        pytest.skip("no Go toolchain")
    env = dict(os.environ)
    env["TB_ADDRESS"] = f"127.0.0.1:{server.port}"
    env["TB_CLUSTER"] = str(CLUSTER)
    proc = subprocess.run(
        [go, "test", "./..."],
        cwd=os.path.join(CLIENTS, "go"),
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (
        f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
    )


def test_node_client_end_to_end(server):
    node = shutil.which("node")
    if node is None:
        pytest.skip("no Node toolchain")
    proc = subprocess.run(
        [node, "--experimental-strip-types", "test/e2e.ts",
         str(server.port)],
        cwd=os.path.join(CLIENTS, "node"),
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (
        f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
    )
    assert "e2e ok" in proc.stdout


def test_server_drops_malformed_request_without_crashing(server):
    """An oversized or unknown-operation request must be dropped by
    the replica, not crash the poll loop via the state machine's
    asserting prepare path (clients validate, but the server must
    survive buggy ones)."""
    import socket

    from tigerbeetle_tpu.client import Client

    def send_raw(operation, body, request=1, client_id=0xBAD):
        h = wire.make_header(
            command=wire.Command.request, cluster=CLUSTER,
            client=client_id, request=request, operation=operation,
        )
        wire.finalize_header(h, body)
        with socket.create_connection(("127.0.0.1", server.port)) as s:
            s.sendall(h.tobytes() + body)

    # Unknown operation byte; truncated event; over-batch_max lookup.
    send_raw(200, b"")
    send_raw(130, b"\x01" * 100)  # not a multiple of 128
    send_raw(131, b"\x00" * (16 * (cfg.TEST_MIN.batch_max(16) + 1)))

    # The server must still serve a well-formed client.
    c = Client(f"127.0.0.1:{server.port}", CLUSTER, client_id=4242)
    assert c.create_accounts([{"id": 77, "ledger": 1, "code": 1}]) == []
    assert len(c.lookup_accounts([77])) == 1
    c.close()


def test_java_client_end_to_end(server):
    """Compile + run the pure-Java client against a live server (the
    reference's per-language CI pattern)."""
    javac = shutil.which("javac")
    java = shutil.which("java")
    if javac is None or java is None:
        pytest.skip("no Java toolchain")
    import tempfile

    src = []
    for root, _dirs, files in os.walk(os.path.join(CLIENTS, "java", "src")):
        src.extend(os.path.join(root, f) for f in files if f.endswith(".java"))
    with tempfile.TemporaryDirectory() as out:
        proc = subprocess.run(
            [javac, "-d", out, *src], capture_output=True, text=True,
            timeout=300,
        )
        assert proc.returncode == 0, proc.stderr
        env = dict(os.environ)
        env["TB_ADDRESS"] = f"127.0.0.1:{server.port}"
        env["TB_CLUSTER"] = str(CLUSTER)
        proc = subprocess.run(
            [java, "-cp", out, "com.tigerbeetle.E2ETest"],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, (
            f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
        )
        assert "e2e ok" in proc.stdout
        # Async pipelined client: N in-flight batches, coalesced wire
        # requests, per-packet demuxed completions (VERDICT r3 #6).
        proc = subprocess.run(
            [java, "-cp", out, "com.tigerbeetle.AsyncE2ETest"],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, (
            f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
        )
        assert "async e2e ok" in proc.stdout
        # Demux vectors: the Java splitter must match the server's
        # demuxer byte-for-byte (clients/fixtures/demux.json).
        proc = subprocess.run(
            [java, "-cp", out, "com.tigerbeetle.AsyncDemuxTest"],
            input=demux_vector_lines(), env=env, capture_output=True,
            text=True, timeout=300,
        )
        assert proc.returncode == 0, (
            f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
        )
        assert "demux ok" in proc.stdout


def demux_vector_lines() -> str:
    """clients/fixtures/demux.json rendered as the line format the
    language demux tests read on stdin ('-' spells an empty hex)."""
    with open(os.path.join(CLIENTS, "fixtures", "demux.json")) as fp:
        cases = json.load(fp)
    lines = []
    for c in cases:
        lines.append(
            "|".join(
                [
                    c["reply_hex"] or "-",
                    ",".join(str(n) for n in c["event_counts"]),
                    ",".join(s or "-" for s in c["slices_hex"]),
                ]
            )
        )
    return "\n".join(lines) + "\n"


def test_demux_fixture_matches_server_demuxer():
    """Always-on (no toolchain): the demux.json vectors every async
    client asserts against are exactly what the SERVER's demuxer
    produces — regenerating must be a no-op."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "gen_demux", os.path.join(CLIENTS, "fixtures", "gen_demux.py")
    )
    gen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gen)
    with open(os.path.join(CLIENTS, "fixtures", "demux.json")) as fp:
        checked_in = json.load(fp)
    assert gen.generate() == checked_in, (
        "demux.json is stale — regenerate via "
        "python clients/fixtures/gen_demux.py"
    )


def test_fixture_replay_end_to_end(server):
    """Toolchain-free e2e for the wire contract: replay the checked-in
    client frames byte-for-byte against a LIVE server over TCP and
    decode the replies.  This drives the exact bytes every foreign
    client emits (the fixtures are byte-asserted against the Go/TS/Java
    encoders), so the server side of each client's session is
    exercised even when no toolchain is installed."""
    import socket

    with open(os.path.join(CLIENTS, "fixtures", "frames.json")) as fp:
        frames = json.load(fp)

    with socket.create_connection(("127.0.0.1", server.port)) as s:
        s.settimeout(30)
        recv = b""

        def read_reply():
            nonlocal recv
            while True:
                if len(recv) >= 256:
                    size = int.from_bytes(recv[144:148], "little")
                    if len(recv) >= size:
                        msg, recv = recv[:size], recv[size:]
                        return msg
                chunk = s.recv(1 << 16)
                assert chunk, "server closed connection"
                recv += chunk

        for case in frames:
            s.sendall(bytes.fromhex(case["frame_hex"]))
            reply = read_reply()
            h = wire.header_from_bytes(reply[:256])
            assert wire.verify_header(h), case["name"]
            assert int(h["command"]) == int(wire.Command.reply), case["name"]
            assert int(h["request"]) == case["request"], case["name"]
            body = reply[256:]
            assert wire.u128(h, "checksum_body") == wire.checksum(body)
            if case["name"] == "create_accounts":
                assert body == b"", "account create should succeed"
            if case["name"] == "lookup_accounts":
                rows = np.frombuffer(body, types.ACCOUNT_DTYPE)
                assert len(rows) == 1 and int(rows[0]["id_lo"]) == 9001


def test_dotnet_client_end_to_end(server):
    dotnet = shutil.which("dotnet")
    if dotnet is None:
        pytest.skip("no .NET toolchain")
    env = dict(os.environ)
    env["TB_ADDRESS"] = f"127.0.0.1:{server.port}"
    env["TB_CLUSTER"] = str(CLUSTER)
    env["TB_DEMUX_STDIN"] = "1"
    proc = subprocess.run(
        [dotnet, "run", "--project", "e2e"],
        cwd=os.path.join(CLIENTS, "dotnet"),
        input=demux_vector_lines(),
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, (
        f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
    )
    assert "e2e ok" in proc.stdout
    assert "async e2e ok" in proc.stdout
    assert "demux ok" in proc.stdout
