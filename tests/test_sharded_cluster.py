"""Tier-1 sharded-cluster smoke: 2 shards x 2 replicas over REAL TCP
(in-process ReplicaServers on the native bus) behind the TCP
RouterServer, driven by native client sessions.

Proves, in seconds: a mixed shard-local / cross-shard workload through
the router replies BIT-IDENTICAL to a single-shard oracle cluster; the
router is killed (no graceful shutdown) and restarted MID-STREAM and
the stream continues — at-most-once intact through the shards' session
dedupe and the 2PC's derived-id idempotency; conservation of money
holds across both shards (settlement accounts net zero); and no client
request is left stranded.  The trace satellite: both 2PC legs carry
the client's wire trace context, so one merge_traces pass over the
router's flight dump + the shard replicas' flight dumps yields a
single Perfetto timeline showing hold -> hold -> post end to end.
"""

import dataclasses
import os
import socket
import threading
import time

import numpy as np
import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.runtime.native import native_available
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.testing.harness import pack, transfer

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native runtime not built"
)

CLUSTER = 31
CONF = dataclasses.replace(cfg.TEST_MIN, clients_max=16)
# Account ids by shard under n_shards=2 (pinned in test_router.py).
S0 = [2, 3, 6, 7]
S1 = [1, 4, 5, 8]


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class _Server:
    def __init__(self, path, addresses, index):
        from tigerbeetle_tpu.runtime.server import ReplicaServer

        self.server = ReplicaServer(
            path, cluster=CLUSTER, addresses=addresses,
            replica_index=index,
            state_machine_factory=lambda: CpuStateMachine(CONF),
            config=CONF,
        )
        self._stop = False
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self):
        while not self._stop:
            self.server.poll_once(timeout_ms=1)

    def close(self):
        self._stop = True
        self.thread.join(timeout=5)
        self.server.close()


class _Router:
    def __init__(self, port, shard_addrs, recover):
        from tigerbeetle_tpu.runtime.router import RouterServer

        self.server = RouterServer(
            f"127.0.0.1:{port}", shard_addrs, cluster=CLUSTER,
            recover=recover,
        )
        self._stop = False
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self):
        while not self._stop:
            self.server.poll_once(timeout_ms=1)

    def kill(self):
        """Crash, not shutdown: stop the loop and drop the sockets;
        every bit of volatile router state dies here."""
        self._stop = True
        self.thread.join(timeout=5)
        self.server.close()


@pytest.fixture()
def tcp_sharded(tmp_path):
    from tigerbeetle_tpu.runtime.server import format_data_file

    n_shards, n_repl = 2, 2
    servers = []
    shard_addrs = []
    for s in range(n_shards):
        ports = _free_ports(n_repl)
        addrs = [f"127.0.0.1:{p}" for p in ports]
        shard_addrs.append(",".join(addrs))
        for i in range(n_repl):
            path = str(tmp_path / f"s{s}_r{i}.tb")
            format_data_file(path, cluster=CLUSTER, replica_index=i,
                             replica_count=n_repl, config=CONF)
            servers.append(_Server(path, addrs, i))
    # Oracle: one single-replica, single-shard cluster fed the same
    # logical stream directly.
    oport = _free_ports(1)[0]
    opath = str(tmp_path / "oracle.tb")
    format_data_file(opath, cluster=CLUSTER, replica_index=0,
                     replica_count=1, config=CONF)
    oracle = _Server(opath, [f"127.0.0.1:{oport}"], 0)
    router_port = _free_ports(1)[0]
    router_box = [_Router(router_port, shard_addrs, recover=False)]
    clients = []
    try:
        yield {
            "shard_addrs": shard_addrs,
            "router_port": router_port,
            "router_box": router_box,
            "oracle_addr": f"127.0.0.1:{oport}",
            "servers": servers,
            "oracle": oracle,
            "clients": clients,
        }
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        if router_box[0] is not None:
            router_box[0].kill()
        oracle.close()
        for s in servers:
            s.close()


def test_sharded_tcp_router_kill_oracle_identical(tcp_sharded):
    """The headline smoke: mixed workload, router kill -9 + restart
    mid-stream, every reply bit-identical to the single-shard oracle."""
    from tigerbeetle_tpu.client import Client

    env = tcp_sharded
    router_addr = f"127.0.0.1:{env['router_port']}"
    # Doubled address: the native client's retransmission rotation
    # keeps reconnecting through the router restart window.
    sharded = Client(f"{router_addr},{router_addr}", CLUSTER,
                     client_id=501, timeout_ms=60_000)
    single = Client(env["oracle_addr"], CLUSTER, client_id=502,
                    timeout_ms=60_000)
    env["clients"] += [sharded, single]

    accounts = [{"id": i, "ledger": 1, "code": 1} for i in S0 + S1]
    assert sharded.create_accounts(accounts) == []
    assert single.create_accounts(accounts) == []

    # Mixed stream: local on each shard, cross both directions, and
    # deliberate failures (unknown accounts, zero amount) whose codes
    # must come back identical.  Unique ids, ample balances, distinct
    # accounts per batch: order-insensitive, so the relaxed intra-batch
    # ordering cannot change any result.
    def batches(base):
        return [
            [t(base + 1, S0[0], S0[1], 5), t(base + 2, S1[0], S1[1], 6)],
            [t(base + 3, S0[0], S1[0], 7), t(base + 4, S1[1], S0[1], 8)],
            [t(base + 5, 999, S1[0], 1), t(base + 6, S0[0], 998, 1),
             t(base + 7, S0[2], S1[2], 0)],
            [t(base + 8, S0[2], S1[2], 9), t(base + 9, S0[3], S0[2], 2)],
        ]

    def t(tid, dr, cr, amount):
        return {"id": tid, "debit_account_id": dr,
                "credit_account_id": cr, "amount": amount,
                "ledger": 1, "code": 1}

    def run_batch(rows):
        got = sharded.create_transfers(rows)
        want = single.create_transfers(rows)
        assert got == want, (rows[0]["id"], got, want)

    for rows in batches(1000):
        run_batch(rows)

    # --- coordinator crash mid-stream -----------------------------
    env["router_box"][0].kill()
    env["router_box"][0] = None
    time.sleep(0.1)
    env["router_box"][0] = _Router(env["router_port"],
                                   env["shard_addrs"], recover=True)

    for rows in batches(2000):
        run_batch(rows)

    # Replies bit-identical extends to reads: balance columns match
    # the oracle account-for-account (timestamps legitimately differ).
    got_rows = sharded.lookup_accounts(S0 + S1)
    want_rows = single.lookup_accounts(S0 + S1)
    assert len(got_rows) == len(want_rows) == len(S0 + S1)
    for g, w in zip(got_rows, want_rows):
        for col in ("id", "debits_pending", "debits_posted",
                    "credits_pending", "credits_posted"):
            assert types.u128_get(g, col) == types.u128_get(w, col), col

    # No stranded client work: the router has nothing open, nothing
    # pending, and both clients saw every reply (sync API returned).
    router = env["router_box"][0].server
    deadline = time.time() + 10
    while time.time() < deadline and (router._open or router._tasks):
        time.sleep(0.05)
    assert not router._open and not router._tasks

    # Conservation of money across both shards: per-shard double entry
    # AND the settlement accounts net to zero cluster-wide.
    imbalance = 0
    for s in env["servers"]:
        sm = s.server.replica.sm
        dp = sum(a.debits_pending for a in sm.accounts.values())
        cp = sum(a.credits_pending for a in sm.accounts.values())
        dpo = sum(a.debits_posted for a in sm.accounts.values())
        cpo = sum(a.credits_posted for a in sm.accounts.values())
        assert dp == cp and dpo == cpo
    for s in env["servers"][::2]:  # one replica per shard
        sm = s.server.replica.sm
        for aid, acct in sm.accounts.items():
            if types.is_coord_account(aid):
                imbalance += acct.credits_posted - acct.debits_posted
    assert imbalance == 0

    # The restarted router's registry (fresh — volatile by design)
    # shows the POST-restart cross-shard work, clean of conflicts.
    from tigerbeetle_tpu.obs.scrape import scrape_stats

    snap = scrape_stats(router_addr, CLUSTER, timeout_ms=20_000)
    assert snap["router.cross_shard_transfers"] >= 3
    assert snap["router.2pc_commits"] >= 3
    assert snap["router.2pc_compensations"] == 0
    assert snap["router.2pc_conflicts"] == 0

    # Cluster proof of state over the wire: each shard's replicas
    # answer the sessionless `state_root` query with one root, and the
    # router's query folds exactly those per-shard roots.
    from tigerbeetle_tpu.obs.scrape import scrape_state_root
    from tigerbeetle_tpu.state_machine import commitment as cm

    import time as _time

    shard_roots = []
    for shard, addr_list in enumerate(env["shard_addrs"]):
        deadline = _time.monotonic() + 30.0
        while True:
            roots = {
                scrape_state_root(addr, CLUSTER, timeout_ms=20_000)[0]
                for addr in addr_list.split(",")
            }
            if len(roots) == 1 or _time.monotonic() > deadline:
                break
            _time.sleep(0.2)  # a backup still applying the tail
        assert len(roots) == 1, (shard, roots)
        shard_roots.append(next(iter(roots)))
    cluster_root, n_folded = scrape_state_root(
        router_addr, CLUSTER, timeout_ms=20_000
    )
    assert n_folded == len(env["shard_addrs"])
    assert cluster_root == cm.fold_cluster(shard_roots)


def test_sharded_trace_context_merges_end_to_end(tcp_sharded, tmp_path):
    """Both 2PC legs carry the client's trace id: the router's flight
    ring records hold/decide/post instants under it, each shard's
    anatomy stages land in that shard's flight ring under it, and
    merge_traces stitches all three dumps into ONE Perfetto timeline."""
    from tigerbeetle_tpu.client import OpenLoopSession
    from tigerbeetle_tpu.testing.cluster import merge_traces

    env = tcp_sharded
    router_addr = f"127.0.0.1:{env['router_port']}"
    session = OpenLoopSession(router_addr, CLUSTER, 0x7AB)
    try:
        # Accounts first (untraced is fine), then one traced
        # cross-shard transfer.
        from tigerbeetle_tpu.testing.harness import account

        session.submit(types.Operation.create_accounts,
                       pack([account(S0[0]), account(S1[0])]))
        deadline = time.time() + 30
        while time.time() < deadline and session.inflight:
            session.poll(20)
        assert not session.inflight
        req = session.submit(
            types.Operation.create_transfers,
            pack([transfer(9001, debit_account_id=S0[0],
                           credit_account_id=S1[0], amount=3)]),
        )
        trace_id = ((session.id << 20) ^ req) & 0xFFFFFFFFFFFFFFFF
        deadline = time.time() + 30
        while time.time() < deadline and session.inflight:
            session.poll(20)
        assert not session.inflight
        reply = [c for c in session.completed if c[0] == req][0]
        assert reply[1] == "reply" and reply[3] == b""
    finally:
        session.close()

    router = env["router_box"][0].server
    names = {
        ev["name"]: ev for ev in router.flight.events()
        if ev.get("args", {}).get("trace_id") == trace_id
    }
    assert "x2pc_holds" in names and "x2pc_post_credit" in names

    # Per-shard flight rings carry the same trace id through the
    # replicas' anatomy stages (ingress/prepare/commit...).
    dumps = []
    rpath = str(tmp_path / "router_flight.json")
    router.flight.write(rpath, reason="test")
    dumps.append(rpath)
    shards_with_trace = 0
    for i, s in enumerate(env["servers"]):
        hits = [
            ev for ev in s.server.flight.events()
            if ev.get("args", {}).get("trace_id") == trace_id
        ]
        if hits:
            shards_with_trace += 1
        p = str(tmp_path / f"shard_flight_{i}.json")
        s.server.flight.write(p, reason="test")
        dumps.append(p)
    assert shards_with_trace >= 2  # both sides of the 2PC

    merged = merge_traces(dumps, str(tmp_path / "merged.json"))
    in_merged = [
        ev for ev in merged["traceEvents"]
        if isinstance(ev.get("args"), dict)
        and ev["args"].get("trace_id") == trace_id
    ]
    pids = {ev["pid"] for ev in in_merged}
    assert len(pids) >= 3  # router + both shards on one timeline
    assert not merged["otherData"].get("skipped")
