"""Tier-1 overload smoke: a real 2-replica TCP cluster driven OPEN
LOOP at ~120% of a quick measured closed-loop capacity for ~2 s.

Asserts the whole request-anatomy + admission-control contract end to
end: the primary's queue stays bounded at TB_ADMIT_QUEUE, at least one
typed Command.client_busy reaches the client, the scraped tail
exemplars carry a full prepare -> journal_write -> gc_covering_sync ->
commit -> reply stage timeline that round-trips into a merged Perfetto
view, and SIGTERM produces a parseable flight-recorder dump.

Subprocess servers (not threads): the SIGTERM flight dump needs a real
main-thread signal handler.  CpuStateMachine + TEST_MIN keeps it
seconds, inside the tier-1 budget; heavier sweeps live in bench.py
--open-loop."""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu.runtime.native import native_available
from tigerbeetle_tpu.types import TRANSFER_DTYPE, Operation

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native runtime not built"
)

CLUSTER = 21
N_REPLICAS = 2
ADMIT_QUEUE = 8
BATCH = 24  # transfers per request (fits TEST_MIN's 3840-byte body)

_RUNNER = """\
import sys
sys.path.insert(0, {here!r})
from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu.runtime.server import ReplicaServer
from tigerbeetle_tpu.state_machine import CpuStateMachine
s = ReplicaServer({path!r}, cluster={cluster}, addresses={addrs!r}.split(','),
    replica_index={i}, config=cfg.TEST_MIN,
    state_machine_factory=lambda: CpuStateMachine(cfg.TEST_MIN))
print('listening', flush=True)
s.serve_forever()
"""


def _transfers(tid0, n, rng):
    arr = np.zeros(n, dtype=TRANSFER_DTYPE)
    arr["id_lo"] = np.arange(tid0, tid0 + n, dtype=np.uint64)
    arr["debit_account_id_lo"] = rng.integers(1, 9, n, np.uint64)
    arr["credit_account_id_lo"] = rng.integers(9, 17, n, np.uint64)
    arr["amount_lo"] = 1
    arr["ledger"] = 1
    arr["code"] = 1
    return arr.tobytes()


def test_open_loop_overload_sheds_and_dumps(tmp_path):
    from tigerbeetle_tpu.client import Client, OpenLoopSession
    from tigerbeetle_tpu.obs.anatomy import exemplar_trace_events
    from tigerbeetle_tpu.obs.scrape import scrape_stats
    from tigerbeetle_tpu.runtime.server import format_data_file
    from tigerbeetle_tpu.testing.cluster import merge_traces

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    socks = [socket.socket() for _ in range(N_REPLICAS)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    addresses = ",".join(f"127.0.0.1:{p}" for p in ports)
    env = dict(os.environ)
    env["TB_ADMIT_QUEUE"] = str(ADMIT_QUEUE)
    env["TB_FLIGHT_PATH"] = str(tmp_path / "flight_r{replica}.json")
    env.pop("TB_METRICS", None)  # anatomy on
    procs = []
    logs = []
    clients = []
    sessions = []
    try:
        for i in range(N_REPLICAS):
            path = str(tmp_path / f"r{i}.tb")
            format_data_file(
                path, cluster=CLUSTER, replica_index=i,
                replica_count=N_REPLICAS, config=cfg.TEST_MIN,
            )
            log = open(tmp_path / f"replica{i}.log", "w")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _RUNNER.format(
                    here=here, path=path, cluster=CLUSTER,
                    addrs=addresses, i=i,
                )],
                stdout=log, stderr=subprocess.STDOUT, cwd=here, env=env,
            ))
        deadline = time.time() + 60
        for i in range(N_REPLICAS):
            lp = tmp_path / f"replica{i}.log"
            while time.time() < deadline:
                assert procs[i].poll() is None, (
                    f"replica {i} died:\n" + lp.read_text()[-2000:]
                )
                if "listening" in lp.read_text():
                    break
                time.sleep(0.2)
            else:
                raise AssertionError(f"replica {i} never listened")

        rng = np.random.default_rng(9)
        setup = Client(addresses, CLUSTER, client_id=70, timeout_ms=30_000)
        clients.append(setup)
        assert setup.create_accounts(
            [{"id": a, "ledger": 1, "code": 1} for a in range(1, 17)]
        ) == []

        # Quick closed-loop capacity probe (~0.6 s, one session).
        tid = 1_000
        t_end = time.perf_counter() + 0.6
        t0 = time.perf_counter()
        sent_events = 0
        while time.perf_counter() < t_end:
            body = _transfers(tid, BATCH, rng)
            tid += BATCH
            reply = setup._native.request(
                Operation.create_transfers, body, 30_000
            )
            assert reply == b""
            sent_events += BATCH
        capacity_eps = sent_events / (time.perf_counter() - t0)
        req_rate = max(2.0, 1.2 * capacity_eps / BATCH)  # 120% offered

        sessions.extend(
            OpenLoopSession(f"127.0.0.1:{ports[0]}", CLUSTER, 0xB0 + k)
            for k in range(2)
        )
        t_start = time.perf_counter()
        t_stop = t_start + 2.0
        next_arrival = t_start
        queue_depths = []
        rr = 0
        while time.perf_counter() < t_stop:
            now = time.perf_counter()
            while next_arrival <= now:
                sessions[rr % 2].submit(
                    Operation.create_transfers, _transfers(tid, BATCH, rng)
                )
                tid += BATCH
                rr += 1
                next_arrival += float(rng.exponential(1.0 / req_rate))
            for s in sessions:
                s.poll(0)
            if len(queue_depths) < 40 and rr % 5 == 0:
                try:
                    snap = scrape_stats(
                        f"127.0.0.1:{ports[0]}", CLUSTER, timeout_ms=3_000
                    )
                    queue_depths.append(int(snap["server.queue_depth"]))
                except (OSError, TimeoutError, ValueError):
                    pass
            time.sleep(0.002)
        # Deterministic overload spike: whatever the box's speed, a
        # back-to-back burst (30 requests vs pipeline 4 + queue 8)
        # must overflow the admit bound and shed — the Poisson phase
        # alone can be absorbed by a fast machine.
        for _ in range(3):
            for _ in range(30):
                sessions[rr % 2].submit(
                    Operation.create_transfers, _transfers(tid, BATCH, rng)
                )
                tid += BATCH
                rr += 1
            time.sleep(0.05)
            for s in sessions:
                s.poll(0)
            try:
                snap = scrape_stats(
                    f"127.0.0.1:{ports[0]}", CLUSTER, timeout_ms=3_000
                )
                queue_depths.append(int(snap["server.queue_depth"]))
            except (OSError, TimeoutError, ValueError):
                pass
        # Grace: drain what the bounded queue admitted.
        grace = time.perf_counter() + 20.0
        while time.perf_counter() < grace and any(
            s.inflight for s in sessions
        ):
            for s in sessions:
                s.poll(20)

        # 1) Bounded queue: every sampled depth within the admit bound.
        assert queue_depths, "no queue-depth samples scraped"
        assert max(queue_depths) <= ADMIT_QUEUE, queue_depths

        # 2) Typed busy surfaced to the client + shed counted.
        busy_total = sum(s.busy_replies for s in sessions)
        snap = scrape_stats(f"127.0.0.1:{ports[0]}", CLUSTER,
                            timeout_ms=10_000)
        assert busy_total >= 1, (
            f"no typed busy at 120% load (shed={snap.get('server.shed')})"
        )
        assert snap["server.shed"] >= busy_total
        assert snap["server.admit_queue"] == ADMIT_QUEUE

        # 3) Tail exemplars: full replicated-drain stage timeline.
        exemplars = snap["anatomy.exemplars"]
        assert exemplars, "no exemplars retained"
        want = {"prepare", "journal_write", "gc_covering_sync", "commit",
                "reply"}
        full = [
            ex for ex in exemplars
            if want <= {s[0] for s in ex["stages"]}
        ]
        assert full, [
            sorted({s[0] for s in ex["stages"]}) for ex in exemplars
        ]
        for ex in full:
            ts = [s[1] for s in ex["stages"]]
            assert ts == sorted(ts)
        assert snap["vsr.anatomy.e2e_us.count"] > 0

        # 4) SIGTERM -> parseable flight-recorder dump (replica 1).
        procs[1].send_signal(signal.SIGTERM)
        flight_path = tmp_path / "flight_r1.json"
        deadline = time.time() + 15
        while time.time() < deadline and not flight_path.exists():
            time.sleep(0.2)
        assert flight_path.exists(), "no flight dump on SIGTERM"
        procs[1].wait(timeout=15)
        flight = json.loads(flight_path.read_text())
        assert flight["otherData"]["flight_recorder"] is True
        assert flight["otherData"]["reason"] == "sigterm"
        assert flight["traceEvents"], "flight ring empty"

        # 4b) The PRIMARY's flight dump names WHO was shed (round 16):
        # every shed note carries the tenant (ledger 1 here, derived
        # from the body — these sessions don't stamp the header), and
        # a per-tenant `shed.t<ledger>` instant makes the per-tenant
        # timeline greppable without parsing note args.
        procs[0].send_signal(signal.SIGTERM)
        flight0_path = tmp_path / "flight_r0.json"
        deadline = time.time() + 15
        while time.time() < deadline and not flight0_path.exists():
            time.sleep(0.2)
        assert flight0_path.exists(), "no flight dump on SIGTERM (r0)"
        procs[0].wait(timeout=15)
        flight0 = json.loads(flight0_path.read_text())
        shed_notes = [
            e for e in flight0["traceEvents"] if e["name"] == "shed"
        ]
        assert shed_notes, "primary shed but recorded no flight notes"
        assert all(
            e.get("args", {}).get("tenant") == 1 for e in shed_notes
        ), shed_notes[:3]
        assert any(
            e["name"] == "shed.t1" for e in flight0["traceEvents"]
        ), "no per-tenant shed instant"

        # 5) Perfetto round-trip: exemplar spans + the flight dump
        # merge into one loadable timeline with all stage names.
        ex_path = tmp_path / "exemplars.json"
        ex_path.write_text(json.dumps({
            "traceEvents": exemplar_trace_events(full),
            "otherData": {},
        }))
        merged = merge_traces(
            [str(ex_path), str(flight_path)],
            str(tmp_path / "merged.json"),
            labels=["exemplars", "flight_r1"],
        )
        names = {e["name"] for e in merged["traceEvents"]}
        assert want <= names, sorted(names)
        assert json.load(open(tmp_path / "merged.json")) == merged
    finally:
        for s in sessions:
            try:
                s.close()
            except Exception:
                pass
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        for p in procs:
            try:
                p.kill()
            except Exception:
                pass
        for log in logs:
            log.close()
