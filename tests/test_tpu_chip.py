"""Real-chip TPU tests (VERDICT r3 #3).

The suite conftest forces every test onto a virtual CPU mesh (the
ambient axon TPU tunnel is a single shared chip and its plugin blocks
when busy), so these tests exercise the REAL device in subprocesses
with the ambient JAX environment.  They run by default whenever the
chip is reachable and skip (visibly) when it is not.

Covered: the exact scan kernel's parity on a real batch, DeviceTable
flush + read-back checksum, table growth, and the device-authoritative
engine end-to-end against the CPU oracle — the production device stack
on real silicon, not just the CPU twin.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.tpu

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _chip_env():
    env = dict(os.environ)
    # Undo the suite's CPU forcing; inherit the ambient axon setup.
    env.pop("JAX_PLATFORMS", None)
    env["JAX_PLATFORMS"] = "axon"
    env["XLA_FLAGS"] = ""
    env["TB_DEV_B"] = "512"  # small bucket: keep chip compiles short
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


_PROBE = """
import jax
assert jax.devices()[0].platform == "tpu", jax.devices()
print("TPU_OK")
"""


def _run_on_chip(code: str, timeout: int = 420,
                 production_bucket: bool = False) -> str:
    """production_bucket=True drops the TB_DEV_B shrink so the chip
    compiles the full B=8192 geometry (first compile ~1-2 min)."""
    env = _chip_env()
    if production_bucket:
        env.pop("TB_DEV_B", None)
    try:
        probe = subprocess.run(
            [sys.executable, "-c", _PROBE], env=env,
            capture_output=True, text=True, timeout=60,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("TPU probe timed out (tunnel busy)")
    if "TPU_OK" not in probe.stdout:
        pytest.skip(f"no TPU reachable: {probe.stderr[-200:]}")
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env,
        capture_output=True, text=True, timeout=timeout,
    )
    assert proc.returncode == 0, (
        f"chip test failed\nstdout: {proc.stdout[-1500:]}\n"
        f"stderr: {proc.stderr[-1500:]}"
    )
    return proc.stdout


def test_exact_scan_kernel_parity_on_chip():
    """kernel.py (the exact sequential-semantics scan) computes the
    same replies on the real TPU as the CPU oracle."""
    out = _run_on_chip(
        """
import numpy as np
from tigerbeetle_tpu.state_machine.cpu import CpuStateMachine
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing import harness as hz
from tigerbeetle_tpu.types import Operation, TransferFlags as TF

sm = TpuStateMachine(account_capacity=1 << 12)
sm._native = None  # force the JAX scan kernel (device) path
h = hz.SingleNodeHarness(sm)
hc = hz.SingleNodeHarness(CpuStateMachine())
ops = [(Operation.create_accounts,
        hz.pack([hz.account(i) for i in range(1, 20)]))]
rows = []
rng = np.random.default_rng(3)
for i in range(200):
    dr = int(rng.integers(1, 20)); cr = dr % 19 + 1
    flags = int(TF.linked) if i % 5 == 0 else 0
    if i % 7 == 0:
        flags |= int(TF.pending)
    rows.append(hz.transfer(1000 + i, debit_account_id=dr,
                            credit_account_id=cr,
                            amount=int(rng.integers(1, 50)), flags=flags))
rows[-1] = hz.transfer(2000, debit_account_id=1, credit_account_id=2,
                       amount=5)
ops.append((Operation.create_transfers, hz.pack(rows)))
ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 20)))))
got = [h.submit(op, body) for op, body in ops]
exp = [hc.submit(op, body) for op, body in ops]
assert got == exp, "scan kernel diverges on chip"
print("SCAN_PARITY_OK")
""",
    )
    assert "SCAN_PARITY_OK" in out


def test_device_table_flush_readback_checksum_on_chip():
    """Write-behind DeviceTable: queue deltas, flush, read back, and
    match the host mirror exactly (incl. after grow())."""
    out = _run_on_chip(
        """
import numpy as np
import jax.numpy as jnp
from tigerbeetle_tpu.state_machine.kernel_fast import DeviceTable
from tigerbeetle_tpu.state_machine.mirror import BalanceMirror

rng = np.random.default_rng(5)
dev = DeviceTable(256)
mir = BalanceMirror(256)
for batch in range(6):
    n = 500
    slots = rng.integers(0, 256, n).astype(np.int64)
    cols = rng.integers(0, 4, n).astype(np.int64)
    lo = rng.integers(0, 1 << 32, n).astype(np.uint64)
    hi = np.zeros(n, np.uint64)
    deltas = mir.try_apply_deltas(slots, cols, lo, hi)
    assert deltas is not None
    dev.enqueue(*deltas)
    if batch == 3:
        dev.grow(512)
        mir.grow(512)
tbl = np.asarray(dev.read())
exp = mir.rows8(np.arange(512, dtype=np.int64))
assert (tbl == exp).all(), "device table != mirror after flush"
print("FLUSH_READBACK_OK")
""",
    )
    assert "FLUSH_READBACK_OK" in out


def test_production_b8192_kernels_on_chip():
    """The PRODUCTION event-bucket geometry (B=8192, the bench.py
    shape) compiles and runs on the real chip with full-batch oracle
    parity — bench must not be the first place this geometry compiles
    (VERDICT r4 #7).  Covers orderfree (all-success 8190-event batch),
    linked chains, and a two-phase batch at the same bucket size."""
    code = """
import numpy as np
from tigerbeetle_tpu.state_machine.cpu import CpuStateMachine
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing import harness as hz
from tigerbeetle_tpu.types import Operation, TransferFlags as TF

from tigerbeetle_tpu.state_machine import device_kernels as dk
assert dk.B == 8192, f"production bucket expected, got {dk.B}"

sm = TpuStateMachine(engine="device", account_capacity=1 << 12,
                     transfer_capacity=64 * 1024)
h = hz.SingleNodeHarness(sm)
hc = hz.SingleNodeHarness(CpuStateMachine())
rng = np.random.default_rng(7)
ops = [(Operation.create_accounts,
        hz.pack([hz.account(i) for i in range(1, 1001)]))]

# Full production batch: 8190 order-free transfers (the bench shape).
tid = 1000
rows = []
for i in range(8190):
    dr = int(rng.integers(1, 1001)); cr = dr % 1000 + 1
    rows.append(hz.transfer(tid, debit_account_id=dr, credit_account_id=cr,
                            amount=int(rng.integers(1, 100))))
    tid += 1
ops.append((Operation.create_transfers, hz.pack(rows)))

# Linked chains at production size (avg len 4, last event unlinked).
rows = []
while len(rows) < 4000:
    clen = int(rng.integers(1, 8))
    for j in range(clen):
        dr = int(rng.integers(1, 1001)); cr = dr % 1000 + 1
        rows.append(hz.transfer(tid, debit_account_id=dr,
                                credit_account_id=cr,
                                amount=int(rng.integers(1, 100)),
                                flags=0 if j == clen - 1 else int(TF.linked)))
        tid += 1
ops.append((Operation.create_transfers, hz.pack(rows)))

# Two-phase pairs at the same bucket.
rows = []
for i in range(1000):
    dr = int(rng.integers(1, 1001)); cr = dr % 1000 + 1
    rows.append(hz.transfer(tid, debit_account_id=dr, credit_account_id=cr,
                            amount=int(rng.integers(1, 100)),
                            flags=int(TF.pending)))
    rows.append(hz.transfer(
        tid + 1, pending_id=tid,
        flags=int(TF.void_pending_transfer if i % 3 == 0
                  else TF.post_pending_transfer)))
    tid += 2
ops.append((Operation.create_transfers, hz.pack(rows)))
ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 1001)))))

futs = [h.submit_async(op, body) for op, body in ops]
got = [f.result() for f in futs]
exp = [hc.submit(op, body) for op, body in ops]
for i, (g, e) in enumerate(zip(got, exp)):
    assert g == e, f"B=8192 kernels diverge on chip at op {i}"
assert sm.stat_device_semantic_events >= 8190 + 4000 + 2000
sm.verify_device_mirror()
print("B8192_OK")
"""
    out = _run_on_chip(code, timeout=540, production_bucket=True)
    assert "B8192_OK" in out


def test_device_engine_oracle_parity_on_chip():
    """The device-authoritative engine end-to-end on real silicon:
    codes from the chip match the CPU oracle; checkpoint checksum
    passes."""
    out = _run_on_chip(
        """
import numpy as np
from tigerbeetle_tpu.state_machine.cpu import CpuStateMachine
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing import harness as hz
from tigerbeetle_tpu.types import Operation, TransferFlags as TF

sm = TpuStateMachine(engine="device", account_capacity=1 << 12)
h = hz.SingleNodeHarness(sm)
hc = hz.SingleNodeHarness(CpuStateMachine())
rng = np.random.default_rng(1)
ops = [(Operation.create_accounts,
        hz.pack([hz.account(i) for i in range(1, 101)]))]
tid = 1000
for b in range(4):
    rows = []
    for i in range(400):
        dr = int(rng.integers(1, 101)); cr = dr % 100 + 1
        rows.append(hz.transfer(tid, debit_account_id=dr,
                                credit_account_id=cr,
                                amount=int(rng.integers(1, 50))))
        tid += 1
    ops.append((Operation.create_transfers, hz.pack(rows)))
# two-phase pair batch through the device kernel
rows = [
    hz.transfer(tid, debit_account_id=1, credit_account_id=2, amount=30,
                flags=int(TF.pending)),
    hz.transfer(tid + 1, pending_id=tid,
                flags=int(TF.post_pending_transfer)),
]
ops.append((Operation.create_transfers, hz.pack(rows)))
ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 101)))))
futs = [h.submit_async(op, body) for op, body in ops]
got = [f.result() for f in futs]
exp = [hc.submit(op, body) for op, body in ops]
assert got == exp, "device engine diverges on chip"
assert sm._dev.stat_semantic_events > 0
sm.verify_device_mirror()
print("ENGINE_PARITY_OK")
""",
    )
    assert "ENGINE_PARITY_OK" in out
