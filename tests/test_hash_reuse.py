"""Hash-once commit path (round 23): digest reuse + multi-lane SHA.

The contract under test: with TB_HASH_REUSE=1 (default) every prepare
body byte is SHA-256'd at most ONCE per replica role, and with
TB_HASH_THREADS=N the remaining passes fan across native lanes — and
neither knob may move a single wire bit.  Evidence tiers mirror
tests/test_native_drain.py:

- Unit differential: tb_pl_build_prepare / tb_pl_build_prepares with
  the reuse flag on vs off produce bit-identical headers, WAL arenas,
  redundant sectors, and slot tables (the reused digest comes from the
  verified request header's checksum_body — the header-carry
  invariant — or the drain-scoped C digest table).
- Wire differential: finalize_header's cached-digest seam (the
  TB_NATIVE_PIPELINE=0 arm) is bit-identical to the hashing path, and
  a WRONG cached digest fails closed (every verifier rejects).
- Cluster differential: the SAME deterministic BatchCluster script
  (including coalesced prepares and a retransmitted duplicate mid
  drain) runs across {reuse on/off} x {lanes 0/2} x {native/Python
  pipeline} and every consensus + reply frame must be bit-identical.
- Counters: reuse-on consumes cached digests (reuse_hits > 0) and the
  off arm provably rehashes more (bytes_hashed strictly higher on the
  primary); the multi-lane arm reports lane jobs via tb_hash_stats.
"""

import time

import numpy as np
import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import SECTOR_SIZE
from tigerbeetle_tpu.runtime import fastpath
from tigerbeetle_tpu.vsr import storage as storage_mod
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.journal import HEADERS_PER_SECTOR
from tigerbeetle_tpu.vsr.wire import Command, HEADER_DTYPE
from tigerbeetle_tpu.testing.harness import pack, transfer

from test_multi import _register, _setup_accounts  # noqa: F401
from test_native_pipeline import (  # noqa: F401
    _capture_frames,
    _fuzz_request,
    _r128,
)
from test_native_drain import BatchCluster  # noqa: F401


# ----------------------------------------------------------------------
# Wire differential: the Python-fallback reuse seam.


def test_finalize_header_cached_digest_bit_identical_fuzz():
    rng = np.random.default_rng(23_01)
    for _ in range(100):
        body = rng.bytes(int(rng.integers(0, 2048)))
        a, _ = _fuzz_request(rng)
        b = a.copy()
        wire.finalize_header(a, body)
        wire.finalize_header(b, body, checksum_body=wire.checksum_pair(body))
        assert a.tobytes() == b.tobytes()


def test_finalize_header_wrong_cached_digest_fails_closed():
    """A bogus cached pair must yield a frame every verifier REJECTS —
    reuse can produce garbage frames only if the caller breaks the
    header-carry invariant, and even then nothing silently commits."""
    h, body = _fuzz_request(np.random.default_rng(23_02))
    body = body or b"x"
    wire.finalize_header(h, body, checksum_body=(123, 456))
    assert not wire.verify_header(h, body)
    # The header itself is self-consistent (checksum covers the bogus
    # checksum_body), so the failure is pinned to the BODY check.
    assert wire.verify_header(h)


# ----------------------------------------------------------------------
# Unit differential: the native build seams, reuse flag on vs off.

needs_pipeline = pytest.mark.skipif(
    not fastpath.pipeline_available(),
    reason="libtb_fastpath pipeline symbols not built",
)

needs_drain = pytest.mark.skipif(
    not fastpath.drain_available(),
    reason="libtb_fastpath r22 drain symbols not built",
)


@needs_pipeline
def test_build_prepare_reuse_bit_identical_fuzz():
    rng = np.random.default_rng(23_03)
    pl = fastpath.create_pipeline()
    for _ in range(150):
        req, body = _fuzz_request(rng)
        kw = dict(
            cluster=_r128(rng) >> 1,
            view=int(rng.integers(0, 1 << 31)),
            op=int(rng.integers(1, 1 << 32)),
            commit=int(rng.integers(0, 1 << 32)),
            timestamp=int(rng.integers(1, 1 << 62)),
            parent=_r128(rng) >> 1,
            replica=int(rng.integers(0, 6)),
            context=int(rng.integers(0, 64)),
            release=int(rng.integers(0, 1 << 31)),
        )
        hashed = pl.build_prepare(req, body, **kw)
        reused = pl.build_prepare(req, body, reuse=True, **kw)
        assert hashed.tobytes() == reused.tobytes()


@needs_drain
def test_build_prepares_reuse_bit_identical_fuzz():
    """The batch seam: reuse on vs off over whole fuzzed runs — every
    output surface compared (headers, WAL arena, redundant sectors,
    headers ring)."""
    from test_native_drain import _fuzz_requests

    rng = np.random.default_rng(23_04)
    slot_count = 64
    for _ in range(30):
        k = int(rng.integers(1, 9))
        req_hdrs, bodies = _fuzz_requests(rng, k)
        timestamps = rng.integers(1, 1 << 62, k, dtype=np.uint64)
        contexts = rng.integers(0, 64, k, dtype=np.uint64)
        kw = dict(
            cluster=_r128(rng) >> 1,
            view=int(rng.integers(0, 1 << 30)),
            op0=int(rng.integers(1, 1 << 32)),
            commit=int(rng.integers(0, 1 << 32)),
            parent=_r128(rng) >> 1,
            replica=int(rng.integers(0, 6)),
            release=int(rng.integers(0, 1 << 31)),
        )
        outs = []
        for reuse in (False, True):
            ring = np.zeros(slot_count, HEADER_DTYPE)
            built = fastpath.build_prepares(
                fastpath.create_pipeline(), req_hdrs, bodies, timestamps,
                contexts, synced=False, headers_ring=ring,
                slot_count=slot_count,
                headers_per_sector=HEADERS_PER_SECTOR,
                sector_size=SECTOR_SIZE, reuse=reuse, **kw,
            )
            assert built is not None
            prepares, (wal, wal_off, wal_len, slots, sectors, _si) = built
            outs.append((
                prepares.tobytes(), wal.tobytes(), wal_off.tobytes(),
                wal_len.tobytes(), slots.tobytes(), sectors.tobytes(),
                ring.tobytes(),
            ))
        assert outs[0] == outs[1]


@needs_drain
def test_build_prepares_multilane_bit_identical_fuzz():
    """Lane-count differential at the same seam: 0 lanes (inline) vs 3
    lanes, both reuse arms — the pool only changes WHO hashes, never
    what lands in a frame."""
    from test_native_drain import _fuzz_requests

    rng = np.random.default_rng(23_05)
    slot_count = 64
    try:
        for _ in range(10):
            k = int(rng.integers(2, 9))
            req_hdrs, bodies = _fuzz_requests(rng, k)
            timestamps = rng.integers(1, 1 << 62, k, dtype=np.uint64)
            contexts = rng.integers(0, 64, k, dtype=np.uint64)
            kw = dict(
                cluster=_r128(rng) >> 1, view=3,
                op0=int(rng.integers(1, 1 << 32)),
                commit=int(rng.integers(0, 1 << 32)),
                parent=_r128(rng) >> 1, replica=0, release=1,
            )
            outs = []
            for lanes in (0, 3):
                assert fastpath.configure_hash(lanes)
                ring = np.zeros(slot_count, HEADER_DTYPE)
                built = fastpath.build_prepares(
                    fastpath.create_pipeline(), req_hdrs, bodies,
                    timestamps, contexts, synced=False, headers_ring=ring,
                    slot_count=slot_count,
                    headers_per_sector=HEADERS_PER_SECTOR,
                    sector_size=SECTOR_SIZE, reuse=False, **kw,
                )
                assert built is not None
                prepares, (wal, *_rest) = built
                outs.append((prepares.tobytes(), wal.tobytes()))
            assert outs[0] == outs[1]
        # The 3-lane arm really ran jobs on worker threads.
        assert fastpath.hash_stats()["lane_jobs"] > 0
    finally:
        assert fastpath.configure_hash(0)


@needs_drain
def test_verify_frames2_counts_and_populates_digest_table():
    """The counted verify: returns exactly the body bytes hashed, and
    its digest-table entries serve the SAME crossing's build (table
    hits observable via tb_hash_stats)."""
    rng = np.random.default_rng(23_06)
    frames = []
    total_body = 0
    for _ in range(8):
        req, body = _fuzz_request(rng)
        frames.append(req.tobytes() + body)
        total_body += len(body)
    arena = np.frombuffer(b"".join(frames), np.uint8)
    offsets = np.zeros(len(frames), np.uint64)
    lens = np.zeros(len(frames), np.uint64)
    at = 0
    for i, f in enumerate(frames):
        offsets[i] = at
        lens[i] = len(f)
        at += len(f)
    got = fastpath.verify_frames2(arena, offsets, lens, len(frames))
    assert got is not None
    ok, bytes_hashed = got
    assert list(ok) == [1] * len(frames)
    assert bytes_hashed == total_body


# ----------------------------------------------------------------------
# Cluster differential: one deterministic script (unit + coalesced
# traffic), every knob combination, frames bit-identical.


def _hash_run(monkeypatch, *, reuse: str, threads: int, pipeline: str,
              drain: str, seed: int = 23):
    monkeypatch.setenv("TB_NATIVE_PIPELINE", pipeline)
    monkeypatch.setenv("TB_NATIVE_DRAIN", drain)
    monkeypatch.setenv("TB_HASH_REUSE", reuse)
    monkeypatch.setattr(time, "perf_counter_ns", lambda: 1_000_000_000)
    monkeypatch.setattr(
        storage_mod.MemoryStorage, "supports_deferred_sync", True,
        raising=False,
    )
    assert fastpath.configure_hash(threads)
    try:
        c = BatchCluster(3, seed=seed)
        for r in c.replicas:
            assert r._hash_reuse == (reuse == "1")
        frames = _capture_frames(c)
        cl = _register(c, 100)
        _setup_accounts(c, cl, ids=(1, 2, 3))
        # Unit traffic: request -> one prepare, digest reused from the
        # verified request header.
        for k in range(8):
            reply = c.run_request(
                cl, types.Operation.create_transfers,
                pack([transfer(500 + k, debit_account_id=1 + (k % 2),
                               credit_account_id=3, amount=1 + k)]),
            )
            assert reply == b""
        # Coalesced traffic: several clients' requests queued in one
        # drain multiplex into batched prepares (_build_batch_request
        # concatenates bodies -> the one legitimate extra hash pass).
        others = [_register(c, 200 + j) for j in range(3)]
        for j, o in enumerate(others):
            o.request(
                types.Operation.create_transfers,
                pack([transfer(800 + j, debit_account_id=1,
                               credit_account_id=2, amount=1)]),
            )
        c.run_until(lambda: not any(o.busy() for o in others), 4000)
        out = c.run_request(
            cl, types.Operation.lookup_accounts,
            np.array([1, 0, 2, 0, 3, 0], "<u8").tobytes(),
        )
        c.settle(4000)
        c.check_linearized()
        c.check_convergence()
        primary = c.replicas[0]
        counters = {
            "reuse_hits": sum(r._c_hash_reuse.value for r in c.replicas),
            "primary_bytes": primary._c_hash_bytes.value,
            "primary_committed": primary._c_hash_commit.value,
        }
        coalesced = any(
            f[0] == "peer" and int(
                np.frombuffer(f[3], HEADER_DTYPE)[0]["context_lo"]
            ) > 0
            for f in frames
            if int(np.frombuffer(f[3], HEADER_DTYPE)[0]["command"])
            == int(Command.prepare)
        )
        return frames, out, counters, coalesced
    finally:
        assert fastpath.configure_hash(0)


def _assert_same_frames(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x == y


@needs_drain
def test_cluster_frames_bit_identical_reuse_on_off(monkeypatch):
    on = _hash_run(monkeypatch, reuse="1", threads=0, pipeline="1",
                   drain="1")
    off = _hash_run(monkeypatch, reuse="0", threads=0, pipeline="1",
                    drain="1")
    _assert_same_frames(on[0], off[0])
    assert on[1] == off[1]
    # The script really exercised the coalesce seam in both arms.
    assert on[3] and off[3]
    # Reuse-on consumed cached digests; reuse-off rehashed every body
    # at build — strictly more hashing for the same frames.
    assert on[2]["reuse_hits"] > 0
    assert off[2]["reuse_hits"] == 0
    assert off[2]["primary_bytes"] > on[2]["primary_bytes"]


@needs_drain
def test_cluster_frames_bit_identical_across_lanes(monkeypatch):
    lanes0 = _hash_run(monkeypatch, reuse="1", threads=0, pipeline="1",
                       drain="1")
    lanes2 = _hash_run(monkeypatch, reuse="1", threads=2, pipeline="1",
                       drain="1")
    _assert_same_frames(lanes0[0], lanes2[0])
    assert lanes0[1] == lanes2[1]


def test_cluster_frames_bit_identical_python_fallback(monkeypatch):
    """TB_NATIVE_PIPELINE=0: the pure-Python prepare build arm, where
    reuse rides wire.finalize_header's cached-digest parameter — the
    same frames as hashing, and the same frames as the native arm runs
    (pinned separately above with the same seed/script)."""
    on = _hash_run(monkeypatch, reuse="1", threads=0, pipeline="0",
                   drain="0")
    off = _hash_run(monkeypatch, reuse="0", threads=0, pipeline="0",
                    drain="0")
    _assert_same_frames(on[0], off[0])
    assert on[1] == off[1]
    assert on[2]["reuse_hits"] > 0
    assert off[2]["primary_bytes"] > on[2]["primary_bytes"]


@needs_drain
def test_cluster_frames_native_vs_python_with_reuse(monkeypatch):
    """Cross-arm: native drain + reuse vs pure Python + reuse — the
    reuse seams live in different layers (C digest table/header-carry
    vs finalize_header parameter) and must still agree bit for bit."""
    native = _hash_run(monkeypatch, reuse="1", threads=2, pipeline="1",
                       drain="1")
    python = _hash_run(monkeypatch, reuse="1", threads=0, pipeline="0",
                       drain="0")
    _assert_same_frames(native[0], python[0])
    assert native[1] == python[1]


@needs_drain
def test_retransmitted_duplicate_mid_drain_reuse_differential(monkeypatch):
    """A retransmitted duplicate prepare spliced into a backup's drain
    run (the test_native_drain prefix-split shape) with reuse on vs
    off: the duplicate re-walks the per-item arm whose header was
    already stamped — no rehash decision can corrupt it, and the two
    arms' frames stay bit-identical."""

    def run(reuse):
        monkeypatch.setenv("TB_NATIVE_PIPELINE", "1")
        monkeypatch.setenv("TB_NATIVE_DRAIN", "1")
        monkeypatch.setenv("TB_HASH_REUSE", reuse)
        monkeypatch.setattr(
            time, "perf_counter_ns", lambda: 1_000_000_000
        )
        monkeypatch.setattr(
            storage_mod.MemoryStorage, "supports_deferred_sync", True,
            raising=False,
        )
        c = BatchCluster(3, seed=77)
        frames = _capture_frames(c)
        backup = next(r for r in c.replicas if not r.is_primary)
        orig = backup.on_prepares_batch
        injected = {"n": 0}

        def wrapped(headers, bodies):
            if headers and backup.status == "normal":
                headers = list(headers) + [headers[0].copy()]
                bodies = [bytes(b) for b in bodies] + [bytes(bodies[0])]
                injected["n"] += 1
            orig(headers, bodies)

        backup.on_prepares_batch = wrapped
        cl = _register(c, 100)
        _setup_accounts(c, cl, ids=(1, 2))
        for k in range(6):
            reply = c.run_request(
                cl, types.Operation.create_transfers,
                pack([transfer(700 + k, debit_account_id=1,
                               credit_account_id=2, amount=1)]),
            )
            assert reply == b""
        c.settle(4000)
        c.check_linearized()
        c.check_convergence()
        assert injected["n"] > 0
        return frames

    _assert_same_frames(run("1"), run("0"))
