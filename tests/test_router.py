"""Router core + sharded-cluster 2PC tests (runtime/router.py over the
deterministic sim harness in testing/cluster.py).

The crash-window regressions at the bottom pin the three coordinator
crash points the protocol must survive: before any decision (clean
abort or retransmit-commit), after the durable decision (recovery
completes the credit side), and with no client left (recovery alone
resolves) — each deterministic, no nemesis randomness.
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.runtime import router as router_mod
from tigerbeetle_tpu.runtime.router import (
    RouterCore,
    pack_results,
    result_codes,
)
from tigerbeetle_tpu.testing.cluster import ShardedCluster
from tigerbeetle_tpu.testing.harness import account, ids_bytes, pack, transfer
from tigerbeetle_tpu.types import (
    CreateTransferResult as CTR,
    TransferPendingStatus as TPS,
    XShardIds,
    shard_of_account,
)

# Account ids 2,3 map to shard 0 and 1,4 to shard 1 under n_shards=2
# (pinned by test_shard_mapping below).
S0A, S0B = 2, 3
S1A, S1B = 1, 4


# ----------------------------------------------------------------------
# Pure helpers.


def test_shard_mapping_deterministic_and_balanced():
    assert shard_of_account(7, 1) == 0
    for n in (2, 3, 8):
        counts = [0] * n
        for i in range(1, 4001):
            s = shard_of_account(i, n)
            assert s == shard_of_account(i, n)  # stable
            counts[s] += 1
        # Multiplicative mixing: no shard starves or hogs.
        assert min(counts) > 4000 / n * 0.7, counts
    assert shard_of_account(S0A, 2) == 0 and shard_of_account(S0B, 2) == 0
    assert shard_of_account(S1A, 2) == 1 and shard_of_account(S1B, 2) == 1


def test_xshard_ids_deterministic_distinct():
    a, b = XShardIds(123), XShardIds(123)
    ids_a = [getattr(a, r) for r in XShardIds._ROLES]
    assert ids_a == [getattr(b, r) for r in XShardIds._ROLES]
    assert len(set(ids_a)) == len(ids_a)
    other = [getattr(XShardIds(124), r) for r in XShardIds._ROLES]
    assert not set(ids_a) & set(other)
    for v in ids_a:
        assert v >> 127 == 1  # derived namespace: upper half
        assert v != types.U128_MAX


def test_result_codes_roundtrip():
    reply = pack_results([(3, 21), (0, 5), (2, 0)])
    assert result_codes(5, reply) == [5, 0, 0, 21, 0]
    assert pack_results([]) == b""


def test_coord_account_namespace():
    assert types.is_coord_account(types.coord_account_id(1))
    assert types.is_coord_account(types.COORD_REGISTRY_ACCOUNT)
    assert not types.is_coord_account(123456789)
    leg, peer = types.xleg_untag(types.xleg_tag(types.XLEG_CREDIT, 7))
    assert (leg, peer) == (types.XLEG_CREDIT, 7)


def test_split_keeps_chains_together_and_broadcasts_post_void():
    core = RouterCore(2, coord_timeout_s=8)
    rows = [
        transfer(1, debit_account_id=S0A, credit_account_id=S1A,
                 amount=1, flags=types.TransferFlags.linked),
        transfer(2, debit_account_id=S1A, credit_account_id=S1B, amount=1),
        transfer(3, pending_id=99,
                 flags=types.TransferFlags.post_pending_transfer),
        transfer(4, debit_account_id=S0A, credit_account_id=S1A, amount=1),
        transfer(5, debit_account_id=S0A, credit_account_id=S0B, amount=1),
    ]
    _rows, fwd, broadcast, xrows, rejects = core._plan_create_transfers(
        pack(rows)
    )
    # The chain [0,1] rides shard_of(debit of row 0) whole.
    assert fwd[shard_of_account(S0A, 2)][:2] == [0, 1]
    assert broadcast == [2]
    assert [x.index for x in xrows] == [3]
    assert fwd[0][-1] == 4 or 4 in fwd[0]
    assert rejects == []


def test_split_rejects_cross_shard_timeout():
    core = RouterCore(2, coord_timeout_s=8)
    rows = [transfer(1, debit_account_id=S0A, credit_account_id=S1A,
                     amount=1, timeout=5)]
    _rows, fwd, broadcast, xrows, rejects = core._plan_create_transfers(
        pack(rows)
    )
    assert not xrows and not fwd
    assert rejects == [(0, int(CTR.timeout_reserved_for_pending_transfer))]


# ----------------------------------------------------------------------
# Sim-cluster end-to-end (deterministic, no nemesis).


@pytest.fixture()
def sharded():
    sc = ShardedCluster(n_shards=2, replica_count=2, seed=5)
    cl = sc.client(9001)
    cl.register()
    sc.run_until(lambda: cl.registered)
    assert sc.run_request(
        cl, types.Operation.create_accounts,
        pack([account(S1A), account(S0A), account(S0B), account(S1B)]),
    ) == b""
    return sc, cl


def test_cross_shard_commit_and_lookup(sharded):
    sc, cl = sharded
    reply = sc.run_request(cl, types.Operation.create_transfers, pack([
        transfer(100, debit_account_id=S0A, credit_account_id=S0B,
                 amount=5),
        transfer(101, debit_account_id=S0A, credit_account_id=S1A,
                 amount=7),
    ]))
    assert reply == b""
    rows = np.frombuffer(
        sc.run_request(cl, types.Operation.lookup_accounts,
                       ids_bytes([S1A, S0A, S0B])),
        types.ACCOUNT_DTYPE,
    )
    assert types.u128_get(rows[0], "credits_posted") == 7
    assert types.u128_get(rows[1], "debits_posted") == 12
    assert types.u128_get(rows[2], "credits_posted") == 5
    # Cross-shard transfers have no row under their client id anywhere;
    # the router reconstructs the client-view row from the 2PC legs.
    trows = np.frombuffer(
        sc.run_request(cl, types.Operation.lookup_transfers,
                       ids_bytes([100, 101])),
        types.TRANSFER_DTYPE,
    )
    assert len(trows) == 2
    assert types.u128_get(trows[1], "id") == 101
    assert types.u128_get(trows[1], "debit_account_id") == S0A
    assert types.u128_get(trows[1], "credit_account_id") == S1A
    assert types.u128_get(trows[1], "amount") == 7
    sc.settle()
    sc.check_shards()
    sc.check_conservation()
    sc.check_atomicity([(101, 0, 1)], final=True)


def test_cross_shard_error_codes_match_oracle(sharded):
    sc, cl = sharded
    # Missing debit account / missing credit account / zero amount:
    # the 2PC holds hit the same validations the oracle runs, and the
    # min-nonzero-code merge reproduces its precedence ordering.
    reply = sc.run_request(cl, types.Operation.create_transfers, pack([
        transfer(200, debit_account_id=777, credit_account_id=S1A,
                 amount=3),  # 777 -> shard 0, unknown
        transfer(201, debit_account_id=S0A, credit_account_id=888,
                 amount=3),  # 888 -> shard 1, unknown
        transfer(202, debit_account_id=S0A, credit_account_id=S1A,
                 amount=0),
    ]))
    got = {int(r["index"]): int(r["result"])
           for r in np.frombuffer(reply, types.CREATE_RESULT_DTYPE)}
    assert got[0] == int(CTR.debit_account_not_found), got
    assert got[1] == int(CTR.credit_account_not_found), got
    assert got[2] == int(CTR.amount_must_not_be_zero), got
    sc.settle()
    sc.check_conservation()
    sc.check_atomicity([(200, 0, 1), (201, 0, 1), (202, 0, 1)],
                       final=True)


def test_local_post_void_broadcast_routing(sharded):
    sc, cl = sharded
    assert sc.run_request(cl, types.Operation.create_transfers, pack([
        transfer(300, debit_account_id=S1A, credit_account_id=S1B,
                 amount=9, flags=types.TransferFlags.pending),
    ])) == b""
    # The post references a pending id only shard 1 knows; the router
    # broadcasts and keeps the owner's verdict.
    assert sc.run_request(cl, types.Operation.create_transfers, pack([
        transfer(301, pending_id=300,
                 flags=types.TransferFlags.post_pending_transfer),
    ])) == b""
    # Unknown pending id: every shard answers not_found.
    reply = sc.run_request(cl, types.Operation.create_transfers, pack([
        transfer(302, pending_id=999_999,
                 flags=types.TransferFlags.void_pending_transfer),
    ]))
    got = np.frombuffer(reply, types.CREATE_RESULT_DTYPE)
    assert int(got[0]["result"]) == int(CTR.pending_transfer_not_found)
    rows = np.frombuffer(
        sc.run_request(cl, types.Operation.lookup_accounts,
                       ids_bytes([S1B])),
        types.ACCOUNT_DTYPE,
    )
    assert types.u128_get(rows[0], "credits_posted") == 9
    assert types.u128_get(rows[0], "credits_pending") == 0


def test_get_account_transfers_routes_by_filter_account(sharded):
    sc, cl = sharded
    assert sc.run_request(cl, types.Operation.create_transfers, pack([
        transfer(400, debit_account_id=S0A, credit_account_id=S0B,
                 amount=2),
    ])) == b""
    row = np.zeros(1, dtype=types.ACCOUNT_FILTER_DTYPE)[0]
    types.u128_set(row, "account_id", S0B)
    row["limit"] = 10
    row["flags"] = (types.AccountFilterFlags.debits
                    | types.AccountFilterFlags.credits)
    reply = sc.run_request(cl, types.Operation.get_account_transfers,
                           row.tobytes())
    trows = np.frombuffer(reply, types.TRANSFER_DTYPE)
    assert len(trows) == 1
    assert types.u128_get(trows[0], "id") == 400


# ----------------------------------------------------------------------
# Deterministic coordinator-crash windows.


def _drive_to(sc, cl, tid, want):
    """Step until the cross-shard transfer reaches hold-state `want`."""
    for _ in range(8000):
        sc.step()
        sd, s_c, _ = sc.cross_status(tid, 0, 1)
        if (sd, s_c) == want:
            return
    raise AssertionError(f"never reached {want}: now {(sd, s_c)}")


def _resolve(sc, cl, max_steps=20_000):
    sc.run_until(lambda: not cl.busy(), max_steps)
    sc.settle(max_steps)


def test_crash_before_decision_retransmit_commits(sharded):
    """Coordinator dies with both holds pending, no decision; the
    client retransmits to the restarted coordinator; the transfer must
    resolve terminally (commit or clean abort), never stay in doubt."""
    sc, cl = sharded
    cl.request(types.Operation.create_transfers, pack([
        transfer(500, debit_account_id=S0A, credit_account_id=S1A,
                 amount=9),
    ]))
    _drive_to(sc, cl, 500, (TPS.pending, TPS.pending))
    sc.kill_router()
    sc.start_router()  # recover=True; client retransmits on attach
    _resolve(sc, cl)
    sd, s_c, comp = sc.cross_status(500, 0, 1)
    assert not comp
    assert (sd, s_c) in ((TPS.posted, TPS.posted),
                         (TPS.voided, TPS.voided))
    codes = np.frombuffer(cl.reply, types.CREATE_RESULT_DTYPE)
    if (sd, s_c) == (TPS.posted, TPS.posted):
        assert len(codes) == 0
    else:
        assert int(codes[0]["result"]) == int(
            CTR.pending_transfer_expired
        )
    sc.check_shards()
    sc.check_conservation()
    sc.check_atomicity([(500, 0, 1)], final=True)


def test_crash_after_decision_recovery_completes_commit(sharded):
    """The durable decision (debit-side post) survives the crash; the
    recovered coordinator MUST finish the credit side — posting, never
    voiding (no lost money) — even with the client gone."""
    sc, cl = sharded
    cl.request(types.Operation.create_transfers, pack([
        transfer(501, debit_account_id=S0A, credit_account_id=S1A,
                 amount=6),
    ]))
    _drive_to(sc, cl, 501, (TPS.posted, TPS.pending))
    sc.kill_router()
    cl._inflight = None  # client dies with the coordinator
    sc.start_router()
    sc.run_until(
        lambda: sc.router.recovery_result is not None and sc.router.idle,
        max_steps=20_000,
    )
    assert sc.router.recovery_result["indoubt"] >= 1
    sc.settle(20_000)
    sd, s_c, comp = sc.cross_status(501, 0, 1)
    assert (sd, s_c) == (TPS.posted, TPS.posted) and not comp
    got = sc._live_sm(1).account_balances_raw(S1A)
    assert got[3] == 6  # credits_posted
    sc.check_atomicity([(501, 0, 1)], final=True)


def test_crash_orphan_recovery_aborts_cleanly(sharded):
    """No decision, no client: recovery alone probe-voids both holds —
    a clean abort, both sides released, zero balance residue."""
    sc, cl = sharded
    cl.request(types.Operation.create_transfers, pack([
        transfer(502, debit_account_id=S0A, credit_account_id=S1A,
                 amount=4),
    ]))
    _drive_to(sc, cl, 502, (TPS.pending, TPS.pending))
    sc.kill_router()
    cl._inflight = None
    sc.start_router()
    sc.run_until(
        lambda: sc.router.recovery_result is not None and sc.router.idle,
        max_steps=20_000,
    )
    assert sc.router.recovery_result["indoubt"] == 1
    sc.settle(20_000)
    sd, s_c, comp = sc.cross_status(502, 0, 1)
    assert (sd, s_c) == (TPS.voided, TPS.voided) and not comp
    assert sc._live_sm(0).account_balances_raw(S0A) == (0, 0, 0, 0)
    assert sc._live_sm(1).account_balances_raw(S1A) == (0, 0, 0, 0)
    sc.check_shards()
    sc.check_conservation()
    sc.check_atomicity([(502, 0, 1)], final=True)


def test_orphan_holds_expire_without_any_coordinator(sharded):
    """Coordinator loss with NO successor: the shards' own transfer-
    timeout machinery expires the orphaned holds — bounded in-doubt
    window, clean abort, never lost money."""
    sc, cl = sharded
    cl.request(types.Operation.create_transfers, pack([
        transfer(503, debit_account_id=S0A, credit_account_id=S1A,
                 amount=3),
    ]))
    _drive_to(sc, cl, 503, (TPS.pending, TPS.pending))
    sc.kill_router()
    cl._inflight = None
    # coord_timeout_s=8 virtual seconds at 10 ms/step, plus pulse slack.
    for _ in range(int(sc.coord_timeout_s * 100) + 400):
        sc.step()
    sd, s_c, comp = sc.cross_status(503, 0, 1)
    assert (sd, s_c) == (TPS.expired, TPS.expired) and not comp
    assert sc._live_sm(0).account_balances_raw(S0A) == (0, 0, 0, 0)
    sc.check_conservation()
    sc.check_atomicity([(503, 0, 1)])


def test_coordinator_session_survives_many_incarnations(sharded):
    """Coordinator kills must not consume shard session slots: the
    stable coordinator identity re-registers (a replay) and resumes
    its numbering; the client's impersonated sessions keep deduping
    retransmissions (19 kills once evicted a live client session)."""
    sc, cl = sharded
    for k in range(20):
        sc.kill_router()
        sc.start_router()
    tid = 600
    assert sc.run_request(cl, types.Operation.create_transfers, pack([
        transfer(tid, debit_account_id=S0A, credit_account_id=S1A,
                 amount=2),
    ]), max_steps=30_000) == b""
    sc.settle(30_000)
    sc.check_shards()
    sc.check_atomicity([(tid, 0, 1)], final=True)


def test_cluster_commitment_query_and_recovery_audit(sharded):
    """Proof of state, cluster-wide: the router's `state_root` query
    folds per-shard roots into one deterministic commitment; the
    ShardedCluster audit checker computes the same fold from live
    shard state; and a recovered coordinator records the folded root
    with its recovery result."""
    from tigerbeetle_tpu.state_machine import commitment as cm

    sc, cl = sharded
    assert sc.run_request(cl, types.Operation.create_transfers, pack([
        transfer(700, debit_account_id=S0A, credit_account_id=S0B,
                 amount=5),
        transfer(701, debit_account_id=S0A, credit_account_id=S1A,
                 amount=7),
    ])) == b""
    sc.settle()
    folded = sc.check_cluster_commitment()
    assert folded != bytes(16)
    root, n_shards = cm.parse_root_body(sc.router.query_cluster_root())
    assert root == folded and n_shards == sc.n_shards
    # Shard roots are genuinely per-shard: folding them in the wrong
    # order is a DIFFERENT commitment.
    shard_roots = [
        sc._live_sm(s).state_root() for s in range(sc.n_shards)
    ]
    assert cm.fold_cluster(shard_roots) == folded
    assert cm.fold_cluster(shard_roots[::-1]) != folded
    # Coordinator kill + recovery: the recovery task ends with a
    # proof-of-state audit whose folded root rides the result (and the
    # "router_recovered" flight note).
    sc.kill_router()
    sc.start_router(recover=True)
    sc.run_until(
        lambda: sc.router.recovery_result is not None, max_steps=20_000
    )
    assert sc.router.recovery_result["cluster_root"] == folded.hex()
    sc.settle()
    assert sc.check_cluster_commitment() == folded
