"""tbcheck: the AST invariant linter (round 17).

Three layers of proof:

1. The repo itself is clean — the tier-1 gate.  Every rule runs over
   the whole package and must report zero unsuppressed findings, and
   every suppression must carry a reason and be used.
2. Per-rule fixtures — a known-bad snippet per rule asserted to flag
   with the right rule id (and line), plus a known-good twin asserted
   clean.  This is also the migration proof for the old tests/test_tidy
   regexes (wall clock / unseeded random / print) and the r16 envcheck
   grep: every pattern they caught is caught here, now alias-aware.
3. Structural unit tests — the import graph puts leaf tools outside
   the sim-reachable set, the wire-layout rule derives the trace and
   tenant carve-outs from vsr/wire.py itself, and the CLI speaks the
   JSON schema.
"""

import json
import os
import subprocess
import sys

import pytest

from tigerbeetle_tpu.analysis import run_lint
from tigerbeetle_tpu.analysis import layout as layout_mod
from tigerbeetle_tpu.analysis.core import SourceFile
from tigerbeetle_tpu.analysis.imports import (
    SIM_ROOTS,
    build_graph,
    module_name,
    reachable,
)
from tigerbeetle_tpu.analysis.rules import all_rules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "tigerbeetle_tpu")
FIXTURES = os.path.join(os.path.dirname(__file__), "tbcheck_fixtures")


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def lint_fixture(name: str):
    """Lint one fixture file with every rule, treating it as
    sim-reachable (fixtures have no import-graph position)."""
    return run_lint(files=[fixture(name)], assume_sim=True)


# ----------------------------------------------------------------------
# 1. the tier-1 gate


@pytest.fixture(scope="module")
def repo_result():
    """One full-package pass shared by the repo-wide assertions (the
    CLI schema test below still runs its own end-to-end subprocess)."""
    return run_lint()


def test_repo_is_clean(repo_result):
    """Zero unsuppressed findings across the package — the invariant
    the whole round exists to enforce.  Failures print the finding
    list verbatim (path:line: [rule] message)."""
    assert not repo_result.findings, "\n".join(
        str(f) for f in repo_result.findings
    )
    assert repo_result.checked_files > 60  # whole package, not a subset


def test_repo_suppressions_all_carry_reasons(repo_result):
    """Indirect but total: a reasonless or unused suppression is
    itself a finding, so test_repo_is_clean also proves every
    suppression in the repo carries a reason and still earns it."""
    assert repo_result.suppressed > 0  # annotated true positives exist
    assert not [
        f for f in repo_result.findings if f.rule == "suppression"
    ]


def test_single_file_run_matches_full_run():
    """Path-scoped lint keeps the full run's import-graph position:
    router.py alone must lint clean (its allow(determinism) comments
    stay used because the sim-reachable set is still computed over
    the whole package, not just the listed file)."""
    result = run_lint(files=[
        os.path.join(PKG, "runtime", "router.py")
    ])
    assert not result.findings, "\n".join(
        str(f) for f in result.findings
    )
    assert result.suppressed >= 5  # the RouterServer wall-clock sites


def test_directory_argument_expands():
    result = run_lint(files=[os.path.join(PKG, "vsr")])
    assert result.checked_files > 5
    assert not result.findings, "\n".join(
        str(f) for f in result.findings
    )


# ----------------------------------------------------------------------
# 2. per-rule fixtures: known-bad flags, known-good twin is clean

_EXPECT_BAD = {
    # fixture -> (rule id, set of expected finding lines)
    "bad_determinism.py": ("determinism", {10, 14, 18, 22}),
    "bad_envcheck.py": ("envcheck", {8, 12, 16}),
    "bad_money.py": ("money", {7, 11, 15, 19}),
    "bad_wire_layout.py": ("wire-layout", None),
    "bad_wire_layout_claim.py": ("wire-layout", None),
    "bad_broad_except.py": ("broad-except", {7, 14, 21}),
    "bad_worker_shared.py": ("worker-shared", None),
    "bad_print.py": ("no-print", {5}),
}


@pytest.mark.parametrize("name", sorted(_EXPECT_BAD))
def test_known_bad_fixture_flags(name):
    rule, lines = _EXPECT_BAD[name]
    result = lint_fixture(name)
    hits = [f for f in result.findings if f.rule == rule]
    assert hits, f"{name}: rule {rule} reported nothing"
    if lines is not None:
        assert {f.line for f in hits} == lines, hits
    # and nothing ELSE fired — bad fixtures are bad in one dimension
    others = [f for f in result.findings if f.rule != rule]
    assert not others, others


@pytest.mark.parametrize("name", [
    "good_determinism.py", "good_envcheck.py", "good_money.py",
    "good_wire_layout.py", "good_broad_except.py",
    "good_worker_shared.py", "good_print.py",
])
def test_known_good_twin_is_clean(name):
    result = lint_fixture(name)
    assert not result.findings, "\n".join(
        str(f) for f in result.findings
    )


def test_tidy_migration_patterns_still_caught():
    """The three regexes the old tests/test_tidy.py enforced —
    time.time(), random.random(), print( — must still be caught after
    the migration, now through aliases a regex can't see."""
    src = (
        "import time as t\n"
        "import random as r\n"
        "def f():\n"
        "    t0 = t.time()\n"       # old: \btime\.time\(\)
        "    x = r.random()\n"      # old: \brandom\.random\(\)
        "    print(t0, x)\n"        # old: \bprint\(
    )
    path = fixture("_tmp_tidy_migration.py")
    with open(path, "w") as fh:
        fh.write(src)
    try:
        result = run_lint(files=[path], assume_sim=True)
        rules_hit = {f.rule for f in result.findings}
        assert "determinism" in rules_hit  # time.time + random.random
        assert "no-print" in rules_hit
        det_lines = {
            f.line for f in result.findings if f.rule == "determinism"
        }
        assert det_lines == {4, 5}
    finally:
        os.remove(path)


def test_envcheck_rule_catches_hash_knob_aliases():
    """r23 knobs (TB_HASH_REUSE / TB_HASH_THREADS) swept through every
    alias form the envcheck rule resolves — a raw read of either must
    flag no matter how the import is spelled, so the only blessed
    readers stay envcheck.hash_reuse()/hash_threads()."""
    src = (
        "import os\n"
        "import os as _o\n"
        "from os import environ as E\n"
        "from os import getenv\n"
        "def a():\n"
        "    return os.environ['TB_HASH_REUSE']\n"
        "def b():\n"
        "    return E.get('TB_HASH_THREADS')\n"
        "def c():\n"
        "    return _o.getenv('TB_HASH_REUSE')\n"
        "def d():\n"
        "    return getenv('TB_HASH_THREADS', '0')\n"
    )
    path = fixture("_tmp_hash_knobs.py")
    with open(path, "w") as fh:
        fh.write(src)
    try:
        result = run_lint(files=[path], assume_sim=True)
        env_findings = [f for f in result.findings if f.rule == "envcheck"]
        flagged = {f.line for f in env_findings}
        # direct-getenv via `from os import getenv` resolves too
        assert flagged >= {6, 8, 10}, env_findings
        assert all("TB_HASH" in f.message for f in env_findings)
    finally:
        os.remove(path)


def test_suppression_requires_reason_and_use():
    result = lint_fixture("bad_suppression.py")
    sup = [f for f in result.findings if f.rule == "suppression"]
    messages = " | ".join(f.message for f in sup)
    assert "without a rule id and reason" in messages
    assert "unused suppression" in messages
    # the reasonless allow does NOT suppress: the print still flags
    assert any(f.rule == "no-print" for f in result.findings)


def test_suppression_with_reason_suppresses():
    src = (
        "def f(x):\n"
        "    # tbcheck: allow(no-print): operator-facing tool\n"
        "    print(x)\n"
    )
    path = fixture("_tmp_allow.py")
    with open(path, "w") as fh:
        fh.write(src)
    try:
        result = run_lint(files=[path], assume_sim=True)
        assert not result.findings
        assert result.suppressed == 1
    finally:
        os.remove(path)


def test_stale_half_of_multi_rule_allow_is_reported():
    """Used-ness is per rule id: an `allow-file(a, b)` where only b
    still fires must report the dead `a` half — suppressions cannot
    rot behind a live sibling."""
    src = (
        "# tbcheck: allow-file(determinism, no-print): tooling module\n"
        "def f(x):\n"
        "    print(x)\n"  # only no-print fires; determinism is stale
    )
    path = fixture("_tmp_stale_half.py")
    with open(path, "w") as fh:
        fh.write(src)
    try:
        result = run_lint(files=[path], assume_sim=True)
        sup = [f for f in result.findings if f.rule == "suppression"]
        assert len(sup) == 1 and "determinism" in sup[0].message, (
            result.findings
        )
        assert "no-print" not in sup[0].message
        assert result.suppressed == 1  # the live half still works
    finally:
        os.remove(path)


def test_envvar_typo_fails_fast_through_tpu_fallback():
    """TB_NATIVE_SANITIZE=msan must surface its named EnvVarError, not
    vanish into the TpuStateMachine optional-native fallback as a
    silent unsanitized run."""
    code = (
        "from tigerbeetle_tpu import constants as cfg;"
        "from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine;"
        "TpuStateMachine(cfg.TEST_MIN)"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=dict(os.environ, TB_NATIVE_SANITIZE="msan",
                 JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode != 0
    assert "TB_NATIVE_SANITIZE" in proc.stderr
    assert "EnvVarError" in proc.stderr


def test_stacked_standalone_allows_merge():
    """Two standalone allows for different rules above one line must
    BOTH apply (neither clobbers the other)."""
    src = (
        "import time\n"
        "def f():\n"
        "    # tbcheck: allow(no-print): operator-facing output\n"
        "    # tbcheck: allow(determinism): log stamp, not state\n"
        "    print(time.time())\n"
    )
    path = fixture("_tmp_stacked.py")
    with open(path, "w") as fh:
        fh.write(src)
    try:
        result = run_lint(files=[path], assume_sim=True)
        assert not result.findings, result.findings
        assert result.suppressed == 2
    finally:
        os.remove(path)


# ----------------------------------------------------------------------
# 3. structural units


def _package_sources():
    files = []
    for dirpath, dirs, names in os.walk(PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        files += [os.path.join(dirpath, n) for n in names
                  if n.endswith(".py")]
    return [SourceFile(p, REPO) for p in sorted(files)]


def test_import_graph_sim_reachable_set():
    """The determinism scope is the import-graph closure of the sim
    roots: consensus/state-machine/storage modules are inside; leaf
    tools that IMPORT the sim (soak, fuzz CLI) and operator front-ends
    are outside — the property the old filename exemption list only
    approximated."""
    sources = _package_sources()
    graph = build_graph({s.path: s.tree for s in sources}, PKG)
    sim = reachable(graph)
    assert set(SIM_ROOTS) <= sim
    must_be_in = {
        "tigerbeetle_tpu.vsr.multi",
        "tigerbeetle_tpu.vsr.journal",
        "tigerbeetle_tpu.state_machine.kernel",
        "tigerbeetle_tpu.state_machine.device_engine",
        "tigerbeetle_tpu.testing.chaos",  # lazily imported by vopr
        "tigerbeetle_tpu.qos",
        "tigerbeetle_tpu.utils.worker",
        # r19: SimFollower drives the follower core inside the sim,
        # so the module is clock-free (FollowerServer's wall clock is
        # injected at the process edge, cli.py/bench.py).
        "tigerbeetle_tpu.runtime.follower",
        "tigerbeetle_tpu.vsr.aof",
    }
    assert must_be_in <= sim, must_be_in - sim
    must_be_out = {
        "tigerbeetle_tpu.testing.soak",   # imports the sim, not vice versa
        "tigerbeetle_tpu.testing.fuzz",
        "tigerbeetle_tpu.cli",
        "tigerbeetle_tpu.repl",
        "tigerbeetle_tpu.benchmark",
        "tigerbeetle_tpu.client",
        "tigerbeetle_tpu.flags",
        "tigerbeetle_tpu.bindings",
        "tigerbeetle_tpu.analysis.core",
    }
    assert not (must_be_out & sim), must_be_out & sim


def test_relative_import_resolution():
    """Relative imports resolve against the importer's package — an
    __init__.py's dotted name already IS its package, so one level
    strips nothing from it."""
    import ast as ast_mod

    files = {
        os.path.join(PKG, "fakepkg", "__init__.py"):
            ast_mod.parse("from . import leaf\n"),
        os.path.join(PKG, "fakepkg", "leaf.py"):
            ast_mod.parse("from .. import constants\n"),
        os.path.join(PKG, "constants.py"): ast_mod.parse(""),
    }
    graph = build_graph(files, PKG)
    assert "tigerbeetle_tpu.fakepkg.leaf" in graph[
        "tigerbeetle_tpu.fakepkg"
    ]
    assert "tigerbeetle_tpu.constants" in graph[
        "tigerbeetle_tpu.fakepkg.leaf"
    ]


def test_module_name_resolution():
    assert module_name(
        os.path.join(PKG, "vsr", "wire.py"), PKG
    ) == "tigerbeetle_tpu.vsr.wire"
    assert module_name(
        os.path.join(PKG, "testing", "__init__.py"), PKG
    ) == "tigerbeetle_tpu.testing"


def test_wire_layout_derived_from_wire_py():
    """The trace/tenant carve-outs the rule checks are DERIVED from
    vsr/wire.py's dtype declaration — assert the derivation against
    the known contract: trace [156, 173), tenant [173, 177), total
    256, no overlaps or gaps."""
    sf = SourceFile(os.path.join(PKG, "vsr", "wire.py"), REPO)
    import ast

    layouts = [
        layout_mod.parse_dtype_layout(node.value)
        for node in ast.walk(sf.tree)
        if isinstance(node, ast.Assign)
        and any(isinstance(t, ast.Name) and t.id == "HEADER_DTYPE"
                for t in node.targets)
    ]
    assert len(layouts) == 1 and layouts[0] is not None
    layout = layouts[0]
    assert layout.total == 256
    assert layout.span_of("trace_id", "trace_ts", "trace_flags") == (
        156, 173
    )
    assert layout.span_of("tenant") == (173, 177)
    problems = layout_mod.check_layout(layout, sf.lines, 256)
    assert not problems, problems


def test_wire_layout_overlap_is_flagged():
    """Acceptance: a scratch overlapping carve-out of header bytes is
    flagged."""
    result = lint_fixture("bad_wire_layout.py")
    assert any(
        f.rule == "wire-layout" and "overlaps" in f.message
        for f in result.findings
    ), result.findings


def test_wire_layout_lying_annotation_is_flagged():
    result = lint_fixture("bad_wire_layout_claim.py")
    assert any(
        f.rule == "wire-layout" and "annotation claims" in f.message
        for f in result.findings
    ), result.findings


def test_cli_end_to_end_json_schema():
    """`python -m tigerbeetle_tpu lint --json` over the repo: exit 0,
    well-formed schema (the machine-readable surface CI consumes)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "lint", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == 1 and doc["tool"] == "tbcheck"
    assert doc["findings"] == [] and doc["counts"] == {}
    assert doc["checked_files"] > 60
    assert isinstance(doc["suppressed"], int)


def test_cli_nonzero_on_findings():
    proc = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "lint", "--json",
         fixture("bad_print.py")],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    finding = doc["findings"][0]
    assert set(finding) == {"rule", "path", "line", "message"}
    assert finding["rule"] == "no-print" and finding["line"] == 5


def _lint_snippet(src: str, name: str = "_tmp_snippet.py"):
    path = fixture(name)
    with open(path, "w") as fh:
        fh.write(src)
    try:
        return run_lint(files=[path], assume_sim=True)
    finally:
        os.remove(path)


def test_determinism_catches_distribution_draws():
    """Global-state distribution draws (np.random.normal, gauss, ...)
    are as nondeterministic as random.random and must not pass."""
    result = _lint_snippet(
        "import numpy as np\n"
        "import random\n"
        "a = np.random.normal()\n"
        "b = np.random.standard_normal(4)\n"
        "c = random.gauss(0, 1)\n"
    )
    det = [f for f in result.findings if f.rule == "determinism"]
    assert {f.line for f in det} == {3, 4, 5}, result.findings


def test_money_catches_bare_float_dtype():
    """astype(float) loses u128 precision above 2^53 exactly like
    astype(np.float64) — bare `float` must flag too."""
    result = _lint_snippet(
        "def widen(amounts):\n"
        "    return amounts.astype(float)\n"
    )
    assert any(
        f.rule == "money" and "`float`" in f.message
        for f in result.findings
    ), result.findings
    # ...but a float ANNOTATION on a money-adjacent assignment is a
    # declaration, not computation: no finding.
    result = _lint_snippet(
        "def f(amount_cents: int):\n"
        "    amount_ratio: float = compute()\n"
        "    return amount_ratio\n"
    )
    assert not result.findings, result.findings


def test_worker_shared_catches_injected_worker():
    """A class that RECEIVES its SerialWorker (instead of
    constructing one) must still trip the rule."""
    result = _lint_snippet(
        "class Flusher:\n"
        "    def __init__(self, worker):\n"
        "        self._w = worker\n"
        "        self.dirty = 0\n"
        "    def _flush_job(self):\n"
        "        self.dirty = 0\n"
        "    def kick(self):\n"
        "        self._w.submit(self._flush_job)\n"
        "    def mark(self):\n"
        "        self.dirty += 1\n"
    )
    assert any(
        f.rule == "worker-shared" and "'dirty'" in f.message
        for f in result.findings
    ), result.findings


def test_unparseable_file_is_a_finding_not_a_crash():
    result = _lint_snippet("def broken(:\n", name="_tmp_broken.py")
    assert len(result.findings) == 1
    f = result.findings[0]
    assert f.rule == "parse" and "not parseable" in f.message
    missing = fixture("_tmp_does_not_exist.py")
    result = run_lint(files=[missing], assume_sim=True)
    assert [f.rule for f in result.findings] == ["parse"]


def test_cli_rejects_unknown_flags():
    proc = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "lint", "--jsn"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 2
    assert "unknown lint flag" in proc.stderr


def test_rule_catalog_is_stable():
    """Suppressions name rule ids — renaming one silently orphans
    every allow comment, so the catalog is pinned here."""
    assert {r.id for r in all_rules()} == {
        "determinism", "envcheck", "money", "wire-layout",
        "broad-except", "worker-shared", "no-print",
    }
    for r in all_rules():
        assert r.doc  # every rule documents its contract
