"""Hot/cold account tiering (state_machine/hot_tier.py).

Unit tests for the LRU admission machinery, plus the differential
contract the whole design hangs on: a machine forced into a tiny hot
set (TB_HOT_CAPACITY) must be BIT-IDENTICAL to the all-resident
machine — same replies, same result codes, same state roots — across
plain/two-phase/linked transfers and lookups, in both engine modes.
The slow Zipf sweep checks the perf story: a skewed workload over a
logical table 10x the hot budget keeps the hit rate >= 90%.
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import hot_tier
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing.harness import (
    SingleNodeHarness,
    account,
    pack,
    transfer,
)

TF = types.TransferFlags
AF = types.AccountFlags


# ----------------------------------------------------------------------
# HotTier unit tests.


def test_from_env_gates(monkeypatch):
    monkeypatch.delenv("TB_HOT_CAPACITY", raising=False)
    assert hot_tier.from_env(1024) is None  # unset: all-resident
    monkeypatch.setenv("TB_HOT_CAPACITY", "0")
    assert hot_tier.from_env(1024) is None
    monkeypatch.setenv("TB_HOT_CAPACITY", "1024")
    assert hot_tier.from_env(1024) is None  # budget covers the table
    monkeypatch.setenv("TB_HOT_CAPACITY", "2048")
    assert hot_tier.from_env(1024) is None
    monkeypatch.setenv("TB_HOT_CAPACITY", "64")
    tier = hot_tier.from_env(1024)
    assert tier is not None
    assert tier.hot_rows == 64 and tier.logical_capacity == 1024


def test_plan_dedups_and_ignores_negatives():
    tier = hot_tier.HotTier(64, 8)
    uniq, missing = tier.plan(np.array([5, 3, 5, -1, 3, 7]))
    assert uniq.tolist() == [3, 5, 7]
    assert missing.tolist() == [3, 5, 7]  # everything cold at start
    uniq, missing = tier.plan(np.array([-1, -1]))
    assert len(uniq) == 0 and len(missing) == 0


def test_admit_free_then_lru_eviction():
    tier = hot_tier.HotTier(64, 4)
    # Fill the four hot slots one batch at a time so the LRU stamps
    # order them oldest-first: 10, 11, 12, 13.
    for row in (10, 11, 12, 13):
        got = tier.admit(np.array([row]), protect=np.array([row]))
        assert got is not None
        tier.record_use(np.array([row]), hits=0, misses=1)
    assert sorted(tier.occupied().tolist()) == [10, 11, 12, 13]
    # Touch 10 again: it becomes most-recently-used.
    tier.record_use(np.array([10]), hits=1, misses=0)
    # Admitting two new rows must evict the two LRU occupants (11, 12),
    # never the protected batch set and never the re-touched 10.
    admitted, hot_slots, evicted = tier.admit(
        np.array([20, 21]), protect=np.array([20, 21, 10])
    )
    assert admitted.tolist() == [20, 21]
    assert sorted(evicted.tolist()) == [11, 12]
    assert sorted(tier.occupied().tolist()) == [10, 13, 20, 21]
    # Maps stay inverse of each other.
    for logical in tier.occupied():
        assert tier.logical_of[tier.hot_of[logical]] == logical
    assert tier.hot_of[11] == -1 and tier.hot_of[12] == -1
    assert tier.evicts == 2


def test_admit_refuses_when_protect_blocks_eviction():
    tier = hot_tier.HotTier(64, 2)
    tier.admit(np.array([1, 2]), protect=np.array([1, 2]))
    # Both occupants are in the new batch's protect set: nothing can
    # be evicted, so a non-partial admit refuses...
    assert tier.admit(np.array([3]), protect=np.array([1, 2, 3])) is None
    # ...and a partial admit returns the empty prefix instead.
    admitted, hot_slots, evicted = tier.admit(
        np.array([3]), protect=np.array([1, 2, 3]), partial=True
    )
    assert len(admitted) == 0 and len(evicted) == 0
    assert sorted(tier.occupied().tolist()) == [1, 2]


def test_admit_partial_prefix():
    tier = hot_tier.HotTier(64, 4)
    tier.admit(np.array([1, 2, 3]), protect=np.array([1, 2, 3]))
    # One free slot, nothing evictable: partial admits just the prefix.
    admitted, hot_slots, evicted = tier.admit(
        np.array([7, 8, 9]), protect=np.array([1, 2, 3, 7, 8, 9]),
        partial=True,
    )
    assert admitted.tolist() == [7]
    assert tier.hot_of[8] == -1 and tier.hot_of[9] == -1


def test_grow_logical_keeps_budget_and_colds_new_rows():
    tier = hot_tier.HotTier(16, 4)
    tier.admit(np.array([3]), protect=np.array([3]))
    tier.grow_logical(64)
    assert tier.logical_capacity == 64
    assert tier.hot_rows == 4  # the HBM allowance does not grow
    assert len(tier.hot_of) == 64
    assert (tier.hot_of[16:] == -1).all()  # new rows are cold
    assert tier.logical_of[tier.hot_of[3]] == 3  # old mapping intact


def test_translate_passes_negatives_through():
    tier = hot_tier.HotTier(16, 4)
    tier.admit(np.array([5, 9]), protect=np.array([5, 9]))
    out = tier.translate(np.array([5, -1, 9, -7]))
    assert out[1] == -1 and out[3] == -7
    assert out[0] == tier.hot_of[5] and out[2] == tier.hot_of[9]
    assert 0 <= out[0] < 4 and 0 <= out[2] < 4


def test_grow_zero_host_noop_and_widen():
    a = np.arange(8, dtype=np.uint64).reshape(4, 2)
    assert hot_tier.grow_zero_host(a, 4) is a
    b = hot_tier.grow_zero_host(a, 6)
    assert b.shape == (6, 2)
    assert (b[:4] == a).all() and (b[4:] == 0).all()


# ----------------------------------------------------------------------
# Differential: forced-tiny hot set vs all-resident, both engines.


def _random_transfer(rng, ids, account_ids, t_index):
    """Parity-fuzz-shaped generator: plain/pending/post/void/linked/
    balancing, with heavy id reuse (mirrors test_parity_fuzz)."""
    kind = rng.random()
    flags = 0
    amount = int(rng.integers(0, 50))
    timeout = 0
    pending_id = 0
    if kind < 0.45:
        if rng.random() < 0.4:
            flags |= TF.pending
            if rng.random() < 0.5:
                timeout = int(rng.integers(1, 4))
        if rng.random() < 0.25:
            flags |= (
                TF.balancing_debit if rng.random() < 0.5
                else TF.balancing_credit
            )
    elif kind < 0.75:
        flags |= (
            TF.post_pending_transfer if rng.random() < 0.6
            else TF.void_pending_transfer
        )
        pending_id = (
            int(rng.choice(ids))
            if len(ids) and rng.random() < 0.8
            else int(rng.integers(0, 30))
        )
    else:
        flags |= TF.pending if rng.random() < 0.3 else 0
    if rng.random() < 0.25:
        flags |= TF.linked
    new_id = (
        int(rng.choice(ids))
        if len(ids) and rng.random() < 0.35
        else t_index + 100
    )
    return transfer(
        new_id,
        debit_account_id=int(rng.choice(account_ids)),
        credit_account_id=int(rng.choice(account_ids)),
        amount=amount,
        pending_id=pending_id,
        timeout=timeout,
        ledger=int(rng.choice([1, 1, 1, 2])),
        code=int(rng.integers(0, 3)),
        flags=flags,
    ), new_id


def _mk(engine, monkeypatch, hot_capacity):
    if hot_capacity is None:
        monkeypatch.delenv("TB_HOT_CAPACITY", raising=False)
    else:
        monkeypatch.setenv("TB_HOT_CAPACITY", str(hot_capacity))
    sm = TpuStateMachine(engine=engine, account_capacity=1 << 12)
    if hot_capacity is None:
        assert sm._dev.hot is None
    else:
        assert sm._dev.hot is not None
        assert sm._dev.hot.hot_rows == hot_capacity
    return SingleNodeHarness(sm)


def _device_eligible_phase(base, tiny, plain_ids):
    """Deterministic batches the device router accepts (fresh ascending
    ids, no limit/history accounts, <= 4 unique accounts per batch —
    within the forced hot budget): orderfree, linked, and two-phase
    classes all cross the tier translation paths, and the rotation
    over six plain accounts churns a 4-row hot set hard enough to
    force evictions between batches."""
    a, b, c, d, e, f = plain_ids

    def both(rows):
        body = pack(rows)
        out_b = base.submit(types.Operation.create_transfers, body)
        out_t = tiny.submit(types.Operation.create_transfers, body)
        assert out_b == out_t
        assert base.sm.state_root() == tiny.sm.state_root()

    # Orderfree incl. pending (touches a,b,c,d: fills a 4-row hot set).
    both([
        transfer(50001, debit_account_id=a, credit_account_id=b,
                 amount=5, flags=TF.pending),
        transfer(50002, debit_account_id=c, credit_account_id=d,
                 amount=3, flags=TF.pending),
        transfer(50003, debit_account_id=a, credit_account_id=d, amount=2),
    ])
    # Orderfree on e,f: must evict two LRU rows.
    both([
        transfer(50010, debit_account_id=e, credit_account_id=f, amount=1),
        transfer(50011, debit_account_id=f, credit_account_id=e, amount=2),
    ])
    # Linked chain on e,f (device linked kernel class).
    both([
        transfer(50020, debit_account_id=e, credit_account_id=f,
                 amount=4, flags=TF.linked),
        transfer(50021, debit_account_id=f, credit_account_id=e, amount=4),
    ])
    # Two-phase finalize: the pending joins pull a,b,c,d back hot.
    both([
        transfer(50030, pending_id=50001,
                 flags=TF.post_pending_transfer),
        transfer(50031, pending_id=50002,
                 flags=TF.void_pending_transfer),
    ])
    # Interleaved lookup while the finalize may still be in flight
    # (device-mode lookups ride the dispatch stream then).
    assert (
        base.lookup_accounts(list(plain_ids)).tobytes()
        == tiny.lookup_accounts(list(plain_ids)).tobytes()
    )


@pytest.mark.parametrize("engine", ["host", "device"])
@pytest.mark.parametrize("seed", [7, 42])
def test_tiny_hot_capacity_differential(engine, seed, monkeypatch):
    """A hot set of 4 rows under 30 accounts forces admission and
    eviction on nearly every batch; replies, roots, and lookups must
    stay bit-identical to the all-resident machine."""
    rng = np.random.default_rng(seed)
    base = _mk(engine, monkeypatch, None)
    tiny = _mk(engine, monkeypatch, 4)

    account_ids = list(range(1, 25))
    rows = []
    for aid in account_ids:
        flags = 0
        r = rng.random()
        if r < 0.2:
            flags |= AF.debits_must_not_exceed_credits
        elif r < 0.4:
            flags |= AF.credits_must_not_exceed_debits
        rows.append(account(aid, flags=flags))
    # Six flag-free accounts for the device-eligible phase (limit or
    # history flags would route those batches off the device).
    plain_ids = tuple(range(25, 31))
    rows += [account(aid) for aid in plain_ids]
    a_bytes = pack(rows)
    assert base.submit(types.Operation.create_accounts, a_bytes) == \
        tiny.submit(types.Operation.create_accounts, a_bytes)

    ids: list[int] = []
    t_index = 0
    realtime = 0
    for batch_no in range(8):
        batch = []
        for _ in range(int(rng.integers(2, 16))):
            row, new_id = _random_transfer(rng, ids, account_ids, t_index)
            batch.append(row)
            ids.append(new_id)
            t_index += 1
        if rng.random() < 0.8:
            last = batch[-1].copy()
            last["flags"] = int(last["flags"]) & ~int(TF.linked)
            batch[-1] = last
        if rng.random() < 0.3:
            realtime += int(rng.integers(1, 4)) * 10**9
        body = pack(batch)
        out_b = base.submit(
            types.Operation.create_transfers, body, realtime=realtime
        )
        out_t = tiny.submit(
            types.Operation.create_transfers, body, realtime=realtime
        )
        assert out_b == out_t, f"batch {batch_no} replies diverge"
        # Interleave lookups so the tiered lookup/prefetch path runs
        # against a half-cold table mid-stream, not just at the end.
        if batch_no % 3 == 2:
            probe = [int(rng.choice(account_ids)) for _ in range(6)]
            assert (
                base.lookup_accounts(probe).tobytes()
                == tiny.lookup_accounts(probe).tobytes()
            )
        assert base.sm.state_root() == tiny.sm.state_root(), (
            f"state roots diverge after batch {batch_no}"
        )

    # Deterministic device-eligible batches: under TB_ENGINE=device the
    # random stream above mostly falls back to the exact host path
    # (reused ids, limit-flag accounts), which never touches the tier;
    # these batches drive the orderfree/linked/two-phase device routes
    # through tier prefetch + translation in both engine modes.
    _device_eligible_phase(base, tiny, plain_ids)

    assert (
        base.lookup_accounts(account_ids).tobytes()
        == tiny.lookup_accounts(account_ids).tobytes()
    )
    probe = sorted(set(ids))
    assert (
        base.lookup_transfers(probe).tobytes()
        == tiny.lookup_transfers(probe).tobytes()
    )
    # The forced-tiny machine really did tier: misses happened, and
    # the checkpoint tripwire (partial-digest compare under tiering)
    # still passes.
    tier = tiny.sm._dev.hot
    assert tier.misses > 0
    assert tier.evicts > 0
    tiny.sm.verify_device_mirror()
    base.sm.verify_device_mirror()
    snap = tiny.sm.metrics.snapshot()
    assert snap.get("dev_tier.miss", 0) == tier.misses
    assert snap.get("dev_tier.evict", 0) == tier.evicts


def test_tiered_growth_differential(monkeypatch):
    """Account creation past the initial capacity grows the LOGICAL
    table while the hot budget stays fixed; parity must hold across
    the resize."""
    base = _mk("host", monkeypatch, None)
    tiny = _mk("host", monkeypatch, 8)
    tiny.sm._dev.grow(1 << 13)
    base.sm._dev.grow(1 << 13)
    assert tiny.sm._dev.hot.logical_capacity == 1 << 13
    assert tiny.sm._dev.hot.hot_rows == 8
    account_ids = list(range(1, 40))
    rows = [account(aid) for aid in account_ids]
    a = pack(rows)
    assert base.submit(types.Operation.create_accounts, a) == tiny.submit(
        types.Operation.create_accounts, a
    )
    batch = [
        transfer(1000 + i, debit_account_id=account_ids[i % 39],
                 credit_account_id=account_ids[(i + 7) % 39], amount=3)
        for i in range(64)
    ]
    b = pack(batch)
    assert base.submit(types.Operation.create_transfers, b) == tiny.submit(
        types.Operation.create_transfers, b
    )
    assert base.sm.state_root() == tiny.sm.state_root()


# ----------------------------------------------------------------------
# Zipf capacity sweep (slow): hit rate under a 10x-logical skew.


@pytest.mark.slow
def test_zipf_hit_rate_at_10x_capacity(monkeypatch):
    """Zipf-head traffic over 640 live accounts with a 64-row hot set
    (touched set 10x the budget): after the compulsory warm-up misses,
    the steady-state hit rate must sustain >= 90% — HBM acting as a
    cache over the head, per the tentpole's perf contract.

    Hit accounting is per UNIQUE touched row per batch (hot_tier.plan
    dedups), so the workload head is near-uniform across a set that
    fits the budget with a thin 1/rank tail over the other 90% of
    accounts — a pure 1/rank draw would concentrate on a handful of
    rows and cap the unique-hit numerator far below the budget."""
    monkeypatch.setenv("TB_HOT_CAPACITY", "64")
    sm = TpuStateMachine(engine="host", account_capacity=1 << 12)
    h = SingleNodeHarness(sm)
    tier = sm._dev.hot
    assert tier is not None and tier.hot_rows == 64

    n_accounts = 640
    account_ids = np.arange(1, n_accounts + 1)
    for lo in range(0, n_accounts, 160):
        h.submit(
            types.Operation.create_accounts,
            pack([account(int(a)) for a in account_ids[lo : lo + 160]]),
        )

    rng = np.random.default_rng(45)
    head = 60  # inside the 64-row budget, leaving slack for tail churn
    p = np.zeros(n_accounts)
    p[:head] = 0.992 / head
    tail_rank = np.arange(1, n_accounts - head + 1, dtype=np.float64)
    p[head:] = (1.0 / tail_rank) / (1.0 / tail_rank).sum() * 0.008
    p /= p.sum()

    tid = 10_000

    def run_batches(n):
        nonlocal tid
        for _ in range(n):
            dr = rng.choice(account_ids, size=256, p=p)
            cr = rng.choice(account_ids, size=256, p=p)
            batch = [
                transfer(
                    tid + i,
                    debit_account_id=int(dr[i]),
                    credit_account_id=int(cr[i]),
                    amount=1,
                )
                for i in range(256)
            ]
            tid += 256
            h.submit(types.Operation.create_transfers, pack(batch))

    run_batches(4)  # warm-up: compulsory misses fill the hot set
    tier.hits = tier.misses = 0
    run_batches(16)
    total = tier.hits + tier.misses
    assert total > 0
    hit_rate = tier.hits / total
    assert hit_rate >= 0.90, (
        f"hit rate {hit_rate:.3f} < 0.90 "
        f"(hits={tier.hits} misses={tier.misses})"
    )
    # Parity spot-check rides along: digest of the tiered machine's
    # logical table equals a freshly computed root.
    sm.verify_device_mirror()
