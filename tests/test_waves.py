"""Conflict-aware wave execution (state_machine/waves.py).

Three layers:

1. Partitioner unit tests: the topological-level scheduler's plans —
   coverage, step bounds, independence inside each wave, chain runs in
   exact scan segments.
2. Wave-vs-scan differential fuzz: random batches mixing linked
   chains, two-phase post/void of in-batch pendings, Zipf hot
   accounts, balancing flags and clock jumps replay through the wave
   path (TB_WAVES=1) and the pure-scan path (TB_WAVES=0), native
   engine disabled on both; replies, balance tables, and
   created-transfer records must be bit-identical.
3. CI smoke benchmark: 10k events through both paths; fails if the
   partitioner ever emits more device-step equivalents than events
   (waves must never be WORSE than the scan) or if any reply/state
   byte diverges — tier-1 catches scheduler regressions without the
   TPU link.
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import resolve, waves
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing.harness import (
    SingleNodeHarness,
    account,
    pack,
    transfer,
)

TF = types.TransferFlags
AF = types.AccountFlags


# ---------------------------------------------------------------------------
# Partitioner.


def _meta(
    n,
    flags=None,
    dr_slot=None,
    cr_slot=None,
    dr_flags=None,
    cr_flags=None,
    id_group=None,
    p_group=None,
    p_tgt=None,
    p_found=None,
):
    z32 = np.zeros(n, np.uint32)
    return resolve.wave_dependency_metadata(
        n,
        z32 if flags is None else np.asarray(flags, np.uint32),
        np.arange(n, dtype=np.int64) if dr_slot is None else np.asarray(dr_slot, np.int64),
        np.arange(n, n + n, dtype=np.int64) if cr_slot is None else np.asarray(cr_slot, np.int64),
        z32 if dr_flags is None else np.asarray(dr_flags, np.uint32),
        z32 if cr_flags is None else np.asarray(cr_flags, np.uint32),
        np.arange(n) if id_group is None else np.asarray(id_group),
        np.full(n, -1, np.int32) if p_group is None else np.asarray(p_group, np.int32),
        np.full(n, -1, np.int32) if p_tgt is None else np.asarray(p_tgt, np.int32),
        np.zeros(n, bool) if p_found is None else np.asarray(p_found, bool),
        np.full(n, -1, np.int64),
        np.full(n, -1, np.int64),
    )


def _check_plan_invariants(plan, meta, n):
    """Structural soundness of any plan: exact cover, step bound,
    chain events only in scan/chain-wave segments, per-wave
    independence."""
    seen = np.zeros(n, bool)
    for k, (kind, idx) in enumerate(plan.segments):
        idx = np.asarray(idx)
        assert not seen[idx].any(), "segments overlap"
        seen[idx] = True
        assert (np.diff(idx) >= 1).all(), "segment indices not ascending"
        if kind == "scan":
            assert (np.diff(idx) == 1).all(), "scan segment not contiguous"
            continue
        if kind == "chains":
            assert (np.diff(idx) == 1).all(), "chain run not contiguous"
            assert meta["chain_member"][idx].all(), (
                "non-chain event in a chain-wave run"
            )
            assert not meta["chain_serial"][idx].any(), (
                "must-scan event in a chain-wave run"
            )
            assert not meta["is_pv"][idx].any(), (
                "post/void in a chain-wave run"
            )
            assert plan.chain_steps[k] < len(idx), (
                "chain-wave run no better than the scan"
            )
            continue
        assert not meta["chain_member"][idx].any(), "chain event in a wave"
        # Independence inside the wave (cross-EVENT only: one event
        # claiming both its id and an equal pending ref is one event):
        # no token claimed by two different wave-mates.
        claimed_groups: set = set()
        claimed_tgts: set = set()
        for e in idx:
            mine = {int(meta["id_group"][e])}
            if meta["p_group"][e] >= 0:
                mine.add(int(meta["p_group"][e]))
            assert not (mine & claimed_groups), "id-group claimed twice"
            claimed_groups |= mine
            if meta["p_tgt"][e] >= 0:
                t = int(meta["p_tgt"][e])
                assert t not in claimed_tgts, "durable target claimed twice"
                claimed_tgts.add(t)
        # Cross-event only: an event reading a slot that a DIFFERENT
        # wave-mate writes (its own read->apply is fine).
        per_ev = []
        for e in idx:
            rr = {int(s) for s in (meta["reads0"][e], meta["reads1"][e]) if s >= 0}
            ww = {int(s) for s in (meta["writes0"][e], meta["writes1"][e]) if s >= 0}
            per_ev.append((rr, ww))
        for a, (rr_a, _) in enumerate(per_ev):
            for b, (_, ww_b) in enumerate(per_ev):
                if a != b:
                    assert not (rr_a & ww_b), (
                        "wave-mate writes a slot another member reads"
                    )
    assert seen.all(), "plan does not cover the batch"
    assert plan.n_steps <= n, "plan worse than the scan"


def test_fresh_batch_is_one_wave():
    n = 64
    plan = waves.plan_waves(n, _meta(n))
    assert plan.n_waves == 1 and plan.n_steps == 1
    assert plan.parallel_events == n
    assert plan.wave_mask.all()


def test_two_phase_pairs_collapse_to_two_waves():
    """(pending, post) pairs: every finalizer references the in-batch
    id right before it — levels put all creators in wave 0 and all
    finalizers in wave 1."""
    n = 32
    flags = np.zeros(n, np.uint32)
    flags[0::2] = int(TF.pending)
    flags[1::2] = int(TF.post_pending_transfer)
    p_group = np.full(n, -1, np.int32)
    p_group[1::2] = np.arange(0, n, 2, dtype=np.int32)
    meta = _meta(n, flags=flags, p_group=p_group)
    plan = waves.plan_waves(n, meta)
    _check_plan_invariants(plan, meta, n)
    assert plan.n_waves == 2 and plan.n_steps == 2
    assert plan.parallel_events == n


def test_chains_run_in_scan_segments():
    n = 12
    flags = np.zeros(n, np.uint32)
    flags[4:7] = int(TF.linked)  # chain covering events 4..7 inclusive
    meta = _meta(n, flags=flags)
    plan = waves.plan_waves(n, meta)
    _check_plan_invariants(plan, meta, n)
    scans = [idx for k, idx in plan.segments if k == "scan"]
    assert len(scans) == 1 and list(scans[0]) == [4, 5, 6, 7]
    assert not plan.wave_mask[4:8].any()
    assert plan.wave_mask[:4].all() and plan.wave_mask[8:].all()


def test_balance_readers_serialize_against_writers():
    """A balancing event reads its account's row: it must not share a
    wave with any earlier or later writer of that slot."""
    n = 8
    flags = np.zeros(n, np.uint32)
    flags[4] = int(TF.balancing_debit)
    dr = np.zeros(n, np.int64)  # everyone debits slot 0
    cr = np.ones(n, np.int64)
    meta = _meta(n, flags=flags, dr_slot=dr, cr_slot=cr)
    plan = waves.plan_waves(n, meta)
    _check_plan_invariants(plan, meta, n)
    # Three levels: adders before the reader, the reader, adders after.
    lvl_of = {}
    for w, (kind, idx) in enumerate(plan.segments):
        for e in idx:
            lvl_of[int(e)] = w
    assert all(lvl_of[e] < lvl_of[4] for e in range(4))
    assert all(lvl_of[e] > lvl_of[4] for e in range(5, n))


def test_independent_chains_become_chain_wave():
    """A run of independent 3-member chains on disjoint accounts
    collapses to one position-stepped segment of ~max_chain_len
    (bucketed) device steps instead of one step per member."""
    n = 30  # 10 chains x 3 members
    flags = np.zeros(n, np.uint32)
    for c in range(10):
        flags[3 * c : 3 * c + 2] = int(TF.linked)
    dr = np.arange(n, dtype=np.int64)
    cr = np.arange(n, 2 * n, dtype=np.int64)
    meta = _meta(n, flags=flags, dr_slot=dr, cr_slot=cr)
    plan = waves.plan_waves(n, meta)
    _check_plan_invariants(plan, meta, n)
    kinds = [k for k, _ in plan.segments]
    assert kinds == ["chains"]
    assert plan.n_steps == 8  # bucketed max_chain_len, not 30
    assert plan.wave_mask.all()


def test_chain_wave_declines_cross_chain_reader():
    """Two chains coupled by a limit-account read keep the exact scan
    (a read tied to another chain's writes — or their rollback —
    would diverge from sequential order)."""
    n = 30
    flags = np.zeros(n, np.uint32)
    for c in range(10):
        flags[3 * c : 3 * c + 2] = int(TF.linked)
    dr = np.arange(n, dtype=np.int64)
    cr = np.arange(n, 2 * n, dtype=np.int64)
    dr_flags = np.zeros(n, np.uint32)
    # Chain 0's member reads its dr slot; chain 1 writes the same slot.
    dr_flags[0] = int(AF.debits_must_not_exceed_credits)
    dr[3] = dr[0]
    meta = _meta(n, flags=flags, dr_slot=dr, cr_slot=cr, dr_flags=dr_flags)
    plan = waves.plan_waves(n, meta)
    _check_plan_invariants(plan, meta, n)
    assert [k for k, _ in plan.segments] == ["scan"]


def test_chain_wave_declines_referenced_ids_and_pv(monkeypatch):
    """A chain whose member id is referenced by another event (shared
    id-group / pending ref) or that carries a post/void keeps the
    exact scan; TB_WAVES_CHAIN_MAX=0 disables chain waves entirely."""
    n = 30
    flags = np.zeros(n, np.uint32)
    for c in range(10):
        flags[3 * c : 3 * c + 2] = int(TF.linked)
    # Duplicate id-group between two chains -> decline.
    id_group = np.arange(n)
    id_group[5] = id_group[2]
    meta = _meta(n, flags=flags, id_group=id_group)
    assert [k for k, _ in waves.plan_waves(n, meta).segments] == ["scan"]
    # A pv member -> decline.
    flags2 = flags.copy()
    flags2[4] |= int(TF.post_pending_transfer)
    meta = _meta(n, flags=flags2)
    assert [k for k, _ in waves.plan_waves(n, meta).segments] == ["scan"]
    # Knob off -> decline even for a clean run.
    monkeypatch.setenv("TB_WAVES_CHAIN_MAX", "0")
    meta = _meta(n, flags=flags)
    assert [k for k, _ in waves.plan_waves(n, meta).segments] == ["scan"]


def test_plan_invariants_random_meta():
    rng = np.random.default_rng(7)
    for _ in range(50):
        n = int(rng.integers(1, 80))
        flags = np.zeros(n, np.uint32)
        flags[rng.random(n) < 0.2] |= int(TF.linked)
        flags[rng.random(n) < 0.1] |= int(TF.balancing_debit)
        pv = rng.random(n) < 0.25
        flags[pv] |= int(TF.post_pending_transfer)
        id_group = rng.integers(0, max(1, n // 2), n).astype(np.int64)
        p_group = np.where(
            pv & (rng.random(n) < 0.7),
            rng.integers(0, max(1, n // 2), n),
            -1,
        ).astype(np.int32)
        p_found = pv & (p_group < 0) & (rng.random(n) < 0.5)
        p_tgt = np.where(
            p_found, rng.integers(0, max(1, n // 3), n), -1
        ).astype(np.int32)
        meta = _meta(
            n,
            flags=flags,
            dr_slot=rng.integers(0, 6, n).astype(np.int64),
            cr_slot=rng.integers(6, 12, n).astype(np.int64),
            id_group=id_group,
            p_group=p_group,
            p_tgt=p_tgt,
            p_found=p_found,
        )
        plan = waves.plan_waves(n, meta)
        _check_plan_invariants(plan, meta, n)


# ---------------------------------------------------------------------------
# Wave-vs-scan differential fuzz (state-machine level).


def _zipf_accounts(rng, account_ids, n):
    ranks = np.arange(1, len(account_ids) + 1, dtype=np.float64)
    p = (1.0 / ranks) / (1.0 / ranks).sum()
    return rng.choice(account_ids, size=n, p=p)


def _random_batch(rng, ids, account_ids, t0):
    """A batch biased toward wave-scheduler hard cases: linked chains,
    two-phase post/void of in-batch pendings, Zipf-hot accounts,
    balancing flags, id reuse."""
    rows = []
    pending_in_batch = []
    t = t0
    n = int(rng.integers(4, 40))
    while len(rows) < n:
        r = rng.random()
        accts = _zipf_accounts(rng, account_ids, 2)
        if r < 0.2 and len(rows) + 3 <= n + 4:
            # Linked chain of 2-4 events.
            clen = int(rng.integers(2, 5))
            for k in range(clen):
                f = int(TF.linked) if k < clen - 1 else 0
                if rng.random() < 0.25:
                    f |= int(TF.pending)
                a2 = _zipf_accounts(rng, account_ids, 2)
                rows.append(
                    transfer(
                        t + 100,
                        debit_account_id=int(a2[0]),
                        credit_account_id=int(a2[1]),
                        amount=int(rng.integers(1, 40)),
                        flags=f,
                    )
                )
                ids.append(t + 100)
                t += 1
            continue
        flags = 0
        amount = int(rng.integers(0, 40))
        pending_id = 0
        timeout = 0
        if r < 0.5:
            # Post/void: usually an in-batch pending, sometimes durable
            # or dangling.
            flags |= (
                int(TF.post_pending_transfer)
                if rng.random() < 0.6
                else int(TF.void_pending_transfer)
            )
            if pending_in_batch and rng.random() < 0.6:
                pending_id = int(rng.choice(pending_in_batch))
            elif len(ids) and rng.random() < 0.7:
                pending_id = int(rng.choice(ids))
            else:
                pending_id = int(rng.integers(1, 50))
            if rng.random() < 0.5:
                amount = 0  # inherit
        else:
            if rng.random() < 0.4:
                flags |= int(TF.pending)
                if rng.random() < 0.4:
                    timeout = int(rng.integers(1, 4))
            if rng.random() < 0.3:
                flags |= (
                    int(TF.balancing_debit)
                    if rng.random() < 0.5
                    else int(TF.balancing_credit)
                )
        new_id = (
            int(rng.choice(ids))
            if len(ids) and rng.random() < 0.2
            else t + 100
        )
        rows.append(
            transfer(
                new_id,
                debit_account_id=int(accts[0]),
                credit_account_id=int(accts[1]),
                amount=amount,
                pending_id=pending_id,
                timeout=timeout,
                flags=flags,
            )
        )
        if flags & int(TF.pending) and new_id == t + 100:
            pending_in_batch.append(new_id)
        ids.append(new_id)
        t += 1
    # Never leave the batch's chain open on purpose-free runs; keep it
    # open occasionally to exercise linked_event_chain_open.
    if rng.random() < 0.8:
        last = rows[-1].copy()
        last["flags"] = int(last["flags"]) & ~int(TF.linked)
        rows[-1] = last
    return rows, t


def _make_machines(monkeypatch):
    """(wave-forced, scan-forced) machines, native disabled on both so
    the comparison isolates the JAX exact path."""
    sm_w = TpuStateMachine()
    sm_w._native = None
    sm_s = TpuStateMachine()
    sm_s._native = None
    return SingleNodeHarness(sm_w), SingleNodeHarness(sm_s)


def _submit_both(monkeypatch, hw, hs, op, body, realtime=0):
    # "1" forces wave plans even when unprofitable (maximal executor
    # coverage); "scan" routes identically but runs the pure B-step
    # scan — the differential isolates the wave executor.
    monkeypatch.setenv("TB_WAVES", "1")
    out_w = hw.submit(op, body, realtime=realtime)
    monkeypatch.setenv("TB_WAVES", "scan")
    out_s = hs.submit(op, body, realtime=realtime)
    return out_w, out_s


@pytest.mark.parametrize("seed", [11, 12, 13, 14, 15, 16])
def test_wave_vs_scan_differential(monkeypatch, seed):
    rng = np.random.default_rng(seed)
    hw, hs = _make_machines(monkeypatch)

    account_ids = list(range(1, 15))
    account_rows = []
    for aid in account_ids:
        flags = 0
        r = rng.random()
        if r < 0.2:
            flags |= AF.debits_must_not_exceed_credits
        elif r < 0.35:
            flags |= AF.credits_must_not_exceed_debits
        if rng.random() < 0.25:
            flags |= AF.history
        account_rows.append(account(aid, flags=flags))
    a_bytes = pack(account_rows)
    out_w, out_s = _submit_both(
        monkeypatch, hw, hs, types.Operation.create_accounts, a_bytes
    )
    assert out_w == out_s

    ids: list[int] = []
    t = 0
    realtime = 0
    for batch_no in range(10):
        rows, t = _random_batch(rng, ids, account_ids, t)
        if rng.random() < 0.3:
            realtime += int(rng.integers(1, 4)) * 10**9
        out_w, out_s = _submit_both(
            monkeypatch,
            hw,
            hs,
            types.Operation.create_transfers,
            pack(rows),
            realtime=realtime,
        )
        assert out_w == out_s, f"batch {batch_no} replies diverge"
        assert (
            hw.sm.pulse_next_timestamp == hs.sm.pulse_next_timestamp
        ), f"batch {batch_no} pulse schedule diverges"

    # The wave path must have actually engaged (not silently declined
    # every batch) or the fuzz is vacuous.
    assert hw.sm.stat_wave_batches > 0
    assert hs.sm.stat_wave_batches == 0

    # Final wire state: balances + created-transfer records.
    out_w = hw.lookup_accounts(account_ids)
    out_s = hs.lookup_accounts(account_ids)
    assert out_w.tobytes() == out_s.tobytes()
    probe = sorted(set(ids))
    out_w = hw.lookup_transfers(probe)
    out_s = hs.lookup_transfers(probe)
    assert out_w.tobytes() == out_s.tobytes()


# ---------------------------------------------------------------------------
# CI smoke benchmark: 10k events, both paths, no TPU link needed.


def test_smoke_10k_wave_vs_scan(monkeypatch):
    """10k mixed events through wave and scan paths on CPU: identical
    replies/state, and the partitioner never emits more device-step
    equivalents than events (a plan worse than the scan is a scheduler
    regression even when outputs stay correct)."""
    rng = np.random.default_rng(99)
    hw, hs = _make_machines(monkeypatch)
    # Limit flags ride the COLD Zipf tail: a limit check reads the
    # account's balance, which is a true serial dependency — putting
    # limits on the hot head would (correctly) serialize most of the
    # stream and measure semantics, not the scheduler.
    n_acct = 64
    a_bytes = pack(
        [
            account(
                i,
                flags=(
                    AF.debits_must_not_exceed_credits
                    if i > 3 * n_acct // 4
                    else 0
                ),
            )
            for i in range(1, n_acct + 1)
        ]
    )
    out_w, out_s = _submit_both(
        monkeypatch, hw, hs, types.Operation.create_accounts, a_bytes
    )
    assert out_w == out_s

    account_ids = np.arange(1, n_acct + 1, dtype=np.uint64)
    total = 0
    tid = 1000
    batch_events = 1024
    while total < 10_000:
        n = min(batch_events, 10_000 - total)
        n_pairs = n // 4
        rows = []
        # Half plain Zipf transfers, a quarter (pending, post) pairs.
        dr = _zipf_accounts(rng, account_ids, n)
        cr = _zipf_accounts(rng, account_ids, n)
        for k in range(n - 2 * n_pairs):
            d = int(dr[k])
            c = int(cr[k]) if int(cr[k]) != d else (d % n_acct) + 1
            rows.append(
                transfer(
                    tid,
                    debit_account_id=d,
                    credit_account_id=c,
                    amount=int(rng.integers(1, 30)),
                    flags=(
                        int(TF.balancing_debit)
                        if rng.random() < 0.02
                        else 0
                    ),
                )
            )
            tid += 1
        for k in range(n_pairs):
            d = int(dr[n - 1 - k])
            c = (d % n_acct) + 1
            rows.append(
                transfer(
                    tid,
                    debit_account_id=d,
                    credit_account_id=c,
                    amount=int(rng.integers(1, 30)),
                    flags=int(TF.pending),
                )
            )
            rows.append(
                transfer(
                    tid + 1,
                    amount=0,
                    pending_id=tid,
                    flags=int(TF.post_pending_transfer),
                )
            )
            tid += 2
        out_w, out_s = _submit_both(
            monkeypatch, hw, hs, types.Operation.create_transfers, pack(rows)
        )
        assert out_w == out_s
        total += n

    sm = hw.sm
    assert sm.stat_wave_batches > 0, "wave path never engaged"
    assert sm.stat_wave_steps <= sm.stat_wave_events, (
        f"partitioner emitted {sm.stat_wave_steps} steps for "
        f"{sm.stat_wave_events} events — worse than the scan"
    )
    # The mixed stream above is wave-friendly (2% balancing readers,
    # whose hot-slot chains serialize by true data dependency): expect
    # a real collapse, not a degenerate per-event partition.
    assert sm.stat_wave_steps * 5 <= sm.stat_wave_events, (
        "step-count collapse lost: "
        f"{sm.stat_wave_steps} steps / {sm.stat_wave_events} events"
    )
    out_w = hw.lookup_accounts(list(range(1, n_acct + 1)))
    out_s = hs.lookup_accounts(list(range(1, n_acct + 1)))
    assert out_w.tobytes() == out_s.tobytes()
