"""Known-bad: worker closure and method both mutate an undeclared
attribute."""

from tigerbeetle_tpu.utils.worker import SerialWorker


class Counter:
    def __init__(self):
        self._worker = SerialWorker("count")
        self.count = 0

    def _bump_job(self):
        self.count += 1  # worker-thread write

    def kick(self):
        self._worker.submit(self._bump_job)

    def reset(self):
        self.count = 0  # foreground write: flagged (undeclared)
