"""Known-good twin: typed, re-raising, or classifying handlers."""

from tigerbeetle_tpu.state_machine.device_engine import (
    classify_link_error,
)


def typed(fn):
    try:
        return fn()
    except ValueError:
        return None  # narrow: allowed


def reraising(fn, log):
    try:
        return fn()
    except Exception as exc:
        log(exc)
        raise  # re-raise: allowed


def classifying(fn):
    try:
        return fn()
    except Exception as exc:
        if classify_link_error(exc) == "transient":
            return None  # classified: allowed
        return False
