"""Known-bad: wall clocks + unseeded entropy, behind import aliases."""

import time as _clock
from os import urandom as entropy

import numpy as np


def tick() -> float:
    return _clock.monotonic()  # flagged: time.monotonic via alias


def stamp() -> int:
    return _clock.time_ns()  # flagged: time.time_ns via alias


def nonce() -> bytes:
    return entropy(8)  # flagged: os.urandom via from-import alias


def rng():
    return np.random.default_rng()  # flagged: unseeded default_rng
