"""Known-bad: float contamination of u128 money math."""

import numpy as np


def split(amount: int) -> int:
    return amount / 2  # flagged: true division


def fee_of(amount: int):
    return amount * 0.01  # flagged: float literal


def widen(debits_pending):
    return np.asarray(debits_pending, np.float64)  # flagged: dtype


def approximate(credits_posted):
    return float(credits_posted)  # flagged: float() cast
