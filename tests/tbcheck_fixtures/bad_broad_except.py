"""Known-bad: broad handlers that swallow without routing anywhere."""


def swallow(fn):
    try:
        return fn()
    except Exception:
        return None  # flagged: no re-raise, no classification


def swallow_everything(fn):
    try:
        return fn()
    except BaseException:
        pass  # flagged


def swallow_bare(fn):
    try:
        return fn()
    except:  # noqa: E722
        pass  # flagged
