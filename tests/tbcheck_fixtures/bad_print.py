"""Known-bad: print in a core module."""


def report(x):
    print("value:", x)  # flagged
