"""Known-good twin: the shared attribute is declared (and the class
says what serializes it)."""

import threading

from tigerbeetle_tpu.utils.worker import SerialWorker


class Counter:
    # count is written by the worker job and by reset(); every write
    # holds _lock.
    _WORKER_SHARED = frozenset({"count"})

    def __init__(self):
        self._worker = SerialWorker("count")
        self._lock = threading.Lock()
        self.count = 0

    def _bump_job(self):
        with self._lock:
            self.count += 1

    def kick(self):
        self._worker.submit(self._bump_job)

    def reset(self):
        with self._lock:
            self.count = 0
