"""Known-good twin: integer/limb money math only."""

import numpy as np


def split(amount: int) -> int:
    return amount // 2  # integer division: allowed


def widen(debits_pending):
    return np.asarray(debits_pending, np.uint64)


def ratio(events: int, secs: float) -> float:
    return events / secs  # floats fine outside money expressions
