"""Known-good twin: core modules narrate through a tracer/logger."""


def report(x, tracer):
    tracer.instant("value", value=x)
