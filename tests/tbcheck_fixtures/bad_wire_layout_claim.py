"""Known-bad: the annotation lies about the derived byte range."""

import numpy as np

CLAIM_HEADER_DTYPE = np.dtype(
    [
        ("checksum", "V16"),                                 # [0, 16)
        ("trace_id", "<u8"),                                 # [150, 158)
        ("reserved", "V232"),                                # [24, 256)
    ]
)
