"""Known-good twin: contiguous carve-outs, annotations match."""

import numpy as np

MINI_HEADER_DTYPE = np.dtype(
    [
        ("checksum", "V16"),                                 # [0, 16)
        ("trace_id", "<u8"),                                 # [16, 24)
        ("tenant", "<u4"),                                   # [24, 28)
        ("reserved", "V228"),                                # [28, 256)
    ]
)
