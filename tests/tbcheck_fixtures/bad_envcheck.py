"""Known-bad: raw TB_*/BENCH_* reads that walk past a grep."""

import os as _o
from os import environ as E


def window() -> str:
    return E["TB_DEV_WINDOW"]  # flagged: subscript via alias


def secs():
    return E.get("BENCH_OPEN_SECS")  # flagged: .get via alias


def waves():
    return _o.getenv("TB_WAVES")  # flagged: getenv via module alias
