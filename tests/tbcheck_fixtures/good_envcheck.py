"""Known-good twin: knobs go through envcheck; non-knob env is fine."""

import os

from tigerbeetle_tpu import envcheck


def window() -> int:
    return envcheck.env_int("TB_DEV_WINDOW", 96, minimum=1)


def home():
    return os.environ.get("HOME")  # not a TB_/BENCH_ knob: allowed
