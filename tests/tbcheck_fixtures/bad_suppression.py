"""Known-bad: suppressions that don't carry their weight."""


def report(x):
    print("value:", x)  # tbcheck: allow(no-print)


def quiet(x, log):
    # tbcheck: allow(no-print): stale — the print below was removed.
    log.info("value: %s", x)
