"""Known-good twin: injected clocks and seeded generators only."""

import time as _clock

import numpy as np


def elapsed_us(t0: int) -> float:
    # perf counters are observability, not state input: allowed.
    return (_clock.perf_counter_ns() - t0) / 1e3


def rng(seed: int):
    return np.random.default_rng(seed)  # seeded: allowed


def tick(clock) -> int:
    return clock()  # injected clock: the sanctioned pattern
