"""Known-bad: a scratch carve-out whose offsets collide."""

import numpy as np

SCRATCH_HEADER_DTYPE = np.dtype(
    {
        "names": ["checksum", "trace_id", "tenant", "reserved"],
        "formats": ["V16", "<u8", "<u4", "V232"],
        # tenant claims [20, 24) — overlapping trace_id [16, 24).
        "offsets": [0, 16, 20, 24],
        "itemsize": 256,
    }
)
