"""Differential coverage of the vectorized order-dependent resolvers.

resolve.py puts linked-chain and two-phase batches on the device
scatter-add path; these tests fuzz exactly the workload shapes that
route there and diff every reply and the final wire state against the
CPU oracle — asserting via the routing counters that the new paths
actually ran (a silently-punting resolver must not pass as covered).

reference: src/state_machine.zig:1220-1306 (chain loop), :1608-1741
(post/void) — the semantics under test.
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing.harness import SingleNodeHarness

TF = types.TransferFlags
AF = types.AccountFlags
Op = types.Operation


@pytest.fixture(params=["native", "python"])
def engine(request):
    """Both resolver backends: the native serial resolvers
    (tb_linked.inc / tb_two_phase.inc) and the pure-numpy fallbacks
    (resolve.py) must produce identical durable state."""
    return request.param


def replay_both(ops, engine="native"):
    replies = []
    machines = []
    for sm in (TpuStateMachine(), CpuStateMachine()):
        if engine == "python" and getattr(sm, "_native", None) is not None:
            # Drop the native resolvers but keep the shared mirror
            # memory alive (the views hold the owner reference).
            sm._native = None
        h = SingleNodeHarness(sm)
        replies.append([h.submit(op, body) for op, body in ops])
        machines.append((sm, h))
    return replies, machines


def assert_parity(ops, account_ids, transfer_ids, engine="native"):
    (rt, rc), machines = replay_both(ops, engine)
    for i, (a, b) in enumerate(zip(rt, rc)):
        assert a == b, f"reply {i} differs"
    lk = np.zeros(len(account_ids), dtype=types.U128_PAIR_DTYPE)
    lk["lo"] = account_ids
    lt = np.zeros(len(transfer_ids), dtype=types.U128_PAIR_DTYPE)
    lt["lo"] = transfer_ids
    final = []
    for sm, h in machines:
        final.append(
            (
                h.submit(Op.lookup_accounts, lk.tobytes()),
                h.submit(Op.lookup_transfers, lt.tobytes()),
            )
        )
    assert final[0] == final[1], "final wire state differs"
    return machines[0][0]  # the TpuStateMachine, for routing asserts


def make_accounts(n, limit_frac=0.0, rng=None):
    flags = np.zeros(n, np.uint16)
    if limit_frac:
        k = int(n * limit_frac)
        flags[: k // 2] = int(AF.debits_must_not_exceed_credits)
        flags[k // 2 : k] = int(AF.credits_must_not_exceed_debits)
    accts = np.zeros(n, dtype=types.ACCOUNT_DTYPE)
    accts["id_lo"] = np.arange(1, n + 1)
    accts["ledger"] = 1
    accts["code"] = 1
    accts["flags"] = flags
    return accts


def chain_batch(rng, n_events, n_acct, id0, max_len=7, amt_hi=200):
    lens = rng.integers(1, max_len + 1, n_events)
    ends = np.cumsum(lens)
    n_chains = int(np.searchsorted(ends, n_events)) + 1
    lens = lens[:n_chains]
    total = int(lens.sum())
    last = np.cumsum(lens) - 1
    tf = np.zeros(total, dtype=types.TRANSFER_DTYPE)
    tf["id_lo"] = np.arange(id0, id0 + total)
    fl = np.full(total, int(TF.linked), np.uint16)
    fl[last] = 0
    tf["flags"] = fl
    dr = rng.integers(1, n_acct + 1, total)
    cr = rng.integers(1, n_acct + 1, total)
    clash = cr == dr
    cr[clash] = dr[clash] % n_acct + 1
    tf["debit_account_id_lo"] = dr
    tf["credit_account_id_lo"] = cr
    tf["amount_lo"] = rng.integers(1, amt_hi, total)
    tf["ledger"] = 1
    tf["code"] = 1
    return tf


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_linked_limit_parity(seed, engine):
    """Chains over limit accounts: failure cascades, rollbacks, and
    re-credits interact across the batch; the fixpoint must land on
    the sequential answer."""
    rng = np.random.default_rng(seed)
    n_acct = 24
    ops = [(Op.create_accounts, make_accounts(n_acct, limit_frac=0.5).tobytes())]
    # Fund limited accounts tightly so trips are common.
    k = n_acct // 2
    f = np.zeros(k, dtype=types.TRANSFER_DTYPE)
    f["id_lo"] = np.arange(900_000, 900_000 + k)
    f["debit_account_id_lo"] = n_acct
    f["credit_account_id_lo"] = np.arange(1, k + 1)
    f["amount_lo"] = rng.integers(100, 800, k)
    f["ledger"] = 1
    f["code"] = 1
    ops.append((Op.create_transfers, f.tobytes()))
    tid = []
    for b in range(4):
        tf = chain_batch(rng, 300, n_acct, 1_000 + b * 10_000)
        tid.extend(tf["id_lo"])
        ops.append((Op.create_transfers, tf.tobytes()))
    sm = assert_parity(ops, np.arange(1, n_acct + 1), np.asarray(tid), engine)
    assert sm.stat_linked_batches >= 4


def test_linked_static_failures_in_chains(engine):
    """Static failures (bad ledger, zero amount, not-found accounts)
    inside chains must fail the whole chain with correct codes."""
    rng = np.random.default_rng(99)
    n_acct = 10
    ops = [(Op.create_accounts, make_accounts(n_acct, limit_frac=0.4).tobytes())]
    tf = chain_batch(rng, 120, n_acct, 5_000)
    # Poison scattered members.
    tf["ledger"][10] = 9  # wrong ledger
    tf["amount_lo"][33] = 0  # amount_must_not_be_zero
    tf["debit_account_id_lo"][57] = 4_242  # not found
    tf["credit_account_id_lo"][80] = tf["debit_account_id_lo"][80]  # same acct
    ops.append((Op.create_transfers, tf.tobytes()))
    sm = assert_parity(ops, np.arange(1, n_acct + 1), tf["id_lo"], engine)
    assert sm.stat_linked_batches >= 1


def test_linked_chain_open_tail(engine):
    """A batch ending on an open chain: chain_open sticks to the last
    event even when the chain already failed earlier."""
    n_acct = 6
    ops = [(Op.create_accounts, make_accounts(n_acct, limit_frac=0.5).tobytes())]
    tf = np.zeros(5, dtype=types.TRANSFER_DTYPE)
    tf["id_lo"] = np.arange(100, 105)
    tf["flags"] = [0, int(TF.linked), int(TF.linked), int(TF.linked), int(TF.linked)]
    tf["debit_account_id_lo"] = [4, 1, 4, 5, 4]  # account 1 is debit-limited
    tf["credit_account_id_lo"] = [5, 4, 5, 4, 6]
    tf["amount_lo"] = [5, 1_000_000, 7, 8, 9]  # member 1 trips the limit
    tf["ledger"] = 1
    tf["code"] = 1
    ops.append((Op.create_transfers, tf.tobytes()))
    sm = assert_parity(ops, np.arange(1, n_acct + 1), tf["id_lo"], engine)
    assert sm.stat_linked_batches >= 1


def test_plain_batch_on_limit_accounts_routes(engine):
    """Chain-free batches touching limit accounts take the resolver
    (not the serial engine): all chains have length 1."""
    rng = np.random.default_rng(5)
    n_acct = 16
    ops = [(Op.create_accounts, make_accounts(n_acct, limit_frac=0.5).tobytes())]
    tf = np.zeros(200, dtype=types.TRANSFER_DTYPE)
    tf["id_lo"] = np.arange(300, 500)
    dr = rng.integers(1, n_acct + 1, 200)
    cr = rng.integers(1, n_acct + 1, 200)
    clash = cr == dr
    cr[clash] = dr[clash] % n_acct + 1
    tf["debit_account_id_lo"] = dr
    tf["credit_account_id_lo"] = cr
    tf["amount_lo"] = rng.integers(1, 50, 200)
    tf["ledger"] = 1
    tf["code"] = 1
    ops.append((Op.create_transfers, tf.tobytes()))
    sm = assert_parity(ops, np.arange(1, n_acct + 1), tf["id_lo"], engine)
    assert sm.stat_linked_batches >= 1
    assert sm.stat_exact_events == 0


def two_phase_batch(rng, n_pairs, n_acct, id0, prev_pend, void_frac=0.3):
    ids = np.arange(id0, id0 + 2 * n_pairs, dtype=np.uint64)
    tf = np.zeros(2 * n_pairs, dtype=types.TRANSFER_DTYPE)
    tf["id_lo"] = ids
    fl = np.zeros(2 * n_pairs, np.uint16)
    fl[0::2] = int(TF.pending)
    void = rng.random(n_pairs) < void_frac
    fl[1::2] = np.where(
        void, int(TF.void_pending_transfer), int(TF.post_pending_transfer)
    )
    tf["flags"] = fl
    dr = rng.integers(1, n_acct + 1, n_pairs).astype(np.uint64)
    tf["debit_account_id_lo"][0::2] = dr
    tf["credit_account_id_lo"][0::2] = dr % n_acct + 1
    tf["amount_lo"][0::2] = rng.integers(1, 100, n_pairs)
    pend_id = ids[0::2].copy()
    for i in range(n_pairs):
        r = rng.random()
        if prev_pend and r < 0.15:
            pend_id[i] = rng.choice(prev_pend)  # durable target (or race)
        elif r < 0.20:
            pend_id[i] = ids[0::2][rng.integers(0, n_pairs)]  # in-batch race
        elif r < 0.25:
            pend_id[i] = 77_000_000 + i  # not found
    tf["pending_id_lo"][1::2] = pend_id
    # Partial amounts, inherits, mismatching fields.
    part = rng.random(n_pairs) < 0.3
    tf["amount_lo"][1::2] = np.where(part, rng.integers(0, 130, n_pairs), 0)
    tf["user_data_64"][1::2] = np.where(rng.random(n_pairs) < 0.1, 9, 0)
    mism = rng.random(n_pairs) < 0.08
    tf["ledger"][1::2] = np.where(mism, 3, 0)
    tf["ledger"][0::2] = 1
    tf["code"][0::2] = 1
    tf["code"][1::2] = np.where(rng.random(n_pairs) < 0.08, 5, 0)
    return tf, ids[0::2]


@pytest.mark.parametrize("seed", [10, 11, 12, 13])
def test_two_phase_parity(seed, engine):
    """Pending/post/void with in-batch + durable references, races,
    inherits, partial posts, and mismatch codes."""
    rng = np.random.default_rng(seed)
    n_acct = 40
    ops = [(Op.create_accounts, make_accounts(n_acct).tobytes())]
    prev_pend = []
    tid = []
    for b in range(4):
        tf, pend_ids = two_phase_batch(rng, 250, n_acct, 1 + b * 100_000, prev_pend)
        tid.extend(tf["id_lo"])
        ops.append((Op.create_transfers, tf.tobytes()))
        prev_pend.extend(pend_ids[rng.random(len(pend_ids)) < 0.25])
    sm = assert_parity(ops, np.arange(1, n_acct + 1), np.asarray(tid), engine)
    assert sm.stat_two_phase_batches >= 3  # adversarial shapes may punt one


def test_two_phase_cross_batch_status(engine):
    """A pending finalized in batch 1 must reject re-finalization in
    batch 2 with the winner's status code, through the resolver."""
    n_acct = 4
    ops = [(Op.create_accounts, make_accounts(n_acct).tobytes())]
    t1 = np.zeros(2, dtype=types.TRANSFER_DTYPE)
    t1["id_lo"] = [10, 11]
    t1["flags"] = [int(TF.pending), int(TF.post_pending_transfer)]
    t1["debit_account_id_lo"][0] = 1
    t1["credit_account_id_lo"][0] = 2
    t1["amount_lo"][0] = 50
    t1["pending_id_lo"][1] = 10
    t1["ledger"][0] = 1
    t1["code"][0] = 1
    ops.append((Op.create_transfers, t1.tobytes()))
    t2 = np.zeros(2, dtype=types.TRANSFER_DTYPE)
    t2["id_lo"] = [20, 21]
    t2["flags"] = [int(TF.void_pending_transfer), int(TF.post_pending_transfer)]
    t2["pending_id_lo"] = [10, 10]
    ops.append((Op.create_transfers, t2.tobytes()))
    sm = assert_parity(ops, np.arange(1, n_acct + 1), np.asarray([10, 11, 20, 21]), engine)
    assert sm.stat_two_phase_batches >= 2


def test_resolver_punts_stay_exact(engine):
    """Shapes outside the resolvers' contracts (duplicate ids in a pv
    batch, balancing flags in chains) must flow to the exact engine
    and still match the oracle."""
    rng = np.random.default_rng(77)
    n_acct = 12
    ops = [(Op.create_accounts, make_accounts(n_acct, limit_frac=0.5).tobytes())]
    tf = chain_batch(rng, 60, n_acct, 3_000)
    tf["flags"][5] |= int(TF.balancing_debit)
    ops.append((Op.create_transfers, tf.tobytes()))
    dup = np.zeros(4, dtype=types.TRANSFER_DTYPE)
    dup["id_lo"] = [7_000, 7_000, 7_001, 7_002]  # in-batch duplicate
    dup["flags"][3] = int(TF.post_pending_transfer)
    dup["debit_account_id_lo"][:3] = 9
    dup["credit_account_id_lo"][:3] = 10
    dup["amount_lo"][:3] = 5
    dup["pending_id_lo"][3] = 7_000
    dup["ledger"][:3] = 1
    dup["code"][:3] = 1
    ops.append((Op.create_transfers, dup.tobytes()))
    assert_parity(
        ops, np.arange(1, n_acct + 1),
        np.concatenate([tf["id_lo"], dup["id_lo"]]), engine,
    )
