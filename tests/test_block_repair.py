"""Automated single-block peer repair (scrubber -> request_blocks ->
block) — a corrupt grid block on one replica heals from a peer with no
operator action and no full state sync (reference:
src/vsr/grid_blocks_missing.zig:1-30, src/vsr/grid_scrubber.zig)."""

import numpy as np
import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.harness import account, pack, transfer
from tigerbeetle_tpu.types import Operation as Op


def build_cluster_with_grid_state(seed=11):
    """3 replicas, committed past a checkpoint so every replica's
    forest holds live grid blocks (spilled rows + manifest log)."""
    c = Cluster(
        replica_count=3, seed=seed,
        state_machine_factory=lambda: TpuStateMachine(cfg.TEST_MIN),
    )
    client = c.client(500)
    client.register()
    c.run_until(lambda: client.registered)
    c.run_request(client, Op.create_accounts, pack([account(1), account(2)]))
    interval = c.replicas[0].config.vsr_checkpoint_interval
    for k in range(interval + 4):
        c.run_request(
            client, Op.create_transfers,
            pack([transfer(1000 + k, debit_account_id=1,
                           credit_account_id=2, amount=1)]),
        )
    assert c.replicas[0].checkpoint_op > 0
    return c


def corrupt_one_block(replica):
    """Corrupt the first allocated grid block on disk; returns its
    address."""
    grid = replica.forest.grid
    allocated = np.flatnonzero(~grid.free_set.free)
    assert len(allocated) > 0, "no live grid blocks to corrupt"
    addr = int(allocated[0]) + 1
    grid._cache.remove(addr)
    replica.storage.corrupt_sector(grid._offset(addr))
    assert not grid.verify_block(addr)
    return addr


def test_scrubber_finds_and_repairs_from_peer():
    c = build_cluster_with_grid_state()
    victim = c.replicas[1]  # a backup
    addr = corrupt_one_block(victim)

    for _ in range(20000):
        c.step()
        if victim.stat_blocks_repaired >= 1 and not victim._blocks_missing:
            break
    assert victim.stat_blocks_repaired >= 1, "block never repaired"
    assert victim.forest.grid.verify_block(addr)
    # Bit-identical to the intact peer's copy.
    healthy = c.replicas[0]
    assert (
        victim.storage.read(
            victim.forest.grid._offset(addr), victim.forest.grid.block_size
        )
        == healthy.storage.read(
            healthy.forest.grid._offset(addr), healthy.forest.grid.block_size
        )
    )
    c.check_convergence()


def test_repair_routes_around_corrupt_peer():
    """When the first peer asked ALSO has a corrupt copy, the
    round-robin retry heals from the remaining intact replica (the
    fault model guarantees >= 1 intact copy cluster-wide)."""
    c = build_cluster_with_grid_state(seed=12)
    victim = c.replicas[2]
    addr = corrupt_one_block(victim)
    # Corrupt the SAME block on one more replica: only replica 1 keeps
    # an intact copy.
    other = c.replicas[0]
    other.forest.grid._cache.remove(addr)
    other.storage.corrupt_sector(other.forest.grid._offset(addr))

    for _ in range(40000):
        c.step()
        if victim.stat_blocks_repaired >= 1 and not victim._blocks_missing:
            break
    assert victim.stat_blocks_repaired >= 1, "block never repaired"
    assert victim.forest.grid.verify_block(addr)


def test_primary_repairs_too():
    c = build_cluster_with_grid_state(seed=13)
    primary = next(r for r in c.replicas if r.is_primary)
    addr = corrupt_one_block(primary)
    for _ in range(20000):
        c.step()
        if primary.stat_blocks_repaired >= 1 and not primary._blocks_missing:
            break
    assert primary.stat_blocks_repaired >= 1
    assert primary.forest.grid.verify_block(addr)
    c.check_convergence()
