"""C-resident drain loop (round 22, native/tb_pipeline.cpp batch
entry points): the differential contract TB_NATIVE_DRAIN=0/1 one layer
above the r20 per-prepare pipeline.

Four tiers of evidence, mirroring how the seam can break:

- Unit differential: tb_pl_build_prepares / tb_pl_accept_prepares /
  tb_pl_on_acks / tb_pl_commit_ready_run fuzzed against the r20
  scalar entry points (themselves fuzzed against wire.py) byte for
  byte — chained parents, journal framing, vote verdicts, ready runs.
- Cluster differential: the sim cluster's per-message delivery never
  reaches the batch seams (runtime/server.py's drain does), so a
  BatchCluster pump regroups each tick's due packets into contiguous
  same-command runs and feeds them through on_prepares_batch /
  on_prepare_oks_batch / on_requests_batch — exactly the server's
  _dispatch_drain shape — then the SAME deterministic script runs
  with TB_NATIVE_DRAIN on and off and every consensus + reply frame
  must be bit-identical.
- Chaos: the r10 group-commit contract (no ack before its covering
  sync — instrumented to see write_prepare_framed, the drain's WAL
  entry point) and crash-at-fsync failover fuzz re-run on the drain
  arm with batched delivery and hash-log convergence.
- Regressions: retransmit-of-committed mid-drain gets its stored
  reply (never a busy) while fresh traffic sheds around it, and a
  stale .so fails fast on explicit TB_NATIVE_DRAIN=1.
"""

import time

import numpy as np
import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import SECTOR_SIZE
from tigerbeetle_tpu.runtime import fastpath
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.harness import account, ids_bytes, pack, transfer
from tigerbeetle_tpu.vsr import storage as storage_mod
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.journal import HEADERS_PER_SECTOR
from tigerbeetle_tpu.vsr.storage import FsyncCrash, _sectors
from tigerbeetle_tpu.vsr.wire import Command, HEADER_DTYPE

from test_multi import _register, _setup_accounts  # noqa: F401
from test_native_pipeline import (  # noqa: F401
    _StaleLib,
    _assert_mirror,
    _capture_frames,
    _fuzz_request,
    _r64,
    _r128,
)

pytestmark = pytest.mark.skipif(
    not fastpath.drain_available(),
    reason="libtb_fastpath with r22 drain symbols not built",
)


# ----------------------------------------------------------------------
# Unit differential: the batch C entry points vs the r20 scalar ones.


def _fuzz_requests(rng, k):
    pairs = [_fuzz_request(rng) for _ in range(k)]
    req_hdrs = np.array([h for h, _ in pairs])
    bodies = [b for _, b in pairs]
    return req_hdrs, bodies


def test_build_prepares_bit_identical_fuzz():
    """One tb_pl_build_prepares call == K chained tb_pl_build_prepare
    calls + K Python journal framings: headers, parent chain, slot
    table registration, WAL arena bytes, redundant header sectors."""
    rng = np.random.default_rng(22_01)
    slot_count = 64
    assert slot_count % HEADERS_PER_SECTOR == 0
    for _ in range(40):
        k = int(rng.integers(1, 9))
        req_hdrs, bodies = _fuzz_requests(rng, k)
        timestamps = rng.integers(1, 1 << 62, k, dtype=np.uint64)
        contexts = rng.integers(0, 64, k, dtype=np.uint64)
        kw = dict(
            cluster=_r128(rng) >> 1,
            view=int(rng.integers(0, 1 << 30)),
            op0=int(rng.integers(1, 1 << 32)),
            commit=int(rng.integers(0, 1 << 32)),
            parent=_r128(rng) >> 1,
            replica=int(rng.integers(0, 6)),
            release=int(rng.integers(0, 1 << 31)),
        )
        ring_c = np.zeros(slot_count, HEADER_DTYPE)
        ring_py = np.zeros(slot_count, HEADER_DTYPE)
        pl_c = fastpath.create_pipeline()
        pl_py = fastpath.create_pipeline()
        built = fastpath.build_prepares(
            pl_c, req_hdrs, bodies, timestamps, contexts,
            synced=False, headers_ring=ring_c, slot_count=slot_count,
            headers_per_sector=HEADERS_PER_SECTOR,
            sector_size=SECTOR_SIZE, **kw,
        )
        assert built is not None
        prepares, (wal, wal_off, wal_len, slots, sectors, sector_index) = (
            built
        )
        parent = kw["parent"]
        expect_off = 0
        for i in range(k):
            op = kw["op0"] + i
            oracle = pl_py.build_prepare(
                req_hdrs[i], bodies[i], cluster=kw["cluster"],
                view=kw["view"], op=op, commit=kw["commit"],
                timestamp=int(timestamps[i]), parent=parent,
                replica=kw["replica"], context=int(contexts[i]),
                release=kw["release"],
            )
            assert prepares[i].tobytes() == oracle.tobytes()
            parent = wire.u128(oracle, "checksum")
            # Slot table: registered with the self-vote, unsynced.
            assert pl_c.votes(op) == 1
            # Journal framing (the write_prepare byte layout).
            msg = oracle.tobytes() + bodies[i]
            padded = msg.ljust(_sectors(len(msg)), b"\x00")
            slot = op % slot_count
            assert int(slots[i]) == slot
            assert int(wal_off[i]) == expect_off
            assert int(wal_len[i]) == len(padded)
            assert (
                wal[expect_off : expect_off + len(padded)].tobytes()
                == padded
            )
            expect_off += len(padded)
            ring_py[slot] = oracle
            first = slot // HEADERS_PER_SECTOR * HEADERS_PER_SECTOR
            sector_py = ring_py[
                first : first + HEADERS_PER_SECTOR
            ].tobytes().ljust(SECTOR_SIZE, b"\x00")
            base = i * SECTOR_SIZE
            assert sectors[base : base + SECTOR_SIZE].tobytes() == sector_py
        assert ring_c.tobytes() == ring_py.tobytes()
        # Unsynced: the commit gate holds over the whole run.
        assert pl_c.commit_ready_run(kw["op0"] - 1, 1) == 0
        pl_c.mark_all_synced()
        assert pl_c.commit_ready_run(kw["op0"] - 1, 1) == k


def test_accept_prepares_bit_identical_fuzz():
    """One tb_pl_accept_prepares call == K Python journal framings +
    K tb_pl_build_prepare_ok calls."""
    rng = np.random.default_rng(22_02)
    slot_count = 64
    pl = fastpath.create_pipeline()
    for _ in range(40):
        k = int(rng.integers(1, 9))
        req_hdrs, bodies = _fuzz_requests(rng, k)
        cluster = _r128(rng) >> 1
        op0 = int(rng.integers(1, 1 << 32))
        prepares = np.empty(k, HEADER_DTYPE)
        parent = _r128(rng) >> 1
        for i in range(k):
            prepares[i] = pl.build_prepare(
                req_hdrs[i], bodies[i], cluster=cluster,
                view=3, op=op0 + i, commit=op0 - 1,
                timestamp=int(rng.integers(1, 1 << 62)), parent=parent,
                replica=0, context=0, release=1,
            )
            parent = wire.u128(prepares[i], "checksum")
        view = int(rng.integers(0, 1 << 30))
        replica = int(rng.integers(0, 6))
        build_oks = bool(rng.integers(0, 2))
        ring_c = np.zeros(slot_count, HEADER_DTYPE)
        ring_py = np.zeros(slot_count, HEADER_DTYPE)
        got = fastpath.accept_prepares(
            prepares, bodies, view=view, replica=replica,
            build_oks=build_oks, headers_ring=ring_c,
            slot_count=slot_count,
            headers_per_sector=HEADERS_PER_SECTOR,
            sector_size=SECTOR_SIZE,
        )
        assert got is not None
        oks, (wal, wal_off, wal_len, slots, sectors, sector_index) = got
        expect_off = 0
        for i in range(k):
            h = prepares[i]
            msg = h.tobytes() + bodies[i]
            padded = msg.ljust(_sectors(len(msg)), b"\x00")
            slot = (op0 + i) % slot_count
            assert int(slots[i]) == slot
            assert int(wal_off[i]) == expect_off
            assert int(wal_len[i]) == len(padded)
            assert (
                wal[expect_off : expect_off + len(padded)].tobytes()
                == padded
            )
            expect_off += len(padded)
            ring_py[slot] = h
            first = slot // HEADERS_PER_SECTOR * HEADERS_PER_SECTOR
            sector_py = ring_py[
                first : first + HEADERS_PER_SECTOR
            ].tobytes().ljust(SECTOR_SIZE, b"\x00")
            base = i * SECTOR_SIZE
            assert sectors[base : base + SECTOR_SIZE].tobytes() == sector_py
            if build_oks:
                oracle = pl.build_prepare_ok(h, view, replica)
                assert oks[i].tobytes() == oracle.tobytes()
        assert ring_c.tobytes() == ring_py.tobytes()


def test_on_acks_verdicts_mirror_scalar_path():
    """One tb_pl_on_acks call over a mixed run (valid, duplicate,
    foreign-cluster, wrong-view, unknown-op, stale-sibling) == the
    per-ack path on a mirror table, plus the typed drop codes."""
    rng = np.random.default_rng(22_03)
    cluster = 7777
    view = 5
    pl = fastpath.create_pipeline()
    mirror = fastpath.create_pipeline()
    prepares = []
    for i in range(6):
        req, body = _fuzz_request(rng)
        p = pl.build_prepare(
            req, body, cluster=cluster, view=view, op=100 + i, commit=99,
            timestamp=1 + i, parent=2, replica=0, context=0, release=1,
        )
        pl.note_prepare(p, False, 0)
        mirror.note_prepare(p, False, 0)
        prepares.append(p)

    def _ok(prepare, *, cluster=cluster, view=view, op=None, context=None,
            replica=1):
        h = wire.make_header(
            command=Command.prepare_ok, cluster=cluster, view=view,
            op=int(prepare["op"]) if op is None else op, replica=replica,
            context=(
                wire.u128(prepare, "checksum") if context is None
                else context
            ),
            client=wire.u128(prepare, "client"),
        )
        wire.finalize_header(h, b"")
        return h

    acks = [
        _ok(prepares[0]),                       # vote -> 2
        _ok(prepares[0]),                       # duplicate -> still 2
        _ok(prepares[0], replica=2),            # vote -> 3
        _ok(prepares[1], cluster=999),          # foreign cluster -> -4
        _ok(prepares[1], view=view + 1),        # future view -> -3
        _ok(prepares[1], op=555),               # unknown op -> -1
        _ok(prepares[1], context=123456789),    # stale sibling -> -2
        _ok(prepares[1]),                       # vote -> 2
    ]
    accepted, verdicts = pl.on_acks(np.array(acks), cluster, view)
    assert list(verdicts) == [2, 2, 3, -4, -3, -1, -2, 2]
    assert accepted == 4
    # Per-ack differential: past the cluster/view screen (the caller's
    # job in the scalar path), every verdict matches the scalar
    # tb_pl_on_ack on a mirror table — None <=> a negative verdict.
    for h, verdict in zip(acks, verdicts):
        if wire.u128(h, "cluster") != cluster or int(h["view"]) != view:
            continue
        got = mirror.on_ack(h)
        assert got == (None if verdict < 0 else int(verdict))
    for op in range(100, 106):
        assert pl.votes(op) == mirror.votes(op)


def test_commit_ready_run_matches_scalar_walk_fuzz():
    """tb_pl_commit_ready_run == iterating tb_pl_commit_ready op by
    op, under fuzzed synced flags and vote counts."""
    rng = np.random.default_rng(22_04)
    for _ in range(50):
        pl = fastpath.create_pipeline()
        k = int(rng.integers(1, 12))
        commit_min = int(rng.integers(0, 1 << 30))
        quorum = int(rng.integers(1, 4))
        for i in range(k):
            req, body = _fuzz_request(rng)
            p = pl.build_prepare(
                req, body, cluster=1, view=1, op=commit_min + 1 + i,
                commit=commit_min, timestamp=1 + i, parent=2, replica=0,
                context=0, release=1,
            )
            pl.note_prepare(p, bool(rng.integers(0, 2)), 0)
            for voter in range(1, int(rng.integers(1, 4))):
                ok = wire.make_header(
                    command=Command.prepare_ok, cluster=1, view=1,
                    op=commit_min + 1 + i, replica=voter,
                    context=wire.u128(p, "checksum"),
                    client=wire.u128(p, "client"),
                )
                wire.finalize_header(ok, b"")
                pl.on_ack(ok)
        run = pl.commit_ready_run(commit_min, quorum)
        oracle = 0
        while pl.commit_ready(commit_min + oracle, quorum):
            oracle += 1
        assert run == oracle


# ----------------------------------------------------------------------
# Batched-delivery cluster: the sim's per-message _deliver never
# reaches the batch seams, so this pump regroups each tick's due
# packets into contiguous same-destination same-command runs — the
# exact shape runtime/server.py's _dispatch_drain produces.


class BatchCluster(Cluster):
    BATCHED = {
        int(Command.request), int(Command.prepare), int(Command.prepare_ok)
    }

    def step(self) -> None:
        self.realtime += cfg.TICK_NS
        for i, r in enumerate(self.replicas):
            if r.status == "crashed":
                continue
            r.realtime = self.realtime + self.clock_skew[i]
            r.tick()
        for c in self.clients.values():
            c.tick()
        for f in self.followers:
            f.tick()
        due: list = []
        self.network.advance(
            lambda dst, header, body: due.append((dst, header, body))
        )
        run_dst = run_cmd = None
        run_hdrs: list = []
        run_bodies: list = []

        def flush_run():
            nonlocal run_dst, run_cmd, run_hdrs, run_bodies
            if not run_hdrs:
                return
            r = self.replicas[run_dst]
            if r.status != "crashed":
                if run_cmd == int(Command.prepare):
                    r.on_prepares_batch(run_hdrs, run_bodies)
                elif run_cmd == int(Command.prepare_ok):
                    r.on_prepare_oks_batch(run_hdrs)
                else:
                    r.on_requests_batch(run_hdrs, run_bodies)
            run_dst = run_cmd = None
            run_hdrs, run_bodies = [], []

        for dst, header, body in due:
            cmd = int(header["command"])
            if (
                isinstance(dst, int)
                and dst < len(self.replicas)
                and cmd in self.BATCHED
            ):
                if run_hdrs and (dst != run_dst or cmd != run_cmd):
                    flush_run()
                run_dst, run_cmd = dst, cmd
                run_hdrs.append(header)
                run_bodies.append(body)
            else:
                flush_run()
                self._deliver(dst, header, body)
        flush_run()
        for r in self.replicas:
            if r.status != "crashed":
                r.flush_group_commit()
        if self.root_ring_size:
            self._merge_root_history()


# ----------------------------------------------------------------------
# Cluster differential: same deterministic script through the batch
# seams, TB_NATIVE_DRAIN on vs off, every frame bit-identical.


def _drained_run(monkeypatch, drain: str, *, seed: int = 31):
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "1")
    monkeypatch.setenv("TB_NATIVE_DRAIN", drain)
    # The only nondeterministic wire bytes are trace_ts stamps: pin
    # the clock so the on/off frames compare bit for bit.
    monkeypatch.setattr(time, "perf_counter_ns", lambda: 1_000_000_000)
    monkeypatch.setattr(
        storage_mod.MemoryStorage, "supports_deferred_sync", True,
        raising=False,
    )
    c = BatchCluster(3, seed=seed)
    for r in c.replicas:
        assert r._gc_enabled and r._np is not None
        assert r._drain_native == (drain == "1")
        assert r.journal._native_frame
    frames = _capture_frames(c)
    cl = _register(c, 100)
    _setup_accounts(c, cl, ids=(1, 2, 3))
    for k in range(12):
        reply = c.run_request(
            cl, types.Operation.create_transfers,
            pack([transfer(500 + k, debit_account_id=1 + (k % 2),
                           credit_account_id=3, amount=1 + k)]),
        )
        assert reply == b""
    bad = c.run_request(
        cl, types.Operation.create_transfers,
        pack([transfer(900, debit_account_id=1, credit_account_id=1,
                       amount=1)]),
    )
    assert bad != b""
    out = c.run_request(
        cl, types.Operation.lookup_accounts,
        np.array([1, 0, 2, 0, 3, 0], "<u8").tobytes(),
    )
    c.settle(4000)
    c.check_linearized()
    c.check_convergence()
    _assert_mirror(c)
    native_calls = sum(r._c_drain_native.value for r in c.replicas)
    return frames, out, native_calls


def test_drain_frames_bit_identical_on_off(monkeypatch):
    frames_on, table_on, native_on = _drained_run(monkeypatch, "1")
    frames_off, table_off, native_off = _drained_run(monkeypatch, "0")
    assert table_on == table_off
    assert len(frames_on) == len(frames_off)
    for a, b in zip(frames_on, frames_off):
        assert a == b
    kinds = {f[0] for f in frames_on}
    assert kinds == {"peer", "client"}
    # The on-arm really crossed into C per batch; the off-arm never did.
    assert native_on > 0
    assert native_off == 0


def test_drain_state_matches_per_message_delivery(monkeypatch):
    """Batched delivery is a transport regrouping, not a semantic
    change: the same script through the legacy per-message sim lands
    on the same account table."""
    _, table_batched, _ = _drained_run(monkeypatch, "1")
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "1")
    monkeypatch.setenv("TB_NATIVE_DRAIN", "1")
    monkeypatch.setattr(time, "perf_counter_ns", lambda: 1_000_000_000)
    monkeypatch.setattr(
        storage_mod.MemoryStorage, "supports_deferred_sync", True,
        raising=False,
    )
    c = Cluster(3, seed=31)
    cl = _register(c, 100)
    _setup_accounts(c, cl, ids=(1, 2, 3))
    for k in range(12):
        reply = c.run_request(
            cl, types.Operation.create_transfers,
            pack([transfer(500 + k, debit_account_id=1 + (k % 2),
                           credit_account_id=3, amount=1 + k)]),
        )
        assert reply == b""
    bad = c.run_request(
        cl, types.Operation.create_transfers,
        pack([transfer(900, debit_account_id=1, credit_account_id=1,
                       amount=1)]),
    )
    assert bad != b""
    out = c.run_request(
        cl, types.Operation.lookup_accounts,
        np.array([1, 0, 2, 0, 3, 0], "<u8").tobytes(),
    )
    c.settle(4000)
    c.check_convergence()
    assert out == table_batched


def test_prefix_split_accepts_fresh_frames_past_a_stale_duplicate(
    monkeypatch,
):
    """A retransmitted (stale-duplicate) prepare glued to the end of a
    drain run must NOT demote the fresh frames ahead of it: the
    eligible prefix still takes the one C call, only the duplicate
    walks per-message _on_prepare (which re-acks it).  Counters pin
    the split: native_calls > 0 and py_fallbacks counts EXACTLY the
    injected duplicates, never whole runs."""
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "1")
    monkeypatch.setenv("TB_NATIVE_DRAIN", "1")
    monkeypatch.setattr(
        storage_mod.MemoryStorage, "supports_deferred_sync", True,
        raising=False,
    )
    c = BatchCluster(3, seed=77)
    backup = next(r for r in c.replicas if not r.is_primary)
    orig = backup.on_prepares_batch
    injected = {"n": 0}

    def wrapped(headers, bodies):
        # Inject only into runs the eligibility scan would accept
        # whole (steady-state shape), so the expected split is exactly
        # prefix=run, suffix=[duplicate].
        inject = (
            len(headers) > 0
            and backup.status == "normal"
            and not backup.is_primary
            and not backup._stash
            and wire.u128(headers[0], "parent") == backup.parent_checksum
            and all(int(h["view"]) == backup.view for h in headers)
            and [int(h["op"]) for h in headers]
            == list(range(backup.op + 1, backup.op + 1 + len(headers)))
            and all(
                wire.u128(b, "parent") == wire.u128(a, "checksum")
                for a, b in zip(headers, headers[1:])
            )
        )
        if inject:
            headers = list(headers) + [headers[0].copy()]
            bodies = [bytes(b) for b in bodies] + [bytes(bodies[0])]
        fb0 = backup._c_drain_fallback.value
        nat0 = backup._c_drain_native.value
        orig(headers, bodies)
        if inject:
            injected["n"] += 1
            # ONE native crossing for the fresh prefix, ONE per-item
            # fallback for the duplicate — never the whole run.
            assert backup._c_drain_native.value == nat0 + 1
            assert backup._c_drain_fallback.value == fb0 + 1

    backup.on_prepares_batch = wrapped
    cl = _register(c, 100)
    _setup_accounts(c, cl, ids=(1, 2))
    for k in range(6):
        reply = c.run_request(
            cl, types.Operation.create_transfers,
            pack([transfer(700 + k, debit_account_id=1,
                           credit_account_id=2, amount=1)]),
        )
        assert reply == b""
    c.settle(4000)
    c.check_linearized()
    c.check_convergence()
    assert injected["n"] > 0
    assert backup._c_drain_native.value > 0


# ----------------------------------------------------------------------
# Chaos on the drain arm: the r10 group-commit contract and
# crash-at-fsync failover, with batched delivery.


def _instrument_ack_ordering_drained(c):
    """test_multi._instrument_ack_ordering extended to see the drain's
    WAL entry point: write_prepare_framed is always an UNSYNCED write
    (deferred-sync only), so it must register in wseq without moving
    the synced watermark."""
    violations = []
    for r, st in zip(c.replicas, c.storages):
        state = {"seq": 0, "synced": 0, "wseq": {}}

        orig_write = r.journal.write_prepare

        def write_prepare(header, body, sync=True, *, _s=state, _w=orig_write):
            _s["seq"] += 1
            _s["wseq"][int(header["op"])] = _s["seq"]
            _w(header, body, sync=sync)
            if sync:
                _s["synced"] = _s["seq"]

        r.journal.write_prepare = write_prepare

        orig_framed = r.journal.write_prepare_framed

        def write_prepare_framed(header, body_len, wal_view, slot,
                                 sector_view, sector_index, *, _s=state,
                                 _w=orig_framed):
            _s["seq"] += 1
            _s["wseq"][int(header["op"])] = _s["seq"]
            _w(header, body_len, wal_view, slot, sector_view, sector_index)

        r.journal.write_prepare_framed = write_prepare_framed

        orig_sync = st.sync

        def sync(*, _s=state, _o=orig_sync):
            _o()  # raises (FsyncCrash) before anything counts as synced
            _s["synced"] = _s["seq"]

        st.sync = sync

        orig_send = r.bus.send

        def send(dst, header, body, *, _s=state, _r=r, _o=orig_send):
            cmd = int(header["command"])
            if cmd == int(Command.prepare_ok):
                w = _s["wseq"].get(int(header["op"]))
                if w is not None and w > _s["synced"]:
                    violations.append(
                        ("prepare_ok", _r.replica, int(header["op"]))
                    )
            if cmd in (int(Command.prepare), int(Command.commit)):
                commit = int(header["commit"])
                w = _s["wseq"].get(commit)
                if w is not None and w > _s["synced"]:
                    violations.append(("commit_leak", _r.replica, commit))
            _o(dst, header, body)

        r.bus.send = send

        orig_send_client = r.bus.send_client

        def send_client(client, header, body, *, _s=state, _r=r,
                        _o=orig_send_client):
            if int(header["command"]) == int(Command.reply):
                w = _s["wseq"].get(int(header["op"]))
                if w is not None and w > _s["synced"]:
                    violations.append(
                        ("reply", _r.replica, int(header["op"]))
                    )
            _o(client, header, body)

        r.bus.send_client = send_client
    return violations


@pytest.fixture
def drained_gc_cluster(monkeypatch):
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "1")
    monkeypatch.setenv("TB_NATIVE_DRAIN", "1")
    monkeypatch.setattr(
        storage_mod.MemoryStorage, "supports_deferred_sync", True,
        raising=False,
    )
    c = BatchCluster(3, seed=11)
    for r in c.replicas:
        assert r._gc_enabled and r._drain_native
    return c


def test_gc_contract_never_acks_before_covering_sync_drained(
    drained_gc_cluster,
):
    """The r10 self-vote-gated-on-covering-sync contract re-driven
    with the C drain journaling whole runs through framed writes."""
    c = drained_gc_cluster
    violations = _instrument_ack_ordering_drained(c)
    cl = _register(c, 100)
    _setup_accounts(c, cl)
    others = [_register(c, 101 + k) for k in range(3)]

    def drive(client, base):
        sent = {"n": 0}

        def step_one():
            if client.busy():
                return False
            if sent["n"] >= 8:
                return True
            sent["n"] += 1
            client.request(
                types.Operation.create_transfers,
                pack([
                    transfer(base + sent["n"], debit_account_id=1,
                             credit_account_id=2, amount=1)
                ]),
            )
            return False

        return step_one

    steppers = [drive(cl, 1000)] + [
        drive(o, 2000 + 100 * k) for k, o in enumerate(others)
    ]
    for _ in range(4000):
        if all(s() for s in steppers):
            break
        c.step()
    c.settle()
    c.check_convergence()
    assert violations == [], violations[:10]
    assert sum(r._c_drain_native.value for r in c.replicas) > 0


@pytest.mark.parametrize("seed", [3, 19, 47])
def test_crash_at_fsync_failover_fuzz_drained(monkeypatch, seed):
    """Primary dies inside a covering fsync at a fuzzed point; with
    batched delivery + the C drain deciding commits, failover must
    lose nothing acked and the hash logs must converge."""
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "1")
    monkeypatch.setenv("TB_NATIVE_DRAIN", "1")
    monkeypatch.setattr(
        storage_mod.MemoryStorage, "supports_deferred_sync", True,
        raising=False,
    )
    rng = np.random.default_rng(seed)
    c = BatchCluster(3, seed=seed)
    violations = _instrument_ack_ordering_drained(c)
    cl = _register(c, 100)
    _setup_accounts(c, cl)
    acked = 0
    next_id = [seed * 1000]

    def send_next():
        next_id[0] += 1
        cl.request(
            types.Operation.create_transfers,
            pack([transfer(next_id[0], debit_account_id=1,
                           credit_account_id=2, amount=1)]),
        )

    for _ in range(int(rng.integers(2, 6))):
        send_next()
        c.run_until(lambda: not cl.busy())
        assert cl.reply == b""
        acked += 1

    c.storages[0].crash_at_fsync = int(rng.integers(1, 4))
    send_next()
    crashed = False
    for _ in range(600):
        try:
            c.step()
        except FsyncCrash:
            crashed = True
            c.crash_replica(0)
            break
        if not cl.busy():
            acked += 1
            send_next()
    assert crashed, "seeded crash_at_fsync never fired"

    c.run_until(lambda: not cl.busy(), 6000)
    acked += 1
    c.restart_replica(0)
    c.settle(6000)
    c.check_linearized()
    c.check_convergence()
    assert violations == [], violations[:10]
    _assert_mirror(c)

    out = c.run_request(cl, types.Operation.lookup_accounts, ids_bytes([1]))
    row = np.frombuffer(out, types.ACCOUNT_DTYPE)[0]
    assert types.u128_get(row, "debits_posted") == acked


# ----------------------------------------------------------------------
# Regression: a retransmission of an already-committed request, landing
# MID-DRAIN between fresh requests under admission pressure, must get
# its stored reply — never a busy (shedding ahead of the at-most-once
# gate had exactly that bug).


def test_retransmit_of_committed_mid_drain_gets_stored_reply(monkeypatch):
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "1")
    monkeypatch.setenv("TB_NATIVE_DRAIN", "1")
    c = Cluster(3, seed=5)
    cl = _register(c, 100)
    _setup_accounts(c, cl)
    cl2 = _register(c, 200)
    cl.request(
        types.Operation.create_transfers,
        pack([transfer(77, debit_account_id=1, credit_account_id=2,
                       amount=1)]),
    )
    retrans_h = cl._inflight[0].copy()
    retrans_b = cl._inflight[1]
    c.run_until(lambda: not cl.busy())
    assert cl.reply == b""
    c.settle()
    primary = c.replicas[0]
    assert primary.is_primary

    sent: list = []
    orig = primary.bus.send_client

    def send_client(client, header, body):
        sent.append((client, header.copy(), bytes(body)))
        orig(client, header, body)

    primary.bus.send_client = send_client
    # Admission bound 0: every FRESH request in the drain sheds.
    primary.admit_queue = 0

    def fresh(n):
        h = wire.make_header(
            command=Command.request,
            operation=types.Operation.create_transfers,
            cluster=c.cluster_id, client=cl2.id,
            request=cl2.request_number + n,
        )
        body = pack([transfer(800 + n, debit_account_id=1,
                              credit_account_id=2, amount=1)])
        wire.finalize_header(h, body)
        return h, body

    f1, b1 = fresh(1)
    f2, b2 = fresh(2)
    primary.on_requests_batch(
        [f1, retrans_h, f2], [b1, retrans_b, b2]
    )
    primary.flush_group_commit()
    to_cl = [
        (int(h["command"]), int(h["request"]))
        for client, h, _ in sent if client == cl.id
    ]
    assert (int(Command.reply), int(retrans_h["request"])) in to_cl
    assert int(Command.client_busy) not in [cmd for cmd, _ in to_cl]
    # The fresh traffic around it really was under pressure: shed with
    # typed busies, not silently dropped.
    to_cl2 = [int(h["command"]) for client, h, _ in sent if client == cl2.id]
    assert to_cl2.count(int(Command.client_busy)) == 2


# ----------------------------------------------------------------------
# Stale-.so forensics extended to the batch symbols (r20's contract):
# explicit TB_NATIVE_DRAIN=1 against a stale library fails fast with
# the rebuild hint; the defaulted knob degrades to the per-item arm.


def test_stale_library_fails_fast_on_explicit_drain_opt_in(monkeypatch):
    monkeypatch.setattr(fastpath, "_load", lambda: _StaleLib())
    monkeypatch.setattr(fastpath, "_pipeline_warned", False)
    monkeypatch.delenv("TB_NATIVE_PIPELINE", raising=False)
    monkeypatch.setenv("TB_NATIVE_DRAIN", "1")
    assert not fastpath.drain_available()
    assert "make -C native" in fastpath.drain_error()
    with pytest.warns(RuntimeWarning, match="falling back"):
        with pytest.raises(RuntimeError, match="make -C native"):
            Cluster(3, seed=1)


def test_stale_library_degrades_when_drain_knob_defaulted(monkeypatch):
    monkeypatch.setattr(fastpath, "_load", lambda: _StaleLib())
    monkeypatch.setattr(fastpath, "_pipeline_warned", False)
    monkeypatch.delenv("TB_NATIVE_PIPELINE", raising=False)
    monkeypatch.delenv("TB_NATIVE_DRAIN", raising=False)
    with pytest.warns(RuntimeWarning, match="falling back"):
        c = Cluster(3, seed=1)
    for r in c.replicas:
        assert not r._drain_native and r._np is None
