"""Multi-chip sharded apply vs a numpy oracle (8-device CPU mesh)."""

import numpy as np
import pytest

import jax

from tigerbeetle_tpu.parallel import sharded

U64 = np.uint64
U128 = 1 << 128


def _oracle(balances, dr, cr, amt, pend):
    """Row-granularity admission + apply, mirroring the sharded step."""
    rows = balances.shape[0]
    sums = np.zeros((rows, 4), object)
    for i in range(len(dr)):
        col_d = 0 if pend[i] else 1
        col_c = 2 if pend[i] else 3
        sums[dr[i], col_d] += int(amt[i])
        sums[cr[i], col_c] += int(amt[i])
    old = np.zeros((rows, 4), object)
    for c in range(4):
        old[:, c] = [
            int(balances[r, 2 * c]) | (int(balances[r, 2 * c + 1]) << 64)
            for r in range(rows)
        ]
    row_over = np.array(
        [any(old[r, c] + sums[r, c] >= U128 for c in range(4)) for r in range(rows)]
    )
    admitted = ~(row_over[dr] | row_over[cr])
    new = old.copy()
    for i in np.flatnonzero(admitted):
        col_d = 0 if pend[i] else 1
        col_c = 2 if pend[i] else 3
        new[dr[i], col_d] += int(amt[i])
        new[cr[i], col_c] += int(amt[i])
    out = np.zeros_like(balances)
    for c in range(4):
        out[:, 2 * c] = [v & ((1 << 64) - 1) for v in new[:, c]]
        out[:, 2 * c + 1] = [(v >> 64) & ((1 << 64) - 1) for v in new[:, c]]
    return out, admitted


@pytest.mark.parametrize("dp", [1, 2])
def test_sharded_apply_matches_oracle(dp):
    devices = jax.devices()
    assert len(devices) >= 8
    mesh = sharded.make_mesh(devices[:8], dp=dp)
    n_shard = mesh.shape["shard"]
    rows = 4 * n_shard
    n_events = 8 * dp

    rng = np.random.default_rng(7)
    balances = np.zeros((rows, 8), U64)
    # Pre-load one row near the u128 ceiling so admission triggers.
    balances[3, 2] = U64(0xFFFFFFFFFFFFFFFF)
    balances[3, 3] = U64(0xFFFFFFFFFFFFFFFF)

    dr = rng.integers(0, rows, n_events).astype(np.int32)
    cr = ((dr + rng.integers(1, rows, n_events)) % rows).astype(np.int32)
    amt = rng.integers(1, 1000, n_events).astype(U64)
    pend = rng.random(n_events) < 0.3

    step = sharded.build_apply_step(mesh, rows)
    out, admitted = step(
        sharded.shard_balances(mesh, balances),
        *sharded.shard_events(mesh, dr, cr, amt, np.zeros(n_events, U64), pend),
    )

    expect, expect_admitted = _oracle(balances, dr, cr, amt, pend)
    np.testing.assert_array_equal(np.asarray(admitted), expect_admitted)
    np.testing.assert_array_equal(np.asarray(out), expect)


def test_sharded_rejects_out_of_range_slots():
    mesh = sharded.make_mesh(jax.devices()[:8], dp=2)
    rows = 4 * mesh.shape["shard"]
    n_events = 4

    balances = np.zeros((rows, 8), U64)
    dr = np.array([0, rows, -1, 2], np.int32)  # events 1 and 2 out of range
    cr = np.array([1, 1, 1, 3], np.int32)
    amt = np.full(n_events, 10, U64)

    step = sharded.build_apply_step(mesh, rows)
    out, admitted = step(
        sharded.shard_balances(mesh, balances),
        *sharded.shard_events(
            mesh, dr, cr, amt, np.zeros(n_events, U64), np.zeros(n_events, bool)
        ),
    )
    np.testing.assert_array_equal(np.asarray(admitted), [True, False, False, True])
    expect = np.zeros((rows, 8), U64)
    expect[0, 2] = expect[2, 2] = 10  # debits_posted
    expect[1, 6] = expect[3, 6] = 10  # credits_posted
    np.testing.assert_array_equal(np.asarray(out), expect)


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    balances, packed = jax.jit(fn)(*args)
    jax.block_until_ready((balances, packed))
    from tigerbeetle_tpu.state_machine import kernel

    out = kernel.unpack_outputs(np.asarray(packed))
    assert (out["results"][:8] == 0).all()


def test_graft_entry_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_device_table_shards_across_mesh():
    """The production write-behind table shards row-wise over every
    available device (conftest forces an 8-device CPU mesh), and the
    state machine stays bit-identical to the CPU oracle through the
    sharded flush path."""
    import jax

    from tigerbeetle_tpu.state_machine import CpuStateMachine
    from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
    from tigerbeetle_tpu.testing.harness import (
        SingleNodeHarness,
        account,
        transfer,
    )

    n_dev = len(jax.devices())
    assert n_dev == 8, "conftest should force the virtual mesh"
    t = TpuStateMachine(account_capacity=1 << 10)
    assert t._dev.sharding is not None
    assert len(t._dev.balances.sharding.device_set) == n_dev

    ht = SingleNodeHarness(t)
    hc = SingleNodeHarness(CpuStateMachine())
    accounts = [account(i, ledger=1, code=1) for i in range(1, 33)]
    transfers = [
        transfer(100 + k, debit_account_id=1 + (k % 31),
                 credit_account_id=2 + ((k + 7) % 31), amount=3 + k,
                 ledger=1, code=1)
        for k in range(64)
    ]
    assert ht.create_accounts(accounts) == hc.create_accounts(accounts)
    assert ht.create_transfers(transfers) == hc.create_transfers(transfers)
    for row_t, row_c in zip(ht.lookup_accounts(range(1, 33)),
                            hc.lookup_accounts(range(1, 33))):
        assert row_t.tobytes() == row_c.tobytes()
    # The flush landed on the sharded table (not silently re-replicated
    # — a replicated array also spans all devices, so check the spec).
    assert not t._dev.balances.sharding.is_fully_replicated
