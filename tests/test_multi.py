"""WAL group commit (deferred-sync mode, vsr/multi.py): the
durability-before-ack contract, fsync batching, the backup
double-fsync regression, and crash-at-fsync chaos.

Group commit is forced onto the deterministic MemoryStorage clusters
here (production gating keys off storage.supports_deferred_sync,
which the fault-injecting backend leaves False so every other seeded
test keeps the synchronous path)."""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.harness import account, ids_bytes, pack, transfer
from tigerbeetle_tpu.vsr import storage as storage_mod
from tigerbeetle_tpu.vsr.storage import FsyncCrash
from tigerbeetle_tpu.vsr.wire import Command


@pytest.fixture
def gc_cluster(monkeypatch):
    monkeypatch.setattr(
        storage_mod.MemoryStorage, "supports_deferred_sync", True,
        raising=False,
    )
    c = Cluster(3, seed=11)
    for r in c.replicas:
        assert r._gc_enabled
    return c


def _register(c, client_id):
    cl = c.client(client_id)
    cl.register()
    c.run_until(lambda: cl.registered)
    return cl


def _setup_accounts(c, cl, ids=(1, 2)):
    reply = c.run_request(
        cl, types.Operation.create_accounts, pack([account(i) for i in ids])
    )
    assert reply == b""


def _instrument_ack_ordering(c):
    """Record a violation whenever a prepare_ok or client reply for op
    N leaves a replica whose WAL write for N is not yet covered by a
    completed sync — the exact contract group commit must not weaken."""
    violations = []
    for r, st in zip(c.replicas, c.storages):
        state = {"seq": 0, "synced": 0, "wseq": {}}

        orig_write = r.journal.write_prepare

        def write_prepare(header, body, sync=True, *, _s=state, _w=orig_write):
            _s["seq"] += 1
            _s["wseq"][int(header["op"])] = _s["seq"]
            _w(header, body, sync=sync)
            if sync:
                _s["synced"] = _s["seq"]

        r.journal.write_prepare = write_prepare

        orig_sync = st.sync

        def sync(*, _s=state, _o=orig_sync):
            _o()  # raises (FsyncCrash) before anything counts as synced
            _s["synced"] = _s["seq"]

        st.sync = sync

        orig_send = r.bus.send

        def send(dst, header, body, *, _s=state, _r=r, _o=orig_send):
            cmd = int(header["command"])
            if cmd == int(Command.prepare_ok):
                w = _s["wseq"].get(int(header["op"]))
                if w is not None and w > _s["synced"]:
                    violations.append(("prepare_ok", _r.replica, int(header["op"])))
            if cmd in (int(Command.prepare), int(Command.commit)):
                # A commit number riding heartbeats / prepare headers
                # is an ack too: the sender vouches the op is quorum
                # -durable, which includes its OWN copy — its covering
                # sync must have completed (the self-vote leak class).
                commit = int(header["commit"])
                w = _s["wseq"].get(commit)
                if w is not None and w > _s["synced"]:
                    violations.append(("commit_leak", _r.replica, commit))
            _o(dst, header, body)

        r.bus.send = send

        orig_send_client = r.bus.send_client

        def send_client(client, header, body, *, _s=state, _r=r,
                        _o=orig_send_client):
            if int(header["command"]) == int(Command.reply):
                w = _s["wseq"].get(int(header["op"]))
                if w is not None and w > _s["synced"]:
                    violations.append(("reply", _r.replica, int(header["op"])))
            _o(client, header, body)

        r.bus.send_client = send_client
    return violations


def test_group_commit_never_acks_before_covering_sync(gc_cluster):
    c = gc_cluster
    violations = _instrument_ack_ordering(c)
    cl = _register(c, 100)
    _setup_accounts(c, cl)
    others = [_register(c, 101 + k) for k in range(3)]
    done = [0]

    def drive(client, base):
        sent = {"n": 0}

        def step_one():
            if client.busy():
                return False
            if sent["n"] >= 8:
                return True
            sent["n"] += 1
            client.request(
                types.Operation.create_transfers,
                pack([
                    transfer(base + sent["n"], debit_account_id=1,
                             credit_account_id=2, amount=1)
                ]),
            )
            return False

        return step_one

    steppers = [drive(cl, 1000)] + [
        drive(o, 2000 + 100 * k) for k, o in enumerate(others)
    ]
    for _ in range(4000):
        if all(s() for s in steppers):
            break
        c.step()
    c.settle()
    c.check_convergence()
    assert violations == [], violations[:10]


def test_group_commit_batches_fsyncs_under_pipelined_load(gc_cluster):
    """Concurrent sessions fill the prepare pipeline; a backup's one
    flush per step then covers several prepares — strictly fewer
    fsyncs than prepares (the replicated bench grades the same ratio
    from real server logs)."""
    c = gc_cluster
    cl = _register(c, 100)
    _setup_accounts(c, cl)
    sessions = [_register(c, 101 + k) for k in range(4)]
    fsyncs0 = [st.stat_fsyncs for st in c.storages]
    prepares0 = [r.stat_prepares_written for r in c.replicas]
    pending = []
    next_id = [1000]
    for _ in range(1200):
        for s in sessions:
            if not s.busy():
                next_id[0] += 1
                s.request(
                    types.Operation.create_transfers,
                    pack([
                        transfer(next_id[0], debit_account_id=1,
                                 credit_account_id=2, amount=1)
                    ]),
                )
        c.step()
        if next_id[0] >= 1080:
            break
    c.settle()
    c.check_convergence()
    # The backup (replica 1 or 2) journals a whole delivered batch per
    # step and flushes once: covered prepares > flushes.
    gains = [
        (r.stat_prepares_written - p0) - (st.stat_fsyncs - f0)
        for r, st, p0, f0 in zip(
            c.replicas, c.storages, prepares0, fsyncs0
        )
    ]
    assert any(g > 0 for g in gains), (
        "no replica ever covered >1 prepare per fsync", gains
    )


def test_scrub_repair_costs_one_covering_sync(gc_cluster):
    """The backup double-cost regression: a scrub-repaired prepare
    (prepare-ring write + redundant-header coverage) folds into ONE
    covering sync in deferred-sync mode — it used to pay an fdatasync
    for the WAL write and another for the header-sector rewrite."""
    c = gc_cluster
    cl = _register(c, 100)
    _setup_accounts(c, cl)
    for i in range(5):
        reply = c.run_request(
            cl, types.Operation.create_transfers,
            pack([transfer(10 + i, debit_account_id=1,
                           credit_account_id=2, amount=1)]),
        )
        assert reply == b""
    c.settle()
    r = c.replicas[1]
    st = c.storages[1]
    op = r.commit_min - 1
    slot = r.journal.slot_for_op(op)
    st.corrupt_sector(st.layout.prepare_slot_offset(slot))
    assert r.journal.read_prepare(op) is None
    before = st.stat_fsyncs
    r._wal_scrub_probe(op)
    c.run_until(lambda: r.journal.read_prepare(op) is not None, 200)
    c.step()  # final flush point
    assert st.stat_fsyncs - before == 1, (
        "repair must cost exactly one covering sync",
        st.stat_fsyncs - before,
    )


def test_scrub_header_heal_rides_covering_sync(gc_cluster):
    """Header-ring damage self-heals from memory; in deferred-sync
    mode the rewrite rides the next covering flush (and flushes the
    WAL file only — never the grid)."""
    c = gc_cluster
    cl = _register(c, 100)
    _setup_accounts(c, cl)
    reply = c.run_request(
        cl, types.Operation.create_transfers,
        pack([transfer(10, debit_account_id=1, credit_account_id=2,
                       amount=1)]),
    )
    assert reply == b""
    c.settle()
    r = c.replicas[2]
    st = c.storages[2]
    op = r.commit_min
    slot = r.journal.slot_for_op(op)
    # Damage ONLY the redundant header sector (prepare intact).
    sector = st.layout.wal_headers_offset + (
        slot // 16 * storage_mod.SECTOR_SIZE
    )
    st.corrupt_sector(sector)
    assert r.journal.read_prepare(op) is not None
    assert not r.journal.header_sector_intact(slot)
    before = st.stat_fsyncs
    r._wal_scrub_probe(op)
    assert r.journal.header_sector_intact(slot)
    c.step()  # covering flush
    assert st.stat_fsyncs - before == 1
    assert r.journal.unsynced_writes == 0


def test_crash_at_fsync_no_acked_op_lost(gc_cluster):
    """Chaos: the primary dies INSIDE a covering fsync.  Nothing that
    sync would have covered was acked (the flush held the sends), so
    after failover + recovery every reply any client ever observed
    must be durable cluster-wide."""
    c = gc_cluster
    violations = _instrument_ack_ordering(c)
    cl = _register(c, 100)
    _setup_accounts(c, cl)
    acked_ids = []
    next_id = [100]

    def send_next():
        next_id[0] += 1
        cl.request(
            types.Operation.create_transfers,
            pack([transfer(next_id[0], debit_account_id=1,
                           credit_account_id=2, amount=1)]),
        )

    for _ in range(6):
        send_next()
        c.run_until(lambda: not cl.busy())
        assert cl.reply == b""
        acked_ids.append(next_id[0])

    # Arm the fault: the primary's 2nd sync from now never completes.
    c.storages[0].crash_at_fsync = 2
    send_next()
    crashed = False
    for _ in range(400):
        try:
            c.step()
        except FsyncCrash:
            crashed = True
            c.crash_replica(0)
            break
        if not cl.busy():
            acked_ids.append(next_id[0])
            send_next()
    assert crashed, "seeded crash_at_fsync never fired"

    # Failover: the remaining replicas elect a new primary; the client
    # retransmits the in-flight request and eventually gets its reply.
    c.run_until(lambda: not cl.busy(), 4000)
    acked_ids.append(next_id[0])
    c.restart_replica(0)
    c.settle(6000)
    c.check_linearized()
    c.check_convergence()
    assert violations == [], violations[:10]

    # Every acked transfer survives: balance == number of acked ops.
    out = c.run_request(cl, types.Operation.lookup_accounts, ids_bytes([1]))
    row = np.frombuffer(out, types.ACCOUNT_DTYPE)[0]
    assert types.u128_get(row, "debits_posted") == len(acked_ids)
