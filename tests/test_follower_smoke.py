"""Tier-1 follower TCP smoke: a real 2-replica cluster + 1 follower +
the read-steering router, end to end over the native bus.

The wire-level half of the follower contract (the state-machine half
lives in tests/test_follower.py's deterministic sim):

- an UNATTESTED follower refuses typed and the router transparently
  re-drives the read on the primary path (reads never fail),
- an attested follower serves reads whose replies carry a verifiable
  (root, commit_min) attestation — checked here against the primary's
  root ring via the scrape_state_root at-op query,
- follower replies are byte-identical to the primary's for the same
  data,
- kill -9 of the follower redirects reads to the primary,
- TB_READ_POLICY=primary pins the legacy path end to end (zero
  follower reads, identical bodies).
"""

import threading
import time

import numpy as np
import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.runtime.native import native_available
from tigerbeetle_tpu.state_machine import CpuStateMachine

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native runtime not built"
)

CLUSTER = 9


class _Loop:
    """Background poll loop for any server with poll_once/close."""

    def __init__(self, server):
        self.server = server
        self._stop = False
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        while not self._stop:
            self.server.poll_once(timeout_ms=1)

    def close(self):
        self._stop = True
        self.thread.join(timeout=5)
        self.server.close()


def _ids_body(ids):
    arr = np.zeros(len(ids), types.U128_PAIR_DTYPE)
    for i, v in enumerate(ids):
        arr[i]["lo"] = v
    return arr.tobytes()


def _read_once(session, body, timeout_s=20.0):
    """One lookup_accounts round trip through an OpenLoopSession;
    returns the completion tuple."""
    req = session.submit(types.Operation.lookup_accounts, body)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        session.poll(20)
        done = [c for c in session.completed if c[0] == req]
        if done:
            return done[0]
    raise TimeoutError("read did not complete")


def test_follower_smoke(tmp_path, monkeypatch):
    from tigerbeetle_tpu.client import Client, OpenLoopSession
    from tigerbeetle_tpu.obs.scrape import scrape_state_root, scrape_stats
    from tigerbeetle_tpu.runtime.follower import FollowerServer
    from tigerbeetle_tpu.runtime.router import RouterServer
    from tigerbeetle_tpu.runtime.server import (
        ReplicaServer,
        format_data_file,
    )

    # Phase control: the first attestation is manually released so the
    # unattested-refusal -> primary-fallback path is deterministic.
    monkeypatch.setenv("TB_FOLLOWER_ATTEST_MS", "60000")
    aof_path = str(tmp_path / "r0.aof")
    paths = [str(tmp_path / f"r{i}.tb") for i in range(2)]
    for i in range(2):
        format_data_file(paths[i], cluster=CLUSTER, replica_index=i,
                         replica_count=2, config=cfg.TEST_MIN)
    loops = []
    clients = []
    try:
        # Replica addresses are bound by the servers themselves
        # (port 0), so start replicas first, then everyone else.
        replicas = []
        addresses = ["127.0.0.1:0", "127.0.0.1:0"]
        servers = []
        for i in range(2):
            srv = ReplicaServer(
                paths[i], cluster=CLUSTER, addresses=addresses,
                replica_index=i,
                state_machine_factory=lambda: CpuStateMachine(cfg.TEST_MIN),
                config=cfg.TEST_MIN,
                aof_path=aof_path if i == 0 else None,
            )
            addresses[i] = f"127.0.0.1:{srv.port}"
            srv.bus.addresses = addresses  # rewritten with real ports
            servers.append(srv)
        for srv in servers:
            loops.append(_Loop(srv))
            replicas.append(srv)
        assert replicas[0].replica.root_ring is not None  # TB_ROOT_RING

        fsrv = FollowerServer(
            "127.0.0.1:0", aof_path=aof_path,
            upstream_address=addresses[0], cluster=CLUSTER,
            state_machine=CpuStateMachine(cfg.TEST_MIN),
            clock_ns=time.monotonic_ns, follower_id=3,
        )
        f_addr = f"127.0.0.1:{fsrv.port}"
        f_loop = _Loop(fsrv)
        loops.append(f_loop)

        router = RouterServer(
            "127.0.0.1:0", [",".join(addresses)], cluster=CLUSTER,
            recover=False, follower_addresses=[f"0:{f_addr}"],
        )
        assert router.read_policy == "follower"  # auto + followers
        loops.append(_Loop(router))
        r_addr = f"127.0.0.1:{router.port}"

        # Seed data THROUGH the router (it is the client surface).
        setup = Client(r_addr, CLUSTER, client_id=77, timeout_ms=60_000)
        clients.append(setup)
        assert setup.create_accounts(
            [{"id": 1, "ledger": 1, "code": 1},
             {"id": 2, "ledger": 1, "code": 1}]
        ) == []
        assert setup.create_transfers(
            [{"id": 5, "debit_account_id": 1, "credit_account_id": 2,
              "amount": 11, "ledger": 1, "code": 1}]
        ) == []

        session = OpenLoopSession(r_addr, CLUSTER, 0xF00D)
        body = _ids_body([1, 2])

        # -- Phase A: unattested follower -> typed refusal -> the
        # router re-drives on the primary; the client still gets its
        # answer (reads never fail because a follower can't serve).
        comp = _read_once(session, body)
        assert comp[1] == "reply"
        primary_body = comp[3]
        rows = np.frombuffer(primary_body, types.ACCOUNT_DTYPE)
        assert types.u128_get(rows[0], "debits_posted") == 11
        assert comp[5][0] == "primary"
        rsnap = scrape_stats(r_addr, CLUSTER, timeout_ms=20_000)
        assert rsnap["router.follower_reads"] >= 1
        assert rsnap["router.follower_redirects"] >= 1
        fsnap = scrape_stats(f_addr, CLUSTER, timeout_ms=20_000)
        assert fsnap["follower.refused"] >= 1
        assert fsnap["follower.attested_op"] == 0

        # -- Phase B: release attestation; the follower catches up,
        # verifies its root against the upstream ring, and serves.
        fsrv._attest_ns = 50_000_000  # 50 ms cadence from here on
        deadline = time.monotonic() + 30.0
        comp = None
        while time.monotonic() < deadline:
            comp = _read_once(session, body)
            if comp[1] == "reply" and comp[5][0] == "follower":
                break
            time.sleep(0.2)
        assert comp is not None and comp[5][0] == "follower", comp
        tier, server_id, commit_min, root = comp[5]
        assert server_id == 3 and commit_min > 0 and len(root) == 16
        # Reply body bit-identical to the primary-served phase-A body.
        assert comp[3] == primary_body
        # Attestation verifiable against the PRIMARY's root ring: the
        # at-op scrape must return the identical root at the claimed
        # commit_min (the client-side verification story).
        proot, pop = scrape_state_root(
            addresses[0], CLUSTER, timeout_ms=20_000, at_op=commit_min
        )
        assert pop == commit_min, "primary no longer retains the op"
        assert proot == root, "follower attestation mismatch"
        # The follower's own state_root query agrees.
        froot, fop = scrape_state_root(f_addr, CLUSTER,
                                       timeout_ms=20_000)
        assert fop >= commit_min and froot != bytes(16)

        # -- Phase C: kill -9 the follower; reads redirect to the
        # primary and keep succeeding.
        f_loop.close()
        loops.remove(f_loop)
        for _ in range(3):
            comp = _read_once(session, body)
            assert comp[1] == "reply"
            assert comp[3] == primary_body
        assert comp[5][0] == "primary"

        # -- Phase D: TB_READ_POLICY=primary pins the legacy path even
        # with followers configured.
        monkeypatch.setenv("TB_READ_POLICY", "primary")
        router2 = RouterServer(
            "127.0.0.1:0", [",".join(addresses)], cluster=CLUSTER,
            recover=False, follower_addresses=[f"0:{f_addr}"],
        )
        assert router2.read_policy == "primary"
        loops.append(_Loop(router2))
        session2 = OpenLoopSession(
            f"127.0.0.1:{router2.port}", CLUSTER, 0xF00E
        )
        comp = _read_once(session2, body)
        assert comp[1] == "reply" and comp[5][0] == "primary"
        assert comp[3] == primary_body
        r2snap = scrape_stats(f"127.0.0.1:{router2.port}", CLUSTER,
                              timeout_ms=20_000)
        assert r2snap["router.follower_reads"] == 0
        session2.close()
        session.close()
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        for loop in loops:
            loop.close()
