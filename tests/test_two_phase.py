"""Two-phase (pending -> post/void) and expiry semantics.

reference: src/state_machine.zig:1608-1804 (post/void),
:1874-1929 + :2018-2172 (expiry pulse).
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.testing.harness import SingleNodeHarness, account, transfer

CTR = types.CreateTransferResult
AF = types.AccountFlags
TF = types.TransferFlags
MAX = types.U128_MAX
POST = TF.post_pending_transfer
VOID = TF.void_pending_transfer


@pytest.fixture
def h(sm):
    h = SingleNodeHarness(sm)
    assert h.create_accounts([account(1), account(2)]) == []
    return h


def t(id, dr=1, cr=2, amount=10, **kw):
    return transfer(id, debit_account_id=dr, credit_account_id=cr, amount=amount, **kw)


def pend(h, id=100, amount=10, timeout=0):
    assert h.create_transfers([t(id, amount=amount, flags=TF.pending, timeout=timeout)]) == []


def balances(h, id):
    row = h.lookup_accounts([id])[0]
    return tuple(
        types.u128_get(row, f)
        for f in ("debits_pending", "debits_posted", "credits_pending", "credits_posted")
    )


def test_pending_then_post_full(h):
    pend(h)
    assert balances(h, 1) == (10, 0, 0, 0)
    assert balances(h, 2) == (0, 0, 10, 0)
    assert h.create_transfers([t(101, dr=0, cr=0, amount=0, pending_id=100, flags=POST)]) == []
    assert balances(h, 1) == (0, 10, 0, 0)
    assert balances(h, 2) == (0, 0, 0, 10)
    # The posting transfer inherits fields from the pending one.
    row = h.lookup_transfers([101])[0]
    assert types.u128_get(row, "amount") == 10
    assert types.u128_get(row, "debit_account_id") == 1
    assert types.u128_get(row, "pending_id") == 100


def test_pending_then_post_partial(h):
    pend(h)
    assert h.create_transfers([t(101, dr=0, cr=0, amount=7, pending_id=100, flags=POST)]) == []
    assert balances(h, 1) == (0, 7, 0, 0)
    assert balances(h, 2) == (0, 0, 0, 7)


def test_pending_then_void(h):
    pend(h)
    assert h.create_transfers([t(101, dr=0, cr=0, amount=0, pending_id=100, flags=VOID)]) == []
    assert balances(h, 1) == (0, 0, 0, 0)
    assert balances(h, 2) == (0, 0, 0, 0)


def test_flag_exclusions(h):
    pend(h)
    cases = [
        (t(101, pending_id=100, flags=POST | VOID), CTR.flags_are_mutually_exclusive),
        (t(101, pending_id=100, flags=POST | TF.pending), CTR.flags_are_mutually_exclusive),
        (t(101, pending_id=100, flags=POST | TF.balancing_debit), CTR.flags_are_mutually_exclusive),
        (t(101, pending_id=100, flags=POST | TF.balancing_credit), CTR.flags_are_mutually_exclusive),
        (t(101, pending_id=0, flags=POST), CTR.pending_id_must_not_be_zero),
        (t(101, pending_id=MAX, flags=POST), CTR.pending_id_must_not_be_int_max),
        (t(101, pending_id=101, flags=POST), CTR.pending_id_must_be_different),
        (t(101, pending_id=100, timeout=5, flags=POST), CTR.timeout_reserved_for_pending_transfer),
        (t(101, pending_id=999, flags=POST), CTR.pending_transfer_not_found),
    ]
    for row, expected in cases:
        assert h.create_transfers([row]) == [(0, expected)], expected


def test_pending_transfer_not_pending(h):
    assert h.create_transfers([t(100)]) == []  # plain posted transfer
    assert h.create_transfers([t(101, pending_id=100, flags=POST)]) == [
        (0, CTR.pending_transfer_not_pending)
    ]


def test_mismatch_ladder(h):
    assert h.create_accounts([account(3), account(4)]) == []
    pend(h)
    cases = [
        (t(101, dr=3, cr=0, amount=0, pending_id=100, flags=POST),
         CTR.pending_transfer_has_different_debit_account_id),
        (t(101, dr=0, cr=4, amount=0, pending_id=100, flags=POST),
         CTR.pending_transfer_has_different_credit_account_id),
        (transfer(101, pending_id=100, ledger=9, code=0, flags=POST),
         CTR.pending_transfer_has_different_ledger),
        (transfer(101, pending_id=100, ledger=0, code=9, flags=POST),
         CTR.pending_transfer_has_different_code),
        (t(101, dr=0, cr=0, amount=11, pending_id=100, flags=POST),
         CTR.exceeds_pending_transfer_amount),
        (t(101, dr=0, cr=0, amount=9, pending_id=100, flags=VOID),
         CTR.pending_transfer_has_different_amount),
    ]
    for row, expected in cases:
        assert h.create_transfers([row]) == [(0, expected)], expected


def test_already_posted_and_voided(h):
    pend(h, id=100)
    pend(h, id=200)
    assert h.create_transfers([t(101, dr=0, cr=0, amount=0, pending_id=100, flags=POST)]) == []
    assert h.create_transfers([t(102, dr=0, cr=0, amount=0, pending_id=100, flags=POST)]) == [
        (0, CTR.pending_transfer_already_posted)
    ]
    assert h.create_transfers([t(201, dr=0, cr=0, amount=0, pending_id=200, flags=VOID)]) == []
    assert h.create_transfers([t(202, dr=0, cr=0, amount=0, pending_id=200, flags=VOID)]) == [
        (0, CTR.pending_transfer_already_voided)
    ]


def test_post_exists_ladder(h):
    pend(h)
    post_row = t(101, dr=0, cr=0, amount=7, pending_id=100, flags=POST,
                 user_data_128=5)
    assert h.create_transfers([post_row]) == []
    cases = [
        # amount=0 passes the void-amount precondition (inherits 10),
        # reaching the exists ladder where the flags differ.
        (t(101, dr=0, cr=0, amount=0, pending_id=100, flags=VOID),
         CTR.exists_with_different_flags),
        (t(101, dr=0, cr=0, amount=6, pending_id=100, flags=POST),
         CTR.exists_with_different_amount),
        # amount=0 means "p.amount" (10) which != e.amount (7).
        (t(101, dr=0, cr=0, amount=0, pending_id=100, flags=POST),
         CTR.exists_with_different_amount),
        (t(101, dr=0, cr=0, amount=7, pending_id=100, flags=POST, user_data_128=9),
         CTR.exists_with_different_user_data_128),
        (t(101, dr=0, cr=0, amount=7, pending_id=100, flags=POST, user_data_128=5),
         CTR.exists),
    ]
    for row, expected in cases:
        assert h.create_transfers([row]) == [(0, expected)], expected


def test_exists_with_different_pending_id(h):
    pend(h, id=100)
    pend(h, id=200)
    assert h.create_transfers([t(101, dr=0, cr=0, amount=10, pending_id=100, flags=POST)]) == []
    assert h.create_transfers([t(101, dr=0, cr=0, amount=10, pending_id=200, flags=POST)]) == [
        (0, CTR.exists_with_different_pending_id)
    ]


def test_expiry_via_pulse(h):
    pend(h, id=100, amount=10, timeout=1)
    assert balances(h, 1) == (10, 0, 0, 0)
    sm = h.sm
    expires_at = sm.transfer_timestamp(100) + 10**9
    assert sm.pulse_next_timestamp == expires_at
    # Advance the wall clock past expiry; the harness injects a pulse.
    h.submit(types.Operation.lookup_accounts, b"", realtime=expires_at + 1)
    assert balances(h, 1) == (0, 0, 0, 0)
    assert balances(h, 2) == (0, 0, 0, 0)
    assert sm.pending_status(100) == types.TransferPendingStatus.expired
    # Posting after expiry fails.
    assert h.create_transfers([t(101, dr=0, cr=0, amount=0, pending_id=100, flags=POST)]) == [
        (0, CTR.pending_transfer_expired)
    ]


def test_post_overdue_pending_before_pulse(h):
    """A post racing an overdue expiry returns pending_transfer_expired.

    Reference quirk preserved: the posting transfer was already inserted
    when the overdue check fires (src/state_machine.zig:1687-1696).
    """
    pend(h, id=100, amount=10, timeout=1)
    sm = h.sm
    expires_at = sm.transfer_timestamp(100) + 10**9
    # Submit the post with the clock past expiry, bypassing the pulse:
    # call _run directly so tick_pulses doesn't fire first.
    h.realtime = expires_at + 10
    out = h._run(
        types.Operation.create_transfers,
        np.asarray(t(101, dr=0, cr=0, amount=0, pending_id=100, flags=POST)).tobytes(),
    )
    arr = np.frombuffer(out, dtype=types.CREATE_RESULT_DTYPE)
    assert [(int(r["index"]), CTR(int(r["result"]))) for r in arr] == [
        (0, CTR.pending_transfer_expired)
    ]
    # The quirk: transfer 101 leaked into the store. (Read state
    # directly — a lookup via the harness would inject the due pulse.)
    assert sm.transfer_timestamp(101) is not None
    dp, dpo, _, _ = sm.account_balances_raw(1)
    assert (dp, dpo) == (10, 0)


def test_expiry_pulse_next_timestamp_bookkeeping(h):
    sm = h.sm
    assert sm.pulse_next_timestamp == types.TIMESTAMP_MIN
    # First pulse (no pendings) parks the timestamp at max.
    h.tick_pulses()
    assert sm.pulse_next_timestamp == types.TIMESTAMP_MAX
    pend(h, id=100, timeout=5)
    pend(h, id=101, timeout=1)
    e100 = sm.transfer_timestamp(100) + 5 * 10**9
    e101 = sm.transfer_timestamp(101) + 10**9
    assert sm.pulse_next_timestamp == min(e100, e101) == e101
    # Void 101: pulse_next resets to min sentinel (it matched e101).
    assert h.create_transfers([t(102, dr=0, cr=0, amount=0, pending_id=101, flags=VOID)]) == []
    assert sm.pulse_next_timestamp == types.TIMESTAMP_MIN
    # Next pulse rescans: finds e100 as next expiry.
    h.tick_pulses()
    assert sm.pulse_next_timestamp == e100


def test_expired_pending_releases_only_pending_amounts(h):
    pend(h, id=100, amount=10, timeout=1)
    assert h.create_transfers([t(101, amount=3)]) == []
    sm = h.sm
    expires_at = sm.transfer_timestamp(100) + 10**9
    h.submit(types.Operation.lookup_accounts, b"", realtime=expires_at + 1)
    assert balances(h, 1) == (0, 3, 0, 0)
    assert balances(h, 2) == (0, 0, 0, 3)
