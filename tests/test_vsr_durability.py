"""Durability: wire headers, journal recovery, superblock quorum,
single-replica crash/restart round-trips."""

import numpy as np
import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.testing.harness import account, ids_bytes, pack, transfer
from tigerbeetle_tpu.vsr import replica as vsr_replica
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.journal import Journal
from tigerbeetle_tpu.vsr.storage import FileStorage, MemoryStorage, ZoneLayout
from tigerbeetle_tpu.vsr.superblock import SuperBlock

CLUSTER = 7


def layout():
    return ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 20)


def fresh_replica(storage=None, sm=None):
    storage = storage or MemoryStorage(layout())
    vsr_replica.format(storage, CLUSTER)
    r = vsr_replica.Replica(storage, CLUSTER, sm or CpuStateMachine(cfg.TEST_MIN))
    r.open()
    return storage, r


def reopen(storage):
    r = vsr_replica.Replica(storage, CLUSTER, CpuStateMachine(cfg.TEST_MIN))
    r.open()
    return r


# ----------------------------------------------------------------------
# Wire.


def test_header_roundtrip_and_checksum():
    h = wire.make_header(
        command=wire.Command.prepare, operation=types.Operation.create_transfers,
        cluster=CLUSTER, op=3, timestamp=99, parent=(1 << 100) + 5,
    )
    body = b"x" * 128
    wire.finalize_header(h, body)
    assert wire.verify_header(h, body)
    h2 = wire.header_from_bytes(h.tobytes())
    assert wire.verify_header(h2, body)
    assert wire.u128(h2, "parent") == (1 << 100) + 5
    # Any flipped byte must fail verification.
    raw = bytearray(h.tobytes())
    raw[40] ^= 0xFF
    assert not wire.verify_header(wire.header_from_bytes(bytes(raw)), body)
    assert not wire.verify_header(h, body + b"y")


def test_root_prepare_deterministic():
    a = vsr_replica.wire.root_prepare(5)
    b = vsr_replica.wire.root_prepare(5)
    assert a.tobytes() == b.tobytes()
    assert a["op"] == 0 and wire.verify_header(a, b"")


# ----------------------------------------------------------------------
# Journal.


def make_prepare(op, parent, body=b"", timestamp=None):
    h = wire.make_header(
        command=wire.Command.prepare, operation=types.Operation.create_accounts,
        cluster=CLUSTER, op=op, timestamp=timestamp or op * 10, parent=parent,
    )
    return wire.finalize_header(h, body)


def test_journal_write_read_recover():
    storage = MemoryStorage(layout())
    j = Journal(storage, CLUSTER)
    root = wire.root_prepare(CLUSTER)
    j.write_prepare(root, b"")
    parent = wire.u128(root, "checksum")
    for op in range(1, 6):
        h = make_prepare(op, parent, body=bytes([op]) * 100)
        j.write_prepare(h, bytes([op]) * 100)
        parent = wire.u128(h, "checksum")

    j2 = Journal(storage, CLUSTER)
    rec = j2.recover(commit_min=0)
    assert rec.op_head == 5
    assert not rec.faulty_ops and not rec.truncated_ops
    h, body = j2.read_prepare(3)
    assert body == b"\x03" * 100


def test_journal_torn_head_truncated():
    storage = MemoryStorage(layout())
    j = Journal(storage, CLUSTER)
    root = wire.root_prepare(CLUSTER)
    j.write_prepare(root, b"")
    parent = wire.u128(root, "checksum")
    for op in range(1, 4):
        h = make_prepare(op, parent)
        j.write_prepare(h, b"", sync=(op < 3))
        parent = wire.u128(h, "checksum")
    storage.crash()  # op 3 unsynced: prepare+header sectors revert

    rec = Journal(storage, CLUSTER).recover(commit_min=0)
    assert rec.op_head == 2
    assert rec.faulty_ops == []


def test_journal_corrupt_prepare_below_head_is_faulty():
    storage = MemoryStorage(layout())
    j = Journal(storage, CLUSTER)
    root = wire.root_prepare(CLUSTER)
    j.write_prepare(root, b"")
    parent = wire.u128(root, "checksum")
    for op in range(1, 5):
        h = make_prepare(op, parent)
        j.write_prepare(h, b"")
        parent = wire.u128(h, "checksum")
    storage.corrupt_sector(storage.layout.prepare_slot_offset(2))

    rec = Journal(storage, CLUSTER).recover(commit_min=0)
    assert rec.faulty_ops == [2]
    assert rec.op_head == 4


def test_journal_ring_wrap():
    slots = cfg.TEST_MIN.journal_slot_count
    storage = MemoryStorage(layout())
    j = Journal(storage, CLUSTER)
    root = wire.root_prepare(CLUSTER)
    j.write_prepare(root, b"")
    parent = wire.u128(root, "checksum")
    last = slots + 10
    for op in range(1, last + 1):
        h = make_prepare(op, parent)
        j.write_prepare(h, b"")
        parent = wire.u128(h, "checksum")

    rec = Journal(storage, CLUSTER).recover(commit_min=last - 5)
    assert rec.op_head == last


def _recover_both_ways(storage, commit_min, window=3):
    """Run recover() with the windowed prepares scan and with the full
    scan on identical storage; return both (Recovery, headers-ring,
    prepare-reads) triples."""
    out = []
    for probe_all in (False, True):
        j = Journal(storage, CLUSTER)
        j.RECOVER_HEAD_WINDOW = window
        j.RECOVER_PROBE_ALL = probe_all
        reads0 = storage.reads
        rec = j.recover(commit_min=commit_min)
        out.append((rec, j.headers.tobytes(), storage.reads - reads0))
    return out


def _assert_equivalent(windowed, full):
    (rec_w, ring_w, _), (rec_f, ring_f, _) = windowed, full
    assert rec_w.op_head == rec_f.op_head
    assert rec_w.faulty_ops == rec_f.faulty_ops
    assert rec_w.truncated_ops == rec_f.truncated_ops
    assert sorted(rec_w.headers) == sorted(rec_f.headers)
    for op in rec_w.headers:
        assert rec_w.headers[op].tobytes() == rec_f.headers[op].tobytes()
    assert ring_w == ring_f


def test_journal_windowed_recover_equivalence():
    """The windowed prepares scan (skip slots settled by the redundant
    ring) must classify every adversarial state exactly like the full
    scan — wraps, corruption below/above the checkpoint, an unsynced
    crash tail, and a stale wrapped redundant header — while reading
    fewer prepare slots."""
    slots = cfg.TEST_MIN.journal_slot_count

    def build(n_ops):
        storage = MemoryStorage(layout())
        j = Journal(storage, CLUSTER)
        root = wire.root_prepare(CLUSTER)
        j.write_prepare(root, b"")
        parent = wire.u128(root, "checksum")
        for op in range(1, n_ops + 1):
            h = make_prepare(op, parent, body=bytes([op & 0xFF]) * 64)
            j.write_prepare(h, bytes([op & 0xFF]) * 64)
            parent = wire.u128(h, "checksum")
        return storage

    # Clean wrapped ring: equivalence AND strictly fewer prepare reads.
    storage = build(slots + 12)
    w, f = _recover_both_ways(storage, commit_min=slots + 4)
    _assert_equivalent(w, f)
    assert w[2] < f[2]

    # Latent corruption below the checkpoint (settled region): both
    # scans must ignore it.
    storage = build(slots + 12)
    storage.corrupt_sector(storage.layout.prepare_slot_offset(
        (slots + 12 - 20) % slots))
    w, f = _recover_both_ways(storage, commit_min=slots + 4)
    _assert_equivalent(w, f)

    # Corruption above the checkpoint: both must report it faulty.
    storage = build(slots + 12)
    storage.corrupt_sector(storage.layout.prepare_slot_offset(
        (slots + 6) % slots))
    w, f = _recover_both_ways(storage, commit_min=slots + 4)
    _assert_equivalent(w, f)
    assert slots + 6 in w[0].faulty_ops

    # Crash with an unsynced tail.
    storage = build(slots + 8)
    j = Journal(storage, CLUSTER)
    rec = j.recover(commit_min=slots)  # fills j.headers
    parent = wire.u128(rec.headers[rec.op_head], "checksum")
    for op in range(slots + 9, slots + 12):
        h = make_prepare(op, parent, body=b"t" * 32)
        j.write_prepare(h, b"t" * 32, sync=(op < slots + 11))
        parent = wire.u128(h, "checksum")
    storage.crash()
    w, f = _recover_both_ways(storage, commit_min=slots + 2)
    _assert_equivalent(w, f)

    # Stale wrapped redundant: the prepare holds a NEW op but the
    # redundant sector still shows the old wrapped op (crash landed
    # between the two writes).  The slot sits below max_op, inside the
    # backward head window.
    storage = build(slots + 12)
    j = Journal(storage, CLUSTER)
    j.recover(commit_min=slots + 4)
    new_op = slots + 13
    stale_slot = new_op % slots
    h = make_prepare(
        new_op,
        wire.u128(j.headers[(slots + 12) % slots], "checksum"),
        body=b"n" * 48,
    )
    from tigerbeetle_tpu.vsr.storage import _sectors

    msg = h.tobytes() + b"n" * 48
    storage.write(
        storage.layout.prepare_slot_offset(stale_slot),
        msg.ljust(_sectors(len(msg)), b"\x00"),
    )
    storage.sync()  # prepare persisted, redundant sector NOT updated
    w, f = _recover_both_ways(storage, commit_min=slots + 5)
    _assert_equivalent(w, f)
    assert w[0].op_head == new_op

    # BACKWARD window: a LATER op's redundant persisted across the
    # crash while this op's did not, so the stale-redundant slot sits
    # BELOW max_op — only the backward branch of the head window
    # rescues it from being settled as its old wrapped op.
    storage = build(slots + 12)
    j = Journal(storage, CLUSTER)
    j.recover(commit_min=slots + 4)
    parent = wire.u128(j.headers[(slots + 12) % slots], "checksum")
    h13 = make_prepare(slots + 13, parent, body=b"a" * 48)
    msg = h13.tobytes() + b"a" * 48
    storage.write(
        storage.layout.prepare_slot_offset((slots + 13) % slots),
        msg.ljust(_sectors(len(msg)), b"\x00"),
    )
    h14 = make_prepare(
        slots + 14, wire.u128(h13, "checksum"), body=b"b" * 48
    )
    msg = h14.tobytes() + b"b" * 48
    storage.write(
        storage.layout.prepare_slot_offset((slots + 14) % slots),
        msg.ljust(_sectors(len(msg)), b"\x00"),
    )
    j.headers[(slots + 14) % slots] = h14
    j._write_header_sector((slots + 14) % slots)
    storage.sync()
    w, f = _recover_both_ways(storage, commit_min=slots + 5)
    _assert_equivalent(w, f)
    assert w[0].op_head == slots + 14


# ----------------------------------------------------------------------
# SuperBlock.


def test_superblock_quorum_and_sequence():
    storage = MemoryStorage(layout())
    sb = SuperBlock(storage, CLUSTER)
    sb.format(replica=0, replica_count=1)
    sb.checkpoint(
        commit_min=24, commit_min_checksum=123, commit_max=24,
        checkpoint_offset=storage.layout.grid_offset, checkpoint_size=100,
        checkpoint_checksum=9,
    )

    sb2 = SuperBlock(storage, CLUSTER)
    h = sb2.open()
    assert int(h["sequence"]) == 2
    assert int(h["commit_min"]) == 24

    # Corrupt two of four copies: quorum (2) still holds.
    storage.corrupt_sector(0)
    storage.corrupt_sector(4096)
    assert int(SuperBlock(storage, CLUSTER).open()["sequence"]) == 2

    # Three corrupt: no quorum.
    storage.corrupt_sector(2 * 4096)
    with pytest.raises(RuntimeError, match="no quorum"):
        SuperBlock(storage, CLUSTER).open()


# ----------------------------------------------------------------------
# Replica end-to-end.


def test_replica_basic_and_restart_replay():
    storage, r = fresh_replica()
    reply = r.on_request(types.Operation.create_accounts,
                         pack([account(1), account(2)]))
    assert reply == b""
    reply = r.on_request(
        types.Operation.create_transfers,
        pack([transfer(10, debit_account_id=1, credit_account_id=2, amount=100)]),
    )
    assert reply == b""

    # Restart from a fresh state machine: WAL replay must rebuild state.
    r2 = reopen(storage)
    assert r2.op == r.op
    out = r2.on_request(types.Operation.lookup_accounts, ids_bytes([1, 2]))
    rows = np.frombuffer(out, types.ACCOUNT_DTYPE)
    assert types.u128_get(rows[0], "debits_posted") == 100
    assert types.u128_get(rows[1], "credits_posted") == 100


def test_replica_crash_loses_unsynced_tail_only():
    storage, r = fresh_replica()
    r.on_request(types.Operation.create_accounts, pack([account(1), account(2)]))
    r.on_request(
        types.Operation.create_transfers,
        pack([transfer(10, debit_account_id=1, credit_account_id=2, amount=7)]),
    )
    storage.crash()  # everything synced: no loss

    r2 = reopen(storage)
    out = r2.on_request(types.Operation.lookup_accounts, ids_bytes([1]))
    assert types.u128_get(np.frombuffer(out, types.ACCOUNT_DTYPE)[0],
                          "debits_posted") == 7


def test_replica_checkpoint_and_wal_wrap():
    storage, r = fresh_replica()
    r.on_request(types.Operation.create_accounts, pack([account(1), account(2)]))
    # Push ops past several checkpoint intervals + full ring wraps.
    n_ops = cfg.TEST_MIN.journal_slot_count * 3
    for i in range(n_ops):
        r.on_request(
            types.Operation.create_transfers,
            pack([transfer(100 + i, debit_account_id=1, credit_account_id=2,
                           amount=1)]),
        )
    assert r.checkpoint_op > 0

    r2 = reopen(storage)
    assert r2.commit_min == r.commit_min
    out = r2.on_request(types.Operation.lookup_accounts, ids_bytes([1]))
    assert types.u128_get(np.frombuffer(out, types.ACCOUNT_DTYPE)[0],
                          "debits_posted") == n_ops


def test_replica_dedupe_replays_reply():
    storage, r = fresh_replica()
    r.register_client(42)
    b1 = r.on_request(types.Operation.create_accounts, pack([account(1)]),
                      client=42, request=1)
    assert b1 == b""
    # Same request again: no re-execution (account already exists would
    # return `exists`, so identical empty reply proves dedupe).
    b2 = r.on_request(types.Operation.create_accounts, pack([account(1)]),
                      client=42, request=1)
    assert b2 == b""
    # New request number does execute (and reports exists).
    b3 = r.on_request(types.Operation.create_accounts, pack([account(1)]),
                      client=42, request=2)
    arr = np.frombuffer(b3, types.CREATE_RESULT_DTYPE)
    assert types.CreateAccountResult(int(arr[0]["result"])).name == "exists"


def test_replica_two_phase_expiry_survives_restart(tmp_path):
    path = str(tmp_path / "data.tb")
    storage = FileStorage(path, layout(), create=True)
    vsr_replica.format(storage, CLUSTER)
    r = vsr_replica.Replica(storage, CLUSTER, CpuStateMachine(cfg.TEST_MIN))
    r.open()
    r.on_request(types.Operation.create_accounts, pack([account(1), account(2)]))
    r.on_request(
        types.Operation.create_transfers,
        pack([transfer(10, debit_account_id=1, credit_account_id=2, amount=50,
                       timeout=1, flags=types.TransferFlags.pending)]),
    )
    storage.close()

    storage = FileStorage(path, layout())
    r2 = vsr_replica.Replica(storage, CLUSTER, CpuStateMachine(cfg.TEST_MIN))
    r2.open()
    # Advance realtime past expiry: pulse fires, pending releases.
    out = r2.on_request(types.Operation.lookup_accounts, ids_bytes([1]),
                        realtime=10 * types.NS_PER_S)
    row = np.frombuffer(out, types.ACCOUNT_DTYPE)[0]
    assert types.u128_get(row, "debits_pending") == 0
    ts = r2.sm.transfer_timestamp(10)
    assert r2.sm.pending_status(10) == types.TransferPendingStatus.expired
    assert ts is not None
    storage.close()


def test_crash_at_fsync_request_never_acked():
    """The crash-at-fsync fault point: an op whose WAL sync dies
    mid-call is never acked (on_request raises instead of returning a
    reply), and recovery shows no trace of it."""
    from tigerbeetle_tpu.vsr.storage import FsyncCrash

    storage, r = fresh_replica()
    r.on_request(types.Operation.create_accounts, pack([account(1), account(2)]))
    r.on_request(
        types.Operation.create_transfers,
        pack([transfer(10, debit_account_id=1, credit_account_id=2, amount=5)]),
    )
    op_before = r.op
    storage.crash_at_fsync = 1
    with pytest.raises(FsyncCrash):
        r.on_request(
            types.Operation.create_transfers,
            pack([transfer(11, debit_account_id=1, credit_account_id=2,
                           amount=900)]),
        )
    storage.crash()  # power loss: the unsynced op's sectors are lost

    r2 = reopen(storage)
    assert r2.op == op_before
    out = r2.on_request(types.Operation.lookup_accounts, ids_bytes([1]))
    assert types.u128_get(np.frombuffer(out, types.ACCOUNT_DTYPE)[0],
                          "debits_posted") == 5


def test_mid_async_checkpoint_crash_recovers_previous_superblock():
    """Crash between an async checkpoint's FREEZE (spill + snapshot +
    buffered blob write) and its background flip: the new superblock
    never landed, so recovery must come up from the PREVIOUS one and
    replay the WAL tail to the same state."""
    from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine

    storage = MemoryStorage(layout())
    vsr_replica.format(storage, CLUSTER)
    r = vsr_replica.Replica(storage, CLUSTER, TpuStateMachine(cfg.TEST_MIN))
    r.open()
    r.on_request(types.Operation.create_accounts, pack([account(1), account(2)]))
    # Cross one full (synchronous) checkpoint so a durable previous
    # superblock exists, then commit a tail beyond it.
    n_ops = cfg.TEST_MIN.vsr_checkpoint_interval + 7
    for i in range(n_ops):
        r.on_request(
            types.Operation.create_transfers,
            pack([transfer(100 + i, debit_account_id=1, credit_account_id=2,
                           amount=2)]),
        )
    assert r.checkpoint_op > 0
    seq_before = int(r.superblock.working["sequence"])
    commit_before = r.commit_min

    # The async split's freeze half only: spill + snapshot + blob
    # write land in the page cache (unsynced); the flip never runs —
    # exactly the state a crash inside the background window leaves.
    r._checkpoint_freeze()
    storage.crash()

    r2 = vsr_replica.Replica(storage, CLUSTER, TpuStateMachine(cfg.TEST_MIN))
    r2.open()
    assert int(r2.superblock.working["sequence"]) == seq_before
    assert r2.checkpoint_op == r.checkpoint_op
    assert r2.commit_min == commit_before  # WAL replay covers the tail
    out = r2.on_request(types.Operation.lookup_accounts, ids_bytes([1, 2]))
    rows = np.frombuffer(out, types.ACCOUNT_DTYPE)
    assert types.u128_get(rows[0], "debits_posted") == 2 * n_ops
    assert types.u128_get(rows[1], "credits_posted") == 2 * n_ops


def test_free_set_quarantines_released_blocks_until_flip():
    """Blocks released by a frozen checkpoint become free (the blob
    encodes them free) but must not be REUSED while the previous
    superblock — which may reference them — is still the durable
    recovery root (async flip window)."""
    from tigerbeetle_tpu.vsr.free_set import FreeSet

    fs = FreeSet(8)
    res = fs.reserve(3)
    a, b, c = fs.acquire(res), fs.acquire(res), fs.acquire(res)
    fs.forfeit(res)
    fs.release(a)
    fs.release(b)
    fs.checkpoint()  # freeze: free again, but quarantined
    assert fs.is_free(a) and fs.is_free(b)
    res2 = fs.reserve(5)
    got = {fs.acquire(res2) for _ in range(5)}
    fs.forfeit(res2)
    assert a not in got and b not in got, "reused a quarantined block"
    # The blob must encode quarantined blocks as FREE (it is only read
    # once its own flip is durable).
    decoded = FreeSet.decode(fs.encode(), 8)
    assert decoded.is_free(a) and decoded.is_free(b)
    # The NEXT freeze releases the previous quarantine (deterministic
    # in the commit stream; the replica's checkpoint join guarantees
    # it postdates the durable flip).
    fs.checkpoint()
    res3 = fs.reserve(2)
    got3 = {fs.acquire(res3) for _ in range(2)}
    fs.forfeit(res3)
    assert got3 == {a, b}
    # Explicit early release stays available for standalone harnesses.
    fs.release(c)
    fs.checkpoint()
    fs.release_quarantine()
    res4 = fs.reserve(1)
    assert fs.acquire(res4) == c
    fs.forfeit(res4)


def test_replica_tpu_state_machine_checkpoint_restart():
    from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine

    storage = MemoryStorage(layout())
    vsr_replica.format(storage, CLUSTER)
    r = vsr_replica.Replica(storage, CLUSTER, TpuStateMachine(cfg.TEST_MIN))
    r.open()
    r.on_request(types.Operation.create_accounts, pack([account(1), account(2)]))
    n_ops = cfg.TEST_MIN.vsr_checkpoint_interval + 5  # cross one checkpoint
    for i in range(n_ops):
        r.on_request(
            types.Operation.create_transfers,
            pack([transfer(100 + i, debit_account_id=1, credit_account_id=2,
                           amount=2)]),
        )
    assert r.checkpoint_op > 0

    r2 = vsr_replica.Replica(storage, CLUSTER, TpuStateMachine(cfg.TEST_MIN))
    r2.open()
    assert r2.commit_min == r.commit_min
    out = r2.on_request(types.Operation.lookup_accounts, ids_bytes([1, 2]))
    rows = np.frombuffer(out, types.ACCOUNT_DTYPE)
    assert types.u128_get(rows[0], "debits_posted") == 2 * n_ops
    assert types.u128_get(rows[1], "credits_posted") == 2 * n_ops
