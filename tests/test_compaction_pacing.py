"""Paced compaction: bounded per-beat work, correct reads mid-merge.

reference: src/lsm/compaction.zig:1-32 (beats of a bar),
src/lsm/forest.zig:846 (CompactionPipeline) — merge debt is spread
across commits instead of stalling checkpoints.
"""

import numpy as np
import pytest

from tigerbeetle_tpu.lsm.runs import KEY_DTYPE, pack_u128
from tigerbeetle_tpu.lsm.tree import GROWTH, Tree
from tigerbeetle_tpu.vsr.storage import MemoryStorage, ZoneLayout
from tigerbeetle_tpu.vsr.grid import Grid
from tigerbeetle_tpu import constants as cfg


def make_tree(memtable_max=64, value_size=8):
    layout = ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 22)
    storage = MemoryStorage(layout)
    grid = Grid(storage, block_size=1 << 12, block_count=1 << 10)
    return Tree(grid, "t", value_size=value_size, memtable_max=memtable_max)


def put_range(tree, lo, hi, tag):
    keys = pack_u128(
        np.arange(lo, hi, dtype=np.uint64), np.zeros(hi - lo, np.uint64)
    )
    vals = np.full(hi - lo, tag, np.uint64)
    tree.put_batch(keys, vals)


def check_values(tree, expect: dict):
    ids = np.fromiter(expect.keys(), np.uint64)
    keys = pack_u128(ids, np.zeros(len(ids), np.uint64))
    found, vals = tree.lookup_batch(np.asarray(keys, KEY_DTYPE))
    assert found.all()
    got = vals.view(np.uint64).reshape(-1)
    want = np.fromiter(expect.values(), np.uint64)
    assert (got == want).all()


def test_beats_are_bounded_and_reads_stay_correct():
    tree = make_tree(memtable_max=64)
    expect = {}
    # Create deep merge debt: many seals, overlapping key ranges so
    # merges actually dedupe (newest tag wins).
    for round_ in range(GROWTH * 3):
        lo = (round_ % 4) * 100
        put_range(tree, lo, lo + 64, tag=round_)
        for k in range(lo, lo + 64):
            expect[k] = round_
        tree.seal_memtable()
    assert tree.compaction_pending()
    budget = 4
    beats = 0
    while tree.compaction_pending():
        used = tree.compact_beat(budget)
        assert used <= budget
        beats += 1
        assert beats < 10_000
        # Reads must be correct at EVERY intermediate state.
        if beats % 7 == 0:
            check_values(tree, expect)
    check_values(tree, expect)
    # The level shape invariant holds after draining.
    for level in range(len(tree.levels) - 1):
        assert len(tree.levels[level]) <= tree._level_run_max(level)


def test_seals_during_job_survive():
    tree = make_tree(memtable_max=64)
    expect = {}
    for round_ in range(GROWTH + 1):
        put_range(tree, 0, 64, tag=round_)
        expect.update({k: round_ for k in range(64)})
        tree.seal_memtable()
    assert tree.compaction_pending()
    # Advance the job partially, then seal NEW data mid-job.
    tree.compact_beat(2)
    put_range(tree, 1000, 1064, tag=77)
    expect.update({k: 77 for k in range(1000, 1064)})
    tree.seal_memtable()
    # Newer version of an existing key, mid-job.
    put_range(tree, 0, 8, tag=99)
    expect.update({k: 99 for k in range(8)})
    tree.seal_memtable()
    while tree.compaction_pending():
        tree.compact_beat(3)
    check_values(tree, expect)


def test_tombstones_drop_only_at_last_level():
    tree = make_tree(memtable_max=32)
    put_range(tree, 0, 32, tag=1)
    tree.seal_memtable()
    keys = pack_u128(np.arange(0, 16, dtype=np.uint64), np.zeros(16, np.uint64))
    tree.remove_batch(np.asarray(keys, KEY_DTYPE))
    tree.seal_memtable()
    for _ in range(GROWTH):
        put_range(tree, 100, 132, tag=2)
        tree.seal_memtable()
    while tree.compaction_pending():
        tree.compact_beat(4)
    found, _ = tree.lookup_batch(np.asarray(keys, KEY_DTYPE))
    assert not found.any()
    check_values(tree, {k: 1 for k in range(16, 32)})


def _forest_fixture():
    from tigerbeetle_tpu.lsm.forest import Forest

    layout = ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 22)
    storage = MemoryStorage(layout)
    forest = Forest(storage, block_size=1 << 12, block_count=1 << 10,
                    memtable_max=64)
    forest.groove("obj", object_size=16, index_fields=[])
    return storage, forest


def _fill(forest, rounds, rng):
    g = forest.grooves["obj"]
    objs_by_id = {}
    for round_ in range(rounds):
        ids = np.arange(1 + round_ * 64, 1 + round_ * 64 + 64, dtype=np.uint64)
        objs = rng.integers(0, 2**63, (64, 2), np.uint64)
        # Interleaved timestamps across rounds: object-tree key ranges
        # OVERLAP, so its merges are real (disjoint inputs would take
        # the metadata move path and finish instantly).
        ts = (np.arange(64, dtype=np.uint64) + np.uint64(1)) * np.uint64(
            1000
        ) + np.uint64(round_)
        g.insert_batch(ids, np.zeros(64, np.uint64), ts,
                       objs.view(np.uint8), {})
        for i, v in zip(ids, objs):
            objs_by_id[int(i)] = v
    return objs_by_id


def _check_objects(forest, objs_by_id):
    g = forest.grooves["obj"]
    ids = np.fromiter(objs_by_id.keys(), np.uint64)
    found, ts = g.lookup_ids(ids, np.zeros(len(ids), np.uint64))
    assert found.all()
    found2, objs = g.get_objects(ts)
    assert found2.all()
    want = np.stack([objs_by_id[int(i)] for i in ids])
    assert (objs.view(np.uint64).reshape(len(ids), 2) == want).all()


def test_checkpoint_drains_active_jobs_only():
    """Checkpoints finish ACTIVE merge jobs (deterministic blobs — no
    job state crosses a checkpoint) but do not start merges for other
    over-full levels; those wait for the next interval's beats."""
    storage, forest = _forest_fixture()
    rng = np.random.default_rng(3)
    objs_by_id = _fill(forest, GROWTH * 2, rng)
    forest.compact_beat(4)  # starts (at least) one job
    assert any(t._job is not None for t in forest._trees)
    forest.checkpoint()
    assert all(t._job is None for t in forest._trees)
    _check_objects(forest, objs_by_id)
    while forest.compaction_pending():
        forest.compact_beat(8)
    _check_objects(forest, objs_by_id)


def test_midinterval_snapshot_orphan_reclaim():
    """A mid-interval snapshot (state sync path) taken with a merge in
    flight records the job's output blocks as orphans; a restore
    reclaims them, cancels the stale job, and the restarted merge
    reaches the same served state."""
    from tigerbeetle_tpu.lsm.forest import Forest

    storage, forest = _forest_fixture()
    rng = np.random.default_rng(3)
    objs_by_id = _fill(forest, GROWTH * 2, rng)
    forest.compact_beat(4)
    assert any(t._job is not None for t in forest._trees)
    blob = forest.manifest_blob()  # NOT a checkpoint: job in flight
    forest2 = Forest(storage, block_size=1 << 12, block_count=1 << 10,
                     memtable_max=64)
    forest2.groove("obj", object_size=16, index_fields=[])
    forest2.open(blob)
    assert all(t._job is None for t in forest2._trees)
    _check_objects(forest2, objs_by_id)
    while forest2.compaction_pending():
        forest2.compact_beat(8)
    forest2.checkpoint()  # activates the staged orphan releases
    _check_objects(forest2, objs_by_id)
