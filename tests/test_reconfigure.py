"""Reconfiguration: replicated membership change with epoch bump
(reference: src/vsr.zig:273-311).  First use case: standby promotion —
swap a dead active's slot with a standby that has been replicating all
along, without losing committed state."""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.harness import account, ids_bytes, pack, transfer
from tigerbeetle_tpu.vsr.replica import Replica
from tigerbeetle_tpu.vsr.wire import VsrOperation


def make_cluster(**kw):
    c = Cluster(replica_count=3, standby_count=1, **kw)
    client = c.client(1000)
    client.register()
    c.run_until(lambda: client.registered)
    return c, client


def reconfigure_body(epoch, members):
    return Replica.encode_reconfigure(epoch, members)


def test_standby_promotion_preserves_committed_state():
    c, client = make_cluster()
    assert c.run_request(
        client, types.Operation.create_accounts, pack([account(1), account(2)])
    ) == b""
    for i in range(5):
        assert c.run_request(
            client, types.Operation.create_transfers,
            pack([transfer(10 + i, debit_account_id=1, credit_account_id=2,
                           amount=10)]),
        ) == b""

    # Kill active slot 2 (a backup); promote the standby (process 3).
    c.crash_replica(2)
    reply = c.run_request(
        client, VsrOperation.reconfigure, reconfigure_body(1, [0, 1, 3, 2])
    )
    assert int.from_bytes(reply, "little") == 0
    # Every live replica adopted the new membership and roles.
    for proc in (0, 1, 3):
        r = c.replicas[proc]
        assert r.epoch == 1
        assert r.members == [0, 1, 3, 2]
    assert c.replicas[3].replica == 2          # promoted into slot 2
    assert not c.replicas[3].standby
    # The cluster keeps committing with the promoted member acking.
    for i in range(5):
        assert c.run_request(
            client, types.Operation.create_transfers,
            pack([transfer(50 + i, debit_account_id=1, credit_account_id=2,
                           amount=10)]),
        ) == b""
    out = c.run_request(
        client, types.Operation.lookup_accounts, ids_bytes([1])
    )
    rows = np.frombuffer(out, types.ACCOUNT_DTYPE)
    assert types.u128_get(rows[0], "debits_posted") == 100
    # The promoted process holds the full committed state.
    assert c.replicas[3].sm.transfer_timestamp(54) is not None
    assert c.replicas[3].sm.transfer_timestamp(10) is not None


def test_reconfigure_rejects_stale_epoch_and_bad_members():
    c, client = make_cluster()
    reply = c.run_request(
        client, VsrOperation.reconfigure, reconfigure_body(7, [0, 1, 2, 3])
    )
    assert int.from_bytes(reply, "little") == 1  # epoch must be current+1
    reply = c.run_request(
        client, VsrOperation.reconfigure, reconfigure_body(1, [0, 1, 2, 2])
    )
    assert int.from_bytes(reply, "little") == 2  # not a permutation
    reply = c.run_request(
        client, VsrOperation.reconfigure, reconfigure_body(1, [0, 2, 1, 3])
    )
    assert int.from_bytes(reply, "little") == 0
    c.run_until(
        lambda: c.replicas[1].epoch == 1 and c.replicas[2].epoch == 1
    )
    assert c.replicas[1].replica == 2 and c.replicas[2].replica == 1


def test_restarted_process_relearns_membership_from_wal():
    """A process that crashed BEFORE a reconfigure committed must
    re-derive its new (standby) role from the replicated log after
    restarting."""
    c, client = make_cluster()
    assert c.run_request(
        client, types.Operation.create_accounts, pack([account(1), account(2)])
    ) == b""
    c.crash_replica(2)
    reply = c.run_request(
        client, VsrOperation.reconfigure, reconfigure_body(1, [0, 1, 3, 2])
    )
    assert int.from_bytes(reply, "little") == 0
    assert c.run_request(
        client, types.Operation.create_transfers,
        pack([transfer(10, debit_account_id=1, credit_account_id=2, amount=5)]),
    ) == b""
    c.restart_replica(2)
    c.settle()
    # Repair/catch-up replays the reconfigure op: the old process now
    # fills the standby slot and still replicates commits.
    c.run_until(lambda: c.replicas[2].epoch == 1, max_steps=4000)
    assert c.replicas[2].members == [0, 1, 3, 2]
    assert c.replicas[2].replica == 3
    assert c.replicas[2].standby
    c.run_until(
        lambda: c.replicas[2].sm.transfer_timestamp(10) is not None,
        max_steps=4000,
    )


def test_reconfigure_malformed_body_is_rejected_not_fatal():
    """A poison reconfigure body (too short / bad count) must commit
    with a result code, never crash the commit path of the cluster."""
    c, client = make_cluster()
    for body in (b"", b"\x01" * 5, (1).to_bytes(8, "little") + b"\xff"):
        reply = c.run_request(client, VsrOperation.reconfigure, body)
        assert int.from_bytes(reply, "little") == 2, body
    # The cluster is still alive and at epoch 0.
    assert c.run_request(
        client, types.Operation.create_accounts, pack([account(1)])
    ) == b""
    assert all(r.epoch == 0 for r in c.replicas)


def test_reconfigure_cannot_displace_primary():
    """Swapping the committing primary's slot is rejected (code 3):
    an accepted self-demotion would orphan the in-flight pipeline."""
    c, client = make_cluster()
    # View 0 primary is slot 0 (process 0); try to move it.
    reply = c.run_request(
        client, VsrOperation.reconfigure, reconfigure_body(1, [1, 0, 2, 3])
    )
    assert int.from_bytes(reply, "little") == 3
    assert all(r.epoch == 0 for r in c.replicas if r.status == "normal")
