"""Tier-1 replicated smoke: a real 2-replica TCP cluster (in-process
ReplicaServers over the native bus) driven by BENCH_REPL_SESSIONS
concurrent client sessions — the group-commit spine exercised end to
end in pytest, so a regression surfaces here and not only in bench
runs.  Small stream, TEST_MIN config, CPU state machine: seconds, not
minutes."""

import os
import socket
import threading
import time

import numpy as np
import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.runtime.native import native_available
from tigerbeetle_tpu.state_machine import CpuStateMachine

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native runtime not built"
)

CLUSTER = 9
N_REPLICAS = 2
TRANSFERS_PER_SESSION = 12


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class _Server:
    def __init__(self, path, addresses, index):
        from tigerbeetle_tpu.runtime.server import ReplicaServer

        self.server = ReplicaServer(
            path, cluster=CLUSTER, addresses=addresses, replica_index=index,
            state_machine_factory=lambda: CpuStateMachine(cfg.TEST_MIN),
            config=cfg.TEST_MIN,
        )
        self._stop = False
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self):
        while not self._stop:
            self.server.poll_once(timeout_ms=1)

    def close(self):
        self._stop = True
        self.thread.join(timeout=5)
        self.server.close()


def test_two_replica_group_commit_smoke(tmp_path, monkeypatch):
    """Both ingest arms, one assertion set: the cluster runs once with
    the columnar fast path forced ON and once forced OFF.  The
    create_transfers reply BODIES (result pairs, including a
    deliberate failure per session) must be identical across arms —
    the wire contract does not move with the decode strategy — and
    the ON arm's scrape must show nonzero fastpath.batch_decode hits
    (bit-level reply-frame identity incl. headers is pinned by the
    pinned-clock differential in tests/test_fastpath_decode.py)."""
    replies_on = _run_cluster_once(tmp_path / "on", "1", monkeypatch)
    replies_off = _run_cluster_once(tmp_path / "off", "0", monkeypatch)
    assert replies_on == replies_off


def test_two_replica_native_pipeline_smoke(tmp_path, monkeypatch):
    """Native-pipeline arm (round 20): the same cluster smoke with the
    per-prepare hot loop in C (TB_NATIVE_PIPELINE=1) vs pure Python
    (=0) — reply bodies identical, both over the columnar ingest path
    (bit-level frame identity is pinned by the sim-cluster
    differential in tests/test_native_pipeline.py)."""
    from tigerbeetle_tpu.runtime import fastpath

    if not fastpath.pipeline_available():
        pytest.skip("libtb_fastpath pipeline symbols not built")
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "1")
    replies_native = _run_cluster_once(tmp_path / "np_on", "1", monkeypatch)
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "0")
    replies_python = _run_cluster_once(tmp_path / "np_off", "1", monkeypatch)
    assert replies_native == replies_python


def test_two_replica_native_drain_smoke(tmp_path, monkeypatch):
    """C-resident drain arm (round 22): the same cluster smoke with a
    whole poll's prepare->ack->commit-decision batched below Python
    (TB_NATIVE_DRAIN=1) vs the per-item loop over the same batch seams
    (=0) — reply bodies identical (bit-level frame identity is pinned
    by the batched-delivery differential in tests/test_native_drain.py),
    and the scrape proves which arm ran: batch C crossings only on the
    ON arm, and far fewer crossings than prepares+acks processed."""
    from tigerbeetle_tpu.runtime import fastpath

    if not fastpath.drain_available():
        pytest.skip("libtb_fastpath r22 drain symbols not built")
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "1")
    monkeypatch.setenv("TB_NATIVE_DRAIN", "1")
    drain_scrapes.clear()
    replies_native = _run_cluster_once(tmp_path / "nd_on", "1", monkeypatch)
    on_snaps = list(drain_scrapes)
    monkeypatch.setenv("TB_NATIVE_DRAIN", "0")
    drain_scrapes.clear()
    replies_python = _run_cluster_once(tmp_path / "nd_off", "1", monkeypatch)
    off_snaps = list(drain_scrapes)
    assert replies_native == replies_python
    # The ON arm crossed into C per batch seam — on BOTH roles (the
    # primary's plan+ack drains, the backup's accept drains) — and the
    # OFF arm never did.  Crossings are per RUN, so they stay bounded
    # by the per-item work they replaced (native_calls <= items; the
    # bench harvests the amortization ratio under real concurrency).
    for s in on_snaps:
        assert s["vsr.drain.native_calls"] > 0
    primary_on, backup_on = on_snaps[0], on_snaps[1]
    assert (
        primary_on["vsr.drain.native_calls"]
        <= primary_on["vsr.prepare_us.count"]
        + primary_on["vsr.prepares_written"] * 2
    )
    assert (
        backup_on["vsr.drain.native_calls"]
        <= backup_on["vsr.prepare_ok_us.count"]
    )
    for s in off_snaps:
        assert s["vsr.drain.native_calls"] == 0


def test_two_replica_hash_reuse_smoke(tmp_path, monkeypatch):
    """Hash-once arm (round 23): the same cluster smoke with
    drain-scoped digest reuse ON vs OFF, pinned to ONE client session
    so every prepare is a unit request — the coalesce finalize is a
    legitimate extra pass over freshly concatenated bytes and would
    muddy the per-byte ratio this test exists to pin.  Reply bodies
    identical across arms; per role the reuse-on arm SHA-256s each
    committed body byte at most once (bytes_hashed <=
    committed_body_bytes), the reuse-off primary strictly more for
    the same stream (the build rehash comes back), and only the
    primary's build seams ever consume cached digests."""
    monkeypatch.setenv("BENCH_REPL_SESSIONS", "1")
    monkeypatch.setenv("TB_HASH_REUSE", "1")
    drain_scrapes.clear()
    replies_on = _run_cluster_once(tmp_path / "hr_on", "1", monkeypatch)
    on_snaps = list(drain_scrapes)
    monkeypatch.setenv("TB_HASH_REUSE", "0")
    drain_scrapes.clear()
    replies_off = _run_cluster_once(tmp_path / "hr_off", "1", monkeypatch)
    off_snaps = list(drain_scrapes)
    assert replies_on == replies_off
    # The counters and the engine forensics reach the scrape on every
    # role in both arms (vsr.* graft for the replica counters, bare
    # names for the server-level engine gauges).
    for s in on_snaps + off_snaps:
        assert s["vsr.hash.committed_body_bytes"] > 0
        assert s["hash.engine_code"] in (1, 2, 3)
        assert s["hash.threads"] >= 0
        assert "server.verify_body_bytes" in s
        assert "hash.scalar_fallback" in s
    # Tentpole contract, numerically: with reuse ON no role spends
    # more than ONE SHA-256 pass per committed body byte.  A
    # retransmitted frame must be verified before it can be
    # recognized as a duplicate — that pass is unavoidable in any
    # design and lands in hash.dup_body_bytes, so the bound is exact,
    # not fuzzed with slack.
    for s in on_snaps:
        assert (
            s["vsr.hash.bytes_hashed"]
            <= s["vsr.hash.committed_body_bytes"]
            + s["vsr.hash.dup_body_bytes"]
        ), s
    primary_on, primary_off = on_snaps[0], off_snaps[0]
    assert primary_on["vsr.hash.reuse_hits"] > 0
    # ... and turning the knob OFF brings the build rehash back: the
    # primary hashes the same committed stream strictly more than
    # once per byte (net of duplicate deliveries), and strictly more
    # than the reuse-on arm did.
    assert primary_off["vsr.hash.reuse_hits"] == 0
    off_net = (
        primary_off["vsr.hash.bytes_hashed"]
        - primary_off["vsr.hash.dup_body_bytes"]
    )
    on_net = (
        primary_on["vsr.hash.bytes_hashed"]
        - primary_on["vsr.hash.dup_body_bytes"]
    )
    assert off_net > primary_off["vsr.hash.committed_body_bytes"], (
        primary_off
    )
    assert off_net > on_net


# Scrape snapshots stashed by _run_cluster_once for arm-level
# assertions that need both runs (the drain smoke above).
drain_scrapes: list = []


def _run_cluster_once(tmp_path, fastpath_flag, monkeypatch):
    from tigerbeetle_tpu.client import Client
    from tigerbeetle_tpu.runtime.server import format_data_file

    monkeypatch.setenv("TB_FASTPATH_DECODE", fastpath_flag)
    tmp_path.mkdir(parents=True, exist_ok=True)
    n_sessions = max(1, int(os.environ.get("BENCH_REPL_SESSIONS", "2")))
    ports = _free_ports(N_REPLICAS)
    addresses = [f"127.0.0.1:{p}" for p in ports]
    paths = [str(tmp_path / f"r{i}.tb") for i in range(N_REPLICAS)]
    for i in range(N_REPLICAS):
        format_data_file(
            paths[i], cluster=CLUSTER, replica_index=i,
            replica_count=N_REPLICAS, config=cfg.TEST_MIN,
        )
    servers = [
        _Server(paths[i], addresses, i) for i in range(N_REPLICAS)
    ]
    clients = []
    reply_bodies: dict = {}
    try:
        for r in servers:
            # Group commit must be live on the real server storage.
            assert r.server.replica._gc_enabled
        addr = ",".join(addresses)
        setup = Client(addr, CLUSTER, client_id=50, timeout_ms=30_000)
        clients.append(setup)
        assert setup.create_accounts(
            [{"id": 1, "ledger": 1, "code": 1},
             {"id": 2, "ledger": 1, "code": 1}]
        ) == []

        errors = []

        def transfer_body(tid, dr, cr):
            row = np.zeros(1, types.TRANSFER_DTYPE)
            row["id_lo"] = tid
            row["debit_account_id_lo"] = dr
            row["credit_account_id_lo"] = cr
            row["amount_lo"] = 1
            row["ledger"] = 1
            row["code"] = 1
            return row.tobytes()

        def drive(s):
            try:
                c = Client(addr, CLUSTER, client_id=100 + s,
                           timeout_ms=30_000)
                clients.append(c)
                base = 1000 * (s + 1)
                bodies = []
                for k in range(TRANSFERS_PER_SESSION):
                    reply = c._native.request(
                        types.Operation.create_transfers,
                        transfer_body(base + k, 1, 2), 30_000,
                    )
                    assert reply == b"", reply
                    bodies.append(reply)
                # Deliberate failure so the compared reply bytes are
                # non-trivial: debit == credit must come back as
                # accounts_must_be_different, identically in both arms.
                reply = c._native.request(
                    types.Operation.create_transfers,
                    transfer_body(base + 900, 1, 1), 30_000,
                )
                res = np.frombuffer(reply, types.CREATE_RESULT_DTYPE)
                assert len(res) == 1 and int(res[0]["result"]) == int(
                    types.CreateTransferResult.accounts_must_be_different
                ), res
                bodies.append(reply)
                reply_bodies[s] = bodies
            except Exception as exc:  # noqa: BLE001
                errors.append(f"session {s}: {exc!r}")

        threads = [
            threading.Thread(target=drive, args=(s,), daemon=True)
            for s in range(n_sessions)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert errors == [], errors

        rows = setup.lookup_accounts([1, 2])
        total = n_sessions * TRANSFERS_PER_SESSION
        assert types.u128_get(rows[0], "debits_posted") == total
        assert types.u128_get(rows[1], "credits_posted") == total

        # Counter-verified group commit: the covering-sync machinery
        # ran on the primary, and the contract-side bookkeeping is
        # clean (nothing deferred forever, nothing left unsynced).
        primary = servers[0].server.replica
        backup = servers[1].server.replica
        assert primary.stat_gc_flushes > 0
        assert backup.stat_prepares_written >= total // 30  # batched
        for r in servers:
            assert r.server.replica.journal.unsynced_writes == 0
            assert not r.server.replica._gc_pending
        # Both replicas committed the full stream (backup learns via
        # piggybacked commit numbers/heartbeats within a tick or two).
        assert primary.commit_min >= backup.commit_min >= 0

        # Proof-of-state query (state_machine/commitment.py): both
        # replicas answer the sessionless `state_root` op with the
        # SAME nonzero 16-byte root once converged — the wire-level
        # rendering of the hash-log convergence claim.  Run BEFORE the
        # scrape so the stashed snapshots are quiescent on both roles
        # (the backup has committed the full tail; the r23 hash-ratio
        # smoke compares bytes_hashed against committed_body_bytes and
        # a mid-catch-up backup would under-count the denominator).
        from tigerbeetle_tpu.obs.scrape import scrape_state_root

        roots = {}
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            roots = {
                i: scrape_state_root(addresses[i], CLUSTER,
                                     timeout_ms=20_000)
                for i in range(len(servers))
            }
            if len({cm for _root, cm in roots.values()}) == 1:
                break
            time.sleep(0.2)  # backup still applying the tail
        assert len({root for root, _cm in roots.values()}) == 1, roots
        assert roots[0][0] != bytes(16)
        assert roots[0][0] == servers[0].server.replica.sm.state_root()

        # Live scrape (obs/scrape.py): the `stats` wire op answers
        # from the same registry the in-process handles feed, and the
        # fsync/prepare counters satisfy the r10 group-commit
        # contract — one covering sync amortized over many prepares,
        # never an ack-relevant prepare left uncovered.
        from tigerbeetle_tpu.obs.scrape import scrape_stats

        for i, server in enumerate(servers):
            snap = scrape_stats(addresses[i], CLUSTER, timeout_ms=20_000)
            drain_scrapes.append(snap)
            assert snap["replica"] == i
            # r22 drain forensics are always scrape-visible, whichever
            # arm ran (the smoke above asserts the arm-specific values).
            assert "vsr.drain.native_calls" in snap
            assert "vsr.drain.py_fallbacks" in snap
            r = server.server.replica
            # Quiescent counters must agree bit-for-bit with the
            # in-process registry (drain histograms keep moving with
            # heartbeats; durability counters do not).
            assert snap["vsr.prepares_written"] == r.stat_prepares_written
            assert snap["vsr.gc_flushes"] == r.stat_gc_flushes
            assert snap["storage.fsyncs"] == server.server.storage.stat_fsyncs
            assert snap["vsr.commit_min"] == r.commit_min
            assert snap["version"] > 0
            # Columnar-ingest contract: the forced arm is the arm that
            # actually ran — nonzero batch-decode hits when on, zero
            # when off — and a native-capable build never fell back.
            if fastpath_flag == "1":
                assert snap["fastpath.batch_decode_hits"] > 0
                if not snap["fastpath.native_unavailable"]:
                    assert snap["fastpath.batch_decode_fallbacks"] == 0
                assert snap["server.decode_us_per_event.count"] > 0
            else:
                assert snap["fastpath.batch_decode_hits"] == 0
            if i == 0:
                # r20 per-prepare instrument: the primary timed every
                # header-build + bookkeeping span, and the histogram
                # reaches the scrape under the replica registry's
                # "vsr." graft.
                assert snap["vsr.prepare_us.count"] > 0
                assert snap["vsr.prepare_us.p50"] > 0
                assert snap["vsr.gc_flushes"] > 0
                # r10 contract: group commit => fewer covering syncs
                # than WAL appends once load overlaps (each flush
                # covers a whole drain), and every sync accounted.
                assert snap["vsr.gc_flushes"] <= snap["vsr.prepares_written"]
                assert snap["storage.fsyncs"] > 0
            else:
                # Backup-side instrument: every accepted prepare timed
                # its prepare_ok build span.
                assert snap["vsr.prepare_ok_us.count"] > 0

        return reply_bodies
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        for r in servers:
            r.close()
