"""Aux subsystems: tracer, statsd, AOF, grid scrubber."""

import json
import socket

import numpy as np

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.testing.harness import account, pack, transfer
from tigerbeetle_tpu.utils.statsd import StatsD
from tigerbeetle_tpu.utils.tracer import Tracer
from tigerbeetle_tpu.vsr import aof as aof_mod
from tigerbeetle_tpu.vsr import replica as vsr_replica
from tigerbeetle_tpu.vsr.grid import Grid
from tigerbeetle_tpu.vsr.scrubber import GridScrubber
from tigerbeetle_tpu.vsr.storage import MemoryStorage, ZoneLayout


def test_tracer_spans():
    t = Tracer(backend="json")
    with t.span("commit"):
        with t.span("state_machine_commit"):
            pass
    doc = json.loads(t.dump())
    names = [e["name"] for e in doc["traceEvents"]]
    assert names == ["state_machine_commit", "commit"]
    assert all(e["dur"] >= 0 for e in doc["traceEvents"])

    none = Tracer(backend="none")
    with none.span("commit"):
        pass
    assert json.loads(none.dump())["traceEvents"] == []


def test_tracer_counters_instants_and_bound():
    t = Tracer(backend="json", buffer_max=10)
    t.count("pipeline_depth", 3)
    t.instant("view_change", view=2)
    with t.span("commit", slot=5, op=77):
        pass
    doc = json.loads(t.dump())
    by_name = {e["name"]: e for e in doc["traceEvents"]}
    assert by_name["pipeline_depth"]["ph"] == "C"
    assert by_name["pipeline_depth"]["args"]["value"] == 3
    assert by_name["view_change"]["ph"] == "i"
    assert by_name["commit"]["tid"] == 5
    assert by_name["commit"]["args"]["op"] == 77
    # Bounded buffer: oldest events drop, drop count reported.
    for i in range(50):
        t.count("x", i)
    doc = json.loads(t.dump())
    assert len(doc["traceEvents"]) == 10
    assert doc["otherData"]["dropped_events"] == 43


def test_server_writes_trace(tmp_path):
    from tigerbeetle_tpu import constants as cfg
    from tigerbeetle_tpu.runtime.native import native_available
    from tigerbeetle_tpu.state_machine import CpuStateMachine

    if not native_available():
        pytest.skip("native runtime not built")
    from tigerbeetle_tpu.client import Client
    from tigerbeetle_tpu.runtime.server import (
        ReplicaServer,
        format_data_file,
    )

    path = str(tmp_path / "data.tigerbeetle")
    trace = str(tmp_path / "trace.json")
    format_data_file(path, cluster=1, config=cfg.TEST_MIN)
    server = ReplicaServer(
        path, cluster=1, addresses=["127.0.0.1:0"], replica_index=0,
        state_machine_factory=lambda: CpuStateMachine(cfg.TEST_MIN),
        config=cfg.TEST_MIN, trace_path=trace,
    )
    import threading

    stop = []
    thread = threading.Thread(
        target=lambda: [server.poll_once(1) for _ in iter(
            lambda: not stop, False)], daemon=True
    )
    thread.start()
    c = Client(f"127.0.0.1:{server.port}", 1, client_id=9)
    assert c.create_accounts([{"id": 1, "ledger": 1, "code": 1}]) == []
    c.close()
    stop.append(1)
    thread.join(timeout=5)
    server.close()
    doc = json.loads(open(trace).read())
    names = {e["name"] for e in doc["traceEvents"]}
    assert "state_machine_commit" in names
    assert "journal_write" in names


def test_statsd_lines():
    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    recv.settimeout(2)
    port = recv.getsockname()[1]
    s = StatsD(port=port, prefix="tb")
    s.gauge("tx_per_s", 100.5)
    s.count("batches")
    s.timing("batch_ms", 12.5)
    got = sorted(recv.recv(1024).decode() for _ in range(3))
    assert got == [
        "tb.batch_ms:12.5|ms", "tb.batches:1|c", "tb.tx_per_s:100.5|g",
    ]
    s.close()
    recv.close()


def test_aof_records_and_replays(tmp_path):
    path = str(tmp_path / "log.aof")
    storage = MemoryStorage(ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 20))
    vsr_replica.format(storage, 5)
    r = vsr_replica.Replica(
        storage, 5, CpuStateMachine(cfg.TEST_MIN), aof=aof_mod.AOF(path)
    )
    r.open()
    r.on_request(types.Operation.create_accounts, pack([account(1), account(2)]))
    r.on_request(
        types.Operation.create_transfers,
        pack([transfer(9, debit_account_id=1, credit_account_id=2, amount=11)]),
    )
    r.aof.sync()

    entries = list(aof_mod.iterate(path))
    assert len(entries) >= 2

    fresh = CpuStateMachine(cfg.TEST_MIN)
    applied = aof_mod.replay(path, fresh, cluster=5)
    assert applied >= 2
    assert fresh.snapshot() == r.sm.snapshot()

    # A torn tail entry truncates iteration, not crashes.
    with open(path, "ab") as f:
        f.write(b"\x01" * 100)
    assert len(list(aof_mod.iterate(path))) == len(entries)


def test_grid_scrubber_finds_corruption():
    storage = MemoryStorage(ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 22))
    grid = Grid(storage, block_size=4096, block_count=64)
    fs = grid.free_set
    res = fs.reserve(8)
    addrs = [fs.acquire(res) for _ in range(8)]
    fs.forfeit(res)
    for a in addrs:
        grid.write_block(a, bytes([a]) * 100)

    bad = addrs[3]
    storage.corrupt_sector(grid._offset(bad))

    scrubber = GridScrubber(grid, cycle_ticks=2, blocks_per_tick_max=4)
    found = []
    while scrubber.cycles == 0:
        found += scrubber.tick()
    assert set(found) == {bad}


def test_grid_scrubber_tour_semantics():
    """Tour machinery (reference: src/vsr/grid_scrubber.zig): a cycle
    walks a STABLE snapshot paced across cycle_ticks, skips blocks
    freed mid-tour instead of flagging their stale frames, and picks
    up new allocations on the next tour."""
    storage = MemoryStorage(ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 22))
    grid = Grid(storage, block_size=4096, block_count=64)
    fs = grid.free_set
    res = fs.reserve(16)
    addrs = [fs.acquire(res) for _ in range(16)]
    fs.forfeit(res)
    for a in addrs:
        grid.write_block(a, bytes([a]) * 64)

    scrubber = GridScrubber(grid, cycle_ticks=4, blocks_per_tick_max=8)
    # Pacing: 16 blocks over 4 ticks -> 4 per tick, progress advances.
    assert scrubber.tick() == []
    assert 0.0 < scrubber.progress < 1.0
    # Release a not-yet-scrubbed block and stale its frame: the tour
    # must SKIP it — the block is leaving the live set and peers may
    # no longer serve it for repair.
    victim = addrs[-1]
    fs.release(victim)
    storage.corrupt_sector(grid._offset(victim))
    while scrubber.cycles == 0:
        assert scrubber.tick() == []
    assert scrubber.faults_found == 0

    # A block allocated after the first snapshot joins the NEXT tour:
    # corrupt it and the scrubber must find it on the following cycle.
    res = fs.reserve(1)
    fresh = fs.acquire(res)
    fs.forfeit(res)
    grid.write_block(fresh, b"fresh")
    storage.corrupt_sector(grid._offset(fresh))  # verify_block reads disk
    found = []
    start_cycles = scrubber.cycles
    while scrubber.cycles < start_cycles + 2:
        found += scrubber.tick()
    assert fresh in found
    assert victim not in found


# ---------------------------------------------------------------------------
# RunIndex: run-compressed id directory (utils/hashindex.py).


def _u64(*vals):
    return np.array(vals, np.uint64)


def test_runindex_sequential_batches_merge_and_lookup():
    from tigerbeetle_tpu.utils import RunIndex

    ix = RunIndex()
    ix.insert(np.arange(1, 8191, dtype=np.uint64), np.zeros(8190, np.uint64),
              np.arange(0, 8190, dtype=np.uint64))
    ix.insert(np.arange(8191, 16381, dtype=np.uint64), np.zeros(8190, np.uint64),
              np.arange(8190, 16380, dtype=np.uint64))
    assert ix.count == 16380
    found, vals = ix.lookup(_u64(1, 16380, 16381), _u64(0, 0, 0))
    assert found.tolist() == [True, True, False]
    assert vals[0] == 0 and vals[1] == 16379


def test_runindex_hash_fallback_and_mixed_lookup():
    from tigerbeetle_tpu.utils import RunIndex

    ix = RunIndex()
    ix.insert(np.arange(10, 20, dtype=np.uint64), np.zeros(10, np.uint64),
              np.arange(10, dtype=np.uint64))
    ix.insert(_u64(500, 7, 99), _u64(0, 0, 0), _u64(100, 101, 102))  # not a run
    found, vals = ix.lookup(_u64(12, 7, 8), _u64(0, 0, 0))
    assert found.tolist() == [True, True, False]
    assert vals[0] == 2 and vals[1] == 101


def test_runindex_remove_splits_and_empties_runs():
    from tigerbeetle_tpu.utils import RunIndex

    ix = RunIndex()
    ix.insert(np.arange(10, 15, dtype=np.uint64), np.zeros(5, np.uint64),
              np.arange(5, dtype=np.uint64))
    ix.remove(_u64(12), _u64(0))  # split middle
    found, vals = ix.lookup(np.arange(10, 15, dtype=np.uint64), np.zeros(5, np.uint64))
    assert found.tolist() == [True, True, False, True, True]
    assert vals[[0, 1, 3, 4]].tolist() == [0, 1, 3, 4]
    ix.remove(_u64(10), _u64(0))  # shrink head
    ix.remove(_u64(14), _u64(0))  # shrink tail
    ix.remove(_u64(11), _u64(0))  # empty first run
    ix.remove(_u64(13), _u64(0))  # empty last run -> group removed
    assert ix.count == 0
    found, _ = ix.lookup(_u64(13), _u64(0))  # must not crash on empty group
    assert not found.any()
    # Reinsert after emptying works.
    ix.insert(np.arange(10, 12, dtype=np.uint64), np.zeros(2, np.uint64),
              _u64(7, 8))
    found, vals = ix.lookup(_u64(11), _u64(0))
    assert found[0] and vals[0] == 8


def test_runindex_rejects_wraparound_run():
    from tigerbeetle_tpu.utils import RunIndex

    ix = RunIndex()
    lo = _u64(2**64 - 1, 0)
    ix.insert(lo, _u64(7, 7), _u64(0, 1))
    found, vals = ix.lookup(lo, _u64(7, 7))
    assert found.all() and vals.tolist() == [0, 1]


# ---------------------------------------------------------------------------
# Binding generation (bindings.py — reference: src/*_bindings.zig).


def test_bindings_c_header_compiles_with_size_asserts(tmp_path):
    import shutil
    import subprocess

    import pytest

    if shutil.which("g++") is None:
        pytest.skip("no C++ compiler on this host")
    from tigerbeetle_tpu import bindings

    paths = bindings.generate(str(tmp_path))
    header = next(p for p in paths if p.endswith("tb_types.h"))
    # The _Static_asserts make the compiler verify every wire layout.
    src = tmp_path / "check.c"
    src.write_text(f'#include "{header}"\nint main(void) {{ return 0; }}\n')
    subprocess.run(
        ["g++", "-x", "c", "-std=c11", "-Wall", "-Werror", "-fsyntax-only",
         str(src)],
        check=True, capture_output=True,
    )
    # ABI consistency: compiling the header TOGETHER with the actual
    # native runtime makes any signature drift a compile error.
    import os

    runtime = os.path.join(os.path.dirname(__file__), "..", "native",
                           "tb_runtime.cpp")
    both = tmp_path / "abi_check.cpp"
    both.write_text(
        f'#include "{header}"\n#include "{os.path.abspath(runtime)}"\n'
    )
    subprocess.run(
        ["g++", "-std=c++17", "-fsyntax-only", str(both)],
        check=True, capture_output=True,
    )


def test_bindings_cover_all_enums_and_fields(tmp_path):
    from tigerbeetle_tpu import bindings

    bindings.generate(str(tmp_path))
    ts = (tmp_path / "types.ts").read_text()
    go = (tmp_path / "types.go").read_text()
    c = (tmp_path / "tb_types.h").read_text()
    # Every CreateTransferResult code appears in every language.
    for member in types.CreateTransferResult:
        assert f"  {member.name}: {member.value}," in ts
        camel = "".join(p.capitalize() for p in member.name.split("_"))
        assert f"CreateTransferResult{camel} CreateTransferResult = {member.value}" in go
        assert (
            f"TB_CREATE_TRANSFER_RESULT_{member.name.upper()} = {member.value},"
            in c
        )
    # u128 fields collapse to one logical field in TS/Go.
    assert "id: bigint;" in ts and "Id [2]uint64" in go
    # The C structs keep raw limb layout for ABI fidelity.
    assert "uint64_t id_lo;" in c and "uint64_t id_hi;" in c
    assert "tb_client_request" in c
