"""Aux subsystems: tracer, statsd, AOF, grid scrubber."""

import json
import socket

import numpy as np

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.testing.harness import account, pack, transfer
from tigerbeetle_tpu.utils.statsd import StatsD
from tigerbeetle_tpu.utils.tracer import Tracer
from tigerbeetle_tpu.vsr import aof as aof_mod
from tigerbeetle_tpu.vsr import replica as vsr_replica
from tigerbeetle_tpu.vsr.grid import Grid
from tigerbeetle_tpu.vsr.scrubber import GridScrubber
from tigerbeetle_tpu.vsr.storage import MemoryStorage, ZoneLayout


def test_tracer_spans():
    t = Tracer(backend="json")
    with t.span("commit"):
        with t.span("state_machine_commit"):
            pass
    doc = json.loads(t.dump())
    names = [e["name"] for e in doc["traceEvents"]]
    assert names == ["state_machine_commit", "commit"]
    assert all(e["dur"] >= 0 for e in doc["traceEvents"])

    none = Tracer(backend="none")
    with none.span("commit"):
        pass
    assert json.loads(none.dump())["traceEvents"] == []


def test_statsd_lines():
    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    recv.settimeout(2)
    port = recv.getsockname()[1]
    s = StatsD(port=port, prefix="tb")
    s.gauge("tx_per_s", 100.5)
    s.count("batches")
    s.timing("batch_ms", 12.5)
    got = sorted(recv.recv(1024).decode() for _ in range(3))
    assert got == [
        "tb.batch_ms:12.5|ms", "tb.batches:1|c", "tb.tx_per_s:100.5|g",
    ]
    s.close()
    recv.close()


def test_aof_records_and_replays(tmp_path):
    path = str(tmp_path / "log.aof")
    storage = MemoryStorage(ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 20))
    vsr_replica.format(storage, 5)
    r = vsr_replica.Replica(
        storage, 5, CpuStateMachine(cfg.TEST_MIN), aof=aof_mod.AOF(path)
    )
    r.open()
    r.on_request(types.Operation.create_accounts, pack([account(1), account(2)]))
    r.on_request(
        types.Operation.create_transfers,
        pack([transfer(9, debit_account_id=1, credit_account_id=2, amount=11)]),
    )
    r.aof.sync()

    entries = list(aof_mod.iterate(path))
    assert len(entries) >= 2

    fresh = CpuStateMachine(cfg.TEST_MIN)
    applied = aof_mod.replay(path, fresh, cluster=5)
    assert applied >= 2
    assert fresh.snapshot() == r.sm.snapshot()

    # A torn tail entry truncates iteration, not crashes.
    with open(path, "ab") as f:
        f.write(b"\x01" * 100)
    assert len(list(aof_mod.iterate(path))) == len(entries)


def test_grid_scrubber_finds_corruption():
    storage = MemoryStorage(ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 22))
    grid = Grid(storage, block_size=4096, block_count=64)
    fs = grid.free_set
    res = fs.reserve(8)
    addrs = [fs.acquire(res) for _ in range(8)]
    fs.forfeit(res)
    for a in addrs:
        grid.write_block(a, bytes([a]) * 100)

    bad = addrs[3]
    storage.corrupt_sector(grid._offset(bad))

    scrubber = GridScrubber(grid, blocks_per_tick=4)
    found = []
    while scrubber.cycles == 0:
        found += scrubber.tick()
    assert set(found) == {bad}
