"""Sanitizer replay driver (run by tests/test_sanitizers.py, or by
hand — see experiments/README.md):

    make -C native asan
    LD_PRELOAD="$(gcc -print-file-name=libasan.so)" \
    ASAN_OPTIONS=detect_leaks=0 TB_NATIVE_SANITIZE=asan \
    JAX_PLATFORMS=cpu python tests/asan_replay.py

Drives the fixture differential from tests/test_fastpath_decode.py
plus a torn-frame / oversize-frame fuzz through the SANITIZED native
libraries (native/asan/): batch frame verification vs the Python
oracle over the checked-in frames and their corrupt mutations, batch
reply finalize parity, seeded random tearing of the fixture stream
through the native bus framing, and oversize size-field frames that
must drop the connection without touching out-of-bounds memory.
Exits 0 with the final OK marker only if every differential holds;
address/UB findings abort the process with a sanitizer report the
caller parses.
"""

import json
import os
import socket
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tigerbeetle_tpu.runtime import fastpath  # noqa: E402
from tigerbeetle_tpu.runtime.native import (  # noqa: E402
    EV_CLOSED,
    EV_MESSAGE,
    NativeBus,
    native_available,
)
from tigerbeetle_tpu.vsr import wire  # noqa: E402

HEADER_SIZE = 256
FIXTURES = os.path.join(REPO, "clients", "fixtures")


def fixture_frames() -> list:
    with open(os.path.join(FIXTURES, "frames.json")) as fh:
        return [bytes.fromhex(c["frame_hex"]) for c in json.load(fh)]


def mutations(frames: list) -> list:
    """Same corrupt variants the tier-1 differential uses (flipped
    body/header bytes, wrong version, lying size field)."""
    out = list(frames)
    body_frame = next(f for f in frames if len(f) > HEADER_SIZE)
    flipped_body = bytearray(body_frame)
    flipped_body[HEADER_SIZE + 3] ^= 0xFF
    out.append(bytes(flipped_body))
    flipped_header = bytearray(frames[0])
    flipped_header[40] ^= 0x01
    out.append(bytes(flipped_header))
    bad_version = bytearray(frames[0])
    bad_version[155] = 99
    out.append(bytes(bad_version))
    lying_size = bytearray(body_frame)
    lying_size[144:148] = (len(body_frame) + 128).to_bytes(4, "little")
    out.append(bytes(lying_size))
    return out


def arena_of(frames: list):
    blob = b"".join(frames)
    arena = np.frombuffer(blob, np.uint8)
    offsets = np.zeros(len(frames), np.uint64)
    lens = np.zeros(len(frames), np.uint32)
    at = 0
    for i, f in enumerate(frames):
        offsets[i] = at
        lens[i] = len(f)
        at += len(f)
    return arena, offsets, lens


def check_fixture_differential() -> None:
    frames = mutations(fixture_frames())
    arena, offsets, lens = arena_of(frames)
    legacy = []
    for f in frames:
        h = wire.header_from_bytes(f[:HEADER_SIZE])
        legacy.append(int(wire.verify_header(h, f[HEADER_SIZE:])))
    ok_native = fastpath.verify_frames(arena, offsets, lens, len(frames))
    assert ok_native is not None, "sanitized fastpath lacks verify"
    assert [int(v) for v in ok_native] == legacy, "verify differential"
    ok_py = fastpath.verify_frames_py(arena, offsets, lens, len(frames))
    assert [int(v) for v in ok_py] == legacy, "python oracle drifted"
    print("asan-replay: fixture differential ok "
          f"({len(frames)} frames incl. corrupt mutations)")


def check_finalize_parity() -> None:
    bodies = [b"", b"r" * 333, bytes(range(128)) * 5, b"x" * 8190]
    hdrs = np.zeros(len(bodies), wire.HEADER_DTYPE)
    hdrs["version"] = wire.VERSION
    hdrs["command"] = int(wire.Command.reply)
    hdrs["request"] = np.arange(len(bodies))
    oracle = hdrs.copy()
    wire.finalize_headers_py(oracle, bodies)
    assert fastpath.finalize_headers(hdrs, bodies), "native finalize"
    assert hdrs.tobytes() == oracle.tobytes(), "finalize parity"
    print("asan-replay: batch finalize parity ok")


def check_torn_frames(seed: int = 4242, rounds: int = 8) -> None:
    """The fixture stream torn at seeded-random boundaries through the
    native bus framing: every frame must reassemble byte-identically,
    every round, with the sanitizer watching the C framing buffers."""
    frames = fixture_frames()
    stream = b"".join(frames)
    rng = np.random.default_rng(seed)
    for _round in range(rounds):
        bus = NativeBus(1 << 20)
        port = bus.listen("127.0.0.1", 0)
        sock = socket.create_connection(("127.0.0.1", port), timeout=30)
        got: list = []

        def drain(timeout_ms: int) -> None:
            r = bus.poll_drain(timeout_ms)
            if r is None:
                raise AssertionError("sanitized bus lacks poll_drain")
            n, types, _conns, offs, lens, arena = r
            for i in range(n):
                if types[i] == EV_MESSAGE:
                    lo = int(offs[i])
                    got.append(bytes(arena[lo : lo + int(lens[i])]))

        at = 0
        while at < len(stream):
            n = int(rng.integers(1, 512))
            sock.sendall(stream[at : at + n])
            at += n
            drain(0)
        deadline = time.time() + 30
        while len(got) < len(frames) and time.time() < deadline:
            drain(10)
        assert got == frames, (
            f"torn round {_round}: {len(got)}/{len(frames)} frames"
        )
        sock.close()
        bus.close()
    print(f"asan-replay: torn-frame fuzz ok ({rounds} rounds)")


def check_oversize_frames() -> None:
    """Size fields past the frame bound (message_size_max bodies +
    the 256-byte header) must drop the connection — never index the
    framing buffer out of bounds.  Probed at bound+1, bound+4096, and
    a u32 in the sign-bit range."""
    max_size = 1 << 20
    bound = max_size + HEADER_SIZE
    for oversize in (bound + 1, bound + 4096, (1 << 31) + 7):
        bus = NativeBus(max_size)
        port = bus.listen("127.0.0.1", 0)
        sock = socket.create_connection(("127.0.0.1", port), timeout=30)
        h = wire.make_header(command=wire.Command.request, cluster=1)
        h["size"] = oversize & 0xFFFFFFFF
        sock.sendall(h.tobytes())
        closed = False
        deadline = time.time() + 30
        while not closed and time.time() < deadline:
            for t, _c, _p in bus.poll(10):
                if t == EV_CLOSED:
                    closed = True
        assert closed, f"oversize {oversize} did not drop the conn"
        sock.close()
        bus.close()
    print("asan-replay: oversize-frame fuzz ok")


def main() -> int:
    assert native_available(), "sanitized native runtime failed to load"
    assert fastpath.available(), "sanitized fastpath failed to load"
    check_fixture_differential()
    check_finalize_parity()
    check_torn_frames()
    check_oversize_frames()
    print("ASAN-REPLAY-OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
