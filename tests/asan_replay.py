"""Sanitizer replay driver (run by tests/test_sanitizers.py, or by
hand — see experiments/README.md):

    make -C native asan
    LD_PRELOAD="$(gcc -print-file-name=libasan.so)" \
    ASAN_OPTIONS=detect_leaks=0 TB_NATIVE_SANITIZE=asan \
    JAX_PLATFORMS=cpu python tests/asan_replay.py

Drives the fixture differential from tests/test_fastpath_decode.py
plus a torn-frame / oversize-frame fuzz through the SANITIZED native
libraries (native/asan/): batch frame verification vs the Python
oracle over the checked-in frames and their corrupt mutations, batch
reply finalize parity, seeded random tearing of the fixture stream
through the native bus framing, the round-20 pipeline entry points
(fuzzed prepare/ack sequences incl. torn WAL framing, oversize ops,
and out-of-order prepare_oks), the round-22 batch drain entry points
(multi-frame drains with chained parents and packed WAL arenas,
shuffled ack runs laced with duplicates / stale siblings / foreign
clusters / wrong views / unknown ops, commit-ready runs,
message_size_max bodies, and the scatter-gather sendv path torn
across socket reads), and oversize size-field frames that must drop
the connection without touching out-of-bounds memory.
Exits 0 with the final OK marker only if every differential holds;
address/UB findings abort the process with a sanitizer report the
caller parses.
"""

import json
import os
import socket
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tigerbeetle_tpu.runtime import fastpath  # noqa: E402
from tigerbeetle_tpu.runtime.native import (  # noqa: E402
    EV_CLOSED,
    EV_MESSAGE,
    NativeBus,
    native_available,
)
from tigerbeetle_tpu.vsr import wire  # noqa: E402

HEADER_SIZE = 256
FIXTURES = os.path.join(REPO, "clients", "fixtures")


def fixture_frames() -> list:
    with open(os.path.join(FIXTURES, "frames.json")) as fh:
        return [bytes.fromhex(c["frame_hex"]) for c in json.load(fh)]


def mutations(frames: list) -> list:
    """Same corrupt variants the tier-1 differential uses (flipped
    body/header bytes, wrong version, lying size field)."""
    out = list(frames)
    body_frame = next(f for f in frames if len(f) > HEADER_SIZE)
    flipped_body = bytearray(body_frame)
    flipped_body[HEADER_SIZE + 3] ^= 0xFF
    out.append(bytes(flipped_body))
    flipped_header = bytearray(frames[0])
    flipped_header[40] ^= 0x01
    out.append(bytes(flipped_header))
    bad_version = bytearray(frames[0])
    bad_version[155] = 99
    out.append(bytes(bad_version))
    lying_size = bytearray(body_frame)
    lying_size[144:148] = (len(body_frame) + 128).to_bytes(4, "little")
    out.append(bytes(lying_size))
    return out


def arena_of(frames: list):
    blob = b"".join(frames)
    arena = np.frombuffer(blob, np.uint8)
    offsets = np.zeros(len(frames), np.uint64)
    lens = np.zeros(len(frames), np.uint32)
    at = 0
    for i, f in enumerate(frames):
        offsets[i] = at
        lens[i] = len(f)
        at += len(f)
    return arena, offsets, lens


def check_fixture_differential() -> None:
    frames = mutations(fixture_frames())
    arena, offsets, lens = arena_of(frames)
    legacy = []
    for f in frames:
        h = wire.header_from_bytes(f[:HEADER_SIZE])
        legacy.append(int(wire.verify_header(h, f[HEADER_SIZE:])))
    ok_native = fastpath.verify_frames(arena, offsets, lens, len(frames))
    assert ok_native is not None, "sanitized fastpath lacks verify"
    assert [int(v) for v in ok_native] == legacy, "verify differential"
    ok_py = fastpath.verify_frames_py(arena, offsets, lens, len(frames))
    assert [int(v) for v in ok_py] == legacy, "python oracle drifted"
    print("asan-replay: fixture differential ok "
          f"({len(frames)} frames incl. corrupt mutations)")


def check_finalize_parity() -> None:
    bodies = [b"", b"r" * 333, bytes(range(128)) * 5, b"x" * 8190]
    hdrs = np.zeros(len(bodies), wire.HEADER_DTYPE)
    hdrs["version"] = wire.VERSION
    hdrs["command"] = int(wire.Command.reply)
    hdrs["request"] = np.arange(len(bodies))
    oracle = hdrs.copy()
    wire.finalize_headers_py(oracle, bodies)
    assert fastpath.finalize_headers(hdrs, bodies), "native finalize"
    assert hdrs.tobytes() == oracle.tobytes(), "finalize parity"
    print("asan-replay: batch finalize parity ok")


def check_torn_frames(seed: int = 4242, rounds: int = 8) -> None:
    """The fixture stream torn at seeded-random boundaries through the
    native bus framing: every frame must reassemble byte-identically,
    every round, with the sanitizer watching the C framing buffers."""
    frames = fixture_frames()
    stream = b"".join(frames)
    rng = np.random.default_rng(seed)
    for _round in range(rounds):
        bus = NativeBus(1 << 20)
        port = bus.listen("127.0.0.1", 0)
        sock = socket.create_connection(("127.0.0.1", port), timeout=30)
        got: list = []

        def drain(timeout_ms: int) -> None:
            r = bus.poll_drain(timeout_ms)
            if r is None:
                raise AssertionError("sanitized bus lacks poll_drain")
            n, types, _conns, offs, lens, arena = r
            for i in range(n):
                if types[i] == EV_MESSAGE:
                    lo = int(offs[i])
                    got.append(bytes(arena[lo : lo + int(lens[i])]))

        at = 0
        while at < len(stream):
            n = int(rng.integers(1, 512))
            sock.sendall(stream[at : at + n])
            at += n
            drain(0)
        deadline = time.time() + 30
        while len(got) < len(frames) and time.time() < deadline:
            drain(10)
        assert got == frames, (
            f"torn round {_round}: {len(got)}/{len(frames)} frames"
        )
        sock.close()
        bus.close()
    print(f"asan-replay: torn-frame fuzz ok ({rounds} rounds)")


def _r64(rng) -> int:
    return int(rng.integers(0, 1 << 64, dtype=np.uint64))


def _r128(rng) -> int:
    return _r64(rng) | (_r64(rng) << 64)


def check_pipeline_fuzz(seed: int = 2020, rounds: int = 60) -> None:
    """Round-20 pipeline entry points under the sanitizer: fuzzed
    prepare/ack sequences (out-of-order and stale prepare_oks, dup
    acks, unknown ops), torn WAL framing (slots re-framed mid-ring
    with different prepares), and oversize ops (message_size_max
    bodies) — every byte differential against the wire.py/journal.py
    Python oracles while asan watches the C builders and slot table."""
    from tigerbeetle_tpu.vsr.journal import HEADERS_PER_SECTOR
    from tigerbeetle_tpu.vsr.storage import _sectors

    assert fastpath.pipeline_available(), (
        f"sanitized fastpath lacks pipeline: {fastpath.pipeline_error()}"
    )
    sector_size = 4096
    slot_count = 32
    assert slot_count % HEADERS_PER_SECTOR == 0
    rng = np.random.default_rng(seed)
    pl = fastpath.create_pipeline()
    ring_c = np.zeros(slot_count, wire.HEADER_DTYPE)
    ring_py = np.zeros(slot_count, wire.HEADER_DTYPE)
    max_body = 1 << 20  # message_size_max: the oversize-op bound
    scratch_prepare = np.zeros(_sectors(HEADER_SIZE + max_body), np.uint8)
    scratch_sector = np.zeros(sector_size, np.uint8)
    for i in range(rounds):
        # Oversize op every 8th round, torn re-frames from slot reuse
        # (op % slot_count collides across rounds by construction).
        body_len = max_body if i % 8 == 7 else int(rng.integers(0, 8192))
        body = rng.bytes(body_len)
        req = wire.make_header(
            command=wire.Command.request,
            operation=int(rng.integers(0, 200)),
            cluster=_r64(rng), client=_r128(rng) or 1,
            request=int(rng.integers(0, 1 << 32)),
            timestamp=_r64(rng) >> 1,
            trace_id=_r64(rng), trace_ts=_r64(rng),
            trace_flags=int(rng.integers(0, 2)),
        )
        wire.finalize_header(req, body)
        op = int(rng.integers(1, 4 * slot_count))
        kw = dict(
            cluster=_r128(rng) >> 1, view=int(rng.integers(0, 1 << 31)),
            op=op, commit=_r64(rng) >> 2, timestamp=_r64(rng) >> 1,
            parent=_r128(rng) >> 1, replica=int(rng.integers(0, 6)),
            context=int(rng.integers(0, 64)),
            release=int(rng.integers(0, 1 << 31)),
        )
        prepare = pl.build_prepare(req, body, **kw)
        oracle = wire.make_header(
            command=wire.Command.prepare, operation=int(req["operation"]),
            client=wire.u128(req, "client"), request=int(req["request"]),
            **kw,
        )
        wire.copy_trace(oracle, req)
        wire.finalize_header(oracle, body)
        assert prepare.tobytes() == oracle.tobytes(), "prepare differential"
        # Torn WAL framing: the slot may already hold an older prepare.
        slot = op % slot_count
        padded_len = fastpath.frame_prepare(
            prepare, body, ring_c, slot, HEADERS_PER_SECTOR, sector_size,
            scratch_prepare, scratch_sector,
        )
        msg = prepare.tobytes() + body
        padded_py = msg.ljust(_sectors(len(msg)), b"\x00")
        ring_py[slot] = prepare
        first = slot // HEADERS_PER_SECTOR * HEADERS_PER_SECTOR
        sector_py = ring_py[
            first : first + HEADERS_PER_SECTOR
        ].tobytes().ljust(sector_size, b"\x00")
        assert padded_len == len(padded_py), "framing length differential"
        assert scratch_prepare.tobytes()[:padded_len] == padded_py
        assert scratch_sector.tobytes() == sector_py, "sector differential"
        # Fuzzed ack sequence: out-of-order replicas, duplicates, a
        # stale-sibling checksum, and an unknown op — vote counts must
        # stay exact-checksum popcounts, never a stray read or write.
        pl.note_prepare(prepare, bool(rng.integers(0, 2)), kw["replica"])
        replicas = rng.permutation(6)
        votes = {kw["replica"]}
        for rep in replicas:
            ok = pl.build_prepare_ok(prepare, kw["view"], int(rep))
            n = pl.on_ack(ok)
            votes.add(int(rep))
            assert n == len(votes), "vote differential"
            if rng.integers(0, 3) == 0:
                assert pl.on_ack(ok) == len(votes)  # dup ack: no-op
        stale = wire.make_header(
            command=wire.Command.prepare_ok, op=op, replica=1,
            context=123456789,
        )
        wire.finalize_header(stale, b"")
        assert pl.on_ack(stale) is None, "stale ack must not vote"
        unknown = pl.build_prepare_ok(prepare, kw["view"], 1)
        unknown["op"] = op + (1 << 40)
        wire.finalize_header(unknown, b"")
        assert pl.on_ack(unknown) is None, "unknown op must not vote"
        pl.mark_all_synced()
        assert pl.commit_ready(op - 1, 2), "gate differential"
        if rng.integers(0, 2):
            pl.drop(op)
        else:
            pl.reset()
        assert pl.size() == 0
    assert ring_c.tobytes() == ring_py.tobytes(), "ring differential"
    print(f"asan-replay: pipeline fuzz ok ({rounds} rounds)")


def check_drain_fuzz(seed: int = 2222, rounds: int = 40) -> None:
    """Round-22 batch drain entry points under the sanitizer: whole
    multi-frame drains through tb_pl_build_prepares (chained parents,
    WAL arena packing, slot re-frames torn across rounds) and
    tb_pl_accept_prepares (backup framing + prepare_ok builds), acks
    voted through tb_pl_on_acks in shuffled runs laced with
    duplicates, stale siblings, foreign clusters, wrong views and
    unknown ops, and the commit gate answered by
    tb_pl_commit_ready_run — every byte and verdict differential
    against the r20 scalar entry points (themselves oracle-checked
    above), including message_size_max bodies."""
    from tigerbeetle_tpu.vsr.journal import HEADERS_PER_SECTOR
    from tigerbeetle_tpu.vsr.storage import _sectors

    assert fastpath.drain_available(), (
        f"sanitized fastpath lacks drain symbols: {fastpath.drain_error()}"
    )
    sector_size = 4096
    slot_count = 32
    max_body = 1 << 20
    rng = np.random.default_rng(seed)
    pl_c = fastpath.create_pipeline()
    pl_py = fastpath.create_pipeline()
    backup = fastpath.create_pipeline()
    ring_primary = np.zeros(slot_count, wire.HEADER_DTYPE)
    ring_oracle = np.zeros(slot_count, wire.HEADER_DTYPE)
    ring_backup = np.zeros(slot_count, wire.HEADER_DTYPE)
    cluster = 7_000_000_000_000_000_001
    view = 9
    op_next = 1
    for i in range(rounds):
        k = int(rng.integers(1, 7))
        bodies = []
        reqs = np.zeros(k, wire.HEADER_DTYPE)
        for j in range(k):
            body_len = (
                max_body if (i % 6 == 5 and j == 0)
                else int(rng.integers(0, 4096))
            )
            body = rng.bytes(body_len)
            req = wire.make_header(
                command=wire.Command.request,
                operation=int(rng.integers(0, 200)),
                cluster=cluster, client=_r128(rng) or 1,
                request=int(rng.integers(0, 1 << 32)),
                timestamp=_r64(rng) >> 1,
                trace_id=_r64(rng), trace_ts=_r64(rng),
                trace_flags=int(rng.integers(0, 2)),
            )
            wire.finalize_header(req, body)
            reqs[j] = req
            bodies.append(body)
        op0 = op_next
        op_next += k
        timestamps = rng.integers(1, 1 << 62, k, dtype=np.uint64)
        contexts = rng.integers(0, 64, k, dtype=np.uint64)
        parent = _r128(rng) >> 1
        kw = dict(
            cluster=cluster, view=view, commit=op0 - 1, replica=0,
            release=1,
        )
        built = fastpath.build_prepares(
            pl_c, reqs, bodies, timestamps, contexts, op0=op0,
            parent=parent, synced=bool(rng.integers(0, 2)),
            headers_ring=ring_primary, slot_count=slot_count,
            headers_per_sector=HEADERS_PER_SECTOR,
            sector_size=sector_size, **kw,
        )
        assert built is not None, "exact-sized drain refused"
        prepares, (wal, wal_off, wal_len, slots, sectors, sec_idx) = built
        # Oracle: the scalar builder, chained by hand, framed by hand.
        chain = parent
        expect_off = 0
        for j in range(k):
            oracle = pl_py.build_prepare(
                reqs[j], bodies[j], op=op0 + j,
                timestamp=int(timestamps[j]), parent=chain,
                context=int(contexts[j]), **kw,
            )
            chain = wire.u128(oracle, "checksum")
            assert prepares[j].tobytes() == oracle.tobytes(), (
                "drain prepare differential"
            )
            msg = oracle.tobytes() + bodies[j]
            padded = msg.ljust(_sectors(len(msg)), b"\x00")
            assert int(wal_off[j]) == expect_off
            assert int(wal_len[j]) == len(padded)
            assert wal[
                expect_off : expect_off + len(padded)
            ].tobytes() == padded, "drain WAL arena differential"
            expect_off += len(padded)
            ring_oracle[(op0 + j) % slot_count] = oracle
        # Backup arm: accept the same run, oks vs the scalar builder.
        accepted = fastpath.accept_prepares(
            prepares, bodies, view=view, replica=2, build_oks=True,
            headers_ring=ring_backup, slot_count=slot_count,
            headers_per_sector=HEADERS_PER_SECTOR,
            sector_size=sector_size,
        )
        assert accepted is not None
        oks, _frames_b = accepted
        for j in range(k):
            oracle_ok = pl_py.build_prepare_ok(prepares[j], view, 2)
            assert oks[j].tobytes() == oracle_ok.tobytes(), (
                "drain prepare_ok differential"
            )
        # Ack runs: shuffled voters + poisoned frames, one C call.
        acks = []
        for j in rng.permutation(k):
            for rep in rng.permutation(3):
                ok = pl_py.build_prepare_ok(prepares[j], view, int(rep) + 1)
                acks.append(ok)
                if rng.integers(0, 4) == 0:
                    acks.append(ok)  # duplicate
        poison = pl_py.build_prepare_ok(prepares[0], view, 1)
        poison["op"] = op0 + (1 << 40)  # unknown op
        wire.finalize_header(poison, b"")
        acks.append(poison)
        stale = wire.make_header(
            command=wire.Command.prepare_ok, cluster=cluster, view=view,
            op=op0, replica=1, context=123456789,
        )
        wire.finalize_header(stale, b"")
        acks.append(stale)
        foreign = pl_py.build_prepare_ok(prepares[0], view, 1)
        foreign["cluster_lo"] = 42
        wire.finalize_header(foreign, b"")
        acks.append(foreign)
        wrong_view = pl_py.build_prepare_ok(prepares[0], view + 7, 1)
        acks.append(wrong_view)
        order = rng.permutation(len(acks))
        run = np.array([acks[x] for x in order])
        mirror = fastpath.create_pipeline()
        for j in range(k):  # same registration build_prepares made
            mirror.note_prepare(prepares[j], True, 0)
        _n, verdicts = pl_c.on_acks(run, cluster, view)
        for x, v in zip(order, (int(t) for t in verdicts)):
            h = acks[x]
            if wire.u128(h, "cluster") != cluster:
                assert v == -4, "foreign cluster verdict"
                continue
            if int(h["view"]) != view:
                assert v == -3, "view verdict"
                continue
            got = mirror.on_ack(h)
            assert got == (None if v < 0 else v), "drain ack differential"
        # Commit gate: the run answer vs the scalar walk.
        pl_c.mark_all_synced()
        ready = pl_c.commit_ready_run(op0 - 1, 2)
        walk = 0
        while pl_c.commit_ready(op0 - 1 + walk, 2):
            walk += 1
        assert ready == walk, "ready-run differential"
        for j in range(k):
            pl_c.drop(op0 + j)
        assert pl_c.size() == 0
    assert ring_primary.tobytes() == ring_oracle.tobytes(), (
        "drain ring differential"
    )
    print(f"asan-replay: drain fuzz ok ({rounds} rounds)")


def check_sendv_torn(seed: int = 777) -> None:
    """tb_bus_sendv (the drain's scatter-gather send list) under the
    sanitizer: multi-frame vectors — including a message_size_max body
    — must arrive byte-identical over a real socket, with the receiver
    reading across arbitrary boundaries."""
    rng = np.random.default_rng(seed)
    frames = list(fixture_frames())
    big_body = rng.bytes(1 << 20)
    h = wire.make_header(command=wire.Command.prepare, cluster=1, op=1)
    wire.finalize_header(h, big_body)
    frames.append(h.tobytes() + big_body)
    bus = NativeBus(1 << 20)
    port = bus.listen("127.0.0.1", 0)
    sock = socket.create_connection(("127.0.0.1", port), timeout=30)
    # Handshake: one inbound frame surfaces the conn id to sendv on.
    sock.sendall(frames[0])
    conn = None
    deadline = time.time() + 30
    while conn is None and time.time() < deadline:
        r = bus.poll_drain(10)
        assert r is not None
        n, types, conns, _offs, _lens, _arena = r
        for i in range(n):
            if types[i] == EV_MESSAGE:
                conn = int(conns[i])
    assert conn is not None, "handshake frame never surfaced"
    bus.sendv(conn, frames)
    want = b"".join(frames)
    got = bytearray()
    sock.settimeout(30)
    while len(got) < len(want):
        bus.poll(0)  # keep the writer side pumping
        chunk = sock.recv(min(1 << 16, len(want) - len(got)))
        assert chunk, "socket closed mid-vector"
        got.extend(chunk)
    assert bytes(got) == want, "sendv byte differential"
    sock.close()
    bus.close()
    print(f"asan-replay: sendv fuzz ok ({len(frames)} frames)")


def check_oversize_frames() -> None:
    """Size fields past the frame bound (message_size_max bodies +
    the 256-byte header) must drop the connection — never index the
    framing buffer out of bounds.  Probed at bound+1, bound+4096, and
    a u32 in the sign-bit range."""
    max_size = 1 << 20
    bound = max_size + HEADER_SIZE
    for oversize in (bound + 1, bound + 4096, (1 << 31) + 7):
        bus = NativeBus(max_size)
        port = bus.listen("127.0.0.1", 0)
        sock = socket.create_connection(("127.0.0.1", port), timeout=30)
        h = wire.make_header(command=wire.Command.request, cluster=1)
        h["size"] = oversize & 0xFFFFFFFF
        sock.sendall(h.tobytes())
        closed = False
        deadline = time.time() + 30
        while not closed and time.time() < deadline:
            for t, _c, _p in bus.poll(10):
                if t == EV_CLOSED:
                    closed = True
        assert closed, f"oversize {oversize} did not drop the conn"
        sock.close()
        bus.close()
    print("asan-replay: oversize-frame fuzz ok")


def check_hash_pool(seed: int = 2323, rounds: int = 4) -> None:
    """Round-23 multi-lane hash pool + drain-scoped digest table under
    the sanitizer: counted batch verifies (tb_fp_verify_frames2)
    fanned across worker lanes over the fixture stream laced with
    corrupt mutations, a torn-body frame, and a message_size_max (1MB)
    body; lane counts resized mid-stream (0 -> 2 -> 5 -> 1 -> 0, the
    respawn/join path); then reuse-flagged batch builds racing three
    threads of concurrent verify crossings that each invalidate and
    repopulate the SHARED digest table — results must stay
    bit-identical to the inline no-reuse arm while asan watches the
    pool threads and table slots."""
    import threading

    from tigerbeetle_tpu.vsr.journal import HEADERS_PER_SECTOR

    assert fastpath.drain_available(), (
        f"sanitized fastpath lacks drain symbols: {fastpath.drain_error()}"
    )
    rng = np.random.default_rng(seed)
    frames = mutations(fixture_frames())
    big_body = rng.bytes(1 << 20)
    h = wire.make_header(command=wire.Command.prepare, cluster=1, op=1)
    wire.finalize_header(h, big_body)
    frames.append(h.tobytes() + big_body)
    body_frame = next(f for f in frames if len(f) > HEADER_SIZE)
    frames.append(body_frame[:-7])  # torn body: structural fail, 0 hashed
    arena, offsets, lens = arena_of(frames)
    try:
        expect = None
        for lanes in (0, 2, 5, 1, 0):
            assert fastpath.configure_hash(lanes)
            got = fastpath.verify_frames2(arena, offsets, lens, len(frames))
            assert got is not None, "sanitized fastpath lacks verify2"
            ok, bytes_hashed = got
            this = ([int(v) for v in ok], bytes_hashed)
            if expect is None:
                expect = this
            assert this == expect, f"lane differential at {lanes} lanes"
        # Epoch races: concurrent crossings invalidate + repopulate the
        # shared table while reuse-flagged builds consume digests.
        assert fastpath.configure_hash(3)
        stop = threading.Event()

        def hammer():
            a, o, ln = arena_of(frames)
            while not stop.is_set():
                fastpath.verify_frames2(a, o, ln, len(frames))

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            for _round in range(rounds):
                k = 5
                reqs = np.zeros(k, wire.HEADER_DTYPE)
                bodies = []
                for j in range(k):
                    body = (
                        big_body if j == 0
                        else rng.bytes(int(rng.integers(0, 8192)))
                    )
                    req = wire.make_header(
                        command=wire.Command.request, operation=3,
                        cluster=9, client=j + 1, request=j,
                    )
                    wire.finalize_header(req, body)
                    reqs[j] = req
                    bodies.append(body)
                timestamps = np.arange(1, k + 1, dtype=np.uint64)
                contexts = np.zeros(k, np.uint64)
                outs = []
                for reuse in (False, True):
                    ring = np.zeros(32, wire.HEADER_DTYPE)
                    built = fastpath.build_prepares(
                        fastpath.create_pipeline(), reqs, bodies,
                        timestamps, contexts, cluster=9, view=1, op0=1,
                        commit=0, parent=1, replica=0, release=1,
                        synced=True, headers_ring=ring, slot_count=32,
                        headers_per_sector=HEADERS_PER_SECTOR,
                        sector_size=4096, reuse=reuse,
                    )
                    assert built is not None
                    prepares, (wal, *_rest) = built
                    outs.append((prepares.tobytes(), wal.tobytes()))
                assert outs[0] == outs[1], "reuse differential under races"
        finally:
            stop.set()
            for t in threads:
                t.join()
    finally:
        assert fastpath.configure_hash(0)
    assert fastpath.hash_stats()["lane_jobs"] > 0, "pool lanes never ran"
    print(f"asan-replay: hash pool + digest table fuzz ok "
          f"({rounds} racing rounds)")


def main() -> int:
    assert native_available(), "sanitized native runtime failed to load"
    assert fastpath.available(), "sanitized fastpath failed to load"
    check_fixture_differential()
    check_finalize_parity()
    check_torn_frames()
    check_pipeline_fuzz()
    check_drain_fuzz()
    check_hash_pool()
    check_sendv_torn()
    check_oversize_frames()
    print("ASAN-REPLAY-OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
