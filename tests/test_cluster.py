"""Deterministic multi-replica cluster scenarios (reference:
src/vsr/replica_test.zig patterns on our simulated network)."""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.testing.cluster import Cluster, PacketOptions
from tigerbeetle_tpu.testing.harness import account, ids_bytes, pack, transfer


def make_cluster(**kw):
    c = Cluster(replica_count=3, **kw)
    client = c.client(1000)
    client.register()
    c.run_until(lambda: client.registered)
    return c, client


def test_normal_operation_replicates_and_converges():
    c, client = make_cluster()
    reply = c.run_request(
        types.Operation.create_accounts, pack([account(1), account(2)])
    ) if False else c.run_request(client, types.Operation.create_accounts,
                                  pack([account(1), account(2)]))
    assert reply == b""
    reply = c.run_request(
        client, types.Operation.create_transfers,
        pack([transfer(10, debit_account_id=1, credit_account_id=2, amount=100)]),
    )
    assert reply == b""
    c.settle()
    c.check_linearized()
    c.check_convergence()
    # State is actually applied on backups too.
    for r in c.replicas:
        assert r.sm.transfer_timestamp(10) is not None


def test_lookup_through_cluster():
    c, client = make_cluster()
    c.run_request(client, types.Operation.create_accounts,
                  pack([account(1), account(2)]))
    c.run_request(
        client, types.Operation.create_transfers,
        pack([transfer(10, debit_account_id=1, credit_account_id=2, amount=55)]),
    )
    out = c.run_request(client, types.Operation.lookup_accounts, ids_bytes([1, 2]))
    rows = np.frombuffer(out, types.ACCOUNT_DTYPE)
    assert types.u128_get(rows[0], "debits_posted") == 55
    assert types.u128_get(rows[1], "credits_posted") == 55


def test_view_change_on_primary_partition():
    c, client = make_cluster()
    c.run_request(client, types.Operation.create_accounts,
                  pack([account(1), account(2)]))
    old_primary = c.replicas[0].primary_index()
    c.network.partition(old_primary)

    # The remaining replicas elect a new primary and keep serving.
    reply = c.run_request(
        client, types.Operation.create_transfers,
        pack([transfer(10, debit_account_id=1, credit_account_id=2, amount=9)]),
        max_steps=4000,
    )
    assert reply == b""
    live = [r for i, r in enumerate(c.replicas) if i != old_primary]
    assert all(r.view > 0 for r in live)
    assert any(r.is_primary for r in live)

    # Heal: the old primary catches up (repair) and converges.
    c.network.heal()
    c.settle(max_steps=6000)
    c.check_linearized()
    c.check_convergence()
    assert c.replicas[old_primary].sm.transfer_timestamp(10) is not None


def test_backup_lag_repairs_after_heal():
    c, client = make_cluster()
    c.run_request(client, types.Operation.create_accounts,
                  pack([account(1), account(2)]))
    lagging = 2  # backup in view 0
    c.network.partition(lagging)
    for i in range(5):
        c.run_request(
            client, types.Operation.create_transfers,
            pack([transfer(100 + i, debit_account_id=1, credit_account_id=2,
                           amount=1)]),
        )
    c.network.heal()
    c.settle(max_steps=6000)
    c.check_linearized()
    c.check_convergence()
    assert c.replicas[lagging].sm.transfer_timestamp(104) is not None


def test_lossy_network_still_converges():
    c, client = make_cluster(
        seed=1234,
        options=PacketOptions(packet_loss_probability=0.05,
                              packet_replay_probability=0.02),
    )
    c.run_request(client, types.Operation.create_accounts,
                  pack([account(1), account(2)]), max_steps=6000)
    for i in range(10):
        reply = c.run_request(
            client, types.Operation.create_transfers,
            pack([transfer(200 + i, debit_account_id=1, credit_account_id=2,
                           amount=2)]),
            max_steps=6000,
        )
        assert reply == b""
    c.settle(max_steps=8000)
    c.check_linearized()
    c.check_convergence()
    for r in c.replicas:
        bal = r.sm.account_balances_raw(1)
        assert bal[1] == 20  # debits_posted


def test_same_seed_same_run():
    def run(seed):
        c, client = make_cluster(
            seed=seed, options=PacketOptions(packet_loss_probability=0.05)
        )
        c.run_request(client, types.Operation.create_accounts,
                      pack([account(1), account(2)]), max_steps=6000)
        c.run_request(
            client, types.Operation.create_transfers,
            pack([transfer(7, debit_account_id=1, credit_account_id=2,
                           amount=3)]),
            max_steps=6000,
        )
        c.settle(max_steps=8000)
        return (
            c.network.now,
            tuple(r.commit_min for r in c.replicas),
            tuple(r.view for r in c.replicas),
        )

    assert run(42) == run(42)


def test_pending_expiry_replicated():
    c, client = make_cluster()
    c.run_request(client, types.Operation.create_accounts,
                  pack([account(1), account(2)]))
    c.run_request(
        client, types.Operation.create_transfers,
        pack([transfer(10, debit_account_id=1, credit_account_id=2, amount=5,
                       timeout=1, flags=types.TransferFlags.pending)]),
    )
    # ~10ms/tick: 200 ticks > 1s timeout -> primary pulses the expiry.
    c.run_until(
        lambda: all(
            r.sm.pending_status(10) == types.TransferPendingStatus.expired
            for r in c.replicas
        ),
        max_steps=4000,
    )
    c.settle()
    c.check_convergence()


def test_session_eviction_when_clients_max_exceeded():
    """clients_max+1 registrations evict the oldest session
    deterministically on every replica; the evicted client's next
    request draws Command.eviction (reference:
    src/vsr/client_sessions.zig evict, src/vsr.zig:301)."""
    c = Cluster(replica_count=3, seed=4)
    cmax = c.replicas[0].config.clients_max
    clients = []
    for i in range(cmax + 1):
        cl = c.client(1000 + i)
        cl.register()
        c.run_until(lambda: cl.registered)
        clients.append(cl)
    for _ in range(20):
        c.step()
    # The over-capacity registration evicts the oldest session on every
    # replica, and the primary notified the victim.
    assert clients[0].evicted
    assert not any(cl.evicted for cl in clients[1:])
    for r in c.replicas:
        assert clients[0].id not in r.sessions
        assert len(r.sessions) == cmax


def test_view_change_mid_pipeline_preserves_committed_prefix():
    """Primary dies with prepares in flight; the committed prefix must
    survive and the uncommitted tail resolves one way only (reference:
    replica_test.zig 'view-change after partition' scenarios)."""
    c = Cluster(replica_count=3, seed=31)
    client = c.client(1000)
    client.register()
    c.run_until(lambda: client.registered)
    c.run_request(client, types.Operation.create_accounts,
                  pack([account(1), account(2)]))
    for k in range(5):
        c.run_request(client, types.Operation.create_transfers,
                      pack([transfer(100 + k, debit_account_id=1,
                                     credit_account_id=2, amount=1)]))
    committed_before = c.replicas[0].commit_min
    # Kill the primary the instant a fresh request reaches it.
    client.request(types.Operation.create_transfers,
                   pack([transfer(200, debit_account_id=1,
                                  credit_account_id=2, amount=7)]))
    c.step()
    c.crash_replica(0)
    c.run_until(lambda: client.reply is not None, max_steps=8000)
    c.restart_replica(0)
    c.settle(max_steps=8000)
    for _ in range(30):
        c.step()
    c.check_linearized()
    c.check_convergence()
    for r in c.replicas:
        assert r.commit_min >= committed_before
        for k in range(5):
            assert r.sm.transfer_timestamp(100 + k) is not None
    # Transfer 200 either committed everywhere or nowhere.
    states = {r.sm.transfer_timestamp(200) is not None for r in c.replicas}
    assert len(states) == 1


def test_deep_lag_catches_up_via_state_sync():
    """A replica partitioned across multiple checkpoints rejoins via
    state sync rather than WAL repair (reference: sync.zig supersedes
    repair once the WAL has wrapped)."""
    c = Cluster(replica_count=3, seed=32)
    client = c.client(1000)
    client.register()
    c.run_until(lambda: client.registered)
    c.run_request(client, types.Operation.create_accounts,
                  pack([account(1), account(2)]))
    c.network.partition(2)
    interval = c.replicas[0].config.vsr_checkpoint_interval
    for k in range(3 * interval):
        c.run_request(client, types.Operation.create_transfers,
                      pack([transfer(1000 + k, debit_account_id=1,
                                     credit_account_id=2, amount=1)]))
    assert c.replicas[0].checkpoint_op > 0
    assert c.replicas[2].commit_min < c.replicas[0].commit_min
    c.network.heal()
    c.settle(max_steps=20000)
    for _ in range(50):
        c.step()
    c.check_convergence()
    assert c.replicas[2].sm.transfer_timestamp(1000 + 3 * interval - 1) is not None


def test_wal_corruption_on_backup_repaired_from_peers():
    """A backup's corrupt WAL slot is refetched from peers by checksum
    (reference: protocol-aware WAL repair, replica.zig:2259-2497)."""
    c = Cluster(replica_count=3, seed=33)
    client = c.client(1000)
    client.register()
    c.run_until(lambda: client.registered)
    c.run_request(client, types.Operation.create_accounts,
                  pack([account(1), account(2)]))
    for k in range(6):
        c.run_request(client, types.Operation.create_transfers,
                      pack([transfer(300 + k, debit_account_id=1,
                                     credit_account_id=2, amount=2)]))
    # Corrupt a committed prepare in backup 1's journal, then restart
    # it so recovery sees the damage.
    victim = c.replicas[1]
    target_op = victim.commit_min - 2
    slot = target_op % victim.config.journal_slot_count
    c.storages[1].corrupt_sector(
        c.storages[1].layout.prepare_slot_offset(slot)
    )
    c.restart_replica(1)
    c.settle(max_steps=10000)
    for _ in range(40):
        c.step()
    c.check_convergence()
    assert c.replicas[1].sm.transfer_timestamp(305) is not None


def test_wal_corruption_on_primary_repaired_from_backups():
    """The PRIMARY's corrupt WAL slot heals from a backup too: scrub
    repair replies arrive as current-view prepares, which the primary
    used to drop on its ring-wrap guard before the repair path could
    see them — leaving the slot unhealable forever (VOPR seed
    99911308)."""
    c = Cluster(replica_count=3, seed=34)
    client = c.client(1001)
    client.register()
    c.run_until(lambda: client.registered)
    c.run_request(client, types.Operation.create_accounts,
                  pack([account(1), account(2)]))
    for k in range(6):
        c.run_request(client, types.Operation.create_transfers,
                      pack([transfer(400 + k, debit_account_id=1,
                                     credit_account_id=2, amount=2)]))
    primary = next(i for i, r in enumerate(c.replicas) if r.is_primary)
    victim = c.replicas[primary]
    target_op = victim.commit_min - 2
    slot = target_op % victim.config.journal_slot_count
    c.storages[primary].corrupt_sector(
        c.storages[primary].layout.prepare_slot_offset(slot)
    )
    assert victim.journal.read_prepare(target_op) is None
    assert victim.is_primary  # the point of this test: no restart
    for _ in range(6):
        victim.wal_scrub_window()
        for _ in range(24):
            c.step()
        if not victim._wal_scrub_wanted:
            break
    assert victim.journal.read_prepare(target_op) is not None
    assert victim.stat_wal_scrub_repaired >= 1
    c.settle(max_steps=10000)
    c.check_linearized()
    c.check_convergence()


def test_sync_install_preserves_journal_tail_above_checkpoint():
    """State sync supersedes WAL repair only BELOW the installed
    checkpoint: a replica holding a journal tail above it (e.g. a new
    primary that adopted the canonical tail via DVC, then synced its
    lagging prefix) must keep that tail — truncating it wiped committed
    ops cluster-wide (VOPR corruption nemesis, seed 8006)."""
    c = Cluster(replica_count=3, seed=31)
    client = c.client(700)
    client.register()
    c.run_until(lambda: client.registered)
    c.run_request(client, types.Operation.create_accounts,
                  pack([account(1), account(2)]))
    interval = c.replicas[0].config.vsr_checkpoint_interval
    for k in range(interval + 6):
        c.run_request(client, types.Operation.create_transfers,
                      pack([transfer(900 + k, debit_account_id=1,
                                     credit_account_id=2, amount=1)]))
    c.settle(max_steps=10000)
    sender = c.replicas[0]
    assert sender.checkpoint_op > 0

    receiver = c.replicas[1]
    op_before = receiver.op
    parent_before = receiver.parent_checksum
    assert op_before > sender.checkpoint_op
    # Forge a lagging commit frontier below the checkpoint (the sync
    # receive path guards checkpoint_op > commit_min), keeping the
    # journaled tail — the state a DVC-adopting primary is in.
    receiver.commit_min = sender.checkpoint_op - 2

    sb = sender.superblock.working
    blob = sender._read_grid(
        int(sb["checkpoint_offset"]), int(sb["checkpoint_size"])
    )
    payload = sender._sync_wrap(blob)
    from tigerbeetle_tpu.vsr import wire as wire_mod

    receiver._install_sync_checkpoint(
        payload, sender.checkpoint_op,
        int(sb["commit_min_checksum_lo"])
        | (int(sb["commit_min_checksum_hi"]) << 64),
        wire_mod.checksum(payload), sender.commit_min,
    )
    # The preserved tail re-commits immediately (every prepare is in
    # the journal), so the frontier lands back at the tail head — the
    # old truncating install left it at checkpoint_op with op reset.
    assert receiver.commit_min >= sender.checkpoint_op
    assert receiver.op == op_before, "sync truncated the journal tail"
    assert receiver.parent_checksum == parent_before


def test_dvc_vouches_for_unreadable_committed_ops():
    """A replica whose committed prepare is torn/corrupt must still
    vouch for that op in its DVC headers via the redundant ring —
    understating DVCs let a view-change quorum of damaged replicas
    truncate committed history (VOPR corruption nemesis, seed 8018)."""
    c = Cluster(replica_count=3, seed=41)
    client = c.client(800)
    client.register()
    c.run_until(lambda: client.registered)
    c.run_request(client, types.Operation.create_accounts,
                  pack([account(1), account(2)]))
    for k in range(8):
        c.run_request(client, types.Operation.create_transfers,
                      pack([transfer(400 + k, debit_account_id=1,
                                     credit_account_id=2, amount=1)]))
    c.settle(max_steps=10000)

    victim = c.replicas[1]
    target_op = victim.commit_min - 3
    assert target_op > victim.checkpoint_op
    slot = victim.journal.slot_for_op(target_op)
    c.storages[1].corrupt_sector(
        c.storages[1].layout.prepare_slot_offset(slot)
    )
    assert victim.journal.read_prepare(target_op) is None

    # After a restart the commit frontier falls back to the checkpoint,
    # so the DVC window covers the committed suffix.  Recovery must
    # PRESERVE the head across the damaged slot (repair refills the
    # prepare from peers), and the corrupt op's header must come from
    # the on-disk redundant ring even though its prepare is unreadable.
    from tigerbeetle_tpu.vsr import wire

    old_op = victim.op
    c.restart_replica(1)
    restarted = c.replicas[1]
    assert restarted.commit_min < target_op
    assert restarted.op == old_op, "recovery truncated at the damaged slot"
    vouched = {
        int(wire.header_from_bytes(raw)["op"])
        for raw in restarted._tail_headers()
    }
    # Every committed op above the new commit frontier is vouched —
    # including the one whose prepare is corrupt.
    for op in range(restarted.commit_min + 1, victim.commit_min + 1):
        assert op in vouched, op
