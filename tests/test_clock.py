"""Cluster clock synchronization (vsr/clock.py).

Covers Marzullo interval intersection (reference: src/vsr/marzullo.zig
semantics), Clock sample admission/expiry, and the end-to-end property
the reference's clock exists for: a primary with a skewed wall clock
assigns prepare timestamps clamped toward the cluster majority's time
(reference: src/vsr/clock.zig, src/vsr/replica.zig:5762-5772).
"""

import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.harness import account, pack, transfer
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.clock import (
    EPOCH_MAX_NS,
    OFFSET_TOLERANCE_NS,
    Clock,
    marzullo_smallest_interval,
)

MS = 1_000_000


# ---------------------------------------------------------------------------
# Marzullo.


def test_marzullo_all_agree():
    lo, hi, n = marzullo_smallest_interval([(0, 2), (1, 2), (-1, 2)])
    assert n == 3
    assert lo == -1 and hi == 1


def test_marzullo_outlier_excluded():
    # Two sources agree around 0; one claims +100 with a tight bound.
    lo, hi, n = marzullo_smallest_interval([(0, 5), (2, 5), (100, 1)])
    assert n == 2
    assert -3 <= lo <= hi <= 5


def test_marzullo_touching_endpoints_overlap():
    # [0,10] and [10,20] touch at exactly 10 -> both count.
    lo, hi, n = marzullo_smallest_interval([(5, 5), (15, 5)])
    assert n == 2
    assert lo == 10 and hi == 10


def test_marzullo_empty_and_single():
    assert marzullo_smallest_interval([]) == (0, 0, 0)
    lo, hi, n = marzullo_smallest_interval([(7, 3)])
    assert (lo, hi, n) == (4, 10, 1)


def test_marzullo_majority_of_disjoint():
    # Three disjoint camps of sizes 1/3/2 -> the size-3 camp wins.
    tuples = [(0, 1)] + [(100, 2)] * 3 + [(200, 1)] * 2
    lo, hi, n = marzullo_smallest_interval(tuples)
    assert n == 3
    assert 98 <= lo <= hi <= 102


# ---------------------------------------------------------------------------
# Clock.


def test_clock_single_replica_always_synchronized():
    c = Clock(0, 1)
    assert c.synchronized
    assert c.realtime_synchronized(12345) == 12345


def test_clock_learns_and_clamps_skewed_local_clock():
    c = Clock(0, 3)
    assert not c.synchronized
    # Local wall clock runs 500ms ahead of both peers (t1 = local-500ms
    # at the sample midpoint), zero-RTT samples.
    local = 10 * types.NS_PER_S
    for peer, m in ((1, 100), (2, 200)):
        c.learn(peer, m0=m, t1=local - 500 * MS, m2=m, realtime_now=local)
    assert c.synchronized
    # Majority window sits ~-500ms from us; our reading is clamped down.
    rt = c.realtime_synchronized(local)
    assert rt is not None and rt < local
    assert abs((local - rt) - 500 * MS) <= 2 * OFFSET_TOLERANCE_NS


def test_clock_rejects_unsane_samples():
    c = Clock(0, 3)
    c.learn(1, m0=100, t1=50, m2=90, realtime_now=100)  # monotonic regressed
    c.learn(1, m0=0, t1=50, m2=10**12, realtime_now=100)  # rtt too large
    assert not c._samples


def test_clock_sample_expiry_desynchronizes():
    c = Clock(0, 3)
    c.learn(1, m0=0, t1=0, m2=0, realtime_now=0)
    c.learn(2, m0=0, t1=0, m2=0, realtime_now=0)
    assert c.synchronized
    c.expire(EPOCH_MAX_NS + 1)
    assert not c.synchronized
    assert c.realtime_synchronized(123) is None


# ---------------------------------------------------------------------------
# End to end: skewed primary in a live cluster.


def test_cluster_clock_skewed_primary_clamped():
    cluster = Cluster(replica_count=3, seed=7)
    skew = 2 * types.NS_PER_S
    cluster.clock_skew[0] = skew  # replica 0 is the initial primary

    for _ in range(20):  # let ping/pong rounds accumulate
        cluster.step()
    primary = cluster.replicas[0]
    assert primary.is_primary
    assert primary.clock.synchronized

    client = cluster.client(1000)
    client.register()
    cluster.run_until(lambda: client.registered)
    cluster.run_request(
        client, Operation.create_accounts, pack([account(1), account(2)])
    )
    cluster.run_request(
        client,
        Operation.create_transfers,
        pack([transfer(9, debit_account_id=1, credit_account_id=2, amount=5)]),
    )
    for _ in range(10):
        cluster.step()

    # The committed transfer's timestamp must track true cluster time,
    # not the primary's wall clock 2s in the future.
    ts = primary.sm.transfer_timestamp(9)
    assert ts is not None
    assert ts < cluster.realtime + skew // 2, (ts, cluster.realtime)
    # And all replicas converge on the same state.
    for r in cluster.replicas[1:]:
        assert r.sm.transfer_timestamp(9) in (None, ts)


def test_cluster_divergent_clocks_refuse_writes():
    """When no majority of clocks agrees within tolerance, there is no
    Marzullo window and the primary must NOT assign timestamps — the
    documented safety property (reference: docs/about/safety.md clock
    requirements; src/vsr/replica.zig realtime_synchronized gate)."""
    cluster = Cluster(replica_count=3, seed=3)
    cluster.clock_skew = [0, 60 * types.NS_PER_S, -60 * types.NS_PER_S]
    for _ in range(50):
        cluster.step()
    primary = cluster.replicas[0]
    assert not primary.clock.synchronized
    client = cluster.client(1000)
    client.register()
    for _ in range(100):
        cluster.step()
    assert not client.registered  # queued, never prepared
