"""Source hygiene (reference: src/tidy.zig).

Round 17: the three banned-pattern regexes (wall clock, unseeded
random, print) migrated into tbcheck AST rules — `determinism` and
`no-print` in tigerbeetle_tpu/analysis/rules.py — which resolve import
aliases, scope by the real import graph instead of a filename
exemption list, and ignore pattern-lookalikes inside string literals
(the regexes flagged a docstring that merely said "print(").
tests/test_tbcheck.py proves by fixture that every previously-flagged
pattern is still caught.  Only the line-length limit stays here: it is
a token-level property, not an AST one.
"""

import os

ROOT = os.path.join(os.path.dirname(__file__), "..", "tigerbeetle_tpu")


def _py_files():
    for dirpath, _dirs, files in os.walk(ROOT):
        if "__pycache__" in dirpath:
            continue
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def test_line_length_limit():
    # reference tidy.zig enforces 100 columns; we allow 100 too.
    offenders = []
    for path in _py_files():
        for lineno, line in enumerate(open(path), 1):
            cols = len(line.rstrip("\n"))
            if cols > 100:
                offenders.append(f"{path}:{lineno}: {cols} cols")
    assert not offenders, "\n".join(offenders[:20])
