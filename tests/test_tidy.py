"""Source hygiene lints (reference: src/tidy.zig — banned patterns and
line-length limits enforced as a test)."""

import os
import re

ROOT = os.path.join(os.path.dirname(__file__), "..", "tigerbeetle_tpu")

BANNED = [
    # (pattern, why)
    (re.compile(r"\btime\.time\(\)"), "wall clock in core code breaks "
     "determinism; use injected realtime/monotonic"),
    (re.compile(r"\brandom\.random\(\)"), "unseeded randomness breaks "
     "deterministic simulation; use seeded numpy Generators"),
    (re.compile(r"\bprint\("), "core modules must not print; use logging "
     "or tracer"),
]
# Modules where process I/O or wall time is the point.
EXEMPT = {"cli.py", "repl.py", "benchmark.py", "server.py", "native.py",
          "fastpath.py", "flags.py", "fuzz.py", "soak.py"}


def _py_files():
    for dirpath, _dirs, files in os.walk(ROOT):
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _strip_comment(line: str) -> str:
    """Drop a trailing comment, respecting string literals (a '#'
    inside quotes is not a comment start)."""
    quote = None
    for i, ch in enumerate(line):
        if quote:
            if ch == quote and line[i - 1] != "\\":
                quote = None
        elif ch in "'\"":
            quote = ch
        elif ch == "#":
            return line[:i]
    return line


def test_no_banned_patterns():
    offenders = []
    for path in _py_files():
        if os.path.basename(path) in EXEMPT:
            continue
        for lineno, line in enumerate(open(path), 1):
            stripped = _strip_comment(line)
            for pat, why in BANNED:
                if pat.search(stripped):
                    offenders.append(f"{path}:{lineno}: {pat.pattern} ({why})")
    assert not offenders, "\n".join(offenders)


def test_line_length_limit():
    # reference tidy.zig enforces 100 columns; we allow 100 too.
    offenders = []
    for path in _py_files():
        for lineno, line in enumerate(open(path), 1):
            cols = len(line.rstrip("\n"))
            if cols > 100:
                offenders.append(f"{path}:{lineno}: {cols} cols")
    assert not offenders, "\n".join(offenders[:20])
