"""LSM engine: EWAH codec, FreeSet, Grid, Tree, Groove, Forest."""

import numpy as np
import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu.lsm import ewah
from tigerbeetle_tpu.lsm.runs import KEY_DTYPE, pack_u128
from tigerbeetle_tpu.lsm.tree import Tree, k_way_merge_flags
from tigerbeetle_tpu.lsm.forest import Forest
from tigerbeetle_tpu.vsr.free_set import FreeSet
from tigerbeetle_tpu.vsr.grid import Grid
from tigerbeetle_tpu.vsr.storage import MemoryStorage, ZoneLayout


def storage():
    return MemoryStorage(ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 22))


def grid(block_size=4096, block_count=1 << 10):
    return Grid(storage(), block_size=block_size, block_count=block_count)


# ----------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_ewah_roundtrip(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 300))
    words = np.zeros(n, np.uint64)
    # Mix of runs of zeros, ones, and literals.
    for _ in range(10):
        at = int(rng.integers(n))
        ln = int(rng.integers(1, 30))
        kind = rng.integers(3)
        if kind == 0:
            words[at : at + ln] = 0
        elif kind == 1:
            words[at : at + ln] = np.uint64(0xFFFFFFFFFFFFFFFF)
        else:
            words[at : at + ln] = rng.integers(
                1, 1 << 63, min(ln, n - at), dtype=np.uint64
            )
    encoded = ewah.encode(words)
    np.testing.assert_array_equal(ewah.decode(encoded, n), words)
    # Compressible input compresses.
    uniform = np.zeros(1000, np.uint64)
    assert len(ewah.encode(uniform)) == 8


def test_free_set_reserve_acquire_forfeit():
    fs = FreeSet(64)
    r1 = fs.reserve(4)
    r2 = fs.reserve(4)
    a = [fs.acquire(r1), fs.acquire(r2), fs.acquire(r1)]
    assert len(set(a)) == 3
    fs.forfeit(r1)
    fs.forfeit(r2)
    assert fs.count_free() == 61
    # Release is staged until checkpoint.
    fs.release(a[0])
    assert not fs.is_free(a[0])
    fs.checkpoint()
    assert fs.is_free(a[0])
    # Round-trips through EWAH.
    fs2 = FreeSet.decode(fs.encode(), 64)
    np.testing.assert_array_equal(fs2.free, fs.free)


def test_grid_blocks_checksummed():
    g = grid()
    fs = g.free_set
    r = fs.reserve(2)
    a1, a2 = fs.acquire(r), fs.acquire(r)
    fs.forfeit(r)
    g.write_block(a1, b"hello world")
    g.write_block(a2, b"x" * 1000)
    assert g.read_block(a1) == b"hello world"
    assert g.verify_block(a2)
    # Corrupt the sector behind a2: verify fails (it probes the DISK,
    # leaving the cache alone), and a disk read raises.
    g.storage.corrupt_sector(g._offset(a2))
    assert not g.verify_block(a2)
    assert g.read_block(a2) == b"x" * 1000  # cache still serves RAM copy
    g._cache.remove(a2)
    with pytest.raises(RuntimeError):
        g.read_block(a2)


# ----------------------------------------------------------------------


def keys_of(ids):
    ids = np.asarray(ids, np.uint64)
    return pack_u128(ids, np.zeros(len(ids), np.uint64))


def test_tree_put_lookup_across_seals():
    t = Tree(grid(), "t", value_size=8, memtable_max=64)
    rng = np.random.default_rng(0)
    all_ids = rng.permutation(np.arange(1, 2001, dtype=np.uint64))
    for at in range(0, 2000, 50):
        chunk = all_ids[at : at + 50]
        t.put_batch(keys_of(chunk), chunk.astype("<u8").view("V8"))
        t.maybe_seal()
    assert any(t.levels[i] for i in range(7))  # actually spilled

    probe = rng.permutation(np.arange(1, 3001, dtype=np.uint64))
    found, values = t.lookup_batch(keys_of(probe))
    expect = probe <= 2000
    np.testing.assert_array_equal(found, expect)
    got = values.view("<u8").reshape(-1)[expect]
    np.testing.assert_array_equal(got, probe[expect])


def test_tree_overwrite_newest_wins():
    t = Tree(grid(), "t", value_size=8, memtable_max=16)
    ids = np.arange(1, 101, dtype=np.uint64)
    t.put_batch(keys_of(ids), ids.astype("<u8").view("V8"))
    t.seal_memtable()
    t.put_batch(keys_of(ids), (ids * 7).astype("<u8").view("V8"))
    t.seal_memtable()
    found, values = t.lookup_batch(keys_of(ids))
    assert found.all()
    np.testing.assert_array_equal(values.view("<u8").reshape(-1), ids * 7)


def test_tree_tombstones():
    t = Tree(grid(), "t", value_size=8, memtable_max=16)
    ids = np.arange(1, 101, dtype=np.uint64)
    t.put_batch(keys_of(ids), ids.astype("<u8").view("V8"))
    t.seal_memtable()
    t.remove_batch(keys_of(ids[:50]))
    t.seal_memtable()
    found, _ = t.lookup_batch(keys_of(ids))
    np.testing.assert_array_equal(found, ids > 50)
    # Compactions drop tombstones at the last populated level.
    for _ in range(20):
        t.put_batch(keys_of(ids[50:]), ids[50:].astype("<u8").view("V8"))
        t.seal_memtable()
    found, _ = t.lookup_batch(keys_of(ids))
    np.testing.assert_array_equal(found, ids > 50)


def test_tree_scan_range():
    t = Tree(grid(), "t", value_size=8, memtable_max=32)
    ids = np.arange(1, 301, dtype=np.uint64)
    t.put_batch(keys_of(ids), ids.astype("<u8").view("V8"))
    t.seal_memtable()
    t.put_batch(keys_of(np.array([500], np.uint64)),
                np.array([500], "<u8").view("V8"))
    lo = keys_of([100]).tobytes()
    hi = keys_of([200]).tobytes()
    keys, values = t.scan_range(lo, hi)
    assert len(keys) == 101
    np.testing.assert_array_equal(
        values.view("<u8").reshape(-1), np.arange(100, 201)
    )


def test_k_way_merge_newest_first():
    k1 = keys_of([1, 2, 3])
    k2 = keys_of([2, 3, 4])
    v = lambda a: np.asarray(a, "<u8").view(np.uint8).reshape(-1, 8)
    newest = (k1, np.zeros(3, np.uint8), v([10, 20, 30]))
    oldest = (k2, np.zeros(3, np.uint8), v([99, 99, 40]))
    keys, flags, vals = k_way_merge_flags([newest, oldest], 8)
    np.testing.assert_array_equal(
        vals.view("<u8").reshape(-1), [10, 20, 30, 40]
    )


# ----------------------------------------------------------------------


def test_groove_end_to_end_with_forest_checkpoint():
    st = storage()
    f = Forest(st, block_size=4096, block_count=1 << 10, memtable_max=64)
    g = f.groove("transfers", object_size=128, index_fields=["ledger", "code"])

    n = 500
    ids = np.arange(1, n + 1, dtype=np.uint64)
    ts = ids * 10
    objects = np.zeros((n, 128), np.uint8)
    objects[:, 0] = (ids & 0xFF).astype(np.uint8)
    ledgers = np.where(ids % 2 == 0, 7, 8).astype(np.uint64)
    codes = np.full(n, 3, np.uint64)
    g.insert_batch(ids, np.zeros(n, np.uint64), ts, objects,
                   {"ledger": ledgers, "code": codes})

    found, got_ts = g.lookup_ids(ids[:10], np.zeros(10, np.uint64))
    assert found.all()
    np.testing.assert_array_equal(got_ts, ts[:10])

    found, objs = g.get_objects(ts[:10])
    assert found.all()
    np.testing.assert_array_equal(objs[:, 0], ids[:10] & 0xFF)

    scan = g.index_scan("ledger", 7)
    np.testing.assert_array_equal(scan, ts[ids % 2 == 0])
    both = g.index_intersect([g.index_scan("ledger", 7), g.index_scan("code", 3)])
    np.testing.assert_array_equal(both, ts[ids % 2 == 0])

    # Checkpoint -> new forest over same storage -> identical reads.
    blob = f.checkpoint()
    f2 = Forest(st, block_size=4096, block_count=1 << 10, memtable_max=64)
    f2.groove("transfers", object_size=128, index_fields=["ledger", "code"])
    f2.open(blob)
    g2 = f2.grooves["transfers"]
    found, got_ts = g2.lookup_ids(ids, np.zeros(n, np.uint64))
    assert found.all()
    np.testing.assert_array_equal(got_ts, ts)
    np.testing.assert_array_equal(g2.index_scan("ledger", 8), ts[ids % 2 == 1])


def test_tree_scales_past_memtable():
    """State far exceeding the memtable spills and stays queryable."""
    t = Tree(grid(block_count=1 << 12), "big", value_size=8, memtable_max=256)
    rng = np.random.default_rng(3)
    ids = rng.permutation(np.arange(1, 20_001, dtype=np.uint64))
    for at in range(0, len(ids), 256):
        chunk = ids[at : at + 256]
        t.put_batch(keys_of(chunk), chunk.astype("<u8").view("V8"))
        t.maybe_seal()
    probe = rng.choice(ids, 1000, replace=False)
    found, values = t.lookup_batch(keys_of(probe))
    assert found.all()
    np.testing.assert_array_equal(values.view("<u8").reshape(-1), probe)


# ----------------------------------------------------------------------
# Scan builder (lsm/scan_builder.py): condition trees over indexes.


def _scan_fixture(seed=0, n=500):
    """Groove of objects with two indexed fields; returns (groove,
    fields-as-arrays) for brute-force comparison."""
    from tigerbeetle_tpu.lsm.forest import Forest

    rng = np.random.default_rng(seed)
    f = Forest(storage(), block_size=4096, block_count=1 << 12)
    g = f.groove("things", object_size=16, index_fields=["color", "size"])
    ts = np.arange(1, n + 1, dtype=np.uint64)
    color = rng.integers(1, 5, n).astype(np.uint64)
    size = rng.integers(1, 4, n).astype(np.uint64)
    objects = np.zeros((n, 16), np.uint8)
    objects[:, 0] = color
    objects[:, 1] = size
    objects[:, 2:10] = ts.astype("<u8").view(np.uint8).reshape(n, 8)
    g.insert_batch(ts, np.zeros(n, np.uint64), ts, objects,
                   {"color": color, "size": size})
    return g, ts, color, size


def test_scan_builder_eq_matches_bruteforce():
    from tigerbeetle_tpu.lsm.scan_builder import ScanBuilder

    g, ts, color, size = _scan_fixture()
    b = ScanBuilder(g)
    got = b.evaluate(b.eq("color", 3))
    want = ts[color == 3]
    np.testing.assert_array_equal(got, want)


def test_scan_builder_union_intersect_range_direction_limit():
    from tigerbeetle_tpu.lsm.scan_builder import ScanBuilder, ScanLookup

    g, ts, color, size = _scan_fixture(seed=1)
    b = ScanBuilder(g)
    # (color==1 OR color==2) AND size==3, ts in [100, 400], newest
    # first, limit 7 — the get_account_transfers query shape
    # (reference: src/state_machine.zig:931-996).
    expr = b.intersect(
        b.union(b.eq("color", 1), b.eq("color", 2)),
        b.eq("size", 3),
    )
    got = b.evaluate(expr, ts_min=100, ts_max=400, reversed=True, limit=7)
    mask = ((color == 1) | (color == 2)) & (size == 3) & (ts >= 100) & (ts <= 400)
    want = ts[mask][::-1][:7]
    np.testing.assert_array_equal(got, want)

    rows = ScanLookup(g).fetch(got)
    assert rows.shape == (len(got), 16)
    got_ts = rows[:, 2:10].copy().view("<u8").reshape(-1)
    np.testing.assert_array_equal(got_ts, want)


def test_scan_builder_survives_seal_and_compaction():
    from tigerbeetle_tpu.lsm.scan_builder import ScanBuilder

    g, ts, color, size = _scan_fixture(seed=2, n=300)
    for t in (g.id_tree, g.object_tree, *g.indexes.values()):
        t.seal_memtable()
        t.compact()
    b = ScanBuilder(g)
    got = b.evaluate(b.union(b.eq("color", 4), b.eq("size", 2)))
    want = ts[(color == 4) | (size == 2)]
    np.testing.assert_array_equal(got, want)


# ----------------------------------------------------------------------
# Set-associative cache (utils/cache.py —
# reference: src/lsm/set_associative_cache.zig).


def test_set_associative_cache_basics():
    from tigerbeetle_tpu.utils.cache import SetAssociativeCache

    c = SetAssociativeCache(capacity=16, ways=4)
    for k in range(8):
        c.put(k, k * 10)
    for k in range(8):
        assert c.get(k) == k * 10
    c.put(3, 999)
    assert c.get(3) == 999
    c.remove(3)
    assert c.get(3) is None and 3 not in c


def test_set_associative_cache_bounded_with_clock_eviction():
    from tigerbeetle_tpu.utils.cache import SetAssociativeCache

    c = SetAssociativeCache(capacity=16, ways=4)
    # Overfill 8x: stays bounded, recently-touched keys survive longer.
    for k in range(128):
        c.put(k, k)
    live = sum(1 for k in range(128) if k in c)
    assert live <= 16
    # Values that survive are always the correct ones, and the hit
    # counter tracks successful lookups (clock eviction is an LRU
    # APPROXIMATION — survival of any one key is not guaranteed).
    survivors = [k for k in range(128) if k in c]
    hits_before = c.hits
    for k in survivors:
        assert c.get(k) == k
    assert c.hits == hits_before + len(survivors)


def test_grid_cache_is_set_associative():
    g = grid()
    fs = g.free_set
    res = fs.reserve(4)
    addrs = [fs.acquire(res) for _ in range(4)]
    fs.forfeit(res)
    for a in addrs:
        g.write_block(a, bytes([a]) * 50)
    before = g._cache.misses
    for a in addrs:
        assert g.read_block(a) == bytes([a]) * 50
    assert g._cache.misses == before  # warm from write-through
