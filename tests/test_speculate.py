"""Optimistic wave execution (round 18): speculate on device, validate
on device, re-execute only the residue.

Four layers:

1. Validator microtests: the on-device conflict detection
   (waves._spec_conflicts) against the partitioner's round-0 level
   assignment over fuzzed metadata, and a hand-built conflicting batch
   pinning the PREFIX-COMMIT rule — an event commits iff no earlier
   event in the batch conflicts with it, so an unconflicted event
   AFTER a conflicted one still commits while the conflicted set (not
   a positional suffix) replays.
2. Acceptance shapes: fresh-id batches forced through speculation
   execute in exactly ONE speculative device step with the partitioner
   never running (plan_skipped == hits == batches); in-batch
   pending/finalize pairs miss validation and replay their finalizers
   as a one-wave residue (2 steps/batch).
3. Forced-optimistic vs pessimistic-waves vs CPU-oracle differential
   fuzz over full device-engine windows (duplicate ids,
   pending/post/void, linked rollback, grow/remove interleavings,
   timeouts): replies, result codes, and the authoritative table
   digest must be byte-identical across every arm.
4. A chaos smoke with speculation forced on: demote / degraded-serve /
   re-promote keeps every reply oracle-identical — speculative records
   replay through their exact host fallback like any other record.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import tigerbeetle_tpu.state_machine.device_engine as de
from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import resolve, waves
from tigerbeetle_tpu.state_machine.cpu import CpuStateMachine
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing import harness as hz
from tigerbeetle_tpu.testing.chaos import ChaosLink
from tigerbeetle_tpu.types import EngineState, Operation, TransferFlags

from test_device_waves import (  # noqa: E402 — shared fuzz fixtures
    _fuzz_stream,
    _pv_balancing_batch,
    accounts,
    mk_pair,
    replay_both,
)

TF = TransferFlags
AF = types.AccountFlags


def spec_counters(sm) -> dict:
    return {
        name: handle.value
        for name, handle in sm._dev.spec_stats.items()
        if hasattr(handle, "value")
    }


# ---------------------------------------------------------------------------
# Validator: on-device conflict flags vs the partitioner's round 0.


def _ev_from_meta(n, meta, p_found):
    """Minimal (B,)-shaped event dict feeding _spec_conflicts: the
    validator reads only the conflict-token columns."""
    K = 1 << max(3, (n - 1).bit_length())
    flags = np.zeros(K, np.uint32)
    flags[:n] = np.where(meta["is_pv"], np.uint32(TF.post_pending_transfer), 0)

    def pad(a, fill=0, dtype=None):
        out = np.full(K, fill, dtype or np.asarray(a).dtype)
        out[:n] = a
        return out

    ev = {
        "i": np.arange(K, dtype=np.int32),
        "flags": flags,
        "id_group": pad(meta["id_group"].astype(np.int32)),
        "p_group": pad(meta["p_group"].astype(np.int32), fill=-1),
        "p_tgt": pad(meta["p_tgt"].astype(np.int32), fill=-1),
        "p_found": pad(p_found),
        "dr_slot": pad(meta["ev_dr"].astype(np.int32), fill=-1),
        "cr_slot": pad(meta["ev_cr"].astype(np.int32), fill=-1),
        # Reads in the metadata came from balancing/limit columns;
        # reconstruct equivalent flag columns: a read on the dr side
        # becomes a balancing_debit flag, on the cr side a limit flag.
        "dr_flags": pad(np.zeros(n, np.uint32)),
        "cr_flags": pad(
            np.where(meta["reads1"] >= 0,
                     np.uint32(AF.credits_must_not_exceed_debits), 0)
        ),
        "p_dr_slot": pad(
            np.where(p_found, meta["writes0"], -1).astype(np.int32),
            fill=-1,
        ),
        "p_cr_slot": pad(
            np.where(p_found, meta["writes1"], -1).astype(np.int32),
            fill=-1,
        ),
    }
    ev["flags"][:n] |= np.where(
        meta["reads0"] >= 0, np.uint32(TF.balancing_debit), 0
    )
    return ev, K


@pytest.mark.parametrize("seed", range(8))
def test_spec_conflicts_match_partitioner_round0(seed):
    """For chain-free batches without in-batch finalizers, the
    on-device validator's conflict set must equal the partitioner's
    non-level-0 set exactly: both are the same round-0 blocked test
    over the same tokens.  (In-batch finalizers are excluded from the
    EQUALITY claim only: their widened static write set makes the
    partitioner strictly more conservative than the validator needs
    to be — see _spec_conflicts' docstring.)"""
    rng = np.random.default_rng(5000 + seed)
    for _ in range(6):
        n = int(rng.integers(2, 100))
        flags = np.zeros(n, np.uint32)
        flags[rng.random(n) < 0.1] |= int(TF.balancing_debit)
        pv = rng.random(n) < 0.25
        flags[pv] |= int(TF.post_pending_transfer)
        p_found = pv & (rng.random(n) < 0.6)
        p_tgt = np.where(
            p_found, rng.integers(0, max(1, n // 3), n), -1
        ).astype(np.int32)
        cr_flags = np.where(
            rng.random(n) < 0.15,
            np.uint32(AF.credits_must_not_exceed_debits), np.uint32(0),
        )
        meta = resolve.wave_dependency_metadata(
            n, flags,
            rng.integers(0, 6, n).astype(np.int64),
            rng.integers(6, 12, n).astype(np.int64),
            np.zeros(n, np.uint32), cr_flags,
            rng.integers(0, max(1, n // 2), n).astype(np.int64),
            np.full(n, -1, np.int32),  # no in-batch finalizers
            p_tgt, p_found,
            np.where(p_found, rng.integers(0, 6, n), -1).astype(np.int64),
            np.where(p_found, rng.integers(6, 12, n), -1).astype(np.int64),
        )
        assert not meta["chain_member"].any()
        ev, K = _ev_from_meta(n, meta, p_found)
        conflicted = np.asarray(
            waves._spec_conflicts(
                {k: jnp.asarray(v) for k, v in ev.items()},
                jnp.zeros(K, bool), jnp.int32(n), 16, K,
            )
        )[:n]
        plan = waves.plan_waves(n, meta, use_walk=True)
        level0 = np.zeros(n, bool)
        kind0, idx0 = plan.segments[0]
        assert kind0 == "wave"
        level0[idx0] = True
        assert np.array_equal(conflicted, ~level0), (
            f"seed {seed}: validator disagrees with round-0 levels"
        )


def test_prefix_commit_rule_hand_built(monkeypatch):
    """The prefix-commit rule on a hand-built conflicting batch:

      e0: create pending t=100            -> commits (no earlier conflict)
      e1: post pending_id=100 (in-batch)  -> CONFLICTED (e0's id claim)
      e2: independent create              -> commits DESPITE following a
                                             conflicted event (commuting)
      e3: duplicate id of e0              -> CONFLICTED (same id group)
      e4: independent create              -> commits

    The committable set is the non-conflicted set, NOT the positional
    prefix before the first conflict — e2/e4 must not replay.  Replies
    stay oracle-identical and the residue counters expose exactly the
    two conflicted events."""
    monkeypatch.setattr(de, "_WINDOW", 1)
    monkeypatch.setenv("TB_WAVES_SPECULATE", "force")
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts(range(1, 9)))]
    rows = [
        hz.transfer(100, debit_account_id=1, credit_account_id=2,
                    amount=10, flags=int(TF.pending)),
        hz.transfer(101, amount=0, pending_id=100,
                    flags=int(TF.post_pending_transfer)),
        hz.transfer(102, debit_account_id=3, credit_account_id=4,
                    amount=7),
        hz.transfer(100, debit_account_id=5, credit_account_id=6,
                    amount=3),  # duplicate id -> exists ladder
        hz.transfer(103, debit_account_id=7, credit_account_id=8,
                    amount=5),
    ]
    ops.append((Operation.create_transfers, hz.pack(rows)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 9)))))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    st = spec_counters(sm)
    assert st["attempts"] >= 1
    assert st["hits"] == 0, "the batch genuinely conflicts"
    # Exactly e1 (in-batch finalizer) and e3 (duplicate id) replay.
    assert st["residue_events"] == 2, st
    # One speculative step + a one-wave residue (e1 and e3 conflict
    # with e0, not each other... e3 shares e0/e1's id group, so the
    # residue serializes e1 before e3: two waves).
    assert st["steps"] <= 1 + 2, st
    sm.verify_device_mirror()


def test_spec_record_codec_roundtrip():
    """The sibling speculative-record codec is lossless: event dict,
    dstat seed, and serial mask round-trip bit-for-bit."""
    from test_device_waves import _random_event_dict

    rng = np.random.default_rng(77)
    n, B = 37, 64
    ev = _random_event_dict(rng, n, B)
    dstat = np.zeros(B, np.uint32)
    dstat[:3] = 2
    serial = rng.random(n) < 0.3
    pk = waves.pack_spec_record(ev, dstat, serial, n)
    ev2, dstat2, serial2 = waves.unpack_spec_record(pk)
    for name, arr in ev.items():
        assert np.array_equal(ev2[name], arr), name
        assert ev2[name].dtype == arr.dtype, name
    assert np.array_equal(dstat2, dstat)
    assert np.array_equal(serial2[:n], serial)
    assert not serial2[n:].any()
    assert pk.nbytes < pk.padded_nbytes


# ---------------------------------------------------------------------------
# Acceptance shapes.


def test_fresh_batches_hit_in_one_step(monkeypatch):
    """Fresh-unique-id batches forced through speculation: every batch
    validates conflict-free and executes in exactly ONE speculative
    device step; the partitioner never runs (plan_skipped == hits ==
    attempts == batches)."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    monkeypatch.setenv("TB_WAVES_SPECULATE", "force")
    rng = np.random.default_rng(11)
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts(range(1, 41)))]
    tid = 100
    for _ in range(6):
        rows = []
        for _k in range(16):
            a, b = rng.choice(np.arange(1, 41), 2, replace=False)
            rows.append(
                hz.transfer(tid, debit_account_id=int(a),
                            credit_account_id=int(b),
                            amount=int(rng.integers(1, 90)))
            )
            tid += 1
        ops.append((Operation.create_transfers, hz.pack(rows)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 41)))))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    st = spec_counters(sm)
    assert st["attempts"] == 6, st
    assert st["hits"] == 6, "fresh batches must validate conflict-free"
    assert st["plan_skipped"] == 6, "partitioner ran on the hit path"
    assert st["steps"] == 6, "hit batches must cost ONE device step"
    assert st["residue_events"] == 0
    assert sm.stat_host_semantic_events == 0
    sm.verify_device_mirror()


def test_two_phase_pairs_replay_finalizer_residue(monkeypatch):
    """In-batch (pending, post) pairs: the pendings commit
    speculatively, every post conflicts on its in-batch reference and
    replays as a ONE-WAVE residue — 2 device steps per batch, with
    first-wins/program-order semantics pinned by the oracle replies."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    rng = np.random.default_rng(7)
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts(range(1, 47)))]
    accs = np.arange(1, 41)
    tid = 100
    for _ in range(6):
        rows, tid = _pv_balancing_batch(
            tid, accs, rng, bal_accs=list(range(41, 47))
        )
        ops.append((Operation.create_transfers, hz.pack(rows)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 47)))))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    st = spec_counters(sm)
    assert st["attempts"] == 6
    assert st["hits"] == 0
    assert st["residue_events"] == 6 * 6, "exactly the finalizers replay"
    assert st["steps"] == 6 * 2, (
        "each miss must cost one speculative step + a one-wave residue"
    )
    assert sm.stat_host_semantic_events == 0
    sm.verify_device_mirror()


def test_residue_cap_gate_skips_serial_batches(monkeypatch):
    """Chain-dominated batches are KNOWN residue up front: the auto
    gate must skip speculation (no wasted step) and route them through
    the pessimistic wave plan — chain waves, ~max_chain_len steps."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts(range(1, 101)))]
    tid = 100
    rows = []
    for c in range(16):
        for j in range(3):
            f = int(TF.linked) if j < 2 else 0
            if j == 0:
                f |= int(TF.pending)
            rows.append(
                hz.transfer(tid, debit_account_id=1 + 2 * c,
                            credit_account_id=2 + 2 * c,
                            amount=3 + j, flags=f)
            )
            tid += 1
    ops.append((Operation.create_transfers, hz.pack(rows)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 101)))))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    st = spec_counters(sm)
    assert st["attempts"] == 0, "the residue-cap gate must not speculate"
    assert sm.stat_dev_wave_batches == 1
    assert sm.stat_dev_wave_steps == 8  # chain-wave position bucket
    sm.verify_device_mirror()


def test_forced_mode_replays_chain_residue(monkeypatch):
    """TB_WAVES_SPECULATE=force takes even known-serial batches: the
    whole chain batch conflicts, and the residue replays through chain
    waves with full-batch claim counts — replies oracle-identical, a
    failing chain still rolls back."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    monkeypatch.setenv("TB_WAVES_SPECULATE", "force")
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts(range(1, 41)))]
    rows = []
    tid = 100
    for c in range(8):
        for j in range(3):
            f = int(TF.linked) if j < 2 else 0
            if j == 0:
                f |= int(TF.pending)
            dr, cr = 1 + 2 * c, 2 + 2 * c
            if c == 3 and j == 1:
                cr = dr  # accounts_must_be_different -> chain fails
            rows.append(
                hz.transfer(tid, debit_account_id=dr,
                            credit_account_id=cr, amount=3 + j, flags=f)
            )
            tid += 1
    ops.append((Operation.create_transfers, hz.pack(rows)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 41)))))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    st = spec_counters(sm)
    assert st["attempts"] == 1
    assert st["hits"] == 0
    assert st["residue_events"] == 24, "every chain member replays"
    sm.verify_device_mirror()


# ---------------------------------------------------------------------------
# Forced-optimistic vs pessimistic waves vs CPU oracle.


@pytest.mark.parametrize("seed", [21, 22])
def test_forced_optimistic_differential(monkeypatch, seed):
    """Three arms over the SAME fuzz stream — speculation forced on
    everything, speculation off (pessimistic wave plans), and the
    default auto gate — must agree byte-for-byte on every reply AND on
    the authoritative table digest with the CPU oracle: speculation is
    an execution strategy, never a semantics change.  The stream mixes
    duplicate ids, pending/post/void, linked rollback, timeouts, and
    grow/remove interleavings (test_device_waves._fuzz_stream)."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    replies = {}
    tables = {}
    for mode in ("force", "0", "auto"):
        monkeypatch.setenv("TB_WAVES_SPECULATE", mode)
        rng = np.random.default_rng(seed)
        sm = TpuStateMachine(engine="device", account_capacity=65)
        h = hz.SingleNodeHarness(sm)
        ops = _fuzz_stream(rng)
        futs = [h.submit_async(op, body) for op, body in ops]
        replies[mode] = [f.result() for f in futs]
        sm.verify_device_mirror()
        tables[mode] = np.asarray(sm._dev.checksum())
        st = spec_counters(sm)
        if mode == "force":
            assert st["attempts"] > 0, "fuzz never speculated: vacuous"
            assert st["hits"] > 0, "no batch validated clean: weak fuzz"
            assert st["residue_events"] > 0, "no residue replayed"
        elif mode == "0":
            assert st["attempts"] == 0
        del sm, h
    rng = np.random.default_rng(seed)
    h_c = hz.SingleNodeHarness(CpuStateMachine())
    replies_c = [h_c.submit(op, body) for op, body in _fuzz_stream(rng)]
    for arm in ("force", "0", "auto"):
        for i, (a, b) in enumerate(zip(replies[arm], replies_c)):
            assert a == b, (
                f"seed {seed}: reply {i} diverges ({arm} vs CPU oracle)"
            )
    assert (tables["force"] == tables["0"]).all()
    assert (tables["auto"] == tables["0"]).all()


def test_chaos_smoke_with_speculation_on(monkeypatch):
    """Probabilistic link chaos with speculation forced on: demote /
    degraded-serve / re-promote must keep every reply oracle-identical
    — speculative records replay through their exact host fallback
    like any other in-flight record, and no in-flight bound leaks."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    monkeypatch.setattr(de, "_BACKOFF_MS", 0.0)
    monkeypatch.setattr(de, "_PROBE_EVERY", 2)
    monkeypatch.setenv("TB_WAVES_SPECULATE", "force")
    rng = np.random.default_rng(5)
    link = ChaosLink(seed=17, p_transient=0.05, p_fatal=0.0, p_kill=0.0)
    sm_d = TpuStateMachine(
        engine="device", account_capacity=(1 << 10) + 1, device_link=link
    )
    h_d = hz.SingleNodeHarness(sm_d)
    h_c = hz.SingleNodeHarness(CpuStateMachine())
    ops = _fuzz_stream(rng, n_accts=40)
    futs = []
    for k, (op, body) in enumerate(ops):
        if k in (len(ops) // 3, 2 * len(ops) // 3):
            link.fail_next(kind="fatal")
        futs.append(h_d.submit_async(op, body))
    replies_d = [f.result() for f in futs]
    for f in futs:
        assert f.done()
    replies_c = [h_c.submit(op, body) for op, body in ops]
    mismatches = [
        i for i, (a, b) in enumerate(zip(replies_d, replies_c)) if a != b
    ]
    assert not mismatches, f"replies diverge at {mismatches[:5]}"
    dev = sm_d._dev
    assert dev.stat_demotions >= 1, "chaos never demoted: weak smoke"
    assert dev.inflight_bound() == 0, "in-flight bound leaked"
    link.heal()
    link.p_transient = link.p_fatal = link.p_kill = 0.0
    assert dev.try_repromote()
    assert dev.state is EngineState.healthy
    sm_d.verify_device_mirror()
