"""Component fuzzer registry smoke tier (reference:
src/fuzz_tests.zig:24-42 — every component fuzzer runs briefly in CI;
long soaks use the same entry point with more rounds)."""

import subprocess
import sys

import pytest

from tigerbeetle_tpu.testing.fuzz import FUZZERS, SMOKE_ROUNDS


@pytest.mark.parametrize("name", sorted(FUZZERS))
@pytest.mark.parametrize("seed", [1, 77])
def test_fuzz_smoke(name, seed):
    FUZZERS[name](seed, SMOKE_ROUNDS)


def test_fuzz_cli_entry_point():
    proc = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu.testing.fuzz", "ewah",
         "--seed", "3", "--rounds", "20"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    assert "fuzz ewah: ok" in proc.stdout


def test_soak_cli_entry_point():
    """The CFO-fleet analog (testing/soak.py) runs end-to-end: a tiny
    all-kinds wave, JSONL records, zero failures expected."""
    import json
    import os
    import tempfile

    out = os.path.join(tempfile.mkdtemp(), "soak.jsonl")
    proc = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu.testing.soak", "all",
         "--n", "2", "--seed-base", "5", "--out", out],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "TB_FORCE_CPU_JAX": "1"},
    )
    assert proc.returncode == 0, proc.stderr
    records = [json.loads(line) for line in open(out)]
    assert len(records) == 2 and all(r["ok"] for r in records)
