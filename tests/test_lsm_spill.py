"""LSM spill tier: the state machine's durable state scales past RAM.

VERDICT r1 item 2's acceptance test: commit more transfer state than
the memtable holds across several checkpoints, restart from disk, and
answer every query class from the LSM tier — with checkpoint blobs
O(RAM tail), not O(history).  The CPU oracle (dict-backed, no forest)
replays the same stream as the semantic reference.
"""

import numpy as np

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing.harness import account, ids_bytes, pack, transfer
from tigerbeetle_tpu.vsr import replica as vsr_replica
from tigerbeetle_tpu.vsr.storage import MemoryStorage, ZoneLayout

CLUSTER = 11
N_ACCOUNTS = 40
BATCH = 500
N_BATCHES = 24  # 12k transfers >> forest memtable (8192)

Op = types.Operation
TF = types.TransferFlags
AF = types.AccountFlags

# test_min's 4KiB messages cap batches at 30 events; this scenario
# needs batches big enough to outgrow the forest memtable quickly.
CONF = cfg.Config(
    name="test_spill",
    message_size_max=1 << 16,
    lsm_batch_multiple=4,
    pipeline_prepare_queue_max=4,
    journal_slot_count=64,
    clients_max=4,
)


def layout():
    return ZoneLayout(config=CONF, grid_size=1 << 20)


def make_tpu_replica(storage):
    r = vsr_replica.Replica(storage, CLUSTER, TpuStateMachine(CONF))
    r.open()
    return r


def build_stream():
    """[(op, body, checkpoint_after)] — accounts, posted transfers,
    one pending/post pair crossing a checkpoint, history accounts."""
    rng = np.random.default_rng(7)
    ops = []
    accounts = [
        # History on a few accounts exercises the history spill.
        account(i, flags=int(AF.history) if i <= 4 else 0)
        for i in range(1, N_ACCOUNTS + 1)
    ]
    ops.append((Op.create_accounts, pack(accounts), False))

    next_id = 1
    pending_id = None
    for b in range(N_BATCHES):
        rows = []
        for _ in range(BATCH):
            dr = int(rng.integers(1, N_ACCOUNTS + 1))
            cr = dr % N_ACCOUNTS + 1
            rows.append(
                transfer(
                    next_id, debit_account_id=dr, credit_account_id=cr,
                    amount=int(rng.integers(1, 50)),
                )
            )
            next_id += 1
        # A live pending created BEFORE a checkpoint and posted well
        # after: the checkpoint spills it (live pendings spill too —
        # a stuck pending must not pin RAM), so the post finalizes a
        # SPILLED pending via the LSM status update path.
        if b == 4:
            rows[-1] = transfer(
                next_id - 1, debit_account_id=5, credit_account_id=6,
                amount=17, flags=int(TF.pending),
            )
            pending_id = next_id - 1
        if b == 9:
            rows[0] = transfer(
                next_id - BATCH, amount=0,
                flags=int(TF.post_pending_transfer), pending_id=pending_id,
            )
        ops.append(
            (Op.create_transfers, pack(rows), b % 6 == 5)
        )
    return ops, next_id - 1


def replay(r, ops, *, checkpoint=True, restart_at=None, storage=None):
    replies = []
    blob_sizes = []
    for i, (op, body, ckpt) in enumerate(ops):
        replies.append(r.on_request(int(op), body))
        if ckpt and checkpoint:
            r.checkpoint()
            blob_sizes.append(
                int(r.superblock.working["checkpoint_size"])
            )
        if restart_at is not None and i == restart_at:
            r = make_tpu_replica(storage)
    return r, replies, blob_sizes


def query_suite(r, max_tid):
    """Wire-level bytes for every query class."""
    out = []
    ids = list(range(1, N_ACCOUNTS + 1))
    out.append(r.on_request(int(Op.lookup_accounts), ids_bytes(ids)))
    # Old (spilled), middle, and recent transfer ids.
    sample = [1, 2, 3, max_tid // 2, max_tid - 1, max_tid, max_tid + 999]
    out.append(r.on_request(int(Op.lookup_transfers), ids_bytes(sample)))
    for acct in (1, 5, 17):
        for flags, rev in ((3, 0), (1, 0), (2, 0), (3, 4)):
            f = np.zeros(1, types.ACCOUNT_FILTER_DTYPE)
            f[0]["account_id_lo"] = acct
            f[0]["limit"] = 100
            f[0]["flags"] = flags | rev
            out.append(
                r.on_request(int(Op.get_account_transfers), f.tobytes())
            )
    # Historical balances on a history-flagged account.
    f = np.zeros(1, types.ACCOUNT_FILTER_DTYPE)
    f[0]["account_id_lo"] = 2
    f[0]["limit"] = 50
    f[0]["flags"] = 3
    out.append(r.on_request(int(Op.get_account_balances), f.tobytes()))
    return out


def test_spill_across_checkpoints_restart_and_queries():
    ops, max_tid = build_stream()

    # TPU replica with LSM forest over (sparse) memory storage.
    storage = MemoryStorage(layout())
    vsr_replica.format(storage, CLUSTER)
    r_tpu = make_tpu_replica(storage)
    assert r_tpu.forest is not None
    r_tpu, replies_tpu, blob_sizes = replay(r_tpu, ops)

    # Oracle: plain CPU replica, no forest, same stream.
    storage_cpu = MemoryStorage(layout())
    vsr_replica.format(storage_cpu, CLUSTER)
    r_cpu = vsr_replica.Replica(
        storage_cpu, CLUSTER, CpuStateMachine(CONF)
    )
    r_cpu.open()
    assert r_cpu.forest is None
    r_cpu, replies_cpu, _ = replay(r_cpu, ops, checkpoint=False)

    assert replies_tpu == replies_cpu

    # Spill actually happened, and most rows left RAM.
    sm = r_tpu.sm
    assert sm._store.base > 8_000, sm._store.base
    assert sm._store.ram.count < 5_000
    assert sm._hspill.base > 0

    # Checkpoint blobs are O(tail): raw transfer state is ~1.5MB+ by
    # the last checkpoint; blobs must stay far below it and must not
    # grow with history.
    raw_state = max_tid * 128
    assert raw_state > 1_500_000
    assert max(blob_sizes) < 600_000, blob_sizes
    assert blob_sizes[-1] < blob_sizes[0] + 200_000

    # Every query class answers identically from LSM + RAM tail.
    q_tpu = query_suite(r_tpu, max_tid)
    q_cpu = query_suite(r_cpu, max_tid)
    assert q_tpu == q_cpu

    # Restart from disk: recovery opens the forest from its manifest.
    r_tpu2 = make_tpu_replica(storage)
    assert r_tpu2.sm._store.base == sm._store.base
    q2 = query_suite(r_tpu2, max_tid)
    assert q2 == q_cpu

    # Duplicate-id resubmission of a long-spilled transfer still hits
    # the exists ladder (duplicate detection spans the LSM tier).
    dup = pack(
        [transfer(1, debit_account_id=1, credit_account_id=2, amount=1)]
    )
    rep_t = r_tpu2.on_request(int(Op.create_transfers), dup)
    rep_c = r_cpu.on_request(int(Op.create_transfers), dup)
    assert rep_t == rep_c
    arr = np.frombuffer(rep_t, types.CREATE_RESULT_DTYPE)
    assert len(arr) == 1  # some exists_* / exists code, not success


def test_state_sync_ships_spilled_blocks():
    """A deeply-lagged TPU replica rejoins via state sync: the sync
    payload must carry the sender's live LSM grid blocks, or the
    installed manifest would reference blocks the receiver never had
    (reference: src/vsr/grid_blocks_missing.zig)."""
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.testing.harness import pack as hpack

    c = Cluster(
        replica_count=3, seed=77,
        state_machine_factory=lambda: TpuStateMachine(cfg.TEST_MIN),
    )
    client = c.client(1000)
    client.register()
    c.run_until(lambda: client.registered)
    c.run_request(client, Op.create_accounts, hpack([account(1), account(2)]))
    c.network.partition(2)
    interval = c.replicas[0].config.vsr_checkpoint_interval
    for k in range(3 * interval):
        c.run_request(
            client, Op.create_transfers,
            hpack(
                [
                    transfer(
                        1000 + k, debit_account_id=1, credit_account_id=2,
                        amount=1,
                    )
                ]
            ),
        )
    assert c.replicas[0].checkpoint_op > 0
    assert c.replicas[0].sm._store.base > 0  # sender actually spilled
    assert c.replicas[2].commit_min < c.replicas[0].commit_min
    c.network.heal()
    c.settle(max_steps=20000)
    for _ in range(50):
        c.step()
    c.check_convergence()
    lagged = c.replicas[2].sm
    assert lagged._store.base > 0
    # The synced replica answers queries over rows it only ever
    # received as shipped grid blocks.
    assert lagged.transfer_timestamp(1000) is not None
    assert lagged.transfer_timestamp(1000 + 3 * interval - 1) is not None


def test_spill_restart_midstream():
    """Restart between checkpoints: WAL replay on top of a spilled
    checkpoint must reconverge with the oracle."""
    ops, max_tid = build_stream()
    storage = MemoryStorage(layout())
    vsr_replica.format(storage, CLUSTER)
    r = make_tpu_replica(storage)
    r, replies_tpu, _ = replay(
        r, ops, restart_at=len(ops) // 2, storage=storage
    )

    storage_cpu = MemoryStorage(layout())
    vsr_replica.format(storage_cpu, CLUSTER)
    r_cpu = vsr_replica.Replica(
        storage_cpu, CLUSTER, CpuStateMachine(CONF)
    )
    r_cpu.open()
    r_cpu, replies_cpu, _ = replay(r_cpu, ops, checkpoint=False)

    q_tpu = query_suite(r, max_tid)
    q_cpu = query_suite(r_cpu, max_tid)
    assert q_tpu == q_cpu
