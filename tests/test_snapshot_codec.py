"""utils/snapshot.py: the fixed-layout checkpoint codec.

The codec replaced pickle for every durable blob; these tests pin the
round-trip contract and the decode hardening (state sync feeds this
decoder bytes received from peers — reference discipline:
src/vsr/checksum.zig:1-10 verify-before-cast).
"""

import numpy as np
import pytest

from tigerbeetle_tpu.utils import snapshot as sc


def test_roundtrip_types():
    tree = {
        "a": np.arange(7, dtype=np.uint64),
        "b": {"c": np.zeros((3, 8), np.uint8), "d": (1 << 100) + 17},
        "e": b"raw-bytes",
        "f": np.array([True, False]),
        "g": np.zeros(0, np.uint32),
        "keys": np.zeros(4, "V16"),
    }
    blob = sc.encode_tree(tree)
    out = sc.decode_tree(blob)
    assert out["b"]["d"] == (1 << 100) + 17
    assert out["e"] == b"raw-bytes"
    np.testing.assert_array_equal(out["a"], tree["a"])
    np.testing.assert_array_equal(out["b"]["c"], tree["b"]["c"])
    np.testing.assert_array_equal(out["f"], tree["f"])
    assert out["g"].dtype == np.uint32 and len(out["g"]) == 0
    assert out["keys"].dtype == np.dtype("V16")


def test_canonical():
    tree = {"x": np.arange(5, dtype=np.int64), "y": 3}
    assert sc.encode_tree(tree) == sc.encode_tree(tree)


def test_checksum_detects_flips():
    blob = bytearray(sc.encode({"x": np.arange(100, dtype=np.uint64)}))
    for at in (len(blob) - 1, len(sc.MAGIC) + 12 + 32 + 2):
        flipped = bytearray(blob)
        flipped[at] ^= 0x40
        with pytest.raises(sc.SnapshotError):
            sc.decode(bytes(flipped))


def test_truncation_rejected():
    blob = sc.encode({"x": np.arange(100, dtype=np.uint64)})
    for cut in (4, len(sc.MAGIC) + 5, len(blob) - 7):
        with pytest.raises(sc.SnapshotError):
            sc.decode(blob[:cut])


def test_bad_magic_rejected():
    blob = sc.encode({"x": 1})
    with pytest.raises(sc.SnapshotError):
        sc.decode(b"PICKLE00" + blob[8:])


def test_object_dtype_rejected_on_encode():
    with pytest.raises(sc.SnapshotError):
        sc.encode({"x": np.array([object()])})


def test_hostile_dtype_rejected_on_decode():
    # Forge a blob whose dtype string is not allowlisted; the payload
    # checksum is valid, so this exercises the dtype gate itself.
    import hashlib
    import struct

    key = b"x"
    ds = b"O8"  # object dtype: would be code execution under pickle
    meta = struct.pack("<BH", 0, len(ds)) + ds + struct.pack("<BQ", 1, 8)
    entry = struct.pack("<H", len(key)) + key + meta + struct.pack("<Q", 8)
    entry += b"\x00" * 8
    blob = (
        sc.MAGIC
        + struct.pack("<IQ", 1, len(entry))
        + hashlib.sha256(entry).digest()
        + entry
    )
    with pytest.raises(sc.SnapshotError):
        sc.decode(blob)


def test_duplicate_key_rejected():
    one = sc.encode({"x": 1})
    # Duplicate the single entry and fix up the header.
    import hashlib
    import struct

    payload = one[len(sc.MAGIC) + 12 + 32 :]
    doubled = payload + payload
    blob = (
        sc.MAGIC
        + struct.pack("<IQ", 2, len(doubled))
        + hashlib.sha256(doubled).digest()
        + doubled
    )
    with pytest.raises(sc.SnapshotError):
        sc.decode(blob)


def test_size_mismatch_rejected():
    import hashlib
    import struct

    key = b"x"
    ds = b"<u8"
    # claims shape (2,) but ships 8 bytes
    meta = struct.pack("<BH", 0, len(ds)) + ds + struct.pack("<BQ", 1, 2)
    entry = struct.pack("<H", len(key)) + key + meta + struct.pack("<Q", 8)
    entry += b"\x00" * 8
    blob = (
        sc.MAGIC
        + struct.pack("<IQ", 1, len(entry))
        + hashlib.sha256(entry).digest()
        + entry
    )
    with pytest.raises(sc.SnapshotError):
        sc.decode(blob)


def test_no_pickle_in_durable_paths():
    """pickle must stay out of vsr/, state_machine/, and lsm/ — the
    checkpoint/state-sync surface (VERDICT r1 item 3)."""
    import pathlib

    root = pathlib.Path(__file__).resolve().parent.parent / "tigerbeetle_tpu"
    offenders = []
    for sub in ("vsr", "state_machine", "lsm"):
        for path in (root / sub).rglob("*.py"):
            text = path.read_text()
            if any(
                pat in text
                for pat in ("import pickle", "pickle.loads", "pickle.dumps")
            ):
                offenders.append(str(path))
    assert not offenders, offenders


def test_sm_snapshot_restore_roundtrip_binary():
    """Both engines' snapshots decode with the codec (no pickle) and
    restore to equivalent state."""
    from tigerbeetle_tpu.state_machine.cpu import CpuStateMachine
    from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
    from tigerbeetle_tpu.testing import SingleNodeHarness, account, transfer
    from tigerbeetle_tpu.types import TransferFlags

    for cls in (CpuStateMachine, TpuStateMachine):
        sm = cls()
        h = SingleNodeHarness(sm)
        h.create_accounts([account(1), account(2), account(3)])
        h.create_transfers(
            [transfer(10, debit_account_id=1, credit_account_id=2, amount=5)]
        )
        h.create_transfers(
            [
                transfer(
                    11, debit_account_id=2, credit_account_id=3, amount=9,
                    flags=TransferFlags.pending, timeout=60,
                )
            ]
        )
        blob = sm.snapshot()
        sc.decode(blob)  # structurally valid, checksummed, pickle-free
        sm2 = cls()
        sm2.restore(blob)
        assert sm2.snapshot() == blob
        h2 = SingleNodeHarness(sm2)
        h2.op = h.op
        rows = h2.lookup_accounts([1, 2, 3])
        assert len(rows) == 3
