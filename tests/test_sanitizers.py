"""Native sanitizer wiring (round 17, slow tier).

Rebuilds both native libraries under -fsanitize=address,undefined
(`make -C native asan`) and replays the fastpath fixture differential
plus a torn-frame / oversize-frame fuzz through them in a subprocess
with the asan runtime LD_PRELOADed (Python itself isn't instrumented,
so the runtime must be injected first).  The subprocess output is
parsed for sanitizer reports — a replay that "passes" while asan
printed an error must still fail here.

Slow-marked: the rebuild + instrumented replay costs ~a minute; the
lint pass (tests/test_tbcheck.py) always runs, this rides the slow
tier (pytest -m slow).
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")

pytestmark = pytest.mark.slow


def _asan_runtime() -> str | None:
    gcc = shutil.which("gcc") or shutil.which("cc")
    if gcc is None:
        return None
    try:
        path = subprocess.run(
            [gcc, "-print-file-name=libasan.so"],
            capture_output=True, text=True, timeout=30,
        ).stdout.strip()
    except (OSError, subprocess.SubprocessError):
        return None
    return path if path and os.path.exists(path) else None


def _sanitizer_report(text: str) -> bool:
    return ("AddressSanitizer" in text
            or "runtime error:" in text          # UBSan
            or "LeakSanitizer" in text)


@pytest.mark.skipif(shutil.which("make") is None, reason="no make")
@pytest.mark.skipif(_asan_runtime() is None, reason="no asan runtime")
def test_fastpath_replay_under_asan():
    build = subprocess.run(
        ["make", "-C", NATIVE, "asan"],
        capture_output=True, text=True, timeout=300,
    )
    assert build.returncode == 0, build.stderr[-2000:]
    for lib in ("libtb_runtime.so", "libtb_fastpath.so"):
        assert os.path.exists(os.path.join(NATIVE, "asan", lib))

    env = dict(os.environ)
    env.update(
        LD_PRELOAD=_asan_runtime(),
        # Python leaks by design; the replay hunts heap/stack/UB bugs
        # in OUR libraries, not CPython allocator noise.
        ASAN_OPTIONS="detect_leaks=0:abort_on_error=1",
        UBSAN_OPTIONS="print_stacktrace=1:halt_on_error=1",
        TB_NATIVE_SANITIZE="asan",
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "asan_replay.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    combined = proc.stdout + "\n" + proc.stderr
    assert proc.returncode == 0, combined[-4000:]
    assert "ASAN-REPLAY-OK" in proc.stdout, combined[-4000:]
    # Every replay stage actually ran.
    for marker in ("fixture differential ok", "finalize parity ok",
                   "torn-frame fuzz ok", "pipeline fuzz ok",
                   "oversize-frame fuzz ok"):
        assert marker in proc.stdout, combined[-4000:]
    assert not _sanitizer_report(combined), combined[-4000:]


def test_asan_build_failure_names_flavor(tmp_path, monkeypatch):
    """runtime/native.py build-failure forensics must name the
    sanitizer flavor attempted — a broken `make asan` must never read
    as a broken release build (fast: no real build, make is stubbed
    to fail)."""
    fake_bin = tmp_path / "bin"
    fake_bin.mkdir()
    fake_make = fake_bin / "make"
    fake_make.write_text("#!/bin/sh\necho boom >&2\nexit 3\n")
    fake_make.chmod(0o755)
    monkeypatch.setenv(
        "PATH", f"{fake_bin}:{os.environ.get('PATH', '')}"
    )
    monkeypatch.setenv("TB_NATIVE_SANITIZE", "asan")
    code = (
        "import warnings; warnings.simplefilter('ignore');"
        "from tigerbeetle_tpu.runtime import native;"
        "native._run_make(native._LIB_PATH);"
        "print(native.build_error())"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env=dict(os.environ, PATH=f"{fake_bin}:{os.environ['PATH']}",
                 TB_NATIVE_SANITIZE="asan", JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = proc.stdout.strip()
    assert "make -C native asan failed" in out, out
    assert "sanitizer=asan" in out, out
