"""Root-attested follower serving (round 19).

Layers under test, bottom-up:
- AofTail: offset-resumable chunked tailing, torn-tail-then-heal,
  mid-record truncation, corrupt-vs-torn classification.
- AOF repair-on-open + recovery gap-fill (the writer-side half of the
  follower's gap-free-stream contract).
- FollowerCore: attestation gate (unattested / lagging / poisoned /
  corrupt / gap / overload / not_readable), byte-charged read
  admission, bit-identical serving.
- The deterministic sim (SimFollower) differential: every read op the
  follower serves is byte-identical to the primary's executor.
- Pinned FollowerVopr seeds: crash mid-tail, torn AOF via upstream
  crash (incl. crash-inside-fsync), corrupt tailed sector, partition,
  lag — refuse-not-lie asserted throughout.
"""

import numpy as np
import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.runtime.follower import (
    FollowerCore,
    FollowerRefusal,
    FollowerReply,
)
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.testing.harness import account, ids_bytes, pack, transfer
from tigerbeetle_tpu.vsr import aof as aof_mod
from tigerbeetle_tpu.vsr import replica as vsr_replica
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.aof import AOF, AofTail, BytesSource
from tigerbeetle_tpu.vsr.storage import MemoryStorage, ZoneLayout
from tigerbeetle_tpu.vsr.wire import FollowerRefuse

CLUSTER = 5


def _record(op: int, body: bytes = b"x" * 64, operation: int = 129,
            timestamp: int | None = None) -> bytes:
    h = wire.make_header(
        command=wire.Command.prepare, cluster=CLUSTER, op=op,
        operation=operation,
        timestamp=timestamp if timestamp is not None else op,
    )
    wire.finalize_header(h, body)
    return h.tobytes() + body


# ----------------------------------------------------------------------
# AofTail


def test_tail_resumes_from_offset():
    buf = bytearray(_record(1) + _record(2) + _record(3))
    tail = AofTail(BytesSource(buf))
    got = tail.poll()
    assert [int(h["op"]) for h, _b in got] == [1, 2, 3]
    assert not tail.torn and not tail.corrupt
    saved = tail.offset
    buf += _record(4) + _record(5)
    assert [int(h["op"]) for h, _ in tail.poll()] == [4, 5]
    # A fresh tail constructed at the saved offset resumes exactly.
    resumed = AofTail(BytesSource(buf), offset=saved)
    assert [int(h["op"]) for h, _ in resumed.poll()] == [4, 5]


def test_tail_torn_then_heal():
    rec = _record(2)
    buf = bytearray(_record(1) + rec[: len(rec) - 17])  # mid-record cut
    tail = AofTail(BytesSource(buf))
    assert [int(h["op"]) for h, _ in tail.poll()] == [1]
    at = tail.offset
    assert tail.torn and not tail.corrupt
    assert tail.poll() == [] and tail.offset == at  # parked, resumable
    buf += rec[len(rec) - 17:]  # the writer completes the record
    assert [int(h["op"]) for h, _ in tail.poll()] == [2]
    assert not tail.torn


def test_tail_mid_header_truncation():
    buf = bytearray(_record(1) + _record(2)[:100])  # inside the header
    tail = AofTail(BytesSource(buf))
    assert [int(h["op"]) for h, _ in tail.poll()] == [1]
    assert tail.torn and not tail.corrupt


def test_tail_corrupt_mid_file_refuses():
    buf = bytearray(_record(1) + _record(2) + _record(3))
    rec1 = len(_record(1))
    buf[rec1 + 40] ^= 0xFF  # inside record 2's header, records follow
    tail = AofTail(BytesSource(buf))
    got = tail.poll()
    assert [int(h["op"]) for h, _ in got] == [1]
    assert tail.corrupt and tail.corrupt_reason
    assert tail.poll() == []  # latched: never skips ahead


def test_tail_corrupt_body_mid_file_refuses():
    buf = bytearray(_record(1) + _record(2) + _record(3))
    rec1 = len(_record(1))
    buf[rec1 + 256 + 5] ^= 0xFF  # inside record 2's body
    tail = AofTail(BytesSource(buf))
    assert [int(h["op"]) for h, _ in tail.poll()] == [1]
    assert tail.corrupt


def test_tail_corruption_at_eof_reads_as_torn():
    # A damaged FINAL record cannot be distinguished from a crash
    # artifact — the conservative read is torn (stall), never serving.
    buf = bytearray(_record(1) + _record(2))
    buf[len(_record(1)) + 300] ^= 0xFF  # final record's body
    tail = AofTail(BytesSource(buf))
    assert [int(h["op"]) for h, _ in tail.poll()] == [1]
    assert tail.torn and not tail.corrupt


def test_tail_shrink_below_offset_waits():
    buf = bytearray(_record(1) + _record(2))
    tail = AofTail(BytesSource(buf))
    assert len(tail.poll()) == 2
    del buf[len(_record(1)):]  # writer crashed + repaired below us
    assert tail.poll() == []
    assert tail.torn and not tail.corrupt
    buf += _record(2)  # gap-fill re-appends the identical bytes
    assert tail.poll() == []  # boundary restored, nothing new yet
    buf += _record(3)
    assert [int(h["op"]) for h, _ in tail.poll()] == [3]


def test_tail_chunked_reads_cross_boundaries():
    big = _record(1, body=b"A" * 5000)
    buf = bytearray(big * 1)
    for op in range(2, 40):
        buf += _record(op, body=bytes([op % 256]) * 700)
    tail = AofTail(BytesSource(buf), chunk_bytes=1 << 12)  # < one record
    ops = [int(h["op"]) for h, _ in tail.poll()]
    assert ops == list(range(1, 40))


# ----------------------------------------------------------------------
# AOF writer: repair-on-open + recovery gap-fill


def _fresh_replica(storage, path):
    sm = CpuStateMachine(cfg.TEST_MIN)
    r = vsr_replica.Replica(storage, CLUSTER, sm, aof=AOF(path))
    r.open()
    return r


def test_aof_repair_truncates_torn_tail(tmp_path):
    path = str(tmp_path / "log.aof")
    storage = MemoryStorage(ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 20))
    vsr_replica.format(storage, CLUSTER)
    r = _fresh_replica(storage, path)
    r.on_request(types.Operation.create_accounts,
                 pack([account(1), account(2)]))
    r.on_request(
        types.Operation.create_transfers,
        pack([transfer(9, debit_account_id=1, credit_account_id=2,
                       amount=11)]),
    )
    r.aof.sync()
    r.aof.close()
    whole = open(path, "rb").read()
    # Tear the final record mid-body.
    open(path, "wb").write(whole[:-20])
    repaired = AOF(path)
    size = len(open(path, "rb").read())
    assert size < len(whole) - 20  # truncated to a record boundary
    entries = list(aof_mod.iterate(path))
    assert entries  # verified prefix intact
    assert repaired.last_op == max(int(h["op"]) for h, _ in entries)
    repaired.close()


def test_recovery_gap_fill_restores_stream(tmp_path):
    """A crash that erases the AOF's unsynced tail while the WAL kept
    the ops: recovery replay re-appends exactly the missing records,
    so a replay of the AOF reaches the identical state."""
    path = str(tmp_path / "log.aof")
    storage = MemoryStorage(ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 20))
    vsr_replica.format(storage, CLUSTER)
    r = _fresh_replica(storage, path)
    r.on_request(types.Operation.create_accounts,
                 pack([account(1), account(2)]))
    for k in range(3):
        r.on_request(
            types.Operation.create_transfers,
            pack([transfer(100 + k, debit_account_id=1,
                           credit_account_id=2, amount=5)]),
        )
    final_snapshot = r.sm.snapshot()
    r.aof.close()
    # Crash model: the last two records never hit the disk.
    whole = open(path, "rb").read()
    entries = list(aof_mod.iterate(path))
    keep = sum(int(h["size"]) for h, _ in entries[:-2])
    open(path, "wb").write(whole[:keep])
    # Restart over the same (synced) storage: recovery replays the WAL
    # and must gap-fill the AOF's lost tail.
    r2 = _fresh_replica(storage, path)
    assert r2.sm.snapshot() == final_snapshot
    r2.aof.sync()
    fresh = CpuStateMachine(cfg.TEST_MIN)
    aof_mod.replay(path, fresh, cluster=CLUSTER)
    assert fresh.snapshot() == final_snapshot
    ops = [int(h["op"]) for h, _ in aof_mod.iterate(path)]
    assert ops == sorted(ops) and len(set(ops)) == len(ops)
    assert max(ops) == r2.commit_min


# ----------------------------------------------------------------------
# FollowerCore over a single-replica primary (SimAof interface)


class _Primary:
    """Single-replica primary whose AOF is an in-memory buffer the
    core tails — the smallest honest commit-stream producer."""

    def __init__(self, root_ring: int = 1 << 12):
        from tigerbeetle_tpu.testing.cluster import SimAof

        self.aof = SimAof()
        self.storage = MemoryStorage(
            ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 20)
        )
        vsr_replica.format(self.storage, CLUSTER)
        self.replica = vsr_replica.Replica(
            self.storage, CLUSTER, CpuStateMachine(cfg.TEST_MIN),
            aof=self.aof,
        )
        self.replica.open()
        self.replica.enable_root_ring(root_ring)

    def seed_accounts(self, n: int = 4):
        self.replica.on_request(
            types.Operation.create_accounts,
            pack([account(i) for i in range(1, n + 1)]),
        )

    def transfer(self, tid: int, dr: int = 1, cr: int = 2, amount: int = 7):
        self.replica.on_request(
            types.Operation.create_transfers,
            pack([transfer(tid, debit_account_id=dr, credit_account_id=cr,
                           amount=amount)]),
        )

    def attest(self, core: FollowerCore, at: int | None = None):
        r = self.replica
        op = r.commit_min if at is None else at
        root = r.root_at(op)
        assert root is not None, op
        core.on_attestation(root, op)


def _core(primary: _Primary, **kw) -> FollowerCore:
    kw.setdefault("staleness_ops", 8)
    return FollowerCore(
        primary.aof.source(), cluster=CLUSTER,
        state_machine=CpuStateMachine(cfg.TEST_MIN), **kw,
    )


def test_core_refuses_unattested_then_serves():
    p = _Primary()
    p.seed_accounts()
    p.transfer(900)
    core = _core(p)
    assert core.pump() > 0
    got = core.serve(int(types.Operation.lookup_accounts), ids_bytes([1, 2]))
    assert isinstance(got, FollowerRefusal)
    assert got.reason == FollowerRefuse.unattested
    p.attest(core)
    assert core.refuse_reason() is None
    got = core.serve(int(types.Operation.lookup_accounts), ids_bytes([1, 2]))
    assert isinstance(got, FollowerReply)
    assert got.commit_min == p.replica.commit_min
    assert got.root == p.replica.root_at(p.replica.commit_min)
    want = p.replica.sm.execute_read(
        types.Operation.lookup_accounts, ids_bytes([1, 2])
    )
    assert got.body == want


def test_core_lagging_refusal_is_a_redirect():
    p = _Primary()
    p.seed_accounts()
    core = _core(p, staleness_ops=4)
    core.pump()
    p.attest(core)
    assert core.refuse_reason() is None
    # Commits continue; the follower does NOT pump (lag injection),
    # but hears about the primary's head via attestation.
    for k in range(6):
        p.transfer(1000 + k)
    p.attest(core)  # current head: lag estimate refreshes
    assert core.lag_ops() > 4
    got = core.serve(int(types.Operation.lookup_accounts), ids_bytes([1]))
    assert isinstance(got, FollowerRefusal)
    assert got.reason == FollowerRefuse.lagging
    snap = core.registry.snapshot()
    assert snap["follower.redirects"] == 1  # lagging = transient
    assert snap["follower.refused"] == 0    # no integrity refusal here
    assert snap["follower.lag_ops"] == core.lag_ops()
    # Catching up clears it.
    core.pump()
    p.attest(core)
    assert core.refuse_reason() is None


def test_core_poisons_on_root_mismatch():
    p = _Primary()
    p.seed_accounts()
    core = _core(p)
    core.pump()
    wrong = bytes(range(16))
    core.on_attestation(wrong, core.commit_min)
    assert core.poisoned
    got = core.serve(int(types.Operation.lookup_accounts), ids_bytes([1]))
    assert isinstance(got, FollowerRefusal)
    assert got.reason == FollowerRefuse.poisoned
    # Terminal: a later GOOD attestation does not resurrect it.
    p.attest(core)
    assert core.refuse_reason() == FollowerRefuse.poisoned
    assert core.registry.snapshot()["follower.attest_mismatch"] == 1


def test_core_gap_refuses():
    p = _Primary()
    p.seed_accounts()
    p.transfer(900)
    # Splice a middle record out of the log: op discontinuity.
    buf = p.aof.buffer
    tail = AofTail(BytesSource(buf))
    entries = tail.poll()
    assert len(entries) >= 3
    first = int(entries[0][0]["size"])
    second = int(entries[1][0]["size"])
    spliced = bytearray(bytes(buf[:first]) + bytes(buf[first + second:]))
    core = FollowerCore(
        BytesSource(spliced), cluster=CLUSTER,
        state_machine=CpuStateMachine(cfg.TEST_MIN), staleness_ops=8,
    )
    core.pump()
    assert core.gapped
    got = core.serve(int(types.Operation.lookup_accounts), ids_bytes([1]))
    assert isinstance(got, FollowerRefusal)
    assert got.reason == FollowerRefuse.gap


def test_core_corrupt_refuses():
    p = _Primary()
    p.seed_accounts()
    core = _core(p)
    core.pump()
    p.attest(core)
    assert core.refuse_reason() is None
    # Corrupt a byte AHEAD of the follower, then commit more so the
    # bad record is mid-file (unambiguously corrupt, not torn).
    at = len(p.aof.buffer) + 40
    p.transfer(901)
    p.transfer(902)
    p.aof.buffer[at] ^= 0xFF
    core.pump()
    assert core.tail.corrupt
    got = core.serve(int(types.Operation.lookup_accounts), ids_bytes([1]))
    assert isinstance(got, FollowerRefusal)
    assert got.reason == FollowerRefuse.corrupt
    assert core.registry.snapshot()["follower.tail_corrupt"] == 1


def test_core_not_readable():
    p = _Primary()
    p.seed_accounts()
    core = _core(p)
    core.pump()
    p.attest(core)
    got = core.serve(int(types.Operation.create_transfers), b"")
    assert isinstance(got, FollowerRefusal)
    assert got.reason == FollowerRefuse.not_readable


def test_core_read_admission_charges_bytes():
    from tigerbeetle_tpu.qos import TenantQos

    p = _Primary()
    p.seed_accounts()
    qos = TenantQos(rate_bytes=100.0)  # burst = 100 body bytes
    core = _core(p, qos=qos)
    core.pump()
    p.attest(core)
    body = ids_bytes([1, 2, 3, 4])  # 64 body bytes
    t0 = 1_000_000_000
    assert isinstance(
        core.serve(int(types.Operation.lookup_accounts), body, now_ns=t0),
        FollowerReply,
    )
    got = core.serve(int(types.Operation.lookup_accounts), body, now_ns=t0)
    assert isinstance(got, FollowerRefusal)
    assert got.reason == FollowerRefuse.overload
    # ~1 second refills the byte budget.
    assert isinstance(
        core.serve(int(types.Operation.lookup_accounts), body,
                   now_ns=t0 + 10**9),
        FollowerReply,
    )


def test_core_serves_all_read_ops_bit_identically():
    p = _Primary()
    p.replica.on_request(
        types.Operation.create_accounts,
        pack([account(i, flags=int(types.AccountFlags.history))
              for i in range(1, 5)]),
    )
    for k in range(6):
        p.transfer(700 + k, dr=1 + k % 3, cr=2 + k % 3, amount=3 + k)
    core = _core(p)
    core.pump()
    p.attest(core)
    filt = np.zeros(1, types.ACCOUNT_FILTER_DTYPE)[0]
    types.u128_set(filt, "account_id", 1)
    filt["limit"] = 100
    filt["flags"] = (types.AccountFilterFlags.debits
                     | types.AccountFilterFlags.credits)
    cases = [
        (types.Operation.lookup_accounts, ids_bytes([1, 2, 3, 4])),
        (types.Operation.lookup_transfers, ids_bytes([700, 701, 999])),
        (types.Operation.get_account_transfers, filt.tobytes()),
        (types.Operation.get_account_balances, filt.tobytes()),
    ]
    for op, body in cases:
        got = core.serve(int(op), body)
        assert isinstance(got, FollowerReply), (op, got)
        assert got.body == p.replica.sm.execute_read(op, body), op


def test_execute_read_has_no_state_effects():
    p = _Primary()
    p.seed_accounts()
    p.transfer(700)
    sm = p.replica.sm
    before = sm.snapshot()
    ts_before = (sm.commit_timestamp, sm.prepare_timestamp,
                 sm.pulse_next_timestamp)
    sm.execute_read(types.Operation.lookup_accounts, ids_bytes([1, 2]))
    filt = np.zeros(1, types.ACCOUNT_FILTER_DTYPE)[0]
    types.u128_set(filt, "account_id", 1)
    filt["limit"] = 8
    filt["flags"] = types.AccountFilterFlags.debits
    sm.execute_read(types.Operation.get_account_transfers, filt.tobytes())
    assert sm.snapshot() == before
    assert (sm.commit_timestamp, sm.prepare_timestamp,
            sm.pulse_next_timestamp) == ts_before


# ----------------------------------------------------------------------
# Deterministic sim: crash mid-tail + resume-offset stability


def test_sim_follower_crash_restart_reattests():
    from tigerbeetle_tpu.testing.cluster import Cluster, SimFollower

    c = Cluster(replica_count=2, seed=3, aof_replicas=(0,),
                root_ring=1 << 16)
    f = SimFollower(c, 0, staleness_ops=64)
    cl = c.client(0x900)
    cl.register()
    c.run_until(lambda: not cl.busy())
    acc = pack([account(i) for i in range(1, 4)])
    c.run_request(cl, types.Operation.create_accounts, acc)
    c.run_request(
        cl, types.Operation.create_transfers,
        pack([transfer(9, debit_account_id=1, credit_account_id=2,
                       amount=11)]),
    )
    c.settle()
    for _ in range(40):
        c.step()
    assert f.core.refuse_reason() is None
    got = f.read(types.Operation.lookup_accounts, ids_bytes([1, 2]))
    assert isinstance(got, FollowerReply)
    # kill -9 mid-tail: everything volatile dies with the process.
    f.crash_restart()
    got = f.read(types.Operation.lookup_accounts, ids_bytes([1, 2]))
    assert isinstance(got, FollowerRefusal)  # unattested again
    for _ in range(60):
        c.step()
    got = f.read(types.Operation.lookup_accounts, ids_bytes([1, 2]))
    assert isinstance(got, FollowerReply)
    want = c.replicas[0].sm.execute_read(
        types.Operation.lookup_accounts, ids_bytes([1, 2])
    )
    assert got.body == want
    f.check_never_lied()


# ----------------------------------------------------------------------
# Pinned VOPR seeds (tier-1): each locks a nemesis scenario the sweep
# surfaced.  The coverage asserts keep the seed honest — a code change
# that silently defuses the nemesis fails here, not in a soak.


@pytest.mark.parametrize(
    "seed, expect",
    [
        # Torn tail (upstream crash) + partition + pause + a corrupt
        # byte behind the read head; heals to a serving follower.
        (0, {"upstream_crashes": 1, "corruptions": 1, "end_ok": True}),
        # Corruption lands AHEAD: latched refuse-not-lie, reads ride
        # the primary fallback for the rest of the run.
        (1, {"end_corrupt": True, "fallbacks": True}),
        # Follower crash/restart x6 mid-tail; ends serving.
        (2, {"follower_crashes": 6, "end_ok": True}),
        # Crash INSIDE a covering fsync + corruption: both torn-tail
        # producers in one run.
        (4, {"fsync_crashes": 1, "end_corrupt": True}),
    ],
)
def test_follower_vopr_pinned(seed, expect):
    from tigerbeetle_tpu.testing.vopr import FollowerVopr

    v = FollowerVopr(seed)
    v.run()  # runs check_never_lied + liveness-after-heal internally
    assert v.reads_attempted > 0
    if expect.get("end_ok"):
        assert not v.follower.core.tail.corrupt
        assert not v.follower.core.gapped
        assert v.reads_served > 0
    if expect.get("end_corrupt"):
        assert v.follower.core.tail.corrupt
    if expect.get("fallbacks"):
        assert v.reads_fallback > 0
    for key in ("upstream_crashes", "corruptions", "follower_crashes",
                "fsync_crashes"):
        if key in expect:
            assert getattr(v, key) == expect[key], key
    assert not v.follower.core.poisoned


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(8, 24))
def test_follower_vopr_sweep(seed):
    from tigerbeetle_tpu.testing.vopr import FollowerVopr

    v = FollowerVopr(seed)
    v.run()


def test_core_incompatible_record_refuses_not_crashes():
    """A checksum-valid record the follower's state machine rejects
    (config/software mismatch — here: a batch above the follower's
    batch_max) latches a typed refusal instead of killing the
    process."""
    p = _Primary()
    p.seed_accounts()
    core = _core(p)
    core.pump()
    p.attest(core)
    assert core.refuse_reason() is None
    # Forge an oversized-but-valid committed record past the follower
    # config's batch_max (TEST_MIN), appended to the tailed log.
    n = cfg.TEST_MIN.batch_max(
        types.TRANSFER_DTYPE.itemsize, types.CREATE_RESULT_DTYPE.itemsize
    ) + 1
    rows = np.zeros(n, types.TRANSFER_DTYPE)
    rows["id_lo"] = np.arange(1, n + 1)
    rows["ledger"] = 1
    body = rows.tobytes()
    h = wire.make_header(
        command=wire.Command.prepare, cluster=CLUSTER,
        op=core.commit_min + 1,
        operation=int(types.Operation.create_transfers),
        timestamp=10**15,
    )
    wire.finalize_header(h, body)
    p.aof.buffer += h.tobytes() + body
    core.pump()
    assert core.incompatible
    got = core.serve(int(types.Operation.lookup_accounts), ids_bytes([1]))
    assert isinstance(got, FollowerRefusal)
    assert got.reason == FollowerRefuse.incompatible
    assert core.registry.snapshot()["follower.incompatible"] == 1


def test_core_replays_logically_batched_prepares():
    """vsr/multi.py packs several clients' create requests into ONE
    prepare (context = sub count, demux trailer appended) — the
    follower must commit the event bytes like the replica commit path
    does.  Surfaced by the read_scale bench: concurrent sessions
    coalesce, and a follower treating the trailer as events latched
    `incompatible` on every batched stream."""
    from tigerbeetle_tpu.state_machine import demuxer

    p = _Primary()
    p.seed_accounts()
    # Forge the batched record exactly as _primary_prepare_batch does:
    # two sub-requests' transfers concatenated + trailer, context=2.
    t1 = pack([transfer(501, debit_account_id=1, credit_account_id=2,
                        amount=3)])
    t2 = pack([transfer(502, debit_account_id=2, credit_account_id=1,
                        amount=4)])
    subs = [(0xAAA, 1, 1), (0xBBB, 1, 1)]
    body = t1 + t2 + demuxer.encode_trailer(subs)
    ts = p.replica.sm.commit_timestamp + 10
    h = wire.make_header(
        command=wire.Command.prepare, cluster=CLUSTER,
        op=p.replica.commit_min + 1,
        operation=int(types.Operation.create_transfers),
        timestamp=ts, context=2,
    )
    wire.finalize_header(h, body)
    p.aof.buffer += h.tobytes() + body
    core = _core(p)
    core.pump()
    assert not core.incompatible
    assert core.commit_min == p.replica.commit_min + 1
    got = core.serve(int(types.Operation.lookup_transfers),
                     ids_bytes([501, 502]))
    # Unattested refusal is fine — check the STATE instead: both
    # batched transfers applied.
    rows = core.sm.execute_read(
        types.Operation.lookup_transfers, ids_bytes([501, 502])
    )
    out = np.frombuffer(rows, types.TRANSFER_DTYPE)
    assert len(out) == 2
    assert sorted(int(r["amount_lo"]) for r in out) == [3, 4]


def test_aof_replay_handles_batched_prepares():
    from tigerbeetle_tpu.state_machine import demuxer

    p = _Primary()
    p.seed_accounts()
    t1 = pack([transfer(601, debit_account_id=1, credit_account_id=2,
                        amount=7)])
    subs = [(0xCCC, 1, 1)]
    body = t1 + demuxer.encode_trailer(subs)
    ts = p.replica.sm.commit_timestamp + 10
    h = wire.make_header(
        command=wire.Command.prepare, cluster=CLUSTER,
        op=p.replica.commit_min + 1,
        operation=int(types.Operation.create_transfers),
        timestamp=ts, context=1,
    )
    wire.finalize_header(h, body)
    p.aof.buffer += h.tobytes() + body
    # replay() consumes a file path.
    import tempfile

    with tempfile.NamedTemporaryFile(delete=False) as f:
        f.write(bytes(p.aof.buffer))
        path = f.name
    fresh = CpuStateMachine(cfg.TEST_MIN)
    aof_mod.replay(path, fresh, cluster=CLUSTER)
    rows = fresh.execute_read(
        types.Operation.lookup_transfers, ids_bytes([601])
    )
    out = np.frombuffer(rows, types.TRANSFER_DTYPE)
    assert len(out) == 1 and int(out[0]["amount_lo"]) == 7


def test_core_attestation_age_bound_refuses_frozen_partition():
    """A FULL partition (upstream and log both unreachable) freezes
    lag_ops at 0 — the attestation AGE bound is what keeps the
    staleness contract honest there: once the last verified
    attestation is older than the bound, reads refuse `lagging`
    instead of serving frozen state as fresh forever."""
    p = _Primary()
    p.seed_accounts()
    core = _core(p, attest_max_age_ns=1_000_000_000)  # 1 s bound
    core.pump()
    t0 = 5_000_000_000
    root = p.replica.root_at(p.replica.commit_min)
    core.on_attestation(root, p.replica.commit_min, now_ns=t0)
    assert isinstance(
        core.serve(int(types.Operation.lookup_accounts), ids_bytes([1]),
                   now_ns=t0 + 500_000_000),
        FollowerReply,
    )
    # Partition: no attestations for > the bound.  lag_ops is still 0
    # (the high-water mark froze), but the age bound refuses.
    assert core.lag_ops() == 0
    got = core.serve(int(types.Operation.lookup_accounts), ids_bytes([1]),
                     now_ns=t0 + 2_000_000_000)
    assert isinstance(got, FollowerRefusal)
    assert got.reason == FollowerRefuse.lagging
    # Heal: a fresh attestation restores serving.
    core.on_attestation(root, p.replica.commit_min,
                        now_ns=t0 + 3_000_000_000)
    assert isinstance(
        core.serve(int(types.Operation.lookup_accounts), ids_bytes([1]),
                   now_ns=t0 + 3_100_000_000),
        FollowerReply,
    )


def test_tail_chunk_cache_persists_across_polls():
    """The chunk cache survives poll() calls: a driver consuming a few
    records per poll must not re-read the chunk every time."""
    reads = []

    class CountingSource(BytesSource):
        def read_at(self, offset, n):
            reads.append((offset, n))
            return super().read_at(offset, n)

    buf = bytearray(b"".join(_record(op) for op in range(1, 33)))
    tail = AofTail(CountingSource(buf))
    got = 0
    while True:
        batch = tail.poll(limit=4)
        if not batch:
            break
        got += len(batch)
    assert got == 32
    # One chunk read covers the whole buffer (records are small).
    assert len(reads) == 1, reads
