"""Manifest log: append-only across checkpoints, self-compacting,
replay-exact (reference: src/lsm/manifest_log.zig:1-40)."""

import numpy as np

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu.lsm.forest import Forest
from tigerbeetle_tpu.lsm.runs import pack_u128
from tigerbeetle_tpu.vsr.storage import MemoryStorage, ZoneLayout


def make_forest(storage=None):
    storage = storage or MemoryStorage(
        ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 20)
    )
    f = Forest(storage, memtable_max=64)
    f.groove("things", object_size=32, index_fields=["field"],
             index_value_size=8)
    return storage, f


def put_batch(groove, start, n):
    ids = np.arange(start, start + n, dtype=np.uint64)
    groove.insert_batch(
        ids, np.zeros(n, np.uint64), ids * 10,
        np.full((n, 32), 7, np.uint8),
        {"field": ids % 5},
    )


def test_checkpoint_appends_only_delta():
    storage, f = make_forest()
    g = f.grooves["things"]
    put_batch(g, 1, 200)
    blob1 = f.checkpoint()
    blocks_after_1 = list(f.mlog.blocks)
    assert blocks_after_1, "first checkpoint writes log blocks"

    put_batch(g, 201, 200)
    f.checkpoint()
    blocks_after_2 = list(f.mlog.blocks)
    # Append-only: with this small workload compaction must not have
    # triggered, the first checkpoint's blocks remain a prefix, and the
    # delta rides in newly appended blocks.
    assert len(blocks_after_2) >= len(blocks_after_1), (
        blocks_after_1, blocks_after_2,
    )
    assert blocks_after_2[: len(blocks_after_1)] == blocks_after_1


def test_replay_matches_live_state():
    storage, f = make_forest()
    g = f.grooves["things"]
    for k in range(6):
        put_batch(g, 1 + k * 300, 300)
        f.checkpoint()
    blob = f.checkpoint()

    storage2 = storage  # same blocks
    _, f2 = make_forest(storage2)
    f2.open(blob)
    g2 = f2.grooves["things"]
    ids = np.array([1, 500, 1200, 1799], np.uint64)
    found1, ts1 = g.lookup_ids(ids, np.zeros(4, np.uint64))
    found2, ts2 = g2.lookup_ids(ids, np.zeros(4, np.uint64))
    np.testing.assert_array_equal(found1, found2)
    np.testing.assert_array_equal(ts1, ts2)
    # Tree levels identical (same runs, same order).
    for t1, t2 in zip(f._trees, f2._trees):
        m1 = [[(r.id, [b.address for b in r.blocks]) for r in lvl]
              for lvl in t1.levels]
        m2 = [[(r.id, [b.address for b in r.blocks]) for r in lvl]
              for lvl in t2.levels]
        assert m1 == m2


def test_self_compaction_bounds_log():
    storage, f = make_forest()
    g = f.grooves["things"]
    # Many overwrites of the same keys: compactions churn runs, dead
    # events accumulate, and the log must keep compacting itself.
    for round_ in range(30):
        put_batch(g, 1, 128)
        f.checkpoint()
    live_runs = sum(
        len(lvl) for t in f._trees for lvl in t.levels
    )
    # Log events are bounded by ~2x live runs (+ flush slack), far
    # below the ~hundreds of events 30 churn rounds generated.
    assert f.mlog._events_total <= 2 * max(live_runs, 8) + 16, (
        f.mlog._events_total, live_runs,
    )
    blob = f.checkpoint()
    _, f2 = make_forest(storage)
    f2.open(blob)
    ids = np.arange(1, 129, dtype=np.uint64)
    found, _ts = f2.grooves["things"].lookup_ids(
        ids, np.zeros(len(ids), np.uint64)
    )
    assert found.all()


def test_mid_interval_snapshot_carries_tail():
    """A pure snapshot between checkpoints includes unflushed tail
    events; open() must replay them."""
    storage, f = make_forest()
    g = f.grooves["things"]
    put_batch(g, 1, 200)
    f.checkpoint()
    put_batch(g, 201, 200)  # seals mid-interval (memtable_max=64)
    blob = f.manifest_blob()  # NOT a checkpoint: tail unflushed
    assert len(f.mlog._tail) > 0 or f.mlog.blocks

    _, f2 = make_forest(storage)
    f2.open(blob)
    ids = np.array([1, 250, 400], np.uint64)
    found, _ = f2.grooves["things"].lookup_ids(
        ids, np.zeros(3, np.uint64)
    )
    assert found.all()


def test_oversized_run_splits_to_block_capacity():
    """A run with more block refs than fit one grid block must split
    into OP_ADD + continuation records sized from grid.payload_size —
    regression: a fixed 1024-ref split crashed checkpoint on 4KiB
    blocks."""
    from tigerbeetle_tpu.lsm.manifest_log import ManifestLog
    from tigerbeetle_tpu.vsr.grid import Grid

    st = MemoryStorage(ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 22))
    grid = Grid(st, block_size=4096, block_count=1 << 9)
    mlog = ManifestLog(grid)
    refs = [
        (1000 + i, 7, b"\x01" * 16, b"\x02" * 16) for i in range(300)
    ]
    mlog.run_add(5, 0, 1, refs)
    addresses = mlog.checkpoint()

    replayed = ManifestLog(grid).open(addresses)
    assert list(replayed.keys()) == [(5, 0, 1)]
    got = replayed[(5, 0, 1)]
    assert len(got) == 300
    assert [r[0] for r in got] == [1000 + i for i in range(300)]
