"""Native runtime integration: C++ bus/client <-> Python server over
real TCP, CLI, REPL, benchmark smoke."""

import io
import json
import os
import threading

import numpy as np
import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.runtime.native import native_available, native_checksum128
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.vsr import wire

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native runtime not built"
)

CLUSTER = 3


def test_native_checksum_matches_python():
    for data in (b"", b"x", b"hello world" * 100, os.urandom(4096)):
        assert native_checksum128(data) == wire.checksum(data)


class ServerFixture:
    def __init__(self, tmp_path, use_test_min=True):
        from tigerbeetle_tpu.runtime.server import (
            ReplicaServer,
            format_data_file,
        )

        config = cfg.TEST_MIN if use_test_min else cfg.PRODUCTION
        path = str(tmp_path / "data.tigerbeetle")
        format_data_file(path, cluster=CLUSTER, config=config)
        self.server = ReplicaServer(
            path, cluster=CLUSTER, addresses=["127.0.0.1:0"], replica_index=0,
            state_machine_factory=lambda: CpuStateMachine(config),
            config=config,
        )
        self.address = f"127.0.0.1:{self.server.port}"
        self._stop = False
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self):
        while not self._stop:
            self.server.poll_once(timeout_ms=1)

    def close(self):
        self._stop = True
        self.thread.join(timeout=5)
        self.server.close()


@pytest.fixture
def server(tmp_path):
    f = ServerFixture(tmp_path)
    yield f
    f.close()


def test_client_end_to_end(server):
    from tigerbeetle_tpu.client import Client

    c = Client(server.address, CLUSTER, client_id=77)
    assert c.create_accounts(
        [{"id": 1, "ledger": 1, "code": 1}, {"id": 2, "ledger": 1, "code": 1}]
    ) == []
    assert c.create_transfers(
        [{"id": 10, "debit_account_id": 1, "credit_account_id": 2,
          "amount": 250, "ledger": 1, "code": 1}]
    ) == []
    rows = c.lookup_accounts([1, 2])
    assert types.u128_get(rows[0], "debits_posted") == 250
    assert types.u128_get(rows[1], "credits_posted") == 250

    transfers = c.get_account_transfers(1)
    assert len(transfers) == 1
    assert types.u128_get(transfers[0], "amount") == 250

    # Error results round-trip.
    results = c.create_accounts([{"id": 1, "ledger": 1, "code": 2}])
    assert results == [(0, types.CreateAccountResult.exists_with_different_code)]
    c.close()


def test_two_clients_isolated_sessions(server):
    from tigerbeetle_tpu.client import Client

    a = Client(server.address, CLUSTER, client_id=101)
    b = Client(server.address, CLUSTER, client_id=102)
    assert a.create_accounts([{"id": 5, "ledger": 1, "code": 1}]) == []
    assert b.create_accounts([{"id": 6, "ledger": 1, "code": 1}]) == []
    assert len(a.lookup_accounts([5, 6])) == 2
    a.close()
    b.close()


def test_repl_statements(server):
    from tigerbeetle_tpu import repl
    from tigerbeetle_tpu.client import Client

    c = Client(server.address, CLUSTER, client_id=55)
    out = repl.execute(
        c, "create_accounts id=1 ledger=700 code=10, id=2 ledger=700 code=10;"
    )
    assert out == []
    out = repl.execute(
        c,
        "create_transfers id=9 debit_account_id=1 credit_account_id=2 "
        "amount=55 ledger=700 code=10;",
    )
    assert out == []
    out = repl.execute(c, "lookup_accounts id=1;")
    assert out[0]["id"] == 1 and out[0]["debits_posted"] == 55
    out = repl.execute(c, "get_account_transfers account_id=1 limit=10;")
    assert len(out) == 1 and out[0]["amount"] == 55

    # flags parsing
    out = repl.execute(
        c,
        "create_transfers id=11 debit_account_id=1 credit_account_id=2 "
        "amount=5 ledger=700 code=10 flags=pending;",
    )
    assert out == []
    c.close()


def test_repl_run_stream(server):
    from tigerbeetle_tpu import repl
    from tigerbeetle_tpu.client import Client

    c = Client(server.address, CLUSTER, client_id=56)
    stdout = io.StringIO()
    repl.run(
        c,
        command="create_accounts id=31 ledger=1 code=1; lookup_accounts id=31",
        stdout=stdout,
    )
    lines = stdout.getvalue().strip().splitlines()
    assert lines[0] == "ok"
    assert json.loads(lines[1])["id"] == 31
    c.close()


def test_tcp_restart_recovers(tmp_path):
    from tigerbeetle_tpu.client import Client

    f = ServerFixture(tmp_path)
    c = Client(f.address, CLUSTER, client_id=60)
    c.create_accounts([{"id": 1, "ledger": 1, "code": 1},
                       {"id": 2, "ledger": 1, "code": 1}])
    c.create_transfers([{"id": 4, "debit_account_id": 1,
                         "credit_account_id": 2, "amount": 9,
                         "ledger": 1, "code": 1}])
    c.close()
    f.close()

    f2 = ServerFixture(tmp_path)
    c2 = Client(f2.address, CLUSTER, client_id=61)
    rows = c2.lookup_accounts([1])
    assert types.u128_get(rows[0], "debits_posted") == 9
    c2.close()
    f2.close()


def test_benchmark_smoke():
    from tigerbeetle_tpu.benchmark import run_benchmark

    result = run_benchmark(
        addresses=None, cluster=0, n_transfers=5000, n_accounts=100,
        batch=1000, use_cpu=True,
    )
    assert result["transfers"] == 5000
    assert result["transfers_per_second"] > 0
    assert result["batch_latency_p100_ms"] >= result["batch_latency_p50_ms"]


def test_cli_version_and_format(tmp_path, capsys):
    from tigerbeetle_tpu import cli

    cli.main(["version"])
    assert "0.1" in capsys.readouterr().out

    path = str(tmp_path / "f.tigerbeetle")
    cli.main([f"format", "--cluster=9", path])
    assert "formatted" in capsys.readouterr().out
    assert os.path.getsize(path) > 0


def test_three_replica_tcp_cluster(tmp_path):
    """Real TCP mesh: three in-process servers, client at the primary."""
    from tigerbeetle_tpu.client import Client
    from tigerbeetle_tpu.runtime.server import ReplicaServer, format_data_file

    # Bind three listeners first (port 0), then rewrite the address list.
    servers = []
    paths = [str(tmp_path / f"r{i}.tigerbeetle") for i in range(3)]
    addresses = ["127.0.0.1:0"] * 3
    for i in range(3):
        format_data_file(paths[i], cluster=CLUSTER, replica_index=i,
                         replica_count=3, config=cfg.TEST_MIN)
        s = ReplicaServer(
            paths[i], cluster=CLUSTER, addresses=list(addresses),
            replica_index=i,
            state_machine_factory=lambda: CpuStateMachine(cfg.TEST_MIN),
            config=cfg.TEST_MIN,
        )
        addresses[i] = f"127.0.0.1:{s.port}"
        servers.append(s)
    for s in servers:
        s.bus.addresses = list(addresses)

    stop = [False]

    def loop():
        while not stop[0]:
            for s in servers:
                s.poll_once(timeout_ms=1)

    thread = threading.Thread(target=loop, daemon=True)
    thread.start()
    try:
        c = Client(addresses[0], CLUSTER, client_id=200, timeout_ms=30_000)
        assert c.create_accounts(
            [{"id": 1, "ledger": 1, "code": 1}, {"id": 2, "ledger": 1, "code": 1}]
        ) == []
        assert c.create_transfers(
            [{"id": 3, "debit_account_id": 1, "credit_account_id": 2,
              "amount": 12, "ledger": 1, "code": 1}]
        ) == []
        rows = c.lookup_accounts([1])
        assert types.u128_get(rows[0], "debits_posted") == 12
        c.close()

        # Replication actually happened on the backups.
        import time as _t

        deadline = _t.time() + 10
        while _t.time() < deadline:
            if all(s.replica.sm.transfer_timestamp(3) is not None
                   for s in servers):
                break
            _t.sleep(0.05)
        for s in servers:
            assert s.replica.sm.transfer_timestamp(3) is not None
    finally:
        stop[0] = True
        thread.join(timeout=5)
        for s in servers:
            s.close()
