"""Logical request batching + reply demultiplexing.

reference: src/state_machine.zig:122-176 (DemuxerType,
batch_logical_allowed) — several client requests of one batchable
operation share a prepare; each client receives only its slice of the
batched reply, indexes rebased.
"""

import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import demuxer
from tigerbeetle_tpu.state_machine.demuxer import Demuxer
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.harness import account, pack, transfer
from tigerbeetle_tpu.types import (
    CREATE_RESULT_DTYPE,
    CreateTransferResult,
    Operation,
)
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.wire import Command


def _results(*pairs):
    arr = np.zeros(len(pairs), CREATE_RESULT_DTYPE)
    for i, (index, result) in enumerate(pairs):
        arr[i]["index"] = index
        arr[i]["result"] = result
    return arr.tobytes()


def test_batch_logical_allowed():
    assert demuxer.batch_logical_allowed(Operation.create_transfers)
    assert demuxer.batch_logical_allowed(Operation.create_accounts)
    assert not demuxer.batch_logical_allowed(Operation.lookup_accounts)
    assert not demuxer.batch_logical_allowed(Operation.get_account_transfers)


def test_demuxer_slices_and_rebases():
    # 3 sub-batches of 4/3/5 events; failures at global 1, 4, 6, 11.
    reply = _results((1, 10), (4, 20), (6, 30), (11, 40))
    dm = Demuxer(Operation.create_transfers, reply)
    a = np.frombuffer(dm.decode(0, 4), CREATE_RESULT_DTYPE)
    b = np.frombuffer(dm.decode(4, 3), CREATE_RESULT_DTYPE)
    c = np.frombuffer(dm.decode(7, 5), CREATE_RESULT_DTYPE)
    assert [(int(r["index"]), int(r["result"])) for r in a] == [(1, 10)]
    assert [(int(r["index"]), int(r["result"])) for r in b] == [(0, 20), (2, 30)]
    assert [(int(r["index"]), int(r["result"])) for r in c] == [(4, 40)]


def test_demuxer_empty_slices():
    dm = Demuxer(Operation.create_accounts, b"")
    assert dm.decode(0, 10) == b""
    assert dm.decode(10, 5) == b""


def test_trailer_roundtrip():
    subs = [(1000, 3, 4), ((7 << 64) | 9, 1, 2)]
    body = b"\x00" * (6 * demuxer.EVENT_SIZE) + demuxer.encode_trailer(subs)
    events, got = demuxer.decode_trailer(body, 2)
    assert got == subs
    assert len(events) == 6 * demuxer.EVENT_SIZE
    assert demuxer.strip_trailer(body, subs) == events


def test_cluster_batched_prepare_demuxes_per_client():
    """Two clients' transfer batches multiplexed into ONE prepare;
    each gets its own failure slice with rebased indexes, and the
    cluster converges."""
    cluster = Cluster(replica_count=3, seed=5)
    a = cluster.client(1000)
    b = cluster.client(2000)
    for c in (a, b):
        c.register()
        cluster.run_until(lambda: c.registered)
    cluster.run_request(
        a, Operation.create_accounts, pack([account(1), account(2)])
    )

    primary = cluster.replicas[0]
    ops_before = primary.op

    # Queue both requests while the primary cannot prepare (clock
    # gate), then re-enable: the drain must batch them into one op.
    def req(client, transfers):
        client.request_number += 1
        h = wire.make_header(
            command=Command.request, operation=Operation.create_transfers,
            cluster=cluster.cluster_id, client=client.id,
            request=client.request_number,
        )
        body = pack(transfers)
        wire.finalize_header(h, body)
        client.reply = None
        client._inflight = (h, body)
        client._send()

    # Hold the clock gate closed while both requests arrive (ping
    # rounds would otherwise re-synchronize mid-delivery).
    primary.clock.synchronized = False
    primary.clock._synchronize = lambda monotonic_now: None
    # a: ok, ok ; b: ok, FAIL(same accounts), ok
    req(a, [
        transfer(10, debit_account_id=1, credit_account_id=2, amount=1),
        transfer(11, debit_account_id=1, credit_account_id=2, amount=2),
    ])
    req(b, [
        transfer(12, debit_account_id=2, credit_account_id=1, amount=3),
        transfer(13, debit_account_id=1, credit_account_id=1, amount=4),
        transfer(14, debit_account_id=2, credit_account_id=1, amount=5),
    ])
    for _ in range(6):  # deliver requests into the gated queue
        cluster.step()
    assert len(primary.request_queue) == 2, len(primary.request_queue)
    del primary.clock._synchronize
    primary.clock.synchronized = True
    cluster.run_until(lambda: a.reply is not None and b.reply is not None)

    # Exactly one op for both requests.
    assert primary.op == ops_before + 1
    assert np.frombuffer(a.reply, CREATE_RESULT_DTYPE).size == 0
    rb = np.frombuffer(b.reply, CREATE_RESULT_DTYPE)
    assert [(int(r["index"]), int(r["result"])) for r in rb] == [
        (1, int(CreateTransferResult.accounts_must_be_different))
    ]
    # All transfers except 13 exist everywhere once replicas catch up.
    for _ in range(20):
        cluster.step()
    for r in cluster.replicas:
        for tid in (10, 11, 12, 14):
            assert r.sm.transfer_timestamp(tid) is not None, (r.replica, tid)
        assert r.sm.transfer_timestamp(13) is None
