"""Incremental state commitments (state_machine/commitment.py).

Codec pinning (golden digest, numpy/JAX bit-identity, fold algebra),
the host twin vs from-scratch differential under fuzz, the device
engine's incremental digest across kernel/wave/grow/remove/demote/
re-promote interleavings on dense AND row-sharded engines, cheap-scrub
fetch-count assertions, corruption catch-and-heal, and checkpoint
state-root recording/recompute through superblock recovery.
"""

import jax
import numpy as np
import pytest

jax.config.update("jax_enable_x64", True)  # u64 lanes (kernel.py does this)

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.state_machine import commitment as cm
from tigerbeetle_tpu.testing.harness import (
    SingleNodeHarness,
    account,
    ids_bytes,
    pack,
    transfer,
)
from tigerbeetle_tpu.types import AccountFlags, Operation, TransferFlags

TF = TransferFlags


# ----------------------------------------------------------------------
# Codec: golden pin, platform bit-identity, fold algebra.


def _fixture_table():
    bal = np.arange(64, dtype=np.uint64).reshape(8, 8) * np.uint64(
        0x0123456789ABCDEF
    )
    meta = np.arange(16, dtype=np.uint32).reshape(8, 2) + np.uint32(1)
    return bal, meta


def test_golden_digest_pinned():
    """Silent drift of the hash formula (constants, mixing, fold) is a
    state-root FORMAT change: recorded checkpoint roots and
    cross-version scrub compares would all mismatch.  This pin makes
    it fail tier-1 instead."""
    bal, meta = _fixture_table()
    d = cm.table_digest(bal, meta)
    assert int(d[0]) == 0xB84D53B618D40315, hex(int(d[0]))
    assert int(d[1]) == 0x924D31B47961A88B, hex(int(d[1]))
    assert cm.root_bytes(d).hex() == "1503d418b6534db88ba86179b4314d92"


def test_numpy_jax_bit_identical():
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    for rows in (1, 8, 257):
        bal = rng.integers(0, 1 << 63, (rows, 8), dtype=np.uint64)
        bal |= rng.integers(0, 2, (rows, 8), dtype=np.uint64) << np.uint64(63)
        meta = rng.integers(0, 1 << 32, (rows, 2), dtype=np.uint64).astype(
            np.uint32
        )
        d_np = cm.table_digest(bal, meta)
        d_j = np.asarray(cm.table_digest(jnp.asarray(bal), jnp.asarray(meta)))
        assert (d_np == d_j).all(), rows


def test_dtype_stability():
    """Meta columns hash by VALUE, not storage dtype: uint16 flags
    (the attrs store) and uint32 flags (the engine's meta table) must
    digest identically."""
    bal, meta = _fixture_table()
    base = cm.table_digest(bal, meta)
    for dt in (np.uint16, np.uint64, np.int64):
        assert (cm.table_digest(bal, meta.astype(dt)) == base).all(), dt
    assert (cm.table_digest(bal.astype(np.uint64), meta) == base).all()


def test_zero_rows_capacity_invariance():
    """All-zero rows contribute exactly nothing, so zero padding,
    growth, and capacity mismatches never move the root."""
    bal, meta = _fixture_table()
    base = cm.table_digest(bal, meta)
    for pad in (1, 9, 100):
        bal2 = np.zeros((8 + pad, 8), np.uint64)
        meta2 = np.zeros((8 + pad, 2), np.uint32)
        bal2[:8], meta2[:8] = bal, meta
        assert (cm.table_digest(bal2, meta2) == base).all(), pad
    assert (
        cm.table_digest(np.zeros((5, 8), np.uint64), np.zeros((5, 2), np.uint32))
        == 0
    ).all()


def test_fold_order_independence_fuzz():
    """The fold is a per-lane modular sum of index-bound row hashes:
    any permutation of rows (hashed AT their true indices) folds to
    the same digest, and incremental subtract/add replays an arbitrary
    mutation order to the same result as from-scratch."""
    rng = np.random.default_rng(42)
    for trial in range(20):
        n = int(rng.integers(2, 200))
        bal = rng.integers(0, 1 << 64, (n, 8), dtype=np.uint64)
        meta = rng.integers(0, 1 << 32, (n, 2), dtype=np.uint64).astype(
            np.uint32
        )
        rows = np.arange(n, dtype=np.uint64)
        lo, hi = cm.rows_hash(rows, bal, meta, np)
        perm = rng.permutation(n)
        assert (
            cm.fold(lo[perm], hi[perm], np) == cm.table_digest(bal, meta)
        ).all()
        # Incremental replay: mutate random rows in random order.
        digest = cm.table_digest(bal, meta).copy()
        for _ in range(10):
            k = int(rng.integers(1, min(n, 16) + 1))
            slots = rng.choice(n, size=k, replace=False)
            old_lo, old_hi = cm.rows_hash(
                slots.astype(np.uint64), bal[slots], meta[slots], np
            )
            bal[slots] ^= rng.integers(0, 1 << 64, (k, 8), dtype=np.uint64)
            new_lo, new_hi = cm.rows_hash(
                slots.astype(np.uint64), bal[slots], meta[slots], np
            )
            digest = digest + np.array(
                [
                    np.add.reduce(new_lo - old_lo, dtype=np.uint64),
                    np.add.reduce(new_hi - old_hi, dtype=np.uint64),
                ],
                np.uint64,
            )
        assert (digest == cm.table_digest(bal, meta)).all(), trial


def test_swapped_rows_change_digest():
    """Row index is bound into the hash: two rows trading places (a
    divergence the plain column-sum digest family is blind to at the
    per-column level) must move the root."""
    bal, meta = _fixture_table()
    base = cm.table_digest(bal, meta)
    bal2 = bal.copy()
    bal2[[2, 5]] = bal2[[5, 2]]
    assert not (cm.table_digest(bal2, meta) == base).all()


def test_device_update_matches_scratch():
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    bal = rng.integers(0, 1 << 64, (128, 8), dtype=np.uint64)
    meta = rng.integers(0, 1 << 32, (128, 2), dtype=np.uint64).astype(
        np.uint32
    )
    fns = cm.device_fns()
    arange = jnp.arange(128, dtype=jnp.uint64)
    row_hash, digest = fns["rebuild"](
        jnp.asarray(bal), jnp.asarray(meta), arange
    )
    assert (np.asarray(digest) == cm.table_digest(bal, meta)).all()
    for _ in range(5):
        k = int(rng.integers(1, 40))
        slots = np.unique(rng.integers(0, 128, k))
        bal[slots] ^= rng.integers(0, 1 << 64, (len(slots), 8), dtype=np.uint64)
        padded = jnp.asarray(cm.pad_slots(slots))
        row_hash, digest = fns["update"](
            jnp.asarray(bal), jnp.asarray(meta), row_hash, digest,
            padded, padded,
        )
        assert (np.asarray(digest) == cm.table_digest(bal, meta)).all()
        pair = np.asarray(
            fns["probe"](jnp.asarray(bal), jnp.asarray(meta), digest, arange)
        )
        assert (pair[0] == pair[1]).all()


def _mk_twin(rng, n):
    """HostCommitment over a random fake mirror (lo/hi column pairs)."""
    meta = rng.integers(0, 1 << 32, (n, 2), dtype=np.uint64).astype(np.uint32)

    class _M:
        pass

    m = _M()
    m.lo = rng.integers(0, 1 << 64, (n, 4), dtype=np.uint64)
    m.hi = rng.integers(0, 1 << 64, (n, 4), dtype=np.uint64)
    twin = cm.HostCommitment(n, meta_fn=lambda s: meta[s])
    twin.refresh(np.arange(n, dtype=np.int64), m)
    return twin, m


def test_partial_fold_hot_cold_split_fuzz():
    """Tiering's root invariant: for ANY hot/cold split of the table,
    partial(hot) + partial(cold) == digest per lane (mod 2^64) — the
    cold partial never needs hashing, it is digest - partial(hot).
    Duplicates collapse and out-of-range/negative rows are ignored, so
    a hot set handed in admission order folds the same as sorted."""
    rng = np.random.default_rng(7)
    for trial in range(10):
        n = int(rng.integers(2, 200))
        twin, _ = _mk_twin(rng, n)
        k = int(rng.integers(0, n + 1))
        hot = rng.choice(n, size=k, replace=False)
        cold = np.setdiff1d(np.arange(n), hot)
        assert (
            twin.partial(hot) + twin.partial(cold) == twin.digest
        ).all(), trial
        messy = np.concatenate(
            [rng.permutation(hot), hot, [-1, n, n + 17]]
        )
        assert (twin.partial(messy) == twin.partial(hot)).all(), trial


def test_partial_fold_degenerate_splits():
    """Empty cold tier: the hot partial IS the root (all-resident
    collapses to today's compare).  Empty hot set: partial is the
    zero lane pair and the cold partial is the whole digest."""
    rng = np.random.default_rng(8)
    twin, _ = _mk_twin(rng, 64)
    assert (twin.partial(np.arange(64)) == twin.digest).all()
    assert (twin.partial(np.zeros(0, np.int64)) == 0).all()


def test_device_admit_tracks_hot_partial():
    """Tiered device digest lifecycle against the host twin: an empty
    hot table folds to zero; every admission (free slots), mid-
    residency mutation (update kernel), and eviction-with-replacement
    (admit kernel over occupied victim slots) leaves the maintained
    device digest equal to twin.partial(occupied) — so
    fold(hot_partial, cold_partial) == twin.digest throughout."""
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    N, H = 32, 8  # logical rows, hot budget
    twin, m = _mk_twin(rng, N)
    fns = cm.device_fns()

    bal_hot = np.zeros((H, 8), np.uint64)
    meta_hot = np.zeros((H, 2), np.uint32)
    logical_of = np.full(H, -1, np.int64)

    def rows_binding():
        # Free slots bind to row 0; their all-zero content hashes to
        # (0, 0) regardless of the binding (the engine's _commit_rows).
        return jnp.asarray(
            np.where(logical_of >= 0, logical_of, 0).astype(np.uint64)
        )

    row_hash, digest = fns["rebuild"](
        jnp.asarray(bal_hot), jnp.asarray(meta_hot), rows_binding()
    )
    assert (np.asarray(digest) == 0).all()  # empty hot set

    def admit(rows, slots):
        rows = np.asarray(rows, np.int64)
        slots = np.asarray(slots, np.int64)
        bal_hot[slots, 0::2] = m.lo[rows]
        bal_hot[slots, 1::2] = m.hi[rows]
        meta_hot[slots] = twin.meta_fn(rows)
        logical_of[slots] = rows
        padded = cm.pad_slots(slots)
        k = len(slots)
        new_lo = np.zeros(len(padded), np.uint64)
        new_hi = np.zeros(len(padded), np.uint64)
        new_lo[:k] = twin.row_lo[rows]
        new_hi[:k] = twin.row_hi[rows]
        return fns["admit"](
            row_hash, digest, jnp.asarray(padded),
            jnp.asarray(new_lo), jnp.asarray(new_hi),
        )

    def check(step):
        occupied = logical_of[logical_of >= 0]
        assert (np.asarray(digest) == twin.partial(occupied)).all(), step
        pair = np.asarray(
            fns["probe"](
                jnp.asarray(bal_hot), jnp.asarray(meta_hot), digest,
                rows_binding(),
            )
        )
        assert (pair[0] == pair[1]).all(), step

    # Admission into free slots.
    row_hash, digest = admit([3, 9, 20], [0, 1, 2])
    check("admit-free")
    row_hash, digest = admit([4, 5, 6, 7, 8], [3, 4, 5, 6, 7])
    check("admit-fill")

    # Mid-residency mutation: the mirror (and twin) move first, then
    # the device row is rewritten and the update kernel rolls the
    # partial — same order as a write-behind flush.
    touched = np.array([9, 5], np.int64)
    m.lo[touched] ^= rng.integers(0, 1 << 64, (2, 4), dtype=np.uint64)
    twin.refresh(touched, m)
    hot_slots = np.array(
        [np.flatnonzero(logical_of == r)[0] for r in touched], np.int64
    )
    bal_hot[hot_slots, 0::2] = m.lo[touched]
    bal_hot[hot_slots, 1::2] = m.hi[touched]
    padded = cm.pad_slots(hot_slots)
    rows_pad = np.where(
        padded >= 0, logical_of[np.maximum(padded, 0)], 0
    ).astype(np.uint64)
    row_hash, digest = fns["update"](
        jnp.asarray(bal_hot), jnp.asarray(meta_hot), row_hash, digest,
        jnp.asarray(padded), jnp.asarray(rows_pad),
    )
    check("update-mid-residency")

    # Eviction with replacement: new rows land on occupied victim
    # slots; the admit kernel rolls out the victims' hashes.
    row_hash, digest = admit([25, 26], [0, 3])
    check("evict-readmit")

    # The digest is the hot PARTIAL, not the table digest: with a
    # non-empty cold tier they differ, and the cold partial closes
    # the fold.
    occupied = logical_of[logical_of >= 0]
    cold = np.setdiff1d(np.arange(N), occupied)
    assert len(cold) and not (np.asarray(digest) == twin.digest).all()
    assert (np.asarray(digest) + twin.partial(cold) == twin.digest).all()


def test_fold_cluster_deterministic_and_index_bound():
    r1 = cm.root_bytes(np.array([1, 2], np.uint64))
    r2 = cm.root_bytes(np.array([3, 4], np.uint64))
    assert cm.fold_cluster([r1, r2]) == cm.fold_cluster([r1, r2])
    # Shards swapping state must move the cluster root.
    assert cm.fold_cluster([r1, r2]) != cm.fold_cluster([r2, r1])


def test_root_body_roundtrip_and_rejects_garbage():
    root = bytes(range(16))
    body = cm.root_body(root, 77)
    assert len(body) == 24
    assert cm.parse_root_body(body) == (root, 77)
    with pytest.raises(ValueError):
        cm.parse_root_body(body + b"x")


# ----------------------------------------------------------------------
# Host twin + state machines.


def _scratch_root(sm) -> bytes:
    """From-scratch root over the TPU build's mirror + attrs — the
    oracle every incremental path must match."""
    n = len(sm._mirror.lo)
    bal8 = np.empty((n, 8), np.uint64)
    bal8[:, 0::2] = sm._mirror.lo
    bal8[:, 1::2] = sm._mirror.hi
    meta = sm._commit_meta_cols(np.arange(n, dtype=np.int64))
    return cm.root_bytes(cm.table_digest(bal8, meta))


def _fuzz_ops(h, rng, n_accounts, tid_start, batches=12):
    """Mixed batches: plain, pending+post/void, linked chains with
    failures, duplicate ids, timeouts — every routing class."""
    tid = tid_start
    for b in range(batches):
        kind = b % 5
        rows = []
        if kind == 0:  # plain order-free
            for _ in range(int(rng.integers(1, 24))):
                rows.append(transfer(
                    tid, debit_account_id=int(rng.integers(1, n_accounts + 1)),
                    credit_account_id=int(rng.integers(1, n_accounts + 1)),
                    amount=int(rng.integers(1, 100)),
                ))
                tid += 1
        elif kind == 1:  # pending + post/void
            p1, p2 = tid, tid + 1
            rows.append(transfer(p1, debit_account_id=1, credit_account_id=2,
                                 amount=5, flags=int(TF.pending), timeout=1000))
            rows.append(transfer(p2, debit_account_id=3, credit_account_id=4,
                                 amount=6, flags=int(TF.pending), timeout=2))
            tid += 2
            h.create_transfers(rows)
            rows = [
                transfer(tid, pending_id=p1, amount=5,
                         flags=int(TF.post_pending_transfer)),
                transfer(tid + 1, pending_id=p2,
                         flags=int(TF.void_pending_transfer)),
            ]
            tid += 2
        elif kind == 2:  # linked chain with a failing member (rollback)
            rows.append(transfer(tid, debit_account_id=1, credit_account_id=2,
                                 amount=1, flags=int(TF.linked)))
            rows.append(transfer(tid, debit_account_id=2, credit_account_id=3,
                                 amount=1))  # duplicate id: chain fails
            tid += 1
        elif kind == 3:  # duplicates + mixed amounts (off-kernel shapes)
            a = int(rng.integers(1, n_accounts + 1))
            for _ in range(6):
                rows.append(transfer(
                    tid, debit_account_id=a,
                    credit_account_id=(a % n_accounts) + 1,
                    amount=int(rng.integers(1, 10)),
                ))
                tid += 1
            rows.append(rows[-1])  # retransmitted duplicate row
        else:  # balancing / limit flags interplay
            rows.append(transfer(
                tid, debit_account_id=n_accounts + 1, credit_account_id=1,
                amount=int(rng.integers(1, 50)),
                flags=int(TF.balancing_debit),
            ))
            tid += 1
        if rows:
            h.create_transfers(rows)
    return tid


def test_host_twin_matches_scratch_and_cpu_oracle():
    from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine

    rng = np.random.default_rng(11)
    sm = TpuStateMachine(account_capacity=1 << 12)
    cpu = CpuStateMachine()
    h, hc = SingleNodeHarness(sm), SingleNodeHarness(cpu)
    n_acct = 24
    accts = [account(i + 1) for i in range(n_acct)] + [
        account(n_acct + 1,
                flags=int(AccountFlags.debits_must_not_exceed_credits))
    ]
    h.create_accounts(accts)
    hc.create_accounts(accts)
    assert sm._commitment is not None
    assert sm._commitment.root_bytes() == _scratch_root(sm)
    tid = _fuzz_ops(h, np.random.default_rng(11), n_acct, 1000)
    _fuzz_ops(hc, np.random.default_rng(11), n_acct, 1000)
    assert sm._commitment.root_bytes() == _scratch_root(sm)
    # Pending expiry (apply_subs path) via a pulse.
    h.create_transfers([transfer(tid, debit_account_id=5, credit_account_id=6,
                                 amount=3, flags=int(TF.pending), timeout=1)])
    hc.create_transfers([transfer(tid, debit_account_id=5, credit_account_id=6,
                                  amount=3, flags=int(TF.pending), timeout=1)])
    far = 20_000_000_000
    h.lookup_accounts([1])
    h.submit(Operation.lookup_accounts, ids_bytes([1]), realtime=far)
    hc.submit(Operation.lookup_accounts, ids_bytes([1]), realtime=far)
    assert sm._commitment.root_bytes() == _scratch_root(sm)
    # The CPU oracle computes the identical root for the same stream.
    assert sm.state_root() == cpu.state_root()


def test_linked_account_rollback_keeps_twin_current():
    from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine

    sm = TpuStateMachine(account_capacity=1 << 12)
    h = SingleNodeHarness(sm)
    h.create_accounts([account(1), account(2)])
    before = sm.state_root()
    # Linked chain whose tail fails (duplicate id): every slot the
    # chain allocated rolls back — the root must return exactly.
    res = h.create_accounts([
        account(50, flags=int(AccountFlags.linked)),
        account(1),  # exists -> chain fails
    ])
    assert any(code != 0 for _i, code in res)
    assert sm.state_root() == before == _scratch_root(sm)
    # And a successful chain moves it.
    h.create_accounts([account(60, flags=int(AccountFlags.linked)),
                       account(61)])
    assert sm.state_root() != before
    assert sm._commitment.root_bytes() == _scratch_root(sm)


def test_state_root_matches_with_commitment_disabled(monkeypatch):
    """TB_STATE_COMMIT=0 disables the incremental machinery, not the
    root: the from-scratch value must be identical."""
    from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine

    def drive(sm):
        h = SingleNodeHarness(sm)
        h.create_accounts([account(i + 1) for i in range(8)])
        h.create_transfers([
            transfer(1, debit_account_id=1, credit_account_id=2, amount=7),
        ])
        return sm.state_root()

    on = drive(TpuStateMachine(account_capacity=1 << 12))
    monkeypatch.setenv("TB_STATE_COMMIT", "0")
    sm_off = TpuStateMachine(account_capacity=1 << 12)
    assert sm_off._commitment is None
    assert sm_off._mirror.commitment is None
    assert drive(sm_off) == on


# ----------------------------------------------------------------------
# Device engine: incremental digest as a by-product of every execution
# path, cheap scrub/handshake with fetch-count assertions.


def _device_sm(capacity, link=None):
    from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine

    return TpuStateMachine(
        engine="device", account_capacity=capacity, device_link=link
    )


def _assert_device_consistent(sm):
    sm._dev.drain()
    sm._dev.flush()
    d = sm._dev
    assert d.dev_digest is not None
    pair = np.asarray(d.commit_probe())
    twin = sm._commitment.digest
    assert (pair[0] == pair[1]).all(), "incremental != from-scratch on device"
    assert (pair[1] == twin).all(), "device digest != host twin"
    assert sm.state_root() == _scratch_root(sm)


@pytest.mark.parametrize(
    "capacity",
    [
        1 << 10,  # 1024 % 8 == 0: row-sharded over the forced 8-dev mesh
        1012,     # 1012 % 8 != 0: dense single-device placement
    ],
    ids=["sharded", "dense"],
)
def test_device_digest_differential_fuzz(capacity):
    sm = _device_sm(capacity)
    if capacity % 8 == 0:
        assert sm._dev.sharding is not None, "expected a row-sharded engine"
    else:
        assert sm._dev.sharding is None
    h = SingleNodeHarness(sm)
    n_acct = 32
    h.create_accounts([account(i + 1) for i in range(n_acct)])
    _assert_device_consistent(sm)
    rng = np.random.default_rng(23)
    tid = _fuzz_ops(h, rng, n_acct, 5000, batches=10)
    _assert_device_consistent(sm)
    # Growth: push the account count past the engine capacity.
    extra = [account(10_000 + i) for i in range(capacity - n_acct + 8)]
    for i in range(0, len(extra), 1024):
        h.create_accounts(extra[i : i + 1024])
    assert sm._dev.capacity > capacity
    _assert_device_consistent(sm)
    _fuzz_ops(h, rng, n_acct, tid, batches=5)
    _assert_device_consistent(sm)


def test_cheap_scrub_no_full_fetch_and_corruption_healed():
    import jax.numpy as jnp

    sm = _device_sm(1 << 10)
    h = SingleNodeHarness(sm)
    h.create_accounts([account(i + 1) for i in range(16)])
    h.create_transfers([
        transfer(1, debit_account_id=1, credit_account_id=2, amount=9),
    ])
    d = sm._dev
    d.drain()
    d.flush()
    # Happy path: cheap scrubs only — the full-table fetch counter
    # must stay at ZERO.
    for _ in range(3):
        assert d.scrub() is True
    assert d.stat_scrub_cheap == 3
    assert d.stat_full_fetches == 0
    assert d.stat_scrub_fallback == 0
    # Corrupt one device row out of band (an HBM bit flip no step
    # touched): the NEXT cheap scrub must catch it (from-scratch vs
    # maintained digest), localize it with exactly one full fetch,
    # and heal through the existing re-upload path.
    d.balances = d.balances.at[7, 2].add(jnp.uint64(1))
    assert d.scrub() is False
    assert d.stat_scrub_fallback == 1
    assert d.stat_full_fetches == 1
    assert d.stat_scrub_heals == 1
    _assert_device_consistent(sm)
    assert d.scrub() is True
    assert d.stat_full_fetches == 1  # healed: back to cheap
    # Meta corruption is as detectable as balance corruption.
    d.meta = d.meta.at[3, 1].add(jnp.uint32(1))
    assert d.scrub() is False
    assert d.stat_scrub_heals == 2
    _assert_device_consistent(sm)


def test_deep_scrub_cadence(monkeypatch):
    """TB_DEV_SCRUB_FALLBACK=2: every 2nd scrub runs the full-fetch
    localization even when the cheap compare matched — and a clean
    deep scrub heals nothing."""
    monkeypatch.setenv("TB_DEV_SCRUB_FALLBACK", "2")
    sm = _device_sm(1 << 10)
    h = SingleNodeHarness(sm)
    h.create_accounts([account(1), account(2)])
    d = sm._dev
    d.drain()
    base_scrubs = d.stat_scrubs
    for _ in range(4):
        assert d.scrub() is True
    deep = sum(
        1 for k in range(base_scrubs + 1, d.stat_scrubs + 1) if k % 2 == 0
    )
    assert d.stat_full_fetches == deep > 0
    assert d.stat_scrub_heals == 0


def test_demote_repromote_handshake_cheap():
    from tigerbeetle_tpu.testing.chaos import ChaosLink

    link = ChaosLink(seed=1)
    sm = _device_sm(1 << 10, link=link)
    h = SingleNodeHarness(sm)
    h.create_accounts([account(i + 1) for i in range(8)])
    h.create_transfers([
        transfer(1, debit_account_id=1, credit_account_id=2, amount=4),
    ])
    sm._dev.drain()
    sm._dev.flush()
    # Fatal loss -> demote; degraded commits keep the twin current.
    link.kill()
    h.create_transfers([
        transfer(2, debit_account_id=2, credit_account_id=3, amount=5),
    ])
    d = sm._dev
    assert d.state is types.EngineState.degraded
    assert sm._commitment.root_bytes() == _scratch_root(sm)
    link.heal()
    full_before = d.stat_full_fetches
    assert d.try_repromote() is True
    assert d.state is types.EngineState.healthy
    # The handshake compared 16-byte roots: no full-table fetch.
    assert d.stat_full_fetches == full_before
    _assert_device_consistent(sm)
    # A twin the mirror does NOT back must fail the handshake closed.
    link.kill()
    h.create_transfers([
        transfer(3, debit_account_id=1, credit_account_id=4, amount=2),
    ])
    assert d.state is types.EngineState.degraded
    sm._commitment.digest = sm._commitment.digest + np.uint64(1)
    link.heal()
    assert d.try_repromote() is False
    assert d.state is types.EngineState.degraded
    sm._commitment.rebuild(sm._mirror)
    assert d.try_repromote() is True
    _assert_device_consistent(sm)


def test_verify_device_mirror_catches_twin_drift():
    sm = _device_sm(1 << 10)
    h = SingleNodeHarness(sm)
    h.create_accounts([account(1), account(2)])
    h.create_transfers([
        transfer(1, debit_account_id=1, credit_account_id=2, amount=3),
    ])
    sm.verify_device_mirror()  # clean
    sm._commitment.digest = sm._commitment.digest + np.uint64(5)
    with pytest.raises(AssertionError, match="commitment divergence"):
        sm.verify_device_mirror()


def test_commitment_disabled_engine_uses_legacy_scrub(monkeypatch):
    monkeypatch.setenv("TB_STATE_COMMIT", "0")
    sm = _device_sm(1 << 10)
    h = SingleNodeHarness(sm)
    h.create_accounts([account(1), account(2)])
    d = sm._dev
    d.drain()
    assert d.dev_digest is None
    assert d.scrub() is True
    assert d.stat_scrub_cheap == 0  # legacy full-digest compare ran
    sm.verify_device_mirror()


# ----------------------------------------------------------------------
# Checkpoint state roots: superblock recording + recovery recompute.


def _layout():
    from tigerbeetle_tpu.vsr.storage import ZoneLayout

    return ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 20)


def test_checkpoint_state_root_roundtrip():
    from tigerbeetle_tpu.vsr import replica as vsr_replica
    from tigerbeetle_tpu.vsr.storage import MemoryStorage

    storage = MemoryStorage(_layout())
    vsr_replica.format(storage, 7)
    r = vsr_replica.Replica(storage, 7, CpuStateMachine(cfg.TEST_MIN))
    r.open()
    r.on_request(Operation.create_accounts, pack([account(1), account(2)]))
    r.on_request(
        Operation.create_transfers,
        pack([transfer(10, debit_account_id=1, credit_account_id=2,
                       amount=100)]),
    )
    r.checkpoint()
    r.close()
    recorded = int(r.superblock.working["state_root_lo"]) | (
        int(r.superblock.working["state_root_hi"]) << 64
    )
    assert recorded == int.from_bytes(r.sm.state_root(), "little") != 0

    # Restart: open() recomputes the root from the restored snapshot
    # and asserts it against the superblock.
    r2 = vsr_replica.Replica(storage, 7, CpuStateMachine(cfg.TEST_MIN))
    r2.open()
    assert r2.sm.state_root() == r.sm.state_root()
    r2.close()

    # A superblock whose recorded root contradicts the snapshot dies
    # at open, not at the next cross-replica divergence.
    sb = r2.superblock
    hdr = sb.working.copy()
    hdr["state_root_lo"] = int(hdr["state_root_lo"]) ^ 1
    hdr["sequence"] = int(hdr["sequence"]) + 1
    sb._write(hdr)
    r3 = vsr_replica.Replica(storage, 7, CpuStateMachine(cfg.TEST_MIN))
    with pytest.raises(RuntimeError, match="state root mismatch"):
        r3.open()


def test_cluster_convergence_compares_roots():
    """The VOPR convergence checker now asserts one root across
    replicas — and a deliberately drifted state machine trips it."""
    from tigerbeetle_tpu.testing.cluster import Cluster

    cluster = Cluster(replica_count=2, seed=3)
    client = cluster.client(100)
    client.register()
    cluster.run_until(lambda: client.registered)
    assert cluster.run_request(
        client, Operation.create_accounts, pack([account(1), account(2)])
    ) == b""
    assert cluster.run_request(
        client, Operation.create_transfers,
        pack([transfer(5, debit_account_id=1, credit_account_id=2, amount=3)]),
    ) == b""
    cluster.settle()
    cluster.check_convergence()
    roots = {r.sm.state_root() for r in cluster.replicas}
    assert len(roots) == 1 and next(iter(roots)) != bytes(16)
