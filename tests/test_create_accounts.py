"""create_accounts semantics vs the reference precedence ladder.

Covers all 22 CreateAccountResult codes (reference:
src/tigerbeetle.zig:145-180, src/state_machine.zig:1421-1459).
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.testing.harness import SingleNodeHarness, account, pack

CAR = types.CreateAccountResult
AF = types.AccountFlags


@pytest.fixture
def h(sm):
    return SingleNodeHarness(sm)


def test_ok_and_timestamps(h):
    assert h.create_accounts([account(1), account(2)]) == []
    found = h.lookup_accounts([1, 2])
    assert len(found) == 2
    ts = [int(r["timestamp"]) for r in found]
    # Events get timestamp - n + i + 1 (reference: src/state_machine.zig:1253).
    assert ts[1] == ts[0] + 1
    assert types.u128_get(found[0], "id") == 1


def test_validation_ladder(h):
    cases = [
        (account(1, reserved=5), CAR.reserved_field),
        (account(1, flags=1 << 9), CAR.reserved_flag),
        (account(0), CAR.id_must_not_be_zero),
        (account(types.U128_MAX), CAR.id_must_not_be_int_max),
        (
            account(1, flags=AF.debits_must_not_exceed_credits | AF.credits_must_not_exceed_debits),
            CAR.flags_are_mutually_exclusive,
        ),
        (account(1, debits_pending=1), CAR.debits_pending_must_be_zero),
        (account(1, debits_posted=1), CAR.debits_posted_must_be_zero),
        (account(1, credits_pending=1), CAR.credits_pending_must_be_zero),
        (account(1, credits_posted=1), CAR.credits_posted_must_be_zero),
        (account(1, ledger=0), CAR.ledger_must_not_be_zero),
        (account(1, code=0), CAR.code_must_not_be_zero),
    ]
    for row, expected in cases:
        assert h.create_accounts([row]) == [(0, expected)], expected


def test_timestamp_must_be_zero(h):
    assert h.create_accounts([account(1, timestamp=99)]) == [
        (0, CAR.timestamp_must_be_zero)
    ]


def test_precedence_reserved_field_first(h):
    # reserved_field outranks everything below it even when several
    # violations coexist.
    row = account(0, reserved=1, ledger=0, code=0, debits_posted=5)
    assert h.create_accounts([row]) == [(0, CAR.reserved_field)]


def test_exists_ladder(h):
    base = dict(ledger=7, code=3, user_data_128=10, user_data_64=20, user_data_32=30)
    assert h.create_accounts([account(1, **base)]) == []
    cases = [
        (account(1, flags=AF.history, **base), CAR.exists_with_different_flags),
        (
            account(1, **{**base, "user_data_128": 11}),
            CAR.exists_with_different_user_data_128,
        ),
        (
            account(1, **{**base, "user_data_64": 21}),
            CAR.exists_with_different_user_data_64,
        ),
        (
            account(1, **{**base, "user_data_32": 31}),
            CAR.exists_with_different_user_data_32,
        ),
        (account(1, **{**base, "ledger": 8}), CAR.exists_with_different_ledger),
        (account(1, **{**base, "code": 4}), CAR.exists_with_different_code),
        (account(1, **base), CAR.exists),
    ]
    for row, expected in cases:
        assert h.create_accounts([row]) == [(0, expected)], expected


def test_linked_chain_success(h):
    rows = [
        account(1, flags=AF.linked),
        account(2, flags=AF.linked),
        account(3),
    ]
    assert h.create_accounts(rows) == []
    assert len(h.lookup_accounts([1, 2, 3])) == 3


def test_linked_chain_rollback_fifo_order(h):
    rows = [
        account(1, flags=AF.linked),
        account(2, flags=AF.linked),
        account(0),  # breaks the chain
    ]
    assert h.create_accounts(rows) == [
        (0, CAR.linked_event_failed),
        (1, CAR.linked_event_failed),
        (2, CAR.id_must_not_be_zero),
    ]
    assert len(h.lookup_accounts([1, 2])) == 0


def test_linked_chain_open(h):
    rows = [account(1), account(2, flags=AF.linked)]
    assert h.create_accounts(rows) == [
        (1, CAR.linked_event_chain_open),
    ]
    assert len(h.lookup_accounts([1])) == 1
    assert len(h.lookup_accounts([2])) == 0


def test_chain_open_rolls_back_whole_chain(h):
    rows = [
        account(1, flags=AF.linked),
        account(2, flags=AF.linked),
    ]
    assert h.create_accounts(rows) == [
        (0, CAR.linked_event_failed),
        (1, CAR.linked_event_chain_open),
    ]
    assert len(h.lookup_accounts([1, 2])) == 0


def test_multiple_independent_chains(h):
    rows = [
        account(1, flags=AF.linked),
        account(2),
        account(0, flags=AF.linked),  # chain 2 fails at head
        account(3),
        account(4),
    ]
    assert h.create_accounts(rows) == [
        (2, CAR.id_must_not_be_zero),
        (3, CAR.linked_event_failed),
    ]
    assert len(h.lookup_accounts([1, 2, 4])) == 3


def test_exists_within_same_batch(h):
    # The second event sees the first event's insert.
    assert h.create_accounts([account(1), account(1)]) == [(1, CAR.exists)]


def test_import_within_failed_chain_not_visible(h):
    rows = [
        account(1, flags=AF.linked),
        account(1),  # duplicate inside the chain -> exists -> chain broke? no:
    ]
    # account(1) sees the chain's insert of id=1; the flags differ
    # (linked vs not) so the exists-ladder stops at flags.
    assert h.create_accounts(rows) == [
        (0, CAR.linked_event_failed),
        (1, CAR.exists_with_different_flags),
    ]
    assert len(h.lookup_accounts([1])) == 0
