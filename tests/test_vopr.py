"""VOPR runs: seeded whole-cluster fuzzing with nemesis events."""

import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu.testing.vopr import Vopr, Workload


@pytest.mark.parametrize("seed", [1, 7, 42, 1234])
def test_vopr_seed(seed):
    v = Vopr(seed, requests=80)
    v.run()
    # Corpus visibility: the restart-equivalence checker must actually
    # run for this corpus (not be skipped by uncommitted suffixes).
    _RESTART_CHECKS.append(not v.restart_check_skipped)


_RESTART_CHECKS: list[bool] = []


def test_vopr_restart_check_exercised():
    assert any(_RESTART_CHECKS), "restart-equivalence never exercised"


def test_vopr_no_faults_longer():
    Vopr(99, requests=200, packet_loss=0.0, crash_probability=0.0).run()


@pytest.mark.parametrize("seed", [44, 71])
def test_vopr_reconfigure_nemesis(seed):
    """Standby-promotion reconfigures ride the op stream under the
    full nemesis suite.  Seed 44 caught reply divergence on replay: a
    replica that heartbeat-adopted epoch N replied "stale" to the
    intermediate epochs it later replayed while live replicas had
    replied "ok" — fixed by splitting committed epoch/members (pure
    function of the op stream, validates replies) from the adopted
    runtime role (may run ahead via heartbeats)."""
    Vopr(seed, requests=80, standby_count=1, reconfigure_nemesis=True,
         queries=True).run()


@pytest.mark.parametrize(
    "seed,pl,cp,co,up,q,req",
    [
        (300661417, 0.07704366683116852, 0.00454365485120272, 0.005,
         False, False, 120),
        (399484635, 0.04704768808915133, 0.034975506481705096, 0.005,
         True, True, 60),
    ],
)
def test_vopr_sync_membership_seed(seed, pl, cp, co, up, q, req):
    """Soak-found class: a state-synced replica jumped commit_min past
    the reconfigure ops without adopting their committed epoch, then
    rejected every later epoch as stale — committed epochs diverged
    cluster-wide (0/4/5/6 at the same prefix) and the cluster wedged
    with three processes believing they filled the standby slot.  The
    checkpoint blob now carries committed epoch+members, and the sync
    install persists them."""
    Vopr(seed, requests=req, packet_loss=pl, crash_probability=cp,
         corruption_probability=co, upgrade_nemesis=up, queries=q,
         standby_count=1, reconfigure_nemesis=True).run()


def test_vopr_reconfigure_superseded_identity_seed():
    """Soak seed 420704875: a process restarted into view_change under
    a superseded identity (its old slot reassigned by a reconfigure it
    missed) dropped the heartbeat membership advertisement at the
    status gate — its DVCs then came from a slot someone else fills,
    start_view replies routed to the new holder, and it never
    rejoined.  Membership adoption now runs before the status gate."""
    Vopr(420704875, requests=120, packet_loss=0.013541258428352805,
         crash_probability=0.025638242944772172,
         corruption_probability=0.0, standby_count=1,
         reconfigure_nemesis=True).run()


def test_vopr_membership_gossip_seed():
    """Soak seed 421977104 (upgrade + reconfigure): the only process
    holding a committed-but-locally-unreplayed epoch sat in
    view_change as a standby — heartbeats (primary -> normal peers
    only) could never spread it, every process answered to a
    different slot mapping, and no view change could elect anyone.
    The adopted-membership advertisement now also rides pings/pongs,
    which flow between ALL processes in ANY status."""
    Vopr(421977104, requests=60, packet_loss=0.04996161937441321,
         crash_probability=0.03123750376134976,
         corruption_probability=0.005, upgrade_nemesis=True,
         standby_count=1, reconfigure_nemesis=True).run()


def test_vopr_uncovered_tail_eviction_seed():
    """Soak seed 460103075 (reconfigure): a freshly-restarted primary
    with an adopted-but-unapplied tail (commit_max still 0, repairs
    pending) requeued only the prepares it HELD; a client whose
    register sat in the repair holes was evicted.  The eviction gate
    now queues while the pipeline does not cover the whole
    uncommitted range."""
    Vopr(460103075, requests=120, packet_loss=0.07999176030219339,
         crash_probability=0.022697472687653826,
         corruption_probability=0.001, standby_count=1,
         reconfigure_nemesis=True).run()


def test_vopr_ring_wrap_headroom_seed():
    """Soak seed 202019721 (upgrade + reconfigure + partition): with
    commits stalled, every view change cleared the pipeline and let
    the new primary accept another pipeline's worth of requests — op
    ran 67 past the stuck commit point and the WAL ring wrap
    DESTROYED the only copies of two uncommitted ops cluster-wide,
    wedging repair forever.  Prepares now stop at
    checkpoint_op + journal_slot_count (_prepare_headroom)."""
    Vopr(202019721, requests=120, packet_loss=0.020119223364905816,
         crash_probability=0.011281813826024015,
         corruption_probability=0.005, upgrade_nemesis=True,
         standby_count=1, reconfigure_nemesis=True,
         partition_probability=0.02).run()


def test_vopr_vouch_chain_hole_seed():
    """Soak seed 157503236 (upgrade + partition): a standby held every
    prepare below the commit frontier EXCEPT a mid-suffix hole; the
    vouch chain walk broke at the hole without pinning it, and since
    commits were gated BELOW the hole, _advance_commit never reached
    it to request repair — the standby wedged at its vouch gate
    forever.  _extend_vouches_down now pins the exact canonical
    checksum when the walk cannot cross a slot."""
    Vopr(157503236, requests=60, packet_loss=0.0035477406232641505,
         crash_probability=0.027937796807999706,
         corruption_probability=0.001, upgrade_nemesis=True,
         standby_count=1, partition_probability=0.01).run()


@pytest.mark.parametrize("seed", [5, 812])
def test_vopr_query_workload(seed):
    """The v2 workload profile: lookup_transfers, AccountFilter scans
    (get_account_transfers / get_account_balances over history
    accounts), and balancing transfers ride the replicated commit
    path under faults — cross-replica determinism of scan replies is
    enforced by the convergence + restart-equivalence checkers."""
    Vopr(seed, requests=80, queries=True, packet_loss=0.03,
         crash_probability=0.015, corruption_probability=0.001).run()


def test_vopr_heavy_faults():
    Vopr(31337, requests=50, packet_loss=0.05, crash_probability=0.02).run()


def test_vopr_mixed_chain_dvc_seed():
    """Soak seed 323928758: a crash-restart resurrected the dead
    pre-install tail from the journal ring (an install truncates only
    in memory), and the replica's DVC shipped a MIXED chain — the
    dead suffix contradicted the persisted canonical at the boundary,
    the receiving merge's sanitize dropped the TRUE canonical op, and
    one replica committed a dead sibling where its peer committed the
    replacement (divergence).  _tail_headers now drops ring leftovers
    above the vouched canonical suffix that both predate the install
    and do not chain from it."""
    Vopr(323928758, requests=60, packet_loss=0.07277437499431165,
         crash_probability=0.026907902268880925,
         corruption_probability=0.001).run()


@pytest.mark.xfail(
    reason="Open soak finds under the (new) hard-partition nemesis, "
    "kept visible: seed 358225701 — a committed pending transfer "
    "deterministically vanishes from every replica's store between "
    "its create and its post (state stays convergent; suspected LSM "
    "spill/prefetch edge at a checkpoint boundary); seed 685139142 — "
    "non-convergence under upgrade+partition.  Neither reproduces "
    "without partitions.",
    strict=False,
)
@pytest.mark.parametrize(
    "seed,pl,cp,co,up,pp",
    [
        (358225701, 0.0140380841210626, 0.013286828489109052, 0.001,
         False, 0.02),
        (685139142, 0.07681442444729558, 0.012627161760209353, 0.001,
         True, 0.01),
    ],
)
def test_vopr_partition_open_finds(seed, pl, cp, co, up, pp):
    Vopr(seed, requests=120, packet_loss=pl, crash_probability=cp,
         corruption_probability=co, upgrade_nemesis=up,
         partition_probability=pp).run()


@pytest.mark.parametrize("seed", [9, 310])
def test_vopr_partition_nemesis(seed):
    """Hard partitions (a process cut off but RUNNING — state intact,
    clock advancing, rejoining live-but-stale) layered over crashes,
    corruption, queries, and reconfiguration."""
    Vopr(seed, requests=80, partition_probability=0.02, queries=True,
         standby_count=1, reconfigure_nemesis=True,
         corruption_probability=0.005).run()


def test_vopr_primary_scrub_repair_seed():
    """Seed 99911308: a latent WAL fault on the PRIMARY for a
    current-view committed op — scrub repair replies were dropped by
    the primary's ring-wrap guard, leaving the slot unhealable."""
    Vopr(99911308, requests=60, packet_loss=0.069,
         crash_probability=0.027, corruption_probability=0.005).run()


@pytest.mark.parametrize(
    "seed,pl,cp,co,up",
    [
        (925761995, 0.039035675104828776, 0.02793538190863725, 0.0, False),
        (941686528, 0.03065367688868138, 0.010939315579479669, 0.005, True),
        (199800160, 0.04844306222485367, 0.026223549036723696, 0.001, True),
    ],
)
def test_vopr_stale_carrier_merge_seed(seed, pl, cp, co, up):
    """The stale-carrier merge class: a replica whose ring lagged its
    installed canonical (repairs pending across crash-restart view
    changes) restarted vouching pre-merge siblings at the freshest
    log_view, and the merge adopted a superseded sibling whose
    replacement no ring still held.  Fixed by persisting the installed
    canonical suffix in the superblock atomically with log_view
    (superblock view_headers — the reference's durable vsr_headers)
    and letting it override older-view ring entries in _tail_headers."""
    Vopr(seed, requests=70, packet_loss=pl, crash_probability=cp,
         corruption_probability=co, upgrade_nemesis=up).run()


def test_vopr_repair_target_rotation_seed():
    """Seed 803272239: the view-4 primary and one backup both lost op
    72's prepare; primary-asks-successor / backup-asks-primary meant
    the lone holder (the other backup) was never asked and the cluster
    wedged with commits gated forever.  Pinned (checksum-addressed)
    repair retries now rotate across all peers."""
    Vopr(803272239, requests=60, packet_loss=0.035301351406234624,
         crash_probability=0.029253284284020395,
         corruption_probability=0.005).run()


def test_vopr_stale_pin_overwrite_seed():
    """Seed 460991023: checksum pins left over from a dead view
    survived the canonical install AND the primary's own fresh
    prepares; a delayed old-view prepare matching such a pin then
    overwrote the newly-prepared canonical slot, hijacked the head
    anchor, and let the out-of-order stash extend the head with stale
    content (its linkage guard silently passed while the head's WAL
    write was in flight).  Fixed by clearing superseded pins at
    install (anchor pin excepted), popping the pin when preparing new
    content at an op, and making the stash-drain linkage check
    positive against parent_checksum."""
    Vopr(460991023, requests=60, packet_loss=0.05448703242272319,
         crash_probability=0.02540533516142603,
         corruption_probability=0.001).run()


def test_vopr_pipelined_register_eviction_seed():
    """Seed 653186412: a new primary re-replicating an adopted tail
    (acks lost) held the client's register in its PIPELINE — none of
    the recovery-state gates covered it and the client was evicted.
    The eviction gate now scans the pipeline for the client's
    register."""
    Vopr(653186412, requests=70, packet_loss=0.07044680383270262,
         crash_probability=0.01897982395119349,
         corruption_probability=0.005, upgrade_nemesis=True).run()


def test_vopr_unapplied_suffix_eviction_seed():
    """Seed 666677761: a replica holding a recovered-but-unapplied
    journal suffix (commit_max lagging self.op right after open)
    evicted a registered client whose register op sat in that suffix.
    Requests must queue while ANY known suffix is unapplied."""
    Vopr(666677761, requests=70, packet_loss=0.02435230291464637,
         crash_probability=0.008999239897508116,
         corruption_probability=0.005, upgrade_nemesis=True).run()


def test_vopr_understating_dvc_seed():
    """Seed 1064614514: a replica installed a view's canonical claim
    (op N) but crashed before repairing the prepares; restart forgot
    the claim, its understating DVC won the next view's merge as the
    highest-log_view cohort, and committed ops above its headers were
    truncated then re-prepared with new content.  The canonical claim
    is now durable in the superblock, and DVC merges gap-fill holes
    from lower-log_view members' headers."""
    Vopr(1064614514, requests=70, packet_loss=0.06103258542385661,
         crash_probability=0.033260095782756224,
         corruption_probability=0.005, upgrade_nemesis=True).run()


def test_vopr_duplicate_start_view_seed():
    """Seed 377174739: a delayed duplicate start_view (same view,
    shorter claimed op) regressed a backup's head while its anchor was
    stale; a chain walk from that anchor derived an unserviceable pin
    that gated commits forever (cluster livelock).  Reinstalls must
    keep the same-view head (min_head) and chain walks must not run
    from an unresolved anchor."""
    Vopr(377174739, requests=60, packet_loss=0.078286280370049,
         crash_probability=0.02088690985851417,
         upgrade_nemesis=True).run()


def test_vopr_unknown_anchor_seed():
    """Seed 170611267: upgrade restarts truncated recovering journals
    below committed ops, the DVC merge then lacked the head's header
    (commit_floor above every merged op), and the new primary prepared
    fresh ops against a stale parent_checksum — baking a chain break
    into the committed log that later recoveries truncated, erasing
    acked creates.  The primary must hold new prepares until the
    canonical head checksum is resolved and repaired."""
    Vopr(170611267, requests=60, packet_loss=0.060985872622017885,
         crash_probability=0.026099500507950336,
         corruption_probability=0.0, upgrade_nemesis=True).run()


def test_vopr_tpu_state_machine():
    from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine

    Vopr(
        17, requests=40, packet_loss=0.0, crash_probability=0.0,
        state_machine_factory=lambda: TpuStateMachine(cfg.TEST_MIN),
    ).run()


def test_workload_deterministic():
    a = Workload(5)
    b = Workload(5)
    for _ in range(50):
        assert a.next_request() == b.next_request()


def test_hash_log_divergence_pinpointing():
    from tigerbeetle_tpu.testing.hash_log import HashLog

    a, b = HashLog(), HashLog()
    for op in range(1, 20):
        a.record(op, b"header%d" % op, b"reply")
        b.record(op, b"header%d" % op, b"reply" if op != 13 else b"DIVERGED")
    assert a.first_divergence(b) == 13
    assert a.first_divergence(a) is None
    # Replay idempotence: re-recording an op yields the identical digest.
    d = a.digest(7)
    a.record(7, b"header7", b"reply")
    assert a.digest(7) == d


def test_vopr_tpu_state_machine_with_faults():
    """Whole-cluster fuzz with the TPU state machine (native C++ fast
    and exact engines + device write-behind) replicated under VSR,
    WITH the crash/partition/clock-skew nemesis enabled."""
    from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine

    Vopr(
        21, requests=40,
        state_machine_factory=lambda: TpuStateMachine(cfg.TEST_MIN),
    ).run()


@pytest.mark.parametrize("seed", [101, 202, 303, 404])
def test_vopr_fault_atlas_seed(seed):
    """Sector corruption (WAL/superblock/grid, atlas-guaranteed >= 1
    intact copy) + crash/partition/clock-skew + upgrade nemesis."""
    v = Vopr(
        seed, requests=150, corruption_probability=0.01,
        upgrade_nemesis=True,
    )
    v.run()
    assert v.corruptions > 0, "corruption nemesis never fired"


DEEP_SEEDS = list(range(8000, 8020))


@pytest.mark.parametrize("seed", DEEP_SEEDS[:6])
def test_vopr_deep_slice(seed):
    """A time-boxed slice of the VERDICT-grade matrix that runs on
    EVERY pytest invocation: 6 seeds x 800 ops with corruption (and
    the upgrade nemesis on even seeds) — the nemesis mix that caught
    three committed-state-loss bugs must not be opt-in.  The full
    20 x 2000 sweep stays behind VOPR_DEEP=1."""
    v = Vopr(
        seed, requests=800, corruption_probability=0.005,
        upgrade_nemesis=(seed % 2 == 0),
    )
    v.run()
    assert v.corruptions > 0, seed


def test_vopr_deep_matrix():
    """The full matrix: 20 seeds x 2000 ops with sector corruption.
    ~10 CPU-minutes, so the complete sweep runs only when explicitly
    requested (VOPR_DEEP=1); the 6-seed x 800-op slice above runs
    every time."""
    import os

    if os.environ.get("VOPR_DEEP") != "1":
        pytest.skip("set VOPR_DEEP=1 for the full matrix")
    for seed in DEEP_SEEDS:
        v = Vopr(
            seed, requests=2000, corruption_probability=0.005,
            upgrade_nemesis=(seed % 2 == 0),
        )
        v.run()
        assert v.corruptions > 0, seed


# ----------------------------------------------------------------------
# Multi-tenant overload VOPR (round 16).


@pytest.mark.parametrize("seed", [6, 23])
def test_vopr_multi_tenant_flood(seed):
    """One tenant floods (3 back-to-back clients on ledger 1) while
    two trickle, against replicas running per-tenant QoS with a tight
    admit queue: hash-log convergence, linearizability, and
    conservation-of-money hold across the shed/retry/backoff storms,
    crash/restart nemesis included — and every constructed-valid
    request eventually commits despite the sheds."""
    from tigerbeetle_tpu.testing.vopr import MultiTenantVopr

    v = MultiTenantVopr(seed, tenants=3, flood_clients=3, requests=30)
    v.run()
    # The run must actually exercise the QoS path: the flood tenant
    # was shed (typed busy reached clients and backoff engaged).
    assert v.sheds > 0, "flood never shed: overload not reached"
    assert v.busy_replies > 0
    assert v.busy_backoffs > 0


def test_vopr_multi_tenant_weighted():
    """Same arm with explicit TB_TENANT_WEIGHTS-shaped weights (the
    flood tenant deliberately UP-weighted 4x): invariants must hold
    regardless of how the shares are skewed."""
    from tigerbeetle_tpu.testing.vopr import MultiTenantVopr

    v = MultiTenantVopr(
        17, tenants=3, flood_clients=3, requests=24,
        weights={1: 4.0},
    )
    v.run()
    assert v.sheds > 0
