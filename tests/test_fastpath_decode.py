"""Columnar ingest fast path: fixture-oracle differential tests.

The server drain (runtime/server.py) may decode a whole poll's worth
of client frames in one batch pass (native tb_fp_verify_frames or the
vectorized Python fallback) and coalesce replies per drain — and the
wire contract must not move by a single bit.  The checked-in client
fixtures (clients/fixtures/frames.json, conversation.json) are the
oracle: decode columns must equal the legacy per-frame decode, and a
pinned-clock server must produce byte-identical reply FRAMES with the
columnar path forced on vs off, including when request frames arrive
torn across drain boundaries.
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from tigerbeetle_tpu.runtime import fastpath
from tigerbeetle_tpu.runtime.native import native_available
from tigerbeetle_tpu.vsr import wire

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "clients", "fixtures")
HEADER_SIZE = 256
CLUSTER = 3
PINNED_NS = 1_000_000_000


def _fixture_frames() -> list[bytes]:
    with open(os.path.join(FIXTURES, "frames.json")) as fh:
        return [bytes.fromhex(c["frame_hex"]) for c in json.load(fh)]


def _arena_of(frames: list[bytes]):
    blob = b"".join(frames)
    arena = np.frombuffer(blob, np.uint8)
    offsets = np.zeros(len(frames), np.uint64)
    lens = np.zeros(len(frames), np.uint32)
    at = 0
    for i, f in enumerate(frames):
        offsets[i] = at
        lens[i] = len(f)
        at += len(f)
    return arena, offsets, lens


def _mutations(frames: list[bytes]) -> list[bytes]:
    """The fixture frames plus torn/corrupt variants every decoder
    must reject identically: flipped body byte, flipped header byte,
    wrong version, size field lying about the frame length."""
    out = list(frames)
    body_frame = next(f for f in frames if len(f) > HEADER_SIZE)
    flipped_body = bytearray(body_frame)
    flipped_body[HEADER_SIZE + 3] ^= 0xFF
    out.append(bytes(flipped_body))
    flipped_header = bytearray(frames[0])
    flipped_header[40] ^= 0x01
    out.append(bytes(flipped_header))
    bad_version = bytearray(frames[0])
    bad_version[155] = 99
    out.append(bytes(bad_version))
    lying_size = bytearray(body_frame)
    lying_size[144:148] = (len(body_frame) + 128).to_bytes(4, "little")
    out.append(bytes(lying_size))
    return out


def test_batch_verify_matches_legacy_per_frame():
    frames = _mutations(_fixture_frames())
    arena, offsets, lens = _arena_of(frames)
    legacy = []
    for f in frames:
        h = wire.header_from_bytes(f[:HEADER_SIZE])
        legacy.append(int(wire.verify_header(h, f[HEADER_SIZE:])))
    ok_py = fastpath.verify_frames_py(arena, offsets, lens, len(frames))
    assert [int(v) for v in ok_py] == legacy
    ok_native = fastpath.verify_frames(arena, offsets, lens, len(frames))
    if ok_native is None:
        pytest.skip("native fastpath not built (fallback verified above)")
    assert [int(v) for v in ok_native] == legacy


def test_headers_from_arena_bit_identical():
    frames = _fixture_frames()
    arena, offsets, _lens = _arena_of(frames)
    hdrs = wire.headers_from_arena(arena, offsets, len(frames))
    for i, f in enumerate(frames):
        assert hdrs[i].tobytes() == f[:HEADER_SIZE]
        legacy = wire.header_from_bytes(f[:HEADER_SIZE])
        for name in ("command", "operation", "request", "client_lo",
                     "size", "trace_id", "trace_flags"):
            assert hdrs[i][name] == legacy[name], name


def test_finalize_headers_batch_parity():
    bodies = [b"", b"r" * 333, bytes(range(128)) * 5]
    hdrs = np.zeros(len(bodies), wire.HEADER_DTYPE)
    hdrs["version"] = wire.VERSION
    hdrs["command"] = int(wire.Command.reply)
    hdrs["request"] = np.arange(len(bodies))
    hdrs["client_lo"] = 0xC0FFEE
    oracle = hdrs.copy()
    wire.finalize_headers_py(oracle, bodies)
    for i, b in enumerate(bodies):
        assert wire.verify_header(oracle[i], b)
    if not fastpath.finalize_headers(hdrs, bodies):
        pytest.skip("native fastpath not built (fallback verified above)")
    assert hdrs.tobytes() == oracle.tobytes()


# ----------------------------------------------------------------------
# Live differential replay: columnar on vs off, bit-identical replies.


@pytest.fixture()
def pinned_time(monkeypatch):
    # Same determinism trick as the conversation fixture: pinned wall
    # clock (timestamps derive from event counts) + pinned monotonic
    # (no pulse op lands at a scheduling-dependent position), so two
    # separate server runs produce byte-identical reply frames.
    monkeypatch.setattr(time, "time_ns", lambda: PINNED_NS)
    monkeypatch.setattr(time, "monotonic_ns", lambda: 0)


def _replay_requests(tmp_path, tag: str, requests: list[bytes],
                     chunker) -> tuple[list[bytes], dict]:
    """One pinned-clock server run: send each request's bytes through
    `chunker` (which may tear them across writes), read one reply
    frame per request.  -> (reply frames, registry snapshot)."""
    from tigerbeetle_tpu.runtime.server import (
        ReplicaServer, format_data_file,
    )
    from tigerbeetle_tpu.state_machine import CpuStateMachine

    path = str(tmp_path / f"{tag}.tigerbeetle")
    format_data_file(path, cluster=CLUSTER, replica_index=0,
                     replica_count=1)
    server = ReplicaServer(
        path, addresses=["127.0.0.1:0"], replica_index=0,
        state_machine_factory=CpuStateMachine,
    )
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            server.poll_once(10)

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    replies = []
    try:
        sock = socket.create_connection(
            ("127.0.0.1", server.port), timeout=30
        )
        sock.settimeout(30)
        buf = b""
        for req in requests:
            for chunk in chunker(req):
                sock.sendall(chunk)
            while True:
                if len(buf) >= HEADER_SIZE:
                    size = int.from_bytes(buf[144:148], "little")
                    if len(buf) >= size:
                        replies.append(buf[:size])
                        buf = buf[size:]
                        break
                chunk = sock.recv(1 << 20)
                assert chunk, "server closed mid-replay"
                buf += chunk
        sock.close()
        return replies, server.registry.snapshot()
    finally:
        stop.set()
        t.join(timeout=5)
        server.close()


def _conversation_requests() -> list[bytes]:
    with open(os.path.join(FIXTURES, "conversation.json")) as fh:
        return [bytes.fromhex(s["request_hex"]) for s in json.load(fh)]


@pytest.mark.skipif(not native_available(), reason="native runtime not built")
def test_conversation_columnar_vs_legacy_bit_identical(
    tmp_path, pinned_time, monkeypatch
):
    """The full recorded conversation (register, failures, RETRANSMIT,
    lookups, queries) through the columnar drain vs the legacy
    per-message path: every reply frame byte-identical — headers,
    trace context, checksums, bodies — and equal to the checked-in
    transcript."""
    requests = _conversation_requests()

    def whole(req):
        return [req]

    monkeypatch.setenv("TB_FASTPATH_DECODE", "1")
    on, snap_on = _replay_requests(tmp_path, "on", requests, whole)
    monkeypatch.setenv("TB_FASTPATH_DECODE", "0")
    off, snap_off = _replay_requests(tmp_path, "off", requests, whole)
    assert on == off
    with open(os.path.join(FIXTURES, "conversation.json")) as fh:
        recorded = [bytes.fromhex(s["reply_hex"]) for s in json.load(fh)]
    assert on == recorded
    assert snap_on.get("fastpath.batch_decode_hits", 0) > 0
    assert snap_off.get("fastpath.batch_decode_hits", 0) == 0


@pytest.mark.skipif(not native_available(), reason="native runtime not built")
def test_torn_frames_across_drain_boundaries(
    tmp_path, pinned_time, monkeypatch
):
    """Fuzz the framing: request bytes torn into random chunks with
    scheduling gaps, so frames split and coalesce arbitrarily across
    poll drains — replies stay bit-identical between the columnar and
    legacy paths (and across tear patterns, since both runs use the
    same seed)."""
    requests = _conversation_requests()

    def torn(req: bytes, rng=np.random.default_rng(4242)):
        chunks = []
        at = 0
        while at < len(req):
            n = int(rng.integers(1, 512))
            chunks.append(req[at : at + n])
            at += n
        return chunks

    monkeypatch.setenv("TB_FASTPATH_DECODE", "1")
    on, _ = _replay_requests(
        tmp_path, "torn_on", requests, lambda r: torn(r)
    )
    monkeypatch.setenv("TB_FASTPATH_DECODE", "0")
    off, _ = _replay_requests(
        tmp_path, "torn_off", requests, lambda r: torn(r)
    )
    assert on == off
    with open(os.path.join(FIXTURES, "conversation.json")) as fh:
        recorded = [bytes.fromhex(s["reply_hex"]) for s in json.load(fh)]
    assert on == recorded


@pytest.mark.skipif(not native_available(), reason="native runtime not built")
def test_wrong_cluster_dropped_on_both_arms(tmp_path, pinned_time,
                                            monkeypatch):
    """A checksum-valid request addressed to a DIFFERENT cluster must
    be dropped by the columnar intake exactly as on_message drops it
    (cross-cluster isolation): the next same-connection request for
    the right cluster is answered, the foreign one never is."""
    from tigerbeetle_tpu import types

    frames = _fixture_frames()
    acct = np.zeros(1, types.ACCOUNT_DTYPE)
    acct["id_lo"] = 4242
    acct["ledger"] = 1
    acct["code"] = 1
    foreign = wire.make_header(
        command=wire.Command.request, cluster=CLUSTER + 1,
        client=0xBAD, request=1,
        operation=int(types.Operation.create_accounts),
    )
    wire.finalize_header(foreign, acct.tobytes())
    ids = np.zeros(1, types.U128_PAIR_DTYPE)
    ids[0]["lo"] = 4242
    lookup = wire.make_header(
        command=wire.Command.request, cluster=CLUSTER,
        client=0xC0FFEE, request=1,
        operation=int(types.Operation.lookup_accounts),
    )
    wire.finalize_header(lookup, ids.tobytes())
    for flag, tag in (("1", "iso_on"), ("0", "iso_off")):
        monkeypatch.setenv("TB_FASTPATH_DECODE", flag)
        # register (real) || foreign-cluster create_accounts || real
        # lookup: the foreign create must never commit, so the lookup
        # reply body is empty on BOTH arms.
        replies, _snap = _replay_requests(
            tmp_path, tag,
            [frames[0],
             foreign.tobytes() + acct.tobytes()
             + lookup.tobytes() + ids.tobytes()],
            lambda req: [req],
        )
        assert replies[1][HEADER_SIZE:] == b"", (
            f"arm {flag}: foreign-cluster request leaked into commit"
        )


@pytest.mark.skipif(not native_available(), reason="native runtime not built")
def test_frames_fixture_flood_one_drain(tmp_path, pinned_time, monkeypatch):
    """All frames.json requests flushed in ONE write after register:
    the whole stream lands in a single drain, so the columnar path
    multiplexes the intake — reply frames must still match the legacy
    path bit-for-bit (pinned clock makes both runs deterministic)."""
    frames = _fixture_frames()

    def run_burst(flag, tag):
        from tigerbeetle_tpu.runtime.server import (
            ReplicaServer, format_data_file,
        )
        from tigerbeetle_tpu.state_machine import CpuStateMachine

        monkeypatch.setenv("TB_FASTPATH_DECODE", flag)
        path = str(tmp_path / f"{tag}.tigerbeetle")
        format_data_file(path, cluster=CLUSTER, replica_index=0,
                         replica_count=1)
        server = ReplicaServer(
            path, addresses=["127.0.0.1:0"], replica_index=0,
            state_machine_factory=CpuStateMachine,
        )
        stop = threading.Event()

        def loop():
            while not stop.is_set():
                server.poll_once(10)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        try:
            sock = socket.create_connection(
                ("127.0.0.1", server.port), timeout=30
            )
            sock.settimeout(30)
            buf = b""

            def read_frame():
                nonlocal buf
                while True:
                    if len(buf) >= HEADER_SIZE:
                        size = int.from_bytes(buf[144:148], "little")
                        if len(buf) >= size:
                            out, buf2 = buf[:size], buf[size:]
                            buf = buf2
                            return out
                    chunk = sock.recv(1 << 20)
                    assert chunk
                    buf += chunk

            sock.sendall(frames[0])  # register
            replies = [read_frame()]
            sock.sendall(b"".join(frames[1:]))  # one drain's worth
            for _ in frames[1:]:
                replies.append(read_frame())
            sock.close()
            return replies
        finally:
            stop.set()
            t.join(timeout=5)
            server.close()

    on = run_burst("1", "flood_on")
    off = run_burst("0", "flood_off")
    assert on == off
    for f, r in zip(frames, on):
        rh = wire.header_from_bytes(r[:HEADER_SIZE])
        assert wire.verify_header(rh, r[HEADER_SIZE:])
        assert int(rh["request"]) == int.from_bytes(f[112:116], "little")
