"""Native commit pipeline (round 20, native/tb_pipeline.cpp): the
differential contract TB_NATIVE_PIPELINE=0/1 one layer above the r14
decode fast path.

Three tiers of evidence, mirroring how the seam can break:

- Unit differential: the C header builders and the journal append
  framing are fuzzed against the wire.py / journal.py Python oracles
  byte for byte.
- Cluster differential: the SAME deterministic sim-cluster script runs
  with the native pipeline on and off, and every prepare, prepare_ok,
  and client-reply FRAME on the wire (header bytes incl. trace /
  tenant + body) must be bit-identical.
- Chaos: crash-at-fsync failover fuzz and the r10 group-commit
  contract (no ack before its covering sync, self-vote gated on sync)
  re-run on the native arm with hash-log convergence, plus the
  C-table/Python-dict mirror invariant checked live.
"""

import time

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import SECTOR_SIZE
from tigerbeetle_tpu.runtime import fastpath
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.harness import account, pack, transfer
from tigerbeetle_tpu.vsr import storage as storage_mod
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.journal import HEADERS_PER_SECTOR
from tigerbeetle_tpu.vsr.storage import FsyncCrash
from tigerbeetle_tpu.vsr.wire import Command, HEADER_DTYPE

from test_multi import (  # noqa: F401  (fixture plumbing)
    _instrument_ack_ordering,
    _register,
    _setup_accounts,
)

pytestmark = pytest.mark.skipif(
    not fastpath.pipeline_available(),
    reason="libtb_fastpath with pipeline symbols not built",
)


# ----------------------------------------------------------------------
# Unit differential: C builders vs the wire.py oracle.


def _r64(rng) -> int:
    return int(rng.integers(0, 1 << 64, dtype=np.uint64))


def _r128(rng) -> int:
    return _r64(rng) | (_r64(rng) << 64)


def _fuzz_request(rng) -> tuple[np.void, bytes]:
    body = rng.bytes(int(rng.integers(0, 512)))
    req = wire.make_header(
        command=Command.request,
        operation=int(rng.integers(0, 200)),
        cluster=_r64(rng),
        client=_r128(rng) or 1,
        request=int(rng.integers(0, 1 << 32)),
        view=int(rng.integers(0, 1 << 16)),
        op=0, commit=0,
        timestamp=_r64(rng) >> 1,
        replica=0,
        release=int(rng.integers(0, 1 << 32)),
        tenant=int(rng.integers(0, 1 << 32)),
        trace_id=_r64(rng),
        trace_ts=_r64(rng),
        trace_flags=int(rng.integers(0, 2)),
    )
    wire.finalize_header(req, body)
    return req, body


def test_build_prepare_bit_identical_fuzz():
    rng = np.random.default_rng(20_01)
    pl = fastpath.create_pipeline()
    assert pl is not None
    for _ in range(200):
        req, body = _fuzz_request(rng)
        kw = dict(
            cluster=_r128(rng) >> 1,
            view=int(rng.integers(0, 1 << 31)),
            op=(_r64(rng) >> 2) or 1,
            commit=_r64(rng) >> 2,
            timestamp=_r64(rng) >> 1,
            parent=_r128(rng) >> 1,
            replica=int(rng.integers(0, 6)),
            context=int(rng.integers(0, 64)),
            release=int(rng.integers(0, 1 << 31)),
        )
        oracle = wire.make_header(
            command=Command.prepare, operation=int(req["operation"]),
            client=wire.u128(req, "client"), request=int(req["request"]),
            **kw,
        )
        wire.copy_trace(oracle, req)
        wire.finalize_header(oracle, body)
        native = pl.build_prepare(req, body, **kw)
        assert native.tobytes() == oracle.tobytes()
        # The oracle's checksum verifies — so the native one does too.
        assert wire.verify_header(native, body)


def test_build_prepare_ok_bit_identical_fuzz():
    rng = np.random.default_rng(20_02)
    pl = fastpath.create_pipeline()
    for _ in range(200):
        req, body = _fuzz_request(rng)
        prepare = wire.make_header(
            command=Command.prepare, operation=int(req["operation"]),
            cluster=_r128(rng) >> 1,
            client=wire.u128(req, "client"),
            view=int(rng.integers(0, 1 << 16)),
            op=(_r64(rng) >> 2) or 1,
            commit=0, timestamp=1, parent=2, replica=0, release=3,
        )
        wire.copy_trace(prepare, req)
        wire.finalize_header(prepare, body)
        view = int(rng.integers(0, 1 << 31))
        replica = int(rng.integers(0, 6))
        oracle = wire.make_header(
            command=Command.prepare_ok,
            cluster=wire.u128(prepare, "cluster"), view=view,
            op=int(prepare["op"]), replica=replica,
            context=wire.u128(prepare, "checksum"),
            client=wire.u128(prepare, "client"),
        )
        wire.copy_trace(oracle, prepare)
        wire.finalize_header(oracle, b"")
        native = pl.build_prepare_ok(prepare, view, replica)
        assert native.tobytes() == oracle.tobytes()


def test_frame_prepare_matches_python_framing_fuzz():
    """The C journal framing (padded prepare + in-place ring update +
    redundant sector) against journal.write_prepare's Python layout."""
    from tigerbeetle_tpu.vsr.storage import _sectors

    rng = np.random.default_rng(20_03)
    slot_count = 64
    assert slot_count % HEADERS_PER_SECTOR == 0
    ring_py = np.zeros(slot_count, HEADER_DTYPE)
    ring_c = np.zeros(slot_count, HEADER_DTYPE)
    scratch_prepare = np.zeros(_sectors(256 + 4096), np.uint8)
    scratch_sector = np.zeros(SECTOR_SIZE, np.uint8)
    for _ in range(100):
        body = rng.bytes(int(rng.integers(0, 4096)))
        op = int(rng.integers(1, 1 << 32))
        h = wire.make_header(
            command=Command.prepare, operation=int(rng.integers(0, 200)),
            cluster=7, client=9, view=1, op=op, commit=0,
            timestamp=_r64(rng) >> 2, parent=1,
            replica=0, release=1,
        )
        wire.finalize_header(h, body)
        slot = op % slot_count
        # Python oracle framing (journal.write_prepare's byte layout).
        msg = h.tobytes() + body
        padded_py = msg.ljust(_sectors(len(msg)), b"\x00")
        ring_py[slot] = h
        first = slot // HEADERS_PER_SECTOR * HEADERS_PER_SECTOR
        sector_py = ring_py[
            first : first + HEADERS_PER_SECTOR
        ].tobytes().ljust(SECTOR_SIZE, b"\x00")
        # Native framing.
        padded_len = fastpath.frame_prepare(
            h, body, ring_c, slot, HEADERS_PER_SECTOR, SECTOR_SIZE,
            scratch_prepare, scratch_sector,
        )
        assert padded_len == len(padded_py)
        assert scratch_prepare.tobytes()[:padded_len] == padded_py
        assert scratch_sector.tobytes() == sector_py
        assert ring_c[slot].tobytes() == h.tobytes()
    assert ring_c.tobytes() == ring_py.tobytes()


def test_slot_table_semantics():
    """The C in-flight table's vote/sync/gate semantics in isolation:
    exact-checksum votes, the synced gate, contiguity, reset."""
    pl = fastpath.create_pipeline()
    req, body = _fuzz_request(np.random.default_rng(20_04))
    prepare = wire.make_header(
        command=Command.prepare, cluster=7, client=9, view=1, op=5,
        commit=4, timestamp=1, parent=2, replica=0, release=1,
    )
    wire.finalize_header(prepare, body)
    pl.note_prepare(prepare, False, 0)
    assert pl.size() == 1 and pl.votes(5) == 1
    ok = wire.make_header(
        command=Command.prepare_ok, cluster=7, view=1, op=5, replica=1,
        context=wire.u128(prepare, "checksum"), client=9,
    )
    wire.finalize_header(ok, b"")
    assert pl.on_ack(ok) == 2
    assert pl.on_ack(ok) == 2  # duplicate ack: same bit, same count
    # Stale-sibling ack (wrong checksum) and unknown op both -> None.
    stale = wire.make_header(
        command=Command.prepare_ok, cluster=7, view=1, op=5, replica=1,
        context=123456789, client=9,
    )
    wire.finalize_header(stale, b"")
    assert pl.on_ack(stale) is None
    unknown = wire.make_header(
        command=Command.prepare_ok, cluster=7, view=1, op=99, replica=1,
        context=wire.u128(prepare, "checksum"), client=9,
    )
    wire.finalize_header(unknown, b"")
    assert pl.on_ack(unknown) is None
    # Quorum met but unsynced: the gate holds; sync opens it; a
    # non-contiguous commit_min keeps it shut.
    assert not pl.commit_ready(4, 2)
    pl.mark_all_synced()
    assert pl.commit_ready(4, 2)
    assert not pl.commit_ready(3, 2)  # op 4 not in flight
    assert not pl.commit_ready(4, 3)  # quorum of 3 not reached
    pl.drop(5)
    assert pl.size() == 0 and not pl.commit_ready(4, 2)
    pl.note_prepare(prepare, True, 0)
    pl.reset()
    assert pl.size() == 0


# ----------------------------------------------------------------------
# Stale-.so forensics: a library without (or with mismatched) pipeline
# symbols must fail fast on explicit opt-in, degrade once otherwise.


class _StaleLib:
    tb_pl_abi_version = None  # the loader's missing-symbol marker


def test_stale_library_fails_fast_on_explicit_opt_in(monkeypatch):
    monkeypatch.setattr(fastpath, "_load", lambda: _StaleLib())
    monkeypatch.setattr(fastpath, "_pipeline_warned", False)
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "1")
    assert not fastpath.pipeline_available()
    assert "make -C native" in fastpath.pipeline_error()
    with pytest.raises(RuntimeError, match="make -C native"):
        fastpath.create_pipeline()
    # Defaulted knob: one RuntimeWarning, then a silent Python
    # fallback — a bench box without a compiler still runs.
    monkeypatch.delenv("TB_NATIVE_PIPELINE")
    with pytest.warns(RuntimeWarning, match="falling back"):
        assert fastpath.create_pipeline() is None
    assert fastpath.create_pipeline() is None  # warned once only


def test_abi_version_mismatch_detected(monkeypatch):
    class _OldLib:
        @staticmethod
        def tb_pl_abi_version():
            return fastpath.PIPELINE_ABI + 1

    monkeypatch.setattr(fastpath, "_load", lambda: _OldLib())
    err = fastpath.pipeline_error()
    assert err is not None and "ABI" in err and "make -C native" in err


# ----------------------------------------------------------------------
# Cluster differential: same deterministic script, native on vs off,
# every consensus + reply frame bit-identical.


def _capture_frames(c: Cluster) -> list[tuple]:
    """Record every prepare / prepare_ok / reply frame leaving any
    replica (header bytes include trace, tenant, and checksum — the
    full 256-byte wire image — plus the body)."""
    frames: list[tuple] = []
    watched = {int(Command.prepare), int(Command.prepare_ok)}
    for r in c.replicas:
        orig_send = r.bus.send

        def send(dst, header, body, *, _r=r, _o=orig_send):
            if int(header["command"]) in watched:
                frames.append(
                    ("peer", _r.replica, dst, header.tobytes(), bytes(body))
                )
            _o(dst, header, body)

        r.bus.send = send
        orig_send_client = r.bus.send_client

        def send_client(client, header, body, *, _r=r,
                        _o=orig_send_client):
            if int(header["command"]) == int(Command.reply):
                frames.append(
                    ("client", _r.replica, client, header.tobytes(),
                     bytes(body))
                )
            _o(client, header, body)

        r.bus.send_client = send_client
    return frames


def _scripted_run(monkeypatch, native: str, *, gc: bool,
                  seed: int = 31) -> tuple[list[tuple], bytes]:
    """One deterministic conversation (register, accounts, transfers
    incl. a failure, lookups) on a 3-replica sim cluster; returns the
    captured wire frames and the final account table bytes."""
    monkeypatch.setenv("TB_NATIVE_PIPELINE", native)
    # The only nondeterministic bytes on the wire are trace_ts stamps
    # (observability-only, CLOCK_MONOTONIC): pin the clock so the
    # on/off frames are comparable bit for bit.
    monkeypatch.setattr(time, "perf_counter_ns", lambda: 1_000_000_000)
    if gc:
        monkeypatch.setattr(
            storage_mod.MemoryStorage, "supports_deferred_sync", True,
            raising=False,
        )
    c = Cluster(3, seed=seed)
    for r in c.replicas:
        assert (r._np is not None) == (native == "1")
        if gc:
            assert r._gc_enabled
    frames = _capture_frames(c)
    cl = _register(c, 100)
    _setup_accounts(c, cl, ids=(1, 2, 3))
    for k in range(12):
        reply = c.run_request(
            cl, types.Operation.create_transfers,
            pack([transfer(500 + k, debit_account_id=1 + (k % 2),
                           credit_account_id=3, amount=1 + k)]),
        )
        assert reply == b""
    # A failing transfer: result rows ride the reply body.
    bad = c.run_request(
        cl, types.Operation.create_transfers,
        pack([transfer(900, debit_account_id=1, credit_account_id=1,
                       amount=1)]),
    )
    assert bad != b""
    out = c.run_request(
        cl, types.Operation.lookup_accounts,
        np.array([1, 0, 2, 0, 3, 0], "<u8").tobytes(),
    )
    c.settle(4000)
    c.check_linearized()
    c.check_convergence()
    return frames, out


@pytest.mark.parametrize("gc", [False, True], ids=["sync", "group_commit"])
def test_conversation_frames_bit_identical_on_off(monkeypatch, gc):
    frames_on, table_on = _scripted_run(monkeypatch, "1", gc=gc)
    frames_off, table_off = _scripted_run(monkeypatch, "0", gc=gc)
    assert table_on == table_off
    assert len(frames_on) == len(frames_off)
    for a, b in zip(frames_on, frames_off):
        assert a == b
    # The comparison covered real consensus traffic.
    kinds = {f[0] for f in frames_on}
    assert kinds == {"peer", "client"}


def _assert_mirror(c: Cluster) -> None:
    """The C slot table must mirror the Python pipeline dict: same
    in-flight ops (above commit_min), same vote counts."""
    for r in c.replicas:
        if r._np is None:
            continue
        for op, entry in r.pipeline.items():
            if op <= r.commit_min:
                continue  # Python-side lazily cleaned; C already dropped
            votes = r._np.votes(op)
            assert votes == len(entry.ok_replicas), (
                f"replica {r.replica} op {op}: native votes {votes} != "
                f"python acks {len(entry.ok_replicas)}"
            )


def test_native_votes_mirror_python_acks(monkeypatch):
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "1")
    c = Cluster(3, seed=77)
    cl = _register(c, 100)
    _setup_accounts(c, cl)
    for k in range(10):
        cl.request(
            types.Operation.create_transfers,
            pack([transfer(700 + k, debit_account_id=1,
                           credit_account_id=2, amount=1)]),
        )
        for _ in range(300):
            c.step()
            _assert_mirror(c)
            if not cl.busy():
                break
        assert not cl.busy()
        assert cl.reply == b""


# ----------------------------------------------------------------------
# Chaos on the native arm: the r10 group-commit contract and
# crash-at-fsync failover with hash-log convergence.


@pytest.fixture
def native_gc_cluster(monkeypatch):
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "1")
    monkeypatch.setattr(
        storage_mod.MemoryStorage, "supports_deferred_sync", True,
        raising=False,
    )
    c = Cluster(3, seed=11)
    for r in c.replicas:
        assert r._gc_enabled and r._np is not None
    return c


def test_gc_contract_never_acks_before_covering_sync_native(
    native_gc_cluster,
):
    """The r10 self-vote-gated-on-covering-sync contract, native arm:
    the exact test body from test_multi re-driven with the C gate
    answering the commit decision."""
    import test_multi

    test_multi.test_group_commit_never_acks_before_covering_sync(
        native_gc_cluster
    )


@pytest.mark.parametrize("seed", [3, 19, 47])
def test_crash_at_fsync_failover_fuzz_native(monkeypatch, seed):
    """Primary dies inside a covering fsync at a fuzzed point in the
    stream; failover + recovery must lose nothing acked, the hash
    logs must converge, and the ack-ordering instrument must stay
    clean — all with the native gate deciding commits."""
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "1")
    monkeypatch.setattr(
        storage_mod.MemoryStorage, "supports_deferred_sync", True,
        raising=False,
    )
    rng = np.random.default_rng(seed)
    c = Cluster(3, seed=seed)
    violations = _instrument_ack_ordering(c)
    cl = _register(c, 100)
    _setup_accounts(c, cl)
    acked = 0
    next_id = [seed * 1000]

    def send_next():
        next_id[0] += 1
        cl.request(
            types.Operation.create_transfers,
            pack([transfer(next_id[0], debit_account_id=1,
                           credit_account_id=2, amount=1)]),
        )

    for _ in range(int(rng.integers(2, 6))):
        send_next()
        c.run_until(lambda: not cl.busy())
        assert cl.reply == b""
        acked += 1

    c.storages[0].crash_at_fsync = int(rng.integers(1, 4))
    send_next()
    crashed = False
    for _ in range(600):
        try:
            c.step()
        except FsyncCrash:
            crashed = True
            c.crash_replica(0)
            break
        if not cl.busy():
            acked += 1
            send_next()
    assert crashed, "seeded crash_at_fsync never fired"

    c.run_until(lambda: not cl.busy(), 6000)
    acked += 1
    c.restart_replica(0)
    c.settle(6000)
    c.check_linearized()
    c.check_convergence()
    assert violations == [], violations[:10]
    _assert_mirror(c)

    from tigerbeetle_tpu.testing.harness import ids_bytes

    out = c.run_request(cl, types.Operation.lookup_accounts, ids_bytes([1]))
    row = np.frombuffer(out, types.ACCOUNT_DTYPE)[0]
    assert types.u128_get(row, "debits_posted") == acked
