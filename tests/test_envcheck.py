"""Env-var validation: bad tuning values fail fast with errors that
name the variable and the violated constraint, instead of dying in a
bare int() traceback or assert deep inside the consumer module."""

import pytest

from tigerbeetle_tpu import envcheck
from tigerbeetle_tpu.state_machine import waves
from tigerbeetle_tpu.state_machine.device_engine import (
    _validate_window_ring,
)


def test_env_int_rejects_garbage(monkeypatch):
    monkeypatch.setenv("TB_DEV_WINDOW", "ninety-six")
    with pytest.raises(envcheck.EnvVarError, match="TB_DEV_WINDOW"):
        envcheck.env_int("TB_DEV_WINDOW", 96, minimum=1)


def test_env_int_bounds(monkeypatch):
    monkeypatch.setenv("TB_DEV_RING", "0")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 2"):
        envcheck.env_int("TB_DEV_RING", 256, minimum=2)
    monkeypatch.setenv("TB_DEV_RING", "512")
    assert envcheck.env_int("TB_DEV_RING", 256, minimum=2) == 512


def test_env_int_default_when_unset(monkeypatch):
    monkeypatch.delenv("TB_DEV_WINDOW", raising=False)
    assert envcheck.env_int("TB_DEV_WINDOW", 96, minimum=1) == 96


def test_tb_group_commit_max_us_validated(monkeypatch):
    monkeypatch.setenv("TB_GROUP_COMMIT_MAX_US", "soon")
    with pytest.raises(envcheck.EnvVarError, match="TB_GROUP_COMMIT_MAX_US"):
        envcheck.group_commit_max_us()
    monkeypatch.setenv("TB_GROUP_COMMIT_MAX_US", "-1")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0"):
        envcheck.group_commit_max_us()
    monkeypatch.setenv("TB_GROUP_COMMIT_MAX_US", "0")  # 0 = disabled
    assert envcheck.group_commit_max_us() == 0
    monkeypatch.setenv("TB_GROUP_COMMIT_MAX_US", "5000")
    assert envcheck.group_commit_max_us() == 5000
    monkeypatch.delenv("TB_GROUP_COMMIT_MAX_US")
    assert envcheck.group_commit_max_us() == 2000  # default on


def test_tb_ckpt_async_validated(monkeypatch):
    monkeypatch.setenv("TB_CKPT_ASYNC", "yes")
    with pytest.raises(envcheck.EnvVarError, match="TB_CKPT_ASYNC"):
        envcheck.ckpt_async()
    monkeypatch.setenv("TB_CKPT_ASYNC", "2")
    with pytest.raises(envcheck.EnvVarError, match="must be <= 1"):
        envcheck.ckpt_async()
    monkeypatch.setenv("TB_CKPT_ASYNC", "0")
    assert envcheck.ckpt_async() == 0
    monkeypatch.delenv("TB_CKPT_ASYNC")
    assert envcheck.ckpt_async() == 1  # default on


def test_tb_ckpt_async_disables_worker(monkeypatch, tmp_path):
    """TB_CKPT_ASYNC=0 keeps the whole checkpoint on the commit path
    (no checkpoint worker), even on FileStorage."""
    from tigerbeetle_tpu import constants as cfg
    from tigerbeetle_tpu.state_machine import CpuStateMachine
    from tigerbeetle_tpu.vsr import replica as vsr_replica
    from tigerbeetle_tpu.vsr.storage import FileStorage, ZoneLayout

    layout = ZoneLayout(config=cfg.TEST_MIN, grid_size=1 << 20)
    path = str(tmp_path / "data.tb")
    storage = FileStorage(path, layout, create=True)
    vsr_replica.format(storage, 5)
    monkeypatch.setenv("TB_CKPT_ASYNC", "0")
    r = vsr_replica.Replica(storage, 5, CpuStateMachine(cfg.TEST_MIN))
    assert r._ckpt_worker is None
    monkeypatch.setenv("TB_CKPT_ASYNC", "1")
    r2 = vsr_replica.Replica(storage, 5, CpuStateMachine(cfg.TEST_MIN))
    assert r2._ckpt_worker is not None
    r.close()
    r2.close()
    storage.close()


def test_tb_fastpath_decode_validated(monkeypatch):
    monkeypatch.setenv("TB_FASTPATH_DECODE", "fast")
    with pytest.raises(envcheck.EnvVarError, match="TB_FASTPATH_DECODE"):
        envcheck.fastpath_decode()
    monkeypatch.setenv("TB_FASTPATH_DECODE", "2")
    with pytest.raises(envcheck.EnvVarError, match="must be <= 1"):
        envcheck.fastpath_decode()
    monkeypatch.setenv("TB_FASTPATH_DECODE", "0")  # forced legacy path
    assert envcheck.fastpath_decode() == 0
    monkeypatch.delenv("TB_FASTPATH_DECODE")
    assert envcheck.fastpath_decode() == 1  # default: columnar on


def test_tb_fastpath_decode_zero_forces_legacy(monkeypatch, tmp_path):
    """TB_FASTPATH_DECODE=0 must actually pin the server to the
    per-message path (differential runs depend on it), and =1 must
    engage the columnar drain when the native bus supports it."""
    from tigerbeetle_tpu import constants as cfg
    from tigerbeetle_tpu.runtime.native import native_available
    from tigerbeetle_tpu.state_machine import CpuStateMachine

    if not native_available():
        pytest.skip("native runtime not built")
    from tigerbeetle_tpu.runtime.server import (
        ReplicaServer, format_data_file,
    )

    def build(flag):
        monkeypatch.setenv("TB_FASTPATH_DECODE", flag)
        path = str(tmp_path / f"fp{flag}.tb")
        format_data_file(path, cluster=5, config=cfg.TEST_MIN)
        return ReplicaServer(
            path, cluster=5, addresses=["127.0.0.1:0"], replica_index=0,
            state_machine_factory=lambda: CpuStateMachine(cfg.TEST_MIN),
            config=cfg.TEST_MIN,
        )

    off = build("0")
    try:
        assert off._fastpath_decode is False
    finally:
        off.close()
    on = build("1")
    try:
        assert on._fastpath_decode == on.bus.native.supports_drain
    finally:
        on.close()


def test_tb_drain_batch_constraint_named(monkeypatch):
    monkeypatch.setenv("TB_DRAIN_BATCH", "many")
    with pytest.raises(envcheck.EnvVarError, match="TB_DRAIN_BATCH"):
        envcheck.drain_batch_max()
    monkeypatch.setenv("TB_DRAIN_BATCH", "4")
    with pytest.raises(envcheck.EnvVarError, match="per-message rounds"):
        envcheck.drain_batch_max()
    monkeypatch.setenv("TB_DRAIN_BATCH", str(1 << 17))
    with pytest.raises(envcheck.EnvVarError, match="must be <="):
        envcheck.drain_batch_max()
    monkeypatch.setenv("TB_DRAIN_BATCH", "64")
    assert envcheck.drain_batch_max() == 64
    monkeypatch.delenv("TB_DRAIN_BATCH")
    assert envcheck.drain_batch_max() == 4096


def test_window_ring_constraint_named():
    with pytest.raises(envcheck.EnvVarError) as err:
        _validate_window_ring(200, 256)
    message = str(err.value)
    assert "TB_DEV_WINDOW" in message
    assert "TB_DEV_RING" in message
    assert "2*TB_DEV_WINDOW" in message
    _validate_window_ring(128, 256)  # boundary is legal


def test_tb_waves_mode_validated(monkeypatch):
    monkeypatch.setenv("TB_WAVES", "fast")
    with pytest.raises(envcheck.EnvVarError, match="TB_WAVES"):
        waves.mode()
    for legal in ("auto", "0", "1", "exact", "scan"):
        monkeypatch.setenv("TB_WAVES", legal)
        assert waves.mode() == legal


def test_tb_waves_min_ratio_validated(monkeypatch):
    monkeypatch.setenv("TB_WAVES_MIN_RATIO", "two")
    with pytest.raises(envcheck.EnvVarError, match="TB_WAVES_MIN_RATIO"):
        waves.min_ratio()
    monkeypatch.setenv("TB_WAVES_MIN_RATIO", "1.5")
    assert waves.min_ratio() == 1.5


def test_tb_dev_waves_mode_validated(monkeypatch):
    monkeypatch.setenv("TB_DEV_WAVES", "fast")
    with pytest.raises(envcheck.EnvVarError, match="TB_DEV_WAVES"):
        waves.dev_mode()
    for legal in ("auto", "0", "1"):
        monkeypatch.setenv("TB_DEV_WAVES", legal)
        assert waves.dev_mode() == legal
    monkeypatch.delenv("TB_DEV_WAVES")
    assert waves.dev_mode() == "auto"


def test_tb_waves_chain_max_validated(monkeypatch):
    monkeypatch.setenv("TB_WAVES_CHAIN_MAX", "many")
    with pytest.raises(envcheck.EnvVarError, match="TB_WAVES_CHAIN_MAX"):
        waves.chain_max()
    monkeypatch.setenv("TB_WAVES_CHAIN_MAX", "-1")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0"):
        waves.chain_max()
    monkeypatch.setenv("TB_WAVES_CHAIN_MAX", "5000")
    with pytest.raises(envcheck.EnvVarError, match="must be <= 4096"):
        waves.chain_max()
    monkeypatch.setenv("TB_WAVES_CHAIN_MAX", "0")  # 0 = chain waves off
    assert waves.chain_max() == 0
    monkeypatch.delenv("TB_WAVES_CHAIN_MAX")
    assert waves.chain_max() == 64


def test_tb_waves_speculate_validated(monkeypatch):
    monkeypatch.setenv("TB_WAVES_SPECULATE", "maybe")
    with pytest.raises(envcheck.EnvVarError, match="TB_WAVES_SPECULATE"):
        waves.spec_mode()
    for legal in ("auto", "0", "1", "force"):
        monkeypatch.setenv("TB_WAVES_SPECULATE", legal)
        assert waves.spec_mode() == legal
    monkeypatch.delenv("TB_WAVES_SPECULATE")
    assert waves.spec_mode() == "auto"


def test_tb_waves_spec_residue_cap_validated(monkeypatch):
    monkeypatch.setenv("TB_WAVES_SPEC_RESIDUE_CAP", "some")
    with pytest.raises(
        envcheck.EnvVarError, match="TB_WAVES_SPEC_RESIDUE_CAP"
    ):
        waves.spec_residue_cap()
    monkeypatch.setenv("TB_WAVES_SPEC_RESIDUE_CAP", "-0.1")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0"):
        waves.spec_residue_cap()
    # Named constraint: the cap is a FRACTION of the batch.
    monkeypatch.setenv("TB_WAVES_SPEC_RESIDUE_CAP", "1.5")
    with pytest.raises(envcheck.EnvVarError, match="fraction of the batch"):
        waves.spec_residue_cap()
    monkeypatch.setenv("TB_WAVES_SPEC_RESIDUE_CAP", "0.5")
    assert waves.spec_residue_cap() == 0.5
    monkeypatch.delenv("TB_WAVES_SPEC_RESIDUE_CAP")
    assert waves.spec_residue_cap() == 0.25


def test_env_float_minimum(monkeypatch):
    monkeypatch.setenv("TB_DEV_BACKOFF_MS", "-1")
    with pytest.raises(envcheck.EnvVarError, match="TB_DEV_BACKOFF_MS"):
        envcheck.env_float("TB_DEV_BACKOFF_MS", 5.0, minimum=0.0)


def test_env_choice(monkeypatch):
    monkeypatch.delenv("TB_WAVES", raising=False)
    assert envcheck.env_choice("TB_WAVES", "auto", ("auto", "0")) == "auto"
    monkeypatch.setenv("TB_WAVES", "nope")
    with pytest.raises(envcheck.EnvVarError, match="expected one of"):
        envcheck.env_choice("TB_WAVES", "auto", ("auto", "0"))


def test_scrub_jitter_constraint_named():
    from tigerbeetle_tpu.state_machine.device_engine import (
        _scrub_jitter_cap,
        _validate_scrub_jitter,
    )

    with pytest.raises(envcheck.EnvVarError) as err:
        _validate_scrub_jitter(256, 256)
    message = str(err.value)
    assert "TB_DEV_SCRUB_JITTER" in message
    assert "TB_DEV_SCRUB_EVERY" in message
    _validate_scrub_jitter(256, 255)  # boundary is legal
    _validate_scrub_jitter(0, 1_000_000)  # scrub disabled: jitter moot
    assert _scrub_jitter_cap(256, -1) == 32  # auto: an eighth
    assert _scrub_jitter_cap(256, 5) == 5
    assert _scrub_jitter_cap(0, -1) == 0


def test_scrub_jitter_env_parses(monkeypatch):
    monkeypatch.setenv("TB_DEV_SCRUB_JITTER", "sometimes")
    with pytest.raises(envcheck.EnvVarError, match="TB_DEV_SCRUB_JITTER"):
        envcheck.env_int("TB_DEV_SCRUB_JITTER", -1, minimum=-1)
    monkeypatch.setenv("TB_DEV_SCRUB_JITTER", "-2")
    with pytest.raises(envcheck.EnvVarError, match="must be >= -1"):
        envcheck.env_int("TB_DEV_SCRUB_JITTER", -1, minimum=-1)
    monkeypatch.setenv("TB_DEV_SCRUB_JITTER", "17")
    assert envcheck.env_int("TB_DEV_SCRUB_JITTER", -1, minimum=-1) == 17


def test_tb_metrics_validated(monkeypatch):
    monkeypatch.setenv("TB_METRICS", "maybe")
    with pytest.raises(envcheck.EnvVarError, match="TB_METRICS"):
        envcheck.metrics_enabled()
    monkeypatch.setenv("TB_METRICS", "2")
    with pytest.raises(envcheck.EnvVarError, match="must be <= 1"):
        envcheck.metrics_enabled()
    monkeypatch.setenv("TB_METRICS", "0")
    assert envcheck.metrics_enabled() == 0
    monkeypatch.delenv("TB_METRICS")
    assert envcheck.metrics_enabled() == 1  # default on


def test_tb_trace_validated(monkeypatch):
    monkeypatch.setenv("TB_TRACE", "perfetto")
    with pytest.raises(
        envcheck.EnvVarError, match="TB_TRACE.*none/json"
    ):
        envcheck.trace_backend()
    monkeypatch.setenv("TB_TRACE", "json")
    assert envcheck.trace_backend() == "json"
    monkeypatch.delenv("TB_TRACE")
    assert envcheck.trace_backend() == "none"  # default off


def test_tb_trace_exemplars_validated(monkeypatch):
    monkeypatch.setenv("TB_TRACE_EXEMPLARS", "lots")
    with pytest.raises(envcheck.EnvVarError, match="TB_TRACE_EXEMPLARS"):
        envcheck.trace_exemplars()
    monkeypatch.setenv("TB_TRACE_EXEMPLARS", "0")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 1"):
        envcheck.trace_exemplars()
    monkeypatch.setenv("TB_TRACE_EXEMPLARS", "64")
    assert envcheck.trace_exemplars() == 64
    monkeypatch.delenv("TB_TRACE_EXEMPLARS")
    assert envcheck.trace_exemplars() == 32  # default


def test_tb_flight_ring_validated(monkeypatch):
    monkeypatch.setenv("TB_FLIGHT_RING", "big")
    with pytest.raises(envcheck.EnvVarError, match="TB_FLIGHT_RING"):
        envcheck.flight_ring()
    monkeypatch.setenv("TB_FLIGHT_RING", "0")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 1"):
        envcheck.flight_ring()
    monkeypatch.setenv("TB_FLIGHT_RING", "128")
    assert envcheck.flight_ring() == 128
    monkeypatch.delenv("TB_FLIGHT_RING")
    assert envcheck.flight_ring() == 4096  # default


def test_tb_admit_queue_constraint_names_pipeline(monkeypatch):
    monkeypatch.setenv("TB_ADMIT_QUEUE", "soonish")
    with pytest.raises(envcheck.EnvVarError, match="TB_ADMIT_QUEUE"):
        envcheck.admit_queue(8)
    # Constraint: queue bound >= pipeline depth, named in the error.
    monkeypatch.setenv("TB_ADMIT_QUEUE", "4")
    with pytest.raises(
        envcheck.EnvVarError, match="pipeline depth \\(8\\)"
    ):
        envcheck.admit_queue(8)
    assert envcheck.admit_queue(4) == 4  # boundary is legal
    monkeypatch.setenv("TB_ADMIT_QUEUE", "16")
    assert envcheck.admit_queue(8) == 16
    monkeypatch.delenv("TB_ADMIT_QUEUE")
    assert envcheck.admit_queue(8) == 1024  # default


def test_open_loop_bench_envs_validated(monkeypatch):
    monkeypatch.setenv("BENCH_OPEN_SECS", "fast")
    with pytest.raises(envcheck.EnvVarError, match="BENCH_OPEN_SECS"):
        envcheck.open_loop_secs()
    monkeypatch.setenv("BENCH_OPEN_SECS", "0.01")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0.1"):
        envcheck.open_loop_secs()
    monkeypatch.delenv("BENCH_OPEN_SECS")
    assert envcheck.open_loop_secs() == 4.0

    monkeypatch.setenv("BENCH_OPEN_BATCH", "9000")
    with pytest.raises(envcheck.EnvVarError, match="must be <= 8190"):
        envcheck.open_loop_batch()
    monkeypatch.delenv("BENCH_OPEN_BATCH")
    assert envcheck.open_loop_batch() == 256

    monkeypatch.setenv("BENCH_OPEN_HOT_PCT", "150")
    with pytest.raises(envcheck.EnvVarError, match="must be <= 100"):
        envcheck.open_loop_hot_pct()
    monkeypatch.setenv("BENCH_OPEN_HOT_PCT", "35")
    assert envcheck.open_loop_hot_pct() == 35.0
    monkeypatch.delenv("BENCH_OPEN_HOT_PCT")

    monkeypatch.setenv("BENCH_OPEN_BURST", "0.5")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 1"):
        envcheck.open_loop_burst()
    monkeypatch.delenv("BENCH_OPEN_BURST")
    assert envcheck.open_loop_burst() == 4.0


def test_tenant_qos_envs_validated(monkeypatch):
    monkeypatch.setenv("TB_TENANT_QOS", "2")
    with pytest.raises(envcheck.EnvVarError, match="TB_TENANT_QOS"):
        envcheck.tenant_qos()
    monkeypatch.delenv("TB_TENANT_QOS")
    assert envcheck.tenant_qos() == 1  # QoS on by default

    monkeypatch.setenv("TB_TENANT_RATE", "-1")
    with pytest.raises(envcheck.EnvVarError, match="TB_TENANT_RATE"):
        envcheck.tenant_rate()
    monkeypatch.delenv("TB_TENANT_RATE")
    assert envcheck.tenant_rate() == 0.0  # rate limit off by default

    monkeypatch.setenv("TB_BUSY_BACKOFF_MS", "nah")
    with pytest.raises(envcheck.EnvVarError, match="TB_BUSY_BACKOFF_MS"):
        envcheck.busy_backoff_ms()
    monkeypatch.setenv("TB_BUSY_BACKOFF_MS", "0")
    assert envcheck.busy_backoff_ms() == 0.0  # legacy immediate retry
    monkeypatch.delenv("TB_BUSY_BACKOFF_MS")
    assert envcheck.busy_backoff_ms() == 20.0

    monkeypatch.setenv("BENCH_QOS_SECS", "0.01")
    with pytest.raises(envcheck.EnvVarError, match="BENCH_QOS_SECS"):
        envcheck.qos_suite_secs()
    monkeypatch.delenv("BENCH_QOS_SECS")
    assert envcheck.qos_suite_secs() == 3.0


def test_tenant_queue_constraint_names_global_bound(monkeypatch):
    # A per-tenant bound above the global queue bound can never bind.
    monkeypatch.setenv("TB_TENANT_QUEUE", "100")
    with pytest.raises(
        envcheck.EnvVarError, match="TB_ADMIT_QUEUE \\(64\\)"
    ):
        envcheck.tenant_queue(64)
    monkeypatch.setenv("TB_TENANT_QUEUE", "16")
    assert envcheck.tenant_queue(64) == 16
    monkeypatch.delenv("TB_TENANT_QUEUE")
    # 0 (default) = the global bound: no extra per-tenant isolation.
    assert envcheck.tenant_queue(64) == 64


def test_tenant_weights_validated(monkeypatch):
    monkeypatch.setenv("TB_TENANT_WEIGHTS", "1:4, 7:2")
    assert envcheck.tenant_weights() == {1: 4.0, 7: 2.0}
    monkeypatch.setenv("TB_TENANT_WEIGHTS", "1:0")
    with pytest.raises(envcheck.EnvVarError, match="TB_TENANT_WEIGHTS"):
        envcheck.tenant_weights()
    monkeypatch.setenv("TB_TENANT_WEIGHTS", "banana")
    with pytest.raises(envcheck.EnvVarError, match="TB_TENANT_WEIGHTS"):
        envcheck.tenant_weights()
    monkeypatch.delenv("TB_TENANT_WEIGHTS")
    assert envcheck.tenant_weights() == {}


def test_tb_native_sanitize_validated(monkeypatch):
    monkeypatch.setenv("TB_NATIVE_SANITIZE", "msan")
    with pytest.raises(envcheck.EnvVarError, match="TB_NATIVE_SANITIZE"):
        envcheck.native_sanitize()
    monkeypatch.setenv("TB_NATIVE_SANITIZE", "asan")
    assert envcheck.native_sanitize() == "asan"
    monkeypatch.delenv("TB_NATIVE_SANITIZE")
    assert envcheck.native_sanitize() == ""  # default: release builds


def test_no_tb_knob_bypasses_envcheck():
    """Audit lint: every TB_*/BENCH_* knob in the package must be read
    through envcheck.py (validated, named errors), never via a raw
    os.environ / os.getenv call.  Round 17 migrated the r16 grep onto
    the tbcheck `envcheck` AST rule, which also resolves import
    aliases — ``from os import environ as E; E["TB_X"]`` no longer
    walks past the audit (proven by fixture in tests/test_tbcheck.py).
    """
    from tigerbeetle_tpu.analysis import run_lint
    from tigerbeetle_tpu.analysis.rules import EnvcheckRule

    result = run_lint(rules=[EnvcheckRule()])
    assert not result.findings, "\n".join(
        str(f) for f in result.findings
    )


def test_tb_metrics_disables_histograms(monkeypatch):
    from tigerbeetle_tpu import obs

    monkeypatch.setenv("TB_METRICS", "0")
    reg = obs.Registry()
    hist = reg.histogram("x_us")
    hist.observe(12.0)  # no-op: nothing recorded, no clock reads
    assert hist.count == 0 and hist.percentile(0.99) == 0.0
    assert "x_us.count" not in reg.snapshot()
    # Counters stay live regardless of the knob.
    reg.counter("c").inc(3)
    assert reg.snapshot()["c"] == 3


def test_sharded_router_envs_validated(monkeypatch):
    monkeypatch.setenv("TB_SHARDS", "many")
    with pytest.raises(envcheck.EnvVarError, match="TB_SHARDS"):
        envcheck.shards()
    monkeypatch.setenv("TB_SHARDS", "0")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 1"):
        envcheck.shards()
    monkeypatch.setenv("TB_SHARDS", "65")
    with pytest.raises(envcheck.EnvVarError, match="must be <= 64"):
        envcheck.shards()
    monkeypatch.setenv("TB_SHARDS", "4")
    assert envcheck.shards() == 4
    monkeypatch.delenv("TB_SHARDS")
    assert envcheck.shards() == 1  # default: unsharded

    monkeypatch.setenv("TB_ROUTER_QUEUE", "0")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 1"):
        envcheck.router_queue()
    monkeypatch.setenv("TB_ROUTER_QUEUE", "512")
    assert envcheck.router_queue() == 512
    monkeypatch.delenv("TB_ROUTER_QUEUE")
    assert envcheck.router_queue() == 256

    monkeypatch.setenv("TB_COORD_RETRY_MS", "5")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 10"):
        envcheck.coord_retry_ms()
    monkeypatch.delenv("TB_COORD_RETRY_MS")
    assert envcheck.coord_retry_ms() == 1000


def test_coord_timeout_names_view_change_constraint(monkeypatch):
    """The named constraint: the cross-shard hold timeout must exceed
    one shard's view-change budget, or a decided commit could expire
    under a primary failover on the credit shard."""
    budget = envcheck.view_change_budget_s()
    assert budget == 5.0  # VIEW_CHANGE_TICKS * TICK_NS
    monkeypatch.setenv("TB_COORD_TIMEOUT_S", "soon")
    with pytest.raises(envcheck.EnvVarError, match="TB_COORD_TIMEOUT_S"):
        envcheck.coord_timeout_s()
    monkeypatch.setenv("TB_COORD_TIMEOUT_S", "5")
    with pytest.raises(
        envcheck.EnvVarError, match="view-change budget \\(5s\\)"
    ):
        envcheck.coord_timeout_s()
    monkeypatch.setenv("TB_COORD_TIMEOUT_S", "6")
    assert envcheck.coord_timeout_s() == 6
    monkeypatch.delenv("TB_COORD_TIMEOUT_S")
    assert envcheck.coord_timeout_s() == 30  # default


def test_open_loop_read_pct_validated(monkeypatch):
    monkeypatch.setenv("BENCH_OPEN_READ_PCT", "110")
    with pytest.raises(envcheck.EnvVarError, match="must be <= 100"):
        envcheck.open_loop_read_pct()
    monkeypatch.setenv("BENCH_OPEN_READ_PCT", "-1")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0"):
        envcheck.open_loop_read_pct()
    monkeypatch.setenv("BENCH_OPEN_READ_PCT", "35")
    assert envcheck.open_loop_read_pct() == 35.0
    monkeypatch.delenv("BENCH_OPEN_READ_PCT")
    assert envcheck.open_loop_read_pct() == 20.0  # default


def test_tb_state_commit_validated(monkeypatch):
    monkeypatch.setenv("TB_STATE_COMMIT", "maybe")
    with pytest.raises(envcheck.EnvVarError, match="TB_STATE_COMMIT"):
        envcheck.state_commit()
    monkeypatch.setenv("TB_STATE_COMMIT", "2")
    with pytest.raises(envcheck.EnvVarError, match="must be <= 1"):
        envcheck.state_commit()
    monkeypatch.setenv("TB_STATE_COMMIT", "0")
    assert envcheck.state_commit() == 0
    monkeypatch.delenv("TB_STATE_COMMIT")
    assert envcheck.state_commit() == 1  # default on


def test_tb_dev_scrub_fallback_validated(monkeypatch):
    monkeypatch.setenv("TB_DEV_SCRUB_FALLBACK", "often")
    with pytest.raises(envcheck.EnvVarError, match="TB_DEV_SCRUB_FALLBACK"):
        envcheck.scrub_fallback_every()
    monkeypatch.setenv("TB_DEV_SCRUB_FALLBACK", "-1")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0"):
        envcheck.scrub_fallback_every()
    monkeypatch.setenv("TB_DEV_SCRUB_FALLBACK", "4")
    assert envcheck.scrub_fallback_every() == 4
    monkeypatch.delenv("TB_DEV_SCRUB_FALLBACK")
    assert envcheck.scrub_fallback_every() == 0  # only on mismatch


def test_tb_metrics_disables_commitment_instruments(monkeypatch):
    """TB_METRICS=0: the commitment's latency histograms (digest
    update, cheap/fallback scrub split) become shared no-ops — a
    digest-update site costs one attribute check, no clock read —
    while the commit.* counters stay live (bench accounting reads
    them)."""
    from tigerbeetle_tpu import obs

    monkeypatch.setenv("TB_METRICS", "0")
    reg = obs.Registry()
    for name in ("commit.update_us", "scrub.cheap_us", "scrub.fallback_us"):
        hist = reg.histogram(name)
        hist.observe(5.0)
        assert hist.count == 0 and hist.percentile(0.5) == 0.0
        assert f"{name}.count" not in reg.snapshot()
    reg.counter("commit.updates").inc()
    reg.counter("commit.scrub_cheap").inc(2)
    snap = reg.snapshot()
    assert snap["commit.updates"] == 1
    assert snap["commit.scrub_cheap"] == 2


# ----------------------------------------------------------------------
# Root-attested follower serving (round 19).


def test_tb_root_ring_validated(monkeypatch):
    monkeypatch.setenv("TB_ROOT_RING", "many")
    with pytest.raises(envcheck.EnvVarError, match="TB_ROOT_RING"):
        envcheck.root_ring()
    monkeypatch.setenv("TB_ROOT_RING", "-1")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0"):
        envcheck.root_ring()
    monkeypatch.setenv("TB_ROOT_RING", "0")  # 0 = no at-op attestation
    assert envcheck.root_ring() == 0
    monkeypatch.delenv("TB_ROOT_RING")
    assert envcheck.root_ring() == 4096


def test_tb_read_policy_validated(monkeypatch):
    monkeypatch.setenv("TB_READ_POLICY", "maybe")
    with pytest.raises(envcheck.EnvVarError, match="TB_READ_POLICY"):
        envcheck.read_policy()
    for value in ("auto", "primary", "follower"):
        monkeypatch.setenv("TB_READ_POLICY", value)
        assert envcheck.read_policy() == value
    monkeypatch.delenv("TB_READ_POLICY")
    assert envcheck.read_policy() == "auto"


def test_tb_read_staleness_ops_validated(monkeypatch):
    monkeypatch.setenv("TB_READ_STALENESS_OPS", "fresh")
    with pytest.raises(envcheck.EnvVarError, match="TB_READ_STALENESS_OPS"):
        envcheck.read_staleness_ops()
    monkeypatch.setenv("TB_READ_STALENESS_OPS", "-1")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0"):
        envcheck.read_staleness_ops()
    monkeypatch.setenv("TB_READ_STALENESS_OPS", "0")  # fully caught up
    assert envcheck.read_staleness_ops() == 0
    monkeypatch.delenv("TB_READ_STALENESS_OPS")
    assert envcheck.read_staleness_ops() == 512


def test_tb_follower_attest_ms_validated(monkeypatch):
    monkeypatch.setenv("TB_FOLLOWER_ATTEST_MS", "0")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 1"):
        envcheck.follower_attest_ms()
    monkeypatch.setenv("TB_FOLLOWER_ATTEST_MS", "250")
    assert envcheck.follower_attest_ms() == 250
    monkeypatch.delenv("TB_FOLLOWER_ATTEST_MS")
    assert envcheck.follower_attest_ms() == 100


def test_tb_follower_root_ring_named_constraint(monkeypatch):
    # Named constraint: < 16 discards the roots attestation needs
    # under write load.
    monkeypatch.setenv("TB_FOLLOWER_ROOT_RING", "8")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 16"):
        envcheck.follower_ring()
    monkeypatch.setenv("TB_FOLLOWER_ROOT_RING", "64")
    assert envcheck.follower_ring() == 64
    monkeypatch.delenv("TB_FOLLOWER_ROOT_RING")
    assert envcheck.follower_ring() == 4096


def test_tb_read_fallback_ms_validated(monkeypatch):
    monkeypatch.setenv("TB_READ_FALLBACK_MS", "1")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 10"):
        envcheck.read_fallback_ms()
    monkeypatch.setenv("TB_READ_FALLBACK_MS", "500")
    assert envcheck.read_fallback_ms() == 500
    monkeypatch.delenv("TB_READ_FALLBACK_MS")
    assert envcheck.read_fallback_ms() == 250


def test_tb_tenant_rate_bytes_validated(monkeypatch):
    monkeypatch.setenv("TB_TENANT_RATE_BYTES", "fast")
    with pytest.raises(envcheck.EnvVarError, match="TB_TENANT_RATE_BYTES"):
        envcheck.tenant_rate_bytes()
    monkeypatch.setenv("TB_TENANT_RATE_BYTES", "-5")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0"):
        envcheck.tenant_rate_bytes()
    monkeypatch.setenv("TB_TENANT_RATE_BYTES", "65536")
    assert envcheck.tenant_rate_bytes() == 65536.0
    monkeypatch.delenv("TB_TENANT_RATE_BYTES")
    assert envcheck.tenant_rate_bytes() == 0.0  # default off


def test_tb_follower_attest_max_ms_validated(monkeypatch):
    monkeypatch.setenv("TB_FOLLOWER_ATTEST_MAX_MS", "0")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 1"):
        envcheck.follower_attest_max_ms()
    monkeypatch.setenv("TB_FOLLOWER_ATTEST_MAX_MS", "5000")
    assert envcheck.follower_attest_max_ms() == 5000
    monkeypatch.delenv("TB_FOLLOWER_ATTEST_MAX_MS")
    assert envcheck.follower_attest_max_ms() == 2000


def test_tb_hot_capacity_validated(monkeypatch):
    monkeypatch.setenv("TB_HOT_CAPACITY", "plenty")
    with pytest.raises(envcheck.EnvVarError, match="TB_HOT_CAPACITY"):
        envcheck.hot_capacity()
    monkeypatch.setenv("TB_HOT_CAPACITY", "-1")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0"):
        envcheck.hot_capacity()
    monkeypatch.setenv("TB_HOT_CAPACITY", str((1 << 31) + 1))
    with pytest.raises(envcheck.EnvVarError, match="must be <="):
        envcheck.hot_capacity()
    monkeypatch.setenv("TB_HOT_CAPACITY", "64")
    assert envcheck.hot_capacity() == 64
    monkeypatch.delenv("TB_HOT_CAPACITY")
    assert envcheck.hot_capacity() == 0  # default: all-resident


def test_tb_hot_capacity_gates_tiering(monkeypatch):
    """The knob is read at CONSTRUCTION through hot_tier.from_env —
    0/unset and budget >= capacity leave the table all-resident
    (today's behavior bit-for-bit); a small budget builds the tier."""
    from tigerbeetle_tpu.state_machine import hot_tier

    monkeypatch.delenv("TB_HOT_CAPACITY", raising=False)
    assert hot_tier.from_env(256) is None
    monkeypatch.setenv("TB_HOT_CAPACITY", "256")
    assert hot_tier.from_env(256) is None
    monkeypatch.setenv("TB_HOT_CAPACITY", "16")
    tier = hot_tier.from_env(256)
    assert tier is not None and tier.hot_rows == 16


def test_tb_native_pipeline_validated(monkeypatch):
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "fast")
    with pytest.raises(envcheck.EnvVarError, match="TB_NATIVE_PIPELINE"):
        envcheck.native_pipeline()
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "2")
    with pytest.raises(envcheck.EnvVarError, match="must be <= 1"):
        envcheck.native_pipeline()
    monkeypatch.setenv("TB_NATIVE_PIPELINE", "0")
    assert envcheck.native_pipeline() == 0
    monkeypatch.delenv("TB_NATIVE_PIPELINE")
    assert envcheck.native_pipeline() == 1  # default on


def test_tb_native_drain_validated(monkeypatch):
    monkeypatch.setenv("TB_NATIVE_DRAIN", "batch")
    with pytest.raises(envcheck.EnvVarError, match="TB_NATIVE_DRAIN"):
        envcheck.native_drain()
    monkeypatch.setenv("TB_NATIVE_DRAIN", "2")
    with pytest.raises(envcheck.EnvVarError, match="must be <= 1"):
        envcheck.native_drain()
    monkeypatch.setenv("TB_NATIVE_DRAIN", "-1")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0"):
        envcheck.native_drain()
    monkeypatch.setenv("TB_NATIVE_DRAIN", "0")
    assert envcheck.native_drain() == 0
    monkeypatch.delenv("TB_NATIVE_DRAIN")
    assert envcheck.native_drain() == 1  # default on


def test_tb_hash_reuse_validated(monkeypatch):
    monkeypatch.setenv("TB_HASH_REUSE", "yes")
    with pytest.raises(envcheck.EnvVarError, match="TB_HASH_REUSE"):
        envcheck.hash_reuse()
    monkeypatch.setenv("TB_HASH_REUSE", "2")
    with pytest.raises(envcheck.EnvVarError, match="must be <= 1"):
        envcheck.hash_reuse()
    monkeypatch.setenv("TB_HASH_REUSE", "-1")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0"):
        envcheck.hash_reuse()
    monkeypatch.setenv("TB_HASH_REUSE", "0")
    assert envcheck.hash_reuse() == 0
    monkeypatch.delenv("TB_HASH_REUSE")
    assert envcheck.hash_reuse() == 1  # default on


def test_tb_hash_threads_validated(monkeypatch):
    monkeypatch.setenv("TB_HASH_THREADS", "many")
    with pytest.raises(envcheck.EnvVarError, match="TB_HASH_THREADS"):
        envcheck.hash_threads()
    monkeypatch.setenv("TB_HASH_THREADS", "-1")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0"):
        envcheck.hash_threads()
    # The named constraint: lanes are capped at 16 — more than any
    # target box's cores only adds submit-path contention.
    monkeypatch.setenv("TB_HASH_THREADS", "17")
    with pytest.raises(envcheck.EnvVarError, match="must be <= 16"):
        envcheck.hash_threads()
    monkeypatch.setenv("TB_HASH_THREADS", "16")
    assert envcheck.hash_threads() == 16  # boundary accepted
    # Explicit 0 = inline hashing (no lanes), same as the default.
    monkeypatch.setenv("TB_HASH_THREADS", "0")
    assert envcheck.hash_threads() == 0
    monkeypatch.delenv("TB_HASH_THREADS")
    assert envcheck.hash_threads() == 0


def test_tb_native_drain_explicit_on_fails_fast_on_stale_so(monkeypatch):
    """TB_NATIVE_DRAIN=1 set EXPLICITLY against a loaded-but-stale
    library is a hard RuntimeError naming the rebuild (`make -C
    native`) at replica construction — the r20 stale-.so forensics
    extended to the r22 batch symbols.  (The defaulted knob degrades
    to the per-item arm; tests/test_native_drain.py covers that.)"""
    from tigerbeetle_tpu.runtime import fastpath

    class _Stale:
        tb_pl_abi_version = None

    monkeypatch.setattr(fastpath, "_load", lambda: _Stale())
    monkeypatch.setattr(fastpath, "_pipeline_warned", True)
    monkeypatch.delenv("TB_NATIVE_PIPELINE", raising=False)
    monkeypatch.setenv("TB_NATIVE_DRAIN", "1")
    err = fastpath.drain_error()
    assert err is not None and "make -C native" in err
    from tigerbeetle_tpu.testing.cluster import Cluster

    with pytest.raises(RuntimeError, match="make -C native"):
        Cluster(3, seed=1)


def test_tb_cpu_affinity_validated(monkeypatch):
    monkeypatch.delenv("TB_CPU_AFFINITY", raising=False)
    assert envcheck.cpu_affinity() == "none"  # default: no pinning
    monkeypatch.setenv("TB_CPU_AFFINITY", "auto")
    assert envcheck.cpu_affinity() == "auto"
    monkeypatch.setenv("TB_CPU_AFFINITY", "0,1,2")
    assert envcheck.cpu_affinity() == "0,1,2"
    monkeypatch.setenv("TB_CPU_AFFINITY", "zero")
    with pytest.raises(envcheck.EnvVarError, match="TB_CPU_AFFINITY"):
        envcheck.cpu_affinity()
    monkeypatch.setenv("TB_CPU_AFFINITY", "0,-1")
    with pytest.raises(envcheck.EnvVarError, match="must be >= 0"):
        envcheck.cpu_affinity()
    monkeypatch.setenv("TB_CPU_AFFINITY", "")
    assert envcheck.cpu_affinity() == "none"  # empty counts as unset


def test_affinity_plan_and_apply(monkeypatch):
    import os as _os

    from tigerbeetle_tpu.runtime import affinity

    assert affinity.plan(0, "none") is None
    ncpu = _os.cpu_count() or 1
    assert affinity.plan(3, "auto") == (3 % ncpu,)
    assert affinity.plan(0, "4,5") == (4,)
    assert affinity.plan(1, "4,5") == (5,)
    assert affinity.plan(2, "4,5") == (4,)  # wraps mod the list
    # apply() pins to a real core and reports it; spec from the env.
    monkeypatch.setenv("TB_CPU_AFFINITY", "auto")
    before = _os.sched_getaffinity(0)
    try:
        pinned = affinity.apply(slot=0)
        assert pinned == (0,)
        assert _os.sched_getaffinity(0) == {0}
    finally:
        _os.sched_setaffinity(0, before)
    # A planned core that does not exist on this box degrades to
    # unpinned (None), never to a failed spawn.
    assert affinity.apply(slot=0, spec="4096") is None
    assert _os.sched_getaffinity(0) == before
