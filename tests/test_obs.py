"""Observability spine: tracer slot discipline, registry/histogram
exactness, snapshot monotonicity under chaos, trace merging, scrape
rendering, and the hot-path overhead contract."""

import json
import math
import random

import numpy as np
import pytest

import tigerbeetle_tpu.state_machine.device_engine as de
from tigerbeetle_tpu import obs
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing import harness as hz
from tigerbeetle_tpu.testing.chaos import ChaosLink
from tigerbeetle_tpu.testing.vopr import Workload
from tigerbeetle_tpu.utils.tracer import _NOOP_SPAN, Tracer

# ----------------------------------------------------------------------
# Tracer slot discipline + buffer accounting.


def test_tracer_double_start_asserts():
    t = Tracer("json")
    t.start("commit", 0)
    with pytest.raises(AssertionError, match=r"commit\[0\] already open"):
        t.start("commit", 0)
    # Same event on a DIFFERENT slot is the documented concurrency
    # escape hatch.
    t.start("commit", 1)
    t.stop("commit", 1)
    t.stop("commit", 0)


def test_tracer_unbalanced_end_asserts():
    t = Tracer("json")
    with pytest.raises(AssertionError, match=r"journal_write\[0\] not open"):
        t.stop("journal_write", 0)
    t.start("commit", 0)
    with pytest.raises(AssertionError, match=r"commit\[3\] not open"):
        t.stop("commit", 3)
    t.stop("commit", 0)


def test_tracer_dump_refuses_open_spans():
    t = Tracer("json")
    t.start("commit")
    with pytest.raises(AssertionError, match="open spans at dump"):
        t.dump()
    t.stop("commit")
    json.loads(t.dump())  # balanced: valid JSON


def test_tracer_buffer_drop_accounting():
    t = Tracer("json", buffer_max=16)
    for i in range(50):
        t.instant("tick", i=i)
    assert t.dropped == 50 - 16
    data = json.loads(t.dump())
    assert len(data["traceEvents"]) == 16
    assert data["otherData"]["dropped_events"] == 34
    # Oldest dropped first: the survivors are the newest 16.
    assert data["traceEvents"][0]["args"]["i"] == 34


# ----------------------------------------------------------------------
# Histogram: exact nearest-rank bucket selection vs a sorted oracle.


def _oracle(sorted_samples, q):
    rank = min(len(sorted_samples), max(1, math.ceil(q * len(sorted_samples))))
    return obs.Histogram.quantize(sorted_samples[rank - 1])


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_histogram_percentiles_match_sorted_oracle(seed):
    rng = random.Random(seed)
    reg = obs.Registry(enabled=True)
    h = reg.histogram("lat_us")
    samples = []
    for _ in range(4000):
        # Mixed scales: sub-µs to minutes, plus exact bucket edges.
        scale = rng.choice([1, 1, 10, 1000, 1e6, 6e7])
        v = rng.random() * scale
        if rng.random() < 0.05:
            v = float(rng.choice([0, 1, 15, 16, 17, 31, 32, 1 << 20]))
        samples.append(v)
        h.observe(v)
    ss = sorted(samples)
    for q in (0.01, 0.25, 0.5, 0.9, 0.99, 0.999, 1.0):
        assert h.percentile(q) == _oracle(ss, q), q
    assert h.count == len(samples)
    assert h.max == max(samples)
    assert abs(h.total - sum(samples)) < 1e-6 * max(1.0, sum(samples))


def test_histogram_bucket_arithmetic_is_consistent():
    # Every representable int maps into a bucket whose [lower, upper)
    # range contains it, and bucket indices are monotone in value.
    prev_idx = -1
    for n in list(range(0, 4096)) + [1 << k for k in range(12, 31)]:
        idx = obs.Histogram.bucket_of(n)
        assert idx >= prev_idx
        prev_idx = max(prev_idx, idx)
        assert n < obs.Histogram.upper_of(idx)


def test_histogram_empty_and_single():
    h = obs.Registry(enabled=True).histogram("x_us")
    assert h.percentile(0.99) == 0.0
    h.observe(42)
    assert h.percentile(0.5) == obs.Histogram.quantize(42)


@pytest.mark.parametrize("seed", [5, 6])
def test_histogram_unit_scale_resolves_sub_unit_floor(seed):
    """unit_scale=16 (r22: vsr.prepare_us / prepare_ok_us): sub-µs
    samples land in 1/16-µs buckets instead of collapsing into bucket
    0, percentiles descale back to raw units and still match the
    sorted oracle quantized at the scaled resolution, and count/sum/
    max stay in raw units."""
    rng = random.Random(seed)
    reg = obs.Registry(enabled=True)
    h = reg.histogram("fine_us", unit_scale=16)
    coarse = reg.histogram("coarse_us")
    samples = [rng.random() * rng.choice([0.2, 1, 4, 50]) for _ in range(3000)]
    for v in samples:
        h.observe(v)
        coarse.observe(v)
    ss = sorted(samples)
    for q in (0.25, 0.5, 0.9, 0.99):
        rank = min(len(ss), max(1, math.ceil(q * len(ss))))
        oracle = obs.Histogram.quantize(ss[rank - 1] * 16) / 16
        assert h.percentile(q) == oracle, q
    # The widened floor actually resolves the sub-µs mass the unscaled
    # histogram collapses: its p50 sits below 1 µs (impossible for
    # unit_scale=1, whose smallest nonzero representative is 1).
    assert h.percentile(0.5) < 1.0 <= coarse.percentile(0.5)
    assert h.count == len(samples)
    assert h.max == max(samples)
    assert abs(h.total - sum(samples)) < 1e-6 * max(1.0, sum(samples))


def test_histogram_unit_scale_must_agree_across_registrations():
    reg = obs.Registry(enabled=True)
    reg.histogram("h_us", unit_scale=16)
    reg.histogram("h_us", unit_scale=16)  # idempotent re-registration
    with pytest.raises(AssertionError, match="unit_scale"):
        reg.histogram("h_us")


# ----------------------------------------------------------------------
# Registry: composition, compat properties, version-driven dedup.


def test_registry_scope_and_attach_compose_one_snapshot():
    parent = obs.Registry(enabled=True)
    child = obs.Registry(enabled=True)
    parent.attach("vsr", child)
    child.counter("prepares").inc(3)
    parent.scope("sm").counter("events").inc(7)
    parent.gauge_fn("queue", lambda: 11)
    snap = parent.snapshot()
    assert snap["vsr.prepares"] == 3
    assert snap["sm.events"] == 7
    assert snap["queue"] == 11
    # Child mutations bump the composed version.
    v0 = parent.version()
    child.counter("prepares").inc()
    assert parent.version() == v0 + 1


def test_registry_rejects_kind_confusion():
    reg = obs.Registry(enabled=True)
    reg.counter("x")
    with pytest.raises(AssertionError):
        reg.gauge("x")


def test_stat_property_compat_reads_and_resets():
    sm = TpuStateMachine(account_capacity=1 << 10, transfer_capacity=1 << 10)
    assert sm.stat_device_events == 0
    sm.stat_device_events += 5          # property routes to the handle
    assert sm.metrics.snapshot()["device_events"] == 5
    sm.stat_device_events = 0           # bench-style reset
    assert sm.stat_device_events == 0
    # Version moved for every write: idle-dedup can't miss it.
    assert sm.metrics.version() >= 2


def test_snapshot_version_changes_with_any_counter():
    reg = obs.Registry(enabled=True)
    a = reg.counter("a")
    s0 = reg.snapshot()
    s1 = reg.snapshot()
    assert s0 == s1  # idle: identical snapshot, same version
    a.inc()
    s2 = reg.snapshot()
    assert s2["version"] > s1["version"]
    # A counter added AFTER the comparison baseline still shows up —
    # the failure mode of the old hand-picked tuple.
    reg.counter("later").inc()
    s3 = reg.snapshot()
    assert s3["version"] > s2["version"] and "later" in s3


# ----------------------------------------------------------------------
# Snapshot monotonicity across a chaos smoke run.


@pytest.fixture
def _fast_lifecycle(monkeypatch):
    monkeypatch.setattr(de, "_WINDOW", 4)
    monkeypatch.setattr(de, "_BACKOFF_MS", 0.0)
    monkeypatch.setattr(de, "_PROBE_EVERY", 2)


def test_registry_snapshot_monotonic_under_chaos(_fast_lifecycle):
    """Counters never decrease and the version strictly increases
    whenever values change, across a seeded chaos workload that
    demotes/re-promotes the device engine mid-stream."""
    link = ChaosLink(seed=31, p_transient=0.03, p_fatal=0.01, down_for=4)
    sm = TpuStateMachine(
        engine="device", account_capacity=1 << 12, device_link=link
    )
    h = hz.SingleNodeHarness(sm)
    wl = Workload(77)
    prev = sm.metrics.snapshot()
    sent = 0
    while sent < 300:
        operation, body, _must = wl.next_request()
        sent += 1 if not body else len(body) // 128
        h.submit(operation, body)
        snap = sm.metrics.snapshot()
        for key, value in snap.items():
            if ".p" in key:  # percentiles may move both ways
                continue
            if key in prev:
                assert value >= prev[key] - 1e-9, (key, prev[key], value)
        if snap != prev:
            assert snap["version"] > prev["version"]
        prev = snap
    # The run exercised the lifecycle counters it claims to cover.
    assert prev["dev.link.errors"] >= 1


# ----------------------------------------------------------------------
# Overhead contract: backend "none" / TB_METRICS=0 cost one check.


def test_disabled_tracer_span_is_shared_noop():
    t = Tracer("none")
    assert not t.enabled
    # Identity: no per-site allocation on the disabled path.
    assert t.span("commit", op=7) is _NOOP_SPAN
    assert t.span("journal_write") is _NOOP_SPAN
    t.count("queue", 3)   # all no-ops
    t.instant("marker")
    assert len(json.loads(t.dump())["traceEvents"]) == 0


def test_disabled_histogram_is_shared_noop():
    reg = obs.Registry(enabled=False)
    h1 = reg.histogram("a_us")
    h2 = reg.histogram("b_us")
    assert h1 is h2  # one shared no-op instance
    timer = h1.time()
    with timer:
        pass
    assert h1.count == 0


def test_traced_site_overhead_is_one_attribute_check():
    """A traced hot-path site on the disabled backend must cost on the
    order of a method call — generously bounded at 5 µs/site so a
    noisy CI box cannot flake this."""
    import time

    t = Tracer("none")
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        with t.span("commit"):
            pass
    per_site = (time.perf_counter() - t0) / n
    assert per_site < 5e-6, f"{per_site * 1e9:.0f} ns/site"


@pytest.mark.slow
def test_metrics_overhead_simple_kernel_within_2pct(monkeypatch):
    """`simple` kernel bench throughput with metrics on vs off stays
    within 2% (median of 5 interleaved runs each)."""
    import time

    from tigerbeetle_tpu.types import Operation

    def run_stream(metrics_on: bool) -> float:
        monkeypatch.setenv("TB_METRICS", "1" if metrics_on else "0")
        sm = TpuStateMachine(
            account_capacity=1 << 12, transfer_capacity=1 << 16
        )
        h = hz.SingleNodeHarness(sm)
        h.submit(
            Operation.create_accounts,
            hz.pack([hz.account(i) for i in range(1, 65)]),
        )
        rng = np.random.default_rng(5)
        bodies = []
        tid = 1000
        for _ in range(6):
            rows = [
                dict(
                    id=tid + j,
                    debit_account_id=int(rng.integers(1, 65)),
                    credit_account_id=int(rng.integers(1, 65)),
                    amount=1,
                )
                for j in range(2048)
            ]
            tid += 2048
            bodies.append(hz.pack([hz.transfer(**r) for r in rows]))
        # Untimed warmup (JIT compiles), then the timed replay.
        h.submit(Operation.create_transfers, bodies[0])
        t0 = time.perf_counter()
        for body in bodies[1:]:
            h.submit(Operation.create_transfers, body)
        sm.sync()
        return (len(bodies) - 1) * 2048 / (time.perf_counter() - t0)

    on, off = [], []
    run_stream(True)  # process-level warmup
    for _ in range(5):
        on.append(run_stream(True))
        off.append(run_stream(False))
    ratio = float(np.median(on)) / float(np.median(off))
    assert 0.98 <= ratio, f"metrics-on throughput ratio {ratio:.4f}"


# ----------------------------------------------------------------------
# Trace merging + scrape rendering.


def test_merge_traces_builds_one_perfetto_timeline(tmp_path):
    from tigerbeetle_tpu.testing.cluster import merge_traces

    paths = []
    for i in range(2):
        t = Tracer("json", process_id=0)  # deliberately colliding pids
        with t.span("commit", op=i):
            t.instant("prepare_ok", op=i)
        p = tmp_path / f"r{i}.json"
        t.write(str(p))
        paths.append(str(p))
    merged = merge_traces(paths, str(tmp_path / "merged.json"))
    data = json.load(open(tmp_path / "merged.json"))
    assert data == merged
    pids = {e["pid"] for e in merged["traceEvents"]}
    assert pids == {0, 1}  # re-keyed per input file
    meta = [e for e in merged["traceEvents"] if e.get("ph") == "M"]
    assert [m["args"]["name"] for m in meta] == ["replica0", "replica1"]


def test_trace_demo_produces_cross_replica_drain(tmp_path):
    from tigerbeetle_tpu.testing.cluster import trace_demo

    out = str(tmp_path / "merged.json")
    info = trace_demo(out, n_replicas=2, batches=3, transfers_per_batch=4)
    assert info["trace_path"] == out and info["ops_committed"] > 0
    data = json.load(open(out))
    names = {e["name"] for e in data["traceEvents"]}
    # The full replicated-drain timeline, across both process tracks.
    for required in (
        "prepare", "journal_write", "gc_covering_sync", "prepare_ok",
        "commit", "reply", "state_machine_commit",
    ):
        assert required in names, required
    assert {e["pid"] for e in data["traceEvents"]} == {0, 1}


def test_merge_traces_skips_bad_files_and_warns(tmp_path):
    """Empty, truncated, missing, and non-object inputs are skipped
    with a warning + otherData.skipped entry; the survivors still
    merge (a replica killed mid-dump must not void the postmortem)."""
    from tigerbeetle_tpu.testing.cluster import merge_traces

    good = tmp_path / "good.json"
    t = Tracer("json")
    t.instant("commit", op=1)
    t.write(str(good))
    empty = tmp_path / "empty.json"
    empty.write_text("")
    truncated = tmp_path / "truncated.json"
    truncated.write_text('{"traceEvents": [{"name": "comm')
    notdict = tmp_path / "notdict.json"
    notdict.write_text("[1, 2, 3]")
    missing = tmp_path / "missing.json"

    with pytest.warns(UserWarning, match="merge_traces: skipping"):
        merged = merge_traces(
            [str(empty), str(good), str(truncated), str(missing),
             str(notdict)],
            str(tmp_path / "merged.json"),
        )
    names = [e["name"] for e in merged["traceEvents"]]
    assert "commit" in names  # the good file survived
    skipped = merged["otherData"]["skipped"]
    assert {s["label"] for s in skipped} == {
        "replica0", "replica2", "replica3", "replica4"
    }
    # The written file parses and matches.
    assert json.load(open(tmp_path / "merged.json")) == merged


def test_merge_traces_many_replicas(tmp_path):
    """>2-replica merges keep every input on its own re-keyed track."""
    from tigerbeetle_tpu.testing.cluster import merge_traces

    paths = []
    for i in range(5):
        t = Tracer("json", process_id=0)
        t.instant("prepare", op=i)
        p = tmp_path / f"r{i}.json"
        t.write(str(p))
        paths.append(str(p))
    merged = merge_traces(paths)
    pids = {e["pid"] for e in merged["traceEvents"]}
    assert pids == {0, 1, 2, 3, 4}
    meta = [e for e in merged["traceEvents"] if e.get("ph") == "M"]
    assert len(meta) == 5
    assert "skipped" not in merged["otherData"]


def test_stats_scrape_monotonic_under_concurrent_load(tmp_path):
    """Scrape while drains are mid-flight: counters in successive
    snapshots never decrease, the version strictly increases whenever
    values change, and the exemplar ring honors its bound —
    concurrency must not tear the snapshot."""
    import socket
    import threading

    from tigerbeetle_tpu import constants as cfg
    from tigerbeetle_tpu.client import Client
    from tigerbeetle_tpu.obs.scrape import scrape_stats
    from tigerbeetle_tpu.runtime.native import native_available
    from tigerbeetle_tpu.runtime.server import (
        ReplicaServer,
        format_data_file,
    )
    from tigerbeetle_tpu.state_machine import CpuStateMachine

    if not native_available():
        pytest.skip("native runtime not built")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    address = f"127.0.0.1:{port}"
    path = str(tmp_path / "r0.tb")
    format_data_file(path, cluster=17, config=cfg.TEST_MIN)
    server = ReplicaServer(
        path, cluster=17, addresses=[address], replica_index=0,
        state_machine_factory=lambda: CpuStateMachine(cfg.TEST_MIN),
        config=cfg.TEST_MIN,
    )
    stop = threading.Event()
    loop = threading.Thread(
        target=lambda: [server.poll_once(1) for _ in iter(
            lambda: not stop.is_set(), False
        )],
        daemon=True,
    )
    loop.start()
    client = None
    try:
        client = Client(address, 17, client_id=91, timeout_ms=30_000)
        assert client.create_accounts(
            [{"id": 1, "ledger": 1, "code": 1},
             {"id": 2, "ledger": 1, "code": 1}]
        ) == []
        errors = []

        def drive():
            try:
                for k in range(60):
                    client.create_transfers([
                        {"id": 1000 + k, "debit_account_id": 1,
                         "credit_account_id": 2, "amount": 1,
                         "ledger": 1, "code": 1}
                    ])
            except Exception as exc:  # noqa: BLE001
                errors.append(repr(exc))

        driver = threading.Thread(target=drive, daemon=True)
        driver.start()
        ring = server.replica.anatomy.exemplar_ring
        prev = None
        scrapes = 0
        while driver.is_alive() or scrapes < 3:
            snap = scrape_stats(address, 17, timeout_ms=10_000)
            scrapes += 1
            assert len(snap["anatomy.exemplars"]) <= ring
            if prev is not None:
                for key, value in snap.items():
                    if ".p" in key or key in (
                        "server.queue_depth", "vsr.anatomy.open",
                        "anatomy.exemplars",
                    ):
                        continue  # gauges/percentiles move both ways
                    if key in prev and isinstance(value, (int, float)):
                        assert value >= prev[key] - 1e-9, (
                            key, prev[key], value
                        )
                if {k: v for k, v in snap.items()
                        if k != "anatomy.exemplars"} != {
                            k: v for k, v in prev.items()
                            if k != "anatomy.exemplars"}:
                    assert snap["version"] >= prev["version"]
            prev = snap
            if not driver.is_alive() and scrapes >= 3:
                break
        driver.join(timeout=30)
        assert errors == [], errors
        assert prev["vsr.commits"] >= 60
    finally:
        stop.set()
        loop.join(timeout=5)
        if client is not None:
            client.close()
        server.close()


def test_stats_reply_roundtrips_snapshot():
    from tigerbeetle_tpu.obs.scrape import SCRAPE_REQUEST, stats_reply
    from tigerbeetle_tpu.vsr import wire
    from tigerbeetle_tpu.vsr.wire import Command, VsrOperation

    request = wire.make_header(
        command=Command.request, operation=VsrOperation.stats,
        cluster=9, request=SCRAPE_REQUEST,
    )
    wire.finalize_header(request, b"")
    snap = {"vsr.prepares_written": 12, "storage.fsyncs": 4, "version": 99}
    reply, body = stats_reply(snap, request)
    assert wire.verify_header(reply, body)
    assert int(reply["command"]) == int(Command.reply)
    assert int(reply["operation"]) == int(VsrOperation.stats)
    assert int(reply["request"]) == SCRAPE_REQUEST
    assert json.loads(body.decode()) == snap


def test_server_stats_op_never_enters_consensus():
    """A stats request reaching a bare VsrReplica (no server layer in
    front) is dropped, not prepared — op 6 would otherwise hit the
    asserting state-machine dispatch at commit."""
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.vsr import wire
    from tigerbeetle_tpu.vsr.wire import Command, VsrOperation

    c = Cluster(replica_count=1)
    r = c.replicas[0]
    c.run_until(lambda: r.status == "normal")
    ops_before = r.op
    h = wire.make_header(
        command=Command.request, operation=VsrOperation.stats,
        cluster=c.cluster_id, request=1,
    )
    wire.finalize_header(h, b"")
    r.on_message(h, b"")
    assert r.op == ops_before


def test_tb_metrics_env_plumbs_to_state_machine(monkeypatch):
    monkeypatch.setenv("TB_METRICS", "0")
    sm = TpuStateMachine(account_capacity=1 << 10, transfer_capacity=1 << 10)
    assert not sm.metrics.enabled
    monkeypatch.setenv("TB_METRICS", "1")
    sm = TpuStateMachine(account_capacity=1 << 10, transfer_capacity=1 << 10)
    assert sm.metrics.enabled


def _drive_speculative_batches(monkeypatch):
    """One fresh-id stream forced through the speculative dispatcher;
    returns the machine after every future resolved."""
    from tigerbeetle_tpu.types import Operation

    monkeypatch.setattr(de, "_WINDOW", 2)
    monkeypatch.setenv("TB_WAVES_SPECULATE", "force")
    sm = TpuStateMachine(engine="device", account_capacity=(1 << 10) + 1)
    h = hz.SingleNodeHarness(sm)
    h.submit(
        Operation.create_accounts,
        hz.pack([hz.account(i) for i in range(1, 9)]),
    )
    futs = []
    for k in range(4):
        rows = [
            hz.transfer(100 + 4 * k + j, debit_account_id=1 + j,
                        credit_account_id=5 + j, amount=1 + j)
            for j in range(4)
        ]
        futs.append(h.submit_async(Operation.create_transfers, hz.pack(rows)))
    for f in futs:
        f.result()
    sm.sync()
    return sm


def test_spec_counters_in_registry_and_metrics_off_noop(monkeypatch):
    """dev_wave.spec.* rides the machine registry (the stats scrape and
    flight postmortem read the same snapshot): counters tick under
    TB_METRICS=1 with the validation histogram populated; under
    TB_METRICS=0 the histogram is the shared no-op (no clock-derived
    samples in the snapshot) while the routing counters stay live —
    bench accounting depends on them."""
    monkeypatch.setenv("TB_METRICS", "1")
    sm = _drive_speculative_batches(monkeypatch)
    snap = sm.metrics.snapshot()
    assert snap["dev_wave.spec.attempts"] == 4
    assert snap["dev_wave.spec.hits"] == 4
    assert snap["dev_wave.spec.plan_skipped"] == 4
    assert snap["dev_wave.spec.steps"] == 4
    assert snap["dev_wave.spec.validation_us.count"] == 4

    monkeypatch.setenv("TB_METRICS", "0")
    sm0 = _drive_speculative_batches(monkeypatch)
    assert not sm0.metrics.enabled
    hist = sm0._dev.spec_stats["validation_us"]
    assert hist is obs.Registry(enabled=False).histogram("x_us"), (
        "TB_METRICS=0 must hand the spec path the shared no-op histogram"
    )
    snap0 = sm0.metrics.snapshot()
    assert snap0["dev_wave.spec.attempts"] == 4  # counters stay live
    assert snap0["dev_wave.spec.hits"] == 4
    assert "dev_wave.spec.validation_us.count" not in snap0


def test_flight_dump_embeds_stats_snapshot(tmp_path):
    """A flight recorder wired with a stats provider embeds the full
    registry snapshot in every dump's otherData — the demotion
    postmortem carries the dev_wave.spec.* / link counters that
    explain it — and a provider failure degrades to a recorded error,
    never a voided postmortem (dumps run inside signal handlers)."""
    from tigerbeetle_tpu.obs.flight import FlightRecorder

    reg = obs.Registry(enabled=True)
    reg.counter("dev_wave.spec.attempts").inc(3)
    fr = FlightRecorder(capacity=8, stats_fn=reg.snapshot)
    fr.note("device_demoted", error="boom")
    dump = fr.dump(reason="test")
    assert dump["otherData"]["stats"]["dev_wave.spec.attempts"] == 3
    path = tmp_path / "flight.json"
    fr.write(str(path))
    assert json.load(open(path))["otherData"]["stats"][
        "dev_wave.spec.attempts"
    ] == 3

    def bad_stats():
        raise RuntimeError("registry gone")

    fr2 = FlightRecorder(capacity=8, stats_fn=bad_stats)
    fr2.note("assertion_failure")
    dump2 = fr2.dump()
    assert "stats" not in dump2["otherData"]
    assert "registry gone" in dump2["otherData"]["stats_error"]
    assert len(dump2["traceEvents"]) == 1  # the ring survived


def test_tb_trace_env_selects_backend(monkeypatch):
    monkeypatch.setenv("TB_TRACE", "json")
    assert Tracer.from_env(3).enabled
    monkeypatch.delenv("TB_TRACE")
    assert not Tracer.from_env().enabled
