"""Standby replicas: hot spares that replicate, commit, and repair
like backups but never ack, never vote, and never lead (reference:
cluster topology standbys, src/simulator.zig:117-122)."""

import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.harness import account, pack, transfer


def make_cluster(**kw):
    c = Cluster(replica_count=3, standby_count=1, **kw)
    client = c.client(1000)
    client.register()
    c.run_until(lambda: client.registered)
    return c, client


def load(c, client, n=8, base=100):
    c.run_request(client, types.Operation.create_accounts,
                  pack([account(1), account(2)]))
    for k in range(n):
        c.run_request(client, types.Operation.create_transfers,
                      pack([transfer(base + k, debit_account_id=1,
                                     credit_account_id=2, amount=1)]))


def test_standby_replicates_and_converges():
    c, client = make_cluster()
    load(c, client)
    c.settle(max_steps=10000)
    c.check_linearized()
    c.check_convergence()
    standby = c.replicas[3]
    assert standby.standby
    assert standby.commit_min == c.replicas[0].commit_min
    assert standby.sm.transfer_timestamp(107) is not None


def test_standby_never_acks_or_leads():
    c, client = make_cluster()
    load(c, client, n=4)
    c.settle(max_steps=10000)
    standby = c.replicas[3]
    assert not standby.is_primary
    # Two of three actives die: no quorum can form even though the
    # standby is alive and current — it must not substitute for a
    # voting replica.
    c.crash_replica(0)
    c.crash_replica(1)
    live_active = c.replicas[2]
    commit_before = live_active.commit_min
    for _ in range(3000):
        c.step()
    assert live_active.commit_min == commit_before, (
        "cluster progressed without a voting quorum"
    )
    # The standby never collected votes or proposed a view.
    assert standby.status == "normal"
    assert not standby._dvc
    assert not standby.is_primary


def test_standby_survives_view_change_and_repairs():
    c, client = make_cluster()
    load(c, client, n=5)
    old_primary = c.replicas[0].primary_index()
    c.network.partition(old_primary)
    reply = c.run_request(
        client, types.Operation.create_transfers,
        pack([transfer(300, debit_account_id=1, credit_account_id=2,
                       amount=7)]),
        max_steps=6000,
    )
    assert reply == b""
    c.network.heal()
    c.settle(max_steps=10000)
    c.check_linearized()
    c.check_convergence()
    standby = c.replicas[3]
    assert standby.view == c.replicas[1].view
    assert standby.sm.transfer_timestamp(300) is not None


def test_standby_restart_catches_up():
    c, client = make_cluster()
    load(c, client, n=6)
    c.settle(max_steps=10000)
    c.crash_replica(3)
    load(c, client, n=6, base=500)
    c.restart_replica(3)
    c.settle(max_steps=12000)
    c.check_convergence()
    assert c.replicas[3].sm.transfer_timestamp(505) is not None


def test_vopr_with_standby():
    """Whole-cluster fuzz with a standby in the topology: crash/
    partition nemesis may hit the standby too; all invariants hold and
    the standby converges with the actives."""
    from tigerbeetle_tpu.testing.vopr import Vopr

    v = Vopr(4242, requests=120, standby_count=1)
    v.run()
    standby = v.cluster.replicas[3]
    assert standby.standby
    assert standby.commit_min == v.cluster.replicas[0].commit_min


def test_upgrade_waits_for_standby():
    """The primary must not commit an upgrade while the standby still
    runs the old binary — the hot spare would silently stop committing
    release-2 prepares and go stale."""
    c, client = make_cluster()
    load(c, client, n=3)
    # Roll only the actives: no upgrade may be proposed.
    for i in range(3):
        c.restart_replica(i, releases_available=(1, 2))
    for _ in range(600):
        c.step()
    assert all(r.upgrade_target is None for r in c.replicas)
    # Roll the standby too: now the upgrade commits cluster-wide.
    c.restart_replica(3, releases_available=(1, 2))
    c.run_until(
        lambda: all(
            r.upgrade_target == 2 for i, r in enumerate(c.replicas)
            if i < 3
        ),
        max_steps=8000,
    )
    for i in range(4):
        c.restart_replica(i, release=2, releases_available=(1, 2))
    c.settle(max_steps=10000)
    assert all(r.release == 2 for r in c.replicas)
    c.check_convergence()


def test_upgrade_works_with_solo_active_and_standby():
    """A 1-active + 1-standby topology still upgrades: release
    advertisement rides clock pings, which a solo active must keep
    sending when standbys exist."""
    c = Cluster(replica_count=1, standby_count=1)
    client = c.client(1000)
    client.register()
    c.run_until(lambda: client.registered)
    c.run_request(client, types.Operation.create_accounts,
                  pack([account(1), account(2)]))
    for i in range(2):
        c.restart_replica(i, releases_available=(1, 2))
    c.run_until(
        lambda: c.replicas[0].upgrade_target == 2, max_steps=8000
    )
    for i in range(2):
        c.restart_replica(i, release=2, releases_available=(1, 2))
    c.settle(max_steps=8000)
    assert all(r.release == 2 for r in c.replicas)
