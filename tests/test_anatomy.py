"""Request anatomy: wire trace context, per-request stage timelines,
tail exemplars, and the flight recorder (obs/anatomy.py, obs/flight.py,
vsr/wire.py trace fields)."""

import json

import pytest

from tigerbeetle_tpu import obs, types
from tigerbeetle_tpu.obs.anatomy import (
    AnatomyRecorder,
    exemplar_trace_events,
)
from tigerbeetle_tpu.obs.flight import FlightRecorder
from tigerbeetle_tpu.utils.tracer import Tracer
from tigerbeetle_tpu.vsr import wire

# ----------------------------------------------------------------------
# Wire trace context.


def test_trace_context_header_roundtrip():
    h = wire.make_header(
        command=wire.Command.request, operation=130, cluster=7,
        client=99, request=3, trace_id=0xDEAD, trace_ts=123_456,
        trace_flags=wire.TRACE_SAMPLED,
    )
    wire.finalize_header(h, b"ab")
    assert wire.verify_header(h, b"ab")
    back = wire.header_from_bytes(h.tobytes())
    assert int(back["trace_id"]) == 0xDEAD
    assert int(back["trace_ts"]) == 123_456
    assert wire.trace_sampled(back) == 0xDEAD


def test_trace_context_copy_propagates():
    req = wire.make_header(
        command=wire.Command.request, trace_id=5, trace_ts=9,
        trace_flags=wire.TRACE_SAMPLED,
    )
    prep = wire.make_header(command=wire.Command.prepare, op=4)
    wire.copy_trace(prep, req)
    wire.finalize_header(prep, b"")
    assert wire.verify_header(prep, b"")
    assert wire.trace_sampled(prep) == 5
    assert int(prep["trace_ts"]) == 9


def test_unsampled_and_zero_id_are_untraced():
    h = wire.make_header(command=wire.Command.request, trace_id=7)
    assert wire.trace_sampled(h) == 0  # flag clear
    h2 = wire.make_header(
        command=wire.Command.request, trace_flags=wire.TRACE_SAMPLED
    )
    assert wire.trace_sampled(h2) == 0  # id zero


def test_untraced_header_is_bit_identical_to_legacy():
    # Zero trace fields leave the header bytes exactly as the
    # all-reserved layout produced them (wire compat).
    h = wire.make_header(command=wire.Command.prepare, op=1)
    raw = h.tobytes()
    assert raw[156:173] == bytes(17)


# ----------------------------------------------------------------------
# AnatomyRecorder.


class _Clock:
    def __init__(self):
        self.now = 1_000_000

    def __call__(self):
        return self.now


def _recorder(ring=8, **kw):
    clock = _Clock()
    reg = obs.Registry(enabled=True)
    rec = AnatomyRecorder(
        reg.scope("anatomy"), exemplar_ring=ring, clock=clock, **kw
    )
    return rec, clock, reg


def test_stage_timeline_and_e2e():
    rec, clock, reg = _recorder()
    clock.now = 1000
    rec.stage(42, "ingress", origin_ts=400)
    clock.now = 2000
    rec.stage(42, "prepare")
    clock.now = 3400
    rec.finish(42, "reply")
    assert len(rec.exemplars) == 1
    ex = rec.exemplars[0]
    assert [s[0] for s in ex["stages"]] == ["ingress", "prepare", "reply"]
    assert ex["e2e_us"] == pytest.approx((3400 - 400) / 1e3)
    snap = reg.snapshot()
    assert snap["anatomy.finished"] == 1
    assert snap["anatomy.e2e_us.count"] == 1


def test_exemplars_keep_only_top_buckets_after_warmup():
    rec, clock, _ = _recorder(ring=64)

    def run(tid, e2e_ns):
        rec.stage(tid, "a", origin_ts=clock.now)
        clock.now += e2e_ns
        rec.finish(tid)

    # Mixed population: mostly fast, a 10% slow tail (interleaved).
    tid = 1
    for i in range(100):
        run(tid, 10_000_000 if i % 10 == 9 else 100_000)
        tid += 1
    # Past warmup: a FAST request is not exemplar-worthy...
    kept_before = len(rec.exemplars)
    run(tid, 100_000)
    tid += 1
    assert len(rec.exemplars) == kept_before
    # ...a tail request is.
    run(tid, 20_000_000)
    assert len(rec.exemplars) == kept_before + 1
    assert rec.exemplars[-1]["e2e_us"] == pytest.approx(20_000)


def test_exemplar_ring_is_bounded():
    rec, clock, _ = _recorder(ring=4)
    for tid in range(1, 40):
        rec.stage(tid, "a", origin_ts=clock.now)
        clock.now += 1_000_000 * tid  # ever slower: all exemplar-worthy
        rec.finish(tid)
    assert len(rec.exemplars) == 4
    assert len(rec.exemplar_snapshot()) == 4


def test_open_records_bounded_with_eviction_counter():
    clock = _Clock()
    reg = obs.Registry(enabled=True)
    rec = AnatomyRecorder(
        reg.scope("anatomy"), exemplar_ring=4, open_max=8, clock=clock
    )
    for tid in range(1, 30):
        rec.stage(tid, "a")  # never finished
    assert len(rec._open) == 8
    assert reg.snapshot()["anatomy.open_evicted"] == 30 - 1 - 8
    # The oldest were evicted; finishing one of them is a no-op.
    rec.finish(1)
    assert reg.snapshot()["anatomy.finished"] == 0


def test_disabled_registry_disables_recorder():
    reg = obs.Registry(enabled=False)
    rec = AnatomyRecorder(reg.scope("anatomy"), exemplar_ring=4)
    assert not rec.enabled
    rec.stage(1, "a")
    rec.finish(1, "reply")
    assert not rec._open and not rec.exemplars


def test_stage_many_shares_one_timestamp():
    rec, clock, _ = _recorder()
    rec.stage(1, "journal_write")
    rec.stage(2, "journal_write")
    clock.now += 777
    rec.stage_many([1, 2], "gc_covering_sync")
    assert rec._open[1]["stages"][-1] == ["gc_covering_sync", clock.now]
    assert rec._open[2]["stages"][-1] == ["gc_covering_sync", clock.now]


def test_exemplar_trace_events_render_stage_spans():
    rec, clock, _ = _recorder()
    clock.now = 10_000
    rec.stage(9, "prepare", origin_ts=9_000)
    clock.now = 12_000
    rec.stage(9, "journal_write")
    clock.now = 15_000
    rec.finish(9, "reply")
    events = exemplar_trace_events(rec.exemplar_snapshot(), pid=3)
    names = [e["name"] for e in events]
    assert "prepare" in names and "journal_write" in names
    assert "reply" in names
    assert all(e["pid"] == 3 for e in events)
    jw = next(e for e in events if e["name"] == "journal_write")
    assert jw["dur"] == pytest.approx(2.0)  # 12_000 - 10_000 ns = 2 µs


# ----------------------------------------------------------------------
# Flight recorder.


def test_flight_ring_bounded_and_dump_parseable(tmp_path):
    fl = FlightRecorder(16, process_id=2)
    for i in range(50):
        fl.note("tick", i=i)
    assert fl.dropped == 34
    path = str(tmp_path / "flight.json")
    fl.write(path, reason="unit")
    data = json.load(open(path))
    assert data["otherData"]["flight_recorder"] is True
    assert data["otherData"]["reason"] == "unit"
    assert data["otherData"]["dropped_events"] == 34
    assert len(data["traceEvents"]) == 16
    assert all(e["pid"] == 2 for e in data["traceEvents"])
    # Oldest dropped first.
    assert data["traceEvents"][0]["args"]["i"] == 34


def test_flight_trigger_event_auto_dumps(tmp_path):
    path = str(tmp_path / "flight.json")
    fl = FlightRecorder(32, dump_path=path)
    fl.note("commit", op=1)
    assert not (tmp_path / "flight.json").exists()
    fl.note("device_demoted", error="FatalLinkError")
    data = json.load(open(path))
    assert data["otherData"]["reason"] == "device_demoted"
    names = [e["name"] for e in data["traceEvents"]]
    assert names == ["commit", "device_demoted"]


def test_tracer_instants_mirror_into_flight_even_when_disabled(tmp_path):
    t = Tracer("none")
    fl = FlightRecorder(8)
    t.flight = fl
    t.instant("device_demoted", error="x")
    t.instant("view_change", view=3)
    assert [ev[1] for ev in fl._ring] == ["device_demoted", "view_change"]
    # Backend "none" still emitted nothing to the trace buffer itself.
    assert len(json.loads(t.dump())["traceEvents"]) == 0


def test_flight_dump_merges_into_perfetto_timeline(tmp_path):
    from tigerbeetle_tpu.testing.cluster import merge_traces

    fl = FlightRecorder(8, process_id=0)
    fl.note("shed", client=1)
    p1 = str(tmp_path / "flight0.json")
    fl.write(p1)
    t = Tracer("json", process_id=0)
    with t.span("commit", op=1):
        pass
    p2 = str(tmp_path / "trace0.json")
    t.write(p2)
    merged = merge_traces([p1, p2], labels=["flight", "trace"])
    names = {e["name"] for e in merged["traceEvents"]}
    assert {"shed", "commit"} <= names
    assert {e["pid"] for e in merged["traceEvents"]} == {0, 1}


# ----------------------------------------------------------------------
# End-to-end propagation through a deterministic 2-replica cluster.


def test_cluster_propagates_trace_context_end_to_end():
    """SimClient stamps a wire trace context; with group commit live
    the PRIMARY's exemplar timeline spans queued/prepare ->
    journal_write -> gc_covering_sync -> prepare_ok -> commit ->
    reply, and the BACKUP holds its own partial record for the same
    request (journal_write -> commit)."""
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.testing.harness import account, pack, transfer
    from tigerbeetle_tpu.vsr.storage import MemoryStorage

    had = MemoryStorage.supports_deferred_sync
    MemoryStorage.supports_deferred_sync = True
    try:
        cluster = Cluster(replica_count=2, seed=11)
        client = cluster.client(1000)
        client.register()
        cluster.run_until(lambda: client.registered)
        assert cluster.run_request(
            client, types.Operation.create_accounts,
            pack([account(1), account(2)]),
        ) == b""
        assert cluster.run_request(
            client, types.Operation.create_transfers,
            pack([transfer(100, debit_account_id=1, credit_account_id=2,
                           amount=1)]),
        ) == b""
        cluster.settle()
        primary = cluster.replicas[0]
        backup = cluster.replicas[1]
        prim_ex = primary.anatomy.exemplar_snapshot()
        assert prim_ex, "primary retained no exemplars"
        stage_sets = [{s[0] for s in ex["stages"]} for ex in prim_ex]
        assert any(
            {"prepare", "journal_write", "gc_covering_sync",
             "prepare_ok", "commit", "reply"} <= stages
            for stages in stage_sets
        ), stage_sets
        # The backup recorded the same requests' replication hops.
        back_ex = backup.anatomy.exemplar_snapshot()
        assert any(
            {"journal_write", "commit"} <= {s[0] for s in ex["stages"]}
            for ex in back_ex
        ), back_ex
        # Stage timestamps are monotone within each record.
        for ex in prim_ex + back_ex:
            ts = [s[1] for s in ex["stages"]]
            assert ts == sorted(ts)
        # And the trace ids line up across replicas (wire-propagated,
        # not independently minted).
        prim_ids = {ex["trace_id"] for ex in prim_ex}
        back_ids = {ex["trace_id"] for ex in back_ex}
        assert prim_ids & back_ids
    finally:
        MemoryStorage.supports_deferred_sync = had


def test_shed_runs_below_the_dedupe_gate():
    """Admission control must never busy a RETRANSMISSION of a
    committed request (the stored reply wins), and a shed fresh
    request recovers once the queue has room."""
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.testing.harness import account, pack

    c = Cluster(replica_count=1)
    r = c.replicas[0]
    client = c.client(1000)
    client.register()
    c.run_until(lambda: client.registered)
    body = pack([account(1)])
    assert c.run_request(
        client, types.Operation.create_accounts, body
    ) == b""

    # Zero-bound the queue: anything that would queue is shed.
    r.admit_queue = 0
    sheds = []
    r.on_shed = lambda h, tenant=None: sheds.append(int(h["request"]))

    # Retransmit of the COMMITTED request: replayed from the stored
    # reply, never shed (the dedupe gate runs first).
    h = wire.make_header(
        command=wire.Command.request,
        operation=types.Operation.create_accounts,
        cluster=c.cluster_id, client=client.id,
        request=client.request_number,
    )
    wire.finalize_header(h, body)
    r.on_message(h, body)
    for _ in range(20):
        c.step()
    # Dedupe replayed the stored reply; the shed path never fired
    # (SimClient drops replies with nothing in flight, so the absence
    # of busy/shed IS the observable contract here).
    assert client.busy_replies == 0 and not sheds

    # A FRESH request while every prepare path is gated (anchor
    # repair pending) must queue — and with the zero bound, shed.
    r._anchor_pending = True
    client.request(types.Operation.create_accounts, pack([account(2)]))
    c.run_until(lambda: client.busy_replies > 0, 200)
    assert sheds and sheds[-1] == client.request_number
    # Lift the gate and the bound: the client's retransmission cadence
    # recovers the shed request — busy was typed, not fatal.
    r._anchor_pending = False
    r.admit_queue = None
    c.run_until(lambda: not client.busy())
    assert client.reply == b""


def test_vsr_drops_unknown_command_without_crashing():
    from tigerbeetle_tpu.testing.cluster import Cluster

    c = Cluster(replica_count=1)
    r = c.replicas[0]
    c.run_until(lambda: r.status == "normal")
    busy = wire.make_header(
        command=wire.Command.client_busy, cluster=c.cluster_id, client=5,
    )
    wire.finalize_header(busy, b"")
    r.on_message(busy, b"")  # must not raise
    # And a genuinely unknown byte is equally harmless.
    junk = wire.make_header(command=200, cluster=c.cluster_id)
    wire.finalize_header(junk, b"")
    r.on_message(junk, b"")
