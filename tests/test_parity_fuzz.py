"""Differential fuzzing: TpuStateMachine vs CpuStateMachine.

Replays identical randomized operation streams through both machines
and diffs every reply byte-for-byte plus final balances. The workload
is biased toward the hard cases (SURVEY.md §7): in-batch id
collisions, linked chains, two-phase races, balancing flags, limits,
timeouts — the reference's VOPR plays the same role
(reference: src/state_machine/workload.zig:1-19).
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing.harness import SingleNodeHarness, account, transfer, pack

TF = types.TransferFlags
AF = types.AccountFlags


def random_transfer(rng, ids, account_ids, t_index):
    kind = rng.random()
    flags = 0
    amount = int(rng.integers(0, 50))
    timeout = 0
    pending_id = 0
    if kind < 0.45:
        # Plain or pending transfer.
        if rng.random() < 0.4:
            flags |= TF.pending
            if rng.random() < 0.5:
                timeout = int(rng.integers(1, 4))
        if rng.random() < 0.25:
            flags |= TF.balancing_debit if rng.random() < 0.5 else TF.balancing_credit
    elif kind < 0.75:
        # Post or void something (often an existing/pending id).
        flags |= TF.post_pending_transfer if rng.random() < 0.6 else TF.void_pending_transfer
        pending_id = int(rng.choice(ids)) if len(ids) and rng.random() < 0.8 else int(rng.integers(0, 30))
    else:
        flags |= TF.pending if rng.random() < 0.3 else 0

    if rng.random() < 0.25:
        flags |= TF.linked

    # Reuse ids often to stress exists/in-batch-duplicate paths.
    new_id = int(rng.choice(ids)) if len(ids) and rng.random() < 0.35 else t_index + 100

    return transfer(
        new_id,
        debit_account_id=int(rng.choice(account_ids)) if rng.random() < 0.9 else int(rng.integers(0, 99)),
        credit_account_id=int(rng.choice(account_ids)) if rng.random() < 0.9 else int(rng.integers(0, 99)),
        amount=amount,
        pending_id=pending_id,
        user_data_128=int(rng.integers(0, 3)),
        user_data_64=int(rng.integers(0, 3)),
        user_data_32=int(rng.integers(0, 3)),
        timeout=timeout,
        ledger=int(rng.choice([1, 1, 1, 2])),
        code=int(rng.integers(0, 3)),
        flags=flags,
    ), new_id


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5, 6, 7, 8, 18, 22, 24])
def test_differential_random_stream(seed):
    rng = np.random.default_rng(seed)
    cpu = SingleNodeHarness(CpuStateMachine())
    tpu = SingleNodeHarness(TpuStateMachine())

    # Accounts: some with limits, some with history.
    account_rows = []
    account_ids = list(range(1, 13))
    for aid in account_ids:
        flags = 0
        r = rng.random()
        if r < 0.2:
            flags |= AF.debits_must_not_exceed_credits
        elif r < 0.4:
            flags |= AF.credits_must_not_exceed_debits
        if rng.random() < 0.3:
            flags |= AF.history
        ledger = 1 if rng.random() < 0.85 else 2
        account_rows.append(account(aid, flags=flags, ledger=ledger))

    a_bytes = pack(account_rows)
    out_cpu = cpu.submit(types.Operation.create_accounts, a_bytes)
    out_tpu = tpu.submit(types.Operation.create_accounts, a_bytes)
    assert out_cpu == out_tpu

    ids: list[int] = []
    t_index = 0
    realtime = 0
    for batch_no in range(12):
        batch = []
        for _ in range(int(rng.integers(1, 18))):
            row, new_id = random_transfer(rng, ids, account_ids, t_index)
            batch.append(row)
            ids.append(new_id)
            t_index += 1
        # Last event must not leave a chain open *sometimes* — leave it
        # sometimes to exercise linked_event_chain_open too.
        if rng.random() < 0.8:
            last = batch[-1].copy()
            last["flags"] = int(last["flags"]) & ~int(TF.linked)
            batch[-1] = last

        # Occasionally jump the clock to trigger expiry pulses.
        if rng.random() < 0.3:
            realtime += int(rng.integers(1, 4)) * 10**9
        body = pack(batch)
        out_cpu = cpu.submit(types.Operation.create_transfers, body, realtime=realtime)
        out_tpu = tpu.submit(types.Operation.create_transfers, body, realtime=realtime)
        assert out_cpu == out_tpu, f"batch {batch_no} replies diverge"
        assert cpu.sm.pulse_next_timestamp == tpu.sm.pulse_next_timestamp
        assert cpu.sm.commit_timestamp == tpu.sm.commit_timestamp

    # Final state: balances + transfer lookups byte-identical.
    out_cpu = cpu.lookup_accounts(account_ids)
    out_tpu = tpu.lookup_accounts(account_ids)
    assert out_cpu.tobytes() == out_tpu.tobytes()

    probe = sorted(set(ids))
    out_cpu = cpu.lookup_transfers(probe)
    out_tpu = tpu.lookup_transfers(probe)
    assert out_cpu.tobytes() == out_tpu.tobytes()

    # Query parity on every account (transfers + balances).
    for aid in account_ids:
        f = np.zeros(1, dtype=types.ACCOUNT_FILTER_DTYPE)[0]
        types.u128_set(f, "account_id", aid)
        f["limit"] = 8190
        f["flags"] = types.AccountFilterFlags.debits | types.AccountFilterFlags.credits
        fb = f.tobytes()
        assert cpu.submit(types.Operation.get_account_transfers, fb) == tpu.submit(
            types.Operation.get_account_transfers, fb
        )
        assert cpu.submit(types.Operation.get_account_balances, fb) == tpu.submit(
            types.Operation.get_account_balances, fb
        )


def test_kernel_path_parity_without_native():
    """The JAX scan kernel stays the exact-path reference implementation
    (the C++ engine shadows it when available): force the kernel path
    and diff it against the CPU oracle on order-dependent workloads."""
    from tigerbeetle_tpu.state_machine.cpu import CpuStateMachine
    from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
    from tigerbeetle_tpu.testing.harness import SingleNodeHarness, account, transfer
    from tigerbeetle_tpu.types import TransferFlags as TF

    hc = SingleNodeHarness(CpuStateMachine())
    ht = SingleNodeHarness(TpuStateMachine())
    ht.sm._native = None  # force the JAX kernel exact path
    for h in (hc, ht):
        assert h.create_accounts([account(i) for i in range(1, 9)]) == []

    cases = []
    # linked chains with a failure mid-chain
    cases.append([
        transfer(100, debit_account_id=1, credit_account_id=2, amount=5, flags=TF.linked),
        transfer(101, debit_account_id=2, credit_account_id=3, amount=5, flags=TF.linked),
        transfer(102, debit_account_id=3, credit_account_id=3, amount=5),  # fails
        transfer(103, debit_account_id=1, credit_account_id=2, amount=7),
    ])
    # two-phase: pending then post (inherit) then double-post
    cases.append([transfer(200, debit_account_id=1, credit_account_id=2, amount=9,
                           flags=TF.pending, timeout=100)])
    cases.append([
        transfer(201, pending_id=200, flags=TF.post_pending_transfer),
        transfer(202, pending_id=200, flags=TF.post_pending_transfer),  # already posted
    ])
    # balancing debit
    cases.append([transfer(300, debit_account_id=2, credit_account_id=4, amount=3,
                           flags=TF.balancing_debit)])
    for i, c in enumerate(cases):
        rc = hc.create_transfers(c)
        rt = ht.create_transfers(c)
        assert rc == rt, (i, rc, rt)
    for i in range(1, 9):
        assert hc.sm.account_balances_raw(i) == ht.sm.account_balances_raw(i), i
