"""Wire-layout parity tests.

Asserts the numpy dtypes reproduce the reference extern-struct layouts
byte for byte (reference: src/tigerbeetle.zig:7-322).
"""

import numpy as np

from tigerbeetle_tpu import constants, types


def offsets(dtype):
    return {name: dtype.fields[name][1] for name in dtype.names}


def test_account_layout():
    assert types.ACCOUNT_DTYPE.itemsize == 128
    off = offsets(types.ACCOUNT_DTYPE)
    assert off["id_lo"] == 0
    assert off["debits_pending_lo"] == 16
    assert off["debits_posted_lo"] == 32
    assert off["credits_pending_lo"] == 48
    assert off["credits_posted_lo"] == 64
    assert off["user_data_128_lo"] == 80
    assert off["user_data_64"] == 96
    assert off["user_data_32"] == 104
    assert off["reserved"] == 108
    assert off["ledger"] == 112
    assert off["code"] == 116
    assert off["flags"] == 118
    assert off["timestamp"] == 120


def test_transfer_layout():
    assert types.TRANSFER_DTYPE.itemsize == 128
    off = offsets(types.TRANSFER_DTYPE)
    assert off["id_lo"] == 0
    assert off["debit_account_id_lo"] == 16
    assert off["credit_account_id_lo"] == 32
    assert off["amount_lo"] == 48
    assert off["pending_id_lo"] == 64
    assert off["user_data_128_lo"] == 80
    assert off["user_data_64"] == 96
    assert off["user_data_32"] == 104
    assert off["timeout"] == 108
    assert off["ledger"] == 112
    assert off["code"] == 116
    assert off["flags"] == 118
    assert off["timestamp"] == 120


def test_account_balance_layout():
    assert types.ACCOUNT_BALANCE_DTYPE.itemsize == 128
    off = offsets(types.ACCOUNT_BALANCE_DTYPE)
    assert off["timestamp"] == 64
    assert off["reserved"] == 72


def test_account_filter_layout():
    assert types.ACCOUNT_FILTER_DTYPE.itemsize == 64
    off = offsets(types.ACCOUNT_FILTER_DTYPE)
    assert off["timestamp_min"] == 16
    assert off["timestamp_max"] == 24
    assert off["limit"] == 32
    assert off["flags"] == 36
    assert off["reserved"] == 40


def test_groove_value_layouts():
    assert types.TRANSFER_PENDING_DTYPE.itemsize == 16
    assert types.ACCOUNT_BALANCES_GROOVE_DTYPE.itemsize == 256
    assert types.CREATE_RESULT_DTYPE.itemsize == 8


def test_u128_roundtrip():
    arr = np.zeros(1, dtype=types.ACCOUNT_DTYPE)
    value = (123 << 64) | 456
    types.u128_set(arr[0], "id", value)
    assert types.u128_get(arr[0], "id") == value
    # Little-endian layout: lo limb first.
    raw = arr.tobytes()[0:16]
    assert raw == value.to_bytes(16, "little")


def test_u128_max_roundtrip():
    arr = np.zeros(1, dtype=types.TRANSFER_DTYPE)
    types.u128_set(arr[0], "amount", types.U128_MAX)
    assert types.u128_get(arr[0], "amount") == types.U128_MAX
    assert arr.tobytes()[48:64] == b"\xff" * 16


def test_result_code_values():
    # Spot-check precedence-critical orderings.
    assert types.CreateTransferResult.exists == 46
    assert types.CreateTransferResult.overflows_debits_pending == 47
    assert types.CreateTransferResult.exceeds_credits == 54
    assert types.CreateTransferResult.exceeds_debits == 55
    assert types.CreateAccountResult.exists == 21
    assert len(types.CreateTransferResult) == 56
    assert len(types.CreateAccountResult) == 22


def test_batch_max():
    assert constants.PRODUCTION.batch_max_create_transfers == 8190
    assert constants.TEST_MIN.batch_max_create_transfers == 30
    assert constants.PRODUCTION.vsr_checkpoint_interval == 960


def test_flags():
    assert types.TransferFlags.pending == 2
    assert types.TransferFlags.balancing_credit == 32
    assert types.AccountFlags.history == 8
