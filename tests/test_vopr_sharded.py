"""Sharded VOPR regressions: the multi-cluster router under the full
per-shard nemesis mix (replica crash losing unsynced sectors, crash
inside a covering fsync, partitions) PLUS the coordinator-kill nemesis,
with conservation-of-money and 2PC atomicity audited mid-run and an
oracle replay at the end.

Seeds are pinned: each reproduced a real protocol hole during
development (see the seed comments) and must stay green bit-for-bit.
"""

import pytest

from tigerbeetle_tpu.testing.vopr import ShardedVopr


def test_sharded_vopr_baseline_no_nemesis():
    """No nemesis at all: every cross-shard transfer must commit (an
    abort without a coordinator kill is a protocol bug)."""
    v = ShardedVopr(
        11, n_shards=2, replica_count=2, requests=25,
        packet_loss=0.0, crash_probability=0.0,
        fsync_crash_probability=0.0, partition_probability=0.0,
        coordinator_kill_probability=0.0,
    )
    v.run()
    assert v._strict_cross
    assert len(v.workload.xfers) > 3
    assert v.compensations == 0


def test_sharded_vopr_coordinator_kill_only():
    """Coordinator kills with healthy shards: in-doubt transfers always
    resolve; aborts are typed and only legal across a kill window."""
    v = ShardedVopr(
        23, n_shards=2, replica_count=2, requests=30,
        packet_loss=0.0, crash_probability=0.0,
        fsync_crash_probability=0.0, partition_probability=0.0,
        coordinator_kill_probability=0.02,
    )
    v.run()
    assert v.coordinator_kills >= 1
    assert len(v.workload.xfers) > 3


# Pinned full-mix seeds.  4242 found the recovery scan unilaterally
# voiding a credit hold whose debit hold it had raced past (half-posted
# money); 2046 found two coordinator incarnations colliding on request
# numbers and adopting each other's replies (fixed by the in-flight-
# covering session-resume hint); 3013 exercises the compensation path
# (decided commit whose credit hold expires under a long stall).
@pytest.mark.parametrize("seed", [1, 55, 616, 2046, 3013, 4242])
def test_sharded_vopr_full_mix(seed):
    v = ShardedVopr(
        seed, n_shards=2, replica_count=2, requests=25,
        coordinator_kill_probability=0.008,
        crash_probability=0.006, partition_probability=0.006,
        fsync_crash_probability=0.004,
    )
    v.run()
    assert v.audits > 0


def test_sharded_vopr_three_shards():
    v = ShardedVopr(
        9, n_shards=3, replica_count=2, requests=22,
        coordinator_kill_probability=0.01,
        crash_probability=0.006, partition_probability=0.006,
        fsync_crash_probability=0.004,
    )
    v.run()
    assert len(v.workload.xfers) > 3


def test_sharded_vopr_device_loss():
    """Per-shard device-loss nemesis: each shard's replicas run the
    device-authoritative engine behind seeded chaos links that the
    nemesis kills/heals mid-run; replies must stay deterministic and
    the cross-shard invariants must hold through demote/re-promote."""
    v = ShardedVopr(
        31, n_shards=2, replica_count=2, requests=14,
        coordinator_kill_probability=0.004,
        crash_probability=0.0, partition_probability=0.0,
        fsync_crash_probability=0.0,
        device_loss_probability=0.01,
    )
    v.run()
    assert v._chaos_links


def test_sharded_vopr_multi_tenant_flood():
    """Multi-tenant workload through the 2PC router (round 16): three
    ledgers with tenant 1 driving ~70% of the traffic, per-tenant QoS
    live on every shard replica, coordinator kills included — 2PC
    atomicity, conservation, and the oracle replay must hold across
    the flood."""
    v = ShardedVopr(
        13, n_shards=2, replica_count=2, requests=26,
        coordinator_kill_probability=0.008,
        crash_probability=0.004, partition_probability=0.004,
        fsync_crash_probability=0.002,
        tenants=3,
        tenant_qos=dict(rate=0.0, queue_bound=4),
    )
    v.run()
    assert v.audits > 0
    # The flood bias actually produced multi-ledger traffic.
    ledgers = set(v.workload.ledger_of.values())
    assert ledgers == {1, 2, 3}, ledgers
