"""Multiversion upgrade protocol (reference: Operation.upgrade,
`release` in every header, replica_release_execute
src/vsr/replica.zig:4298, src/tigerbeetle/main.zig:421).

Operators install new binary bundles replica-by-replica; the cluster
keeps running the old release until EVERY replica advertises the new
one, then the primary replicates one upgrade op and each process
re-executes into the new release.
"""

from tigerbeetle_tpu import types
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.harness import account, pack, transfer
from tigerbeetle_tpu.types import Operation


def test_rolling_upgrade_switches_release_cluster_wide():
    c = Cluster(replica_count=3, seed=2)
    client = c.client(1000)
    client.register()
    c.run_until(lambda: client.registered)
    c.run_request(client, Operation.create_accounts,
                  pack([account(1), account(2)]))

    # Rolling binary install: one replica at a time gets (1, 2); the
    # cluster must keep serving release 1 and NOT propose an upgrade
    # while any replica lacks release 2.
    for i in range(3):
        c.restart_replica(i, releases_available=(1, 2))
        c.settle()
        assert all(r.upgrade_target is None for r in c.replicas) or i == 2
        c.run_request(client, Operation.create_transfers,
                      pack([transfer(100 + i, debit_account_id=1,
                                     credit_account_id=2, amount=1)]))
        if i < 2:
            assert all(r.release == 1 for r in c.replicas)

    # All replicas advertise 2 -> the primary replicates the upgrade op.
    c.run_until(
        lambda: all(r.upgrade_target == 2 for r in c.replicas
                    if r.status == "normal"),
        max_steps=4000,
    )
    # Operator restarts each process into the committed target.
    for i in range(3):
        c.restart_replica(i, release=2)
    c.settle()
    assert all(r.release == 2 for r in c.replicas)

    # The cluster keeps serving, and new prepares are stamped release 2.
    c.run_request(client, Operation.create_transfers,
                  pack([transfer(200, debit_account_id=1,
                                 credit_account_id=2, amount=5)]))
    primary = c.replicas[c.replicas[0].primary_index()]
    head = primary.journal.read_prepare(primary.op)
    assert head is not None and int(head[0]["release"]) == 2
    for _ in range(30):
        c.step()
    for r in c.replicas:
        assert r.sm.transfer_timestamp(200) is not None or r.status != "normal"


def test_old_release_replica_defers_new_release_prepares():
    """A replica still running release 1 must not commit a prepare
    stamped release 2 (it cannot execute that logic) until upgraded."""
    c = Cluster(replica_count=3, seed=6)
    client = c.client(1000)
    client.register()
    c.run_until(lambda: client.registered)

    # Upgrade replicas 0 and 1 to release 2 by hand; leave 2 at 1.
    for i in (0, 1):
        c.restart_replica(i, release=2, releases_available=(1, 2))
    c.settle()
    c.run_request(client, Operation.create_accounts, pack([account(1), account(2)]))
    c.run_request(client, Operation.create_transfers,
                  pack([transfer(300, debit_account_id=1,
                                 credit_account_id=2, amount=2)]))
    for _ in range(30):
        c.step()
    # Quorum (0, 1) committed; the stale replica held back.
    assert c.replicas[0].sm.transfer_timestamp(300) is not None
    assert c.replicas[2].sm.transfer_timestamp(300) is None
    assert c.replicas[2].commit_min < c.replicas[0].commit_min

    # Once upgraded, it catches up.
    c.restart_replica(2, release=2, releases_available=(1, 2))
    c.settle()
    for _ in range(30):
        c.step()
    assert c.replicas[2].sm.transfer_timestamp(300) is not None


def test_second_upgrade_not_blocked_by_replayed_target():
    """After upgrading 1->2, replaying the old upgrade op must not
    latch a stale target that blocks proposing 2->3."""
    c = Cluster(replica_count=3, seed=8)
    client = c.client(1000)
    client.register()
    c.run_until(lambda: client.registered)

    for target in (2, 3):
        for i in range(3):
            c.restart_replica(i, releases_available=tuple(range(1, target + 1)))
        c.settle()
        c.run_until(
            lambda: all(r.upgrade_target == target for r in c.replicas
                        if r.status == "normal"),
            max_steps=4000,
        )
        for i in range(3):
            c.restart_replica(i, release=target)
        c.settle()
        assert all(r.release == target for r in c.replicas)
    c.run_request(client, Operation.create_accounts, pack([account(1)]))
    assert c.replicas[0].release == 3
