"""Multi-tenant QoS (round 16): per-tenant admission, weighted-fair
scheduling, typed busy payloads, and the differential contracts.

The tenant key is the LEDGER.  Contracts pinned here:

- qos.py primitives: token-bucket refill/burst, smooth-WRR exact
  proportional share + starvation bound + determinism, rolling rate
  window, weight parsing, bounded tenant state.
- wire: busy-payload codec roundtrip (legacy empty body stays legal),
  tenant derivation precedence (header stamp > body ledger > 0).
- Differential: QoS ON under non-overload load is bit-identical to
  QoS OFF (the overload-episode gate keeps the drain strict FIFO
  until the first shed).
- The r12 invariant extended to the tenant-keyed path: a retransmit
  of a COMMITTED request is never answered with client_busy, even
  while its tenant's neighbors are being shed at >100% load.
- Weighted-fair drain: inside an overload episode a trickle tenant's
  requests interleave with a flooding tenant's backlog instead of
  queueing behind all of it.
- SimClient busy backoff (TB_BUSY_BACKOFF_MS): consecutive busies
  back the retransmit cadence off exponentially (capped, jittered),
  so shed storms don't self-amplify.
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.obs import Registry
from tigerbeetle_tpu.qos import (
    RateWindow,
    TenantQos,
    TokenBucket,
    WeightedFair,
    parse_weights,
)
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.harness import account, pack
from tigerbeetle_tpu.vsr import wire

SEC = 1_000_000_000


# ----------------------------------------------------------------------
# Primitives.


def test_token_bucket_refill_and_burst():
    b = TokenBucket(rate=10.0)  # burst = one second's worth = 10
    t = 0
    for _ in range(10):
        assert b.admit(t)
    assert not b.admit(t)  # burst exhausted
    t += SEC // 10  # 100 ms -> one token refilled
    assert b.admit(t)
    assert not b.admit(t)
    # A long idle gap refills only up to the burst cap.
    t += 100 * SEC
    for _ in range(10):
        assert b.admit(t)
    assert not b.admit(t)


def test_token_bucket_zero_rate_admits_everything():
    b = TokenBucket(rate=0.0)
    assert all(b.admit(t) for t in range(1000))


def test_token_bucket_fractional_rate_never_starves():
    b = TokenBucket(rate=0.5, burst=1.0)
    t = 0
    assert b.admit(t)
    assert not b.admit(t + SEC)  # half a token
    assert b.admit(t + 2 * SEC)


def test_weighted_fair_exact_proportional_share():
    w = WeightedFair({1: 3.0, 2: 1.0})
    picks = [w.pick([1, 2]) for _ in range(40)]
    # Smooth WRR is exact: every 4 consecutive picks hold 3x tenant 1
    # and 1x tenant 2.
    for i in range(0, 40, 4):
        window = picks[i : i + 4]
        assert window.count(1) == 3 and window.count(2) == 1, window


def test_weighted_fair_starvation_bound():
    # Weight w among total W is picked >= once every ceil(W/w) picks.
    w = WeightedFair({1: 15.0, 2: 1.0})
    picks = [w.pick([1, 2]) for _ in range(64)]
    for i in range(0, 64 - 16):
        assert 2 in picks[i : i + 16], "tenant 2 starved"


def test_weighted_fair_deterministic_and_tie_breaks_low():
    a = WeightedFair()
    b = WeightedFair()
    seq_a = [a.pick([3, 7, 9]) for _ in range(30)]
    seq_b = [b.pick([3, 7, 9]) for _ in range(30)]
    assert seq_a == seq_b
    # Equal weights, fresh credits: the first pick ties — lowest id.
    assert WeightedFair().pick([9, 3, 7]) == 3


def test_weighted_fair_dynamic_set_prunes_credit():
    w = WeightedFair()
    for _ in range(10):
        w.pick([1, 2, 3])
    for _ in range(10):
        w.pick([4, 5])  # original tenants left
    # Departed tenants' credit is dropped (idle tenants must not
    # hoard credit); state never outgrows the concurrently active set.
    assert set(w._credit) <= {4, 5}


def test_rate_window_counts_and_scales_idle_gaps():
    r = RateWindow()
    for i in range(50):
        r.observe(7, i * (SEC // 100))  # 50 arrivals in 0.5 s
    assert r.rate(7) == 0  # window not complete yet
    r.observe(7, SEC + 1)  # closes the window
    assert 40 <= r.rate(7) <= 51
    # A 10 s idle gap must not report the stale burst as a rate.
    r.observe(7, 11 * SEC)
    assert r.rate(7) <= 5
    r.drop(7)
    assert r.rate(7) == 0


def test_parse_weights():
    assert parse_weights("") == {}
    assert parse_weights("1:4,7:2") == {1: 4.0, 7: 2.0}
    assert parse_weights(" 1:4 , 7 ") == {1: 4.0, 7: 1.0}
    with pytest.raises(ValueError):
        parse_weights("1:0")
    with pytest.raises(ValueError):
        parse_weights("-2:1")
    with pytest.raises(ValueError):
        parse_weights("x:1")


@pytest.mark.parametrize("rate", [1.0, 0.0])
def test_tenant_qos_bounded_tenant_state(rate):
    """A tenant-id sweep must not grow server state without bound in
    EITHER config — rate=0 (the default) never takes the bucket
    eviction path, so the rate window needs its own cap."""
    q = TenantQos(rate=rate, queue_bound=0,
                  registry=Registry().scope("q"))
    for tenant in range(3 * TenantQos.TENANTS_MAX):
        q.observe(tenant, 0)
        q.admit(tenant, 0, 0)
        q.on_admit(tenant)
    assert len(q._buckets) <= TenantQos.TENANTS_MAX + 1
    assert len(q.window._win) <= TenantQos.TENANTS_MAX + 1
    # Metrics overflow into the shared "tother" scope, never unbounded.
    assert len(q._metrics) <= TenantQos.TENANTS_MAX + 1


def test_tenant_id_churn_cannot_mint_burst_credit():
    """The tenant key is client-controlled, so an id sweep past
    TENANTS_MAX must not hand returning tenants fresh burst credit:
    overflow tenants share ONE bucket (no eviction of established
    buckets), and a sweep's total admitted count is bounded by that
    shared bucket, not multiplied by the number of ids used."""
    q = TenantQos(rate=4.0, queue_bound=0)
    # Fill the tracked-bucket table.
    for tenant in range(TenantQos.TENANTS_MAX):
        assert q.admit(tenant, 0, 0)
    established = set(q._buckets)
    # Sweep 200 fresh ids at one instant: admitted <= the ONE shared
    # overflow burst (4 tokens), nowhere near 200 fresh bursts.
    admitted = sum(
        q.admit(10_000 + k, 0, 0) for k in range(200)
    )
    assert admitted <= 4, admitted
    # No established tenant's bucket was evicted by the sweep.
    assert established <= set(q._buckets)
    # The returning overflow tenant shares the drained bucket: still
    # rate-limited, no fresh burst.
    assert not q.admit(10_000, 0, 0)


def test_per_tenant_counters_scoped_into_registry():
    reg = Registry()
    q = TenantQos(rate=0.0, queue_bound=4, registry=reg.scope("vsr.qos"))
    q.on_admit(1)
    q.on_admit(1)
    q.on_shed(9)
    snap = reg.snapshot()
    assert snap["vsr.qos.t1.admit"] == 2
    assert snap["vsr.qos.t9.shed"] == 1
    assert q.admits == 2 and q.sheds == 1


# ----------------------------------------------------------------------
# Wire: busy payload + tenant derivation.


def test_busy_body_roundtrip_and_legacy():
    body = wire.busy_body(7, 12, 3400)
    assert wire.parse_busy_body(body) == (7, 12, 3400)
    assert wire.parse_busy_body(b"") is None  # legacy QoS-off busy
    assert wire.parse_busy_body(b"x" * 7) is None


def test_tenant_of_precedence():
    # 1) Explicit header stamp wins.
    h = wire.make_header(
        command=wire.Command.request,
        operation=types.Operation.create_accounts, tenant=5,
    )
    body = pack([account(1, ledger=9)])
    assert wire.tenant_of(h, body) == 5
    # 2) Legacy client (no stamp): the body's leading event's ledger.
    h["tenant"] = 0
    assert wire.tenant_of(h, body) == 9
    h2 = wire.make_header(
        command=wire.Command.request,
        operation=types.Operation.create_transfers,
    )
    t = np.zeros(1, types.TRANSFER_DTYPE)[0]
    t["ledger"] = 3
    assert wire.tenant_of(h2, t.tobytes()) == 3
    # 3) No ledger on the wire (lookups) / short body -> shared class 0.
    h3 = wire.make_header(
        command=wire.Command.request,
        operation=types.Operation.lookup_accounts,
    )
    assert wire.tenant_of(h3, b"\0" * 16) == 0
    assert wire.tenant_of(h2, b"\1\2") == 0
    assert wire.tenant_of(h2, None) == 0


# ----------------------------------------------------------------------
# Replica integration.


def _enable_qos(cluster, **kw) -> list:
    out = []
    for r in cluster.replicas:
        r.qos = TenantQos(**kw)
        out.append(r.qos)
    return out


def _mixed_workload(c, client, n=18):
    """n create_accounts requests alternating across ledgers 1..3."""
    replies = []
    aid = 100
    for k in range(n):
        body = pack([account(aid, ledger=1 + k % 3), account(aid + 1,
                                                            ledger=1 + k % 3)])
        aid += 2
        replies.append(
            c.run_request(client, types.Operation.create_accounts, body)
        )
    return replies


def test_qos_on_bit_identical_to_off_under_non_overload():
    """The differential contract: with no shed (no overload episode)
    the QoS-on drain is strict FIFO — replies byte-identical to the
    QoS-off run, and nothing is ever shed."""
    runs = []
    for qos_on in (False, True):
        c = Cluster(replica_count=2, seed=77)
        qs = _enable_qos(c, rate=0.0, queue_bound=0) if qos_on else []
        client = c.client(1000)
        client.register()
        c.run_until(lambda: client.registered)
        runs.append(_mixed_workload(c, client))
        assert all(q.sheds == 0 for q in qs)
        assert client.busy_replies == 0
    assert runs[0] == runs[1]


def test_tenant_shed_retransmit_of_committed_never_busy():
    """The r12 below-the-gate invariant on the TENANT-KEYED path: at
    >100% offered load with per-tenant shedding active, a retransmit
    of an already-committed request is answered from the stored
    reply — never with client_busy."""
    c = Cluster(replica_count=1, seed=3)
    r = c.replicas[0]
    victim = c.client(1000)
    victim.register()
    c.run_until(lambda: victim.registered)
    committed_body = pack([account(1, ledger=1)])
    assert c.run_request(
        victim, types.Operation.create_accounts, committed_body
    ) == b""

    # TEST_MIN's session table holds 4 clients: victim + 3 flooders.
    flooders = [c.client(2000 + i) for i in range(3)]
    for f in flooders:
        f.register()
    c.run_until(lambda: all(f.registered for f in flooders))

    # Tenant-keyed admission: each ledger may queue at most 1; the
    # flood tenant (ledger 2) drives 120%+ of what the gated replica
    # drains (nothing drains while the anchor gate holds).
    sheds = []
    r.qos = TenantQos(rate=0.0, queue_bound=1)
    r.on_shed = lambda h, tenant=None: sheds.append(
        (int(h["request"]), tenant)
    )
    r._anchor_pending = True  # prepare path gated: the queue only grows
    for i, f in enumerate(flooders):
        f.request(
            types.Operation.create_accounts,
            pack([account(50 + i, ledger=2)]),
        )
    # Per-tenant shedding fires for the flood tenant...
    c.run_until(lambda: len(sheds) >= 2, 400)
    assert all(t == 2 for _req, t in sheds), sheds

    # ...while a retransmit of the victim's COMMITTED request replays
    # the stored reply (the at-most-once gate runs above admission).
    h = wire.make_header(
        command=wire.Command.request,
        operation=types.Operation.create_accounts,
        cluster=c.cluster_id, client=victim.id,
        request=victim.request_number,
    )
    wire.finalize_header(h, committed_body)
    for _ in range(3):
        r.on_message(h, committed_body)
        for _ in range(20):
            c.step()
    assert victim.busy_replies == 0
    assert all(t == 2 for _req, t in sheds), sheds

    # Typed busy carried the tenant + observed rate to the clients.
    # (SimClient just counts; assert via the qos accounting.)
    assert r.qos.sheds == len(sheds) > 0

    # Lift the gate: the flood tenant's retransmit cadence (with busy
    # backoff) recovers every shed request — busy was typed, not fatal.
    r._anchor_pending = False
    c.run_until(lambda: all(not f.busy() for f in flooders), 4000)
    assert all(f.reply == b"" for f in flooders)


def _fresh_request(client_id: int, request: int, ledger: int,
                   cluster_id: int) -> tuple:
    body = pack([account(10_000 + client_id * 100 + request,
                         ledger=ledger)])
    h = wire.make_header(
        command=wire.Command.request,
        operation=types.Operation.create_accounts,
        cluster=cluster_id, client=client_id, request=request,
    )
    wire.finalize_header(h, body)
    return h, body


def test_wfq_drain_interleaves_trickle_tenant_inside_episode():
    """Noisy neighbor, drain-order view: inside an overload episode a
    trickle tenant's requests drain interleaved with the flooding
    tenant's backlog (smooth WRR), not behind all of it; outside an
    episode the drain is strict FIFO."""
    c = Cluster(replica_count=1, seed=5)
    r = c.replicas[0]
    r.qos = TenantQos(rate=0.0, queue_bound=0)

    def fill():
        # 6 flood-tenant (ledger 2) requests arrive BEFORE 2 trickle
        # (ledger 1) requests.
        for req in range(1, 7):
            r._enqueue_request(*_fresh_request(0x900, req, 2, c.cluster_id))
        for req in range(1, 3):
            r._enqueue_request(*_fresh_request(0x901, req, 1, c.cluster_id))

    # FIFO outside an episode (the differential contract).
    fill()
    assert not r._qos_episode
    fifo = []
    while r.request_queue:
        r._pop_request()
        fifo.append(r._last_pop_tenant)
    assert fifo == [2] * 6 + [1] * 2

    # Weighted-fair inside an episode: tenant 1 drains early.
    fill()
    r._qos_episode = True
    order = []
    while r.request_queue:
        r._pop_request()
        order.append(r._last_pop_tenant)
    assert set(order[:2]) == {1, 2}, order  # trickle not starved
    assert order.count(1) == 2 and order.count(2) == 6
    # Queue ran empty: the episode closed, FIFO resumes.
    assert not r._qos_episode


def test_sim_client_busy_backoff_slows_retransmit_storm(monkeypatch):
    """Consecutive busies back the retransmit cadence off (capped
    exponential + deterministic jitter): over a fixed horizon the
    backoff client retransmits — and is shed — far fewer times than
    the immediate-cadence client, and still recovers afterward."""
    counts = {}
    for backoff_ms in (0, 400):  # 0 = legacy immediate cadence
        monkeypatch.setenv("TB_BUSY_BACKOFF_MS", str(backoff_ms))
        c = Cluster(replica_count=1, seed=9)
        r = c.replicas[0]
        client = c.client(1000)
        client.register()
        c.run_until(lambda: client.registered)
        r.qos = TenantQos(rate=0.0, queue_bound=0)
        r.admit_queue = 0  # everything fresh sheds
        r._anchor_pending = True
        client.request(
            types.Operation.create_accounts, pack([account(2, ledger=1)])
        )
        for _ in range(600):
            c.step()
        counts[backoff_ms] = client.busy_replies
        if backoff_ms:
            assert client.busy_backoffs >= 2
        # Recovery: lift the gate and bound; the cadence (backed off
        # or not) completes the request.
        r._anchor_pending = False
        r.admit_queue = None
        c.run_until(lambda: not client.busy(), 8000)
        assert client.reply == b""
    assert counts[400] >= 1
    # 600 ticks / RETRY_TICKS(8) ~ 75 immediate retransmits vs a
    # 40-tick base doubling to the 16x cap: >5x fewer busies.
    assert counts[400] * 5 <= counts[0], counts


def test_shed_busy_payload_carries_tenant_and_rate():
    """The typed busy body names WHO was shed, their queue depth, and
    the server-observed arrival rate (wire.busy_body through
    VsrReplica._shed_request)."""
    c = Cluster(replica_count=1, seed=11)
    r = c.replicas[0]
    r.qos = TenantQos(rate=0.0, queue_bound=1)
    r._anchor_pending = True
    seen = []
    orig = r.bus.send_client

    def capture(client, header, body):
        if int(header["command"]) == int(wire.Command.client_busy):
            seen.append(wire.parse_busy_body(body))
        return orig(client, header, body)

    r.bus.send_client = capture
    # Two fresh requests, same tenant: the second breaches the
    # per-tenant bound of 1 and sheds with a typed payload.
    r._enqueue_request(*_fresh_request(0x910, 1, 4, c.cluster_id))
    r._enqueue_request(*_fresh_request(0x910, 2, 4, c.cluster_id))
    assert seen and seen[0] is not None
    tenant, depth, _rps = seen[0]
    assert tenant == 4 and depth == 1
    assert r.qos.rate_of(4) >= 0  # observed-rate window is live


# ----------------------------------------------------------------------
# Per-tenant BYTE accounting (round 19, TB_TENANT_RATE_BYTES).


def test_byte_bucket_charges_by_body_bytes():
    """Mixed-size batches can't cheat the count bucket: with a byte
    rate configured, admission is priced by body size."""
    q = TenantQos(rate_bytes=1000.0)  # burst = 1000 body bytes
    t0 = 10**9
    assert q.admit(7, t0, 0, body_bytes=600)
    assert not q.admit(7, t0, 0, body_bytes=600)  # budget exhausted
    assert q.admit(7, t0, 0, body_bytes=300)      # small still fits
    # ~1 second refills the byte budget.
    assert q.admit(7, t0 + 10**9, 0, body_bytes=900)


def test_byte_bucket_zero_rate_is_off():
    q = TenantQos(rate_bytes=0.0)
    t0 = 10**9
    for _ in range(100):
        assert q.admit(7, t0, 0, body_bytes=1 << 20)


def test_dual_bucket_charge_is_atomic():
    """A request the BYTE bucket refuses must not drain a COUNT token
    (and vice versa): the shed leaves no half-charge behind."""
    q = TenantQos(rate=1.0, rate_bytes=100.0)  # burst: 1 req, 100 bytes
    t0 = 10**9
    # Byte-refused: the count token must survive.
    assert not q.admit(7, t0, 0, body_bytes=500)
    assert q.admit(7, t0, 0, body_bytes=50)
    # Count now exhausted: a zero-byte request is refused by count and
    # must not drain the remaining byte budget.
    assert not q.admit(7, t0, 0, body_bytes=50)
    assert q._byte_buckets[7].tokens == pytest.approx(50.0)


def test_byte_bucket_overflow_tenants_share():
    """Past TENANTS_MAX distinct tenants, byte buckets share the
    overflow bucket exactly like count buckets — an id sweep cannot
    mint fresh byte budget."""
    q = TenantQos(rate_bytes=100.0)
    t0 = 10**9
    for tenant in range(TenantQos.TENANTS_MAX):
        assert q.admit(tenant, t0, 0, body_bytes=1)
    assert q.admit(9999, t0, 0, body_bytes=90)   # overflow bucket
    assert not q.admit(8888, t0, 0, body_bytes=90)  # shared, drained


def test_follower_read_admission_uses_byte_bucket():
    """The follower charges reads by body bytes through the same
    TenantQos — covered end-to-end in tests/test_follower.py
    (test_core_read_admission_charges_bytes); here: the bucket state
    is per-tenant."""
    q = TenantQos(rate_bytes=100.0)
    t0 = 10**9
    assert q.admit(1, t0, 0, body_bytes=90)
    assert not q.admit(1, t0, 0, body_bytes=90)
    assert q.admit(2, t0, 0, body_bytes=90)  # other tenant unaffected
