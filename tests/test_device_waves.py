"""Wave dispatch inside the device engine's window launch.

Three layers:

1. Partitioner microtests: the vectorized wavefront level assigner
   (waves._levels_wavefront) against the Python-walk oracle
   (plan_waves(use_walk=True)) over fuzzed metadata, and the
   <100 µs planning budget for an 8k fresh-ids batch.
2. Window acceptance shapes: a two_phase pending/finalize stream that
   previously drained to the host executes inside the device window
   as <=2 wave steps per batch, and a chain-dominated linked batch of
   independent chains executes in ~max_chain_len device steps (not
   ~B) — both with replies byte-identical to the CPU oracle.
3. Forced-on vs forced-off differential fuzz: full device-engine
   windows (mixed kinds, two-phase, chains, duplicate ids, timeouts,
   grow/remove interleavings) run with TB_DEV_WAVES=1 and
   TB_DEV_WAVES=0; replies, final wire state, and the authoritative
   device table must be byte-identical.  Plus a chaos smoke with wave
   dispatch forced on (the degraded-mode lifecycle must keep working).
"""

import time

import numpy as np
import pytest

import tigerbeetle_tpu.state_machine.device_engine as de
from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import resolve, waves
from tigerbeetle_tpu.state_machine.cpu import CpuStateMachine
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing import harness as hz
from tigerbeetle_tpu.testing.chaos import ChaosLink
from tigerbeetle_tpu.types import EngineState, Operation, TransferFlags

TF = TransferFlags
AF = types.AccountFlags


# ---------------------------------------------------------------------------
# Partitioner: vectorized wavefront vs the Python-walk oracle.


def _random_meta(rng, n):
    flags = np.zeros(n, np.uint32)
    flags[rng.random(n) < 0.2] |= int(TF.linked)
    flags[rng.random(n) < 0.1] |= int(TF.balancing_debit)
    pv = rng.random(n) < 0.25
    flags[pv] |= int(TF.post_pending_transfer)
    id_group = rng.integers(0, max(1, n // 2), n).astype(np.int64)
    p_group = np.where(
        pv & (rng.random(n) < 0.7), rng.integers(0, max(1, n // 2), n), -1
    ).astype(np.int32)
    p_found = pv & (p_group < 0) & (rng.random(n) < 0.5)
    p_tgt = np.where(
        p_found, rng.integers(0, max(1, n // 3), n), -1
    ).astype(np.int32)
    dr_flags = np.where(
        rng.random(n) < 0.15,
        np.uint32(AF.debits_must_not_exceed_credits),
        np.uint32(0),
    )
    return resolve.wave_dependency_metadata(
        n,
        flags,
        rng.integers(0, 6, n).astype(np.int64),
        rng.integers(6, 12, n).astype(np.int64),
        dr_flags,
        np.zeros(n, np.uint32),
        id_group,
        p_group,
        p_tgt,
        p_found,
        np.where(p_found, rng.integers(0, 6, n), -1).astype(np.int64),
        np.where(p_found, rng.integers(6, 12, n), -1).astype(np.int64),
    )


def _plans_equal(a, b):
    assert len(a.segments) == len(b.segments)
    for (ka, ia), (kb, ib) in zip(a.segments, b.segments):
        assert ka == kb
        assert np.array_equal(np.asarray(ia), np.asarray(ib))
    assert a.chain_steps == b.chain_steps
    assert np.array_equal(a.wave_mask, b.wave_mask)
    assert a.n_steps == b.n_steps


@pytest.mark.parametrize("seed", range(20))
def test_vectorized_partitioner_matches_walk_oracle(seed):
    """The wavefront level assigner and the per-event Python walk must
    emit IDENTICAL plans (segment kinds, index sets, step counts) for
    arbitrary dependency metadata."""
    rng = np.random.default_rng(1000 + seed)
    for _ in range(8):
        n = int(rng.integers(2, 120))
        meta = _random_meta(rng, n)
        _plans_equal(
            waves.plan_waves(n, meta),
            waves.plan_waves(n, meta, use_walk=True),
        )


def test_wavefront_cap_falls_back_to_walk():
    """A fully serial region (every event reads+writes one hot slot via
    balancing) exceeds the wavefront round cap; the fallback walk must
    yield the same (degenerate, one-event-per-wave) plan."""
    n = 80
    flags = np.full(n, int(TF.balancing_debit), np.uint32)
    meta = resolve.wave_dependency_metadata(
        n, flags,
        np.zeros(n, np.int64), np.ones(n, np.int64),
        np.zeros(n, np.uint32), np.zeros(n, np.uint32),
        np.arange(n), np.full(n, -1, np.int32), np.full(n, -1, np.int32),
        np.zeros(n, bool), np.full(n, -1, np.int64),
        np.full(n, -1, np.int64),
    )
    fast = waves.plan_waves(n, meta)
    walk = waves.plan_waves(n, meta, use_walk=True)
    _plans_equal(fast, walk)
    assert fast.n_steps == n  # true serial dependency chain


def test_plan_waves_8k_fresh_under_100us():
    """Planning an 8k fresh-ids batch (the dominant shape) must cost
    <100 µs — it runs inside every window launch."""
    n = 8192
    meta = resolve.wave_dependency_metadata(
        n, np.zeros(n, np.uint32),
        np.arange(n, dtype=np.int64),
        np.arange(n, 2 * n, dtype=np.int64),
        np.zeros(n, np.uint32), np.zeros(n, np.uint32),
        np.arange(n), np.full(n, -1, np.int32), np.full(n, -1, np.int32),
        np.zeros(n, bool), np.full(n, -1, np.int64),
        np.full(n, -1, np.int64),
    )
    waves.plan_waves(n, meta)  # warm any lazy imports
    best = float("inf")
    for _ in range(20):
        t0 = time.perf_counter()
        waves.plan_waves(n, meta)
        best = min(best, time.perf_counter() - t0)
    assert best < 100e-6, f"plan_waves took {best * 1e6:.0f} µs"


# ---------------------------------------------------------------------------
# Window acceptance shapes.


def accounts(ids, flags=0):
    return hz.pack([hz.account(i, flags=flags) for i in ids])


def mk_pair(**tpu_kw):
    # Odd capacity: the test mesh exposes 8 virtual CPU devices, and a
    # device-divisible capacity would shard the engine — these tests
    # pin the SINGLE-CHIP executors (the sharded tests below use
    # mk_pair_sharded, whose capacity divides the mesh).
    sm_d = TpuStateMachine(
        engine="device",
        account_capacity=tpu_kw.pop("account_capacity", (1 << 12) + 1),
        **tpu_kw,
    )
    assert sm_d._dev.sharding is None
    return hz.SingleNodeHarness(sm_d), hz.SingleNodeHarness(CpuStateMachine())


def mk_pair_sharded(**tpu_kw):
    # Device-divisible capacity on the 8-device test mesh: the engine
    # row-shards its tables and wave plans execute SPMD (shard_map
    # over the ("shard",) mesh).
    sm_d = TpuStateMachine(
        engine="device",
        account_capacity=tpu_kw.pop("account_capacity", 1 << 12),
        **tpu_kw,
    )
    assert sm_d._dev.sharding is not None
    assert sm_d._dev.wave_mesh() is not None
    return hz.SingleNodeHarness(sm_d), hz.SingleNodeHarness(CpuStateMachine())


def replay_both(h_d, h_c, ops):
    futs = [h_d.submit_async(op, body) for op, body in ops]
    replies_d = [f.result() for f in futs]
    replies_c = [h_c.submit(op, body) for op, body in ops]
    for i, (a, b) in enumerate(zip(replies_d, replies_c)):
        assert a == b, f"reply {i} differs: {ops[i][0]!r}"
    return replies_d


def _pv_balancing_batch(tid, accs, rng, bal_accs=None):
    """(pending, post) pairs plus balancing singles: has_bal falls off
    every semantic kernel, previously draining the whole batch to the
    host.  `bal_accs`: dedicated per-event account pairs for the
    balancing riders (disjoint slots keep their reads independent of
    the pairs' writes — the acceptance-shape variant); default samples
    from the shared pool (overlap allowed, fuzz variant)."""
    rows = []
    for _ in range(6):
        a, b = rng.choice(accs, 2, replace=False)
        rows.append(
            hz.transfer(tid, debit_account_id=int(a),
                        credit_account_id=int(b),
                        amount=int(rng.integers(1, 50)),
                        flags=int(TF.pending))
        )
        rows.append(
            hz.transfer(tid + 1, amount=0, pending_id=tid,
                        flags=int(TF.post_pending_transfer))
        )
        tid += 2
    for k in range(3):
        if bal_accs is not None:
            a, b = bal_accs[2 * k], bal_accs[2 * k + 1]
        else:
            a, b = rng.choice(accs, 2, replace=False)
        rows.append(
            hz.transfer(tid, debit_account_id=int(a),
                        credit_account_id=int(b),
                        amount=int(rng.integers(1, 20)),
                        flags=int(TF.balancing_debit))
        )
        tid += 1
    return rows, tid


def test_two_phase_stream_waves_in_window(monkeypatch):
    """Acceptance: a pending/finalize stream the semantic kernels
    cannot express executes INSIDE the device window as <=2 wave steps
    per batch — no host drain — with oracle-identical replies."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    rng = np.random.default_rng(7)
    h_d, h_c = mk_pair()
    setup = (Operation.create_accounts, accounts(range(1, 47)))
    ops = [setup]
    accs = np.arange(1, 41)
    tid = 100
    for _ in range(6):
        rows, tid = _pv_balancing_batch(
            tid, accs, rng, bal_accs=list(range(41, 47))
        )
        ops.append((Operation.create_transfers, hz.pack(rows)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 47)))))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    assert sm.stat_dev_wave_batches == 6, "wave dispatch did not engage"
    assert sm.stat_host_semantic_events == 0, "batch drained to the host"
    # Steps live on either side of the r18 speculation split: wave-plan
    # steps in dev_wave.steps, speculative + residue steps in
    # dev_wave.spec.steps — combined, pairs still collapse to <=2.
    steps = sm.stat_dev_wave_steps + sm._dev.spec_stats["steps"].value
    assert steps <= 2 * sm.stat_dev_wave_batches, (
        f"{steps} steps for {sm.stat_dev_wave_batches} "
        "batches — two_phase pairs must collapse to <=2 waves"
    )
    sm.verify_device_mirror()


def test_chain_batch_waves_in_window(monkeypatch):
    """Acceptance: a chain-dominated linked batch of independent
    chains (with pending members, so the device `linked` kernel
    declines it) executes in ~max_chain_len device steps, not ~B."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts(range(1, 101)))]
    tid = 100
    for _b in range(3):
        rows = []
        for c in range(16):  # 16 independent chains x 3 members
            for j in range(3):
                f = int(TF.linked) if j < 2 else 0
                if j == 0:
                    f |= int(TF.pending)
                rows.append(
                    hz.transfer(
                        tid, debit_account_id=1 + 2 * c,
                        credit_account_id=2 + 2 * c,
                        amount=3 + j, flags=f,
                    )
                )
                tid += 1
        ops.append((Operation.create_transfers, hz.pack(rows)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 101)))))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    assert sm.stat_dev_wave_batches == 3
    assert sm.stat_host_semantic_events == 0
    # 48 members/batch; the position-stepped executor pays the padded
    # max_chain_len bucket (8), nowhere near one step per member.
    assert sm.stat_dev_wave_steps == 3 * 8, (
        f"{sm.stat_dev_wave_steps} steps for 3 chain batches"
    )
    sm.verify_device_mirror()


def test_dev_waves_off_drains_to_host(monkeypatch):
    """TB_DEV_WAVES=0 keeps the r7 behavior: off-kernel batches drain
    and run host-side (the differential fuzz's control arm really is
    the old path)."""
    monkeypatch.setenv("TB_DEV_WAVES", "0")
    rng = np.random.default_rng(8)
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts(range(1, 41)))]
    rows, _ = _pv_balancing_batch(100, np.arange(1, 41), rng)
    ops.append((Operation.create_transfers, hz.pack(rows)))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    assert sm.stat_dev_wave_batches == 0
    assert sm.stat_host_semantic_events > 0


def test_degraded_admission_counts_inflight_bound(monkeypatch):
    """Near-overflow balances: a second wave batch planned while the
    first is still in flight must count the first's amount bound on
    top of the (lagging) mirror and decline — serving exactly via the
    host instead of executing an unsound plan."""
    monkeypatch.setattr(de, "_WINDOW", 64)
    h_d, h_c = mk_pair()
    big = (1 << 127) + 5
    ops = [(Operation.create_accounts, accounts([1, 2, 3, 4]))]
    # Two off-kernel batches (balancing rider) pushing the same column
    # toward 2^128 while pipelined in one window.
    for k, tid in ((0, 100), (1, 200)):
        ops.append(
            (
                Operation.create_transfers,
                hz.pack(
                    [
                        hz.transfer(tid, debit_account_id=1,
                                    credit_account_id=2, amount=big),
                        hz.transfer(tid + 1, debit_account_id=3,
                                    credit_account_id=4, amount=5,
                                    flags=int(TF.balancing_debit)),
                    ]
                ),
            )
        )
    ops.append((Operation.lookup_accounts, hz.ids_bytes([1, 2, 3, 4])))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    # First batch may wave (headroom exists); the second must decline
    # (mirror + in-flight bound exceeds u128 headroom).
    assert sm.stat_dev_wave_batches <= 1
    assert sm.stat_dev_wave_declined >= 1
    sm.verify_device_mirror()


def test_wave_records_across_exact_recovery(monkeypatch):
    """A window holding [wave batch, cap-exceeded semantic batch, wave
    batch]: recovery must resolve the first wave record from its
    already-computed output, host-re-execute the flagged batch, and
    RE-EXECUTE the second wave record against the rebuilt table — all
    replies oracle-identical, no bound leaked."""
    monkeypatch.setattr(de, "_WINDOW", 8)
    rng = np.random.default_rng(9)
    h_d, h_c = mk_pair()
    ops = [(Operation.create_accounts, accounts(range(1, 47)))]
    accs = np.arange(1, 41)
    rows1, tid = _pv_balancing_batch(100, accs, rng, bal_accs=list(range(41, 47)))
    ops.append((Operation.create_transfers, hz.pack(rows1)))
    # accounts_must_be_different x100 > FAIL_CAP -> summary flag ->
    # exact recovery (small amount bound: later admissions unaffected).
    ops.append(
        (
            Operation.create_transfers,
            hz.pack(
                [
                    hz.transfer(500 + i, debit_account_id=1,
                                credit_account_id=1, amount=1)
                    for i in range(100)
                ]
            ),
        )
    )
    rows3, _ = _pv_balancing_batch(700, accs, rng, bal_accs=list(range(41, 47)))
    ops.append((Operation.create_transfers, hz.pack(rows3)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 47)))))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    assert sm._dev.stat_fallback_batches >= 1, "recovery never ran"
    assert sm.stat_dev_wave_batches == 2, "wave records missing"
    assert sm._dev.inflight_bound() == 0, "in-flight bound leaked"
    sm.verify_device_mirror()


# ---------------------------------------------------------------------------
# Forced-on vs forced-off differential fuzz over full windows.


def _fuzz_stream(rng, n_accts=60):
    """Ops mixing every routing class: semantic-kernel batches, wave
    batches (pv pairs + balancing, chains with pendings, duplicate
    ids, timeouts), account creation mid-stream (grow), a failing
    linked account chain (remove), and lookups."""
    ops = [(Operation.create_accounts, accounts(range(1, n_accts + 1)))]
    accs = np.arange(1, n_accts + 1)
    tid = 1000
    ids = []
    for k in range(14):
        r = rng.random()
        rows = []
        if r < 0.2:
            # Plain fresh batch -> orderfree semantic kernel.
            for _ in range(8):
                a, b = rng.choice(accs, 2, replace=False)
                rows.append(
                    hz.transfer(tid, debit_account_id=int(a),
                                credit_account_id=int(b),
                                amount=int(rng.integers(1, 90)))
                )
                ids.append(tid)
                tid += 1
        elif r < 0.45:
            rows, tid0 = _pv_balancing_batch(tid, accs, rng)
            ids.extend(range(tid, tid0))
            tid = tid0
            if rng.random() < 0.4 and ids:
                # Duplicate id rider: ids_unique fails -> off-kernel.
                rows.append(
                    hz.transfer(int(rng.choice(ids)),
                                debit_account_id=1, credit_account_id=2,
                                amount=1)
                )
        elif r < 0.7:
            # Independent chains, some pending members, some timeouts.
            for c in range(6):
                clen = int(rng.integers(2, 5))
                for j in range(clen):
                    f = int(TF.linked) if j < clen - 1 else 0
                    timeout = 0
                    if rng.random() < 0.3:
                        f |= int(TF.pending)
                        if rng.random() < 0.3:
                            timeout = int(rng.integers(1, 4))
                    a, b = rng.choice(accs, 2, replace=False)
                    rows.append(
                        hz.transfer(tid, debit_account_id=int(a),
                                    credit_account_id=int(b),
                                    amount=int(rng.integers(1, 40)),
                                    timeout=timeout, flags=f)
                    )
                    ids.append(tid)
                    tid += 1
        elif r < 0.8:
            # Account burst (meta records + possible grow) and a
            # failing linked account chain (rollback -> remove).
            base = n_accts + 1 + k * 40
            ops.append(
                (Operation.create_accounts,
                 accounts(range(base, base + 30)))
            )
            ops.append(
                (
                    Operation.create_accounts,
                    hz.pack(
                        [
                            hz.account(base + 30, flags=int(AF.linked)),
                            hz.account(1),  # duplicate -> chain fails
                        ]
                    ),
                )
            )
            continue
        else:
            ops.append(
                (
                    Operation.lookup_accounts,
                    hz.ids_bytes(
                        [int(x) for x in rng.choice(accs, 10, replace=False)]
                    ),
                )
            )
            continue
        ops.append((Operation.create_transfers, hz.pack(rows)))
    ops.append(
        (Operation.lookup_accounts, hz.ids_bytes([int(x) for x in accs]))
    )
    if ids:
        ops.append(
            (Operation.lookup_transfers,
             hz.ids_bytes([int(x) for x in sorted(set(ids))]))
        )
    return ops


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_device_waves_forced_on_off_differential(monkeypatch, seed):
    """Full device-engine windows with wave dispatch forced ON vs
    forced OFF: every reply, the final wire state, and the
    authoritative device table must be byte-identical — the wave plan
    is an execution strategy, never a semantics change."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    replies = {}
    tables = {}
    for mode in ("1", "0"):
        monkeypatch.setenv("TB_DEV_WAVES", mode)
        rng = np.random.default_rng(seed)
        sm = TpuStateMachine(engine="device", account_capacity=65)
        h = hz.SingleNodeHarness(sm)
        ops = _fuzz_stream(rng)
        futs = [h.submit_async(op, body) for op, body in ops]
        replies[mode] = [f.result() for f in futs]
        sm.verify_device_mirror()
        tables[mode] = np.asarray(sm._dev.checksum())
        if mode == "1":
            assert sm.stat_dev_wave_batches > 0, "fuzz never waved: vacuous"
        else:
            assert sm.stat_dev_wave_batches == 0
        del sm, h
    for i, (a, b) in enumerate(zip(replies["1"], replies["0"])):
        assert a == b, f"seed {seed}: reply {i} diverges (waves on vs off)"
    assert (tables["1"] == tables["0"]).all(), (
        "authoritative table diverges between wave-on and wave-off"
    )


def test_chaos_smoke_with_waves_on(monkeypatch):
    """Probabilistic link chaos with wave dispatch forced on: demote /
    degraded-serve / re-promote must keep every reply oracle-identical
    — wave records replay through their exact host fallback like any
    other in-flight record."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    monkeypatch.setattr(de, "_BACKOFF_MS", 0.0)
    monkeypatch.setattr(de, "_PROBE_EVERY", 2)
    monkeypatch.setenv("TB_DEV_WAVES", "1")
    rng = np.random.default_rng(5)
    link = ChaosLink(
        seed=17, p_transient=0.05, p_fatal=0.0, p_kill=0.0
    )
    sm_d = TpuStateMachine(
        engine="device", account_capacity=(1 << 10) + 1, device_link=link
    )
    h_d = hz.SingleNodeHarness(sm_d)
    h_c = hz.SingleNodeHarness(CpuStateMachine())
    ops = _fuzz_stream(rng, n_accts=40)
    futs = []
    for k, (op, body) in enumerate(ops):
        if k in (len(ops) // 3, 2 * len(ops) // 3):
            # Deterministic mid-stream losses: wave records must be in
            # flight when the link dies, replaying via host fallback.
            link.fail_next(kind="fatal")
        futs.append(h_d.submit_async(op, body))
    replies_d = [f.result() for f in futs]
    for f in futs:
        assert f.done()
    replies_c = [h_c.submit(op, body) for op, body in ops]
    mismatches = [
        i for i, (a, b) in enumerate(zip(replies_d, replies_c)) if a != b
    ]
    assert not mismatches, f"replies diverge at {mismatches[:5]}"
    dev = sm_d.sm._dev if hasattr(sm_d, "sm") else sm_d._dev
    assert dev.stat_demotions >= 1, "chaos never demoted: weak smoke"
    link.heal()
    link.p_transient = link.p_fatal = link.p_kill = 0.0
    assert dev.try_repromote()
    assert dev.state is EngineState.healthy
    sm_d.verify_device_mirror()


# ---------------------------------------------------------------------------
# SPMD wave dispatch on the row-sharded engine (the conftest mesh
# exposes 8 virtual CPU devices; a device-divisible capacity shards
# the engine's tables with NamedSharding over a ("shard",) mesh and
# the wave plans execute through waves._execute_plan_sharded).


def test_sharded_two_phase_stream_waves_in_window(monkeypatch):
    """Acceptance: the off-kernel pending/finalize stream executes
    INSIDE the window of a ROW-SHARDED engine — no decline, every plan
    SPMD over the mesh, replies oracle-identical — and the pending
    wave records hold compact columns, >= 10x smaller than the padded
    event dicts they replace."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    rng = np.random.default_rng(7)
    h_d, h_c = mk_pair_sharded()
    setup = (Operation.create_accounts, accounts(range(1, 47)))
    ops = [setup]
    accs = np.arange(1, 41)
    tid = 100
    for _ in range(6):
        rows, tid = _pv_balancing_batch(
            tid, accs, rng, bal_accs=list(range(41, 47))
        )
        ops.append((Operation.create_transfers, hz.pack(rows)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 47)))))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    assert sm.stat_dev_wave_batches == 6, "sharded engine declined waves"
    assert sm.stat_dev_wave_declined == 0, (
        sm.stat_dev_wave_decline_reasons
    )
    assert sm.stat_host_semantic_events == 0, "batch drained to the host"
    assert sm._dev.stat_wave_sharded >= 6, "plans did not execute SPMD"
    assert sm.stat_dev_wave_steps <= 2 * sm.stat_dev_wave_batches
    assert sm._dev.stat_wave_window_bytes_peak > 0
    reduction = (
        sm._dev.stat_wave_window_padded_peak
        / sm._dev.stat_wave_window_bytes_peak
    )
    assert reduction >= 10, (
        f"pending wave records only {reduction:.1f}x smaller than the "
        "padded event dicts"
    )
    sm.verify_device_mirror()


def test_sharded_chain_batch_waves_in_window(monkeypatch):
    """The chain-wave scan (one lax.scan over chain position) also
    runs SPMD: per-position sharded row updates, ~max_chain_len steps,
    oracle-identical replies."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    h_d, h_c = mk_pair_sharded()
    ops = [(Operation.create_accounts, accounts(range(1, 101)))]
    tid = 100
    for _b in range(3):
        rows = []
        for c in range(16):
            for j in range(3):
                f = int(TF.linked) if j < 2 else 0
                if j == 0:
                    f |= int(TF.pending)
                rows.append(
                    hz.transfer(
                        tid, debit_account_id=1 + 2 * c,
                        credit_account_id=2 + 2 * c,
                        amount=3 + j, flags=f,
                    )
                )
                tid += 1
        ops.append((Operation.create_transfers, hz.pack(rows)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 101)))))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    assert sm.stat_dev_wave_batches == 3
    assert sm.stat_dev_wave_declined == 0
    assert sm._dev.stat_wave_sharded >= 3
    assert sm.stat_dev_wave_steps == 3 * 8
    sm.verify_device_mirror()


def test_sharded_chain_rollback_in_window(monkeypatch):
    """A failing chain member (debit == credit: static ladder) rolls
    its whole chain back through the SPMD trailing-subtraction repair
    while sibling chains apply — oracle-identical replies and mirror."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    h_d, h_c = mk_pair_sharded()
    ops = [(Operation.create_accounts, accounts(range(1, 41)))]
    rows = []
    tid = 100
    for c in range(8):
        for j in range(3):
            f = int(TF.linked) if j < 2 else 0
            if j == 0:
                f |= int(TF.pending)
            dr, cr = 1 + 2 * c, 2 + 2 * c
            if c == 3 and j == 1:
                cr = dr  # accounts_must_be_different -> chain fails
            rows.append(
                hz.transfer(tid, debit_account_id=dr,
                            credit_account_id=cr, amount=3 + j, flags=f)
            )
            tid += 1
    ops.append((Operation.create_transfers, hz.pack(rows)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 41)))))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    assert sm.stat_dev_wave_batches == 1, "chain batch did not wave"
    assert sm._dev.stat_wave_sharded >= 1
    sm.verify_device_mirror()


def test_sharded_plan_with_scan_segment_declines(monkeypatch):
    """Unsupported plan shapes DECLINE, never error: history-account
    events force exact scan segments, which have no SPMD executor —
    the sharded engine counts the decline by reason and drains to the
    host, replies still oracle-identical."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    monkeypatch.setenv("TB_DEV_WAVES", "1")
    rng = np.random.default_rng(13)
    h_d, h_c = mk_pair_sharded()
    ops = [
        (
            Operation.create_accounts,
            hz.pack(
                [hz.account(i) for i in range(1, 41)]
                + [
                    hz.account(41, flags=int(AF.history)),
                    hz.account(42, flags=int(AF.history)),
                ]
            ),
        )
    ]
    rows = []
    tid = 100
    for _ in range(20):
        a, b = rng.choice(np.arange(1, 41), 2, replace=False)
        rows.append(
            hz.transfer(tid, debit_account_id=int(a),
                        credit_account_id=int(b),
                        amount=int(rng.integers(1, 40)),
                        flags=int(TF.pending))  # off the orderfree route
        )
        tid += 1
    rows.append(
        hz.transfer(tid, debit_account_id=41, credit_account_id=42,
                    amount=5, flags=int(TF.pending))
    )
    ops.append((Operation.create_transfers, hz.pack(rows)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes(list(range(1, 43)))))
    replay_both(h_d, h_c, ops)
    sm = h_d.sm
    assert sm.stat_dev_wave_batches == 0
    assert sm.stat_dev_wave_decline_reasons.get("shard_plan", 0) >= 1, (
        sm.stat_dev_wave_decline_reasons
    )
    assert sm.stat_host_semantic_events > 0, "decline must drain to host"
    sm.verify_device_mirror()


@pytest.mark.parametrize("seed", [31, 32])
def test_sharded_waves_differential(monkeypatch, seed):
    """Three arms over the SAME fuzz stream — sharded waves forced on,
    sharded waves off (drain), unsharded waves forced on — must agree
    byte-for-byte on every reply; the two sharded arms must also agree
    on the authoritative table digest.  The SPMD executors are an
    execution strategy, never a semantics change."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    replies = {}
    tables = {}
    arms = (
        ("sharded_on", 1 << 10, "1"),
        ("sharded_off", 1 << 10, "0"),
        ("unsharded_on", (1 << 10) + 1, "1"),
    )
    for name, capacity, mode in arms:
        monkeypatch.setenv("TB_DEV_WAVES", mode)
        rng = np.random.default_rng(seed)
        sm = TpuStateMachine(engine="device", account_capacity=capacity)
        sharded = capacity % 8 == 0
        assert (sm._dev.sharding is not None) == sharded
        h = hz.SingleNodeHarness(sm)
        ops = _fuzz_stream(rng)
        futs = [h.submit_async(op, body) for op, body in ops]
        replies[name] = [f.result() for f in futs]
        sm.verify_device_mirror()
        if sharded:
            tables[name] = np.asarray(sm._dev.checksum())
        if mode == "1":
            assert sm.stat_dev_wave_batches > 0, f"{name}: never waved"
            if sharded:
                assert sm._dev.stat_wave_sharded > 0
        else:
            assert sm.stat_dev_wave_batches == 0
        del sm, h
    for arm in ("sharded_off", "unsharded_on"):
        for i, (a, b) in enumerate(zip(replies["sharded_on"], replies[arm])):
            assert a == b, (
                f"seed {seed}: reply {i} diverges (sharded_on vs {arm})"
            )
    assert (tables["sharded_on"] == tables["sharded_off"]).all(), (
        "authoritative table diverges between sharded wave-on and -off"
    )


def test_sharded_chaos_smoke_with_waves_on(monkeypatch):
    """Link chaos on the ROW-SHARDED engine with wave dispatch forced
    on: demote / degraded-serve / re-promote keep every reply
    oracle-identical — sharded wave records replay through their exact
    host fallback like any other in-flight record."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    monkeypatch.setattr(de, "_BACKOFF_MS", 0.0)
    monkeypatch.setattr(de, "_PROBE_EVERY", 2)
    monkeypatch.setenv("TB_DEV_WAVES", "1")
    rng = np.random.default_rng(5)
    link = ChaosLink(seed=23, p_transient=0.05, p_fatal=0.0, p_kill=0.0)
    sm_d = TpuStateMachine(
        engine="device", account_capacity=1 << 10, device_link=link
    )
    assert sm_d._dev.sharding is not None
    h_d = hz.SingleNodeHarness(sm_d)
    h_c = hz.SingleNodeHarness(CpuStateMachine())
    ops = _fuzz_stream(rng, n_accts=40)
    futs = []
    for k, (op, body) in enumerate(ops):
        if k in (len(ops) // 3, 2 * len(ops) // 3):
            link.fail_next(kind="fatal")
        futs.append(h_d.submit_async(op, body))
    replies_d = [f.result() for f in futs]
    for f in futs:
        assert f.done()
    replies_c = [h_c.submit(op, body) for op, body in ops]
    mismatches = [
        i for i, (a, b) in enumerate(zip(replies_d, replies_c)) if a != b
    ]
    assert not mismatches, f"replies diverge at {mismatches[:5]}"
    dev = sm_d._dev
    assert dev.stat_demotions >= 1, "chaos never demoted: weak smoke"
    link.heal()
    link.p_transient = link.p_fatal = link.p_kill = 0.0
    assert dev.try_repromote()
    assert dev.state is EngineState.healthy
    sm_d.verify_device_mirror()


# ---------------------------------------------------------------------------
# Pending wave-record compaction (waves.pack_wave_record).


def _random_event_dict(rng, n, B):
    from tigerbeetle_tpu.state_machine import kernel

    ev = {}
    for name, dtype in kernel.EVENT_FIELDS:
        dt = np.dtype(dtype)
        if name == "i":
            ev[name] = np.arange(B, dtype=dt)
            continue
        arr = np.zeros(B, dt)
        style = rng.random()
        if style < 0.25:
            pass  # all-zero column
        elif style < 0.45:
            arr[:n] = np.asarray(7, dt)  # constant
        elif dt.kind == "b":
            arr[:n] = rng.random(n) < 0.3
        elif dt.kind == "i":
            arr[:n] = rng.integers(-1, 50, n)
        else:
            hi = int(rng.choice([40, 70_000, 1 << 40]))
            arr[:n] = rng.integers(0, hi, n).astype(dt)
        ev[name] = arr
    return ev


@pytest.mark.parametrize("seed", range(4))
def test_pending_wave_record_codec_roundtrip(seed):
    """The columnar compaction is LOSSLESS for arbitrary event dicts:
    unpack(pack(ev)) reproduces every column bit-for-bit, dtype and
    padding included."""
    rng = np.random.default_rng(400 + seed)
    n = int(rng.integers(1, 200))
    B = 256
    ev = _random_event_dict(rng, n, B)
    dstat = np.zeros(B, np.uint32)
    dstat[: int(rng.integers(0, 5))] = 2
    hist_fix = np.zeros(B, bool)
    hist_fix[:n] = rng.random(n) < 0.8
    pk = waves.pack_wave_record(ev, dstat, hist_fix, n)
    ev2, dstat2, hist2 = waves.unpack_wave_record(pk)
    assert set(ev2) == set(ev)
    for name, arr in ev.items():
        got = ev2[name]
        assert got.dtype == arr.dtype, name
        assert np.array_equal(got, arr), name
    assert np.array_equal(dstat2, dstat) and dstat2.dtype == dstat.dtype
    assert np.array_equal(hist2, hist_fix) and hist2.dtype == hist_fix.dtype
    assert pk.nbytes < pk.padded_nbytes


def test_pending_wave_record_nonzero_padding_is_lossless():
    """A column with nonzero bytes PAST the batch length (not a shape
    the router produces, but the codec must never corrupt) is stored
    verbatim."""
    from tigerbeetle_tpu.state_machine import kernel

    rng = np.random.default_rng(9)
    B = 64
    ev = _random_event_dict(rng, 10, B)
    ev["amount_lo"][B - 1] = 77  # poison the padding
    pk = waves.pack_wave_record(ev, np.zeros(B, np.uint32),
                                np.zeros(B, bool), 10)
    ev2, _, _ = waves.unpack_wave_record(pk)
    for name, arr in ev.items():
        assert np.array_equal(ev2[name], arr), name
    del kernel
