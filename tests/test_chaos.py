"""Device-loss chaos: demote -> serve degraded -> re-promote, on CPU.

The chaos shim (testing/chaos.py) injects seeded faults into every
link crossing of the device-authoritative engine; these tests pin the
degraded-mode lifecycle (state_machine/device_engine.py) to the CPU
oracle: under ANY injected fault schedule, every reply is bit-identical
to the pure-host oracle, no future is ever left unresolved, and the
engine re-promotes through the checksum handshake once the link heals.
"""

import numpy as np
import pytest

import tigerbeetle_tpu.state_machine.device_engine as de
from tigerbeetle_tpu.state_machine.cpu import CpuStateMachine
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
from tigerbeetle_tpu.testing import harness as hz
from tigerbeetle_tpu.testing.chaos import ChaosLink
from tigerbeetle_tpu.testing.vopr import Vopr, Workload
from tigerbeetle_tpu.types import EngineState, Operation


@pytest.fixture(autouse=True)
def _fast_lifecycle(monkeypatch):
    """Small window + no backoff sleeps + tight probe cadence: the
    lifecycle spins fast enough for a `not slow` smoke."""
    monkeypatch.setattr(de, "_WINDOW", 4)
    monkeypatch.setattr(de, "_BACKOFF_MS", 0.0)
    monkeypatch.setattr(de, "_PROBE_EVERY", 2)


def mk_chaos_pair(seed=0, **chaos_kw):
    link = ChaosLink(seed=seed, **chaos_kw)
    sm_d = TpuStateMachine(
        engine="device", account_capacity=1 << 12, device_link=link
    )
    sm_c = CpuStateMachine()
    return hz.SingleNodeHarness(sm_d), hz.SingleNodeHarness(sm_c), link


def accounts(ids, flags=0):
    return hz.pack([hz.account(i, flags=flags) for i in ids])


def transfers(rows):
    return hz.pack([hz.transfer(**r) for r in rows])


def simple_ops(n_batches=6, tid0=100):
    ops = [(Operation.create_accounts, accounts([1, 2, 3]))]
    tid = tid0
    for k in range(n_batches):
        rows = [
            dict(id=tid + j, debit_account_id=1 + (k + j) % 3,
                 credit_account_id=1 + (k + j + 1) % 3, amount=1 + j)
            for j in range(3)
        ]
        tid += 3
        ops.append((Operation.create_transfers, transfers(rows)))
    ops.append((Operation.lookup_accounts, hz.ids_bytes([1, 2, 3])))
    return ops


def replay_pipelined(h_d, h_c, ops):
    futs = [h_d.submit_async(op, body) for op, body in ops]
    replies_d = [f.result() for f in futs]
    replies_c = [h_c.submit(op, body) for op, body in ops]
    for i, (a, b) in enumerate(zip(replies_d, replies_c)):
        assert a == b, f"reply {i} differs under chaos: {ops[i][0]!r}"
    return replies_d


@pytest.mark.parametrize("stage", ["h2d", "dispatch", "fetch"])
def test_demote_at_every_stage_resolves_inflight(stage):
    """Fatal loss at each pipeline stage (pre-upload, mid-dispatch, at
    ring fetch): every in-flight future resolves bit-identically via
    host replay, and the engine lands in degraded mode."""
    h_d, h_c, link = mk_chaos_pair()
    ops = simple_ops()
    # Arm the fault AFTER setup so the loss hits with batches in
    # flight at the targeted stage.
    h_d.submit(*ops[0])
    h_c.submit(*ops[0])
    link.fail_next(stage=stage, kind="fatal")
    replay_pipelined(h_d, h_c, ops[1:])
    dev = h_d.sm._dev
    assert dev.stat_demotions >= 1
    assert not dev.has_inflight()
    # The link is healthy again after the one scripted fault, so the
    # probe cadence may already have re-promoted — either way the
    # lifecycle must be in a legal steady state.
    if dev.state is EngineState.healthy:
        assert dev.stat_repromotions >= 1
    else:
        assert dev.state is EngineState.degraded


def test_transient_faults_retry_without_demotion():
    """A burst of transient errors below the retry budget costs
    backoff only: no demotion, exact replies."""
    h_d, h_c, link = mk_chaos_pair()
    ops = simple_ops()
    h_d.submit(*ops[0])
    h_c.submit(*ops[0])
    link.fail_next(kind="transient", count=2)
    replay_pipelined(h_d, h_c, ops[1:])
    dev = h_d.sm._dev
    assert dev.state is EngineState.healthy
    assert dev.stat_retries >= 2
    assert dev.stat_demotions == 0


def test_retry_budget_exhaustion_demotes(monkeypatch):
    monkeypatch.setattr(de, "_RETRIES", 2)
    h_d, h_c, link = mk_chaos_pair()
    ops = simple_ops()
    h_d.submit(*ops[0])
    h_c.submit(*ops[0])
    # More consecutive transients than the budget: the crossing turns
    # into a device loss.
    link.fail_next(kind="transient", count=10)
    replay_pipelined(h_d, h_c, ops[1:])
    # Three transients (initial + 2 retries) exhausted the budget ->
    # demotion; the probe cadence may then have healed the engine once
    # the scripted faults drained.
    assert h_d.sm._dev.stat_demotions >= 1
    assert h_d.sm._dev.stat_retries >= 2


def test_degraded_serves_then_repromotes_with_handshake():
    """Kill -> exact degraded service -> heal -> probe cadence
    re-promotes through the checksum handshake -> device authority
    resumes (semantic events start counting again)."""
    h_d, h_c, link = mk_chaos_pair()
    ops = simple_ops(n_batches=4)
    replay_pipelined(h_d, h_c, ops)  # healthy warm-up
    dev = h_d.sm._dev
    sem_before = dev.stat_semantic_events
    assert sem_before > 0

    link.kill()
    mid = simple_ops(n_batches=6, tid0=500)[1:]  # accounts already exist
    replay_pipelined(h_d, h_c, mid)
    assert dev.state is EngineState.degraded
    assert dev.stat_degraded_events > 0

    link.heal()
    tail = simple_ops(n_batches=8, tid0=900)[1:]
    replay_pipelined(h_d, h_c, tail)
    assert dev.state is EngineState.healthy
    assert dev.stat_repromotions == 1
    # Authority genuinely moved back: post-heal batches ran on device.
    assert dev.stat_semantic_events > sem_before
    h_d.sm.verify_device_mirror()


def test_failed_probe_stays_degraded():
    """While the link is down, probes fail and the engine must keep
    serving degraded — never half-promote."""
    h_d, h_c, link = mk_chaos_pair()
    link.kill()
    replay_pipelined(h_d, h_c, simple_ops(n_batches=8))
    dev = h_d.sm._dev
    assert dev.state is EngineState.degraded
    assert dev.stat_probe_failures >= 1
    assert dev.stat_repromotions == 0


def test_scrub_heals_seeded_divergence(monkeypatch):
    """The healthy-mode checksum scrub detects a device/mirror
    divergence and heals it by re-uploading from the mirror."""
    monkeypatch.setattr(de, "_SCRUB_EVERY", 1)
    h_d, h_c, _link = mk_chaos_pair()
    ops = simple_ops(n_batches=2)
    replay_pipelined(h_d, h_c, ops)
    dev = h_d.sm._dev
    # Corrupt the device table behind the engine's back (a bit flip in
    # HBM), then let the next tick's scrub find and heal it.
    dev.balances = dev.balances.at[0, 1].add(np.uint64(1))
    with pytest.raises(AssertionError, match="divergence"):
        h_d.sm.verify_device_mirror()
    replay_pipelined(h_d, h_c, simple_ops(n_batches=2, tid0=700)[1:])
    assert dev.stat_scrubs >= 1
    assert dev.stat_scrub_heals == 1
    h_d.sm.verify_device_mirror()
    assert dev.state is EngineState.healthy


def test_scrub_heals_meta_divergence(monkeypatch):
    """The scrub digest covers the account-META table too: the ladder
    verdicts read it, so silent meta corruption is as dangerous as a
    balance flip.  A flipped word heals by re-upload from the host
    copy."""
    monkeypatch.setattr(de, "_SCRUB_EVERY", 1)
    h_d, h_c, _link = mk_chaos_pair()
    replay_pipelined(h_d, h_c, simple_ops(n_batches=2))
    dev = h_d.sm._dev
    dev.meta = dev.meta.at[1, 1].add(np.uint32(7))
    replay_pipelined(h_d, h_c, simple_ops(n_batches=2, tid0=800)[1:])
    assert dev.stat_scrub_heals == 1
    assert (np.asarray(dev.meta) == dev._meta_host).all()
    assert dev.state is EngineState.healthy


def test_lookup_and_meta_resolve_under_loss():
    """Device-side lookups and account-meta records in flight when the
    link dies must resolve from the mirror, in stream order."""
    h_d, h_c, link = mk_chaos_pair()
    ops = [(Operation.create_accounts, accounts([1, 2]))]
    h_d.submit(*ops[0])
    h_c.submit(*ops[0])
    link.fail_next(stage="fetch", kind="fatal")
    mixed = [
        (Operation.create_transfers, transfers(
            [dict(id=10, debit_account_id=1, credit_account_id=2,
                  amount=5)])),
        (Operation.lookup_accounts, hz.ids_bytes([1, 2])),
        (Operation.create_accounts, accounts([7])),
        (Operation.create_transfers, transfers(
            [dict(id=11, debit_account_id=7, credit_account_id=2,
                  amount=9)])),
        (Operation.lookup_accounts, hz.ids_bytes([1, 2, 7])),
    ]
    replay_pipelined(h_d, h_c, mixed)
    assert h_d.sm._dev.stat_demotions >= 1


def test_grow_while_degraded_then_repromote():
    """Capacity growth during an outage defers the HBM widen; the
    re-promotion upload must rebuild BOTH tables at the grown capacity
    and still pass the checksum handshake."""
    link = ChaosLink()
    sm_d = TpuStateMachine(
        engine="device", account_capacity=64, device_link=link
    )
    h_d = hz.SingleNodeHarness(sm_d)
    h_c = hz.SingleNodeHarness(CpuStateMachine())
    first = (Operation.create_accounts, accounts(range(1, 33)))
    assert h_d.submit(*first) == h_c.submit(*first)
    link.kill()
    burst = (Operation.create_accounts, accounts(range(33, 161)))
    assert h_d.submit(*burst) == h_c.submit(*burst)
    dev = sm_d._dev
    tx = [
        (Operation.create_transfers, transfers(
            [dict(id=100 + k, debit_account_id=1 + k,
                  credit_account_id=150 - k, amount=2 + k)]))
        for k in range(4)
    ]
    replay_pipelined(h_d, h_c, tx)
    assert dev.state is EngineState.degraded
    grown = dev.capacity
    assert grown >= 160
    link.heal()
    tail = [
        (Operation.create_transfers, transfers(
            [dict(id=200 + k, debit_account_id=10 + k,
                  credit_account_id=120 + k, amount=3 + k)]))
        for k in range(6)
    ]
    tail.append(
        (Operation.lookup_accounts, hz.ids_bytes(list(range(1, 161))))
    )
    replay_pipelined(h_d, h_c, tail)
    assert dev.state is EngineState.healthy
    assert int(dev.balances.shape[0]) == grown
    sm_d.verify_device_mirror()


def test_close_terminates_every_future():
    """DeviceEngine.close() resolves (host replay) or fails (typed
    error) every outstanding future — no caller is ever stranded."""
    h_d, _h_c, link = mk_chaos_pair()
    h_d.submit(Operation.create_accounts, accounts([1, 2]))
    fut = h_d.submit_async(
        Operation.create_transfers,
        transfers([dict(id=10, debit_account_id=1, credit_account_id=2,
                        amount=5)]),
    )
    link.kill()
    h_d.sm._dev.close()
    assert fut.done()
    fut.result()  # resolved exactly via host replay, not an assert


def test_reply_future_fail_is_typed():
    fut = de.ReplyFuture(None)
    with pytest.raises(de.DeviceLostError):
        fut.result()
    fut2 = de.ReplyFuture(None)
    fut2.fail(de.DeviceLostError("close", "boom"))
    assert fut2.done()
    with pytest.raises(de.DeviceLostError, match="close"):
        fut2.result()


def test_chaos_smoke_differential():
    """CI smoke (tier-1, CPU-only): ~1k seeded workload events through
    the device engine under probabilistic chaos at every stage —
    kills, fatal and transient faults — differentially checked against
    the pure-host oracle.  Fails on any reply mismatch or any
    permanently unresolved future; ends by healing and proving
    re-promotion passes the checksum handshake."""
    wl = Workload(1234)
    h_d, h_c, link = mk_chaos_pair(
        seed=99,
        p_transient=0.02,
        p_fatal=0.004,
        p_kill=0.002,
        down_for=6,
    )
    sent_events = 0
    pending: list = []
    ops_log: list = []
    while sent_events < 1000:
        operation, body, _must = wl.next_request()
        n = 1 if not body else len(body) // 128
        sent_events += n
        ops_log.append((operation, body))
        pending.append(h_d.submit_async(operation, body))
    replies_d = [f.result() for f in pending]  # no future may strand
    for f in pending:
        assert f.done()
    replies_c = [h_c.submit(op, body) for op, body in ops_log]
    mismatches = [
        i for i, (a, b) in enumerate(zip(replies_d, replies_c)) if a != b
    ]
    assert not mismatches, f"replies diverge at {mismatches[:5]}"

    dev = h_d.sm._dev
    # The schedule must actually have exercised the lifecycle.
    assert dev.stat_demotions >= 1, "chaos never demoted: weak smoke"
    assert dev.stat_retries >= 1
    # Heal and force the handshake: the engine must come back.
    link.heal()
    link.p_transient = link.p_fatal = link.p_kill = 0.0
    assert dev.try_repromote()
    assert dev.state is EngineState.healthy
    h_d.sm.verify_device_mirror()
    # And serve exactly after re-promotion.
    tail = simple_ops(n_batches=4, tid0=10_000_000)
    replay_pipelined(h_d, h_c, tail)


def test_demote_during_speculation_releases_whole_batch_bound(monkeypatch):
    """Regression (r18): a speculative record's admission charge is the
    WHOLE-batch superset — the same bound the wave path charges.  Kill
    the link with speculative batches in flight (mid-validation): the
    demotion replays them through the exact host fallback, the bound
    releases exactly once, and a sibling batch admitted after the
    replay sees the true mirror headroom — replies stay
    oracle-identical, nothing over-applies."""
    monkeypatch.setenv("TB_WAVES_SPECULATE", "force")
    h_d, h_c, link = mk_chaos_pair()
    ops = [(Operation.create_accounts, accounts(range(1, 21)))]
    h_d.submit(*ops[0])
    h_c.submit(*ops[0])
    # Fatal loss at the dispatch stage: the speculative step (or
    # its validation fetch) dies with the record in flight.
    link.fail_next(stage="dispatch", kind="fatal")
    mixed = []
    tid = 100
    for k in range(6):
        rows = [
            dict(id=tid + j, debit_account_id=1 + (k + j) % 20,
                 credit_account_id=1 + (k + j + 1) % 20,
                 amount=(1 << 40) + j)
            for j in range(4)
        ]
        tid += 4
        mixed.append((Operation.create_transfers, transfers(rows)))
    mixed.append((Operation.lookup_accounts,
                  hz.ids_bytes(list(range(1, 21)))))
    replay_pipelined(h_d, h_c, mixed)
    dev = h_d.sm._dev
    assert dev.stat_demotions >= 1, "fault never hit a record in flight"
    assert dev.inflight_bound() == 0, (
        "speculative record leaked (or double-released) its "
        "admission bound across the demotion replay"
    )
    # Post-heal batches must re-admit against the true mirror state.
    link.heal()
    tail = [
        (Operation.create_transfers, transfers(
            [dict(id=900 + k, debit_account_id=1 + k,
                  credit_account_id=2 + k, amount=7)]))
        for k in range(4)
    ]
    replay_pipelined(h_d, h_c, tail)
    assert dev.inflight_bound() == 0
    h_d.sm.verify_device_mirror()


def test_vopr_device_loss_nemesis():
    """Whole-cluster VOPR with the device-loss nemesis: replicas run
    the device engine behind seeded chaos links that die and heal at
    different times; linearization, convergence, conservation, and
    restart-replay equivalence must all hold."""
    v = Vopr(
        21, requests=18, packet_loss=0.0, crash_probability=0.0,
        device_loss_probability=0.04,
    )
    v.run()
    assert v._chaos_links, "device-loss nemesis built no chaos links"
    kills = sum(link.stat_kills for link in v._chaos_links)
    demotions = sum(
        r.sm._dev.stat_demotions
        for r in v.cluster.replicas
        if getattr(r.sm, "engine", "") == "device"
    )
    assert kills >= 1, "nemesis never killed a link: weak seed"
    assert demotions >= 1, "kills never demoted an engine"
