"""create_transfers semantics vs the reference precedence ladder.

Covers the single-phase subset of the 56 CreateTransferResult codes
(reference: src/tigerbeetle.zig:185-265, src/state_machine.zig:1462-1606).
Two-phase codes live in test_two_phase.py.
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.testing.harness import SingleNodeHarness, account, transfer

CTR = types.CreateTransferResult
AF = types.AccountFlags
TF = types.TransferFlags
MAX = types.U128_MAX


@pytest.fixture
def h(sm):
    h = SingleNodeHarness(sm)
    assert (
        h.create_accounts(
            [account(1), account(2), account(3, ledger=2), account(4)]
        )
        == []
    )
    return h


def t(id, dr=1, cr=2, amount=10, **kw):
    return transfer(id, debit_account_id=dr, credit_account_id=cr, amount=amount, **kw)


def balances(h, id):
    row = h.lookup_accounts([id])[0]
    return tuple(
        types.u128_get(row, f)
        for f in ("debits_pending", "debits_posted", "credits_pending", "credits_posted")
    )


def test_ok_posted(h):
    assert h.create_transfers([t(100)]) == []
    assert balances(h, 1) == (0, 10, 0, 0)
    assert balances(h, 2) == (0, 0, 0, 10)
    row = h.lookup_transfers([100])[0]
    assert types.u128_get(row, "amount") == 10
    assert int(row["timestamp"]) > 0


def test_validation_ladder(h):
    cases = [
        (t(1, flags=1 << 9), CTR.reserved_flag),
        (t(0), CTR.id_must_not_be_zero),
        (t(MAX), CTR.id_must_not_be_int_max),
        (t(1, dr=0), CTR.debit_account_id_must_not_be_zero),
        (t(1, dr=MAX), CTR.debit_account_id_must_not_be_int_max),
        (t(1, cr=0), CTR.credit_account_id_must_not_be_zero),
        (t(1, cr=MAX), CTR.credit_account_id_must_not_be_int_max),
        (t(1, dr=1, cr=1), CTR.accounts_must_be_different),
        (t(1, pending_id=5), CTR.pending_id_must_be_zero),
        (t(1, timeout=5), CTR.timeout_reserved_for_pending_transfer),
        (t(1, amount=0), CTR.amount_must_not_be_zero),
        (t(1, ledger=0), CTR.ledger_must_not_be_zero),
        (t(1, code=0), CTR.code_must_not_be_zero),
        (t(1, dr=99), CTR.debit_account_not_found),
        (t(1, cr=99), CTR.credit_account_not_found),
        (t(1, cr=3), CTR.accounts_must_have_the_same_ledger),
        (t(1, ledger=2), CTR.transfer_must_have_the_same_ledger_as_accounts),
    ]
    for row, expected in cases:
        assert h.create_transfers([row]) == [(0, expected)], expected


def test_timestamp_must_be_zero(h):
    assert h.create_transfers([t(1, timestamp=1)]) == [(0, CTR.timestamp_must_be_zero)]


def test_exists_ladder(h):
    base = dict(
        amount=10, user_data_128=1, user_data_64=2, user_data_32=3, code=5
    )
    assert h.create_transfers([t(100, **base)]) == []
    cases = [
        (t(100, flags=TF.pending, timeout=1, **base), CTR.exists_with_different_flags),
        (
            transfer(
                100, debit_account_id=2, credit_account_id=1, amount=10,
                user_data_128=1, user_data_64=2, user_data_32=3, code=5,
            ),
            CTR.exists_with_different_debit_account_id,
        ),
        (t(100, cr=4, **base), CTR.exists_with_different_credit_account_id),
        (t(100, **{**base, "amount": 11}), CTR.exists_with_different_amount),
        (
            t(100, **{**base, "user_data_128": 9}),
            CTR.exists_with_different_user_data_128,
        ),
        (
            t(100, **{**base, "user_data_64": 9}),
            CTR.exists_with_different_user_data_64,
        ),
        (
            t(100, **{**base, "user_data_32": 9}),
            CTR.exists_with_different_user_data_32,
        ),
        (t(100, **{**base, "code": 9}), CTR.exists_with_different_code),
        (t(100, **base), CTR.exists),
    ]
    for row, expected in cases:
        assert h.create_transfers([row]) == [(0, expected)], expected
    # Balances unchanged by all the exists probes.
    assert balances(h, 1) == (0, 10, 0, 0)


def test_exists_with_different_timeout(h):
    assert h.create_transfers([t(100, flags=TF.pending, timeout=5)]) == []
    assert h.create_transfers([t(100, flags=TF.pending, timeout=6)]) == [
        (0, CTR.exists_with_different_timeout)
    ]


def test_overflow_codes(h):
    big = MAX - 5
    assert h.create_transfers([t(100, amount=big)]) == []
    assert balances(h, 1) == (0, big, 0, 0)
    # debits_posted would overflow.
    assert h.create_transfers([t(101, amount=10)]) == [
        (0, CTR.overflows_debits_posted)
    ]
    # Pending-side overflow: use fresh accounts.
    assert h.create_accounts([account(10), account(11), account(12)]) == []
    assert h.create_transfers(
        [t(102, dr=10, cr=11, amount=big, flags=TF.pending)]
    ) == []
    assert h.create_transfers(
        [t(103, dr=10, cr=12, amount=10, flags=TF.pending)]
    ) == [(0, CTR.overflows_debits_pending)]
    assert h.create_transfers(
        [t(104, dr=12, cr=11, amount=10, flags=TF.pending)]
    ) == [(0, CTR.overflows_credits_pending)]
    # overflows_debits: pending + posted + amount > u128 max.
    assert h.create_accounts([account(13), account(14)]) == []
    assert h.create_transfers([t(105, dr=13, cr=14, amount=big, flags=TF.pending)]) == []
    assert h.create_transfers([t(106, dr=13, cr=14, amount=4)]) == []
    assert h.create_transfers([t(107, dr=13, cr=14, amount=2)]) == [
        (0, CTR.overflows_debits)
    ]


def test_overflows_timeout(h):
    # timestamp + timeout_ns must fit u64 (reference:
    # src/state_machine.zig:1545); needs a wall clock near u64 max.
    late = types.U64_MAX - 1_500_000_000
    assert h.create_transfers(
        [t(100, flags=TF.pending, timeout=2)], realtime=late
    ) == [(0, CTR.overflows_timeout)]
    assert h.create_transfers([t(101, flags=TF.pending, timeout=1)]) == []


def test_exceeds_credits_and_debits(h):
    assert h.create_accounts(
        [
            account(20, flags=AF.debits_must_not_exceed_credits),
            account(21, flags=AF.credits_must_not_exceed_debits),
            account(22),
        ]
    ) == []
    # Fund account 20 with 50 credits.
    assert h.create_transfers([t(100, dr=22, cr=20, amount=50)]) == []
    assert h.create_transfers([t(101, dr=20, cr=22, amount=51)]) == [
        (0, CTR.exceeds_credits)
    ]
    assert h.create_transfers([t(102, dr=20, cr=22, amount=50)]) == []
    # account 21: credits must not exceed debits (has 0 debits).
    assert h.create_transfers([t(103, dr=22, cr=21, amount=1)]) == [
        (0, CTR.exceeds_debits)
    ]


def test_balancing_debit(h):
    assert h.create_accounts(
        [account(30, flags=AF.debits_must_not_exceed_credits), account(31)]
    ) == []
    assert h.create_transfers([t(100, dr=31, cr=30, amount=40)]) == []
    # balancing_debit clamps the amount to what's available (40).
    assert h.create_transfers(
        [t(101, dr=30, cr=31, amount=100, flags=TF.balancing_debit)]
    ) == []
    row = h.lookup_transfers([101])[0]
    assert types.u128_get(row, "amount") == 40
    assert balances(h, 30) == (0, 40, 0, 40)
    # Nothing left -> exceeds_credits.
    assert h.create_transfers(
        [t(102, dr=30, cr=31, amount=1, flags=TF.balancing_debit)]
    ) == [(0, CTR.exceeds_credits)]
    # amount=0 with balancing = "transfer as much as possible".
    assert h.create_transfers([t(103, dr=31, cr=30, amount=5)]) == []
    assert h.create_transfers(
        [t(104, dr=30, cr=31, amount=0, flags=TF.balancing_debit)]
    ) == []
    row = h.lookup_transfers([104])[0]
    assert types.u128_get(row, "amount") == 5


def test_balancing_credit(h):
    assert h.create_accounts(
        [account(40, flags=AF.credits_must_not_exceed_debits), account(41)]
    ) == []
    assert h.create_transfers([t(100, dr=40, cr=41, amount=30)]) == []
    assert h.create_transfers(
        [t(101, dr=41, cr=40, amount=100, flags=TF.balancing_credit)]
    ) == []
    row = h.lookup_transfers([101])[0]
    assert types.u128_get(row, "amount") == 30
    assert h.create_transfers(
        [t(102, dr=41, cr=40, amount=1, flags=TF.balancing_credit)]
    ) == [(0, CTR.exceeds_debits)]


def test_linked_chain_rollback_restores_balances(h):
    rows = [
        t(100, amount=10, flags=TF.linked),
        t(101, amount=20, flags=TF.linked),
        t(0),  # id_must_not_be_zero breaks the chain
    ]
    assert h.create_transfers(rows) == [
        (0, CTR.linked_event_failed),
        (1, CTR.linked_event_failed),
        (2, CTR.id_must_not_be_zero),
    ]
    assert balances(h, 1) == (0, 0, 0, 0)
    assert balances(h, 2) == (0, 0, 0, 0)
    assert len(h.lookup_transfers([100, 101])) == 0


def test_chain_sees_prior_chain_events(h):
    # Second event in the chain duplicates the first -> exists ladder.
    rows = [
        t(100, flags=TF.linked),
        t(100),
    ]
    results = h.create_transfers(rows)
    assert results == [
        (0, CTR.linked_event_failed),
        (1, CTR.exists_with_different_flags),
    ]


def test_batch_sees_earlier_events(h):
    # Same-account transfers accumulate within one batch.
    rows = [t(100, amount=10), t(101, amount=20)]
    assert h.create_transfers(rows) == []
    assert balances(h, 1) == (0, 30, 0, 0)


def test_limit_interacts_within_batch(h):
    assert h.create_accounts(
        [account(50, flags=AF.debits_must_not_exceed_credits), account(51)]
    ) == []
    # Fund 50 with 25, then two debits of 20: the second must fail only
    # because the first applied.
    rows = [
        t(100, dr=51, cr=50, amount=25),
        t(101, dr=50, cr=51, amount=20),
        t(102, dr=50, cr=51, amount=20),
    ]
    assert h.create_transfers(rows) == [(2, CTR.exceeds_credits)]
    assert balances(h, 50) == (0, 20, 0, 25)


def test_results_are_sparse_failures_only(h):
    sm = h.sm
    out = h.submit(
        types.Operation.create_transfers,
        np.stack([t(100), t(0), t(101)]).tobytes(),
    )
    arr = np.frombuffer(out, dtype=types.CREATE_RESULT_DTYPE)
    assert len(arr) == 1
    assert int(arr[0]["index"]) == 1


def test_overflow_beats_overflows_timeout(h):
    # A balance overflow outranks overflows_timeout in the precedence
    # ladder (reference: src/state_machine.zig:1531-1545) even when the
    # event also has an overflowing timeout — regression test for the
    # fast path mis-ranking it.
    big = MAX - 2
    assert h.create_accounts([account(20), account(21)]) == []
    late = types.U64_MAX - 100_000_000_000
    assert h.create_transfers(
        [t(200, dr=20, cr=21, amount=big, flags=TF.pending)], realtime=late
    ) == []
    assert h.create_transfers(
        [t(201, dr=20, cr=21, amount=5, timeout=400, flags=TF.pending)]
    ) == [(0, CTR.overflows_debits_pending)]
