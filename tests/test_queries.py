"""get_account_transfers / get_account_balances / lookup queries.

reference: src/state_machine.zig:786-1008 (filter validation + scans),
:1346-1419 (execution), :1806-1841 (historical balances).
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.testing.harness import SingleNodeHarness, account, transfer

AF = types.AccountFlags
FF = types.AccountFilterFlags
TF = types.TransferFlags


def account_filter(
    account_id,
    *,
    timestamp_min=0,
    timestamp_max=0,
    limit=8190,
    flags=FF.debits | FF.credits,
    reserved=b"\x00" * 24,
) -> bytes:
    row = np.zeros(1, dtype=types.ACCOUNT_FILTER_DTYPE)[0]
    types.u128_set(row, "account_id", account_id)
    row["timestamp_min"] = timestamp_min
    row["timestamp_max"] = timestamp_max
    row["limit"] = limit
    row["flags"] = flags
    row["reserved"] = np.frombuffer(reserved, dtype=np.uint8)
    return row.tobytes()


@pytest.fixture
def h(sm):
    h = SingleNodeHarness(sm)
    assert (
        h.create_accounts(
            [account(1, flags=AF.history), account(2), account(3, flags=AF.history)]
        )
        == []
    )
    # 1 -> 2 (x2), 2 -> 1, 1 -> 3
    assert (
        h.create_transfers(
            [
                transfer(100, debit_account_id=1, credit_account_id=2, amount=10),
                transfer(101, debit_account_id=1, credit_account_id=2, amount=20),
                transfer(102, debit_account_id=2, credit_account_id=1, amount=5),
                transfer(103, debit_account_id=1, credit_account_id=3, amount=1),
            ]
        )
        == []
    )
    return h


def get_transfers(h, filter_bytes):
    out = h.submit(types.Operation.get_account_transfers, filter_bytes)
    return np.frombuffer(out, dtype=types.TRANSFER_DTYPE)


def get_balances(h, filter_bytes):
    out = h.submit(types.Operation.get_account_balances, filter_bytes)
    return np.frombuffer(out, dtype=types.ACCOUNT_BALANCE_DTYPE)


def tids(rows):
    return [types.u128_get(r, "id") for r in rows]


def test_get_account_transfers_both_sides(h):
    rows = get_transfers(h, account_filter(1))
    assert tids(rows) == [100, 101, 102, 103]


def test_get_account_transfers_debits_only(h):
    rows = get_transfers(h, account_filter(1, flags=FF.debits))
    assert tids(rows) == [100, 101, 103]


def test_get_account_transfers_credits_only(h):
    rows = get_transfers(h, account_filter(1, flags=FF.credits))
    assert tids(rows) == [102]


def test_get_account_transfers_reversed(h):
    rows = get_transfers(h, account_filter(1, flags=FF.debits | FF.credits | FF.reversed))
    assert tids(rows) == [103, 102, 101, 100]


def test_get_account_transfers_limit(h):
    rows = get_transfers(h, account_filter(1, limit=2))
    assert tids(rows) == [100, 101]


def test_get_account_transfers_timestamp_range(h):
    all_rows = get_transfers(h, account_filter(1))
    ts = [int(r["timestamp"]) for r in all_rows]
    rows = get_transfers(h, account_filter(1, timestamp_min=ts[1], timestamp_max=ts[2]))
    assert tids(rows) == [101, 102]


def test_get_account_transfers_invalid_filters(h):
    # reference: src/state_machine.zig:934-944
    invalid = [
        account_filter(0),
        account_filter(types.U128_MAX),
        account_filter(1, timestamp_min=types.U64_MAX),
        account_filter(1, timestamp_max=types.U64_MAX),
        account_filter(1, timestamp_min=5, timestamp_max=4),
        account_filter(1, limit=0),
        account_filter(1, flags=0),
        account_filter(1, flags=1 << 30),
        account_filter(1, reserved=b"\x01" + b"\x00" * 23),
    ]
    for f in invalid:
        assert len(get_transfers(h, f)) == 0


def test_get_account_balances_history(h):
    rows = get_balances(h, account_filter(1))
    assert len(rows) == 4
    # Account 1 debits: 10, 30, 30 (credit of 5 on other side), 31.
    posted = [types.u128_get(r, "debits_posted") for r in rows]
    assert posted == [10, 30, 30, 31]
    credits = [types.u128_get(r, "credits_posted") for r in rows]
    assert credits == [0, 0, 5, 5]


def test_get_account_balances_non_history_account(h):
    # Account 2 has no history flag -> empty reply.
    assert len(get_balances(h, account_filter(2))) == 0


def test_get_account_balances_missing_account(h):
    assert len(get_balances(h, account_filter(99))) == 0


def test_get_account_balances_other_side_zeroed(h):
    # Transfer 103 credited account 3 (history); its balance row must
    # reflect account 3's side.
    rows = get_balances(h, account_filter(3))
    assert len(rows) == 1
    assert types.u128_get(rows[0], "credits_posted") == 1
    assert types.u128_get(rows[0], "debits_posted") == 0


def test_lookup_missing_are_omitted(h):
    found = h.lookup_accounts([1, 99, 2])
    assert len(found) == 2
    found_t = h.lookup_transfers([100, 999])
    assert len(found_t) == 1


def test_rollback_does_not_leak_history(h):
    before = h.sm.history_count
    assert h.create_transfers(
        [
            transfer(
                200, debit_account_id=1, credit_account_id=2, amount=1,
                flags=TF.linked,
            ),
            transfer(0),
        ]
    ) == [
        (0, types.CreateTransferResult.linked_event_failed),
        (1, types.CreateTransferResult.id_must_not_be_zero),
    ]
    assert h.sm.history_count == before
    assert tids(get_transfers(h, account_filter(1))) == [100, 101, 102, 103]
