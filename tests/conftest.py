"""Test configuration: force a virtual 8-device CPU mesh.

The ambient environment routes JAX at the axon TPU tunnel (a single
shared chip) via sitecustomize, which both sets the jax_platforms
config programmatically and registers a PJRT plugin whose discovery
blocks when the tunnel is busy. Tests must never touch it — they run
on a virtual 8-device CPU mesh instead — so we override the config and
unregister the plugin factory before any backend initializes.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
# Shrink the device-kernel event bucket: the semantic kernels' one-hot
# matmuls at the production bucket (8192) are far too slow on the CPU
# backend.  Production size is exercised by the tpu-marked tests.
os.environ.setdefault("TB_DEV_B", "512")

from tigerbeetle_tpu.jaxenv import pin_cpu_backend

pin_cpu_backend()

import pytest


@pytest.fixture(params=["cpu", "tpu"])
def sm(request):
    """Both state-machine implementations, for differential coverage."""
    if request.param == "tpu":
        from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine

        return TpuStateMachine()
    from tigerbeetle_tpu.state_machine import CpuStateMachine

    return CpuStateMachine()
