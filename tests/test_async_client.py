"""End-to-end test of the async packet-based C client (tb_async.cpp).

Spawns the Python replica server in-process, compiles the C test
program (native/test_async_client.c) against the native runtime
library, and runs it as a real foreign-language client over TCP —
the same shape as the reference's per-language client integration
tests (reference: src/integration_tests.zig, src/scripts/ci.zig
spawning a server per language client).
"""

import os
import shutil
import subprocess
import threading

import pytest

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu.runtime.native import native_available
from tigerbeetle_tpu.state_machine import CpuStateMachine

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native runtime not built"
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")

CLUSTER = 3


class ServerFixture:
    def __init__(self, tmp_path):
        from tigerbeetle_tpu.runtime.server import (
            ReplicaServer,
            format_data_file,
        )

        config = cfg.TEST_MIN
        path = str(tmp_path / "data.tigerbeetle")
        format_data_file(path, cluster=CLUSTER, config=config)
        self.server = ReplicaServer(
            path, cluster=CLUSTER, addresses=["127.0.0.1:0"],
            replica_index=0,
            state_machine_factory=lambda: CpuStateMachine(config),
            config=config,
        )
        self.port = self.server.port
        self._stop = False
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self):
        while not self._stop:
            self.server.poll_once(timeout_ms=1)

    def close(self):
        self._stop = True
        self.thread.join(timeout=5)
        self.server.close()


@pytest.fixture
def server(tmp_path):
    f = ServerFixture(tmp_path)
    yield f
    f.close()


@pytest.fixture(scope="module")
def test_binary(tmp_path_factory):
    cc = shutil.which("gcc") or shutil.which("cc") or shutil.which("g++")
    if cc is None:
        pytest.skip("no C compiler")
    out = str(tmp_path_factory.mktemp("cbin") / "test_async_client")
    subprocess.run(
        [
            cc, "-O2", "-o", out,
            os.path.join(NATIVE, "test_async_client.c"),
            "-I", NATIVE,
            "-L", NATIVE, "-ltb_runtime",
            f"-Wl,-rpath,{NATIVE}",
            "-pthread",
        ],
        check=True, capture_output=True,
    )
    return out


def test_async_c_client_end_to_end(server, test_binary):
    proc = subprocess.run(
        [test_binary, str(server.port)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, (
        f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
    )
    assert "out-of-order completion verified" in proc.stdout
