"""Wire-contract conversation replay (VERDICT r3 #5).

clients/fixtures/conversation.json is the cross-language contract: a
full recorded session (register -> creates incl. a failure -> a
RETRANSMIT -> lookups -> query) with exact request/reply frame bytes.
Every language client asserts its encoder emits exactly these request
frames; THIS test replays the recorded request stream against a live
in-process TCP server and asserts the reply bytes — so the wire
behavior every client depends on is verified in this container with no
foreign toolchain, zero skips (reference: src/scripts/ci.zig:20-62
runs each client against a spawned server the same way).
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "clients", "fixtures", "conversation.json")

HEADER_SIZE = 256
PINNED_NS = 1_000_000_000
CLUSTER = 3


@pytest.fixture()
def pinned_time(monkeypatch):
    # Pin the wall clock (timestamps derive from event counts) and the
    # monotonic clock (the tick cadence never fires, so no pulse op
    # lands at a scheduling-dependent position).
    monkeypatch.setattr(time, "time_ns", lambda: PINNED_NS)
    monkeypatch.setattr(time, "monotonic_ns", lambda: 0)


def _recv_frame(sock, buf):
    while True:
        if len(buf) >= HEADER_SIZE:
            size = int.from_bytes(buf[144:148], "little")
            if len(buf) >= size:
                return buf[:size], buf[size:]
        chunk = sock.recv(1 << 20)
        assert chunk, "server closed mid-conversation"
        buf += chunk


@pytest.mark.parametrize("tenant_qos", ["0", "1"])
def test_conversation_replay_byte_exact(tmp_path, pinned_time,
                                        monkeypatch, tenant_qos):
    """Replies must match the recorded pre-QoS contract in BOTH
    tenant-QoS arms (round 16 differential): TB_TENANT_QOS=0 pins the
    legacy single-queue path, and QoS ON under non-overload load must
    be bit-identical to it (strict-FIFO drain outside an overload
    episode)."""
    from tigerbeetle_tpu.runtime.server import (
        ReplicaServer, format_data_file,
    )
    from tigerbeetle_tpu.state_machine import CpuStateMachine

    monkeypatch.setenv("TB_TENANT_QOS", tenant_qos)
    with open(FIXTURE) as fh:
        steps = json.load(fh)
    assert len(steps) >= 7
    assert any(s["retransmit_of"] for s in steps), "transcript lacks a retransmit"

    path = str(tmp_path / "0_0.tigerbeetle")
    format_data_file(path, cluster=CLUSTER, replica_index=0, replica_count=1)
    server = ReplicaServer(
        path, addresses=["127.0.0.1:0"], replica_index=0,
        state_machine_factory=CpuStateMachine,
    )
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            server.poll_once(10)

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    try:
        sock = socket.create_connection(("127.0.0.1", server.port), timeout=30)
        sock.settimeout(30)
        buf = b""
        for step in steps:
            sock.sendall(bytes.fromhex(step["request_hex"]))
            reply, buf = _recv_frame(sock, buf)
            assert reply == bytes.fromhex(step["reply_hex"]), (
                f"step {step['name']}: reply bytes diverge from the "
                f"recorded contract"
            )
        sock.close()
    finally:
        stop.set()
        t.join(timeout=5)
        server.close()


def test_conversation_fixture_is_current(tmp_path, pinned_time):
    """Regenerating the transcript reproduces the checked-in fixture
    byte-for-byte (stale fixtures after a wire change fail loudly)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "gen_conversation",
        os.path.join(REPO, "clients", "fixtures", "gen_conversation.py"),
    )
    gen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gen)
    regenerated = gen.generate()
    with open(FIXTURE) as fh:
        checked_in = json.load(fh)
    assert regenerated == checked_in, (
        "conversation.json is stale — regenerate via "
        "python clients/fixtures/gen_conversation.py"
    )


def test_conversation_reply_bodies_decode(pinned_time):
    """The transcript's reply BODIES decode to the expected results
    (the languages' decoders parse these same bytes)."""
    from tigerbeetle_tpu import types

    with open(FIXTURE) as fh:
        steps = {s["name"]: s for s in json.load(fh)}

    def body(name):
        return bytes.fromhex(steps[name]["reply_hex"])[HEADER_SIZE:]

    assert body("register") == b""
    assert body("create_accounts") == b""  # all ok
    res = np.frombuffer(body("create_transfers"), types.CREATE_RESULT_DTYPE)
    assert len(res) == 1 and res[0]["index"] == 1
    assert res[0]["result"] == int(
        types.CreateTransferResult.accounts_must_be_different
    )
    assert body("create_transfers_retransmit") == body("create_transfers")
    accts = np.frombuffer(body("lookup_accounts"), types.ACCOUNT_DTYPE)
    assert [int(a["id_lo"]) for a in accts] == [9001, 9002]
    assert int(accts[0]["debits_posted_lo"]) == 140
    assert int(accts[1]["credits_posted_lo"]) == 140
    xfers = np.frombuffer(body("lookup_transfers"), types.TRANSFER_DTYPE)
    assert [int(x["id_lo"]) for x in xfers] == [501, 503]  # 502 failed
    q = np.frombuffer(body("get_account_transfers"), types.TRANSFER_DTYPE)
    assert [int(x["id_lo"]) for x in q] == [501, 503]
    # r5 filter/balance surface (VERDICT r4 #8): the history account's
    # balance snapshots decode as 128-byte AccountBalance rows — the
    # bytes every client's AccountBalanceBatch decoder parses.
    assert body("create_accounts_history") == b""
    assert body("create_transfers_history") == b""
    bal = np.frombuffer(
        body("get_account_balances"), types.ACCOUNT_BALANCE_DTYPE
    )
    assert len(bal) == 1
    assert int(bal[0]["credits_posted_lo"]) == 7
    assert int(bal[0]["timestamp"]) != 0
