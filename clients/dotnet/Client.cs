// Blocking pure-C# client session for the tigerbeetle_tpu cluster —
// the TCP counterpart of the reference's dotnet client
// (src/clients/dotnet), minus P/Invoke: like the Go/TS/Java clients
// here it speaks the checksummed wire protocol directly.  One
// registered VSR session, one request in flight; retransmission under
// the same request number is safe (server-side at-most-once dedupe).
using System;
using System.Buffers.Binary;
using System.IO;
using System.Net.Sockets;

namespace TigerBeetle;

public sealed class Client : IDisposable
{
    /// Most events per request (1 MiB message - 256 B header,
    /// 128 B/event; reference: src/state_machine.zig:75-81).
    public const int BatchMax = (Wire.MessageSizeMax - Wire.HeaderSize) / 128;

    // Operation codes from the generated enum (tigerbeetle_tpu/
    // types.py Operation is the single source of truth).
    internal const byte OpCreateAccounts = (byte)Operation.CreateAccounts;
    internal const byte OpCreateTransfers = (byte)Operation.CreateTransfers;
    internal const byte OpLookupAccounts = (byte)Operation.LookupAccounts;
    internal const byte OpLookupTransfers = (byte)Operation.LookupTransfers;
    internal const byte OpGetAccountTransfers =
        (byte)Operation.GetAccountTransfers;
    internal const byte OpGetAccountBalances =
        (byte)Operation.GetAccountBalances;

    private readonly TcpClient _socket;
    private readonly NetworkStream _stream;
    private readonly ulong _cluster;
    private readonly ulong _clientLo;
    private readonly ulong _clientHi;
    private uint _requestNumber;
    private bool _registered;
    private bool _evicted;
    private byte[] _recv = new byte[1 << 16];
    private int _recvLen;

    public int TimeoutMillis { get; set; } = 30_000;
    private const int RetransmitMillis = 1_000;

    public Client(string host, int port, ulong cluster)
        : this(host, port, cluster,
               (ulong)Random.Shared.NextInt64() | 1UL, 0UL) { }

    public Client(string host, int port, ulong cluster, ulong clientLo,
                  ulong clientHi)
    {
        _socket = new TcpClient();
        _socket.Connect(host, port);
        _socket.NoDelay = true;
        _stream = _socket.GetStream();
        _cluster = cluster;
        _clientLo = clientLo;
        _clientHi = clientHi;
    }

    private bool _closed;

    public void Dispose()
    {
        _closed = true;
        _socket.Dispose();
    }

    public CreateResultBatch CreateAccounts(AccountBatch batch) =>
        new(Request(OpCreateAccounts, batch.ToArray()));

    public CreateResultBatch CreateTransfers(TransferBatch batch) =>
        new(Request(OpCreateTransfers, batch.ToArray()));

    public AccountBatch LookupAccounts(IdBatch ids) =>
        new(Request(OpLookupAccounts, ids.ToArray()));

    public TransferBatch LookupTransfers(IdBatch ids) =>
        new(Request(OpLookupTransfers, ids.ToArray()));

    /// Transfers touching the filter's account, timestamp-ordered
    /// (reference: src/state_machine.zig:786-1008).
    public TransferBatch GetAccountTransfers(AccountFilter filter) =>
        new(Request(OpGetAccountTransfers, filter.ToArray()));

    /// Historical balance snapshots (requires the account's history
    /// flag).
    public AccountBalanceBatch GetAccountBalances(AccountFilter filter) =>
        new(Request(OpGetAccountBalances, filter.ToArray()));

    /// Raw request: registers on first use, returns the reply body.
    public byte[] Request(byte operation, byte[] body)
    {
        lock (this)
        {
            if (!_registered)
            {
                Roundtrip(Wire.OpRegister, 0, Array.Empty<byte>());
                _registered = true;
            }
            _requestNumber++;
            return Roundtrip(operation, _requestNumber, body);
        }
    }

    private byte[] Roundtrip(byte operation, uint requestNumber, byte[] body)
    {
        if (_closed) throw new ClientClosedException("client is closed");
        if (_evicted) throw new ClientEvictedException("session evicted");
        var msg = Wire.BuildRequest(
            _cluster, _clientLo, _clientHi, requestNumber, operation, body);
        long deadline = Environment.TickCount64 + TimeoutMillis;
        while (true)
        {
            long now = Environment.TickCount64;
            if (now > deadline)
                throw new RequestTimeoutException(
                    $"request {requestNumber} timed out after "
                    + $"{TimeoutMillis}ms");
            // Clamp >= 1: a 0 ReceiveTimeout means INFINITE in .NET.
            _socket.ReceiveTimeout =
                (int)Math.Max(1, Math.Min(RetransmitMillis, deadline - now));
            _stream.Write(msg);
            while (true)
            {
                byte[] reply;
                try
                {
                    reply = ReadMessage();
                }
                catch (IOException e) when (
                    e.InnerException is SocketException se
                    && se.SocketErrorCode == SocketError.TimedOut)
                {
                    break; // retransmit under the same request number
                }
                byte command = reply[Wire.OffCommand];
                if (command == Wire.CmdEviction)
                {
                    _evicted = true;
                    throw new ClientEvictedException("session evicted");
                }
                if (command != Wire.CmdReply) continue;
                uint got = BinaryPrimitives.ReadUInt32LittleEndian(
                    reply.AsSpan(Wire.OffRequest));
                if (got != requestNumber) continue; // stale duplicate
                return reply[Wire.HeaderSize..];
            }
        }
    }

    private byte[] ReadMessage()
    {
        while (true)
        {
            if (_recvLen >= Wire.HeaderSize)
            {
                int size = (int)BinaryPrimitives.ReadUInt32LittleEndian(
                    _recv.AsSpan(Wire.OffSize));
                if (size < Wire.HeaderSize
                    || size > Wire.MessageSizeMax + Wire.HeaderSize)
                    throw new InvalidFrameException($"bad frame size {size}");
                if (_recvLen >= size)
                {
                    var msg = _recv.AsSpan(0, size).ToArray();
                    _recv.AsSpan(size, _recvLen - size).CopyTo(_recv);
                    _recvLen -= size;
                    Wire.VerifyMessage(msg);
                    return msg;
                }
            }
            if (_recvLen == _recv.Length)
                Array.Resize(ref _recv, _recv.Length * 2);
            int n = _stream.Read(_recv, _recvLen, _recv.Length - _recvLen);
            if (n <= 0) throw new IOException("connection closed");
            _recvLen += n;
        }
    }
}
