// Typed client failure taxonomy (the reference's dotnet exception
// classes — src/clients/dotnet/TigerBeetle/Exceptions.cs).  All
// extend IOException so pre-taxonomy call sites keep compiling;
// catch the subtypes to distinguish retryable timeouts from fatal
// session states.
using System.IO;

namespace TigerBeetle;

public class ClientException : IOException
{
    public ClientException(string message) : base(message) { }
}

/// The per-request deadline elapsed before a reply arrived.  The
/// request may still commit server-side; retrying under the same
/// session observes the stored reply via at-most-once dedupe.
public sealed class RequestTimeoutException : ClientException
{
    public RequestTimeoutException(string message) : base(message) { }
}

/// The cluster evicted this session (too many live clients).  The
/// session is dead; build a NEW Client to continue.
public sealed class ClientEvictedException : ClientException
{
    public ClientEvictedException(string message) : base(message) { }
}

/// Request submitted after Dispose() — programming error.
public sealed class ClientClosedException : ClientException
{
    public ClientClosedException(string message) : base(message) { }
}

/// The peer sent a malformed frame (bad size word or checksum).
public sealed class InvalidFrameException : ClientException
{
    public InvalidFrameException(string message) : base(message) { }
}
