// In-process test double with the Client's API shape: every request
// ECHOES its event payload back as the reply body instead of touching
// a cluster (the reference's echo client —
// src/clients/c/tb_client/echo_client.zig:1-20).  CreateAccounts /
// CreateTransfers therefore report zero failures, and the typed echo
// helpers hand the submitted batch back through the reply-side
// decoder for marshaling round-trip tests.
using System;

namespace TigerBeetle;

public sealed class EchoClient : IDisposable
{
    private bool _closed;

    public void Dispose() => _closed = true;

    /// Echo: the reply body IS the request body.
    public byte[] Request(byte operation, byte[] body)
    {
        lock (this)
        {
            if (_closed)
                throw new ClientClosedException("client is closed");
            if (body.Length > Wire.MessageSizeMax - Wire.HeaderSize)
                throw new InvalidFrameException("body exceeds message size");
            return (byte[])body.Clone();
        }
    }

    /// create_accounts double: no failures (reply decodes empty).
    public CreateResultBatch CreateAccounts(AccountBatch batch)
    {
        Request(Client.OpCreateAccounts, batch.ToArray());
        return new CreateResultBatch(Array.Empty<byte>());
    }

    /// create_transfers double: no failures (reply decodes empty).
    public CreateResultBatch CreateTransfers(TransferBatch batch)
    {
        Request(Client.OpCreateTransfers, batch.ToArray());
        return new CreateResultBatch(Array.Empty<byte>());
    }

    /// Marshaling round-trip: encode, echo, decode as accounts.
    public AccountBatch EchoAccounts(AccountBatch batch) =>
        new(Request(Client.OpLookupAccounts, batch.ToArray()));

    /// Marshaling round-trip: encode, echo, decode as transfers.
    public TransferBatch EchoTransfers(TransferBatch batch) =>
        new(Request(Client.OpLookupTransfers, batch.ToArray()));
}
