// Wire protocol: the 256-byte VSR message header and checksums.
//
// Layout mirrors tigerbeetle_tpu/vsr/wire.py HEADER_DTYPE (a
// re-design of the reference's per-command header unions into one
// flat little-endian layout — reference:
// src/vsr/message_header.zig:17-103).  Checksums are SHA-256
// truncated to 128 bits: `checksum` covers header bytes [16, 256),
// `checksum_body` covers the body; both are verified before any
// message is trusted.  Byte-identical to the Go/TS/Java clients
// (clients/fixtures/frames.json).
using System;
using System.Buffers.Binary;
using System.Security.Cryptography;

namespace TigerBeetle;

internal static class Wire
{
    public const int HeaderSize = 256;
    public const int MessageSizeMax = 1 << 20;

    public const int OffChecksum = 0;
    public const int OffChecksumBody = 16;
    public const int OffClient = 48;
    public const int OffCluster = 64;
    public const int OffRequest = 112;
    public const int OffSize = 144;
    public const int OffCommand = 153;
    public const int OffOperation = 154;
    public const int OffVersion = 155;

    public const byte CmdRequest = 5;
    public const byte CmdReply = 8;
    public const byte CmdEviction = 18;

    public const byte OpRegister = 2;

    public const byte WireVersion = 1;

    internal static byte[] Checksum128(ReadOnlySpan<byte> data)
    {
        Span<byte> sum = stackalloc byte[32];
        SHA256.HashData(data, sum);
        return sum[..16].ToArray();
    }

    /// Frames one request: header + body, checksums finalized.
    internal static byte[] BuildRequest(
        ulong cluster, ulong clientLo, ulong clientHi, uint requestNumber,
        byte operation, ReadOnlySpan<byte> body)
    {
        var msg = new byte[HeaderSize + body.Length];
        body.CopyTo(msg.AsSpan(HeaderSize));
        var h = msg.AsSpan(0, HeaderSize);
        BinaryPrimitives.WriteUInt64LittleEndian(h[OffClient..], clientLo);
        BinaryPrimitives.WriteUInt64LittleEndian(h[(OffClient + 8)..], clientHi);
        BinaryPrimitives.WriteUInt64LittleEndian(h[OffCluster..], cluster);
        BinaryPrimitives.WriteUInt32LittleEndian(h[OffRequest..], requestNumber);
        BinaryPrimitives.WriteUInt32LittleEndian(h[OffSize..], (uint)msg.Length);
        h[OffCommand] = CmdRequest;
        h[OffOperation] = operation;
        h[OffVersion] = WireVersion;

        Checksum128(msg.AsSpan(HeaderSize)).CopyTo(msg, OffChecksumBody);
        Checksum128(msg.AsSpan(16, HeaderSize - 16)).CopyTo(msg, OffChecksum);
        return msg;
    }

    /// Verifies both checksums of a framed message.
    internal static void VerifyMessage(ReadOnlySpan<byte> msg)
    {
        var head = Checksum128(msg.Slice(16, HeaderSize - 16));
        if (!msg.Slice(OffChecksum, 16).SequenceEqual(head))
            throw new InvalidOperationException("header checksum mismatch");
        var body = Checksum128(msg[HeaderSize..]);
        if (!msg.Slice(OffChecksumBody, 16).SequenceEqual(body))
            throw new InvalidOperationException("body checksum mismatch");
    }
}
