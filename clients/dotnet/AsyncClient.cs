// Asynchronous pipelined client: the packet-queue model of the
// reference's async API (reference: src/clients/dotnet/TigerBeetle/
// Client.cs async surface over src/clients/c/tb_client/packet.zig)
// on the pure-TCP session.
//
// Submissions enqueue PACKETS and return Tasks immediately; a worker
// thread drains the queue, COALESCING adjacent packets of the same
// batchable operation (create_accounts / create_transfers — the
// server's logical-batching surface, tigerbeetle_tpu/state_machine/
// demuxer.py) into one wire request up to BatchMax events, and on
// reply DEMUXES the result slices back to each packet's Task with
// indexes rebased to its sub-batch.  One wire request in flight, any
// number of packets queued — the reference's client pipeline.

using System;
using System.Collections.Generic;
using System.Threading;
using System.Threading.Tasks;

namespace TigerBeetle;

public sealed class AsyncClient : IDisposable
{
    private const int EventSize = 128;

    private readonly Client _client;
    private readonly Queue<Packet> _queue = new();
    private readonly object _lock = new();
    private readonly Thread _worker;
    private volatile bool _closed;

    private sealed class Packet
    {
        public readonly byte Operation;
        public readonly byte[] Body;
        public readonly TaskCompletionSource<byte[]> Completion =
            new(TaskCreationOptions.RunContinuationsAsynchronously);

        public Packet(byte operation, byte[] body)
        {
            Operation = operation;
            Body = body;
        }

        public int EventCount => Body.Length / EventSize;
    }

    public AsyncClient(string host, int port, ulong cluster)
    {
        _client = new Client(host, port, cluster);
        _worker = new Thread(DrainLoop) { IsBackground = true, Name = "tb-async-client" };
        _worker.Start();
    }

    public void Dispose()
    {
        _closed = true;
        lock (_lock) Monitor.PulseAll(_lock);
        _worker.Join(5_000);
        FailPending("client disposed");
        _client.Dispose();
    }

    private void FailPending(string why)
    {
        lock (_lock)
        {
            while (_queue.Count > 0)
            {
                _queue.Dequeue().Completion.SetException(
                    new ObjectDisposedException(nameof(AsyncClient), why));
            }
        }
    }

    public Task<CreateResultBatch> CreateAccountsAsync(AccountBatch batch) =>
        Submit(Client.OpCreateAccounts, batch.ToArray())
            .ContinueWith(t => new CreateResultBatch(t.Result));

    public Task<CreateResultBatch> CreateTransfersAsync(TransferBatch batch) =>
        Submit(Client.OpCreateTransfers, batch.ToArray())
            .ContinueWith(t => new CreateResultBatch(t.Result));

    public Task<AccountBatch> LookupAccountsAsync(IdBatch ids) =>
        Submit(Client.OpLookupAccounts, ids.ToArray())
            .ContinueWith(t => new AccountBatch(t.Result));

    public Task<TransferBatch> LookupTransfersAsync(IdBatch ids) =>
        Submit(Client.OpLookupTransfers, ids.ToArray())
            .ContinueWith(t => new TransferBatch(t.Result));

    /// <summary>Enqueue one packet; the Task completes when its
    /// (possibly coalesced) wire request's reply is demuxed.</summary>
    public Task<byte[]> Submit(byte operation, byte[] body)
    {
        var packet = new Packet(operation, body);
        lock (_lock)
        {
            // Re-check under the lock: a concurrent Dispose may have
            // already drained the queue and stopped the worker.
            if (_closed)
            {
                packet.Completion.SetException(
                    new ObjectDisposedException(nameof(AsyncClient)));
                return packet.Completion.Task;
            }
            _queue.Enqueue(packet);
            Monitor.PulseAll(_lock);
        }
        return packet.Completion.Task;
    }

    private static bool Batchable(byte operation) =>
        operation == Client.OpCreateAccounts
        || operation == Client.OpCreateTransfers;

    /// <summary>A packet whose FINAL event carries flags.linked has an
    /// open chain: coalescing another packet behind it would splice
    /// that packet's first events into the chain.  Both event types
    /// keep flags as a u16 at byte 118 of the 128-byte record.</summary>
    private static bool EndsWithOpenChain(byte[] body)
    {
        if (body.Length < EventSize) return false;
        int off = body.Length - EventSize + 118;
        int flags = body[off] | (body[off + 1] << 8);
        return (flags & 1) != 0;
    }

    private void DrainLoop()
    {
        while (true)
        {
            var group = new List<Packet>();
            lock (_lock)
            {
                while (_queue.Count == 0 && !_closed) Monitor.Wait(_lock);
                if (_queue.Count == 0) return; // closed and drained
                var head = _queue.Dequeue();
                group.Add(head);
                // Coalesce adjacent same-operation batchable packets
                // while the combined batch stays within BatchMax.
                if (Batchable(head.Operation))
                {
                    int events = head.EventCount;
                    while (_queue.Count > 0
                           && _queue.Peek().Operation == head.Operation
                           && !EndsWithOpenChain(group[^1].Body)
                           && events + _queue.Peek().EventCount
                               <= Client.BatchMax)
                    {
                        var next = _queue.Dequeue();
                        events += next.EventCount;
                        group.Add(next);
                    }
                }
            }
            RunGroup(group);
        }
    }

    private void RunGroup(List<Packet> group)
    {
        int total = 0;
        foreach (var p in group) total += p.Body.Length;
        var events = new byte[total];
        int at = 0;
        foreach (var p in group)
        {
            System.Buffer.BlockCopy(p.Body, 0, events, at, p.Body.Length);
            at += p.Body.Length;
        }
        byte[] reply;
        try
        {
            reply = _client.Request(group[0].Operation, events);
        }
        catch (Exception e)
        {
            foreach (var p in group) p.Completion.SetException(e);
            return;
        }
        if (group.Count == 1)
        {
            group[0].Completion.SetResult(reply);
            return;
        }
        var counts = new int[group.Count];
        for (int i = 0; i < group.Count; i++) counts[i] = group[i].EventCount;
        var slices = DemuxSlices(counts, reply);
        for (int i = 0; i < group.Count; i++)
        {
            group[i].Completion.SetResult(slices[i]);
        }
    }

    /// <summary>Split a coalesced create_* reply ({index u32, result
    /// u32} pairs sorted by index) into per-packet slices with rebased
    /// indexes — the client-side mirror of the server demuxer
    /// (reference: src/state_machine.zig:133-176 DemuxerType).  Pure
    /// function: asserted against clients/fixtures/demux.json.</summary>
    public static byte[][] DemuxSlices(int[] eventCounts, byte[] reply)
    {
        int n = reply.Length / 8;
        var output = new byte[eventCounts.Length][];
        int cursor = 0;  // next unread result pair
        int offset = 0;  // first event index of the current packet
        for (int k = 0; k < eventCounts.Length; k++)
        {
            int count = eventCounts[k];
            int start = cursor;
            while (cursor < n
                   && BitConverter.ToUInt32(reply, cursor * 8)
                       < (uint)(offset + count))
            {
                cursor++;
            }
            var slice = new byte[(cursor - start) * 8];
            for (int i = start; i < cursor; i++)
            {
                uint index = BitConverter.ToUInt32(reply, i * 8) - (uint)offset;
                uint result = BitConverter.ToUInt32(reply, i * 8 + 4);
                BitConverter.GetBytes(index).CopyTo(slice, (i - start) * 8);
                BitConverter.GetBytes(result).CopyTo(slice, (i - start) * 8 + 4);
            }
            offset += count;
            output[k] = slice;
        }
        return output;
    }
}
