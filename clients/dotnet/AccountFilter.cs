// Builder for the 64-byte AccountFilter wire record driving
// GetAccountTransfers / GetAccountBalances (tigerbeetle_tpu/types.py
// ACCOUNT_FILTER_DTYPE; reference: src/tigerbeetle.zig:288-322 and
// the generated dotnet AccountFilter).
using System;
using System.Buffers.Binary;

namespace TigerBeetle;

public sealed class AccountFilter
{
    internal const int Size = 64;

    private readonly byte[] _buffer = new byte[Size];

    public AccountFilter()
    {
        Limit = Client.BatchMax;
        Debits = true;
        Credits = true;
    }

    public void SetAccountId(ulong lo, ulong hi)
    {
        BinaryPrimitives.WriteUInt64LittleEndian(_buffer.AsSpan(0), lo);
        BinaryPrimitives.WriteUInt64LittleEndian(_buffer.AsSpan(8), hi);
    }

    public ulong AccountIdLo =>
        BinaryPrimitives.ReadUInt64LittleEndian(_buffer.AsSpan(0));
    public ulong AccountIdHi =>
        BinaryPrimitives.ReadUInt64LittleEndian(_buffer.AsSpan(8));

    /// Inclusive minimum server timestamp; 0 = no bound.
    public ulong TimestampMin
    {
        get => BinaryPrimitives.ReadUInt64LittleEndian(_buffer.AsSpan(16));
        set => BinaryPrimitives.WriteUInt64LittleEndian(
            _buffer.AsSpan(16), value);
    }

    /// Inclusive maximum server timestamp; 0 = no bound.
    public ulong TimestampMax
    {
        get => BinaryPrimitives.ReadUInt64LittleEndian(_buffer.AsSpan(24));
        set => BinaryPrimitives.WriteUInt64LittleEndian(
            _buffer.AsSpan(24), value);
    }

    /// Maximum result rows (capped by the 1 MiB reply).
    public uint Limit
    {
        get => BinaryPrimitives.ReadUInt32LittleEndian(_buffer.AsSpan(32));
        set => BinaryPrimitives.WriteUInt32LittleEndian(
            _buffer.AsSpan(32), value);
    }

    private bool GetFlag(uint bit) =>
        (BinaryPrimitives.ReadUInt32LittleEndian(_buffer.AsSpan(36)) & bit)
        != 0;

    private void SetFlag(uint bit, bool on)
    {
        uint flags =
            BinaryPrimitives.ReadUInt32LittleEndian(_buffer.AsSpan(36));
        flags = on ? flags | bit : flags & ~bit;
        BinaryPrimitives.WriteUInt32LittleEndian(_buffer.AsSpan(36), flags);
    }

    /// Include rows where the account is the debit side.
    public bool Debits
    {
        get => GetFlag((uint)AccountFilterFlags.Debits);
        set => SetFlag((uint)AccountFilterFlags.Debits, value);
    }

    /// Include rows where the account is the credit side.
    public bool Credits
    {
        get => GetFlag((uint)AccountFilterFlags.Credits);
        set => SetFlag((uint)AccountFilterFlags.Credits, value);
    }

    /// Newest-first results.
    public bool Reversed
    {
        get => GetFlag((uint)AccountFilterFlags.Reversed);
        set => SetFlag((uint)AccountFilterFlags.Reversed, value);
    }

    internal byte[] ToArray() => (byte[])_buffer.Clone();
}
