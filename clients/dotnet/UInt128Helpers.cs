// 128-bit id/amount helpers (the reference's dotnet UInt128
// extensions — src/clients/dotnet/TigerBeetle/UInt128Extensions.cs):
// conversions between (lo, hi) ulong limbs, 16-byte little-endian
// arrays, System.Numerics.BigInteger, and Guid, plus a monotonic
// time-based Id() generator (ULID-shaped, strictly increasing within
// the process — reference ID() semantics).
using System;
using System.Numerics;
using System.Security.Cryptography;

namespace TigerBeetle;

public static class UInt128Helpers
{
    public const int Size = 16;

    /// (lo, hi) limbs -> 16 little-endian bytes.
    public static byte[] AsBytes(ulong lo, ulong hi)
    {
        var bytes = new byte[Size];
        BitConverter.TryWriteBytes(bytes.AsSpan(0, 8), lo);
        BitConverter.TryWriteBytes(bytes.AsSpan(8, 8), hi);
        return bytes;
    }

    public static ulong BytesToLo(ReadOnlySpan<byte> bytes)
    {
        CheckLength(bytes);
        return BitConverter.ToUInt64(bytes[..8]);
    }

    public static ulong BytesToHi(ReadOnlySpan<byte> bytes)
    {
        CheckLength(bytes);
        return BitConverter.ToUInt64(bytes[8..16]);
    }

    /// Non-negative BigInteger (must fit 128 bits) -> (lo, hi) limbs.
    public static (ulong Lo, ulong Hi) FromBigInteger(BigInteger value)
    {
        if (value.Sign < 0 || value.GetBitLength() > 128)
            throw new ArgumentOutOfRangeException(
                nameof(value), "must be a non-negative 128-bit integer");
        ulong lo = (ulong)(value & ulong.MaxValue);
        ulong hi = (ulong)((value >> 64) & ulong.MaxValue);
        return (lo, hi);
    }

    public static BigInteger AsBigInteger(ulong lo, ulong hi) =>
        (new BigInteger(hi) << 64) | new BigInteger(lo);

    /// Guid (RFC byte order) <-> limbs via the 16-byte wire image.
    public static (ulong Lo, ulong Hi) FromGuid(Guid guid)
    {
        var bytes = guid.ToByteArray();
        return (BytesToLo(bytes), BytesToHi(bytes));
    }

    public static Guid AsGuid(ulong lo, ulong hi) =>
        new(AsBytes(lo, hi));

    private static readonly object IdLock = new();
    private static long _idLastMillis;
    private static ulong _idLastLo;
    private static ulong _idLastHi;

    /// Time-ordered unique 128-bit id as (lo, hi) limbs: 48-bit
    /// millisecond timestamp in the topmost bits, random bits below,
    /// strictly monotonic within the process (same-millisecond calls
    /// increment — reference UInt128.ID()).
    public static (ulong Lo, ulong Hi) Id()
    {
        lock (IdLock)
        {
            long now = DateTimeOffset.UtcNow.ToUnixTimeMilliseconds();
            if (now > _idLastMillis)
            {
                _idLastMillis = now;
                Span<byte> rand = stackalloc byte[10];
                RandomNumberGenerator.Fill(rand);
                _idLastHi = ((ulong)now << 16)
                    | ((ulong)rand[0] << 8) | rand[1];
                _idLastLo = BitConverter.ToUInt64(rand[2..10]);
            }
            else
            {
                _idLastLo++;
                if (_idLastLo == 0) _idLastHi++;
            }
            return (_idLastLo, _idLastHi);
        }
    }

    private static void CheckLength(ReadOnlySpan<byte> bytes)
    {
        if (bytes.Length != Size)
            throw new ArgumentException("expected 16 bytes");
    }
}
