// Batch encoders/decoders: cursors over little-endian byte buffers of
// fixed 128-byte wire elements (tigerbeetle_tpu/types.py layouts;
// reference: the generated com.tigerbeetle / TigerBeetle dotnet batch
// classes, src/dotnet_bindings.zig).
using System;
using System.Buffers.Binary;

namespace TigerBeetle;

public abstract class Batch
{
    internal readonly byte[] Buffer;
    private readonly int _elementSize;
    private int _length;
    private int _position = -1;

    private protected Batch(int capacity, int elementSize)
    {
        Buffer = new byte[capacity * elementSize];
        _elementSize = elementSize;
    }

    private protected Batch(byte[] wrapped, int elementSize)
    {
        Buffer = wrapped;
        _elementSize = elementSize;
        _length = wrapped.Length / elementSize;
    }

    public int Length => _length;
    public int Capacity => Buffer.Length / _elementSize;
    public int Position => _position;

    /// Appends a zeroed element and moves the cursor to it.
    public void Add()
    {
        if (_length >= Capacity)
            throw new IndexOutOfRangeException("batch is full");
        _position = _length++;
        Buffer.AsSpan(_position * _elementSize, _elementSize).Clear();
    }

    /// Advances the cursor; false when past the last element.
    public bool Next()
    {
        if (_position + 1 >= _length) return false;
        _position++;
        return true;
    }

    public void BeforeFirst() => _position = -1;

    public void SetPosition(int index)
    {
        if (index < 0 || index >= _length)
            throw new IndexOutOfRangeException($"position {index}");
        _position = index;
    }

    private protected Span<byte> At(int offset)
    {
        if (_position < 0)
            throw new InvalidOperationException("cursor before first element");
        return Buffer.AsSpan(_position * _elementSize + offset);
    }

    private protected ulong GetU64(int o) =>
        BinaryPrimitives.ReadUInt64LittleEndian(At(o));
    private protected void SetU64(int o, ulong v) =>
        BinaryPrimitives.WriteUInt64LittleEndian(At(o), v);
    private protected uint GetU32(int o) =>
        BinaryPrimitives.ReadUInt32LittleEndian(At(o));
    private protected void SetU32(int o, uint v) =>
        BinaryPrimitives.WriteUInt32LittleEndian(At(o), v);
    private protected ushort GetU16(int o) =>
        BinaryPrimitives.ReadUInt16LittleEndian(At(o));
    private protected void SetU16(int o, ushort v) =>
        BinaryPrimitives.WriteUInt16LittleEndian(At(o), v);

    internal byte[] ToArray() => Buffer.AsSpan(0, _length * _elementSize).ToArray();
}

public sealed class AccountBatch : Batch
{
    internal const int ElementSize = 128;

    public AccountBatch(int capacity) : base(capacity, ElementSize) { }
    internal AccountBatch(byte[] wrapped) : base(wrapped, ElementSize) { }

    public void SetId(ulong lo, ulong hi) { SetU64(0, lo); SetU64(8, hi); }
    public ulong IdLo => GetU64(0);
    public ulong IdHi => GetU64(8);
    public ulong DebitsPendingLo => GetU64(16);
    public ulong DebitsPostedLo => GetU64(32);
    public ulong CreditsPendingLo => GetU64(48);
    public ulong CreditsPostedLo => GetU64(64);
    public void SetUserData128(ulong lo, ulong hi) { SetU64(80, lo); SetU64(88, hi); }
    public ulong UserData64 { get => GetU64(96); set => SetU64(96, value); }
    public uint UserData32 { get => GetU32(104); set => SetU32(104, value); }
    public uint Ledger { get => GetU32(112); set => SetU32(112, value); }
    public ushort Code { get => GetU16(116); set => SetU16(116, value); }
    public AccountFlags Flags
    {
        get => (AccountFlags)GetU16(118);
        set => SetU16(118, (ushort)value);
    }
    public ulong Timestamp => GetU64(120);
}

public sealed class TransferBatch : Batch
{
    internal const int ElementSize = 128;

    public TransferBatch(int capacity) : base(capacity, ElementSize) { }
    internal TransferBatch(byte[] wrapped) : base(wrapped, ElementSize) { }

    public void SetId(ulong lo, ulong hi) { SetU64(0, lo); SetU64(8, hi); }
    public ulong IdLo => GetU64(0);
    public void SetDebitAccountId(ulong lo, ulong hi) { SetU64(16, lo); SetU64(24, hi); }
    public void SetCreditAccountId(ulong lo, ulong hi) { SetU64(32, lo); SetU64(40, hi); }
    public void SetAmount(ulong lo, ulong hi) { SetU64(48, lo); SetU64(56, hi); }
    public ulong AmountLo => GetU64(48);
    public void SetPendingId(ulong lo, ulong hi) { SetU64(64, lo); SetU64(72, hi); }
    public ulong PendingIdLo => GetU64(64);
    public void SetUserData128(ulong lo, ulong hi) { SetU64(80, lo); SetU64(88, hi); }
    public ulong UserData64 { get => GetU64(96); set => SetU64(96, value); }
    public uint UserData32 { get => GetU32(104); set => SetU32(104, value); }
    public uint Timeout { get => GetU32(108); set => SetU32(108, value); }
    public uint Ledger { get => GetU32(112); set => SetU32(112, value); }
    public ushort Code { get => GetU16(116); set => SetU16(116, value); }
    public TransferFlags Flags
    {
        get => (TransferFlags)GetU16(118);
        set => SetU16(118, (ushort)value);
    }
    public ulong Timestamp => GetU64(120);
}

public sealed class IdBatch : Batch
{
    internal const int ElementSize = 16;

    public IdBatch(int capacity) : base(capacity, ElementSize) { }

    public void Add(ulong lo, ulong hi)
    {
        Add();
        SetU64(0, lo);
        SetU64(8, hi);
    }
}

/// Failures only: an empty batch means every event succeeded.
public sealed class CreateResultBatch : Batch
{
    internal const int ElementSize = 8;

    internal CreateResultBatch(byte[] wrapped) : base(wrapped, ElementSize) { }

    public uint Index => GetU32(0);
    public uint Result => GetU32(4);
}

/// 128-byte AccountBalance reply rows (tigerbeetle_tpu/types.py
/// ACCOUNT_BALANCE_DTYPE; reference: src/tigerbeetle.zig:65-78).
public sealed class AccountBalanceBatch : Batch
{
    internal const int ElementSize = 128;

    internal AccountBalanceBatch(byte[] wrapped)
        : base(wrapped, ElementSize) { }

    public ulong DebitsPendingLo => GetU64(0);
    public ulong DebitsPendingHi => GetU64(8);
    public ulong DebitsPostedLo => GetU64(16);
    public ulong DebitsPostedHi => GetU64(24);
    public ulong CreditsPendingLo => GetU64(32);
    public ulong CreditsPendingHi => GetU64(40);
    public ulong CreditsPostedLo => GetU64(48);
    public ulong CreditsPostedHi => GetU64(56);

    /// Server timestamp of the transfer that produced this snapshot.
    public ulong Timestamp => GetU64(64);
}
