// End-to-end test against a live server (spawned by
// tests/test_foreign_clients.py; TB_ADDRESS/TB_CLUSTER via env).
// Prints "e2e ok" on success, throws on failure.
using TigerBeetle;

var addr = Environment.GetEnvironmentVariable("TB_ADDRESS")!.Split(':');
var cluster = ulong.Parse(Environment.GetEnvironmentVariable("TB_CLUSTER")!);
using var client = new Client(addr[0], int.Parse(addr[1]), cluster);

var accounts = new AccountBatch(2);
for (ulong id = 1; id <= 2; id++)
{
    accounts.Add();
    accounts.SetId(id, 0);
    accounts.Ledger = 1;
    accounts.Code = 1;
}
if (client.CreateAccounts(accounts).Length != 0)
    throw new Exception("create_accounts failed");

var transfers = new TransferBatch(2);
transfers.Add();                       // pending 40: 1 -> 2
transfers.SetId(10, 0);
transfers.SetDebitAccountId(1, 0);
transfers.SetCreditAccountId(2, 0);
transfers.SetAmount(40, 0);
transfers.Ledger = 1;
transfers.Code = 1;
transfers.Flags = TransferFlags.Pending;
transfers.Add();                       // post it, amount inherited
transfers.SetId(11, 0);
transfers.SetPendingId(10, 0);
transfers.Flags = TransferFlags.PostPendingTransfer;
if (client.CreateTransfers(transfers).Length != 0)
    throw new Exception("create_transfers failed");

var ids = new IdBatch(2);
ids.Add(1, 0);
ids.Add(2, 0);
var got = client.LookupAccounts(ids);
if (got.Length != 2) throw new Exception($"lookup count {got.Length}");
got.Next();
if (got.DebitsPostedLo != 40) throw new Exception("acct1 dpo");
got.Next();
if (got.CreditsPostedLo != 40) throw new Exception("acct2 cpo");

var tid = new IdBatch(1);
tid.Add(11, 0);
var t = client.LookupTransfers(tid);
if (t.Length != 1) throw new Exception("t11 missing");
t.Next();
if (t.AmountLo != 40 || t.PendingIdLo != 10)
    throw new Exception("t11 fields");

Console.WriteLine("e2e ok");
