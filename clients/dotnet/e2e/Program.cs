// End-to-end test against a live server (spawned by
// tests/test_foreign_clients.py; TB_ADDRESS/TB_CLUSTER via env).
// Prints "e2e ok" on success, throws on failure.
using System;
using System.Collections.Generic;
using System.Threading.Tasks;
using TigerBeetle;

var addr = Environment.GetEnvironmentVariable("TB_ADDRESS")!.Split(':');
var cluster = ulong.Parse(Environment.GetEnvironmentVariable("TB_CLUSTER")!);
using var client = new Client(addr[0], int.Parse(addr[1]), cluster);

var accounts = new AccountBatch(2);
for (ulong id = 1; id <= 2; id++)
{
    accounts.Add();
    accounts.SetId(id, 0);
    accounts.Ledger = 1;
    accounts.Code = 1;
}
if (client.CreateAccounts(accounts).Length != 0)
    throw new Exception("create_accounts failed");

var transfers = new TransferBatch(2);
transfers.Add();                       // pending 40: 1 -> 2
transfers.SetId(10, 0);
transfers.SetDebitAccountId(1, 0);
transfers.SetCreditAccountId(2, 0);
transfers.SetAmount(40, 0);
transfers.Ledger = 1;
transfers.Code = 1;
transfers.Flags = TransferFlags.Pending;
transfers.Add();                       // post it, amount inherited
transfers.SetId(11, 0);
transfers.SetPendingId(10, 0);
transfers.Flags = TransferFlags.PostPendingTransfer;
if (client.CreateTransfers(transfers).Length != 0)
    throw new Exception("create_transfers failed");

var ids = new IdBatch(2);
ids.Add(1, 0);
ids.Add(2, 0);
var got = client.LookupAccounts(ids);
if (got.Length != 2) throw new Exception($"lookup count {got.Length}");
got.Next();
if (got.DebitsPostedLo != 40) throw new Exception("acct1 dpo");
got.Next();
if (got.CreditsPostedLo != 40) throw new Exception("acct2 cpo");

var tid = new IdBatch(1);
tid.Add(11, 0);
var t = client.LookupTransfers(tid);
if (t.Length != 1) throw new Exception("t11 missing");
t.Next();
if (t.AmountLo != 40 || t.PendingIdLo != 10)
    throw new Exception("t11 fields");

// r5 surface: filter-driven queries, UInt128 helpers, EchoClient.
var filter = new AccountFilter();
filter.SetAccountId(1, 0);
filter.Limit = 10;
var qt = client.GetAccountTransfers(filter);
if (qt.Length != 2) throw new Exception($"query rows {qt.Length}");
qt.Next();
if (qt.IdLo != 10) throw new Exception("query order");

var (idLo, idHi) = UInt128Helpers.Id();
var (idLo2, idHi2) = UInt128Helpers.Id();
if (UInt128Helpers.AsBigInteger(idLo2, idHi2)
    <= UInt128Helpers.AsBigInteger(idLo, idHi))
    throw new Exception("UInt128 ids must be monotonic");

using (var echo = new EchoClient())
{
    var back = echo.EchoTransfers(transfers);
    if (back.Length != transfers.Length) throw new Exception("echo length");
    back.Next();
    if (back.IdLo != 10 || back.AmountLo != 40)
        throw new Exception("echo fields");
    if (echo.CreateTransfers(transfers).Length != 0)
        throw new Exception("echo create must report no failures");
}

Console.WriteLine("e2e ok");

// ---------------------------------------------------------------------
// Async pipelined client (VERDICT r3 #6): N batches in flight at once;
// the worker coalesces adjacent create batches into one wire request
// and demuxes the reply per-packet with rebased indexes.
using (var async = new AsyncClient(addr[0], int.Parse(addr[1]), cluster))
{
    var tasks = new List<Task<CreateResultBatch>>();
    for (int k = 0; k < 8; k++)
    {
        var batch = new TransferBatch(1);
        batch.Add();
        batch.SetId((ulong)(100 + k), 0);
        batch.SetDebitAccountId(1, 0);
        // Odd batches invalid: same debit and credit account.
        batch.SetCreditAccountId(k % 2 == 1 ? 1UL : 2UL, 0);
        batch.SetAmount((ulong)(10 + k), 0);
        batch.Ledger = 1;
        batch.Code = 1;
        tasks.Add(async.CreateTransfersAsync(batch));
    }
    var idsB = new IdBatch(1);
    idsB.Add(1, 0);
    var lookupTask = async.LookupAccountsAsync(idsB);
    for (int k = 0; k < 8; k++)
    {
        var r = tasks[k].Result;
        if (k % 2 == 1)
        {
            if (r.Length != 1) throw new Exception($"odd batch {k} must fail");
            r.Next();
            if (r.Index != 0) throw new Exception("rebased index");
            if (r.Result != (uint)CreateTransferResult.AccountsMustBeDifferent)
                throw new Exception($"odd batch {k} result {r.Result}");
        }
        else if (r.Length != 0)
        {
            throw new Exception($"even batch {k} failed");
        }
    }
    var rows = lookupTask.Result;
    if (rows.Length != 1) throw new Exception("async lookup rows");
    Console.WriteLine("async e2e ok");
}

// Demux vectors (clients/fixtures/demux.json, rendered to stdin lines
// by the harness as reply_hex|counts|slices, "-" = empty).
if (Environment.GetEnvironmentVariable("TB_DEMUX_STDIN") == "1")
{
    int cases = 0;
    string? line;
    while ((line = Console.ReadLine()) != null)
    {
        if (line.Length == 0) continue;
        var parts = line.Split('|');
        var reply = Unhex(parts[0]);
        var counts = Array.ConvertAll(parts[1].Split(','), int.Parse);
        var slices = parts[2].Split(',');
        var gotSlices = AsyncClient.DemuxSlices(counts, reply);
        for (int i = 0; i < counts.Length; i++)
        {
            if (!gotSlices[i].AsSpan().SequenceEqual(Unhex(slices[i])))
                throw new Exception($"demux case {cases} packet {i}");
        }
        cases++;
    }
    if (cases == 0) throw new Exception("no demux cases on stdin");
    Console.WriteLine($"demux ok ({cases} cases)");
}

static byte[] Unhex(string s)
{
    if (s == "-") return Array.Empty<byte>();
    var output = new byte[s.Length / 2];
    for (int i = 0; i < output.Length; i++)
        output[i] = Convert.ToByte(s.Substring(2 * i, 2), 16);
    return output;
}
