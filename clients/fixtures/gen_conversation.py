"""Generate conversation.json: a full client<->server wire transcript.

The fixture is the cross-language CONTRACT (VERDICT r3 #5): a scripted
session — register, create_accounts, create_transfers (with a failure),
a RETRANSMIT of the same request (byte-identical reply via session
dedupe), lookups, and a query — recorded as exact request/reply frame
bytes against a live in-process TCP server whose wall clock is pinned
(prepare timestamps then derive from event counts alone, so the
transcript is deterministic and replayable forever).

Every language client asserts its encoder produces EXACTLY these
request frames and its decoder accepts these reply frames; the
in-container test (tests/test_client_conversations.py) replays the
requests against a live server and asserts the reply bytes — so the
wire contract is verified here with zero toolchains.

Regenerate: python clients/fixtures/gen_conversation.py
(reference conversation shape: src/scripts/ci.zig:20-62.)
"""

from __future__ import annotations

import json
import os
import socket
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import numpy as np

CLUSTER = 3
CLIENT_LO = 0xC0FFEE
PINNED_NS = 1_000_000_000


def pinned_server(tmp):
    """A real TCP ReplicaServer with time.time_ns pinned (prepare
    timestamps then advance by event count only) AND monotonic_ns
    pinned (the tick cadence never fires, so no pulse/ping op ever
    lands at a scheduling-dependent position) — deterministic."""
    time.time_ns = lambda: PINNED_NS  # monkeypatch BEFORE server import
    time.monotonic_ns = lambda: 0

    from tigerbeetle_tpu.runtime.server import ReplicaServer, format_data_file
    from tigerbeetle_tpu.state_machine import CpuStateMachine

    path = os.path.join(tmp, "0_0.tigerbeetle")
    format_data_file(path, cluster=CLUSTER, replica_index=0, replica_count=1)
    server = ReplicaServer(
        path, addresses=["127.0.0.1:0"], replica_index=0,
        state_machine_factory=CpuStateMachine,
    )
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            server.poll_once(10)

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return server, stop, t


def build_frames():
    from tigerbeetle_tpu import types
    from tigerbeetle_tpu.vsr import wire

    def frame(request, operation, body, command=wire.Command.request):
        h = wire.make_header(
            command=command, cluster=CLUSTER, client=CLIENT_LO,
            request=request, operation=operation,
        )
        wire.finalize_header(h, body)
        return h.tobytes() + body

    steps = []
    steps.append(("register", frame(0, int(wire.VsrOperation.register), b""), False))

    a = np.zeros(2, types.ACCOUNT_DTYPE)
    a["id_lo"] = [9001, 9002]
    a["ledger"] = 1
    a["code"] = 1
    steps.append(
        ("create_accounts", frame(1, int(types.Operation.create_accounts), a.tobytes()), False)
    )

    t = np.zeros(3, types.TRANSFER_DTYPE)
    t["id_lo"] = [501, 502, 503]
    t["debit_account_id_lo"] = [9001, 9001, 9001]
    t["credit_account_id_lo"] = [9002, 9001, 9002]  # 502: same account
    t["amount_lo"] = [100, 5, 40]
    t["ledger"] = 1
    t["code"] = 1
    tf = frame(2, int(types.Operation.create_transfers), t.tobytes())
    steps.append(("create_transfers", tf, False))
    # Retransmission of the SAME request: session dedupe must return a
    # byte-identical stored reply (reference: at-most-once sessions,
    # src/vsr/client_sessions.zig).
    steps.append(("create_transfers_retransmit", tf, True))

    ids = np.zeros(2, types.U128_PAIR_DTYPE)
    ids["lo"] = [9001, 9002]
    steps.append(
        ("lookup_accounts", frame(3, int(types.Operation.lookup_accounts), ids.tobytes()), False)
    )

    tids = np.zeros(3, types.U128_PAIR_DTYPE)
    tids["lo"] = [501, 502, 503]
    steps.append(
        ("lookup_transfers", frame(4, int(types.Operation.lookup_transfers), tids.tobytes()), False)
    )

    f = np.zeros(1, types.ACCOUNT_FILTER_DTYPE)
    f[0]["account_id_lo"] = 9001
    f[0]["limit"] = 10
    f[0]["flags"] = int(types.AccountFilterFlags.debits | types.AccountFilterFlags.credits)
    steps.append(
        (
            "get_account_transfers",
            frame(5, int(types.Operation.get_account_transfers), f.tobytes()),
            False,
        )
    )

    # History-flagged account + a transfer into it, then the
    # get_account_balances query — the filter-builder/balance-decode
    # surface every language client ships (VERDICT r4 #8).  Appended
    # AFTER the original steps so their recorded frames stay stable.
    ah = np.zeros(1, types.ACCOUNT_DTYPE)
    ah["id_lo"] = 9003
    ah["ledger"] = 1
    ah["code"] = 1
    ah["flags"] = int(types.AccountFlags.history)
    steps.append(
        (
            "create_accounts_history",
            frame(6, int(types.Operation.create_accounts), ah.tobytes()),
            False,
        )
    )
    th = np.zeros(1, types.TRANSFER_DTYPE)
    th["id_lo"] = 504
    th["debit_account_id_lo"] = 9001
    th["credit_account_id_lo"] = 9003
    th["amount_lo"] = 7
    th["ledger"] = 1
    th["code"] = 1
    steps.append(
        (
            "create_transfers_history",
            frame(7, int(types.Operation.create_transfers), th.tobytes()),
            False,
        )
    )
    fb = np.zeros(1, types.ACCOUNT_FILTER_DTYPE)
    fb[0]["account_id_lo"] = 9003
    fb[0]["limit"] = 10
    fb[0]["flags"] = int(
        types.AccountFilterFlags.debits | types.AccountFilterFlags.credits
    )
    steps.append(
        (
            "get_account_balances",
            frame(8, int(types.Operation.get_account_balances), fb.tobytes()),
            False,
        )
    )
    return steps


def converse(port, steps):
    from tigerbeetle_tpu.vsr import wire

    HEADER_SIZE = 256
    sock = socket.create_connection(("127.0.0.1", port), timeout=30)
    sock.settimeout(30)
    buf = b""
    out = []
    for name, req, is_retransmit in steps:
        sock.sendall(req)
        # One reply frame per request.
        while True:
            if len(buf) >= HEADER_SIZE:
                size = int.from_bytes(buf[144:148], "little")
                if len(buf) >= size:
                    reply, buf = buf[:size], buf[size:]
                    break
            chunk = sock.recv(1 << 20)
            assert chunk, "server closed"
            buf += chunk
        h = wire.header_from_bytes(reply[:HEADER_SIZE])
        assert wire.verify_header(h, reply[HEADER_SIZE:]), name
        assert int(h["command"]) == int(wire.Command.reply), name
        out.append(
            {
                "name": name,
                "retransmit_of": name.replace("_retransmit", "")
                if is_retransmit
                else None,
                "request_hex": req.hex(),
                "reply_hex": reply.hex(),
            }
        )
    sock.close()
    return out


def generate():
    tmp = tempfile.mkdtemp(prefix="tb_conv_")
    server, stop, t = pinned_server(tmp)
    try:
        steps = build_frames()
        out = converse(server.port, steps)
    finally:
        stop.set()
        t.join(timeout=5)
        server.close()
    return out


if __name__ == "__main__":
    out = generate()
    # Retransmit reply must equal the original's reply byte-for-byte.
    by_name = {e["name"]: e for e in out}
    assert (
        by_name["create_transfers_retransmit"]["reply_hex"]
        == by_name["create_transfers"]["reply_hex"]
    ), "retransmit reply diverged"
    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)), "conversation.json")
    with open(dest, "w") as fh:
        json.dump(out, fh, indent=1)
    print(f"wrote {dest} ({len(out)} steps)")
