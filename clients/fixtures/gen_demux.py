"""Generate demux.json: client-side reply-demux test vectors.

Each case: a coalesced create_* reply (sorted {index u32, result u32}
pairs) + per-packet event counts -> the expected rebased slice per
packet, produced by the SERVER's own demuxer
(tigerbeetle_tpu/state_machine/demuxer.py — reference:
src/state_machine.zig:133-176 DemuxerType).  The async Java/C# clients
assert their demux against these vectors, so all implementations split
coalesced replies identically.

Regenerate: python clients/fixtures/gen_demux.py
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import numpy as np

from tigerbeetle_tpu.state_machine.demuxer import Demuxer
from tigerbeetle_tpu.types import CREATE_RESULT_DTYPE, Operation


def results(pairs):
    arr = np.zeros(len(pairs), CREATE_RESULT_DTYPE)
    for i, (idx, res) in enumerate(pairs):
        arr[i]["index"] = idx
        arr[i]["result"] = res
    return arr.tobytes()


CASES = [
    # (name, reply pairs, packet event counts)
    ("all_ok", [], [3, 2, 4]),
    ("spread_failures", [(0, 46), (3, 12), (4, 33), (8, 5)], [2, 3, 4]),
    ("first_packet_only", [(0, 1), (1, 1)], [2, 5]),
    ("last_packet_only", [(6, 46)], [3, 3, 1]),
    ("dense", [(i, 40 + (i % 3)) for i in range(9)], [4, 1, 4]),
    ("single_event_packets", [(1, 5), (2, 6)], [1, 1, 1, 1]),
]


def generate():
    out = []
    for name, pairs, counts in CASES:
        reply = results(pairs)
        demux = Demuxer(Operation.create_transfers, reply)
        offset = 0
        slices = []
        for count in counts:
            slices.append(demux.decode(offset, count).hex())
            offset += count
        out.append(
            {
                "name": name,
                "reply_hex": reply.hex(),
                "event_counts": counts,
                "slices_hex": slices,
            }
        )
    return out


if __name__ == "__main__":
    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)), "demux.json")
    with open(dest, "w") as fh:
        json.dump(generate(), fh, indent=1)
    print(f"wrote {dest}")
