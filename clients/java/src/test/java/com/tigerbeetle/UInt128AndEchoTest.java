// Unit tests for the r5 client surface: UInt128 helpers, the
// AccountFilter builder's wire layout, and the EchoClient marshaling
// double (reference test shape: src/clients/java/src/test/java/com/
// tigerbeetle/UInt128Test.java, EchoTest.java).  Runs under any JUnit4
// runner when a JVM toolchain is present; the wire layouts themselves
// are pinned toolchain-free by tests/test_client_conversations.py.
package com.tigerbeetle;

import static org.junit.Assert.assertArrayEquals;
import static org.junit.Assert.assertEquals;
import static org.junit.Assert.assertTrue;

import java.math.BigInteger;
import java.util.UUID;
import org.junit.Test;

public class UInt128AndEchoTest {

    @Test
    public void uint128RoundTrips() {
        long lo = 0xDEAD_BEEF_CAFE_F00DL;
        long hi = 0x0123_4567_89AB_CDEFL;
        byte[] bytes = UInt128.asBytes(lo, hi);
        assertEquals(16, bytes.length);
        assertEquals(lo, UInt128.bytesToLo(bytes));
        assertEquals(hi, UInt128.bytesToHi(bytes));
        BigInteger big = UInt128.asBigInteger(lo, hi);
        assertEquals(lo, UInt128.bigIntegerToLo(big));
        assertEquals(hi, UInt128.bigIntegerToHi(big));
        UUID uuid = UInt128.asUuid(lo, hi);
        assertEquals(lo, UInt128.uuidToLo(uuid));
        assertEquals(hi, UInt128.uuidToHi(uuid));
    }

    @Test
    public void idsAreMonotonic() {
        long[] prev = UInt128.id();
        for (int i = 0; i < 10_000; i++) {
            long[] next = UInt128.id();
            BigInteger a = UInt128.asBigInteger(prev[0], prev[1]);
            BigInteger b = UInt128.asBigInteger(next[0], next[1]);
            assertTrue("ids must be strictly increasing", b.compareTo(a) > 0);
            prev = next;
        }
    }

    @Test
    public void accountFilterLayout() {
        AccountFilter f = new AccountFilter();
        f.setAccountId(9003L, 0L);
        f.setTimestampMin(5L);
        f.setTimestampMax(99L);
        f.setLimit(10);
        f.setReversed(true);
        byte[] wire = f.toArray();
        assertEquals(64, wire.length);
        assertEquals(9003L, UInt128.bytesToLo(java.util.Arrays.copyOf(wire, 16)));
        assertTrue(f.getDebits());
        assertTrue(f.getCredits());
        assertTrue(f.getReversed());
        assertEquals(10, f.getLimit());
    }

    @Test
    public void echoClientRoundTripsTransfers() throws Exception {
        try (EchoClient echo = new EchoClient()) {
            TransferBatch batch = new TransferBatch(2);
            batch.add();
            batch.setId(501, 0);
            batch.setDebitAccountId(9001, 0);
            batch.setCreditAccountId(9002, 0);
            batch.setAmount(100, 0);
            batch.setLedger(1);
            batch.setCode(1);
            batch.add();
            batch.setId(502, 0);
            batch.setDebitAccountId(9002, 0);
            batch.setCreditAccountId(9001, 0);
            batch.setAmount(40, 0);
            batch.setLedger(1);
            batch.setCode(1);

            assertEquals(0, echo.createTransfers(batch).getLength());
            TransferBatch back = echo.echoTransfers(batch);
            assertEquals(2, back.getLength());
            back.next();
            assertEquals(501, back.getIdLo());
            assertEquals(100, back.getAmountLo());
            back.next();
            assertEquals(502, back.getIdLo());
            assertEquals(40, back.getAmountLo());
            assertArrayEquals(batch.toArray(), back.toArray());
        }
    }
}
