// End-to-end test against a live server (spawned by
// tests/test_foreign_clients.py, which passes host:port + cluster via
// env).  Plain main() — no test-framework dependency; prints "e2e ok"
// on success, exits nonzero on failure.
package com.tigerbeetle;

public final class E2ETest {
    public static void main(String[] args) throws Exception {
        String addr = System.getenv("TB_ADDRESS");
        long cluster = Long.parseLong(System.getenv("TB_CLUSTER"));
        String[] parts = addr.split(":");
        try (Client client =
                 new Client(parts[0], Integer.parseInt(parts[1]), cluster)) {
            AccountBatch accounts = new AccountBatch(3);
            for (int id = 1; id <= 3; id++) {
                accounts.add();
                accounts.setId(id, 0);
                accounts.setLedger(1);
                accounts.setCode(1);
            }
            CreateResultBatch r = client.createAccounts(accounts);
            expect(r.getLength() == 0, "create_accounts failures");

            // Duplicate id with different code -> exists_with_different_code.
            AccountBatch dup = new AccountBatch(1);
            dup.add();
            dup.setId(1, 0);
            dup.setLedger(1);
            dup.setCode(9);
            r = client.createAccounts(dup);
            expect(r.getLength() == 1, "dup should fail");
            r.next();
            expect(
                r.getResult()
                    == Types.CreateAccountResult.ExistsWithDifferentCode.value,
                "dup code " + r.getResult());

            TransferBatch transfers = new TransferBatch(3);
            transfers.add();
            transfers.setId(10, 0);
            transfers.setDebitAccountId(1, 0);
            transfers.setCreditAccountId(2, 0);
            transfers.setAmount(100, 0);
            transfers.setLedger(1);
            transfers.setCode(1);
            transfers.add();  // pending
            transfers.setId(11, 0);
            transfers.setDebitAccountId(2, 0);
            transfers.setCreditAccountId(3, 0);
            transfers.setAmount(40, 0);
            transfers.setLedger(1);
            transfers.setCode(1);
            transfers.setFlags(Types.TransferFlags.Pending);
            transfers.add();  // post it (amount inherited)
            transfers.setId(12, 0);
            transfers.setPendingId(11, 0);
            transfers.setFlags(Types.TransferFlags.PostPendingTransfer);
            CreateResultBatch tr = client.createTransfers(transfers);
            expect(tr.getLength() == 0, "create_transfers failures");

            IdBatch ids = new IdBatch(3);
            ids.add(1, 0);
            ids.add(2, 0);
            ids.add(3, 0);
            AccountBatch got = client.lookupAccounts(ids);
            expect(got.getLength() == 3, "lookup count " + got.getLength());
            got.next();
            expect(got.getDebitsPostedLo() == 100, "acct1 dpo");
            got.next();
            expect(got.getDebitsPostedLo() == 40, "acct2 dpo");
            expect(got.getCreditsPostedLo() == 100, "acct2 cpo");
            got.next();
            expect(got.getCreditsPostedLo() == 40, "acct3 cpo");

            IdBatch tid = new IdBatch(1);
            tid.add(12, 0);
            TransferBatch t12 = client.lookupTransfers(tid);
            expect(t12.getLength() == 1, "t12 found");
            t12.next();
            expect(t12.getAmountLo() == 40, "t12 inherited amount");
            expect(t12.getPendingIdLo() == 11, "t12 pending id");

            // r5 surface: filter-driven query through the builder.
            AccountFilter filter = new AccountFilter();
            filter.setAccountId(2, 0);
            filter.setLimit(10);
            TransferBatch qt = client.getAccountTransfers(filter);
            expect(qt.getLength() >= 2, "query rows " + qt.getLength());
            qt.next();
            expect(qt.getIdLo() == 10, "query order " + qt.getIdLo());
        }
        System.out.println("e2e ok");
    }

    private static void expect(boolean cond, String what) {
        if (!cond) {
            throw new AssertionError(what);
        }
    }
}
