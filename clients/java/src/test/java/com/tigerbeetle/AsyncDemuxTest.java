// Fixture-driven demux test (plain main, no framework — driven by
// tests/test_foreign_clients.py, which streams the
// clients/fixtures/demux.json vectors on stdin as lines of
//   reply_hex|count,count,...|slice_hex,slice_hex,...
// with empty hex spelled "-").  Verifies AsyncClient.demuxSlices
// splits a coalesced create_* reply into per-packet rebased slices
// exactly as the server's demuxer does.
package com.tigerbeetle;

import java.io.BufferedReader;
import java.io.InputStreamReader;

public final class AsyncDemuxTest {
    public static void main(String[] args) throws Exception {
        BufferedReader in =
            new BufferedReader(new InputStreamReader(System.in));
        String line;
        int cases = 0;
        while ((line = in.readLine()) != null) {
            if (line.isEmpty()) {
                continue;
            }
            String[] parts = line.split("\\|", -1);
            byte[] reply = unhex(parts[0]);
            String[] countStrs = parts[1].split(",");
            String[] slices = parts[2].split(",", -1);
            int[] counts = new int[countStrs.length];
            for (int i = 0; i < counts.length; i++) {
                counts[i] = Integer.parseInt(countStrs[i]);
            }
            byte[][] got = AsyncClient.demuxSlices(counts, reply);
            for (int i = 0; i < counts.length; i++) {
                byte[] want = unhex(slices[i]);
                if (!java.util.Arrays.equals(got[i], want)) {
                    System.err.println(
                        "case " + cases + " packet " + i + " demux mismatch");
                    System.exit(1);
                }
            }
            cases++;
        }
        if (cases == 0) {
            System.err.println("no demux cases on stdin");
            System.exit(1);
        }
        System.out.println("demux ok (" + cases + " cases)");
    }

    private static byte[] unhex(String s) {
        if (s.equals("-")) {
            return new byte[0];
        }
        byte[] out = new byte[s.length() / 2];
        for (int i = 0; i < out.length; i++) {
            out[i] = (byte) Integer.parseInt(
                s.substring(2 * i, 2 * i + 2), 16);
        }
        return out;
    }
}
