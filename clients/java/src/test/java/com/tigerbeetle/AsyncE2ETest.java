// Pipelined async e2e against a live server (spawned by
// tests/test_foreign_clients.py): N create_transfers batches and
// interleaved lookups submitted WITHOUT awaiting — the worker
// coalesces adjacent create batches into one wire request and demuxes
// the reply back per-packet — then every future is awaited and
// checked.  Plain main(); prints "async e2e ok" on success.
package com.tigerbeetle;

import java.util.ArrayList;
import java.util.concurrent.CompletableFuture;

public final class AsyncE2ETest {
    public static void main(String[] args) throws Exception {
        String addr = System.getenv("TB_ADDRESS");
        long cluster = Long.parseLong(System.getenv("TB_CLUSTER"));
        String[] parts = addr.split(":");
        try (AsyncClient client = new AsyncClient(
                parts[0], Integer.parseInt(parts[1]), cluster)) {
            AccountBatch accounts = new AccountBatch(4);
            for (int id = 1; id <= 4; id++) {
                accounts.add();
                accounts.setId(id, 0);
                accounts.setLedger(1);
                accounts.setCode(1);
            }
            expect(client.createAccounts(accounts).get().getLength() == 0,
                   "create_accounts failures");

            // 8 single-transfer batches in flight at once; batch k uses
            // amount 10+k, and every odd batch is invalid (same debit
            // and credit account) so the demuxed failures interleave.
            ArrayList<CompletableFuture<CreateResultBatch>> futs =
                new ArrayList<>();
            for (int k = 0; k < 8; k++) {
                TransferBatch batch = new TransferBatch(1);
                batch.add();
                batch.setId(100 + k, 0);
                batch.setDebitAccountId(1, 0);
                batch.setCreditAccountId(k % 2 == 1 ? 1 : 2, 0);
                batch.setAmount(10 + k, 0);
                batch.setLedger(1);
                batch.setCode(1);
                futs.add(client.createTransfers(batch));
            }
            IdBatch ids = new IdBatch(2);
            ids.add(1, 0);
            ids.add(2, 0);
            CompletableFuture<AccountBatch> lookup = client.lookupAccounts(ids);
            for (int k = 0; k < 8; k++) {
                CreateResultBatch r = futs.get(k).get();
                if (k % 2 == 1) {
                    expect(r.getLength() == 1, "odd batch " + k + " must fail");
                    r.next();
                    expect(r.getIndex() == 0, "rebased index");
                    expect(r.getResult()
                               == Types.CreateTransferResult
                                     .AccountsMustBeDifferent.value,
                           "odd batch " + k + " result " + r.getResult());
                } else {
                    expect(r.getLength() == 0, "even batch " + k + " failed");
                }
            }
            AccountBatch rows = lookup.get();
            expect(rows.getLength() == 2, "lookup rows");
            // Debits on account 1: amounts 10+0,10+2,10+4,10+6 = 52.
            rows.next();
            expect(rows.getDebitsPostedLo() == 52, "debits_posted");
        }
        System.out.println("async e2e ok");
    }

    static void expect(boolean ok, String what) {
        if (!ok) {
            System.err.println("FAIL: " + what);
            System.exit(1);
        }
    }
}
