// Request submitted after close() — programming error, not a
// cluster condition.
package com.tigerbeetle;

public final class ClientClosedException extends ClientException {
    public ClientClosedException(String message) {
        super(message);
    }
}
