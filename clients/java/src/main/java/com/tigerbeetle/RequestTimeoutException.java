// The per-request deadline elapsed before a reply arrived.  The
// request may still commit server-side; a new client session (or the
// same session retrying under the same request number) observes the
// stored reply via at-most-once dedupe.  Retryable.
package com.tigerbeetle;

public final class RequestTimeoutException extends ClientException {
    public RequestTimeoutException(String message) {
        super(message);
    }
}
