// Wire protocol: the 256-byte VSR message header and checksums.
//
// Layout mirrors tigerbeetle_tpu/vsr/wire.py HEADER_DTYPE (a
// re-design of the reference's per-command header unions into one
// flat little-endian layout — reference:
// src/vsr/message_header.zig:17-103).  Checksums are SHA-256
// truncated to 128 bits: `checksum` covers header bytes [16, 256),
// `checksum_body` covers the body; both are verified before any
// message is trusted.  Byte-identical to the Go/TS clients
// (clients/fixtures/frames.json).
package com.tigerbeetle;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.security.MessageDigest;
import java.security.NoSuchAlgorithmException;

final class Wire {
    static final int HEADER_SIZE = 256;
    static final int MESSAGE_SIZE_MAX = 1 << 20;

    static final int OFF_CHECKSUM = 0;
    static final int OFF_CHECKSUM_BODY = 16;
    static final int OFF_CLIENT = 48;
    static final int OFF_CLUSTER = 64;
    static final int OFF_REQUEST = 112;
    static final int OFF_SIZE = 144;
    static final int OFF_COMMAND = 153;
    static final int OFF_OPERATION = 154;
    static final int OFF_VERSION = 155;

    static final int CMD_REQUEST = 5;
    static final int CMD_REPLY = 8;
    static final int CMD_EVICTION = 18;

    static final int OP_REGISTER = 2;

    static final int WIRE_VERSION = 1;

    private Wire() {}

    static byte[] checksum128(byte[] data, int offset, int length) {
        try {
            MessageDigest d = MessageDigest.getInstance("SHA-256");
            d.update(data, offset, length);
            byte[] sum = d.digest();
            byte[] out = new byte[16];
            System.arraycopy(sum, 0, out, 0, 16);
            return out;
        } catch (NoSuchAlgorithmException e) {
            throw new AssertionError(e);
        }
    }

    /** Frames one request: header + body, checksums finalized. */
    static byte[] buildRequest(long cluster, long clientLo, long clientHi,
                               int requestNumber, int operation,
                               byte[] body) {
        byte[] msg = new byte[HEADER_SIZE + body.length];
        System.arraycopy(body, 0, msg, HEADER_SIZE, body.length);
        ByteBuffer h = ByteBuffer.wrap(msg).order(ByteOrder.LITTLE_ENDIAN);
        h.putLong(OFF_CLIENT, clientLo);
        h.putLong(OFF_CLIENT + 8, clientHi);
        h.putLong(OFF_CLUSTER, cluster);
        h.putInt(OFF_REQUEST, requestNumber);
        h.putInt(OFF_SIZE, msg.length);
        h.put(OFF_COMMAND, (byte) CMD_REQUEST);
        h.put(OFF_OPERATION, (byte) operation);
        h.put(OFF_VERSION, (byte) WIRE_VERSION);

        byte[] bodySum = checksum128(msg, HEADER_SIZE, body.length);
        System.arraycopy(bodySum, 0, msg, OFF_CHECKSUM_BODY, 16);
        byte[] headSum = checksum128(msg, 16, HEADER_SIZE - 16);
        System.arraycopy(headSum, 0, msg, OFF_CHECKSUM, 16);
        return msg;
    }

    /** Verifies both checksums of a framed message. */
    static void verifyMessage(byte[] msg, int size) {
        byte[] headSum = checksum128(msg, 16, HEADER_SIZE - 16);
        for (int i = 0; i < 16; i++) {
            if (msg[OFF_CHECKSUM + i] != headSum[i]) {
                throw new IllegalStateException("header checksum mismatch");
            }
        }
        byte[] bodySum = checksum128(msg, HEADER_SIZE, size - HEADER_SIZE);
        for (int i = 0; i < 16; i++) {
            if (msg[OFF_CHECKSUM_BODY + i] != bodySum[i]) {
                throw new IllegalStateException("body checksum mismatch");
            }
        }
    }
}
