// Asynchronous pipelined client: the packet-queue model of the
// reference's async API (reference:
// src/clients/java/src/main/java/com/tigerbeetle/AsyncRequest.java,
// src/clients/c/tb_client/packet.zig) over the pure-TCP session.
//
// Submissions enqueue PACKETS and return CompletableFutures
// immediately; a worker thread drains the queue, COALESCING adjacent
// packets of the same batchable operation (create_accounts /
// create_transfers — the server's logical-batching surface,
// tigerbeetle_tpu/state_machine/demuxer.py) into one wire request up
// to BATCH_MAX events, and on reply DEMUXES the result slices back to
// each packet's future with indexes rebased to its sub-batch.  The VSR
// session keeps its at-most-once guarantee: one wire request in
// flight, any number of packets queued — exactly the reference's
// client pipeline.
package com.tigerbeetle;

import java.io.IOException;
import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.util.ArrayDeque;
import java.util.ArrayList;
import java.util.concurrent.CompletableFuture;

public final class AsyncClient implements AutoCloseable {
    private static final int EVENT_SIZE = 128;

    private final Client client;
    private final ArrayDeque<Packet> queue = new ArrayDeque<>();
    private final Thread worker;
    private volatile boolean closed;

    private static final class Packet {
        final int operation;
        final byte[] body;
        final CompletableFuture<byte[]> future = new CompletableFuture<>();

        Packet(int operation, byte[] body) {
            this.operation = operation;
            this.body = body;
        }

        int eventCount() {
            return body.length / EVENT_SIZE;
        }
    }

    public AsyncClient(String host, int port, long cluster)
            throws IOException {
        this.client = new Client(host, port, cluster);
        this.worker = new Thread(this::drainLoop, "tb-async-client");
        this.worker.setDaemon(true);
        this.worker.start();
    }

    @Override
    public void close() throws IOException {
        closed = true;
        synchronized (queue) {
            queue.notifyAll();
        }
        try {
            worker.join(5_000);
        } catch (InterruptedException e) {
            Thread.currentThread().interrupt();
        }
        failPending("client closed");
        client.close();
    }

    private void failPending(String why) {
        synchronized (queue) {
            for (Packet p : queue) {
                p.future.completeExceptionally(new IOException(why));
            }
            queue.clear();
        }
    }

    public CompletableFuture<CreateResultBatch> createAccounts(
            AccountBatch batch) {
        return submit(Client.OP_CREATE_ACCOUNTS, batch.toArray())
            .thenApply(b -> new CreateResultBatch(wrap(b)));
    }

    public CompletableFuture<CreateResultBatch> createTransfers(
            TransferBatch batch) {
        return submit(Client.OP_CREATE_TRANSFERS, batch.toArray())
            .thenApply(b -> new CreateResultBatch(wrap(b)));
    }

    public CompletableFuture<AccountBatch> lookupAccounts(IdBatch ids) {
        return submit(Client.OP_LOOKUP_ACCOUNTS, ids.toArray())
            .thenApply(b -> new AccountBatch(wrap(b)));
    }

    public CompletableFuture<TransferBatch> lookupTransfers(IdBatch ids) {
        return submit(Client.OP_LOOKUP_TRANSFERS, ids.toArray())
            .thenApply(b -> new TransferBatch(wrap(b)));
    }

    private static ByteBuffer wrap(byte[] body) {
        return ByteBuffer.wrap(body).order(ByteOrder.LITTLE_ENDIAN);
    }

    /** Enqueue one packet; the future completes when its (possibly
     * coalesced) wire request's reply is demuxed. */
    public CompletableFuture<byte[]> submit(int operation, byte[] body) {
        Packet p = new Packet(operation, body);
        synchronized (queue) {
            // Re-check under the lock: a concurrent close() may have
            // already drained the queue and stopped the worker.
            if (closed) {
                p.future.completeExceptionally(
                    new IOException("client closed"));
                return p.future;
            }
            queue.addLast(p);
            queue.notifyAll();
        }
        return p.future;
    }

    private static boolean batchable(int operation) {
        return operation == Client.OP_CREATE_ACCOUNTS
            || operation == Client.OP_CREATE_TRANSFERS;
    }

    /** A packet whose FINAL event carries flags.linked has an open
     * chain: coalescing another packet behind it would splice that
     * packet's first events into the chain (cross-packet
     * contamination the per-packet API forbids). Both event types
     * keep flags as a u16 at byte 118 of the 128-byte record. */
    private static boolean endsWithOpenChain(byte[] body) {
        if (body.length < EVENT_SIZE) {
            return false;
        }
        int off = body.length - EVENT_SIZE + 118;
        int flags = (body[off] & 0xFF) | ((body[off + 1] & 0xFF) << 8);
        return (flags & 1) != 0; // TransferFlags.linked / AccountFlags bit 0
    }

    private void drainLoop() {
        while (true) {
            ArrayList<Packet> group = new ArrayList<>();
            synchronized (queue) {
                while (queue.isEmpty() && !closed) {
                    try {
                        queue.wait();
                    } catch (InterruptedException e) {
                        failPending("worker interrupted");
                        return;
                    }
                }
                if (queue.isEmpty()) {
                    return; // closed and drained
                }
                Packet head = queue.removeFirst();
                group.add(head);
                // Coalesce adjacent same-operation batchable packets
                // while the combined batch stays within BATCH_MAX and
                // no packet in the group leaves a linked chain open.
                if (batchable(head.operation)) {
                    int events = head.eventCount();
                    while (!queue.isEmpty()
                            && queue.peekFirst().operation == head.operation
                            && !endsWithOpenChain(
                                group.get(group.size() - 1).body)
                            && events + queue.peekFirst().eventCount()
                                <= Client.BATCH_MAX) {
                        Packet next = queue.removeFirst();
                        events += next.eventCount();
                        group.add(next);
                    }
                }
            }
            runGroup(group);
        }
    }

    private void runGroup(ArrayList<Packet> group) {
        int total = 0;
        for (Packet p : group) {
            total += p.body.length;
        }
        byte[] events = new byte[total];
        int at = 0;
        for (Packet p : group) {
            System.arraycopy(p.body, 0, events, at, p.body.length);
            at += p.body.length;
        }
        byte[] reply;
        try {
            reply = client.request(group.get(0).operation, events);
        } catch (IOException e) {
            for (Packet p : group) {
                p.future.completeExceptionally(e);
            }
            return;
        }
        if (group.size() == 1) {
            group.get(0).future.complete(reply);
            return;
        }
        demux(group, reply);
    }

    private static void demux(ArrayList<Packet> group, byte[] reply) {
        int[] counts = new int[group.size()];
        for (int i = 0; i < group.size(); i++) {
            counts[i] = group.get(i).eventCount();
        }
        byte[][] slices = demuxSlices(counts, reply);
        for (int i = 0; i < group.size(); i++) {
            group.get(i).future.complete(slices[i]);
        }
    }

    /** Split a coalesced create_* reply ({index u32, result u32} pairs
     * sorted by index) into per-packet slices with rebased indexes —
     * the client-side mirror of the server demuxer (reference:
     * src/state_machine.zig:133-176 DemuxerType).  Pure function:
     * asserted against clients/fixtures/demux.json. */
    static byte[][] demuxSlices(int[] eventCounts, byte[] reply) {
        ByteBuffer results = wrap(reply);
        int n = reply.length / 8;
        byte[][] out = new byte[eventCounts.length][];
        int cursor = 0;      // next unread result pair
        int offset = 0;      // first event index of the current packet
        for (int k = 0; k < eventCounts.length; k++) {
            int count = eventCounts[k];
            int start = cursor;
            while (cursor < n
                    && (results.getInt(cursor * 8) & 0xFFFFFFFFL)
                        < offset + count) {
                cursor++;
            }
            byte[] slice = new byte[(cursor - start) * 8];
            for (int i = start; i < cursor; i++) {
                ByteBuffer sb = ByteBuffer.wrap(slice, (i - start) * 8, 8)
                    .order(ByteOrder.LITTLE_ENDIAN);
                sb.putInt(results.getInt(i * 8) - offset);
                sb.putInt(results.getInt(i * 8 + 4));
            }
            offset += count;
            out[k] = slice;
        }
        return out;
    }
}
