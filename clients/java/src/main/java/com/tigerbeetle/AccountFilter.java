// Builder for the 64-byte AccountFilter wire record driving
// get_account_transfers / get_account_balances
// (tigerbeetle_tpu/types.py ACCOUNT_FILTER_DTYPE; reference:
// src/tigerbeetle.zig:288-322 and the generated AccountFilterBatch —
// src/clients/java/src/main/java/com/tigerbeetle/AccountFilterBatch.java).
package com.tigerbeetle;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;

public final class AccountFilter {
    static final int SIZE = 64;

    private final ByteBuffer buffer =
        ByteBuffer.allocate(SIZE).order(ByteOrder.LITTLE_ENDIAN);

    public AccountFilter() {
        // limit defaults to the max reply batch; debits+credits on.
        setLimit(Client.BATCH_MAX);
        setDebits(true);
        setCredits(true);
    }

    public void setAccountId(long lo, long hi) {
        buffer.putLong(0, lo).putLong(8, hi);
    }

    public long getAccountIdLo() { return buffer.getLong(0); }
    public long getAccountIdHi() { return buffer.getLong(8); }

    /** Inclusive minimum server timestamp; 0 = no bound. */
    public void setTimestampMin(long ns) { buffer.putLong(16, ns); }
    public long getTimestampMin() { return buffer.getLong(16); }

    /** Inclusive maximum server timestamp; 0 = no bound. */
    public void setTimestampMax(long ns) { buffer.putLong(24, ns); }
    public long getTimestampMax() { return buffer.getLong(24); }

    /** Maximum result rows (capped by the 1 MiB reply). */
    public void setLimit(int limit) { buffer.putInt(32, limit); }
    public int getLimit() { return buffer.getInt(32); }

    private void setFlag(int bit, boolean on) {
        int flags = buffer.getInt(36);
        buffer.putInt(36, on ? flags | bit : flags & ~bit);
    }

    private boolean getFlag(int bit) {
        return (buffer.getInt(36) & bit) != 0;
    }

    /** Include rows where the account is the debit side. */
    public void setDebits(boolean on) {
        setFlag(Types.AccountFilterFlags.Debits, on);
    }

    public boolean getDebits() {
        return getFlag(Types.AccountFilterFlags.Debits);
    }

    /** Include rows where the account is the credit side. */
    public void setCredits(boolean on) {
        setFlag(Types.AccountFilterFlags.Credits, on);
    }

    public boolean getCredits() {
        return getFlag(Types.AccountFilterFlags.Credits);
    }

    /** Newest-first results. */
    public void setReversed(boolean on) {
        setFlag(Types.AccountFilterFlags.Reversed, on);
    }

    public boolean getReversed() {
        return getFlag(Types.AccountFilterFlags.Reversed);
    }

    byte[] toArray() {
        return buffer.array().clone();
    }
}
