// 128-bit id/amount helpers (the reference's
// src/clients/java/src/main/java/com/tigerbeetle/UInt128.java):
// conversions between (lo, hi) long limbs, 16-byte little-endian
// arrays, BigInteger, and java.util.UUID, plus a monotonic
// time-based id() generator (ULID-shaped: millisecond timestamp in
// the high bits, random low bits, strictly increasing within a
// process — reference id() semantics).
package com.tigerbeetle;

import java.math.BigInteger;
import java.security.SecureRandom;
import java.util.UUID;

public final class UInt128 {
    public static final int SIZE = 16;

    private UInt128() {}

    /** (lo, hi) limbs -> 16 little-endian bytes. */
    public static byte[] asBytes(long lo, long hi) {
        byte[] out = new byte[SIZE];
        for (int i = 0; i < 8; i++) {
            out[i] = (byte) (lo >>> (8 * i));
            out[8 + i] = (byte) (hi >>> (8 * i));
        }
        return out;
    }

    /** 16 little-endian bytes -> low limb. */
    public static long bytesToLo(byte[] bytes) {
        checkLength(bytes);
        long v = 0;
        for (int i = 7; i >= 0; i--) {
            v = (v << 8) | (bytes[i] & 0xFFL);
        }
        return v;
    }

    /** 16 little-endian bytes -> high limb. */
    public static long bytesToHi(byte[] bytes) {
        checkLength(bytes);
        long v = 0;
        for (int i = 15; i >= 8; i--) {
            v = (v << 8) | (bytes[i] & 0xFFL);
        }
        return v;
    }

    /** Non-negative BigInteger (must fit 128 bits) -> low limb. */
    public static long bigIntegerToLo(BigInteger value) {
        return limbs(value)[0];
    }

    /** Non-negative BigInteger (must fit 128 bits) -> high limb. */
    public static long bigIntegerToHi(BigInteger value) {
        return limbs(value)[1];
    }

    /** (lo, hi) limbs -> non-negative BigInteger. */
    public static BigInteger asBigInteger(long lo, long hi) {
        BigInteger l = BigInteger.valueOf(lo & Long.MAX_VALUE);
        if (lo < 0) {
            l = l.setBit(63);
        }
        BigInteger h = BigInteger.valueOf(hi & Long.MAX_VALUE);
        if (hi < 0) {
            h = h.setBit(63);
        }
        return h.shiftLeft(64).or(l);
    }

    /** UUID (its canonical msb/lsb halves) -> (lo, hi): lsb is the
     * low limb, msb the high limb. */
    public static long uuidToLo(UUID uuid) {
        return uuid.getLeastSignificantBits();
    }

    public static long uuidToHi(UUID uuid) {
        return uuid.getMostSignificantBits();
    }

    public static UUID asUuid(long lo, long hi) {
        return new UUID(hi, lo);
    }

    private static final SecureRandom RANDOM = new SecureRandom();
    private static final Object ID_LOCK = new Object();
    private static long idLastMillis = 0;
    private static long idLastLo = 0;
    private static long idLastHi = 0;

    /** Time-ordered unique 128-bit id as (lo, hi) limbs packed into a
     * two-element array {lo, hi}: 48-bit millisecond timestamp in the
     * topmost bits, 80 random bits below, strictly monotonic within
     * the process (same-millisecond calls increment the random part —
     * reference UInt128.id()). */
    public static long[] id() {
        synchronized (ID_LOCK) {
            long now = System.currentTimeMillis();
            if (now > idLastMillis) {
                idLastMillis = now;
                // hi = timestamp(48) | random(16); lo = random(64).
                idLastHi = (now << 16) | (RANDOM.nextInt(1 << 16) & 0xFFFFL);
                idLastLo = RANDOM.nextLong();
            } else {
                // Same or regressed millisecond: increment as u128.
                idLastLo++;
                if (idLastLo == 0) {
                    idLastHi++;
                }
            }
            return new long[] {idLastLo, idLastHi};
        }
    }

    private static long[] limbs(BigInteger value) {
        if (value.signum() < 0 || value.bitLength() > 128) {
            throw new IllegalArgumentException(
                "value must be a non-negative 128-bit integer");
        }
        long lo = value.longValue();
        long hi = value.shiftRight(64).longValue();
        return new long[] {lo, hi};
    }

    private static void checkLength(byte[] bytes) {
        if (bytes == null || bytes.length != SIZE) {
            throw new IllegalArgumentException("expected 16 bytes");
        }
    }
}
