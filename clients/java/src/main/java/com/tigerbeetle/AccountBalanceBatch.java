// Typed cursor over 128-byte AccountBalance reply rows
// (tigerbeetle_tpu/types.py ACCOUNT_BALANCE_DTYPE; reference:
// src/tigerbeetle.zig:65-78 and the generated AccountBalanceBatch).
package com.tigerbeetle;

import java.nio.ByteBuffer;

public final class AccountBalanceBatch extends Batch {
    static final int ELEMENT_SIZE = 128;

    AccountBalanceBatch(ByteBuffer wrapped) {
        super(wrapped, ELEMENT_SIZE);
    }

    public long getDebitsPendingLo() { return getU64(0); }
    public long getDebitsPendingHi() { return getU64(8); }

    public long getDebitsPostedLo() { return getU64(16); }
    public long getDebitsPostedHi() { return getU64(24); }

    public long getCreditsPendingLo() { return getU64(32); }
    public long getCreditsPendingHi() { return getU64(40); }

    public long getCreditsPostedLo() { return getU64(48); }
    public long getCreditsPostedHi() { return getU64(56); }

    /** Server timestamp of the transfer that produced this snapshot. */
    public long getTimestamp() { return getU64(64); }
}
