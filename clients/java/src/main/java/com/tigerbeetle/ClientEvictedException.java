// The cluster evicted this session (too many live clients,
// reference: src/vsr.zig Command.eviction).  The session is dead;
// callers must build a NEW Client (new client id) to continue.
package com.tigerbeetle;

public final class ClientEvictedException extends ClientException {
    public ClientEvictedException(String message) {
        super(message);
    }
}
