// Typed client failure taxonomy (the reference's per-condition
// exception classes — src/clients/java/src/main/java/com/tigerbeetle/
// RequestException.java and friends).  All extend IOException so
// pre-taxonomy call sites keep compiling; catch the subtypes to
// distinguish retryable timeouts from fatal session states.
package com.tigerbeetle;

import java.io.IOException;

public class ClientException extends IOException {
    public ClientException(String message) {
        super(message);
    }
}
