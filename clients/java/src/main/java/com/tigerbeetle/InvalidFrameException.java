// The peer sent a malformed frame (bad size word or checksum) —
// indicates a protocol bug or corrupted transport, never retried.
package com.tigerbeetle;

public final class InvalidFrameException extends ClientException {
    public InvalidFrameException(String message) {
        super(message);
    }
}
