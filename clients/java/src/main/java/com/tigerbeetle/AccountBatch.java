// Typed cursor over 128-byte Account wire elements
// (tigerbeetle_tpu/types.py ACCOUNT_DTYPE; reference:
// src/tigerbeetle.zig:7-40).
package com.tigerbeetle;

import java.nio.ByteBuffer;

public final class AccountBatch extends Batch {
    static final int ELEMENT_SIZE = 128;

    public AccountBatch(int capacity) {
        super(capacity, ELEMENT_SIZE);
    }

    AccountBatch(ByteBuffer wrapped) {
        super(wrapped, ELEMENT_SIZE);
    }

    public void setId(long lo, long hi) { setU64(0, lo); setU64(8, hi); }
    public long getIdLo() { return getU64(0); }
    public long getIdHi() { return getU64(8); }

    public long getDebitsPendingLo() { return getU64(16); }
    public long getDebitsPendingHi() { return getU64(24); }
    public long getDebitsPostedLo() { return getU64(32); }
    public long getDebitsPostedHi() { return getU64(40); }
    public long getCreditsPendingLo() { return getU64(48); }
    public long getCreditsPendingHi() { return getU64(56); }
    public long getCreditsPostedLo() { return getU64(64); }
    public long getCreditsPostedHi() { return getU64(72); }

    public void setUserData128(long lo, long hi) { setU64(80, lo); setU64(88, hi); }
    public long getUserData128Lo() { return getU64(80); }
    public long getUserData128Hi() { return getU64(88); }

    public void setUserData64(long value) { setU64(96, value); }
    public long getUserData64() { return getU64(96); }

    public void setUserData32(int value) { setU32(104, value); }
    public int getUserData32() { return getU32(104); }

    public void setLedger(int ledger) { setU32(112, ledger); }
    public int getLedger() { return getU32(112); }

    public void setCode(int code) { setU16(116, code); }
    public int getCode() { return getU16(116); }

    /** Bit set of Types.AccountFlags values. */
    public void setFlags(int flags) { setU16(118, flags); }
    public int getFlags() { return getU16(118); }

    /** Server-assigned; must be zero on create. */
    public long getTimestamp() { return getU64(120); }
}
