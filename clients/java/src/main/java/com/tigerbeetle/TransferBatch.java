// Typed cursor over 128-byte Transfer wire elements
// (tigerbeetle_tpu/types.py TRANSFER_DTYPE; reference:
// src/tigerbeetle.zig:80-111 and the reference's generated
// TransferBatch — src/clients/java/src/main/java/com/tigerbeetle/).
package com.tigerbeetle;

import java.nio.ByteBuffer;

public final class TransferBatch extends Batch {
    static final int ELEMENT_SIZE = 128;

    public TransferBatch(int capacity) {
        super(capacity, ELEMENT_SIZE);
    }

    TransferBatch(ByteBuffer wrapped) {
        super(wrapped, ELEMENT_SIZE);
    }

    public void setId(long lo, long hi) { setU64(0, lo); setU64(8, hi); }
    public long getIdLo() { return getU64(0); }
    public long getIdHi() { return getU64(8); }

    public void setDebitAccountId(long lo, long hi) { setU64(16, lo); setU64(24, hi); }
    public long getDebitAccountIdLo() { return getU64(16); }
    public long getDebitAccountIdHi() { return getU64(24); }

    public void setCreditAccountId(long lo, long hi) { setU64(32, lo); setU64(40, hi); }
    public long getCreditAccountIdLo() { return getU64(32); }
    public long getCreditAccountIdHi() { return getU64(40); }

    public void setAmount(long lo, long hi) { setU64(48, lo); setU64(56, hi); }
    public long getAmountLo() { return getU64(48); }
    public long getAmountHi() { return getU64(56); }

    public void setPendingId(long lo, long hi) { setU64(64, lo); setU64(72, hi); }
    public long getPendingIdLo() { return getU64(64); }
    public long getPendingIdHi() { return getU64(72); }

    public void setUserData128(long lo, long hi) { setU64(80, lo); setU64(88, hi); }
    public long getUserData128Lo() { return getU64(80); }
    public long getUserData128Hi() { return getU64(88); }

    public void setUserData64(long value) { setU64(96, value); }
    public long getUserData64() { return getU64(96); }

    public void setUserData32(int value) { setU32(104, value); }
    public int getUserData32() { return getU32(104); }

    public void setTimeout(int seconds) { setU32(108, seconds); }
    public int getTimeout() { return getU32(108); }

    public void setLedger(int ledger) { setU32(112, ledger); }
    public int getLedger() { return getU32(112); }

    public void setCode(int code) { setU16(116, code); }
    public int getCode() { return getU16(116); }

    /** Bit set of Types.TransferFlags values. */
    public void setFlags(int flags) { setU16(118, flags); }
    public int getFlags() { return getU16(118); }

    /** Server-assigned; must be zero on create. */
    public long getTimestamp() { return getU64(120); }
}
