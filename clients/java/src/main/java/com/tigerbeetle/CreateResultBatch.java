// Read-only cursor over {index u32, result u32} reply elements for
// create_accounts / create_transfers.  The reply contains FAILURES
// ONLY: an empty batch means every event succeeded
// (tigerbeetle_tpu/types.py CREATE_RESULT_DTYPE; reference:
// src/tigerbeetle.zig:267-285).
package com.tigerbeetle;

import java.nio.ByteBuffer;

public final class CreateResultBatch extends Batch {
    static final int ELEMENT_SIZE = 8;

    CreateResultBatch(ByteBuffer wrapped) {
        super(wrapped, ELEMENT_SIZE);
    }

    /** Index of the failed event within the request batch. */
    public int getIndex() { return getU32(0); }

    /** Raw result code (Types.CreateAccountResult /
     * Types.CreateTransferResult value). */
    public int getResult() { return getU32(4); }
}
