// Pure-Java client for the tigerbeetle_tpu cluster.
//
// Batch is the zero-copy event encoder the north star names: a cursor
// over a direct little-endian ByteBuffer holding fixed 128-byte wire
// elements, filled in place and handed to the socket without any
// per-event object allocation (the same shape as the reference's
// com.tigerbeetle.Batch — src/clients/java/src/main/java/com/
// tigerbeetle/Batch.java:15-45 — minus JNI: this client speaks the
// TCP wire protocol directly, like the Go/TS clients here).
package com.tigerbeetle;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;

/** Cursor over a direct little-endian buffer of fixed-size elements. */
public abstract class Batch {
    final ByteBuffer buffer;
    private final int elementSize;
    private int length;    // elements written
    private int position;  // current element index, -1 = before first

    Batch(int capacity, int elementSize) {
        this.buffer =
            ByteBuffer.allocateDirect(capacity * elementSize)
                .order(ByteOrder.LITTLE_ENDIAN);
        this.elementSize = elementSize;
        this.length = 0;
        this.position = -1;
    }

    /** Wraps reply bytes (read path). */
    Batch(ByteBuffer wrapped, int elementSize) {
        this.buffer = wrapped.order(ByteOrder.LITTLE_ENDIAN);
        this.elementSize = elementSize;
        this.length = wrapped.capacity() / elementSize;
        this.position = -1;
    }

    /** Number of elements in the batch. */
    public int getLength() {
        return length;
    }

    public int getCapacity() {
        return buffer.capacity() / elementSize;
    }

    /** Appends a zeroed element and moves the cursor to it. */
    public void add() {
        if (length >= getCapacity()) {
            throw new IndexOutOfBoundsException("batch is full");
        }
        position = length++;
        int base = at(0);
        for (int i = 0; i < elementSize; i += 8) {
            buffer.putLong(base + i, 0L);
        }
    }

    /** Advances the cursor; false when past the last element. */
    public boolean next() {
        if (position + 1 >= length) {
            return false;
        }
        position++;
        return true;
    }

    public void beforeFirst() {
        position = -1;
    }

    public void setPosition(int index) {
        if (index < 0 || index >= length) {
            throw new IndexOutOfBoundsException("position " + index);
        }
        position = index;
    }

    public int getPosition() {
        return position;
    }

    final int at(int fieldOffset) {
        if (position < 0) {
            throw new IllegalStateException("cursor before first element");
        }
        return position * elementSize + fieldOffset;
    }

    final long getU64(int offset) {
        return buffer.getLong(at(offset));
    }

    final void setU64(int offset, long value) {
        buffer.putLong(at(offset), value);
    }

    final int getU32(int offset) {
        return buffer.getInt(at(offset));
    }

    final void setU32(int offset, int value) {
        buffer.putInt(at(offset), value);
    }

    final int getU16(int offset) {
        return buffer.getShort(at(offset)) & 0xFFFF;
    }

    final void setU16(int offset, int value) {
        buffer.putShort(at(offset), (short) value);
    }

    /** Serializes the written elements (for the request body). */
    final byte[] toArray() {
        byte[] out = new byte[length * elementSize];
        ByteBuffer dup = buffer.duplicate();
        dup.position(0).limit(out.length);
        dup.get(out);
        return out;
    }
}
