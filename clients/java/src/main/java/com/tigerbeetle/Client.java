// Blocking pure-Java client session for the tigerbeetle_tpu cluster —
// the TCP counterpart of the reference's com.tigerbeetle.Client
// (src/clients/java/src/main/java/com/tigerbeetle/Client.java), minus
// JNI: like the Go/TS clients here it speaks the wire protocol
// directly.  One registered VSR session, one request in flight,
// retransmission under the same request number is made safe by the
// server's at-most-once session dedupe.
package com.tigerbeetle;

import java.io.IOException;
import java.io.InputStream;
import java.io.OutputStream;
import java.net.InetSocketAddress;
import java.net.Socket;
import java.net.SocketTimeoutException;
import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.util.Random;

public final class Client implements AutoCloseable {
    /** Most events per request (1 MiB message - 256 B header,
     * 128 B/event; reference: src/state_machine.zig:75-81). */
    public static final int BATCH_MAX =
        (Wire.MESSAGE_SIZE_MAX - Wire.HEADER_SIZE) / 128;

    // Operation codes from the generated enum (tigerbeetle_tpu/
    // types.py Operation is the single source of truth).
    static final int OP_CREATE_ACCOUNTS =
        Types.Operation.CreateAccounts.value;
    static final int OP_CREATE_TRANSFERS =
        Types.Operation.CreateTransfers.value;
    static final int OP_LOOKUP_ACCOUNTS =
        Types.Operation.LookupAccounts.value;
    static final int OP_LOOKUP_TRANSFERS =
        Types.Operation.LookupTransfers.value;
    static final int OP_GET_ACCOUNT_TRANSFERS =
        Types.Operation.GetAccountTransfers.value;
    static final int OP_GET_ACCOUNT_BALANCES =
        Types.Operation.GetAccountBalances.value;

    private final Socket socket;
    private final InputStream in;
    private final OutputStream out;
    private final long cluster;
    private final long clientLo;
    private final long clientHi;
    private int requestNumber;
    private boolean registered;
    private boolean evicted;
    private byte[] recv = new byte[0];
    private int recvLen = 0;

    /** Per-request deadline in milliseconds (default 30s). */
    public int timeoutMillis = 30_000;
    private static final int RETRANSMIT_MILLIS = 1_000;

    public Client(String host, int port, long cluster) throws IOException {
        this(host, port, cluster, new Random().nextLong() | 1L, 0L);
    }

    /** clientId (lo, hi limbs) must be unique per live session. */
    public Client(String host, int port, long cluster, long clientLo,
                  long clientHi) throws IOException {
        this.socket = new Socket();
        this.socket.connect(new InetSocketAddress(host, port), 10_000);
        this.socket.setTcpNoDelay(true);
        this.in = socket.getInputStream();
        this.out = socket.getOutputStream();
        this.cluster = cluster;
        this.clientLo = clientLo;
        this.clientHi = clientHi;
    }

    private boolean closed;

    @Override
    public void close() throws IOException {
        closed = true;
        socket.close();
    }

    /** create_accounts: reply lists FAILURES only (empty = all ok). */
    public CreateResultBatch createAccounts(AccountBatch batch)
            throws IOException {
        return new CreateResultBatch(
            wrap(request(OP_CREATE_ACCOUNTS, batch.toArray())));
    }

    /** create_transfers: reply lists FAILURES only (empty = all ok). */
    public CreateResultBatch createTransfers(TransferBatch batch)
            throws IOException {
        return new CreateResultBatch(
            wrap(request(OP_CREATE_TRANSFERS, batch.toArray())));
    }

    /** lookup_accounts: found records only. */
    public AccountBatch lookupAccounts(IdBatch ids) throws IOException {
        return new AccountBatch(
            wrap(request(OP_LOOKUP_ACCOUNTS, ids.toArray())));
    }

    /** lookup_transfers: found records only. */
    public TransferBatch lookupTransfers(IdBatch ids) throws IOException {
        return new TransferBatch(
            wrap(request(OP_LOOKUP_TRANSFERS, ids.toArray())));
    }

    /** get_account_transfers: transfers touching the filter's account,
     * timestamp-ordered (reference: src/state_machine.zig:786-1008). */
    public TransferBatch getAccountTransfers(AccountFilter filter)
            throws IOException {
        return new TransferBatch(
            wrap(request(OP_GET_ACCOUNT_TRANSFERS, filter.toArray())));
    }

    /** get_account_balances: historical balance snapshots (requires
     * the account's history flag). */
    public AccountBalanceBatch getAccountBalances(AccountFilter filter)
            throws IOException {
        return new AccountBalanceBatch(
            wrap(request(OP_GET_ACCOUNT_BALANCES, filter.toArray())));
    }

    private static ByteBuffer wrap(byte[] body) {
        return ByteBuffer.wrap(body).order(ByteOrder.LITTLE_ENDIAN);
    }

    /** Raw request: registers on first use, returns the reply body. */
    public synchronized byte[] request(int operation, byte[] body)
            throws IOException {
        if (!registered) {
            roundtrip(Wire.OP_REGISTER, 0, new byte[0]);
            registered = true;
        }
        requestNumber++;
        return roundtrip(operation, requestNumber, body);
    }

    private byte[] roundtrip(int operation, int reqNumber, byte[] body)
            throws IOException {
        if (closed) {
            throw new ClientClosedException("client is closed");
        }
        if (evicted) {
            throw new ClientEvictedException("session evicted");
        }
        byte[] msg = Wire.buildRequest(
            cluster, clientLo, clientHi, reqNumber, operation, body);
        long deadline = System.currentTimeMillis() + timeoutMillis;
        while (true) {
            long now = System.currentTimeMillis();
            if (now > deadline) {
                throw new RequestTimeoutException(
                    "request " + reqNumber + " timed out after "
                    + timeoutMillis + "ms");
            }
            // Clamp >= 1: a 0 soTimeout means INFINITE in Java.
            socket.setSoTimeout(
                (int) Math.max(1, Math.min(RETRANSMIT_MILLIS, deadline - now)));
            out.write(msg);
            out.flush();
            while (true) {
                byte[] reply;
                int size;
                try {
                    int[] sz = new int[1];
                    reply = readMessage(sz);
                    size = sz[0];
                } catch (SocketTimeoutException e) {
                    break; // retransmit under the same request number
                }
                ByteBuffer h =
                    ByteBuffer.wrap(reply).order(ByteOrder.LITTLE_ENDIAN);
                int command = reply[Wire.OFF_COMMAND] & 0xFF;
                if (command == Wire.CMD_EVICTION) {
                    evicted = true;
                    throw new ClientEvictedException("session evicted");
                }
                if (command != Wire.CMD_REPLY) {
                    continue;
                }
                if (h.getInt(Wire.OFF_REQUEST) != reqNumber) {
                    continue; // stale duplicate
                }
                byte[] bodyOut = new byte[size - Wire.HEADER_SIZE];
                System.arraycopy(reply, Wire.HEADER_SIZE, bodyOut, 0,
                                 bodyOut.length);
                return bodyOut;
            }
        }
    }

    private byte[] readMessage(int[] sizeOut) throws IOException {
        while (true) {
            if (recvLen >= Wire.HEADER_SIZE) {
                ByteBuffer h =
                    ByteBuffer.wrap(recv).order(ByteOrder.LITTLE_ENDIAN);
                int size = h.getInt(Wire.OFF_SIZE);
                if (size < Wire.HEADER_SIZE
                    || size > Wire.MESSAGE_SIZE_MAX + Wire.HEADER_SIZE) {
                    throw new InvalidFrameException("bad frame size " + size);
                }
                if (recvLen >= size) {
                    byte[] msg = new byte[size];
                    System.arraycopy(recv, 0, msg, 0, size);
                    System.arraycopy(recv, size, recv, 0, recvLen - size);
                    recvLen -= size;
                    Wire.verifyMessage(msg, size);
                    sizeOut[0] = size;
                    return msg;
                }
            }
            byte[] buf = new byte[1 << 16];
            int n = in.read(buf);
            if (n < 0) {
                throw new IOException("connection closed");
            }
            if (recvLen + n > recv.length) {
                byte[] grown =
                    new byte[Math.max(recv.length * 2, recvLen + n)];
                System.arraycopy(recv, 0, grown, 0, recvLen);
                recv = grown;
            }
            System.arraycopy(buf, 0, recv, recvLen, n);
            recvLen += n;
        }
    }
}
