// In-process test double with the Client's API shape: every request
// ECHOES its event payload back as the reply body instead of touching
// a cluster (the reference's echo client —
// src/clients/c/tb_client/echo_client.zig:1-20 — swaps the real
// request path for a body copy so binding marshaling round-trips are
// testable without a server).  createAccounts/createTransfers
// therefore report zero failures, and the typed echo helpers hand the
// submitted batch back through the reply-side decoder.
package com.tigerbeetle;

import java.io.IOException;
import java.nio.ByteBuffer;
import java.nio.ByteOrder;

public final class EchoClient implements AutoCloseable {
    private boolean closed;

    public EchoClient() {}

    @Override
    public void close() {
        closed = true;
    }

    /** Echo: the reply body IS the request body. */
    public synchronized byte[] request(int operation, byte[] body)
            throws IOException {
        if (closed) {
            throw new ClientClosedException("client is closed");
        }
        if (body.length > Wire.MESSAGE_SIZE_MAX - Wire.HEADER_SIZE) {
            throw new InvalidFrameException("body exceeds message size");
        }
        return body.clone();
    }

    /** create_accounts double: no failures (reply decodes empty). */
    public CreateResultBatch createAccounts(AccountBatch batch)
            throws IOException {
        request(Client.OP_CREATE_ACCOUNTS, batch.toArray());
        return new CreateResultBatch(wrap(new byte[0]));
    }

    /** create_transfers double: no failures (reply decodes empty). */
    public CreateResultBatch createTransfers(TransferBatch batch)
            throws IOException {
        request(Client.OP_CREATE_TRANSFERS, batch.toArray());
        return new CreateResultBatch(wrap(new byte[0]));
    }

    /** Marshaling round-trip: encode, echo, decode as accounts. */
    public AccountBatch echoAccounts(AccountBatch batch) throws IOException {
        return new AccountBatch(
            wrap(request(Client.OP_LOOKUP_ACCOUNTS, batch.toArray())));
    }

    /** Marshaling round-trip: encode, echo, decode as transfers. */
    public TransferBatch echoTransfers(TransferBatch batch)
            throws IOException {
        return new TransferBatch(
            wrap(request(Client.OP_LOOKUP_TRANSFERS, batch.toArray())));
    }

    private static ByteBuffer wrap(byte[] body) {
        return ByteBuffer.wrap(body).order(ByteOrder.LITTLE_ENDIAN);
    }
}
