// Cursor over 16-byte u128 ids (lookup_accounts / lookup_transfers
// request bodies — tigerbeetle_tpu/types.py U128_PAIR_DTYPE).
package com.tigerbeetle;

public final class IdBatch extends Batch {
    static final int ELEMENT_SIZE = 16;

    public IdBatch(int capacity) {
        super(capacity, ELEMENT_SIZE);
    }

    public void add(long lo, long hi) {
        add();
        setU64(0, lo);
        setU64(8, hi);
    }

    public long getLo() { return getU64(0); }
    public long getHi() { return getU64(8); }
}
