// 128-bit id helpers (the reference's src/clients/node id/UInt128
// surface).  u128 values are `bigint` end to end in this client; the
// helpers here convert to/from the 16-byte little-endian wire image
// and generate time-ordered unique ids (ULID-shaped: millisecond
// timestamp in the topmost bits, random bits below, strictly
// monotonic within the process — reference id() semantics).

import { randomFillSync } from "node:crypto";

export const U128_MAX = (1n << 128n) - 1n;

/** bigint -> 16-byte little-endian image (must fit 128 bits). */
export function u128Bytes(value: bigint): Buffer {
  if (value < 0n || value > U128_MAX) {
    throw new RangeError("value must be a non-negative 128-bit integer");
  }
  const out = Buffer.alloc(16);
  out.writeBigUInt64LE(value & 0xffffffffffffffffn, 0);
  out.writeBigUInt64LE(value >> 64n, 8);
  return out;
}

/** 16-byte little-endian image -> bigint. */
export function u128FromBytes(bytes: Buffer): bigint {
  if (bytes.length !== 16) {
    throw new RangeError("expected 16 bytes");
  }
  return bytes.readBigUInt64LE(0) | (bytes.readBigUInt64LE(8) << 64n);
}

let idLastMillis = 0n;
let idLast = 0n;

/** Time-ordered unique 128-bit id: 48-bit millisecond timestamp in
 * the topmost bits, 80 random bits below, strictly monotonic within
 * the process (same-millisecond calls increment). */
export function id(): bigint {
  const now = BigInt(Date.now());
  if (now > idLastMillis) {
    idLastMillis = now;
    const rand = Buffer.alloc(10);
    randomFillSync(rand);
    const randBits =
      rand.readBigUInt64LE(0) | (BigInt(rand.readUInt16LE(8)) << 64n);
    idLast = (now << 80n) | randBits;
  } else {
    idLast += 1n;
  }
  return idLast & U128_MAX;
}
