// TypeScript (Node) client for a tigerbeetle_tpu cluster.
//
// Pure TypeScript over node:net — it speaks the TCP wire protocol
// directly (256-byte SHA-256/128-checksummed headers,
// tigerbeetle_tpu/vsr/wire.py) instead of wrapping the C ABI, the
// role the reference's Node client fills via N-API (reference:
// src/clients/node/, src/node.zig).  One VSR session, promise-based:
// requests queue client-side and go out one at a time (the session
// invariant); results resolve with FAILURES ONLY for create batches.
//
// u64/u128 values are `bigint` end to end.

import * as net from "node:net";
import { createHash } from "node:crypto";

export { U128_MAX, id, u128Bytes, u128FromBytes } from "./u128";

import {
  Account,
  AccountBalance,
  AccountFilter,
  AccountFilterFlags,
  CreateResult,
  Operation,
  Transfer,
} from "./types.ts";

export { Operation };

const HEADER_SIZE = 256;
const MESSAGE_SIZE_MAX = 1 << 20;
const OFF_CHECKSUM = 0;
const OFF_CHECKSUM_BODY = 16;
const OFF_CLIENT = 48;
const OFF_CLUSTER = 64;
const OFF_REQUEST = 112;
const OFF_SIZE = 144;
const OFF_COMMAND = 153;
const OFF_OPERATION = 154;
const OFF_VERSION = 155;
const CMD_REQUEST = 5;
const CMD_REPLY = 8;
const CMD_EVICTION = 18;
const OP_REGISTER = 2;
const WIRE_VERSION = 1;

/** Max events per request (1 MiB message − 256 B header, 128 B/event). */
export const BATCH_MAX = Math.floor((MESSAGE_SIZE_MAX - HEADER_SIZE) / 128);

const ACCOUNT_SIZE = 128;
const TRANSFER_SIZE = 128;
const BALANCE_SIZE = 128;
const FILTER_SIZE = 64;

function checksum128(data: Buffer): Buffer {
  return createHash("sha256").update(data).digest().subarray(0, 16);
}

// ---------------------------------------------------------------------
// Struct codecs (field offsets: tigerbeetle_tpu/types.py).

function writeU128(buf: Buffer, at: number, v: bigint): void {
  buf.writeBigUInt64LE(v & 0xffffffffffffffffn, at);
  buf.writeBigUInt64LE(v >> 64n, at + 8);
}

function readU128(buf: Buffer, at: number): bigint {
  return buf.readBigUInt64LE(at) | (buf.readBigUInt64LE(at + 8) << 64n);
}

function encodeAccounts(events: Partial<Account>[]): Buffer {
  const buf = Buffer.alloc(events.length * ACCOUNT_SIZE);
  events.forEach((e, i) => {
    const at = i * ACCOUNT_SIZE;
    writeU128(buf, at + 0, e.id ?? 0n);
    writeU128(buf, at + 16, e.debitsPending ?? 0n);
    writeU128(buf, at + 32, e.debitsPosted ?? 0n);
    writeU128(buf, at + 48, e.creditsPending ?? 0n);
    writeU128(buf, at + 64, e.creditsPosted ?? 0n);
    writeU128(buf, at + 80, e.userData128 ?? 0n);
    buf.writeBigUInt64LE(e.userData64 ?? 0n, at + 96);
    buf.writeUInt32LE(e.userData32 ?? 0, at + 104);
    buf.writeUInt32LE(e.reserved ?? 0, at + 108);
    buf.writeUInt32LE(e.ledger ?? 0, at + 112);
    buf.writeUInt16LE(e.code ?? 0, at + 116);
    buf.writeUInt16LE(e.flags ?? 0, at + 118);
    buf.writeBigUInt64LE(e.timestamp ?? 0n, at + 120);
  });
  return buf;
}

function decodeAccount(buf: Buffer, at: number): Account {
  return {
    id: readU128(buf, at + 0),
    debitsPending: readU128(buf, at + 16),
    debitsPosted: readU128(buf, at + 32),
    creditsPending: readU128(buf, at + 48),
    creditsPosted: readU128(buf, at + 64),
    userData128: readU128(buf, at + 80),
    userData64: buf.readBigUInt64LE(at + 96),
    userData32: buf.readUInt32LE(at + 104),
    reserved: buf.readUInt32LE(at + 108),
    ledger: buf.readUInt32LE(at + 112),
    code: buf.readUInt16LE(at + 116),
    flags: buf.readUInt16LE(at + 118),
    timestamp: buf.readBigUInt64LE(at + 120),
  };
}

function encodeTransfers(events: Partial<Transfer>[]): Buffer {
  const buf = Buffer.alloc(events.length * TRANSFER_SIZE);
  events.forEach((e, i) => {
    const at = i * TRANSFER_SIZE;
    writeU128(buf, at + 0, e.id ?? 0n);
    writeU128(buf, at + 16, e.debitAccountId ?? 0n);
    writeU128(buf, at + 32, e.creditAccountId ?? 0n);
    writeU128(buf, at + 48, e.amount ?? 0n);
    writeU128(buf, at + 64, e.pendingId ?? 0n);
    writeU128(buf, at + 80, e.userData128 ?? 0n);
    buf.writeBigUInt64LE(e.userData64 ?? 0n, at + 96);
    buf.writeUInt32LE(e.userData32 ?? 0, at + 104);
    buf.writeUInt32LE(e.timeout ?? 0, at + 108);
    buf.writeUInt32LE(e.ledger ?? 0, at + 112);
    buf.writeUInt16LE(e.code ?? 0, at + 116);
    buf.writeUInt16LE(e.flags ?? 0, at + 118);
    buf.writeBigUInt64LE(e.timestamp ?? 0n, at + 120);
  });
  return buf;
}

function decodeTransfer(buf: Buffer, at: number): Transfer {
  return {
    id: readU128(buf, at + 0),
    debitAccountId: readU128(buf, at + 16),
    creditAccountId: readU128(buf, at + 32),
    amount: readU128(buf, at + 48),
    pendingId: readU128(buf, at + 64),
    userData128: readU128(buf, at + 80),
    userData64: buf.readBigUInt64LE(at + 96),
    userData32: buf.readUInt32LE(at + 104),
    timeout: buf.readUInt32LE(at + 108),
    ledger: buf.readUInt32LE(at + 112),
    code: buf.readUInt16LE(at + 116),
    flags: buf.readUInt16LE(at + 118),
    timestamp: buf.readBigUInt64LE(at + 120),
  };
}

function decodeBalance(buf: Buffer, at: number): AccountBalance {
  return {
    debitsPending: readU128(buf, at + 0),
    debitsPosted: readU128(buf, at + 16),
    creditsPending: readU128(buf, at + 32),
    creditsPosted: readU128(buf, at + 48),
    timestamp: buf.readBigUInt64LE(at + 64),
  };
}

function encodeFilter(f: Partial<AccountFilter>): Buffer {
  const buf = Buffer.alloc(FILTER_SIZE);
  writeU128(buf, 0, f.accountId ?? 0n);
  buf.writeBigUInt64LE(f.timestampMin ?? 0n, 16);
  buf.writeBigUInt64LE(f.timestampMax ?? 0n, 24);
  buf.writeUInt32LE(f.limit ?? 0, 32);
  buf.writeUInt32LE(
    f.flags ?? AccountFilterFlags.debits | AccountFilterFlags.credits,
    36,
  );
  return buf;
}

function encodeIds(ids: bigint[]): Buffer {
  const buf = Buffer.alloc(ids.length * 16);
  ids.forEach((id, i) => writeU128(buf, i * 16, id));
  return buf;
}

function decodeCreateResults(buf: Buffer): CreateResult[] {
  const out: CreateResult[] = [];
  for (let at = 0; at + 8 <= buf.length; at += 8) {
    out.push({
      index: buf.readUInt32LE(at),
      result: buf.readUInt32LE(at + 4),
    });
  }
  return out;
}

// ---------------------------------------------------------------------
// Wire framing.

export function buildRequest(
  cluster: bigint,
  clientId: bigint,
  requestNumber: number,
  operation: number,
  body: Buffer,
): Buffer {
  const msg = Buffer.alloc(HEADER_SIZE + body.length);
  body.copy(msg, HEADER_SIZE);
  writeU128(msg, OFF_CLIENT, clientId);
  writeU128(msg, OFF_CLUSTER, cluster);
  msg.writeUInt32LE(requestNumber, OFF_REQUEST);
  msg.writeUInt32LE(HEADER_SIZE + body.length, OFF_SIZE);
  msg[OFF_COMMAND] = CMD_REQUEST;
  msg[OFF_OPERATION] = operation;
  msg[OFF_VERSION] = WIRE_VERSION;
  checksum128(body).copy(msg, OFF_CHECKSUM_BODY);
  checksum128(msg.subarray(16, HEADER_SIZE)).copy(msg, OFF_CHECKSUM);
  return msg;
}

function verifyMessage(msg: Buffer): boolean {
  const head = checksum128(msg.subarray(16, HEADER_SIZE));
  if (!head.equals(msg.subarray(OFF_CHECKSUM, OFF_CHECKSUM + 16))) {
    return false;
  }
  const body = checksum128(msg.subarray(HEADER_SIZE));
  return body.equals(msg.subarray(OFF_CHECKSUM_BODY, OFF_CHECKSUM_BODY + 16));
}

// ---------------------------------------------------------------------
// Client.

interface Pending {
  requestNumber: number;
  resolve: (body: Buffer) => void;
  reject: (err: Error) => void;
}

export interface ClientOptions {
  cluster?: bigint;
  /** Unique per live session. */
  clientId?: bigint;
  timeoutMs?: number;
}

export class Client {
  private socket: net.Socket;
  private recv: Buffer = Buffer.alloc(0);
  private cluster: bigint;
  private clientId: bigint;
  private requestNumber = 0;
  private registered: Promise<void> | null = null;
  private inflight: Pending | null = null;
  private chain: Promise<unknown> = Promise.resolve();
  private timeoutMs: number;
  private dead: Error | null = null;
  private connected: Promise<void>;

  constructor(address: string, options: ClientOptions = {}) {
    const [host, port] = splitAddress(address);
    this.cluster = options.cluster ?? 0n;
    this.clientId =
      options.clientId ?? BigInt(Math.floor(Math.random() * 2 ** 52)) + 1n;
    this.timeoutMs = options.timeoutMs ?? 30_000;
    this.socket = net.connect({ host, port, noDelay: true });
    this.connected = new Promise((resolve, reject) => {
      this.socket.once("connect", resolve);
      this.socket.once("error", reject);
    });
    this.socket.on("data", (chunk) => this.onData(chunk));
    this.socket.on("error", (err) => this.fail(err));
    this.socket.on("close", () => this.fail(new Error("connection closed")));
  }

  close(): void {
    // Reject the in-flight request BEFORE marking dead (fail() is a
    // no-op once this.dead is set).
    this.fail(new Error("client closed"));
    this.socket.destroy();
  }

  private fail(err: Error): void {
    if (this.dead) return;
    this.dead = err;
    if (this.inflight) {
      this.inflight.reject(err);
      this.inflight = null;
    }
  }

  private onData(chunk: Buffer): void {
    this.recv = Buffer.concat([this.recv, chunk]);
    for (;;) {
      if (this.recv.length < HEADER_SIZE) return;
      const size = this.recv.readUInt32LE(OFF_SIZE);
      if (size < HEADER_SIZE || size > MESSAGE_SIZE_MAX + HEADER_SIZE) {
        this.fail(new Error(`bad frame size ${size}`));
        return;
      }
      if (this.recv.length < size) return;
      const msg = this.recv.subarray(0, size);
      this.recv = this.recv.subarray(size);
      if (!verifyMessage(msg)) continue;
      if (msg[OFF_COMMAND] === CMD_EVICTION) {
        this.fail(new Error("session evicted"));
        return;
      }
      if (msg[OFF_COMMAND] !== CMD_REPLY) continue;
      const req = msg.readUInt32LE(OFF_REQUEST);
      if (this.inflight && req === this.inflight.requestNumber) {
        const pending = this.inflight;
        this.inflight = null;
        pending.resolve(Buffer.from(msg.subarray(HEADER_SIZE)));
      }
    }
  }

  private roundtrip(operation: number, requestNumber: number, body: Buffer): Promise<Buffer> {
    if (this.dead) return Promise.reject(this.dead);
    return new Promise<Buffer>((resolve, reject) => {
      const msg = buildRequest(
        this.cluster, this.clientId, requestNumber, operation, body,
      );
      // Retransmit under the SAME request number until answered: the
      // server's at-most-once dedupe replays the stored reply for a
      // request it already committed, never re-executing it.
      const resend = setInterval(() => {
        if (!this.dead) this.socket.write(msg);
      }, 1000);
      const done = () => {
        clearTimeout(timer);
        clearInterval(resend);
        if (this.inflight?.requestNumber === requestNumber) {
          this.inflight = null;
        }
      };
      const timer = setTimeout(() => {
        done();
        reject(new Error("request timeout"));
      }, this.timeoutMs);
      this.inflight = {
        requestNumber,
        resolve: (b) => {
          done();
          resolve(b);
        },
        reject: (e) => {
          done();
          reject(e);
        },
      };
      this.socket.write(msg);
    });
  }

  /** Serialize requests: one in flight per session. */
  private request(operation: number, body: Buffer): Promise<Buffer> {
    const run = this.chain.then(async () => {
      await this.connected;
      if (this.registered === null) {
        // A failed registration resets so the next request retries it
        // (the server replays the register reply for an existing
        // session, so re-registering is always safe).
        const attempt = this.roundtrip(OP_REGISTER, 0, Buffer.alloc(0)).then(
          () => undefined,
          (err) => {
            this.registered = null;
            throw err;
          },
        );
        this.registered = attempt;
      }
      await this.registered;
      this.requestNumber += 1;
      return this.roundtrip(operation, this.requestNumber, body);
    });
    this.chain = run.catch(() => undefined);
    return run;
  }

  /** Returns FAILURES only — `[]` means every account applied. */
  async createAccounts(accounts: Partial<Account>[]): Promise<CreateResult[]> {
    if (accounts.length > BATCH_MAX) throw new Error("batch too large");
    const reply = await this.request(
      Operation.create_accounts,
      encodeAccounts(accounts),
    );
    return decodeCreateResults(reply);
  }

  /** Returns FAILURES only — `[]` means every transfer applied. */
  async createTransfers(transfers: Partial<Transfer>[]): Promise<CreateResult[]> {
    if (transfers.length > BATCH_MAX) throw new Error("batch too large");
    const reply = await this.request(
      Operation.create_transfers,
      encodeTransfers(transfers),
    );
    return decodeCreateResults(reply);
  }

  /** Missing ids are omitted from the result. */
  async lookupAccounts(ids: bigint[]): Promise<Account[]> {
    if (ids.length > BATCH_MAX) throw new Error("batch too large");
    const reply = await this.request(Operation.lookup_accounts, encodeIds(ids));
    const out: Account[] = [];
    for (let at = 0; at + ACCOUNT_SIZE <= reply.length; at += ACCOUNT_SIZE) {
      out.push(decodeAccount(reply, at));
    }
    return out;
  }

  async lookupTransfers(ids: bigint[]): Promise<Transfer[]> {
    if (ids.length > BATCH_MAX) throw new Error("batch too large");
    const reply = await this.request(Operation.lookup_transfers, encodeIds(ids));
    const out: Transfer[] = [];
    for (let at = 0; at + TRANSFER_SIZE <= reply.length; at += TRANSFER_SIZE) {
      out.push(decodeTransfer(reply, at));
    }
    return out;
  }

  async getAccountTransfers(filter: Partial<AccountFilter>): Promise<Transfer[]> {
    const reply = await this.request(
      Operation.get_account_transfers,
      encodeFilter(filter),
    );
    const out: Transfer[] = [];
    for (let at = 0; at + TRANSFER_SIZE <= reply.length; at += TRANSFER_SIZE) {
      out.push(decodeTransfer(reply, at));
    }
    return out;
  }

  async getAccountBalances(filter: Partial<AccountFilter>): Promise<AccountBalance[]> {
    const reply = await this.request(
      Operation.get_account_balances,
      encodeFilter(filter),
    );
    const out: AccountBalance[] = [];
    for (let at = 0; at + BALANCE_SIZE <= reply.length; at += BALANCE_SIZE) {
      out.push(decodeBalance(reply, at));
    }
    return out;
  }
}

function splitAddress(address: string): [string, number] {
  const i = address.lastIndexOf(":");
  if (i < 0) return ["127.0.0.1", Number(address)];
  return [address.slice(0, i) || "127.0.0.1", Number(address.slice(i + 1))];
}
