// End-to-end + fixture test for the TypeScript client.  Run directly
// (no build step — the sources are erasable-syntax TypeScript):
//
//   node --experimental-strip-types test/e2e.ts <port>      # live server
//   node --experimental-strip-types test/e2e.ts --fixtures  # offline
//
// Driven by tests/test_foreign_clients.py when a Node toolchain
// exists.  Prints "e2e ok" / "fixtures ok" and exits 0 on success.

import { readFileSync } from "node:fs";
import { dirname, join } from "node:path";
import { fileURLToPath } from "node:url";

import { buildRequest, Client } from "../src/client.ts";
import { AccountFilterFlags, CreateTransferResult } from "../src/types.ts";

function check(cond: boolean, message: string): void {
  if (!cond) {
    console.error(`FAIL: ${message}`);
    process.exit(1);
  }
}

function fixtures(): void {
  const here = dirname(fileURLToPath(import.meta.url));
  const raw = readFileSync(join(here, "../../fixtures/frames.json"), "utf8");
  const cases = JSON.parse(raw) as Array<{
    name: string;
    cluster: number;
    client_lo: number;
    client_hi: number;
    request: number;
    operation: number;
    body_hex: string;
    frame_hex: string;
  }>;
  check(cases.length > 0, "empty fixtures");
  for (const c of cases) {
    const clientId =
      BigInt(c.client_lo) | (BigInt(c.client_hi) << 64n);
    const got = buildRequest(
      BigInt(c.cluster),
      clientId,
      c.request,
      c.operation,
      Buffer.from(c.body_hex, "hex"),
    );
    check(
      got.toString("hex") === c.frame_hex,
      `${c.name}: frame mismatch\n got ${got.toString("hex")}\nwant ${c.frame_hex}`,
    );
  }
  console.log("fixtures ok");
}

async function e2e(port: number): Promise<void> {
  const client = new Client(`127.0.0.1:${port}`, {
    cluster: 3n,
    clientId: 0xabcdefn,
  });

  let failures = await client.createAccounts([
    { id: 8001n, ledger: 1, code: 1 },
    { id: 8002n, ledger: 1, code: 1 },
  ]);
  check(failures.length === 0, `create_accounts failures: ${JSON.stringify(failures)}`);

  failures = await client.createTransfers([
    {
      id: 88001n,
      debitAccountId: 8001n,
      creditAccountId: 8002n,
      amount: 250n,
      ledger: 1,
      code: 1,
    },
    {
      id: 88002n,
      debitAccountId: 8001n,
      creditAccountId: 8001n, // accounts_must_be_different
      amount: 1n,
      ledger: 1,
      code: 1,
    },
  ]);
  check(failures.length === 1, `expected 1 failure, got ${failures.length}`);
  check(failures[0].index === 1, `failure index ${failures[0].index}`);
  check(
    failures[0].result === CreateTransferResult.accounts_must_be_different,
    `failure result ${failures[0].result}`,
  );

  const rows = await client.lookupAccounts([8001n, 8002n]);
  check(rows.length === 2, `lookup rows ${rows.length}`);
  check(rows[0].debitsPosted === 250n, `debits ${rows[0].debitsPosted}`);
  check(rows[1].creditsPosted === 250n, `credits ${rows[1].creditsPosted}`);

  const transfers = await client.getAccountTransfers({
    accountId: 8001n,
    timestampMax: (1n << 63n) - 1n,
    limit: 10,
    flags: AccountFilterFlags.debits | AccountFilterFlags.credits,
  });
  check(transfers.length === 1, `get_account_transfers ${transfers.length}`);
  check(transfers[0].amount === 250n, `amount ${transfers[0].amount}`);

  client.close();
  console.log("e2e ok");
}

const arg = process.argv[2];
if (arg === "--fixtures") {
  fixtures();
} else {
  fixtures();
  await e2e(Number(arg));
}
