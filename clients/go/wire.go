// Wire protocol: the 256-byte VSR message header and checksums.
//
// Layout mirrors tigerbeetle_tpu/vsr/wire.py HEADER_DTYPE (a
// re-design of the reference's per-command header unions into one
// flat little-endian layout — reference:
// src/vsr/message_header.zig:17-103).  Checksums are SHA-256
// truncated to 128 bits: `checksum` covers header bytes [16, 256),
// `checksum_body` covers the body; both are verified before any
// message is trusted.
package tigerbeetle

import (
	"crypto/sha256"
	"encoding/binary"
	"fmt"
)

const (
	headerSize     = 256
	messageSizeMax = 1 << 20

	offChecksum     = 0
	offChecksumBody = 16
	offClient       = 48
	offCluster      = 64
	offRequest      = 112
	offSize         = 144
	offCommand      = 153
	offOperation    = 154
	offVersion      = 155

	cmdRequest  = 5
	cmdReply    = 8
	cmdEviction = 18

	opRegister = 2

	wireVersion = 1
)

// checksum128 returns the first 16 bytes of SHA-256(data).
func checksum128(data []byte) [16]byte {
	sum := sha256.Sum256(data)
	var out [16]byte
	copy(out[:], sum[:16])
	return out
}

// buildRequest frames one request message: header + body, checksums
// finalized.
func buildRequest(cluster uint64, clientID [2]uint64, requestNumber uint32,
	operation uint8, body []byte) []byte {
	msg := make([]byte, headerSize+len(body))
	copy(msg[headerSize:], body)
	h := msg[:headerSize]
	binary.LittleEndian.PutUint64(h[offClient:], clientID[0])
	binary.LittleEndian.PutUint64(h[offClient+8:], clientID[1])
	binary.LittleEndian.PutUint64(h[offCluster:], cluster)
	binary.LittleEndian.PutUint32(h[offRequest:], requestNumber)
	binary.LittleEndian.PutUint32(h[offSize:], uint32(headerSize+len(body)))
	h[offCommand] = cmdRequest
	h[offOperation] = operation
	h[offVersion] = wireVersion

	bodySum := checksum128(body)
	copy(h[offChecksumBody:], bodySum[:])
	headSum := checksum128(h[16:headerSize])
	copy(h[offChecksum:], headSum[:])
	return msg
}

// verifyMessage checks both checksums of a framed message.
func verifyMessage(msg []byte) error {
	h := msg[:headerSize]
	headSum := checksum128(h[16:headerSize])
	for i := 0; i < 16; i++ {
		if h[offChecksum+i] != headSum[i] {
			return fmt.Errorf("tigerbeetle: header checksum mismatch")
		}
	}
	bodySum := checksum128(msg[headerSize:])
	for i := 0; i < 16; i++ {
		if h[offChecksumBody+i] != bodySum[i] {
			return fmt.Errorf("tigerbeetle: body checksum mismatch")
		}
	}
	return nil
}
