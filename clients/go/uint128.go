// 128-bit id/amount helpers (the reference's src/clients/go/uint128.go
// shape): conversions between the client's [2]uint64 little-endian
// limb pairs, 16-byte arrays, and math/big, plus a monotonic
// time-based ID() generator (ULID-shaped: millisecond timestamp in
// the topmost bits, random bits below, strictly increasing within the
// process — reference ID() semantics).
package tigerbeetle

import (
	"crypto/rand"
	"encoding/binary"
	"math/big"
	"sync"
	"time"
)

// U128Bytes converts (lo, hi) limbs to the 16-byte little-endian wire
// image.
func U128Bytes(v [2]uint64) [16]byte {
	var out [16]byte
	putU128(out[:], v)
	return out
}

// U128FromBytes converts a 16-byte little-endian image to limbs.
func U128FromBytes(b [16]byte) [2]uint64 {
	return getU128(b[:])
}

// U128BigInt converts limbs to a non-negative big.Int.
func U128BigInt(v [2]uint64) *big.Int {
	out := new(big.Int).SetUint64(v[1])
	out.Lsh(out, 64)
	return out.Or(out, new(big.Int).SetUint64(v[0]))
}

// U128FromBigInt converts a non-negative big.Int (must fit 128 bits)
// to limbs; ok is false when it does not fit.
func U128FromBigInt(value *big.Int) (v [2]uint64, ok bool) {
	if value.Sign() < 0 || value.BitLen() > 128 {
		return v, false
	}
	var b [16]byte
	value.FillBytes(b[:]) // big-endian
	v[1] = binary.BigEndian.Uint64(b[0:8])
	v[0] = binary.BigEndian.Uint64(b[8:16])
	return v, true
}

var (
	idMu         sync.Mutex
	idLastMillis int64
	idLast       [2]uint64
)

// ID returns a time-ordered unique 128-bit identifier: 48-bit
// millisecond timestamp in the topmost bits, random bits below,
// strictly monotonic within the process (same-millisecond calls
// increment — reference ID() semantics).
func ID() [2]uint64 {
	idMu.Lock()
	defer idMu.Unlock()
	now := time.Now().UnixMilli()
	if now > idLastMillis {
		idLastMillis = now
		var r [10]byte
		if _, err := rand.Read(r[:]); err != nil {
			panic(err)
		}
		idLast[1] = uint64(now)<<16 |
			uint64(r[0])<<8 | uint64(r[1])
		idLast[0] = binary.LittleEndian.Uint64(r[2:10])
	} else {
		idLast[0]++
		if idLast[0] == 0 {
			idLast[1]++
		}
	}
	return idLast
}
