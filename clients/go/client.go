// Package tigerbeetle is a pure-Go client for the tigerbeetle_tpu
// cluster: it speaks the TCP wire protocol directly (256-byte
// checksummed headers, tigerbeetle_tpu/vsr/wire.py) with no cgo
// dependency — the same role the reference's Go client fills over its
// tb_client C ABI (reference: src/clients/go/).
//
// The client is a synchronous VSR session: it registers on first use,
// keeps one request in flight, and relies on the server's
// at-most-once session dedupe for safe retransmission.  For pipelined
// multi-packet load use the native async client (native/tb_client.h)
// via cgo.
package tigerbeetle

import (
	"encoding/binary"
	"fmt"
	"net"
	"time"
)

// BatchMax is the most events a single request may carry
// (1 MiB message - 256 B header, 128 B per event; reference:
// src/state_machine.zig:75-81).
const BatchMax = (messageSizeMax - headerSize) / 128


// ErrEvicted is returned once the cluster evicts this session; the
// client must be re-created with a fresh ID.
var ErrEvicted = fmt.Errorf("tigerbeetle: session evicted")

// Client is one registered session against a cluster.  Not safe for
// concurrent use; wrap with a mutex or use one Client per goroutine.
type Client struct {
	conn          net.Conn
	cluster       uint64
	clientID      [2]uint64
	requestNumber uint32
	registered    bool
	evicted       bool
	recv          []byte
	Timeout       time.Duration // per-request deadline (default 30s)
}

// NewClient connects to `address` ("host:port") for `cluster`.
// clientID must be unique per live session ([lo, hi] limbs of a u128).
func NewClient(address string, cluster uint64, clientID [2]uint64) (*Client, error) {
	conn, err := net.Dial("tcp", address)
	if err != nil {
		return nil, err
	}
	if tc, ok := conn.(*net.TCPConn); ok {
		tc.SetNoDelay(true)
	}
	return &Client{
		conn:     conn,
		cluster:  cluster,
		clientID: clientID,
		Timeout:  30 * time.Second,
	}, nil
}

func (c *Client) Close() error { return c.conn.Close() }

// retransmitInterval is how often an unanswered request is re-sent
// within the overall Timeout.  Retransmission under the SAME request
// number is always safe: the server's at-most-once session dedupe
// replays the stored reply for a request it already committed instead
// of re-executing it.
const retransmitInterval = time.Second

// roundtrip sends one request and blocks for its reply body,
// retransmitting periodically until the Timeout deadline.
func (c *Client) roundtrip(operation uint8, requestNumber uint32, body []byte) ([]byte, error) {
	if c.evicted {
		return nil, ErrEvicted
	}
	msg := buildRequest(c.cluster, c.clientID, requestNumber, operation, body)
	deadline := time.Now().Add(c.Timeout)
	for {
		if time.Now().After(deadline) {
			return nil, fmt.Errorf("tigerbeetle: request %d timed out", requestNumber)
		}
		// The deadline covers this iteration's write AND reads; it
		// must be set BEFORE Write (a stale expired deadline from the
		// previous iteration would fail the retransmit instantly).
		step := time.Now().Add(retransmitInterval)
		if step.After(deadline) {
			step = deadline
		}
		c.conn.SetDeadline(step)
		if _, err := c.conn.Write(msg); err != nil {
			return nil, err
		}
		for {
			reply, err := c.readMessage()
			if err != nil {
				if ne, ok := err.(net.Error); ok && ne.Timeout() {
					break // retransmit
				}
				return nil, err
			}
			h := reply[:headerSize]
			if h[offCommand] == cmdEviction {
				c.evicted = true
				return nil, ErrEvicted
			}
			if h[offCommand] != cmdReply {
				continue
			}
			if binary.LittleEndian.Uint32(h[offRequest:]) != requestNumber {
				continue // stale duplicate
			}
			return reply[headerSize:], nil
		}
	}
}

// readMessage reads and verifies one framed message.
func (c *Client) readMessage() ([]byte, error) {
	for {
		// Complete message already buffered?
		if len(c.recv) >= headerSize {
			size := binary.LittleEndian.Uint32(c.recv[offSize:])
			if size < headerSize || size > messageSizeMax+headerSize {
				return nil, fmt.Errorf("tigerbeetle: bad frame size %d", size)
			}
			if uint32(len(c.recv)) >= size {
				msg := c.recv[:size]
				c.recv = c.recv[size:]
				if err := verifyMessage(msg); err != nil {
					return nil, err
				}
				return msg, nil
			}
		}
		buf := make([]byte, 1<<16)
		n, err := c.conn.Read(buf)
		if err != nil {
			return nil, err
		}
		c.recv = append(c.recv, buf[:n]...)
	}
}

func (c *Client) ensureRegistered() error {
	if c.registered {
		return nil
	}
	if _, err := c.roundtrip(opRegister, 0, nil); err != nil {
		return err
	}
	c.registered = true
	return nil
}

func (c *Client) request(operation uint8, body []byte) ([]byte, error) {
	if err := c.ensureRegistered(); err != nil {
		return nil, err
	}
	c.requestNumber++
	return c.roundtrip(operation, c.requestNumber, body)
}

// CreateAccounts submits a batch; the result lists FAILURES only
// (an empty slice means every account was created or already existed
// identically).
func (c *Client) CreateAccounts(accounts []Account) ([]CreateResult, error) {
	if len(accounts) > BatchMax {
		return nil, fmt.Errorf("tigerbeetle: batch exceeds %d events", BatchMax)
	}
	reply, err := c.request(uint8(OperationCreateAccounts), marshalAccounts(accounts))
	if err != nil {
		return nil, err
	}
	return unmarshalCreateResults(reply), nil
}

// CreateTransfers submits a batch; the result lists FAILURES only.
func (c *Client) CreateTransfers(transfers []Transfer) ([]CreateResult, error) {
	if len(transfers) > BatchMax {
		return nil, fmt.Errorf("tigerbeetle: batch exceeds %d events", BatchMax)
	}
	reply, err := c.request(uint8(OperationCreateTransfers), marshalTransfers(transfers))
	if err != nil {
		return nil, err
	}
	return unmarshalCreateResults(reply), nil
}

// LookupAccounts returns the rows found (missing ids are omitted).
func (c *Client) LookupAccounts(ids [][2]uint64) ([]Account, error) {
	if len(ids) > BatchMax {
		return nil, fmt.Errorf("tigerbeetle: batch exceeds %d events", BatchMax)
	}
	reply, err := c.request(uint8(OperationLookupAccounts), marshalIds(ids))
	if err != nil {
		return nil, err
	}
	out := make([]Account, len(reply)/accountSize)
	for i := range out {
		out[i] = unmarshalAccount(reply[i*accountSize:])
	}
	return out, nil
}

// LookupTransfers returns the rows found (missing ids are omitted).
func (c *Client) LookupTransfers(ids [][2]uint64) ([]Transfer, error) {
	if len(ids) > BatchMax {
		return nil, fmt.Errorf("tigerbeetle: batch exceeds %d events", BatchMax)
	}
	reply, err := c.request(uint8(OperationLookupTransfers), marshalIds(ids))
	if err != nil {
		return nil, err
	}
	out := make([]Transfer, len(reply)/transferSize)
	for i := range out {
		out[i] = unmarshalTransfer(reply[i*transferSize:])
	}
	return out, nil
}

// GetAccountTransfers scans transfers touching filter.AccountId.
func (c *Client) GetAccountTransfers(filter AccountFilter) ([]Transfer, error) {
	reply, err := c.request(uint8(OperationGetAccountTransfers), marshalFilter(filter))
	if err != nil {
		return nil, err
	}
	out := make([]Transfer, len(reply)/transferSize)
	for i := range out {
		out[i] = unmarshalTransfer(reply[i*transferSize:])
	}
	return out, nil
}

// GetAccountBalances returns historical balances for an account with
// the history flag.
func (c *Client) GetAccountBalances(filter AccountFilter) ([]AccountBalance, error) {
	reply, err := c.request(uint8(OperationGetAccountBalances), marshalFilter(filter))
	if err != nil {
		return nil, err
	}
	out := make([]AccountBalance, len(reply)/balanceSize)
	for i := range out {
		out[i] = unmarshalBalance(reply[i*balanceSize:])
	}
	return out, nil
}

// U128 builds a [lo, hi] id from a uint64.
func U128(v uint64) [2]uint64 { return [2]uint64{v, 0} }
