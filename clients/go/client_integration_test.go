// Integration test against a live server.  Run via
// tests/test_foreign_clients.py (which spawns the server and sets
// TB_ADDRESS / TB_CLUSTER), or by hand:
//
//	TB_ADDRESS=127.0.0.1:3000 TB_CLUSTER=3 go test ./...
//
// Skips when no server address is configured.
package tigerbeetle

import (
	"os"
	"strconv"
	"testing"
)

func dial(t *testing.T) *Client {
	addr := os.Getenv("TB_ADDRESS")
	if addr == "" {
		t.Skip("TB_ADDRESS not set")
	}
	cluster := uint64(0)
	if s := os.Getenv("TB_CLUSTER"); s != "" {
		v, err := strconv.ParseUint(s, 10, 64)
		if err != nil {
			t.Fatal(err)
		}
		cluster = v
	}
	c, err := NewClient(addr, cluster, U128(0xD0_60))
	if err != nil {
		t.Fatal(err)
	}
	return c
}

func TestEndToEnd(t *testing.T) {
	c := dial(t)
	defer c.Close()

	res, err := c.CreateAccounts([]Account{
		{Id: U128(9001), Ledger: 1, Code: 1},
		{Id: U128(9002), Ledger: 1, Code: 1},
	})
	if err != nil {
		t.Fatal(err)
	}
	if len(res) != 0 {
		t.Fatalf("create_accounts failures: %+v", res)
	}

	res, err = c.CreateTransfers([]Transfer{
		{Id: U128(99001), DebitAccountId: U128(9001),
			CreditAccountId: U128(9002), Amount: U128(250),
			Ledger: 1, Code: 1},
		{Id: U128(99002), DebitAccountId: U128(9001),
			CreditAccountId: U128(9001), Amount: U128(1),
			Ledger: 1, Code: 1}, // accounts_must_be_different
	})
	if err != nil {
		t.Fatal(err)
	}
	if len(res) != 1 || res[0].Index != 1 ||
		res[0].Result != uint32(CreateTransferResultAccountsMustBeDifferent) {
		t.Fatalf("expected one accounts_must_be_different failure, got %+v", res)
	}

	rows, err := c.LookupAccounts([][2]uint64{U128(9001), U128(9002)})
	if err != nil {
		t.Fatal(err)
	}
	if len(rows) != 2 {
		t.Fatalf("lookup rows: %d", len(rows))
	}
	if rows[0].DebitsPosted != U128(250) || rows[1].CreditsPosted != U128(250) {
		t.Fatalf("balances: %+v %+v", rows[0], rows[1])
	}

	transfers, err := c.GetAccountTransfers(AccountFilter{
		AccountId:    U128(9001),
		TimestampMax: 1<<63 - 1,
		Limit:        10,
		Flags:        AccountFilterFlagsDebits | AccountFilterFlagsCredits,
	})
	if err != nil {
		t.Fatal(err)
	}
	if len(transfers) != 1 || transfers[0].Amount != U128(250) {
		t.Fatalf("get_account_transfers: %+v", transfers)
	}
}
