// Binary marshaling of the 128-byte wire structs.
//
// Field offsets follow tigerbeetle_tpu/types.py (the single source of
// truth, itself mirroring reference: src/tigerbeetle.zig:7-111); all
// integers are little-endian and the layouts are tightly packed.
package tigerbeetle

import "encoding/binary"

const (
	accountSize       = 128
	transferSize      = 128
	balanceSize       = 128
	filterSize        = 64
	idPairSize        = 16
	createResultSize  = 8
)

func putU128(b []byte, v [2]uint64) {
	binary.LittleEndian.PutUint64(b, v[0])
	binary.LittleEndian.PutUint64(b[8:], v[1])
}

func getU128(b []byte) [2]uint64 {
	return [2]uint64{
		binary.LittleEndian.Uint64(b),
		binary.LittleEndian.Uint64(b[8:]),
	}
}

func marshalAccounts(events []Account) []byte {
	out := make([]byte, len(events)*accountSize)
	for i := range events {
		e := &events[i]
		b := out[i*accountSize:]
		putU128(b[0:], e.Id)
		putU128(b[16:], e.DebitsPending)
		putU128(b[32:], e.DebitsPosted)
		putU128(b[48:], e.CreditsPending)
		putU128(b[64:], e.CreditsPosted)
		putU128(b[80:], e.UserData128)
		binary.LittleEndian.PutUint64(b[96:], e.UserData64)
		binary.LittleEndian.PutUint32(b[104:], e.UserData32)
		binary.LittleEndian.PutUint32(b[108:], e.Reserved)
		binary.LittleEndian.PutUint32(b[112:], e.Ledger)
		binary.LittleEndian.PutUint16(b[116:], e.Code)
		binary.LittleEndian.PutUint16(b[118:], uint16(e.Flags))
		binary.LittleEndian.PutUint64(b[120:], e.Timestamp)
	}
	return out
}

func unmarshalAccount(b []byte) Account {
	return Account{
		Id:             getU128(b[0:]),
		DebitsPending:  getU128(b[16:]),
		DebitsPosted:   getU128(b[32:]),
		CreditsPending: getU128(b[48:]),
		CreditsPosted:  getU128(b[64:]),
		UserData128:    getU128(b[80:]),
		UserData64:     binary.LittleEndian.Uint64(b[96:]),
		UserData32:     binary.LittleEndian.Uint32(b[104:]),
		Reserved:       binary.LittleEndian.Uint32(b[108:]),
		Ledger:         binary.LittleEndian.Uint32(b[112:]),
		Code:           binary.LittleEndian.Uint16(b[116:]),
		Flags:          AccountFlags(binary.LittleEndian.Uint16(b[118:])),
		Timestamp:      binary.LittleEndian.Uint64(b[120:]),
	}
}

func marshalTransfers(events []Transfer) []byte {
	out := make([]byte, len(events)*transferSize)
	for i := range events {
		e := &events[i]
		b := out[i*transferSize:]
		putU128(b[0:], e.Id)
		putU128(b[16:], e.DebitAccountId)
		putU128(b[32:], e.CreditAccountId)
		putU128(b[48:], e.Amount)
		putU128(b[64:], e.PendingId)
		putU128(b[80:], e.UserData128)
		binary.LittleEndian.PutUint64(b[96:], e.UserData64)
		binary.LittleEndian.PutUint32(b[104:], e.UserData32)
		binary.LittleEndian.PutUint32(b[108:], e.Timeout)
		binary.LittleEndian.PutUint32(b[112:], e.Ledger)
		binary.LittleEndian.PutUint16(b[116:], e.Code)
		binary.LittleEndian.PutUint16(b[118:], uint16(e.Flags))
		binary.LittleEndian.PutUint64(b[120:], e.Timestamp)
	}
	return out
}

func unmarshalTransfer(b []byte) Transfer {
	return Transfer{
		Id:              getU128(b[0:]),
		DebitAccountId:  getU128(b[16:]),
		CreditAccountId: getU128(b[32:]),
		Amount:          getU128(b[48:]),
		PendingId:       getU128(b[64:]),
		UserData128:     getU128(b[80:]),
		UserData64:      binary.LittleEndian.Uint64(b[96:]),
		UserData32:      binary.LittleEndian.Uint32(b[104:]),
		Timeout:         binary.LittleEndian.Uint32(b[108:]),
		Ledger:          binary.LittleEndian.Uint32(b[112:]),
		Code:            binary.LittleEndian.Uint16(b[116:]),
		Flags:           TransferFlags(binary.LittleEndian.Uint16(b[118:])),
		Timestamp:       binary.LittleEndian.Uint64(b[120:]),
	}
}

func unmarshalBalance(b []byte) AccountBalance {
	var out AccountBalance
	out.DebitsPending = getU128(b[0:])
	out.DebitsPosted = getU128(b[16:])
	out.CreditsPending = getU128(b[32:])
	out.CreditsPosted = getU128(b[48:])
	out.Timestamp = binary.LittleEndian.Uint64(b[64:])
	copy(out.Reserved[:], b[72:128])
	return out
}

func marshalFilter(f AccountFilter) []byte {
	b := make([]byte, filterSize)
	putU128(b[0:], f.AccountId)
	binary.LittleEndian.PutUint64(b[16:], f.TimestampMin)
	binary.LittleEndian.PutUint64(b[24:], f.TimestampMax)
	binary.LittleEndian.PutUint32(b[32:], f.Limit)
	binary.LittleEndian.PutUint32(b[36:], uint32(f.Flags))
	copy(b[40:], f.Reserved[:])
	return b
}

func marshalIds(ids [][2]uint64) []byte {
	out := make([]byte, len(ids)*idPairSize)
	for i, id := range ids {
		putU128(out[i*idPairSize:], id)
	}
	return out
}

func unmarshalCreateResults(b []byte) []CreateResult {
	n := len(b) / createResultSize
	out := make([]CreateResult, n)
	for i := 0; i < n; i++ {
		out[i] = CreateResult{
			Index:  binary.LittleEndian.Uint32(b[i*8:]),
			Result: binary.LittleEndian.Uint32(b[i*8+4:]),
		}
	}
	return out
}
