module github.com/tigerbeetle-tpu/tigerbeetle-go

go 1.21
