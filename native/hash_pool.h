// Round 23: multi-lane hashing + the drain-scoped digest table.
//
// HashPool: a small persistent worker pool that fans independent
// SHA-256 jobs (frame verifies, body digests, reply finalizes) across
// TB_HASH_THREADS lanes *inside* one Python→C crossing — ctypes has
// already released the GIL, so lanes are real parallelism even while
// the drain thread owns the Python side.  0 lanes (the default on
// 1-core containers) runs every job inline on the calling thread;
// batches from concurrent callers (two in-process servers) serialize
// on a submit mutex while jobs within a batch run in parallel.
//
// DigestTable: a (ptr,len)→digest cache scoped to ONE drain crossing:
// tb_fp_verify_frames populates it with every verified frame's body
// digest and bumps the epoch (invalidating the previous crossing's
// entries — arena memory is reused across drains, so a stale pointer
// key must never survive into the next drain).  Consumers
// (tb_pl_build_prepares under TB_HASH_REUSE=1) treat it as a
// secondary tier: the primary digest-reuse tier is the verified
// header itself, whose checksum_body field IS the body digest the
// verify pass just proved.
#pragma once
#include "sha256.h"

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace tb {

constexpr int HASH_THREADS_MAX = 16;  // envcheck names this bound

inline std::atomic<int>& hash_threads_cfg() {
    static std::atomic<int> cfg{0};  // 0 = inline (no lanes)
    return cfg;
}

inline std::atomic<uint64_t>& hash_lane_jobs() {
    static std::atomic<uint64_t> jobs{0};  // jobs run ON POOL LANES
    return jobs;
}

inline std::atomic<uint64_t>& hash_table_hits() {
    static std::atomic<uint64_t> hits{0};  // digest-table lookups served
    return hits;
}

struct HashPool {
    std::mutex submit_mu;  // one batch in flight at a time
    std::mutex mu;
    std::condition_variable cv;
    std::condition_variable done_cv;
    std::vector<std::thread> workers;
    bool stop = false;
    uint64_t epoch = 0;
    uint32_t idle = 0;  // workers parked on cv (all, between batches)
    // Current batch: workers and the caller pull indices from `next`.
    const std::function<void(uint32_t)>* fn = nullptr;
    uint32_t total = 0;
    std::atomic<uint32_t> next{0};
    std::atomic<uint32_t> inflight{0};  // lanes still inside run_jobs

    ~HashPool() { shutdown(); }

    void shutdown() {
        {
            std::unique_lock<std::mutex> lk(mu);
            stop = true;
        }
        cv.notify_all();
        for (std::thread& t : workers)
            if (t.joinable()) t.join();
        workers.clear();
        std::unique_lock<std::mutex> lk(mu);
        stop = false;
        idle = 0;
    }

    void worker_loop() {
        uint64_t seen = 0;
        for (;;) {
            {
                std::unique_lock<std::mutex> lk(mu);
                idle++;
                // resize_locked waits for every lane to park before a
                // batch can be posted; done_cv doubles as that signal.
                done_cv.notify_all();
                // `fn != nullptr` guards two races: a fresh lane
                // spawning with seen=0 against a pool whose epoch
                // already advanced (it must park, not chase a dead
                // batch), and a lane waking AFTER the submitter
                // observed completion and cleared the batch under mu.
                cv.wait(lk, [&] {
                    return stop || (epoch != seen && fn != nullptr);
                });
                idle--;
                if (stop) return;
                seen = epoch;
                // Registered under mu: the submitter's completion
                // wait holds mu too, so it can never observe
                // inflight==0 and retire the batch between this
                // lane's wake-up and its first job claim (the
                // lost-lane race a plain post-unlock increment had).
                inflight.fetch_add(1, std::memory_order_acq_rel);
            }
            run_jobs(true);
            if (inflight.fetch_sub(1, std::memory_order_acq_rel) == 1) {
                std::unique_lock<std::mutex> lk(mu);
                done_cv.notify_all();
            }
        }
    }

    void run_jobs(bool on_lane) {
        for (;;) {
            uint32_t i = next.fetch_add(1, std::memory_order_relaxed);
            if (i >= total) return;
            (*fn)(i);
            if (on_lane)
                hash_lane_jobs().fetch_add(1, std::memory_order_relaxed);
        }
    }

    // Respawn to the configured lane count (rare: env/bench-driven).
    // Runs WITH submit_mu held — workers never touch submit_mu, so
    // joining them here cannot deadlock, and releasing submit_mu
    // mid-resize is exactly what must never happen: two submitters
    // resizing concurrently would both run shutdown() and join the
    // same std::thread objects (the r23 fuzz found that hang).
    void resize_locked(int lanes) {
        if (int(workers.size()) == lanes) return;
        shutdown();
        for (int i = 0; i < lanes; i++)
            workers.emplace_back([this] { worker_loop(); });
        // Wait until every lane is parked: a batch posted before a
        // lane reaches the cv would otherwise be missed by it (the
        // caller still completes the batch inline, but lanes_busy
        // would under-report the very first crossing).  Bounded: a
        // lane between unpark and re-park re-checks the predicate.
        std::unique_lock<std::mutex> lk(mu);
        done_cv.wait_for(lk, std::chrono::milliseconds(100), [&] {
            return idle == workers.size();
        });
    }

    // Run fn(i) for i in [0, n): on the caller plus every lane.  The
    // caller always participates, so TB_HASH_THREADS=N gives N+1-way
    // parallelism and N=0 degrades to the plain inline loop.
    void run(uint32_t n, const std::function<void(uint32_t)>& f) {
        int lanes = hash_threads_cfg().load(std::memory_order_relaxed);
        if (lanes <= 0 || n < 2) {
            for (uint32_t i = 0; i < n; i++) f(i);
            return;
        }
        std::lock_guard<std::mutex> batch(submit_mu);
        resize_locked(lanes);
        {
            std::unique_lock<std::mutex> lk(mu);
            fn = &f;
            total = n;
            next.store(0, std::memory_order_relaxed);
            epoch++;
        }
        cv.notify_all();
        run_jobs(false);
        std::unique_lock<std::mutex> lk(mu);
        done_cv.wait(lk, [&] {
            return inflight.load(std::memory_order_acquire) == 0;
        });
        fn = nullptr;
        total = 0;
    }
};

inline HashPool& hash_pool() {
    static HashPool pool;
    return pool;
}

template <class F>
inline void hash_parallel_for(uint32_t n, F&& f) {
    std::function<void(uint32_t)> fn(std::forward<F>(f));
    hash_pool().run(n, fn);
}

// ---------------------------------------------------------------------
// Drain-scoped digest table.

struct DigestTable {
    struct Entry {
        const void* ptr = nullptr;
        uint64_t len = 0;
        uint64_t d0 = 0, d1 = 0;
        uint64_t epoch = 0;
    };
    static constexpr size_t SLOTS = 4096;  // one drain's frames fit
    std::vector<Entry> slots{SLOTS};
    std::mutex mu;
    std::atomic<uint64_t> epoch{1};

    static size_t slot_of(const void* p, uint64_t n) {
        uint64_t h = (uint64_t(reinterpret_cast<uintptr_t>(p)) >> 4) *
                         0x9E3779B97F4A7C15ULL ^
                     n;
        return size_t(h % SLOTS);
    }

    // New crossing: every previous entry dies (arena reuse would
    // otherwise alias a stale digest onto fresh bytes at the same
    // address).  O(1): entries carry the epoch they were written in.
    void invalidate() { epoch.fetch_add(1, std::memory_order_acq_rel); }

    void put(const void* p, uint64_t n, uint64_t d0, uint64_t d1) {
        uint64_t e = epoch.load(std::memory_order_acquire);
        std::lock_guard<std::mutex> lk(mu);
        Entry& s = slots[slot_of(p, n)];
        s.ptr = p;
        s.len = n;
        s.d0 = d0;
        s.d1 = d1;
        s.epoch = e;
    }

    bool get(const void* p, uint64_t n, uint64_t out[2]) {
        uint64_t e = epoch.load(std::memory_order_acquire);
        std::lock_guard<std::mutex> lk(mu);
        const Entry& s = slots[slot_of(p, n)];
        if (s.epoch != e || s.ptr != p || s.len != n) return false;
        out[0] = s.d0;
        out[1] = s.d1;
        hash_table_hits().fetch_add(1, std::memory_order_relaxed);
        return true;
    }
};

inline DigestTable& digest_table() {
    static DigestTable table;
    return table;
}

}  // namespace tb
