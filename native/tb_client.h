// tb_client.h — public C ABI of the tigerbeetle_tpu native client.
//
// Two client APIs over the same TCP wire protocol (256-byte header +
// body, see tigerbeetle_tpu/vsr/wire.py):
//
//  1. The synchronous session API (tb_client_init / tb_client_request)
//     implemented in tb_runtime.cpp — one blocking round-trip at a
//     time.  Kept for simple callers and the Python ctypes binding.
//
//  2. The asynchronous packet API (tb_async_*) implemented in
//     tb_async.cpp — the analog of the reference's packet-based
//     tb_client (reference: src/clients/c/tb_client.zig:1-142,
//     src/clients/c/tb_client/context.zig): callers submit
//     tb_packet_t's from any thread; a dedicated IO thread owns the
//     socket, coalesces queued packets of the same batchable operation
//     into one request (reference: batch_logical_allowed,
//     src/state_machine.zig:122-131), keeps one request in flight per
//     session (the VSR client invariant), demultiplexes batched
//     replies back per packet, and fires the completion callback from
//     the IO thread.  Many packets can be in flight at once and
//     completions are NOT in submission order (a later packet batched
//     into an earlier request completes first).
//
// All language bindings (Go / TypeScript sources under clients/) speak
// either this ABI or the TCP protocol directly.

#ifndef TB_CLIENT_H
#define TB_CLIENT_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

// ---------------------------------------------------------------------
// Shared wire-level constants (tigerbeetle_tpu/types.py Operation).

enum TB_OPERATION {
    TB_OPERATION_PULSE = 128,
    TB_OPERATION_CREATE_ACCOUNTS = 129,
    TB_OPERATION_CREATE_TRANSFERS = 130,
    TB_OPERATION_LOOKUP_ACCOUNTS = 131,
    TB_OPERATION_LOOKUP_TRANSFERS = 132,
    TB_OPERATION_GET_ACCOUNT_TRANSFERS = 133,
    TB_OPERATION_GET_ACCOUNT_BALANCES = 134,
};

// ---------------------------------------------------------------------
// Synchronous session API (tb_runtime.cpp).

typedef struct tb_client tb_client_t;

tb_client_t* tb_client_init(const char* host, uint16_t port,
                            uint64_t cluster, uint64_t client_lo,
                            uint64_t client_hi);
void tb_client_deinit(tb_client_t* client);

// Returns reply body length (>= 0) or a negative status:
// -2 evicted, -3 timeout, -4 io error, -5 reply buffer too small.
int64_t tb_client_request(tb_client_t* client, uint8_t operation,
                          const uint8_t* body, uint32_t body_len,
                          uint8_t* reply_buf, uint32_t reply_cap,
                          int timeout_ms);

// ---------------------------------------------------------------------
// Asynchronous packet API (tb_async.cpp).

typedef enum TB_PACKET_STATUS {
    TB_PACKET_OK = 0,
    TB_PACKET_TOO_MUCH_DATA = 1,      // > batch_max events for the op
    TB_PACKET_INVALID_OPERATION = 2,  // unknown operation byte
    TB_PACKET_INVALID_DATA_SIZE = 3,  // not a multiple of the event size
    TB_PACKET_CLIENT_EVICTED = 4,     // session evicted by the cluster
    TB_PACKET_CLIENT_SHUTDOWN = 5,    // deinit before completion
} TB_PACKET_STATUS;

// One request unit.  The caller owns the packet and its data buffer;
// both must stay valid until the completion callback fires for the
// packet.  `next` is internal queue linkage (reference packet layout:
// src/clients/c/tb_client/packet.zig).
typedef struct tb_packet {
    struct tb_packet* next;  // internal; must be NULL on submit
    void* user_data;         // opaque, returned in the completion
    uint8_t operation;       // TB_OPERATION_*
    uint8_t status;          // TB_PACKET_STATUS, set before completion
    uint32_t data_size;      // bytes in `data`
    const void* data;        // event array (wire layout)
} tb_packet_t;

typedef struct tb_async_client tb_async_client_t;

// Completion callback: fired on the IO thread once per packet, exactly
// once.  `reply`/`reply_len` hold the packet's slice of the reply body
// (valid only for the duration of the callback; NULL when status !=
// TB_PACKET_OK).
typedef void (*tb_async_on_completion)(void* context, tb_packet_t* packet,
                                       const uint8_t* reply,
                                       uint32_t reply_len);

// Create a client session and spawn its IO thread.  The thread
// connects, registers the session, and starts draining submissions.
// Returns NULL on resource exhaustion (never blocks on the network).
tb_async_client_t* tb_async_init(const char* host, uint16_t port,
                                 uint64_t cluster, uint64_t client_lo,
                                 uint64_t client_hi,
                                 tb_async_on_completion on_completion,
                                 void* completion_context);

// Submit a packet (thread-safe, non-blocking).  Returns 0 on enqueue;
// on immediate validation failure the packet status is set and the
// completion fires synchronously on the calling thread, return -1.
int tb_async_submit(tb_async_client_t* client, tb_packet_t* packet);

// Flow control for tests and batch-heavy callers: while paused the IO
// thread completes in-flight requests but pops no new submissions, so
// everything submitted during the pause coalesces maximally on resume.
void tb_async_pause(tb_async_client_t* client);
void tb_async_resume(tb_async_client_t* client);

// Join the IO thread.  Every packet not yet completed — queued or in
// flight — completes with TB_PACKET_CLIENT_SHUTDOWN.  NOTE: an
// in-flight request may still commit server-side; SHUTDOWN means
// "completion unknown", not "not executed".  To resolve the ambiguity,
// reconnect under the SAME client id: the session's at-most-once
// dedupe replays the stored reply instead of re-executing.
void tb_async_deinit(tb_async_client_t* client);

#ifdef __cplusplus
}
#endif

#endif  // TB_CLIENT_H
