// tb_runtime: native host runtime — event loop, TCP message bus, and
// the C-ABI client session.
//
// TPU-native re-design of the reference's native runtime components
// (reference: src/io/linux.zig io_uring proactor, src/message_bus.zig
// TCP mesh, src/clients/c/tb_client.zig C ABI).  The compute path is
// JAX/XLA on the device; this is the host side: non-blocking epoll
// event loop, header-framed message streams (a message is self-framing
// via the `size` u32 at byte offset 144 of the 256-byte header — see
// tigerbeetle_tpu/vsr/wire.py HEADER_DTYPE), per-connection send
// queues, and a synchronous-API client with request/reply matching.
//
// Exposed as a C ABI for ctypes (Python) and any other language
// binding, mirroring the tb_client role.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <map>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>
#include <vector>

#include "sha256.h"

namespace {

constexpr uint32_t HEADER_SIZE = 256;
constexpr uint32_t SIZE_OFFSET = 144;  // wire.py HEADER_DTYPE "size"

// Header field offsets (must match tigerbeetle_tpu/vsr/wire.py).
constexpr uint32_t OFF_CHECKSUM = 0;
constexpr uint32_t OFF_CHECKSUM_BODY = 16;
constexpr uint32_t OFF_CLIENT = 48;
constexpr uint32_t OFF_CLUSTER = 64;
constexpr uint32_t OFF_REQUEST = 112;
constexpr uint32_t OFF_COMMAND = 153;
constexpr uint32_t OFF_OPERATION = 154;
constexpr uint32_t OFF_VERSION = 155;

constexpr uint8_t CMD_REQUEST = 5;
constexpr uint8_t CMD_REPLY = 8;
constexpr uint8_t CMD_EVICTION = 18;
constexpr uint8_t OP_REGISTER = 2;
constexpr uint8_t WIRE_VERSION = 1;

void put_u32(uint8_t* p, uint32_t v) { memcpy(p, &v, 4); }
void put_u64(uint8_t* p, uint64_t v) { memcpy(p, &v, 8); }
uint32_t get_u32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }

int set_nonblocking(int fd) {
    int flags = fcntl(fd, F_GETFL, 0);
    return fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

struct Connection {
    int fd = -1;
    bool connecting = false;
    std::vector<uint8_t> recv_buf;
    std::deque<std::vector<uint8_t>> send_queue;
    size_t send_offset = 0;
};

}  // namespace

// ----------------------------------------------------------------------
// Bus.

extern "C" {

struct tb_event {
    int32_t type;  // 1=accepted 2=connected 3=message 4=closed
    int32_t conn;
    const uint8_t* data;  // message events: valid until next poll
    uint32_t len;
};

struct tb_bus {
    int epfd = -1;
    int listen_fd = -1;
    uint32_t message_size_max = 1u << 20;
    int next_conn = 1;
    std::map<int, Connection> conns;       // conn id -> state
    std::map<int, int> fd_to_conn;
    std::deque<tb_event> events;
    // Message buffers backing queued events' data pointers, FIFO in
    // event order.  A buffer must outlive BOTH its queued event and
    // (for the legacy next_event API) the poll that follows its
    // consumption — so consumed buffers are counted and reclaimed at
    // the next poll, NOT freed on pop (clearing only when the event
    // deque drained empty leaked every buffer under sustained load,
    // where the deque is never observed empty).
    std::deque<std::vector<uint8_t>> held;
    size_t held_consumed = 0;
};

tb_bus* tb_bus_create(uint32_t message_size_max) {
    tb_bus* bus = new tb_bus();
    bus->epfd = epoll_create1(0);
    if (message_size_max) bus->message_size_max = message_size_max;
    if (bus->epfd < 0) { delete bus; return nullptr; }
    return bus;
}

void tb_bus_destroy(tb_bus* bus) {
    if (!bus) return;
    for (auto& [id, c] : bus->conns) close(c.fd);
    if (bus->listen_fd >= 0) close(bus->listen_fd);
    if (bus->epfd >= 0) close(bus->epfd);
    delete bus;
}

static void bus_arm(tb_bus* bus, Connection& c) {
    epoll_event ev{};
    ev.events = EPOLLIN | (c.send_queue.empty() && !c.connecting
                               ? 0u
                               : uint32_t(EPOLLOUT));
    ev.data.fd = c.fd;
    epoll_ctl(bus->epfd, EPOLL_CTL_MOD, c.fd, &ev);
}

int tb_bus_listen(tb_bus* bus, const char* host, uint16_t port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    inet_pton(AF_INET, host, &addr.sin_addr);
    if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
        listen(fd, 64) < 0) {
        close(fd);
        return -1;
    }
    set_nonblocking(fd);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    epoll_ctl(bus->epfd, EPOLL_CTL_ADD, fd, &ev);
    bus->listen_fd = fd;
    return 0;
}

// Bound port of the listener (for port-0 listens).
int tb_bus_listen_port(tb_bus* bus) {
    if (bus->listen_fd < 0) return -1;
    sockaddr_in addr{};
    socklen_t len = sizeof(addr);
    getsockname(bus->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
    return ntohs(addr.sin_port);
}

int tb_bus_connect(tb_bus* bus, const char* host, uint16_t port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    set_nonblocking(fd);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    inet_pton(AF_INET, host, &addr.sin_addr);
    int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    if (rc < 0 && errno != EINPROGRESS) { close(fd); return -1; }
    int id = bus->next_conn++;
    Connection& c = bus->conns[id];
    c.fd = fd;
    c.connecting = (rc < 0);
    bus->fd_to_conn[fd] = id;
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.fd = fd;
    epoll_ctl(bus->epfd, EPOLL_CTL_ADD, fd, &ev);
    if (rc == 0) bus->events.push_back({2, id, nullptr, 0});
    return id;
}

int tb_bus_send(tb_bus* bus, int conn, const uint8_t* data, uint32_t len) {
    auto it = bus->conns.find(conn);
    if (it == bus->conns.end()) return -1;
    Connection& c = it->second;
    c.send_queue.emplace_back(data, data + len);
    bus_arm(bus, c);
    return 0;
}

// Scatter-gather send: header + body queued as ONE message without
// the caller concatenating them first (the Python-side `header.tobytes
// () + body` concat copied every megabyte body an extra time per hop).
int tb_bus_send2(tb_bus* bus, int conn, const uint8_t* head,
                 uint32_t head_len, const uint8_t* body,
                 uint32_t body_len) {
    auto it = bus->conns.find(conn);
    if (it == bus->conns.end()) return -1;
    Connection& c = it->second;
    c.send_queue.emplace_back();
    auto& msg = c.send_queue.back();
    msg.reserve(size_t(head_len) + body_len);
    msg.insert(msg.end(), head, head + head_len);
    msg.insert(msg.end(), body, body + body_len);
    bus_arm(bus, c);
    return 0;
}

// Vector send (r22 drain loop): queue k complete frames for one
// connection in a single crossing — the backup's per-drain prepare_ok
// run and any other same-destination frame burst.  Each frame is
// appended as its own queued message; one epoll (re)arm at the end.
int tb_bus_sendv(tb_bus* bus, int conn, const uint8_t* const* bufs,
                 const uint32_t* lens, uint32_t k) {
    auto it = bus->conns.find(conn);
    if (it == bus->conns.end()) return -1;
    Connection& c = it->second;
    for (uint32_t i = 0; i < k; i++) {
        c.send_queue.emplace_back(bufs[i], bufs[i] + lens[i]);
    }
    bus_arm(bus, c);
    return 0;
}

static void bus_close_conn(tb_bus* bus, int id) {
    auto it = bus->conns.find(id);
    if (it == bus->conns.end()) return;
    epoll_ctl(bus->epfd, EPOLL_CTL_DEL, it->second.fd, nullptr);
    close(it->second.fd);
    bus->fd_to_conn.erase(it->second.fd);
    bus->conns.erase(it);
    bus->events.push_back({4, id, nullptr, 0});
}

void tb_bus_close(tb_bus* bus, int conn) { bus_close_conn(bus, conn); }

static void bus_drain_recv(tb_bus* bus, int id, Connection& c) {
    // Extract complete messages: size u32 at header offset 144.
    size_t at = 0;
    while (c.recv_buf.size() - at >= HEADER_SIZE) {
        uint32_t size = get_u32(c.recv_buf.data() + at + SIZE_OFFSET);
        if (size < HEADER_SIZE || size > bus->message_size_max + HEADER_SIZE) {
            bus_close_conn(bus, id);
            return;
        }
        if (c.recv_buf.size() - at < size) break;
        bus->held.emplace_back(c.recv_buf.begin() + at,
                               c.recv_buf.begin() + at + size);
        bus->events.push_back(
            {3, id, bus->held.back().data(), size});
        at += size;
    }
    if (at) c.recv_buf.erase(c.recv_buf.begin(), c.recv_buf.begin() + at);
}

int tb_bus_poll(tb_bus* bus, int timeout_ms) {
    // Reclaim buffers whose message events were consumed before this
    // poll (their data pointers were only promised valid until now);
    // buffers for still-queued events stay (partial drains — arena
    // full — leave events queued across polls).
    while (bus->held_consumed > 0 && !bus->held.empty()) {
        bus->held.pop_front();
        bus->held_consumed--;
    }
    if (bus->events.empty()) {
        bus->held.clear();
        bus->held_consumed = 0;
    }
    epoll_event evs[64];
    int n = epoll_wait(bus->epfd, evs, 64, timeout_ms);
    for (int i = 0; i < n; i++) {
        int fd = evs[i].data.fd;
        if (fd == bus->listen_fd) {
            for (;;) {
                int cfd = accept(bus->listen_fd, nullptr, nullptr);
                if (cfd < 0) break;
                set_nonblocking(cfd);
                int one = 1;
                setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
                int id = bus->next_conn++;
                Connection& c = bus->conns[id];
                c.fd = cfd;
                bus->fd_to_conn[cfd] = id;
                epoll_event ev{};
                ev.events = EPOLLIN;
                ev.data.fd = cfd;
                epoll_ctl(bus->epfd, EPOLL_CTL_ADD, cfd, &ev);
                bus->events.push_back({1, id, nullptr, 0});
            }
            continue;
        }
        auto cit = bus->fd_to_conn.find(fd);
        if (cit == bus->fd_to_conn.end()) continue;
        int id = cit->second;
        Connection& c = bus->conns[id];

        if (evs[i].events & (EPOLLERR | EPOLLHUP)) {
            bus_close_conn(bus, id);
            continue;
        }
        if (evs[i].events & EPOLLOUT) {
            if (c.connecting) {
                int err = 0;
                socklen_t len = sizeof(err);
                getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
                if (err) { bus_close_conn(bus, id); continue; }
                c.connecting = false;
                bus->events.push_back({2, id, nullptr, 0});
            }
            while (!c.send_queue.empty()) {
                auto& front = c.send_queue.front();
                ssize_t w = ::send(fd, front.data() + c.send_offset,
                                   front.size() - c.send_offset, MSG_NOSIGNAL);
                if (w < 0) break;
                c.send_offset += size_t(w);
                if (c.send_offset == front.size()) {
                    c.send_queue.pop_front();
                    c.send_offset = 0;
                }
            }
            bus_arm(bus, c);
        }
        if (evs[i].events & EPOLLIN) {
            uint8_t tmp[65536];
            for (;;) {
                ssize_t r = recv(fd, tmp, sizeof(tmp), 0);
                if (r > 0) {
                    c.recv_buf.insert(c.recv_buf.end(), tmp, tmp + r);
                } else if (r == 0) {
                    bus_close_conn(bus, id);
                    break;
                } else {
                    break;  // EAGAIN
                }
            }
            if (bus->conns.count(id)) bus_drain_recv(bus, id, c);
        }
    }
    return int(bus->events.size());
}

int tb_bus_next_event(tb_bus* bus, tb_event* out) {
    if (bus->events.empty()) return 0;
    *out = bus->events.front();
    bus->events.pop_front();
    if (out->type == 3 && out->len) bus->held_consumed++;
    return 1;
}

// One-call drain for the columnar ingest fast path: poll, then copy
// every pending event out in one pass — message payloads packed
// back-to-back into `arena`, one (type, conn, offset, len) row per
// event.  Returns the number of events emitted; events that don't fit
// this arena stay queued for the next call (their buffers are held
// until consumed — see tb_bus_poll).  This replaces the per-event
// ctypes round trip AND hands Python one contiguous buffer the batch
// decoder (tb_fastpath tb_fp_verify_frames) can verify in one pass.
int tb_bus_poll_drain(tb_bus* bus, int timeout_ms, uint8_t* arena,
                      uint64_t arena_cap, int32_t* types, int32_t* conns,
                      uint64_t* offsets, uint32_t* lens,
                      int32_t max_events) {
    tb_bus_poll(bus, timeout_ms);
    int32_t n = 0;
    uint64_t at = 0;
    while (n < max_events && !bus->events.empty()) {
        const tb_event& ev = bus->events.front();
        if (ev.type == 3 && ev.len) {
            if (at + ev.len > arena_cap) break;  // next call resumes
            memcpy(arena + at, ev.data, ev.len);
            offsets[n] = at;
            lens[n] = ev.len;
            at += ev.len;
            bus->held_consumed++;  // copied out: reclaim at next poll
        } else {
            offsets[n] = at;
            lens[n] = 0;
        }
        types[n] = ev.type;
        conns[n] = ev.conn;
        bus->events.pop_front();
        n++;
    }
    return n;
}

// ----------------------------------------------------------------------
// Wire helpers (header checksum discipline, C side).

void tb_header_finalize(uint8_t* header, const uint8_t* body, uint32_t body_len) {
    put_u32(header + SIZE_OFFSET, HEADER_SIZE + body_len);
    uint64_t cb[2];
    tb::checksum128(body, body_len, cb);
    put_u64(header + OFF_CHECKSUM_BODY, cb[0]);
    put_u64(header + OFF_CHECKSUM_BODY + 8, cb[1]);
    uint64_t cs[2];
    tb::checksum128(header + 16, HEADER_SIZE - 16, cs);
    put_u64(header + OFF_CHECKSUM, cs[0]);
    put_u64(header + OFF_CHECKSUM + 8, cs[1]);
}

int tb_header_verify(const uint8_t* header, const uint8_t* body,
                     uint32_t body_len) {
    uint64_t cs[2];
    tb::checksum128(header + 16, HEADER_SIZE - 16, cs);
    uint8_t want[16];
    memcpy(want, header + OFF_CHECKSUM, 16);
    uint8_t got[16];
    memcpy(got, cs, 16);
    if (memcmp(want, got, 16) != 0) return 0;
    if (body) {
        uint64_t cb[2];
        tb::checksum128(body, body_len, cb);
        if (memcmp(header + OFF_CHECKSUM_BODY, cb, 16) != 0) return 0;
    }
    return 1;
}

// ----------------------------------------------------------------------
// Client session (the tb_client analog): synchronous request/reply.

struct tb_client {
    tb_bus* bus = nullptr;
    int conn = -1;
    uint64_t cluster = 0;
    uint64_t client_lo = 0, client_hi = 0;
    uint32_t request_number = 0;
    bool registered = false;
    std::string host;
    uint16_t port = 0;
    // Additional cluster addresses: retransmits rotate through them so
    // a view change (new primary without this client's conn) recovers
    // — the reference client similarly re-targets replicas on timeout
    // (src/vsr/client.zig).
    std::vector<std::pair<std::string, uint16_t>> extra_addrs;
    std::vector<int> extra_conns;
    size_t target = 0;  // 0 = primary conn, 1.. = extra
    std::vector<uint8_t> reply;
    int32_t last_status = 0;  // 0 ok, -2 evicted, -3 timeout, -4 io
};

static int client_connect(tb_client* c) {
    c->conn = tb_bus_connect(c->bus, c->host.c_str(), c->port);
    return c->conn >= 0 ? 0 : -1;
}

static int client_conn_for_target(tb_client* c) {
    if (c->target == 0 || c->extra_addrs.empty()) {
        if (c->conn < 0) client_connect(c);  // primary died: reconnect
        return c->conn;
    }
    size_t idx = (c->target - 1) % c->extra_addrs.size();
    while (c->extra_conns.size() <= idx) c->extra_conns.push_back(-1);
    if (c->extra_conns[idx] < 0) {
        c->extra_conns[idx] = tb_bus_connect(
            c->bus, c->extra_addrs[idx].first.c_str(),
            c->extra_addrs[idx].second);
    }
    return c->extra_conns[idx] >= 0 ? c->extra_conns[idx] : c->conn;
}

// A closed connection must not abort the request when other replicas
// (or a reconnect) can still serve it — invalidate the cached id and
// let the retransmission rotation recover.
static void client_note_closed(tb_client* c, int conn) {
    if (conn == c->conn) c->conn = -1;
    for (auto& ec : c->extra_conns)
        if (ec == conn) ec = -1;
}

tb_client* tb_client_init(const char* host, uint16_t port, uint64_t cluster,
                          uint64_t client_lo, uint64_t client_hi) {
    tb_client* c = new tb_client();
    c->bus = tb_bus_create(0);
    c->cluster = cluster;
    c->client_lo = client_lo;
    c->client_hi = client_hi;
    c->host = host;
    c->port = port;
    if (!c->bus || client_connect(c) < 0) {
        tb_bus_destroy(c->bus);
        delete c;
        return nullptr;
    }
    return c;
}

void tb_client_add_address(tb_client* c, const char* host, uint16_t port) {
    c->extra_addrs.emplace_back(host, port);
}

void tb_client_deinit(tb_client* c) {
    if (!c) return;
    tb_bus_destroy(c->bus);
    delete c;
}

// Send one request and wait for its reply.  Returns reply body length
// (>= 0) or a negative status.
static int64_t client_roundtrip(tb_client* c, uint8_t operation,
                                uint32_t request_number, const uint8_t* body,
                                uint32_t body_len, uint8_t* reply_buf,
                                uint32_t reply_cap, int timeout_ms) {
    uint8_t header[HEADER_SIZE];
    memset(header, 0, sizeof(header));
    header[OFF_COMMAND] = CMD_REQUEST;
    header[OFF_OPERATION] = operation;
    header[OFF_VERSION] = WIRE_VERSION;
    put_u64(header + OFF_CLUSTER, c->cluster);
    put_u64(header + OFF_CLIENT, c->client_lo);
    put_u64(header + OFF_CLIENT + 8, c->client_hi);
    put_u32(header + OFF_REQUEST, request_number);
    tb_header_finalize(header, body, body_len);

    std::vector<uint8_t> msg(header, header + HEADER_SIZE);
    msg.insert(msg.end(), body, body + body_len);
    // A failed initial send is not fatal: the retransmission loop
    // rotates targets (and reconnects) until the timeout.
    tb_bus_send(c->bus, client_conn_for_target(c), msg.data(),
                uint32_t(msg.size()));

    int waited = 0;
    const int step = 10;
    // Retransmit cadence: a lost reply (or a request that landed while
    // the primary was mid-repair or mid-view-change) is recovered by
    // resending the SAME request — session dedupe returns the stored
    // reply, so repeats are harmless — ROTATING through the cluster
    // addresses so a new primary that lacks this client's connection
    // learns it (reference: src/vsr/client.zig request_timeout
    // retransmission + replica re-targeting).
    int next_retransmit = 1000;
    while (waited <= timeout_ms) {
        tb_bus_poll(c->bus, step);
        waited += step;
        if (waited >= next_retransmit) {
            next_retransmit += 1000;
            if (!c->extra_addrs.empty())
                c->target = (c->target + 1) % (c->extra_addrs.size() + 1);
            int conn = client_conn_for_target(c);
            if (conn < 0 && c->extra_addrs.empty())
                return -4;  // single address, reconnect refused: fail fast
            tb_bus_send(c->bus, conn, msg.data(), uint32_t(msg.size()));
        }
        tb_event ev;
        while (tb_bus_next_event(c->bus, &ev)) {
            if (ev.type == 4) {  // closed: rotation/reconnect recovers
                client_note_closed(c, ev.conn);
                continue;
            }
            if (ev.type != 3) continue;
            const uint8_t* h = ev.data;
            uint32_t size = get_u32(h + SIZE_OFFSET);
            const uint8_t* rbody = h + HEADER_SIZE;
            uint32_t rbody_len = size - HEADER_SIZE;
            if (!tb_header_verify(h, rbody, rbody_len)) continue;
            if (h[OFF_COMMAND] == CMD_EVICTION) return -2;
            if (h[OFF_COMMAND] != CMD_REPLY) continue;
            if (get_u32(h + OFF_REQUEST) != request_number) continue;
            if (rbody_len > reply_cap) return -5;
            memcpy(reply_buf, rbody, rbody_len);
            return int64_t(rbody_len);
        }
    }
    return -3;  // timeout
}

int64_t tb_client_request(tb_client* c, uint8_t operation, const uint8_t* body,
                          uint32_t body_len, uint8_t* reply_buf,
                          uint32_t reply_cap, int timeout_ms) {
    if (!c->registered) {
        int64_t rc = client_roundtrip(c, OP_REGISTER, 0, nullptr, 0, reply_buf,
                                      reply_cap, timeout_ms);
        if (rc < 0) return rc;
        c->registered = true;
    }
    c->request_number += 1;
    return client_roundtrip(c, operation, c->request_number, body, body_len,
                            reply_buf, reply_cap, timeout_ms);
}

// Checksum export for parity tests.
void tb_checksum128(const uint8_t* data, uint64_t len, uint64_t out[2]) {
    tb::checksum128(data, size_t(len), out);
}

}  // extern "C"
