// tb_async: asynchronous packet-based client (the tb_client analog).
//
// The reference's tb_client is a submission-queue + completion-callback
// API around one VSR client session, with a dedicated IO thread and
// signal-based wakeup (reference: src/clients/c/tb_client.zig:1-142,
// src/clients/c/tb_client/context.zig, signal.zig).  This is the same
// design on the tigerbeetle_tpu wire protocol:
//
//  - callers submit tb_packet_t's from any thread onto an intrusive
//    lock-protected queue and wake the IO thread via an eventfd;
//  - the IO thread owns the socket: it registers the session, keeps
//    ONE request in flight (the VSR session invariant — request
//    numbers are strictly increasing and the server replays the stored
//    reply on retransmission, tigerbeetle_tpu/vsr/multi.py), and
//    coalesces consecutive queued packets of the same batchable
//    operation (create_accounts / create_transfers — reference
//    batch_logical_allowed, src/state_machine.zig:122-131) into one
//    wire request up to batch_max events;
//  - replies are demultiplexed back onto packets: create_* results
//    carry {index, result} pairs which are re-based per packet, so a
//    packet sees exactly its own failures with its own indexing;
//  - completions fire on the IO thread, out of submission order when
//    batching overtakes (a create packet submitted after a lookup can
//    complete first by riding an earlier create request).
//
// Reconnects retransmit the in-flight request under the same request
// number; the server's at-most-once session dedupe turns that into a
// stored-reply replay, so a request is never executed twice.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

#include "tb_client.h"

// From tb_runtime.cpp (same shared library).
extern "C" void tb_header_finalize(uint8_t* header, const uint8_t* body,
                                   uint32_t body_len);
extern "C" int tb_header_verify(const uint8_t* header, const uint8_t* body,
                                uint32_t body_len);

namespace {

constexpr uint32_t HEADER_SIZE = 256;
constexpr uint32_t MESSAGE_BODY_MAX = (1u << 20) - HEADER_SIZE;
constexpr uint32_t SIZE_OFFSET = 144;
constexpr uint32_t OFF_CLIENT = 48;
constexpr uint32_t OFF_CLUSTER = 64;
constexpr uint32_t OFF_REQUEST = 112;
constexpr uint32_t OFF_COMMAND = 153;
constexpr uint32_t OFF_OPERATION = 154;
constexpr uint32_t OFF_VERSION = 155;
constexpr uint8_t CMD_REQUEST = 5;
constexpr uint8_t CMD_REPLY = 8;
constexpr uint8_t CMD_EVICTION = 18;
constexpr uint8_t OP_REGISTER = 2;
constexpr uint8_t WIRE_VERSION = 1;

void put_u32(uint8_t* p, uint32_t v) { memcpy(p, &v, 4); }
void put_u64(uint8_t* p, uint64_t v) { memcpy(p, &v, 8); }
uint32_t get_u32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }

// Wire event size per operation; 0 = unknown operation.
uint32_t event_size_of(uint8_t op) {
    switch (op) {
        case TB_OPERATION_CREATE_ACCOUNTS:
        case TB_OPERATION_CREATE_TRANSFERS:
            return 128;
        case TB_OPERATION_LOOKUP_ACCOUNTS:
        case TB_OPERATION_LOOKUP_TRANSFERS:
            return 16;
        case TB_OPERATION_GET_ACCOUNT_TRANSFERS:
        case TB_OPERATION_GET_ACCOUNT_BALANCES:
            return 64;  // one AccountFilter (types.py ACCOUNT_FILTER_DTYPE)
        default:
            return 0;
    }
}

bool batchable(uint8_t op) {
    return op == TB_OPERATION_CREATE_ACCOUNTS ||
           op == TB_OPERATION_CREATE_TRANSFERS;
}

// Max events per request: bounded by the 1 MiB message for the events
// themselves AND by the reply (lookups return 128-byte rows per event).
uint32_t batch_max_of(uint8_t op) {
    uint32_t esize = event_size_of(op);
    uint32_t by_request = MESSAGE_BODY_MAX / esize;
    uint32_t by_reply = MESSAGE_BODY_MAX / 128u;
    return by_request < by_reply ? by_request : by_reply;
}

}  // namespace

extern "C" {

struct tb_async_client {
    std::string host;
    uint16_t port = 0;
    uint64_t cluster = 0;
    uint64_t client_lo = 0, client_hi = 0;
    tb_async_on_completion on_completion = nullptr;
    void* ctx = nullptr;

    std::mutex mu;
    tb_packet_t* q_head = nullptr;
    tb_packet_t* q_tail = nullptr;
    bool paused = false;
    bool shutdown = false;
    int event_fd = -1;
    std::thread io;

    // IO-thread state.
    int fd = -1;
    bool registered = false;
    uint32_t request_number = 0;
    bool evicted = false;
    // In-flight request: the packets it carries, each packet's event
    // count, and the full wire message for retransmission.
    std::vector<tb_packet_t*> inflight;
    std::vector<uint32_t> inflight_events;
    std::vector<uint8_t> inflight_msg;
    std::vector<uint8_t> recv_buf;
};

static void complete(tb_async_client* c, tb_packet_t* p, uint8_t status,
                     const uint8_t* reply, uint32_t reply_len) {
    p->status = status;
    p->next = nullptr;
    c->on_completion(c->ctx, p, status == TB_PACKET_OK ? reply : nullptr,
                     status == TB_PACKET_OK ? reply_len : 0);
}

static void wake(tb_async_client* c) {
    uint64_t one = 1;
    ssize_t rc = write(c->event_fd, &one, 8);
    (void)rc;
}

// --- IO thread ------------------------------------------------------

static bool send_all(tb_async_client* c, const uint8_t* data, size_t len) {
    size_t at = 0;
    while (at < len) {
        ssize_t w = send(c->fd, data + at, len - at, MSG_NOSIGNAL);
        if (w > 0) {
            at += size_t(w);
            continue;
        }
        if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
            pollfd pf{c->fd, POLLOUT, 0};
            poll(&pf, 1, 100);
            {
                std::lock_guard<std::mutex> g(c->mu);
                if (c->shutdown) return false;
            }
            continue;
        }
        return false;
    }
    return true;
}

static bool io_connect(tb_async_client* c) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return false;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(c->port);
    inet_pton(AF_INET, c->host.c_str(), &addr.sin_addr);
    if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
        close(fd);
        return false;
    }
    c->fd = fd;
    c->recv_buf.clear();
    return true;
}

static void build_request(tb_async_client* c, uint8_t operation,
                          uint32_t request_number, const uint8_t* body,
                          uint32_t body_len, std::vector<uint8_t>* out) {
    out->assign(HEADER_SIZE + body_len, 0);
    uint8_t* h = out->data();
    h[OFF_COMMAND] = CMD_REQUEST;
    h[OFF_OPERATION] = operation;
    h[OFF_VERSION] = WIRE_VERSION;
    put_u64(h + OFF_CLUSTER, c->cluster);
    put_u64(h + OFF_CLIENT, c->client_lo);
    put_u64(h + OFF_CLIENT + 8, c->client_hi);
    put_u32(h + OFF_REQUEST, request_number);
    if (body_len) memcpy(out->data() + HEADER_SIZE, body, body_len);
    tb_header_finalize(h, out->data() + HEADER_SIZE, body_len);
}

// Pop the next request's worth of packets off the submission queue
// (coalescing batchable same-operation runs) and send it.  Packets are
// validated at submit time, so the queue only holds well-formed ones.
// Caller holds no lock.
static void io_pump_submissions(tb_async_client* c) {
    if (!c->inflight.empty() || !c->registered) return;
    for (;;) {
        std::vector<tb_packet_t*> take;
        {
            std::lock_guard<std::mutex> g(c->mu);
            if (c->paused || c->q_head == nullptr) return;
            tb_packet_t* first = c->q_head;
            uint32_t esize = event_size_of(first->operation);
            uint32_t max_events = batch_max_of(first->operation);
            if (!batchable(first->operation)) {
                c->q_head = first->next;
                if (!c->q_head) c->q_tail = nullptr;
                take.push_back(first);
            } else {
                // Coalesce queued packets of this operation (not just
                // a consecutive run — the reference links
                // same-operation packets across the queue) within
                // batch_max; other operations keep their queue
                // positions.  The scan STOPS at the first same-op
                // packet that does not fit: same-operation packets
                // must never overtake each other in execution order
                // (a later create may post a pending created by an
                // earlier one).
                uint32_t total = 0;
                tb_packet_t** link = &c->q_head;
                while (*link) {
                    tb_packet_t* p = *link;
                    if (p->operation == first->operation) {
                        uint32_t ev = p->data_size / esize;
                        if (total + ev > max_events) break;
                        total += ev;
                        take.push_back(p);
                        *link = p->next;
                    } else {
                        link = &p->next;
                    }
                }
                c->q_tail = nullptr;
                for (tb_packet_t* p = c->q_head; p; p = p->next)
                    c->q_tail = p;
            }
        }
        if (c->evicted) {
            for (tb_packet_t* p : take)
                complete(c, p, TB_PACKET_CLIENT_EVICTED, nullptr, 0);
            continue;
        }

        // Build the coalesced body.
        uint32_t esize = event_size_of(take[0]->operation);
        std::vector<uint8_t> body;
        c->inflight_events.clear();
        for (tb_packet_t* p : take) {
            body.insert(body.end(), static_cast<const uint8_t*>(p->data),
                        static_cast<const uint8_t*>(p->data) + p->data_size);
            c->inflight_events.push_back(p->data_size / esize);
        }
        c->request_number += 1;
        build_request(c, take[0]->operation, c->request_number, body.data(),
                      uint32_t(body.size()), &c->inflight_msg);
        c->inflight = std::move(take);
        send_all(c, c->inflight_msg.data(), c->inflight_msg.size());
        return;  // one request in flight
    }
}

// Demultiplex a create_* reply: {index u32, result u32} entries sorted
// by index; each packet owns indices [base, base + events).  Indices
// are re-based in place so every packet sees its own 0-based slice.
static void io_complete_create_reply(tb_async_client* c, uint8_t* rbody,
                                     uint32_t rlen) {
    uint32_t n_entries = rlen / 8;
    uint32_t entry_at = 0;
    uint64_t base = 0;
    for (size_t k = 0; k < c->inflight.size(); k++) {
        uint32_t events = c->inflight_events[k];
        uint32_t start = entry_at;
        while (entry_at < n_entries &&
               get_u32(rbody + size_t(entry_at) * 8) < base + events) {
            put_u32(rbody + size_t(entry_at) * 8,
                    uint32_t(get_u32(rbody + size_t(entry_at) * 8) - base));
            entry_at++;
        }
        complete(c, c->inflight[k], TB_PACKET_OK, rbody + size_t(start) * 8,
                 (entry_at - start) * 8);
        base += events;
    }
}

static void io_on_message(tb_async_client* c, uint8_t* msg, uint32_t size) {
    uint8_t* body = msg + HEADER_SIZE;
    uint32_t body_len = size - HEADER_SIZE;
    if (!tb_header_verify(msg, body, body_len)) return;
    if (msg[OFF_COMMAND] == CMD_EVICTION) {
        c->evicted = true;
        for (tb_packet_t* p : c->inflight)
            complete(c, p, TB_PACKET_CLIENT_EVICTED, nullptr, 0);
        c->inflight.clear();
        return;
    }
    if (msg[OFF_COMMAND] != CMD_REPLY) return;
    uint32_t req = get_u32(msg + OFF_REQUEST);
    if (msg[OFF_OPERATION] == OP_REGISTER) {
        if (!c->registered && req == 0) c->registered = true;
        return;
    }
    if (c->inflight.empty() || req != c->request_number) return;
    uint8_t op = c->inflight[0]->operation;
    if (msg[OFF_OPERATION] != op) return;
    if (batchable(op)) {
        io_complete_create_reply(c, body, body_len);
        c->inflight.clear();
    } else {
        tb_packet_t* p = c->inflight[0];
        c->inflight.clear();
        complete(c, p, TB_PACKET_OK, body, body_len);
    }
}

static void io_drain_socket(tb_async_client* c) {
    uint8_t tmp[65536];
    for (;;) {
        ssize_t r = recv(c->fd, tmp, sizeof(tmp), MSG_DONTWAIT);
        if (r > 0) {
            c->recv_buf.insert(c->recv_buf.end(), tmp, tmp + r);
        } else if (r == 0 || (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
            close(c->fd);
            c->fd = -1;
            return;
        } else {
            break;
        }
    }
    size_t at = 0;
    while (c->recv_buf.size() - at >= HEADER_SIZE) {
        uint32_t size = get_u32(c->recv_buf.data() + at + SIZE_OFFSET);
        if (size < HEADER_SIZE || size > HEADER_SIZE + MESSAGE_BODY_MAX) {
            close(c->fd);
            c->fd = -1;
            return;
        }
        if (c->recv_buf.size() - at < size) break;
        io_on_message(c, c->recv_buf.data() + at, size);
        at += size;
    }
    if (at) c->recv_buf.erase(c->recv_buf.begin(), c->recv_buf.begin() + at);
}

static void io_thread_main(tb_async_client* c) {
    std::vector<uint8_t> reg_msg;
    int backoff_ms = 10;
    for (;;) {
        {
            std::lock_guard<std::mutex> g(c->mu);
            if (c->shutdown) break;
        }
        if (c->fd < 0) {
            if (!io_connect(c)) {
                pollfd pf{c->event_fd, POLLIN, 0};
                poll(&pf, 1, backoff_ms);
                uint64_t v;
                ssize_t rc = read(c->event_fd, &v, 8);
                (void)rc;
                backoff_ms = backoff_ms < 1000 ? backoff_ms * 2 : 1000;
                continue;
            }
            backoff_ms = 10;
            // (Re-)register, then retransmit any in-flight request:
            // the server's session dedupe replays the stored reply if
            // it already committed.
            c->registered = false;
            build_request(c, OP_REGISTER, 0, nullptr, 0, &reg_msg);
            send_all(c, reg_msg.data(), reg_msg.size());
            if (!c->inflight.empty())
                send_all(c, c->inflight_msg.data(), c->inflight_msg.size());
        }

        io_pump_submissions(c);

        pollfd pfs[2] = {{c->fd, POLLIN, 0}, {c->event_fd, POLLIN, 0}};
        poll(pfs, 2, 100);
        if (pfs[1].revents & POLLIN) {
            uint64_t v;
            ssize_t rc = read(c->event_fd, &v, 8);
            (void)rc;
        }
        if (pfs[0].revents & (POLLIN | POLLHUP | POLLERR)) io_drain_socket(c);
    }

    // Shutdown: everything not completed fails with CLIENT_SHUTDOWN.
    for (tb_packet_t* p : c->inflight)
        complete(c, p, TB_PACKET_CLIENT_SHUTDOWN, nullptr, 0);
    c->inflight.clear();
    for (;;) {
        tb_packet_t* p;
        {
            std::lock_guard<std::mutex> g(c->mu);
            p = c->q_head;
            if (p) {
                c->q_head = p->next;
                if (!c->q_head) c->q_tail = nullptr;
            }
        }
        if (!p) break;
        complete(c, p, TB_PACKET_CLIENT_SHUTDOWN, nullptr, 0);
    }
    if (c->fd >= 0) close(c->fd);
}

// --- Public API -----------------------------------------------------

tb_async_client_t* tb_async_init(const char* host, uint16_t port,
                                 uint64_t cluster, uint64_t client_lo,
                                 uint64_t client_hi,
                                 tb_async_on_completion on_completion,
                                 void* completion_context) {
    in_addr scratch;
    if (inet_pton(AF_INET, host, &scratch) != 1) return nullptr;
    tb_async_client* c = new tb_async_client();
    c->host = host;
    c->port = port;
    c->cluster = cluster;
    c->client_lo = client_lo;
    c->client_hi = client_hi;
    c->on_completion = on_completion;
    c->ctx = completion_context;
    c->event_fd = eventfd(0, EFD_NONBLOCK);
    if (c->event_fd < 0) {
        delete c;
        return nullptr;
    }
    c->io = std::thread(io_thread_main, c);
    return c;
}

int tb_async_submit(tb_async_client_t* c, tb_packet_t* p) {
    uint32_t esize = event_size_of(p->operation);
    if (esize == 0) {
        complete(c, p, TB_PACKET_INVALID_OPERATION, nullptr, 0);
        return -1;
    }
    if (p->data_size % esize != 0) {
        complete(c, p, TB_PACKET_INVALID_DATA_SIZE, nullptr, 0);
        return -1;
    }
    // Query operations take exactly one AccountFilter.
    if ((p->operation == TB_OPERATION_GET_ACCOUNT_TRANSFERS ||
         p->operation == TB_OPERATION_GET_ACCOUNT_BALANCES) &&
        p->data_size != esize) {
        complete(c, p, TB_PACKET_INVALID_DATA_SIZE, nullptr, 0);
        return -1;
    }
    if (p->data_size / esize > batch_max_of(p->operation)) {
        complete(c, p, TB_PACKET_TOO_MUCH_DATA, nullptr, 0);
        return -1;
    }
    p->next = nullptr;
    p->status = TB_PACKET_OK;
    {
        std::lock_guard<std::mutex> g(c->mu);
        if (c->shutdown) {
            // Completing under the lock would be rude; do it outside.
        } else {
            if (c->q_tail) {
                c->q_tail->next = p;
            } else {
                c->q_head = p;
            }
            c->q_tail = p;
            p = nullptr;
        }
    }
    if (p) {
        complete(c, p, TB_PACKET_CLIENT_SHUTDOWN, nullptr, 0);
        return -1;
    }
    wake(c);
    return 0;
}

void tb_async_pause(tb_async_client_t* c) {
    std::lock_guard<std::mutex> g(c->mu);
    c->paused = true;
}

void tb_async_resume(tb_async_client_t* c) {
    {
        std::lock_guard<std::mutex> g(c->mu);
        c->paused = false;
    }
    wake(c);
}

void tb_async_deinit(tb_async_client_t* c) {
    if (!c) return;
    {
        std::lock_guard<std::mutex> g(c->mu);
        c->shutdown = true;
    }
    wake(c);
    if (c->io.joinable()) c->io.join();
    close(c->event_fd);
    delete c;
}

}  // extern "C"
