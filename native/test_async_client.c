// End-to-end C integration test for the asynchronous packet client
// (tb_async.cpp) — the analog of the reference's C client sample +
// tb_client integration tests (reference: src/clients/c/tb_client.zig,
// samples).  Driven by tests/test_async_client.py against a live
// in-process server:   ./test_async_client <port>
//
// Exercises:
//  1. create_accounts packet completes OK with an empty result set;
//  2. THREE packets in flight at once (two create_transfers and one
//     lookup_accounts submitted while paused) — the two create packets
//     coalesce into ONE wire request and complete BEFORE the lookup
//     that was submitted between them: out-of-order completion;
//  3. per-packet demux re-bases failure indices (a failing transfer in
//     the second create packet reports index 0, not its batch offset);
//  4. lookup replies carry the expected balances;
//  5. invalid operation fails synchronously without touching the wire.
//
// Exits 0 on success; prints the failing check and exits 1 otherwise.

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <pthread.h>

#include "tb_client.h"

#define CHECK(cond, ...)                                        \
    do {                                                        \
        if (!(cond)) {                                          \
            fprintf(stderr, "FAIL %s:%d: ", __FILE__, __LINE__); \
            fprintf(stderr, __VA_ARGS__);                       \
            fprintf(stderr, "\n");                              \
            exit(1);                                            \
        }                                                       \
    } while (0)

// 128-byte wire layouts (tigerbeetle_tpu/types.py; reference:
// src/tigerbeetle.zig:7-111).
#pragma pack(push, 1)
typedef struct {
    uint64_t id_lo, id_hi;
    uint64_t debits_pending_lo, debits_pending_hi;
    uint64_t debits_posted_lo, debits_posted_hi;
    uint64_t credits_pending_lo, credits_pending_hi;
    uint64_t credits_posted_lo, credits_posted_hi;
    uint64_t user_data_128_lo, user_data_128_hi;
    uint64_t user_data_64;
    uint32_t user_data_32;
    uint32_t reserved;
    uint32_t ledger;
    uint16_t code;
    uint16_t flags;
    uint64_t timestamp;
} wire_account_t;

typedef struct {
    uint64_t id_lo, id_hi;
    uint64_t debit_account_id_lo, debit_account_id_hi;
    uint64_t credit_account_id_lo, credit_account_id_hi;
    uint64_t amount_lo, amount_hi;
    uint64_t pending_id_lo, pending_id_hi;
    uint64_t user_data_128_lo, user_data_128_hi;
    uint64_t user_data_64;
    uint32_t user_data_32;
    uint32_t timeout;
    uint32_t ledger;
    uint16_t code;
    uint16_t flags;
    uint64_t timestamp;
} wire_transfer_t;

typedef struct {
    uint32_t index;
    uint32_t result;
} wire_create_result_t;

typedef struct {
    uint64_t account_id_lo, account_id_hi;
    uint64_t timestamp_min, timestamp_max;
    uint32_t limit;
    uint32_t flags;
    uint8_t reserved[24];
} wire_account_filter_t;
#pragma pack(pop)

// Completion log: order + per-packet reply copies, cross-thread.
typedef struct {
    pthread_mutex_t mu;
    pthread_cond_t cv;
    int order[16];        // packet tags in completion order
    int statuses[16];
    uint8_t replies[16][4096];
    uint32_t reply_lens[16];
    int completed;
} harness_t;

static void on_completion(void* ctx, tb_packet_t* packet,
                          const uint8_t* reply, uint32_t reply_len) {
    harness_t* h = (harness_t*)ctx;
    int tag = (int)(intptr_t)packet->user_data;
    pthread_mutex_lock(&h->mu);
    h->order[h->completed] = tag;
    h->statuses[tag] = packet->status;
    if (reply && reply_len <= sizeof(h->replies[0])) {
        memcpy(h->replies[tag], reply, reply_len);
        h->reply_lens[tag] = reply_len;
    } else {
        h->reply_lens[tag] = 0;
    }
    h->completed++;
    pthread_cond_broadcast(&h->cv);
    pthread_mutex_unlock(&h->mu);
}

static void wait_completed(harness_t* h, int n) {
    pthread_mutex_lock(&h->mu);
    while (h->completed < n) pthread_cond_wait(&h->cv, &h->mu);
    pthread_mutex_unlock(&h->mu);
}

static int pos_of(harness_t* h, int tag) {
    for (int i = 0; i < h->completed; i++)
        if (h->order[i] == tag) return i;
    return -1;
}

int main(int argc, char** argv) {
    CHECK(argc == 2, "usage: test_async_client <port>");
    uint16_t port = (uint16_t)atoi(argv[1]);

    harness_t h;
    memset(&h, 0, sizeof(h));
    pthread_mutex_init(&h.mu, NULL);
    pthread_cond_init(&h.cv, NULL);

    tb_async_client_t* c =
        tb_async_init("127.0.0.1", port, 3, 0xC0FFEE, 0, on_completion, &h);
    CHECK(c != NULL, "tb_async_init");

    // --- 1. create_accounts -----------------------------------------
    wire_account_t accounts[2];
    memset(accounts, 0, sizeof(accounts));
    accounts[0].id_lo = 1;
    accounts[0].ledger = 1;
    accounts[0].code = 1;
    accounts[1].id_lo = 2;
    accounts[1].ledger = 1;
    accounts[1].code = 1;

    tb_packet_t p_acct;
    memset(&p_acct, 0, sizeof(p_acct));
    p_acct.user_data = (void*)(intptr_t)0;
    p_acct.operation = TB_OPERATION_CREATE_ACCOUNTS;
    p_acct.data = accounts;
    p_acct.data_size = sizeof(accounts);
    CHECK(tb_async_submit(c, &p_acct) == 0, "submit accounts");
    wait_completed(&h, 1);
    CHECK(h.statuses[0] == TB_PACKET_OK, "accounts status %d", h.statuses[0]);
    CHECK(h.reply_lens[0] == 0, "accounts should all succeed (len %u)",
          h.reply_lens[0]);

    // --- 2-3. paused fan-out: T1, LOOKUP, T2 ------------------------
    // While paused, submit create packet T1, then a lookup, then
    // create packet T2 (whose second transfer is invalid: same debit
    // and credit account).  On resume, T1+T2 coalesce into one request
    // ahead of the lookup, so T2 completes before the lookup despite
    // being submitted after it.
    wire_transfer_t t1[2];
    memset(t1, 0, sizeof(t1));
    for (int i = 0; i < 2; i++) {
        t1[i].id_lo = 100 + (uint64_t)i;
        t1[i].debit_account_id_lo = 1;
        t1[i].credit_account_id_lo = 2;
        t1[i].amount_lo = 10;
        t1[i].ledger = 1;
        t1[i].code = 1;
    }
    wire_transfer_t t2[2];
    memset(t2, 0, sizeof(t2));
    t2[0].id_lo = 200;
    t2[0].debit_account_id_lo = 2;
    t2[0].credit_account_id_lo = 1;
    t2[0].amount_lo = 5;
    t2[0].ledger = 1;
    t2[0].code = 1;
    t2[1].id_lo = 201;  // accounts_must_be_different => result 12
    t2[1].debit_account_id_lo = 1;
    t2[1].credit_account_id_lo = 1;
    t2[1].amount_lo = 5;
    t2[1].ledger = 1;
    t2[1].code = 1;

    struct {
        uint64_t lo, hi;
    } lookup_ids[2] = {{1, 0}, {2, 0}};

    tb_packet_t p_t1, p_lookup, p_t2;
    memset(&p_t1, 0, sizeof(p_t1));
    p_t1.user_data = (void*)(intptr_t)1;
    p_t1.operation = TB_OPERATION_CREATE_TRANSFERS;
    p_t1.data = t1;
    p_t1.data_size = sizeof(t1);
    memset(&p_lookup, 0, sizeof(p_lookup));
    p_lookup.user_data = (void*)(intptr_t)2;
    p_lookup.operation = TB_OPERATION_LOOKUP_ACCOUNTS;
    p_lookup.data = lookup_ids;
    p_lookup.data_size = sizeof(lookup_ids);
    memset(&p_t2, 0, sizeof(p_t2));
    p_t2.user_data = (void*)(intptr_t)3;
    p_t2.operation = TB_OPERATION_CREATE_TRANSFERS;
    p_t2.data = t2;
    p_t2.data_size = sizeof(t2);

    tb_async_pause(c);
    CHECK(tb_async_submit(c, &p_t1) == 0, "submit t1");
    CHECK(tb_async_submit(c, &p_lookup) == 0, "submit lookup");
    CHECK(tb_async_submit(c, &p_t2) == 0, "submit t2");
    tb_async_resume(c);
    wait_completed(&h, 4);

    CHECK(h.statuses[1] == TB_PACKET_OK, "t1 status %d", h.statuses[1]);
    CHECK(h.statuses[2] == TB_PACKET_OK, "lookup status %d", h.statuses[2]);
    CHECK(h.statuses[3] == TB_PACKET_OK, "t2 status %d", h.statuses[3]);

    // Out-of-order completion: t2 (submitted last) completed before
    // the lookup (submitted second) by riding t1's request.
    CHECK(pos_of(&h, 3) < pos_of(&h, 2),
          "t2 should complete before lookup (order: t1=%d lookup=%d t2=%d)",
          pos_of(&h, 1), pos_of(&h, 2), pos_of(&h, 3));

    // t1: no failures.  t2: exactly one failure, re-based to index 1.
    CHECK(h.reply_lens[1] == 0, "t1 failures (%u bytes)", h.reply_lens[1]);
    CHECK(h.reply_lens[3] == sizeof(wire_create_result_t),
          "t2 failure count (%u bytes)", h.reply_lens[3]);
    wire_create_result_t r;
    memcpy(&r, h.replies[3], sizeof(r));
    CHECK(r.index == 1, "t2 failure index %u (demux re-base)", r.index);
    CHECK(r.result == 12, "t2 failure result %u", r.result);

    // --- 4. lookup balances: 1 posted 2x10 debit, 5 credit ----------
    CHECK(h.reply_lens[2] == 2 * sizeof(wire_account_t), "lookup rows %u",
          h.reply_lens[2]);
    wire_account_t rows[2];
    memcpy(rows, h.replies[2], sizeof(rows));
    CHECK(rows[0].id_lo == 1 && rows[1].id_lo == 2, "lookup row ids");
    CHECK(rows[0].debits_posted_lo == 20, "acct1 debits %llu",
          (unsigned long long)rows[0].debits_posted_lo);
    CHECK(rows[0].credits_posted_lo == 5, "acct1 credits %llu",
          (unsigned long long)rows[0].credits_posted_lo);
    CHECK(rows[1].debits_posted_lo == 5, "acct2 debits %llu",
          (unsigned long long)rows[1].debits_posted_lo);
    CHECK(rows[1].credits_posted_lo == 20, "acct2 credits %llu",
          (unsigned long long)rows[1].credits_posted_lo);

    // --- 5. get_account_transfers: 64-byte filter, row reply --------
    wire_account_filter_t filter;
    memset(&filter, 0, sizeof(filter));
    filter.account_id_lo = 1;
    filter.timestamp_max = ~0ull >> 1;
    filter.limit = 10;
    filter.flags = 3;  // debits | credits
    tb_packet_t p_filter;
    memset(&p_filter, 0, sizeof(p_filter));
    p_filter.user_data = (void*)(intptr_t)5;
    p_filter.operation = TB_OPERATION_GET_ACCOUNT_TRANSFERS;
    p_filter.data = &filter;
    p_filter.data_size = sizeof(filter);
    CHECK(sizeof(filter) == 64, "filter wire size %zu", sizeof(filter));
    CHECK(tb_async_submit(c, &p_filter) == 0, "submit filter");
    wait_completed(&h, 5);
    CHECK(h.statuses[5] == TB_PACKET_OK, "filter status %d", h.statuses[5]);
    CHECK(h.reply_lens[5] == 3 * sizeof(wire_transfer_t),
          "account 1 touched by 3 transfers (%u bytes)", h.reply_lens[5]);
    wire_transfer_t got[3];
    memcpy(got, h.replies[5], sizeof(got));
    CHECK(got[0].amount_lo == 10 && got[1].amount_lo == 10 &&
              got[2].amount_lo == 5,
          "transfer amounts %llu %llu %llu",
          (unsigned long long)got[0].amount_lo,
          (unsigned long long)got[1].amount_lo,
          (unsigned long long)got[2].amount_lo);

    // --- 6. invalid operation fails synchronously -------------------
    tb_packet_t p_bad;
    memset(&p_bad, 0, sizeof(p_bad));
    p_bad.user_data = (void*)(intptr_t)4;
    p_bad.operation = 77;
    CHECK(tb_async_submit(c, &p_bad) == -1, "bad op should reject");
    CHECK(p_bad.status == TB_PACKET_INVALID_OPERATION, "bad op status %d",
          p_bad.status);

    tb_async_deinit(c);
    printf("async client ok: 5 packets, out-of-order completion verified\n");
    return 0;
}
