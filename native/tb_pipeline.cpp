// tb_pipeline: the per-prepare native commit pipeline (round 20).
//
// Moves the VSR steady-state per-prepare hot loop below Python
// (vsr/multi.py keeps view changes, checkpoints, and recovery):
//
// - Header construction + checksum stamping for prepares and
//   prepare_oks, bit-identical to wire.make_header + wire.copy_trace +
//   wire.finalize_header (tigerbeetle_tpu/vsr/wire.py HEADER_DTYPE —
//   the offsets below are asserted against it by the Python binding's
//   ABI version check and the differential tests).
// - Journal append framing: the sector-padded prepare buffer and the
//   redundant-header sector are built here and handed to
//   vsr/journal.py as ready-to-write buffers; the in-memory redundant
//   header ring (journal.headers, a contiguous numpy HEADER_DTYPE
//   array) is written in place.
// - Pipeline bookkeeping: the primary's in-flight slot table (op,
//   canonical checksum, ack bitset, synced flag) and the group-commit
//   gate query (quorum AND synced AND contiguous) — one C call per
//   gate decision instead of per-entry Python set/flag churn.
//
// Differential contract (the r14 TB_FASTPATH_DECODE pattern one layer
// higher): with TB_NATIVE_PIPELINE=0/1 every reply frame, WAL byte,
// and commit decision must be identical.  Nothing here may consult
// any state Python does not also hold.
//
// Compiled into libtb_fastpath.so (Makefile adds this file to both
// the release and asan FASTPATH rules).  tb_pl_abi_version() is the
// stale-.so tripwire: the Python loader refuses a library whose
// version disagrees instead of AttributeError-ing mid-drain.

#include <array>
#include <cstdint>
#include <cstring>
#include <vector>

#include "hash_pool.h"
#include "sha256.h"

namespace {

// HEADER_DTYPE field offsets (tigerbeetle_tpu/vsr/wire.py).
constexpr uint32_t PL_HEADER_SIZE = 256;
constexpr uint32_t OFF_CHECKSUM = 0;        // u128
constexpr uint32_t OFF_CHECKSUM_BODY = 16;  // u128
constexpr uint32_t OFF_PARENT = 32;         // u128
constexpr uint32_t OFF_CLIENT = 48;         // u128
constexpr uint32_t OFF_CLUSTER = 64;        // u128
constexpr uint32_t OFF_CONTEXT = 80;        // u128
constexpr uint32_t OFF_REQUEST = 112;       // u32
constexpr uint32_t OFF_VIEW = 116;          // u32
constexpr uint32_t OFF_OP = 120;            // u64
constexpr uint32_t OFF_COMMIT = 128;        // u64
constexpr uint32_t OFF_TIMESTAMP = 136;     // u64
constexpr uint32_t OFF_HDRSIZE = 144;       // u32
constexpr uint32_t OFF_RELEASE = 148;       // u32
constexpr uint32_t OFF_REPLICA = 152;       // u8
constexpr uint32_t OFF_COMMAND = 153;       // u8
constexpr uint32_t OFF_OPERATION = 154;     // u8
constexpr uint32_t OFF_HDRVERSION = 155;    // u8
constexpr uint32_t OFF_TRACE = 156;         // trace_id u64 + trace_ts u64
constexpr uint32_t TRACE_BYTES = 17;        // ... + trace_flags u8

constexpr uint8_t CMD_PREPARE = 6;
constexpr uint8_t CMD_PREPARE_OK = 7;
constexpr uint8_t PL_WIRE_VERSION = 1;

inline void wr32(uint8_t* p, uint32_t v) { memcpy(p, &v, 4); }
inline void wr64(uint8_t* p, uint64_t v) { memcpy(p, &v, 8); }
inline uint64_t pl_rd64(const uint8_t* p) {
    uint64_t v;
    memcpy(&v, p, 8);
    return v;
}

// Stamp size + a KNOWN checksum_body + checksum: the header hash can
// never be skipped (it covers fresh fields), but the body hash can
// when an earlier stage already proved the digest — that split is the
// whole r23 reuse seam.
void pl_stamp(uint8_t* h, uint64_t body_len, const uint64_t cb[2]) {
    wr32(h + OFF_HDRSIZE, PL_HEADER_SIZE + (uint32_t)body_len);
    memcpy(h + OFF_CHECKSUM_BODY, cb, 16);
    uint64_t cs[2];
    tb::checksum128(h + 16, PL_HEADER_SIZE - 16, cs);
    memcpy(h + OFF_CHECKSUM, cs, 16);
}

// Stamp size + checksum_body + checksum — wire.finalize_header.
void pl_finalize(uint8_t* h, const uint8_t* body, uint64_t body_len) {
    uint64_t cb[2];
    tb::checksum128(body, body_len, cb);
    pl_stamp(h, body_len, cb);
}

// Resolve a prepare body's digest without hashing, under the reuse
// invariant: every body reaching a build seam arrived under a header
// whose checksum_body was verified (ingress frames) or freshly
// computed over these exact bytes (_build_batch_request's coalesce
// finalize) — so req_hdr[OFF_CHECKSUM_BODY] IS SHA-256(body)[:16].
// The drain-scoped digest table is consulted first (zero-copy bodies
// still in the arena); the header carry covers everything else.
void pl_reuse_digest(const uint8_t* req_hdr, const uint8_t* body,
                     uint64_t body_len, uint64_t cb[2]) {
    if (!tb::digest_table().get(body, body_len, cb))
        memcpy(cb, req_hdr + OFF_CHECKSUM_BODY, 16);
}

// Journal append framing body, shared by the per-prepare entry point
// (tb_pl_frame_prepare, r20) and the per-drain batch calls (r22):
// out_prepare := header || body zero-padded to a sector multiple
// (returned); headers_ring[slot] := header (in-memory redundant ring,
// written in place); out_sector := the slot's redundant-header sector.
uint64_t pl_frame(const uint8_t* hdr, const uint8_t* body, uint64_t body_len,
                  uint8_t* headers_ring, uint64_t slot,
                  uint32_t headers_per_sector, uint32_t sector_size,
                  uint8_t* out_prepare, uint8_t* out_sector) {
    uint64_t msg = PL_HEADER_SIZE + body_len;
    uint64_t padded = (msg + sector_size - 1) / sector_size * sector_size;
    memcpy(out_prepare, hdr, PL_HEADER_SIZE);
    if (body_len) memcpy(out_prepare + PL_HEADER_SIZE, body, body_len);
    memset(out_prepare + msg, 0, padded - msg);
    memcpy(headers_ring + slot * PL_HEADER_SIZE, hdr, PL_HEADER_SIZE);
    uint64_t first = slot / headers_per_sector * headers_per_sector;
    uint64_t used = (uint64_t)headers_per_sector * PL_HEADER_SIZE;
    memcpy(out_sector, headers_ring + first * PL_HEADER_SIZE, used);
    memset(out_sector + used, 0, sector_size - used);
    return padded;
}

// The primary's in-flight slot table.  Pipelines are shallow
// (pipeline_prepare_queue_max, single digits), so a linear-scan
// vector beats any hashing; entries are appended in op order and
// erased on commit/reset.
struct PlEntry {
    uint64_t op;
    uint8_t checksum[16];  // the prepare's canonical checksum
    uint64_t votes;        // ack bitset by replica index (< 64)
    uint8_t synced;        // own WAL copy covered by a sync
};

struct Pipeline {
    std::vector<PlEntry> entries;
};

PlEntry* pl_find(Pipeline* pl, uint64_t op) {
    for (auto& e : pl->entries) {
        if (e.op == op) return &e;
    }
    return nullptr;
}

}  // namespace

extern "C" {

// Bumped whenever any tb_pl_* signature or semantic changes; the
// Python binding refuses to use a library reporting a different
// version (stale prebuilt .so whose rebuild failed).
uint32_t tb_pl_abi_version(void) { return 3; }

Pipeline* tb_pl_create(void) { return new Pipeline(); }

void tb_pl_destroy(Pipeline* pl) { delete pl; }

void tb_pl_reset(Pipeline* pl) { pl->entries.clear(); }

uint32_t tb_pl_size(Pipeline* pl) {
    return (uint32_t)pl->entries.size();
}

// Build + finalize a prepare header into out[256] — bit-identical to
// _primary_prepare's make_header(command=prepare, ...) + copy_trace
// (request -> prepare) + finalize_header(body).  client / request /
// operation / trace context are read from the triggering request's
// header; everything else arrives as scalars.  `context` is the
// logical-batch sub-request count (u128 low limb; high limb zero).
// flags bit 0 (r23, TB_HASH_REUSE): take checksum_body from the
// digest table / the request header instead of rehashing the body —
// bit-identical by the reuse invariant (see pl_reuse_digest).
static void pl_prepare_fields(
    const uint8_t* req_hdr, uint64_t cluster_lo, uint64_t cluster_hi,
    uint32_t view, uint64_t op, uint64_t commit, uint64_t timestamp,
    uint64_t parent_lo, uint64_t parent_hi, uint32_t replica,
    uint64_t context, uint32_t release, uint8_t* out) {
    memset(out, 0, PL_HEADER_SIZE);
    memcpy(out + OFF_CLIENT, req_hdr + OFF_CLIENT, 16);
    memcpy(out + OFF_REQUEST, req_hdr + OFF_REQUEST, 4);
    out[OFF_OPERATION] = req_hdr[OFF_OPERATION];
    memcpy(out + OFF_TRACE, req_hdr + OFF_TRACE, TRACE_BYTES);
    wr64(out + OFF_CLUSTER, cluster_lo);
    wr64(out + OFF_CLUSTER + 8, cluster_hi);
    wr64(out + OFF_PARENT, parent_lo);
    wr64(out + OFF_PARENT + 8, parent_hi);
    wr64(out + OFF_CONTEXT, context);
    wr32(out + OFF_VIEW, view);
    wr64(out + OFF_OP, op);
    wr64(out + OFF_COMMIT, commit);
    wr64(out + OFF_TIMESTAMP, timestamp);
    wr32(out + OFF_RELEASE, release);
    out[OFF_REPLICA] = (uint8_t)replica;
    out[OFF_COMMAND] = CMD_PREPARE;
    out[OFF_HDRVERSION] = PL_WIRE_VERSION;
}

void tb_pl_build_prepare(
    const uint8_t* req_hdr, const uint8_t* body, uint64_t body_len,
    uint64_t cluster_lo, uint64_t cluster_hi, uint32_t view, uint64_t op,
    uint64_t commit, uint64_t timestamp, uint64_t parent_lo,
    uint64_t parent_hi, uint32_t replica, uint64_t context,
    uint32_t release, uint32_t flags, uint8_t* out) {
    pl_prepare_fields(req_hdr, cluster_lo, cluster_hi, view, op, commit,
                      timestamp, parent_lo, parent_hi, replica, context,
                      release, out);
    if (flags & 1u) {
        uint64_t cb[2];
        pl_reuse_digest(req_hdr, body, body_len, cb);
        pl_stamp(out, body_len, cb);
    } else {
        pl_finalize(out, body, body_len);
    }
}

// Build + finalize a prepare_ok header into out[256] — bit-identical
// to _send_prepare_ok's make_header(command=prepare_ok, ...) +
// copy_trace(prepare -> ok) + finalize_header(b"").  `context` is the
// prepare's own checksum (the vote names exact content).
void tb_pl_build_prepare_ok(const uint8_t* prepare_hdr, uint32_t view,
                            uint32_t replica, uint8_t* out) {
    memset(out, 0, PL_HEADER_SIZE);
    memcpy(out + OFF_CLUSTER, prepare_hdr + OFF_CLUSTER, 16);
    memcpy(out + OFF_CONTEXT, prepare_hdr + OFF_CHECKSUM, 16);
    memcpy(out + OFF_CLIENT, prepare_hdr + OFF_CLIENT, 16);
    memcpy(out + OFF_OP, prepare_hdr + OFF_OP, 8);
    memcpy(out + OFF_TRACE, prepare_hdr + OFF_TRACE, TRACE_BYTES);
    wr32(out + OFF_VIEW, view);
    out[OFF_REPLICA] = (uint8_t)replica;
    out[OFF_COMMAND] = CMD_PREPARE_OK;
    out[OFF_HDRVERSION] = PL_WIRE_VERSION;
    pl_finalize(out, nullptr, 0);
}

// Journal append framing (journal.write_prepare's byte layout):
// out_prepare := header || body, zero-padded to a sector multiple
// (returned); headers_ring[slot] := header (the in-memory redundant
// ring, written in place); out_sector := the slot's redundant-header
// sector (headers_per_sector ring entries, zero-padded to
// sector_size).  The caller issues the two storage writes at offsets
// it computes from the zone layout.
uint64_t tb_pl_frame_prepare(
    const uint8_t* hdr, const uint8_t* body, uint64_t body_len,
    uint8_t* headers_ring, uint64_t slot, uint32_t headers_per_sector,
    uint32_t sector_size, uint8_t* out_prepare, uint8_t* out_sector) {
    return pl_frame(hdr, body, body_len, headers_ring, slot,
                    headers_per_sector, sector_size, out_prepare, out_sector);
}

// Register an in-flight prepare (op + canonical checksum from its
// header) with the primary's self-vote.  An existing entry for the op
// is overwritten (view-change requeue re-registers the adopted tail).
void tb_pl_note_prepare(Pipeline* pl, const uint8_t* hdr, int synced,
                        uint32_t self_replica) {
    uint64_t op = pl_rd64(hdr + OFF_OP);
    PlEntry* e = pl_find(pl, op);
    if (e == nullptr) {
        pl->entries.push_back(PlEntry{});
        e = &pl->entries.back();
    }
    e->op = op;
    memcpy(e->checksum, hdr + OFF_CHECKSUM, 16);
    e->votes = 1ull << (self_replica & 63u);
    e->synced = (uint8_t)(synced != 0);
}

// Record a prepare_ok vote.  Returns the entry's vote count, or
// -1 when the op has no in-flight entry (already committed/dropped),
// -2 when the ack's context does not name the entry's exact checksum
// (a stale sibling's vote) — both mirror _on_prepare_ok's early
// returns exactly.
int tb_pl_on_ack(Pipeline* pl, const uint8_t* ok_hdr) {
    uint64_t op = pl_rd64(ok_hdr + OFF_OP);
    PlEntry* e = pl_find(pl, op);
    if (e == nullptr) return -1;
    if (memcmp(e->checksum, ok_hdr + OFF_CONTEXT, 16) != 0) return -2;
    e->votes |= 1ull << (ok_hdr[OFF_REPLICA] & 63u);
    return __builtin_popcountll(e->votes);
}

void tb_pl_mark_all_synced(Pipeline* pl) {
    for (auto& e : pl->entries) e.synced = 1;
}

int tb_pl_set_synced(Pipeline* pl, uint64_t op, int synced) {
    PlEntry* e = pl_find(pl, op);
    if (e == nullptr) return -1;
    e->synced = (uint8_t)(synced != 0);
    return 0;
}

void tb_pl_drop(Pipeline* pl, uint64_t op) {
    for (size_t i = 0; i < pl->entries.size(); i++) {
        if (pl->entries[i].op == op) {
            pl->entries.erase(pl->entries.begin() + (ptrdiff_t)i);
            return;
        }
    }
}

// The group-commit gate: 1 when the NEXT op (commit_min + 1) is
// in-flight with a replication quorum of exact-checksum votes AND its
// own WAL copy is sync-covered — _maybe_commit_pipeline's quorum /
// synced / contiguity checks in one call.
int tb_pl_commit_ready(Pipeline* pl, uint64_t commit_min,
                       uint32_t quorum) {
    PlEntry* e = pl_find(pl, commit_min + 1);
    if (e == nullptr || !e->synced) return 0;
    return __builtin_popcountll(e->votes) >= (int)quorum ? 1 : 0;
}

uint32_t tb_pl_votes(Pipeline* pl, uint64_t op) {
    PlEntry* e = pl_find(pl, op);
    return e == nullptr ? 0 : (uint32_t)__builtin_popcountll(e->votes);
}

// ---- r22: the C-resident drain loop (one call per drain batch) ----
//
// The per-item calls above stay for K=1 callers and the differential
// arm; the three batch entry points below run a whole drain's worth
// of builds/framings/votes in one Python->C crossing each.  Every
// byte they produce must match a loop over the per-item calls — the
// TB_NATIVE_DRAIN=0/1 differential suite holds them to it.

// Primary drain: build + finalize K prepares chained
// parent->checksum (parent_lo/hi seeds op0's parent), register each
// in the slot table with the self-vote, and frame each for the
// journal into caller arenas:
//   wal_arena[out_wal_off[i] .. +out_wal_len[i]]  — sector-padded
//     prepare for slot out_slot[i] (= (op0+i) % slot_count);
//   sector_arena[i*sector_size ..]                — redundant-header
//     sector out_sector_index[i].
// Capacity is checked up front: on overflow returns -1 with NOTHING
// mutated (the caller falls back to the per-item path).  Returns k.
// flags bit 0 (r23): digest reuse — see tb_pl_build_prepare.  With
// reuse OFF the body digests (the only order-independent hash work;
// the header pass is strictly sequential through the parent chain)
// are computed up front across the hash pool lanes.
int64_t tb_pl_build_prepares(
    Pipeline* pl, const uint8_t* req_hdrs, const uint8_t* const* bodies,
    const uint64_t* body_lens, const uint64_t* timestamps,
    const uint64_t* contexts, uint64_t k, uint64_t cluster_lo,
    uint64_t cluster_hi, uint32_t view, uint64_t op0, uint64_t commit,
    uint64_t parent_lo, uint64_t parent_hi, uint32_t replica,
    uint32_t release, int synced, uint32_t flags, uint8_t* out_hdrs,
    uint8_t* headers_ring,
    uint64_t slot_count, uint32_t headers_per_sector, uint32_t sector_size,
    uint8_t* wal_arena, uint64_t wal_cap, uint64_t* out_wal_off,
    uint64_t* out_wal_len, uint64_t* out_slot, uint8_t* sector_arena,
    uint64_t* out_sector_index) {
    uint64_t need = 0;
    for (uint64_t i = 0; i < k; i++) {
        uint64_t msg = PL_HEADER_SIZE + body_lens[i];
        need += (msg + sector_size - 1) / sector_size * sector_size;
    }
    if (need > wal_cap) return -1;
    std::vector<std::array<uint64_t, 2>> cbs(k);
    if (flags & 1u) {
        for (uint64_t i = 0; i < k; i++)
            pl_reuse_digest(req_hdrs + i * PL_HEADER_SIZE, bodies[i],
                            body_lens[i], cbs[i].data());
    } else {
        tb::hash_parallel_for((uint32_t)k, [&](uint32_t i) {
            tb::checksum128(bodies[i], body_lens[i], cbs[i].data());
        });
    }
    uint64_t wal_at = 0;
    uint64_t plo = parent_lo;
    uint64_t phi = parent_hi;
    for (uint64_t i = 0; i < k; i++) {
        uint8_t* out = out_hdrs + i * PL_HEADER_SIZE;
        pl_prepare_fields(req_hdrs + i * PL_HEADER_SIZE, cluster_lo,
                          cluster_hi, view, op0 + i, commit, timestamps[i],
                          plo, phi, replica, contexts[i], release, out);
        pl_stamp(out, body_lens[i], cbs[i].data());
        plo = pl_rd64(out + OFF_CHECKSUM);
        phi = pl_rd64(out + OFF_CHECKSUM + 8);
        tb_pl_note_prepare(pl, out, synced, replica);
        uint64_t slot = (op0 + i) % slot_count;
        uint64_t padded =
            pl_frame(out, bodies[i], body_lens[i], headers_ring, slot,
                     headers_per_sector, sector_size, wal_arena + wal_at,
                     sector_arena + i * sector_size);
        out_wal_off[i] = wal_at;
        out_wal_len[i] = padded;
        out_slot[i] = slot;
        out_sector_index[i] = slot / headers_per_sector;
        wal_at += padded;
    }
    return (int64_t)k;
}

// Backup drain: frame K accepted prepares for the journal (same
// descriptor layout as tb_pl_build_prepares) and, unless the caller
// is a standby (build_oks=0), build the K prepare_ok headers in one
// pass.  No slot-table involvement — backups hold no vote state.
// Returns k, or -1 on arena overflow with nothing mutated.
int64_t tb_pl_accept_prepares(
    const uint8_t* hdrs, const uint8_t* const* bodies,
    const uint64_t* body_lens, uint64_t k, uint32_t view, uint32_t replica,
    int build_oks, uint8_t* out_oks, uint8_t* headers_ring,
    uint64_t slot_count, uint32_t headers_per_sector, uint32_t sector_size,
    uint8_t* wal_arena, uint64_t wal_cap, uint64_t* out_wal_off,
    uint64_t* out_wal_len, uint64_t* out_slot, uint8_t* sector_arena,
    uint64_t* out_sector_index) {
    uint64_t need = 0;
    for (uint64_t i = 0; i < k; i++) {
        uint64_t msg = PL_HEADER_SIZE + body_lens[i];
        need += (msg + sector_size - 1) / sector_size * sector_size;
    }
    if (need > wal_cap) return -1;
    uint64_t wal_at = 0;
    for (uint64_t i = 0; i < k; i++) {
        const uint8_t* h = hdrs + i * PL_HEADER_SIZE;
        uint64_t slot = pl_rd64(h + OFF_OP) % slot_count;
        uint64_t padded =
            pl_frame(h, bodies[i], body_lens[i], headers_ring, slot,
                     headers_per_sector, sector_size, wal_arena + wal_at,
                     sector_arena + i * sector_size);
        out_wal_off[i] = wal_at;
        out_wal_len[i] = padded;
        out_slot[i] = slot;
        out_sector_index[i] = slot / headers_per_sector;
        wal_at += padded;
        if (build_oks) {
            tb_pl_build_prepare_ok(h, view, replica,
                                   out_oks + i * PL_HEADER_SIZE);
        }
    }
    return (int64_t)k;
}

// Vote a whole run of prepare_ok headers in one call.  Per-ack
// verdict in out_votes[i]: -4 foreign cluster, -3 stale/future view,
// -1 unknown op, -2 stale-sibling checksum (tb_pl_on_ack's codes),
// else the entry's vote count after this ack.  Returns the number of
// acks that landed a vote.
int64_t tb_pl_on_acks(Pipeline* pl, const uint8_t* ok_hdrs, uint64_t k,
                      uint64_t cluster_lo, uint64_t cluster_hi,
                      uint32_t view, int64_t* out_votes) {
    int64_t accepted = 0;
    for (uint64_t i = 0; i < k; i++) {
        const uint8_t* h = ok_hdrs + i * PL_HEADER_SIZE;
        if (pl_rd64(h + OFF_CLUSTER) != cluster_lo ||
            pl_rd64(h + OFF_CLUSTER + 8) != cluster_hi) {
            out_votes[i] = -4;
            continue;
        }
        uint32_t hv;
        memcpy(&hv, h + OFF_VIEW, 4);
        if (hv != view) {
            out_votes[i] = -3;
            continue;
        }
        int r = tb_pl_on_ack(pl, h);
        out_votes[i] = r;
        if (r >= 0) accepted++;
    }
    return accepted;
}

// The contiguous run of commit-ready ops: the largest n such that
// every op in (commit_min, commit_min + n] is in-flight, synced, and
// holds a replication quorum — tb_pl_commit_ready extended from one
// gate decision to the whole drain's worth.
uint64_t tb_pl_commit_ready_run(Pipeline* pl, uint64_t commit_min,
                                uint32_t quorum) {
    uint64_t n = 0;
    for (;;) {
        PlEntry* e = pl_find(pl, commit_min + 1 + n);
        if (e == nullptr || !e->synced) break;
        if (__builtin_popcountll(e->votes) < (int)quorum) break;
        n++;
    }
    return n;
}

}  // extern "C"
