// tb_fastpath: native commit hot path for create_transfers.
//
// TPU-native split (see tigerbeetle_tpu/state_machine/tpu.py): the
// device (HBM) balance table is authoritative and fed by async fused
// scatter-adds; the HOST must decode the 8190x128B wire batch, run the
// static validation ladder, resolve accounts, detect duplicates, and
// admit balance deltas (monotone u128 overflow checks) without ever
// waiting on the device.  This file is that host loop in C++ — the
// native counterpart the reference implements in Zig
// (reference: src/state_machine.zig:1220-1306 execute loop,
// :1465-1547 create_transfer ladder + overflow checks).
//
// Ownership contract with Python (runtime/fastpath.py):
// - The balance mirror (lo/hi, A x 4 u64 each) lives HERE; Python wraps
//   the same memory as numpy arrays, so exact-path (JAX kernel) commits
//   and expiry mutations are visible to this code with zero copies.
// - Account metadata and the id directories are maintained via explicit
//   add/remove calls from Python on every commit path.
// - tb_fp_commit_transfers applies a batch ONLY when it is order-free
//   (no linked/post/void/balancing flags), duplicate-free, and touches
//   no limit/history accounts, and no overflow is possible — the exact
//   conditions of the Python fast path.  Otherwise it returns FALLBACK
//   having mutated nothing, and Python runs the exact JAX scan path.

#include <algorithm>
#include <array>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "hash_pool.h"
#include "sha256.h"

typedef unsigned __int128 u128;

namespace {

// Wire offsets within the 128-byte Transfer
// (tigerbeetle_tpu/types.py TRANSFER_DTYPE; reference:
// src/tigerbeetle.zig:80-111).
constexpr int OFF_ID_LO = 0;
constexpr int OFF_DR_LO = 16;
constexpr int OFF_CR_LO = 32;
constexpr int OFF_AMOUNT_LO = 48;
constexpr int OFF_PENDING_LO = 64;
constexpr int OFF_UD128_LO = 80;
constexpr int OFF_UD64 = 96;
constexpr int OFF_UD32 = 104;
constexpr int OFF_TIMEOUT = 108;
constexpr int OFF_LEDGER = 112;
constexpr int OFF_CODE = 116;
constexpr int OFF_FLAGS = 118;
constexpr int OFF_TIMESTAMP = 120;

// TransferFlags (types.py).
constexpr uint32_t F_LINKED = 1, F_PENDING = 2, F_POST = 4, F_VOID = 8;
constexpr uint32_t F_BAL_DR = 16, F_BAL_CR = 32;
constexpr uint32_t F_ORDER_DEP = F_LINKED | F_POST | F_VOID | F_BAL_DR | F_BAL_CR;
// AccountFlags.
constexpr uint32_t A_LIMIT_DR = 2, A_LIMIT_CR = 4, A_HISTORY = 8;

// CreateTransferResult codes used by the static ladder (types.py).
enum Code : uint32_t {
    OK = 0,
    TIMESTAMP_MUST_BE_ZERO = 3,
    RESERVED_FLAG = 4,
    ID_ZERO = 5,
    ID_MAX = 6,
    DR_ZERO = 8,
    DR_MAX = 9,
    CR_ZERO = 10,
    CR_MAX = 11,
    ACCOUNTS_SAME = 12,
    PENDING_ID_MUST_BE_ZERO = 13,
    TIMEOUT_RESERVED = 17,
    AMOUNT_ZERO = 18,
    LEDGER_ZERO = 19,
    CODE_ZERO = 20,
    DR_NOT_FOUND = 21,
    CR_NOT_FOUND = 22,
    LEDGERS_DIFFER = 23,
    TRANSFER_LEDGER_DIFFERS = 24,
};

constexpr uint64_t U64_MAX = ~0ull;
constexpr uint64_t NS_PER_S = 1000000000ull;

struct U128Hash {
    size_t operator()(u128 v) const {
        uint64_t lo = (uint64_t)v, hi = (uint64_t)(v >> 64);
        uint64_t h = lo * 0x9E3779B97F4A7C15ull ^ (hi + 0xC2B2AE3D27D4EB4Full);
        h ^= h >> 29;
        return (size_t)h;
    }
};

// Id directory: run-length ranges over sequential hi==0 ids (the
// recommended/benchmark id scheme) + hash fallback for everything else
// (mirrors tigerbeetle_tpu/utils/hashindex.py RunIndex).
struct IdDir {
    // Sorted, disjoint: ids [start, start+len) -> values [val0, ...).
    std::vector<uint64_t> starts, lens, vals;
    std::unordered_map<u128, uint64_t, U128Hash> map;

    size_t range_index(uint64_t lo) const {
        // Last range with start <= lo (or SIZE_MAX).
        size_t n = starts.size();
        size_t left = 0, right = n;
        while (left < right) {
            size_t mid = (left + right) / 2;
            if (starts[mid] <= lo) left = mid + 1; else right = mid;
        }
        return left == 0 ? SIZE_MAX : left - 1;
    }

    bool lookup(uint64_t lo, uint64_t hi, uint64_t* val) const {
        if (hi == 0 && !starts.empty()) {
            size_t i = range_index(lo);
            if (i != SIZE_MAX && lo - starts[i] < lens[i]) {
                *val = vals[i] + (lo - starts[i]);
                return true;
            }
        }
        auto it = map.find(((u128)hi << 64) | lo);
        if (it == map.end()) return false;
        *val = it->second;
        return true;
    }

    bool contains(uint64_t lo, uint64_t hi) const {
        uint64_t v;
        return lookup(lo, hi, &v);
    }

    // Batch insert; detects contiguous runs (ids and values both +1
    // steps, hi all zero, no u64 wrap).
    void insert(const uint64_t* lo, const uint64_t* hi, uint64_t val0,
                uint32_t n) {
        bool run = n >= 2 && hi[0] == 0 && lo[n - 1] >= lo[0];
        if (run) {
            for (uint32_t i = 1; i < n; i++) {
                if (hi[i] != 0 || lo[i] != lo[i - 1] + 1) { run = false; break; }
            }
        }
        if (run) {
            insert_range(lo[0], n, val0);
        } else {
            for (uint32_t i = 0; i < n; i++) {
                map.emplace(((u128)hi[i] << 64) | lo[i], val0 + i);
            }
        }
    }

    void insert_range(uint64_t start, uint64_t len, uint64_t val0) {
        size_t i = range_index(start);
        // Merge with predecessor when both ids and values abut.
        if (i != SIZE_MAX && starts[i] + lens[i] == start &&
            vals[i] + lens[i] == val0) {
            lens[i] += len;
            // May now abut the successor.
            size_t j = i + 1;
            if (j < starts.size() && starts[i] + lens[i] == starts[j] &&
                vals[i] + lens[i] == vals[j]) {
                lens[i] += lens[j];
                starts.erase(starts.begin() + j);
                lens.erase(lens.begin() + j);
                vals.erase(vals.begin() + j);
            }
            return;
        }
        size_t at = (i == SIZE_MAX) ? 0 : i + 1;
        // Merge with successor.
        if (at < starts.size() && start + len == starts[at] &&
            val0 + len == vals[at]) {
            starts[at] = start;
            lens[at] += len;
            vals[at] = val0;
            return;
        }
        starts.insert(starts.begin() + at, start);
        lens.insert(lens.begin() + at, len);
        vals.insert(vals.begin() + at, val0);
    }

    void remove(uint64_t lo, uint64_t hi) {
        // Remove from BOTH structures: defensive against an id that
        // was ever double-registered (map + range).
        u128 key = ((u128)hi << 64) | lo;
        map.erase(key);
        if (hi != 0) return;
        size_t i = range_index(lo);
        if (i == SIZE_MAX || lo - starts[i] >= lens[i]) return;
        uint64_t off = lo - starts[i];
        uint64_t tail = lens[i] - off - 1;
        if (off == 0 && tail == 0) {
            starts.erase(starts.begin() + i);
            lens.erase(lens.begin() + i);
            vals.erase(vals.begin() + i);
        } else if (off == 0) {
            starts[i] += 1; vals[i] += 1; lens[i] = tail;
        } else if (tail == 0) {
            lens[i] = off;
        } else {
            uint64_t ns = lo + 1, nv = vals[i] + off + 1;
            lens[i] = off;
            starts.insert(starts.begin() + i + 1, ns);
            lens.insert(lens.begin() + i + 1, tail);
            vals.insert(vals.begin() + i + 1, nv);
        }
    }
};

struct Fastpath {
    uint64_t capacity;
    // Balance mirror, SHARED with Python (numpy wraps these buffers).
    // Layout matches mirror.py: lo[A][4], hi[A][4]; cols dp,dpo,cp,cpo.
    std::vector<uint64_t> bal_lo, bal_hi;
    // Immutable account attributes.
    std::vector<uint32_t> acct_flags, acct_ledger;
    IdDir accounts;
    IdDir transfers;  // values unused (duplicate-id set)

    // Per-batch scratch (avoids reallocation).  Deltas use epoch-tagged
    // flat arrays over slot*4+col — O(1) accumulate with no hashing and
    // no per-batch clearing.
    std::unordered_set<u128, U128Hash> batch_ids;
    std::unordered_map<u128, uint32_t, U128Hash> batch_map;  // id -> index
    std::unordered_map<int64_t, uint32_t> dur_map;  // store row -> status
    std::vector<uint8_t> st_scratch;   // in-batch pending statuses
    std::vector<u128> delta_sum;       // capacity*4
    std::vector<uint32_t> delta_epoch; // capacity*4
    std::vector<uint64_t> delta_keys;  // touched keys, insertion order
    uint32_t epoch = 0;

    explicit Fastpath(uint64_t cap) : capacity(cap) {
        bal_lo.assign(cap * 4, 0);
        bal_hi.assign(cap * 4, 0);
        acct_flags.assign(cap, 0);
        acct_ledger.assign(cap, 0);
        delta_sum.assign(cap * 4, 0);
        delta_epoch.assign(cap * 4, 0);
        delta_keys.reserve(1 << 14);
    }

    // Accumulate `amount` into the per-batch delta for key; returns
    // false on u128 wrap.
    bool delta_add(uint64_t key, u128 amount) {
        if (delta_epoch[key] != epoch) {
            delta_epoch[key] = epoch;
            delta_sum[key] = 0;
            delta_keys.push_back(key);
        }
        u128& d = delta_sum[key];
        if (d + amount < d) return false;
        d += amount;
        return true;
    }

    u128 bal(uint64_t slot, int col) const {
        return ((u128)bal_hi[slot * 4 + col] << 64) | bal_lo[slot * 4 + col];
    }
    void set_bal(uint64_t slot, int col, u128 v) {
        bal_lo[slot * 4 + col] = (uint64_t)v;
        bal_hi[slot * 4 + col] = (uint64_t)(v >> 64);
    }
};

inline uint64_t rd64(const uint8_t* p) { uint64_t v; memcpy(&v, p, 8); return v; }
inline uint32_t rd32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }
inline uint16_t rd16(const uint8_t* p) { uint16_t v; memcpy(&v, p, 2); return v; }

}  // namespace

extern "C" {

Fastpath* tb_fp_create(uint64_t account_capacity) {
    return new Fastpath(account_capacity);
}

void tb_fp_destroy(Fastpath* fp) { delete fp; }

// Shared-memory views for Python's BalanceMirror.
uint64_t* tb_fp_balances_lo(Fastpath* fp) { return fp->bal_lo.data(); }
uint64_t* tb_fp_balances_hi(Fastpath* fp) { return fp->bal_hi.data(); }

void tb_fp_add_accounts(Fastpath* fp, const uint64_t* id_lo,
                        const uint64_t* id_hi, const uint32_t* flags,
                        const uint32_t* ledger, uint32_t n,
                        uint64_t base_slot) {
    for (uint32_t i = 0; i < n; i++) {
        fp->acct_flags[base_slot + i] = flags[i];
        fp->acct_ledger[base_slot + i] = ledger[i];
    }
    fp->accounts.insert(id_lo, id_hi, base_slot, n);
}

void tb_fp_remove_accounts(Fastpath* fp, const uint64_t* id_lo,
                           const uint64_t* id_hi, uint32_t n) {
    for (uint32_t i = 0; i < n; i++) fp->accounts.remove(id_lo[i], id_hi[i]);
}

void tb_fp_add_transfer_ids(Fastpath* fp, const uint64_t* id_lo,
                            const uint64_t* id_hi, uint64_t base_row,
                            uint32_t n) {
    fp->transfers.insert(id_lo, id_hi, base_row, n);
}

void tb_fp_remove_transfer_ids(Fastpath* fp, const uint64_t* id_lo,
                               const uint64_t* id_hi, uint32_t n) {
    for (uint32_t i = 0; i < n; i++) fp->transfers.remove(id_lo[i], id_hi[i]);
}

// Returns 0 = applied (results/slots/deltas valid, balances updated);
//         1 = fallback required (NOTHING mutated).
int tb_fp_commit_transfers(
    Fastpath* fp, const uint8_t* body, uint32_t n, uint64_t ts_base,
    uint32_t* out_results, int32_t* out_dr_slot, int32_t* out_cr_slot,
    int64_t* out_dslot, int64_t* out_dcol, uint64_t* out_dlo,
    uint64_t* out_dhi, uint32_t* out_ndeltas) {
    // Pass 0: order-dependence scan + in-batch duplicate detection.
    bool seq = true;  // strictly-increasing hi==0 ids
    for (uint32_t i = 0; i < n; i++) {
        const uint8_t* e = body + (size_t)i * 128;
        uint32_t flags = rd16(e + OFF_FLAGS);
        if (flags & F_ORDER_DEP) return 1;
        if (rd64(e + OFF_ID_LO + 8) != 0 ||
            (i > 0 && rd64(e + OFF_ID_LO) <= rd64(e + OFF_ID_LO - 128)))
            seq = false;
    }
    if (!seq) {
        fp->batch_ids.clear();
        fp->batch_ids.reserve(n * 2);
        for (uint32_t i = 0; i < n; i++) {
            const uint8_t* e = body + (size_t)i * 128;
            u128 id = ((u128)rd64(e + OFF_ID_LO + 8) << 64) | rd64(e + OFF_ID_LO);
            if (!fp->batch_ids.insert(id).second) return 1;  // in-batch dup
        }
    }

    // Pass 1: ladder + admission accumulation (no mutation yet).
    if (++fp->epoch == 0) {  // epoch wrap: invalidate all tags
        std::fill(fp->delta_epoch.begin(), fp->delta_epoch.end(), 0);
        fp->epoch = 1;
    }
    fp->delta_keys.clear();
    for (uint32_t i = 0; i < n; i++) {
        const uint8_t* e = body + (size_t)i * 128;
        uint64_t id_lo = rd64(e + OFF_ID_LO), id_hi = rd64(e + OFF_ID_LO + 8);
        uint64_t dr_lo = rd64(e + OFF_DR_LO), dr_hi = rd64(e + OFF_DR_LO + 8);
        uint64_t cr_lo = rd64(e + OFF_CR_LO), cr_hi = rd64(e + OFF_CR_LO + 8);
        uint64_t amt_lo = rd64(e + OFF_AMOUNT_LO);
        uint64_t amt_hi = rd64(e + OFF_AMOUNT_LO + 8);
        uint64_t pend_lo = rd64(e + OFF_PENDING_LO);
        uint64_t pend_hi = rd64(e + OFF_PENDING_LO + 8);
        uint32_t timeout = rd32(e + OFF_TIMEOUT);
        uint32_t ledger = rd32(e + OFF_LEDGER);
        uint32_t code = rd16(e + OFF_CODE);
        uint32_t flags = rd16(e + OFF_FLAGS);
        uint64_t timestamp = rd64(e + OFF_TIMESTAMP);

        // Durable duplicate id -> exists-ladder territory: fallback.
        if (fp->transfers.contains(id_lo, id_hi)) return 1;

        uint64_t dr_slot_u = 0, cr_slot_u = 0;
        bool dr_found = fp->accounts.lookup(dr_lo, dr_hi, &dr_slot_u);
        bool cr_found = fp->accounts.lookup(cr_lo, cr_hi, &cr_slot_u);
        out_dr_slot[i] = dr_found ? (int32_t)dr_slot_u : -1;
        out_cr_slot[i] = cr_found ? (int32_t)cr_slot_u : -1;

        // Limit/history accounts need the exact kernel's bookkeeping.
        if (dr_found &&
            (fp->acct_flags[dr_slot_u] & (A_LIMIT_DR | A_LIMIT_CR | A_HISTORY)))
            return 1;
        if (cr_found &&
            (fp->acct_flags[cr_slot_u] & (A_LIMIT_DR | A_LIMIT_CR | A_HISTORY)))
            return 1;

        // Static ladder, precedence-exact
        // (reference: src/state_machine.zig:1465-1504; the timestamp
        // check precedes everything, :1251-1256).
        uint32_t c = OK;
        uint32_t dr_ledger = dr_found ? fp->acct_ledger[dr_slot_u] : 0;
        uint32_t cr_ledger = cr_found ? fp->acct_ledger[cr_slot_u] : 0;
        if (timestamp != 0) c = TIMESTAMP_MUST_BE_ZERO;
        else if (flags & ~0x3Fu) c = RESERVED_FLAG;
        else if (id_lo == 0 && id_hi == 0) c = ID_ZERO;
        else if (id_lo == U64_MAX && id_hi == U64_MAX) c = ID_MAX;
        else if (dr_lo == 0 && dr_hi == 0) c = DR_ZERO;
        else if (dr_lo == U64_MAX && dr_hi == U64_MAX) c = DR_MAX;
        else if (cr_lo == 0 && cr_hi == 0) c = CR_ZERO;
        else if (cr_lo == U64_MAX && cr_hi == U64_MAX) c = CR_MAX;
        else if (dr_lo == cr_lo && dr_hi == cr_hi) c = ACCOUNTS_SAME;
        else if (pend_lo != 0 || pend_hi != 0) c = PENDING_ID_MUST_BE_ZERO;
        else if (!(flags & F_PENDING) && timeout != 0) c = TIMEOUT_RESERVED;
        else if (amt_lo == 0 && amt_hi == 0) c = AMOUNT_ZERO;
        else if (ledger == 0) c = LEDGER_ZERO;
        else if (code == 0) c = CODE_ZERO;
        else if (!dr_found) c = DR_NOT_FOUND;
        else if (!cr_found) c = CR_NOT_FOUND;
        else if (dr_ledger != cr_ledger) c = LEDGERS_DIFFER;
        else if (ledger != dr_ledger) c = TRANSFER_LEDGER_DIFFERS;
        out_results[i] = c;
        if (c != OK) continue;

        if (flags & F_PENDING) {
            // Timeout expiry arithmetic must not overflow (the exact
            // path ranks overflows_timeout correctly).
            uint64_t ts_i = ts_base + i;
            uint64_t expires = ts_i + (uint64_t)timeout * NS_PER_S;
            if (timeout != 0 && expires < ts_i) return 1;
        }

        u128 amount = ((u128)amt_hi << 64) | amt_lo;
        int dr_col = (flags & F_PENDING) ? 0 : 1;  // dp : dpo
        int cr_col = (flags & F_PENDING) ? 2 : 3;  // cp : cpo
        // Accumulate with wrap detection: a wrapped u128 sum would
        // corrupt the admission check below.
        if (!fp->delta_add(dr_slot_u * 4 + (uint64_t)dr_col, amount)) return 1;
        if (!fp->delta_add(cr_slot_u * 4 + (uint64_t)cr_col, amount)) return 1;
    }

    // Pass 2: admission — every touched column and combined total must
    // stay within u128 (reference: src/state_machine.zig:1531-1547).
    for (uint64_t key : fp->delta_keys) {
        u128 old_v = fp->bal(key / 4, (int)(key % 4));
        if (old_v + fp->delta_sum[key] < old_v) return 1;  // column overflow
    }
    // Combined totals per touched slot (dp+dpo, cp+cpo): a slot may
    // appear under several keys; checking it per key is idempotent.
    for (uint64_t key : fp->delta_keys) {
        uint64_t slot = key / 4;
        u128 cols[4];
        for (int c2 = 0; c2 < 4; c2++) {
            cols[c2] = fp->bal(slot, c2);
            uint64_t k2 = slot * 4 + (uint64_t)c2;
            if (fp->delta_epoch[k2] == fp->epoch) cols[c2] += fp->delta_sum[k2];
        }
        u128 dr_tot = cols[0] + cols[1];
        if (dr_tot < cols[0]) return 1;
        u128 cr_tot = cols[2] + cols[3];
        if (cr_tot < cols[2]) return 1;
    }

    // Pass 3: apply + emit compacted deltas for the device queue.
    uint32_t k = 0;
    for (uint64_t key : fp->delta_keys) {
        uint64_t slot = key / 4;
        int col = (int)(key % 4);
        u128 d = fp->delta_sum[key];
        fp->set_bal(slot, col, fp->bal(slot, col) + d);
        out_dslot[k] = (int64_t)slot;
        out_dcol[k] = col;
        out_dlo[k] = (uint64_t)d;
        out_dhi[k] = (uint64_t)(d >> 64);
        k++;
    }
    *out_ndeltas = k;
    return 0;
}

// ----------------------------------------------------------------------
// Columnar ingest fast path: batch wire verification + batch reply
// finalize for a whole server drain (runtime/server.py poll_once).
// Frame layout per tigerbeetle_tpu/vsr/wire.py HEADER_DTYPE.

static constexpr uint32_t WIRE_HEADER_SIZE = 256;
static constexpr uint32_t WIRE_OFF_CHECKSUM = 0;
static constexpr uint32_t WIRE_OFF_CHECKSUM_BODY = 16;
static constexpr uint32_t WIRE_OFF_SIZE = 144;
static constexpr uint32_t WIRE_OFF_VERSION = 155;
static constexpr uint8_t WIRE_VERSION = 1;

// Verify one frame — exactly wire.verify_header(header, body).
// Returns the count of BODY bytes hashed (0 when the frame fails a
// structural check before the body pass) and, on a fully-verified
// frame, records the body digest in the drain-scoped digest table so
// the build seams can reuse it without rehashing.
static uint64_t fp_verify_one(const uint8_t* frame, uint32_t len,
                              uint8_t* ok) {
    *ok = 0;
    if (len < WIRE_HEADER_SIZE) return 0;
    uint32_t size;
    memcpy(&size, frame + WIRE_OFF_SIZE, 4);
    if (size != len || size < WIRE_HEADER_SIZE) return 0;
    if (frame[WIRE_OFF_VERSION] != WIRE_VERSION) return 0;
    uint64_t cs[2];
    tb::checksum128(frame + 16, WIRE_HEADER_SIZE - 16, cs);
    if (memcmp(frame + WIRE_OFF_CHECKSUM, cs, 16) != 0) return 0;
    uint64_t body_len = size - WIRE_HEADER_SIZE;
    tb::checksum128(frame + WIRE_HEADER_SIZE, body_len, cs);
    if (memcmp(frame + WIRE_OFF_CHECKSUM_BODY, cs, 16) != 0)
        return body_len;
    tb::digest_table().put(frame + WIRE_HEADER_SIZE, body_len, cs[0],
                           cs[1]);
    *ok = 1;
    return body_len;
}

// One pass over a drain's frames packed in `arena`: per frame, verify
// the header checksum (bytes [16, 256)), the version byte, the size
// field against the framed length, and the body checksum.  ok[i] = 1
// when frame i is valid.  (r20 entry point, kept for old bindings;
// the r23 drain path calls tb_fp_verify_frames2 below.)
void tb_fp_verify_frames(const uint8_t* arena, const uint64_t* offsets,
                         const uint32_t* lens, uint32_t n, uint8_t* ok) {
    for (uint32_t i = 0; i < n; i++)
        fp_verify_one(arena + offsets[i], lens[i], &ok[i]);
}

// r23 verify: same contract plus (a) a new digest-table crossing —
// the previous drain's cached digests die here, this drain's verified
// body digests are recorded for the build seams to reuse; (b) frames
// fan out across the hash pool lanes (each lane verifies whole frames
// — header hash, body hash, memcmps all off the drain thread); and
// (c) the return value is the total BODY bytes this crossing hashed,
// feeding the hash.bytes_hashed counter.
uint64_t tb_fp_verify_frames2(const uint8_t* arena, const uint64_t* offsets,
                              const uint32_t* lens, uint32_t n,
                              uint8_t* ok) {
    tb::digest_table().invalidate();
    std::atomic<uint64_t> bytes{0};
    tb::hash_parallel_for(n, [&](uint32_t i) {
        uint64_t b = fp_verify_one(arena + offsets[i], lens[i], &ok[i]);
        if (b) bytes.fetch_add(b, std::memory_order_relaxed);
    });
    return bytes.load(std::memory_order_relaxed);
}

// Batch reply finalize: `headers` is n contiguous 256-byte records
// with every field but the checksums already set; bodies[i]/body_lens
// [i] is reply i's body.  Sets size, checksum_body, checksum — one C
// call replaces 2n hashlib calls + per-reply numpy churn (the "one
// encode pass + scatter" half of the columnar ingest path).  Replies
// are independent of each other, so the per-reply finalize (body hash
// + header hash) fans out across the hash pool — no signature change,
// the r20 binding gets the lanes for free.
void tb_fp_finalize_headers(uint8_t* headers, uint32_t n,
                            const uint8_t* const* bodies,
                            const uint32_t* body_lens) {
    tb::hash_parallel_for(n, [&](uint32_t i) {
        uint8_t* h = headers + uint64_t(i) * WIRE_HEADER_SIZE;
        uint32_t blen = body_lens[i];
        uint32_t size = WIRE_HEADER_SIZE + blen;
        memcpy(h + WIRE_OFF_SIZE, &size, 4);
        uint64_t cb[2];
        tb::checksum128(bodies[i], blen, cb);
        memcpy(h + WIRE_OFF_CHECKSUM_BODY, cb, 16);
        uint64_t cs[2];
        tb::checksum128(h + 16, WIRE_HEADER_SIZE - 16, cs);
        memcpy(h + WIRE_OFF_CHECKSUM, cs, 16);
    });
}

// ---- r23: hash pool + engine control (envcheck-validated knobs are
// read in Python and pushed down here; C never reads the env) ----

// threads: worker lanes beside the calling thread (0 = inline, the
// 1-core default); clamped to [0, HASH_THREADS_MAX].  force_engine:
// 0 = auto-resolve, else a Sha256Engine value for the --hash-only
// bench grid (forcing an unresolved tier degrades down, same as auto).
void tb_hash_configure(int32_t threads, int32_t force_engine) {
    if (threads < 0) threads = 0;
    if (threads > tb::HASH_THREADS_MAX) threads = tb::HASH_THREADS_MAX;
    tb::hash_threads_cfg().store(threads, std::memory_order_relaxed);
    tb::sha256_force() = (int)force_engine;
}

// Which SHA-256 tier actually resolved (Sha256Engine: 1 = EVP one-shot
// / SHA-NI dispatch, 2 = legacy SHA256(), 3 = the 225 MB/s scalar
// core).  The Python side names these in bench rows and raises the
// one-time scalar-fallback warning.
int32_t tb_hash_engine(void) { return (int32_t)tb::sha256_engine(); }

// out[0] = jobs executed on pool lanes (lanes_busy numerator);
// out[1] = digest-table hits; out[2] = configured lane count.
void tb_hash_stats(uint64_t out[3]) {
    out[0] = tb::hash_lane_jobs().load(std::memory_order_relaxed);
    out[1] = tb::hash_table_hits().load(std::memory_order_relaxed);
    out[2] =
        (uint64_t)tb::hash_threads_cfg().load(std::memory_order_relaxed);
}

}  // extern "C"

#include "tb_exact.inc"
#include "tb_linked.inc"
#include "tb_two_phase.inc"
#include "tb_lsm.inc"
